"""MoE training (reference examples/moe/test_moe_*.py unified).

Gate selected by --gate {top,hash,ktop1,sam,balance}; expert parallelism
over the 'ep' mesh axis via --all2all-size N (all_to_all over ICI instead
of the reference's NCCL alltoall, SURVEY.md §2.5 Expert parallel row).
"""

import os
import sys

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.abspath(__file__)), '..', '..'))

import argparse
import logging
import time

import numpy as np

import hetu_tpu as ht
from hetu_tpu.models import moe_mlp

logging.basicConfig(level=logging.INFO, format="%(asctime)s %(message)s")
logger = logging.getLogger("moe")


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--batch-size", type=int, default=16)
    parser.add_argument("--num-tokens", type=int, default=1024)
    parser.add_argument("--model-dim", type=int, default=2048)
    parser.add_argument("--hidden-size", type=int, default=2048)
    parser.add_argument("--num-local-experts", type=int, default=2)
    parser.add_argument("--all2all-size", type=int, default=1)
    parser.add_argument("--gate", default="top",
                        choices=["top", "hash", "ktop1", "sam", "balance"])
    parser.add_argument("--top-k", type=int, default=2)
    parser.add_argument("--hierarchical", action="store_true",
                        help="two-stage A2A over (dcn, ici) axes")
    parser.add_argument("--num-steps", type=int, default=20)
    parser.add_argument("--learning-rate", type=float, default=0.01)
    args = parser.parse_args()

    n_classes = args.model_dim
    x = ht.placeholder_op("x")
    y_ = ht.placeholder_op("y_")
    loss, y = moe_mlp(
        x, y_, batch_size=args.batch_size, num_tokens=args.num_tokens,
        model_dim=args.model_dim, hidden_size=args.hidden_size,
        num_local_experts=args.num_local_experts,
        all2all_size=args.all2all_size, gate_type=args.gate,
        top_k=args.top_k, hierarchical=args.hierarchical)
    train_op = ht.optim.SGDOptimizer(
        learning_rate=args.learning_rate).minimize(loss)
    executor = ht.Executor({"train": [loss, train_op]})

    rng = np.random.RandomState(0)
    xs = rng.normal(size=(args.batch_size, args.num_tokens,
                          args.model_dim)).astype(np.float32)
    targets = rng.randint(0, n_classes,
                          size=(args.batch_size * args.num_tokens,))
    ys = np.eye(n_classes, dtype=np.float32)[targets]

    t0 = time.time()
    for step in range(args.num_steps):
        out = executor.run("train", feed_dict={x: xs, y_: ys})
        if step % 5 == 0 or step == args.num_steps - 1:
            dt = time.time() - t0
            tok_s = (step + 1) * args.batch_size * args.num_tokens / dt
            logger.info("step %d loss=%.4f (%.0f tokens/s)", step,
                        float(np.asarray(out[0]).reshape(-1)[0]), tok_s)


if __name__ == "__main__":
    main()
