"""MoE training (reference examples/moe/test_moe_*.py unified).

Gate selected by --gate {top,hash,ktop1,sam,balance}; expert parallelism
over the 'ep' mesh axis via --all2all-size N (all_to_all over ICI instead
of the reference's NCCL alltoall, SURVEY.md §2.5 Expert parallel row).
"""

import os
import sys

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.abspath(__file__)), '..', '..'))

import argparse
import logging
import time

import numpy as np

import hetu_tpu as ht
from hetu_tpu.models import moe_mlp

logging.basicConfig(level=logging.INFO, format="%(asctime)s %(message)s")
logger = logging.getLogger("moe")


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--batch-size", type=int, default=16)
    parser.add_argument("--num-tokens", type=int, default=1024)
    parser.add_argument("--model-dim", type=int, default=2048)
    parser.add_argument("--hidden-size", type=int, default=2048)
    parser.add_argument("--num-local-experts", type=int, default=2)
    parser.add_argument("--all2all-size", type=int, default=1)
    parser.add_argument("--gate", default="top",
                        choices=["top", "hash", "ktop1", "sam", "balance"])
    parser.add_argument("--top-k", type=int, default=2)
    parser.add_argument("--hierarchical", action="store_true",
                        help="two-stage A2A over (dcn, ici) axes")
    parser.add_argument("--num-steps", type=int, default=20)
    parser.add_argument("--learning-rate", type=float, default=0.01)
    parser.add_argument("--bf16", action="store_true",
                        help="bf16 compute, fp32 masters")
    args = parser.parse_args()

    n_classes = args.model_dim
    # feed through the dataloader prefetch ring with sparse int labels:
    # a one-hot (B*T, C=model_dim) fp32 target is ~100 MB/step of
    # host->device traffic; int32 ids are ~64 KB
    rng = np.random.RandomState(0)
    n_batches = 4
    xs = rng.normal(size=(n_batches * args.batch_size, args.num_tokens,
                          args.model_dim)).astype(np.float32)
    if args.bf16:
        # halve the H2D bytes for the token feed; compute is bf16 anyway
        import ml_dtypes
        xs = xs.astype(ml_dtypes.bfloat16)
    targets = rng.randint(
        0, n_classes, size=(n_batches * args.batch_size, args.num_tokens)
    ).astype(np.int32)
    x = ht.dataloader_op([ht.Dataloader(xs, args.batch_size, "train")])
    yb = ht.dataloader_op([ht.Dataloader(targets, args.batch_size,
                                         "train")])
    y_ = ht.array_reshape_op(yb, [args.batch_size * args.num_tokens])

    # --all2all-size N over N+ devices: experts shard over the 'ep' mesh
    # axis and the token exchange is a REAL all_to_all (reference NCCL
    # alltoall, gpu_ops/AllToAll.py); --hierarchical uses a (dcn, ici)
    # mesh so the exchange stages intra- then inter-group
    mesh, strategy = None, None
    ep = args.all2all_size
    if ep > 1:
        if args.gate == "balance":
            raise SystemExit(
                "--gate balance uses the per-local-expert balance-"
                "assignment formulation, which has no expert-parallel "
                "lowering; drop --all2all-size")
        import jax
        from hetu_tpu.parallel.mesh import make_mesh
        n_dev = jax.device_count()
        if n_dev % ep:
            raise SystemExit(f"--all2all-size {ep} needs a device count "
                             f"divisible by it (have {n_dev})")
        if args.hierarchical:
            if ep % 2 or ep < 4:
                raise SystemExit("--hierarchical needs an even "
                                 "--all2all-size >= 4 (dcn x ici mesh)")
            if n_dev != ep:
                raise SystemExit(
                    f"--hierarchical builds a dcn x ici mesh of exactly "
                    f"--all2all-size devices; have {n_dev}, want {ep} "
                    f"(the non-hierarchical path adds a dp axis instead)")
            from jax.sharding import PartitionSpec as P
            mesh = make_mesh({"dcn": 2, "ici": ep // 2})
            # experts shard over the combined (dcn, ici) superaxis
            strategy = ht.dist.ShardingPlan({
                "expert_expert_stack_w1": P(("dcn", "ici"), None, None),
                "expert_expert_stack_w2": P(("dcn", "ici"), None, None)})
        else:
            dp = n_dev // ep
            strategy = ht.dist.ExpertParallel(ep=ep, dp=dp)
    loss, y = moe_mlp(
        x, y_, batch_size=args.batch_size, num_tokens=args.num_tokens,
        model_dim=args.model_dim, hidden_size=args.hidden_size,
        num_local_experts=args.num_local_experts,
        all2all_size=args.all2all_size, gate_type=args.gate,
        top_k=args.top_k, hierarchical=args.hierarchical,
        sparse_labels=True, expert_parallel=ep > 1)
    train_op = ht.optim.SGDOptimizer(
        learning_rate=args.learning_rate).minimize(loss)
    executor = ht.Executor({"train": [loss, train_op]}, mesh=mesh,
                           dist_strategy=strategy,
                           mixed_precision="bf16" if args.bf16 else None)

    out = executor.run("train")                       # compile + warmup
    logger.info("step 0 loss=%.4f (compile)",
                float(np.asarray(out[0]).reshape(-1)[0]))
    t0 = time.time()
    for step in range(1, args.num_steps):
        out = executor.run("train")
        if step % 5 == 0 or step == args.num_steps - 1:
            dt = time.time() - t0
            tok_s = step * args.batch_size * args.num_tokens / dt
            logger.info("step %d loss=%.4f (%.0f tokens/s)", step,
                        float(np.asarray(out[0]).reshape(-1)[0]), tok_s)


if __name__ == "__main__":
    main()
