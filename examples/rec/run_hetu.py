"""NCF training (reference examples/rec/run_hetu.py + hetu_ncf.py).

MovieLens implicit-feedback NeuMF; synthetic interactions stand in when
the dataset is absent.
"""

import os
import sys

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.abspath(__file__)), '..', '..'))

import argparse
import logging
import time

import numpy as np

import hetu_tpu as ht
from hetu_tpu.models import neural_mf

logging.basicConfig(level=logging.INFO, format="%(asctime)s %(message)s")
logger = logging.getLogger("ncf")


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--num-users", type=int, default=6040)
    parser.add_argument("--num-items", type=int, default=3706)
    parser.add_argument("--batch-size", type=int, default=1024)
    parser.add_argument("--num-steps", type=int, default=100)
    parser.add_argument("--learning-rate", type=float, default=0.01)
    parser.add_argument("--negative-ratio", type=int, default=4)
    parser.add_argument("--data-path", default=None,
                        help="dir with reference-format movielens "
                             "ratings.csv / ratings.dat; synthetic "
                             "interactions when unset")
    args = parser.parse_args()

    rng = np.random.RandomState(0)
    bs = args.batch_size
    data = None
    if args.data_path:
        # reference-format movielens (ratings.csv / ratings.dat —
        # hetu_tpu.data.load_movielens)
        from hetu_tpu.data import load_movielens
        us, its, labs, nu, ni = load_movielens(
            args.data_path, num_negatives=args.negative_ratio)
        args.num_users, args.num_items = nu, ni
        data = (us, its, labs.reshape(-1, 1))
        logger.info("loaded movielens from %s: %d triples, %d users, "
                    "%d items", args.data_path, len(us), nu, ni)

    user = ht.placeholder_op("user_input")
    item = ht.placeholder_op("item_input")
    y_ = ht.placeholder_op("y_")
    loss, pred, train_op = neural_mf(
        user, item, y_, num_users=args.num_users, num_items=args.num_items,
        lr=args.learning_rate)
    executor = ht.Executor({"train": [loss, pred, train_op]})
    t0 = time.time()
    for step in range(args.num_steps):
        if data is not None:
            sel = rng.randint(0, len(data[0]), bs)
            users, items, labels = (data[0][sel], data[1][sel],
                                    data[2][sel])
        else:
            users = rng.randint(0, args.num_users, (bs,)).astype(np.int32)
            items = rng.randint(0, args.num_items, (bs,)).astype(np.int32)
            labels = (rng.rand(bs, 1) < 1.0 / (1 + args.negative_ratio))\
                .astype(np.float32)
        out = executor.run("train", feed_dict={
            user: users, item: items, y_: labels})
        if step % 20 == 0 or step == args.num_steps - 1:
            dt = time.time() - t0
            logger.info("step %d loss=%.4f (%.0f samples/s)", step,
                        float(np.asarray(out[0]).reshape(-1)[0]),
                        (step + 1) * bs / dt)


if __name__ == "__main__":
    main()
