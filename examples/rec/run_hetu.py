"""NCF training (reference examples/rec/run_hetu.py + hetu_ncf.py).

MovieLens implicit-feedback NeuMF; synthetic interactions stand in when
the dataset is absent.
"""

import os
import sys

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.abspath(__file__)), '..', '..'))

import argparse
import logging
import time

import numpy as np

import hetu_tpu as ht
from hetu_tpu.models import neural_mf

logging.basicConfig(level=logging.INFO, format="%(asctime)s %(message)s")
logger = logging.getLogger("ncf")


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--num-users", type=int, default=6040)
    parser.add_argument("--num-items", type=int, default=3706)
    parser.add_argument("--batch-size", type=int, default=1024)
    parser.add_argument("--num-steps", type=int, default=100)
    parser.add_argument("--learning-rate", type=float, default=0.01)
    parser.add_argument("--negative-ratio", type=int, default=4)
    args = parser.parse_args()

    user = ht.placeholder_op("user_input")
    item = ht.placeholder_op("item_input")
    y_ = ht.placeholder_op("y_")
    loss, pred, train_op = neural_mf(
        user, item, y_, num_users=args.num_users, num_items=args.num_items,
        lr=args.learning_rate)
    executor = ht.Executor({"train": [loss, pred, train_op]})

    rng = np.random.RandomState(0)
    bs = args.batch_size
    t0 = time.time()
    for step in range(args.num_steps):
        users = rng.randint(0, args.num_users, (bs,)).astype(np.int32)
        items = rng.randint(0, args.num_items, (bs,)).astype(np.int32)
        labels = (rng.rand(bs, 1) < 1.0 / (1 + args.negative_ratio))\
            .astype(np.float32)
        out = executor.run("train", feed_dict={
            user: users, item: items, y_: labels})
        if step % 20 == 0 or step == args.num_steps - 1:
            dt = time.time() - t0
            logger.info("step %d loss=%.4f (%.0f samples/s)", step,
                        float(np.asarray(out[0]).reshape(-1)[0]),
                        (step + 1) * bs / dt)


if __name__ == "__main__":
    main()
