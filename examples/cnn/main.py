"""CNN-family training (reference examples/cnn/main.py).

Usage:
    python examples/cnn/main.py --model resnet18 --dataset CIFAR10 \
        --batch-size 128 --learning-rate 0.1 --num-epochs 10 [--validate]

Models: mlp, logreg, cnn_3_layers, lenet, alexnet, vgg16, vgg19,
resnet18, resnet34, resnet50, rnn, lstm.  Falls back to synthetic data
when the dataset files are absent (no-egress environments).
"""

import os
import sys

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.abspath(__file__)), '..', '..'))

import argparse
import logging
import time

import numpy as np

import hetu_tpu as ht
from hetu_tpu import models

logging.basicConfig(level=logging.INFO, format="%(asctime)s %(message)s")
logger = logging.getLogger("cnn")

MODELS = {
    "mlp": (models.mlp, "mnist"),
    "logreg": (models.logreg, "mnist"),
    "cnn_3_layers": (models.cnn_3_layers, "mnist"),
    "lenet": (models.lenet, "mnist"),
    "rnn": (models.rnn, "mnist"),
    "lstm": (models.lstm, "mnist"),
    "alexnet": (models.alexnet, "cifar"),
    "vgg16": (models.vgg16, "cifar"),
    "vgg19": (models.vgg19, "cifar"),
    "resnet18": (models.resnet18, "cifar"),
    "resnet34": (models.resnet34, "cifar"),
    "resnet50": (models.resnet50, "cifar"),
}


def load_dataset(kind, dataset):
    if kind == "mnist":
        tx, ty, vx, vy = ht.data.mnist(onehot=True)
        tx = tx.reshape(-1, 784)
        vx = vx.reshape(-1, 784)
    else:
        loader = ht.data.cifar100 if dataset == "CIFAR100" else ht.data.cifar10
        tx, ty, vx, vy = loader(onehot=True)
    return (tx.astype(np.float32), ty.astype(np.float32),
            vx.astype(np.float32), vy.astype(np.float32))


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--model", default="resnet18", choices=MODELS)
    parser.add_argument("--dataset", default=None)
    parser.add_argument("--batch-size", type=int, default=128)
    parser.add_argument("--learning-rate", type=float, default=0.1)
    parser.add_argument("--num-epochs", type=int, default=1)
    parser.add_argument("--opt", default="sgd",
                        choices=["sgd", "momentum", "nesterov", "adagrad",
                                 "adam", "adamw", "lamb"])
    parser.add_argument("--validate", action="store_true")
    parser.add_argument("--comm-mode", default=None,
                        help="None / AllReduce / PS / Hybrid")
    args = parser.parse_args()

    builder, kind = MODELS[args.model]
    tx, ty, vx, vy = load_dataset(kind, args.dataset)
    n_cls = ty.shape[-1]

    x = ht.placeholder_op("x")
    y_ = ht.placeholder_op("y_")
    import inspect
    params = inspect.signature(builder).parameters
    if "num_class" in params:
        loss, y = builder(x, y_, num_class=n_cls)
    elif "dimoutput" in params:
        loss, y = builder(x, y_, dimoutput=n_cls)
    else:
        assert n_cls == 10, (
            f"{args.model} has a fixed 10-class head; got {n_cls} classes")
        loss, y = builder(x, y_)

    opts = {"sgd": ht.optim.SGDOptimizer,
            "momentum": ht.optim.MomentumOptimizer,
            "nesterov": lambda **kw: ht.optim.MomentumOptimizer(
                nesterov=True, **kw),
            "adagrad": ht.optim.AdaGradOptimizer,
            "adam": ht.optim.AdamOptimizer,
            "adamw": ht.optim.AdamWOptimizer,
            "lamb": ht.optim.LambOptimizer}
    opt = opts[args.opt](learning_rate=args.learning_rate)
    train_op = opt.minimize(loss)

    executor = ht.Executor({"train": [loss, y, train_op],
                            "validate": [loss, y]},
                           comm_mode=args.comm_mode)
    bs = args.batch_size
    n_train = (len(tx) // bs) * bs
    n_valid = (len(vx) // bs) * bs

    for epoch in range(args.num_epochs):
        t0 = time.time()
        train_loss, train_acc, nb = 0.0, 0.0, 0
        for i in range(0, n_train, bs):
            out = executor.run("train", feed_dict={
                x: tx[i:i + bs], y_: ty[i:i + bs]})
            train_loss += float(np.asarray(out[0]).reshape(-1)[0])
            pred = np.asarray(out[1])
            train_acc += float(
                (pred.argmax(-1) == ty[i:i + bs].argmax(-1)).mean())
            nb += 1
        dt = time.time() - t0
        logger.info(
            "epoch %d: loss=%.4f acc=%.4f (%.1f samples/s)", epoch,
            train_loss / nb, train_acc / nb, n_train / dt)
        if args.validate:
            v_loss, v_acc, vb = 0.0, 0.0, 0
            for i in range(0, n_valid, bs):
                out = executor.run("validate", feed_dict={
                    x: vx[i:i + bs], y_: vy[i:i + bs]})
                v_loss += float(np.asarray(out[0]).reshape(-1)[0])
                pred = np.asarray(out[1])
                v_acc += float(
                    (pred.argmax(-1) == vy[i:i + bs].argmax(-1)).mean())
                vb += 1
            logger.info("epoch %d: val_loss=%.4f val_acc=%.4f", epoch,
                        v_loss / vb, v_acc / vb)


if __name__ == "__main__":
    main()
