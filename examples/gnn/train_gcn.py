"""GCN node classification (reference: GNN examples on GraphMix/DistGCN;
tests/test_DistGCN drives the 1.5-D partitioned GCN).

Two stacked graph-convolution layers built from `distgcn_15d_op`
(Z = (A @ H) @ W): on a single device it is a dense fused matmul chain;
with --mesh it runs the 1.5-D partition over (dp x tp) mesh axes — rows
of A/H over 'dp', columns of W over 'tp' — the TPU-native equivalent of
the reference's process-grid partitioning (DistGCN_15d.py).

Data: a synthetic two-community stochastic block model (dense intra-block
edges), labels = community — learnable from structure alone, no egress.

  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
      python examples/gnn/train_gcn.py --mesh dp4xtp2
"""

import os
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.join(_HERE, '..', '..'))
sys.path.insert(0, _HERE)   # for the shared `gnn_common` helpers

import argparse
import logging

import numpy as np

import hetu_tpu as ht
from gnn_common import parse_mesh, sbm_graph

logging.basicConfig(level=logging.INFO, format="%(asctime)s %(message)s")
logger = logging.getLogger("gcn")


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--nodes", type=int, default=256)
    p.add_argument("--classes", type=int, default=4)
    p.add_argument("--feat-dim", type=int, default=16)
    p.add_argument("--hidden", type=int, default=32)
    p.add_argument("--epochs", type=int, default=60)
    p.add_argument("--learning-rate", type=float, default=0.2)
    p.add_argument("--mesh", default=None,
                   help="e.g. dp4xtp2 — 1.5-D partition axes")
    args = p.parse_args()

    mesh = parse_mesh(args.mesh, logger)
    adj, feat, labels = sbm_graph(args.nodes, args.classes, 0.2, 0.01,
                                  args.feat_dim)
    train_mask = np.zeros(args.nodes, bool)
    train_mask[np.random.RandomState(1).choice(
        args.nodes, args.nodes // 2, replace=False)] = True

    a = ht.placeholder_op("adj")
    x = ht.placeholder_op("feat")
    y = ht.placeholder_op("labels")
    m = ht.placeholder_op("mask")
    w1 = ht.init.xavier_uniform((args.feat_dim, args.hidden), name="gcn_w1")
    w2 = ht.init.xavier_uniform((args.hidden, args.classes), name="gcn_w2")
    h = ht.relu_op(ht.distgcn_15d_op(a, x, w1))
    logits = ht.distgcn_15d_op(a, h, w2)
    per_node = ht.softmaxcrossentropy_sparse_op(logits, y)
    # semi-supervised: only train-mask nodes contribute to the loss;
    # held-out nodes are classified purely through graph propagation
    masked = ht.mul_op(per_node, m)
    loss = ht.div_op(ht.reduce_sum_op(masked, [0]),
                     ht.reduce_sum_op(m, [0]))
    train = ht.optim.AdamOptimizer(
        learning_rate=args.learning_rate).minimize(loss)
    ex = ht.Executor({"train": [loss, train], "eval": [logits]}, mesh=mesh)

    feed = {a: adj, x: feat, y: labels,
            m: train_mask.astype(np.float32)}
    for epoch in range(args.epochs):
        out = ex.run("train", feed_dict=feed)
        if (epoch + 1) % 20 == 0:
            lg = np.asarray(ex.run("eval", feed_dict=feed)[0])
            acc = (lg.argmax(-1) == labels)[~train_mask].mean()
            logger.info("epoch %d loss %.4f held-out acc %.3f",
                        epoch + 1, float(np.asarray(out[0])), acc)
    lg = np.asarray(ex.run("eval", feed_dict=feed)[0])
    acc = (lg.argmax(-1) == labels)[~train_mask].mean()
    logger.info("final held-out accuracy %.3f", acc)
    return acc


if __name__ == "__main__":
    main()
