"""Shared helpers for the GNN example scripts."""

import numpy as np


def sbm_graph(n, n_classes, p_in, p_out, feat_dim=None, seed=0):
    """Stochastic block model: dense intra-community edges, labels =
    community.  Returns (row-normalized adj, features-or-None, labels);
    features (when ``feat_dim``) are noisy community one-hot-ish."""
    rng = np.random.RandomState(seed)
    labels = rng.randint(0, n_classes, n)
    same = labels[:, None] == labels[None, :]
    adj = (rng.rand(n, n) < np.where(same, p_in, p_out)).astype(np.float32)
    adj = np.maximum(adj, adj.T)
    np.fill_diagonal(adj, 1.0)              # self loops
    adj /= adj.sum(1, keepdims=True)        # row-normalized
    feat = None
    if feat_dim:
        feat = rng.randn(n, feat_dim).astype(np.float32) * 0.5
        feat[np.arange(n), labels % feat_dim] += 1.0
    return adj.astype(np.float32), feat, labels.astype(np.int32)


def parse_mesh(spec, logger=None):
    """'dp4xtp2' → a device mesh (or None when ``spec`` is falsy)."""
    if not spec:
        return None
    from hetu_tpu.parallel.mesh import make_mesh
    axes = {}
    for part in spec.split("x"):
        name = part.rstrip("0123456789")
        axes[name] = int(part[len(name):])
    if logger is not None:
        logger.info("mesh %s", axes)
    return make_mesh(axes)
