"""Hybrid-PS GCN: learnable node embeddings served by the parameter
server, graph convolutions on the device mesh.

Reference: examples/gnn/run_dist_hybrid.py:1 — the GraphMix/PS hybrid
deployment where node embeddings live server-side and each worker runs
GCN compute; here the embedding table is an ``is_embed`` variable the
Executor's Hybrid phases A/B pull/push through the PS (and through the
native C++ van when HETU_PS_VAN autoserve is on), while the 1.5-D
``distgcn_15d_op`` layers run on the mesh (examples/gnn/run_dist.py's
partitioning, SURVEY tests/test_DistGCN).

Data: the same synthetic stochastic block model as train_gcn.py —
labels recoverable from structure, no egress.

  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
      python examples/gnn/train_gcn_hybrid.py --mesh dp4xtp2
"""

import os
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.join(_HERE, '..', '..'))
sys.path.insert(0, _HERE)   # for the shared `gnn_common` helpers

import argparse
import logging

import numpy as np

import hetu_tpu as ht
from gnn_common import parse_mesh, sbm_graph

logging.basicConfig(level=logging.INFO, format="%(asctime)s %(message)s")
logger = logging.getLogger("gcn-hybrid")


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--nodes", type=int, default=256)
    p.add_argument("--classes", type=int, default=4)
    p.add_argument("--embed-dim", type=int, default=16)
    p.add_argument("--hidden", type=int, default=32)
    p.add_argument("--epochs", type=int, default=80)
    p.add_argument("--learning-rate", type=float, default=0.2)
    p.add_argument("--mesh", default=None,
                   help="e.g. dp4xtp2 — 1.5-D partition axes")
    p.add_argument("--cache-policy", default=None,
                   choices=[None, "LRU", "LFU", "LFUOpt"],
                   help="HET embedding cache between worker and PS")
    p.add_argument("--cache-bound", type=int, default=64)
    args = p.parse_args()

    mesh = parse_mesh(args.mesh, logger)
    adj, _, labels = sbm_graph(args.nodes, args.classes, 0.2, 0.01)
    node_ids = np.arange(args.nodes).astype(np.int32)
    train_mask = np.zeros(args.nodes, bool)
    train_mask[np.random.RandomState(1).choice(
        args.nodes, args.nodes // 2, replace=False)] = True

    a = ht.placeholder_op("adj")
    ids = ht.placeholder_op("node_ids")
    y = ht.placeholder_op("labels")
    m = ht.placeholder_op("mask")
    # the PS-served table: structure is the only signal, so the
    # embeddings must LEARN community-separating features
    emb = ht.init.random_normal((args.nodes, args.embed_dim), stddev=0.3,
                                name="gcn_node_emb")
    emb.is_embed = True
    x = ht.embedding_lookup_op(emb, ids)
    w1 = ht.init.xavier_uniform((args.embed_dim, args.hidden),
                                name="gcn_w1")
    w2 = ht.init.xavier_uniform((args.hidden, args.classes),
                                name="gcn_w2")
    h = ht.relu_op(ht.distgcn_15d_op(a, x, w1))
    logits = ht.distgcn_15d_op(a, h, w2)
    per_node = ht.softmaxcrossentropy_sparse_op(logits, y)
    masked = ht.mul_op(per_node, m)
    loss = ht.div_op(ht.reduce_sum_op(masked, [0]),
                     ht.reduce_sum_op(m, [0]))
    train = ht.optim.SGDOptimizer(
        learning_rate=args.learning_rate).minimize(loss)
    kw = dict(comm_mode="Hybrid", mesh=mesh)
    if args.cache_policy:
        kw.update(cstable_policy=args.cache_policy,
                  cache_bound=args.cache_bound)
    ex = ht.Executor({"train": [loss, train], "eval": [logits]}, **kw)

    feed = {a: adj, ids: node_ids, y: labels,
            m: train_mask.astype(np.float32)}
    for epoch in range(args.epochs):
        out = ex.run("train", feed_dict=feed)
        if (epoch + 1) % 20 == 0:
            lg = np.asarray(ex.run("eval", feed_dict=feed)[0])
            acc = (lg.argmax(-1) == labels)[~train_mask].mean()
            logger.info("epoch %d loss %.4f held-out acc %.3f",
                        epoch + 1, float(np.asarray(out[0])), acc)
    lg = np.asarray(ex.run("eval", feed_dict=feed)[0])
    acc = (lg.argmax(-1) == labels)[~train_mask].mean()
    logger.info("final held-out accuracy %.3f", acc)
    return acc


if __name__ == "__main__":
    main()
