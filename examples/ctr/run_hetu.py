"""CTR training (reference examples/ctr/run_hetu.py).

Models: wdl_adult, wdl_criteo, dcn_criteo, deepfm_criteo, dc_criteo.
--comm-mode Hybrid routes embedding grads through the PS with the HET
cache while dense grads ride psum over the mesh (reference
optimizer.py:157-162 semantics).  Synthetic data stands in for Criteo
when raw files are absent.
"""

import os
import sys

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.abspath(__file__)), '..', '..'))

import argparse
import logging
import time

import numpy as np

import hetu_tpu as ht
from hetu_tpu import models

logging.basicConfig(level=logging.INFO, format="%(asctime)s %(message)s")
logger = logging.getLogger("ctr")


def synthetic_criteo(rng, n, feature_dimension):
    dense = rng.randn(n, 13).astype(np.float32)
    sparse = rng.randint(0, feature_dimension, (n, 26)).astype(np.int32)
    y = rng.randint(0, 2, (n, 1)).astype(np.float32)
    return dense, sparse, y


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--model", default="wdl_criteo",
                        choices=["wdl_adult", "wdl_criteo", "dcn_criteo",
                                 "deepfm_criteo", "dc_criteo"])
    parser.add_argument("--batch-size", type=int, default=128)
    parser.add_argument("--num-steps", type=int, default=100)
    parser.add_argument("--feature-dim", type=int, default=100000,
                        help="embedding rows (Criteo full: 33762577)")
    parser.add_argument("--embedding-size", type=int, default=128)
    parser.add_argument("--comm-mode", default=None,
                        help="None / AllReduce / PS / Hybrid")
    parser.add_argument("--cache", default=None,
                        help="cstable policy: lru / lfu / lfuopt")
    parser.add_argument("--cache-bound", type=int, default=100)
    parser.add_argument("--all", action="store_true",
                        help="eval AUC each 10 steps")
    args = parser.parse_args()

    rng = np.random.RandomState(0)
    if args.model == "wdl_adult":
        X_deep = [ht.placeholder_op(f"xd{i}") for i in range(12)]
        X_wide = ht.placeholder_op("x_wide")
        y_ = ht.placeholder_op("y_")
        loss, pred, label, train_op = models.wdl_adult(X_deep, X_wide, y_)

        def batch():
            feeds = {X_wide: rng.randn(args.batch_size, 809)
                     .astype(np.float32),
                     y_: np.eye(2, dtype=np.float32)[
                         rng.randint(0, 2, args.batch_size)]}
            for i in range(8):
                feeds[X_deep[i]] = rng.randint(
                    0, 50, (args.batch_size,)).astype(np.int32)
            for i in range(8, 12):
                feeds[X_deep[i]] = rng.randn(args.batch_size)\
                    .astype(np.float32)
            return feeds
    else:
        builder = getattr(models, args.model)
        dense = ht.placeholder_op("dense")
        sparse = ht.placeholder_op("sparse")
        y_ = ht.placeholder_op("y_")
        loss, pred, label, train_op = builder(
            dense, sparse, y_, feature_dimension=args.feature_dim,
            embedding_size=args.embedding_size)

        def batch():
            d, s, y = synthetic_criteo(rng, args.batch_size,
                                       args.feature_dim)
            return {dense: d, sparse: s, y_: y}

    executor = ht.Executor({"train": [loss, pred, label, train_op]},
                           comm_mode=args.comm_mode,
                           cstable_policy=args.cache,
                           cache_bound=args.cache_bound)
    t0 = time.time()
    for step in range(args.num_steps):
        out = executor.run("train", feed_dict=batch())
        if step % 10 == 0 or step == args.num_steps - 1:
            dt = time.time() - t0
            msg = ""
            if args.all:
                y_score = np.asarray(out[1])
                y_true = np.asarray(out[2])
                if y_score.ndim == 2 and y_score.shape[-1] == 2:
                    y_score = y_score[:, 1]
                if y_true.ndim == 2 and y_true.shape[-1] == 2:
                    y_true = y_true[:, 1]
                msg = " auc=%.4f" % ht.metrics.auc_score(
                    y_score.reshape(-1), y_true.reshape(-1))
            if executor.cstables:
                perf = executor.ps_perf_summary()
                hr = np.mean([p["hit_rate"] for p in perf.values()])
                msg += " cache_hit=%.3f" % hr
            logger.info("step %d loss=%.4f (%.1f samples/s)%s", step,
                        float(np.asarray(out[0]).reshape(-1)[0]),
                        (step + 1) * args.batch_size / dt, msg)


if __name__ == "__main__":
    main()
