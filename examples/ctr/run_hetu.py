"""CTR training (reference examples/ctr/run_hetu.py).

Models: wdl_adult, wdl_criteo, dcn_criteo, deepfm_criteo, dc_criteo.
--comm-mode Hybrid routes embedding grads through the PS with the HET
cache while dense grads ride psum over the mesh (reference
optimizer.py:157-162 semantics).  Synthetic data stands in for Criteo
when raw files are absent.
"""

import os
import sys

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.abspath(__file__)), '..', '..'))

import argparse
import logging
import time

import numpy as np

import hetu_tpu as ht
from hetu_tpu import models

logging.basicConfig(level=logging.INFO, format="%(asctime)s %(message)s")
logger = logging.getLogger("ctr")


def zipf_ids(rng, dim, size, a):
    """Zipf-skewed categorical ids (CTR id frequencies are power-law —
    the skew the HET cache exploits); a<=0 falls back to uniform."""
    if a <= 0:
        return rng.randint(0, dim, size).astype(np.int32)
    ranks = np.arange(1, dim + 1, dtype=np.float64)
    p = ranks ** -a
    p /= p.sum()
    ids = rng.choice(dim, size=size, p=p)
    # hotness should not imply row locality: scatter hot ids over the table
    perm = rng.permutation(dim)
    return perm[ids].astype(np.int32)


def synthetic_criteo(rng, n, feature_dimension, zipf=1.05):
    dense = rng.randn(n, 13).astype(np.float32)
    sparse = zipf_ids(rng, feature_dimension, (n, 26), zipf)
    y = rng.randint(0, 2, (n, 1)).astype(np.float32)
    return dense, sparse, y


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--model", default="wdl_criteo",
                        choices=["wdl_adult", "wdl_criteo", "dcn_criteo",
                                 "deepfm_criteo", "dc_criteo"])
    parser.add_argument("--batch-size", type=int, default=128)
    parser.add_argument("--data-path", default=None,
                        help="dir with reference-format criteo files "
                             "(train_*.npy / train.txt / train.csv); "
                             "synthetic data when unset")
    parser.add_argument("--num-steps", type=int, default=100)
    parser.add_argument("--feature-dim", type=int, default=100000,
                        help="embedding rows (Criteo full: 33762577)")
    parser.add_argument("--embedding-size", type=int, default=128)
    parser.add_argument("--comm-mode", default=None,
                        help="None / AllReduce / PS / Hybrid")
    parser.add_argument("--cache", default=None,
                        help="cstable policy: lru / lfu / lfuopt")
    parser.add_argument("--cache-bound", type=int, default=None,
                        help="cache capacity in rows (default: 10%% of "
                             "--feature-dim)")
    parser.add_argument("--zipf", type=float, default=1.05,
                        help="id skew exponent for synthetic data "
                             "(0 = uniform)")
    parser.add_argument("--bf16", action="store_true",
                        help="bf16 compute + bf16 embedding-row "
                             "transfers; fp32 masters on the PS")
    parser.add_argument("--all", action="store_true",
                        help="eval AUC each 10 steps")
    args = parser.parse_args()
    if args.cache_bound is None:
        args.cache_bound = max(args.feature_dim // 10, 1024)

    rng = np.random.RandomState(0)
    if args.model == "wdl_adult":
        X_deep = [ht.placeholder_op(f"xd{i}") for i in range(12)]
        X_wide = ht.placeholder_op("x_wide")
        y_ = ht.placeholder_op("y_")
        loss, pred, label, train_op = models.wdl_adult(X_deep, X_wide, y_)

        def batch():
            feeds = {X_wide: rng.randn(args.batch_size, 809)
                     .astype(np.float32),
                     y_: np.eye(2, dtype=np.float32)[
                         rng.randint(0, 2, args.batch_size)]}
            for i in range(8):
                feeds[X_deep[i]] = rng.randint(
                    0, 50, (args.batch_size,)).astype(np.int32)
            for i in range(8, 12):
                feeds[X_deep[i]] = rng.randn(args.batch_size)\
                    .astype(np.float32)
            return feeds
    else:
        builder = getattr(models, args.model)
        # feed through dataloaders: the ring prefetches batches and the
        # executor overlaps the NEXT batch's PS/cache embedding lookup
        # with the current step (placeholder feeds cannot be peeked)
        n_pool = 32
        if args.data_path:
            # reference-format local criteo (train_*.npy / train.txt /
            # train.csv — hetu_tpu.data.load_criteo)
            from hetu_tpu.data import load_criteo
            d, s, y = load_criteo(args.data_path)
            args.feature_dim = max(args.feature_dim, int(s.max()) + 1)
            logger.info("loaded criteo from %s: %d rows, %d features",
                        args.data_path, len(y), args.feature_dim)
        else:
            d, s, y = synthetic_criteo(rng, n_pool * args.batch_size,
                                       args.feature_dim, args.zipf)
        dense = ht.dataloader_op([ht.Dataloader(d, args.batch_size,
                                                "train")])
        sparse = ht.dataloader_op([ht.Dataloader(s, args.batch_size,
                                                 "train")])
        y_ = ht.dataloader_op([ht.Dataloader(y, args.batch_size,
                                             "train")])
        loss, pred, label, train_op = builder(
            dense, sparse, y_, feature_dimension=args.feature_dim,
            embedding_size=args.embedding_size)

        def batch():
            return None

    executor = ht.Executor({"train": [loss, pred, label, train_op]},
                           comm_mode=args.comm_mode,
                           cstable_policy=args.cache,
                           cache_bound=args.cache_bound,
                           mixed_precision="bf16" if args.bf16 else None)
    out = executor.run("train", feed_dict=batch())  # compile + warmup
    logger.info("step 0 loss=%.4f (compile)",
                float(np.asarray(out[0]).reshape(-1)[0]))
    t0 = time.time()
    for step in range(1, args.num_steps):
        out = executor.run("train", feed_dict=batch())
        if step % 10 == 0 or step == args.num_steps - 1:
            dt = time.time() - t0
            msg = ""
            if args.all:
                y_score = np.asarray(out[1])
                y_true = np.asarray(out[2])
                if y_score.ndim == 2 and y_score.shape[-1] == 2:
                    y_score = y_score[:, 1]
                if y_true.ndim == 2 and y_true.shape[-1] == 2:
                    y_true = y_true[:, 1]
                msg = " auc=%.4f" % ht.metrics.auc_score(
                    y_score.reshape(-1), y_true.reshape(-1))
            if executor.cstables:
                perf = executor.ps_perf_summary()
                hr = np.mean([p["hit_rate"] for p in perf.values()])
                msg += " cache_hit=%.3f" % hr
            logger.info("step %d loss=%.4f (%.1f samples/s)%s", step,
                        float(np.asarray(out[0]).reshape(-1)[0]),
                        step * args.batch_size / dt, msg)


if __name__ == "__main__":
    main()
