"""Embedding-cache CTR serving demo (hetu_tpu.serving.embed_engine).

Stands up an in-process PS holding a Criteo-shaped embedding table,
fronts it with the HET ``CacheSparseTable``, and serves a zipf-skewed
click-through scoring trace through the ``EmbedServingEngine``: each
wave gathers 26 sparse-feature embeddings per pair through the cache
(hits local, misses PS-pulled) and scores the whole wave in one jitted
WDL/DCN tower forward.  Cache hit rate, latency percentiles, and the
gather/forward breakdown print at the end.

    python examples/ctr/serve_ctr.py --requests 32 --wave 4

``--kill-ps`` kills the PS for the middle third of the trace: the
cache serves stale rows for warm ids and zero vectors for cold ones,
NOTHING is lost, and the pull counters resume after recovery — the
training degradation protocol doing serving duty:

    python examples/ctr/serve_ctr.py --requests 32 --kill-ps
"""

import os
import sys

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.abspath(__file__)), '..', '..'))

import argparse
import logging

import numpy as np

import hetu_tpu as ht  # noqa: F401  (platform forcing + compat shims)
from hetu_tpu.cache.cstable import CacheSparseTable
from hetu_tpu.ps.client import PSConnectionError
from hetu_tpu.ps.server import PSServer
from hetu_tpu.serving import EmbedRequest, EmbedServingEngine

logging.basicConfig(level=logging.INFO, format="%(asctime)s %(message)s")
logger = logging.getLogger("serve_ctr")


class _KillablePS:
    """PS wrapper whose verbs raise while ``down`` — the demo's stand-in
    for a real parameter-server outage."""

    def __init__(self, server):
        self._server = server
        self.down = False

    def __getattr__(self, name):
        fn = getattr(self._server, name)

        def wrapper(*a, **kw):
            if self.down:
                raise PSConnectionError("PS down (demo outage)")
            return fn(*a, **kw)
        return wrapper


def build_engine(args):
    server = PSServer()
    server.param_init("snd_order_embedding",
                      (args.vocab, args.embed_dim),
                      "normal", 0.0, 1.0, seed=3)
    comm = _KillablePS(server)
    table = CacheSparseTable(limit=args.cache_limit,
                             vocab_size=args.vocab,
                             width=args.embed_dim,
                             key="snd_order_embedding", comm=comm,
                             policy="LRU")
    rng = np.random.RandomState(0)
    h = 16
    flat = 26 * args.embed_dim
    params = {"W1": rng.randn(13, h) * 0.3,
              "W2": rng.randn(h, h) * 0.3,
              "W3": rng.randn(h, h) * 0.3,
              "W4": rng.randn(flat + h, 1) * 0.3}
    if args.model == "dcn":
        D = flat + 13
        params["W1"] = rng.randn(D, h) * 0.1
        params["W4"] = rng.randn(D + h, 1) * 0.1
        for i in range(3):
            params[f"cross{i}_weight"] = rng.randn(D, 1) * 0.1
            params[f"cross{i}_bias"] = rng.randn(D) * 0.1
    eng = EmbedServingEngine(params, {"snd_order_embedding": table},
                             model=args.model, wave=args.wave,
                             queue_limit=max(64, args.requests))
    return eng, table, comm


def zipf_trace(args):
    """The bench regime: zipf(1.05) sparse ids folded into the vocab —
    a few hot features dominate, which is what makes the cache pay."""
    rng = np.random.RandomState(42)
    reqs = []
    for _ in range(args.requests):
        raw = rng.zipf(1.05, size=(args.pairs, 26))
        reqs.append(EmbedRequest(
            item_ids=(raw - 1) % args.vocab,
            dense_features=rng.randn(args.pairs, 13).astype(np.float32)))
    return reqs


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="wdl", choices=["wdl", "dcn"])
    ap.add_argument("--requests", type=int, default=32)
    ap.add_argument("--pairs", type=int, default=2,
                    help="candidate items per request")
    ap.add_argument("--wave", type=int, default=4)
    ap.add_argument("--vocab", type=int, default=512)
    ap.add_argument("--embed-dim", type=int, default=8)
    ap.add_argument("--cache-limit", type=int, default=128)
    ap.add_argument("--kill-ps", action="store_true",
                    help="kill the PS for the middle third of the trace")
    args = ap.parse_args()

    eng, table, comm = build_engine(args)
    reqs = zipf_trace(args)
    third = len(reqs) // 3
    results = {}

    results.update(eng.run(reqs[:third]))            # warm
    if args.kill_ps:
        logger.info("killing the PS mid-trace")
        comm.down = True
    results.update(eng.run(reqs[third:2 * third]))   # (maybe) dark
    if args.kill_ps:
        comm.down = False
        logger.info("PS back up")
    results.update(eng.run(reqs[2 * third:]))        # recovered

    scored = sum(1 for r in results.values()
                 if r.finish_reason == "scored")
    snap = eng.metrics.snapshot()
    cache = table.perf_summary()
    logger.info("scored %d/%d requests, zero loss=%s",
                scored, len(reqs), scored == len(reqs))
    logger.info("cache: hit_rate %.3f, pulled %d rows (%d bytes), "
                "ps_failures %d, stale_served %d, zero_served %d",
                cache["hit_rate"], cache["pulled_rows"],
                cache["pull_bytes"], cache["ps_failures"],
                cache["stale_served_rows"], cache["zero_served_rows"])
    logger.info("latency p50 %.2fms p99 %.2fms, gather p50 %.2fms, "
                "pairs/s %s",
                (snap["latency_p50_s"] or 0) * 1e3,
                (snap["latency_p99_s"] or 0) * 1e3,
                snap["gather_ms_p50"] or 0, snap["pairs_per_sec"])
    tail = eng.metrics.explain_tail()
    if tail:
        logger.info("%s", tail["summary"])
    if args.kill_ps:
        assert cache["ps_failures"] > 0, "the outage never fired"
    return scored / len(reqs)


if __name__ == "__main__":
    frac = main()
    print(f"OK scored_fraction={frac}")
    sys.exit(0 if frac == 1.0 else 1)
