"""N-worker concurrent PS sd_pushpull scaling bench (VERDICT r2 item 7).

Reference counterpart: ps-lite's multi-worker keyed RPC throughput
(tests/pstests/test_bandwidth.py pattern).  One TCP PSServer on
localhost, N worker PROCESSES each hammering sd_pushpull on a shared
embedding table (zipf-skewed ids, the CTR regime); reports aggregate
embedding rows/s per worker count and writes BENCH_PS_SCALING.json next
to this script (the artifact the round records).

Run: python examples/ctr/bench_ps_scaling.py [--rows 1000000]
"""

from __future__ import annotations

import argparse
import json
import multiprocessing as mp
import os
import socket
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _make_client(ports):
    from hetu_tpu.ps.client import PSClient, _TCPTransport
    if len(ports) > 1:
        from hetu_tpu.ps.sharded import ShardedPSClient
        return ShardedPSClient(
            addrs=[f"127.0.0.1:{p}" for p in ports])
    return PSClient(transport=_TCPTransport("127.0.0.1", ports[0]))


def _timed_pushpull(make, close, key, batch, dim, iters, nrows, seed, q,
                    barrier):
    """Shared measurement body: one warmup round-trip, barrier-aligned
    timed window, rows/s onto the queue.  Both tiers (python PSServer
    client, native van client) run EXACTLY this loop so their numbers
    stay comparable."""
    rng = np.random.RandomState(seed)
    c = make()
    ids = ((rng.zipf(1.05, size=(iters, batch)) - 1) % nrows)
    rows = rng.randn(batch, dim).astype(np.float32)
    # warmup (connection + first apply), then line up: the timed windows
    # must overlap or process spawn/import time pollutes the aggregate
    c.sd_pushpull(key, ids[0], rows)
    barrier.wait()
    t0 = time.perf_counter()
    for i in range(iters):
        c.sd_pushpull(key, ids[i], rows)
    dt = time.perf_counter() - t0
    q.put(batch * iters / dt)
    close(c)


def _worker(ports, key, batch, dim, iters, nrows, seed, q, barrier):
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))))
    _timed_pushpull(lambda: _make_client(ports), lambda c: c.finalize(),
                    key, batch, dim, iters, nrows, seed, q, barrier)


def _van_worker(port, batch, dim, iters, nrows, seed, q, barrier):
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))))
    from hetu_tpu.ps.van import VanClient
    _timed_pushpull(lambda: VanClient("127.0.0.1", port, dim=dim),
                    lambda c: c.close(), 0, batch, dim, iters, nrows,
                    seed, q, barrier)


def _van_serve(port, rows, dim, ready):
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))))
    from hetu_tpu.ps.van import NativeVan
    van = NativeVan()
    van.listen(port)
    van.register_sgd_table(0, np.zeros((rows, dim), np.float32),
                           lr=0.01)
    ready.set()
    while True:
        time.sleep(3600)


def _fan_out(ctx, target, args_for, n):
    """Spawn n measured workers, collect barrier-aligned rates."""
    q = ctx.Queue()
    barrier = ctx.Barrier(n)
    procs = [ctx.Process(target=target, args=args_for(r, q, barrier))
             for r in range(n)]
    for p in procs:
        p.start()
    rates = [q.get(timeout=300) for _ in procs]
    for p in procs:
        p.join()
    return rates


def quant_ab(iters=20, dense_shape=(512, 1024), sparse_batch=4096,
             dim=16, rows=100_000):
    """Int8 PS wire A/B (ISSUE 9): the SAME dense push/pull and sparse
    sd_pushpull traffic against one TCP PSServer, exact f32 vs
    ``HETU_PS_QUANT=int8``, measured by the PR 5 per-shard
    ``ps.rpc.bytes_sent/recv`` counters — the artifact records the wire
    bytes, the reduction ratio (acceptance floor 3.5x, ASSERTED), the
    ``ps.rpc.bytes_saved`` counter, and wall time per round trip.
    Returns the ``quant_ab`` dict merged into BENCH_PS_SCALING.json."""
    from hetu_tpu import envvars, quant, telemetry
    from hetu_tpu.ps.client import PSClient, _TCPTransport

    port = _free_port()
    ctx = mp.get_context("spawn")
    srv = ctx.Process(target=_serve, args=(port,), daemon=True)
    srv.start()
    _wait(port)
    rng = np.random.RandomState(7)
    dense_grad = rng.randn(*dense_shape).astype(np.float32)
    ids = ((rng.zipf(1.05, size=(iters, sparse_batch)) - 1) % rows)
    sparse_rows = rng.randn(sparse_batch, dim).astype(np.float32)

    def measure(mode):
        old = envvars.get_raw("HETU_PS_QUANT")
        if mode:
            os.environ["HETU_PS_QUANT"] = mode
        else:
            os.environ.pop("HETU_PS_QUANT", None)
        telemetry.reset()
        c = PSClient(transport=_TCPTransport("127.0.0.1", port))
        try:
            key = f"qab_{mode or 'off'}"
            c.param_set(key, np.zeros(dense_shape, np.float32),
                        opt="sgd", opt_args={"learning_rate": 0.01})
            c.param_set(key + "_emb", np.zeros((rows, dim), np.float32),
                        opt="sgd", opt_args={"learning_rate": 0.01})
            c.push(key, dense_grad)          # warm the connection
            telemetry.reset()
            t0 = time.perf_counter()
            for i in range(iters):
                c.push(key, dense_grad)
                c.pull(key)
                c.sd_pushpull(key + "_emb", ids[i], sparse_rows)
            dt = time.perf_counter() - t0
            snap = telemetry.snapshot()["counters"]
            out = {
                "quant": mode or "off",
                "iters": iters,
                "wall_s": round(dt, 3),
                "ms_per_round": round(dt / iters * 1e3, 3),
                "bytes_sent": int(snap.get("ps.rpc.bytes_sent", 0)),
                "bytes_recv": int(snap.get("ps.rpc.bytes_recv", 0)),
                "bytes_saved": int(snap.get("ps.rpc.bytes_saved", 0)),
            }
            out["bytes_total"] = out["bytes_sent"] + out["bytes_recv"]
            return out
        finally:
            c.finalize()
            if old is None:
                os.environ.pop("HETU_PS_QUANT", None)
            else:
                os.environ["HETU_PS_QUANT"] = old

    try:
        exact = measure(None)
        int8 = measure("int8")
    finally:
        srv.terminate()
    ratio = round(exact["bytes_total"] / max(int8["bytes_total"], 1), 2)
    section = {
        "config": {"dense_shape": list(dense_shape),
                   "sparse_batch": sparse_batch, "dim": dim,
                   "rows": rows, "iters": iters,
                   "chunk": quant.wire_chunk(),
                   "traffic": "dense push + dense pull + sparse "
                              "sd_pushpull per round",
                   "counters": "ps.rpc.bytes_sent/recv (PR 5), "
                               "ps.rpc.bytes_saved (this PR)"},
        "exact": exact,
        "int8": int8,
        "wire_reduction": ratio,
        "note": "symmetric per-chunk int8 + f32 scales on the typed "
                "wire (ps/wire.py tag Q); dequantized server-side "
                "before the optimizer step, symmetrically on pull; "
                "acceptance floor 3.5x asserted",
    }
    assert ratio >= 3.5, (
        f"int8 PS wire reduction {ratio}x below the 3.5x acceptance "
        f"floor: {exact} vs {int8}")
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "..", "..", "BENCH_PS_SCALING.json")
    path = os.path.abspath(path)
    try:
        with open(path) as f:
            art = json.load(f)
    except (OSError, ValueError):
        art = {"bench": "ps_sd_pushpull_scaling"}
    art["quant_ab"] = section
    with open(path, "w") as f:
        json.dump(art, f, indent=1)
    print(json.dumps({"quant_ab_wire_reduction": ratio,
                      "ms_per_round_exact": exact["ms_per_round"],
                      "ms_per_round_int8": int8["ms_per_round"]}))
    return section


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=1_000_000)
    ap.add_argument("--dim", type=int, default=16)
    ap.add_argument("--batch", type=int, default=4096)
    ap.add_argument("--iters", type=int, default=30)
    ap.add_argument("--workers", default="1,2,4,8")
    ap.add_argument("--servers", default="1,4",
                    help="server-group sizes to sweep (row-sharded)")
    ap.add_argument("--quant-only", action="store_true",
                    help="run ONLY the int8-wire quant A/B and merge "
                         "its quant_ab section into "
                         "BENCH_PS_SCALING.json")
    args = ap.parse_args()

    if args.quant_only:
        quant_ab()
        return

    ctx = mp.get_context("spawn")
    results = {}
    server_counts = [int(x) for x in args.servers.split(",")]
    worker_counts = [int(x) for x in args.workers.split(",")]
    for ns in server_counts:
        ports = [_free_port() for _ in range(ns)]
        srvs = [ctx.Process(target=_serve, args=(p,), daemon=True)
                for p in ports]
        for s in srvs:
            s.start()
        for p in ports:
            _wait(p)
        admin = _make_client(ports)
        # param_set (not parameter_init): the sharded client row-shards
        # explicit 2-D values across the group — the executor bridge path
        admin.param_set("emb", np.zeros((args.rows, args.dim), np.float32),
                        opt="sgd", opt_args={"learning_rate": 0.01})
        for n in worker_counts:
            # barrier-aligned windows: the sum of concurrent per-worker
            # rates is the aggregate service rate
            rates = _fan_out(
                ctx, _worker,
                lambda r, q, b: (ports, "emb", args.batch, args.dim,
                                 args.iters, args.rows, 100 + r, q, b),
                n)
            agg = sum(rates)
            results[f"{n}w_{ns}s"] = {
                "aggregate_rows_per_sec": round(agg, 1),
                "per_worker_rows_per_sec": [round(r, 1) for r in rates],
            }
            print(f"workers={n} servers={ns}: "
                  f"{agg/1e6:.3f}M rows/s aggregate")
        admin.finalize()
        for s in srvs:
            s.terminate()

    # ---- native C++ van tier (ps-lite zmq_van role) ----
    from hetu_tpu.ps.van import van_available
    van_iters = args.iters * 4     # 4x window: the van is ~7x faster,
    if van_available():            # same wall time per cell (recorded)
        port = _free_port()
        ready = ctx.Event()
        srv = ctx.Process(target=_van_serve,
                          args=(port, args.rows, args.dim, ready),
                          daemon=True)
        srv.start()
        if not ready.wait(60):
            raise TimeoutError(
                "van server did not come up (register/listen stalled)")
        _wait(port)
        for n in worker_counts:
            rates = _fan_out(
                ctx, _van_worker,
                lambda r, q, b: (port, args.batch, args.dim, van_iters,
                                 args.rows, 100 + r, q, b),
                n)
            agg = sum(rates)
            results[f"van_{n}w"] = {
                "aggregate_rows_per_sec": round(agg, 1),
                "per_worker_rows_per_sec": [round(r, 1) for r in rates],
            }
            print(f"van workers={n}: {agg/1e6:.3f}M rows/s aggregate")
        srv.terminate()

        # in-process single stream: the van's service rate with no
        # second python process competing for the core
        from hetu_tpu.ps.van import NativeVan, VanClient
        van = NativeVan()
        vport = van.listen()
        van.register_sgd_table(0, np.zeros((args.rows, args.dim),
                                           np.float32), lr=0.01)
        cli = VanClient("127.0.0.1", vport, dim=args.dim)
        rng = np.random.RandomState(0)
        vids = ((rng.zipf(1.05, args.batch) - 1) % args.rows)
        vrows = rng.randn(args.batch, args.dim).astype(np.float32)
        for _ in range(3):
            cli.sd_pushpull(0, vids, vrows)
        t0 = time.perf_counter()
        vit = van_iters
        for _ in range(vit):
            cli.sd_pushpull(0, vids, vrows)
        vr = args.batch * vit / (time.perf_counter() - t0)
        results["van_inprocess_single_stream"] = {
            "aggregate_rows_per_sec": round(vr, 1)}
        print(f"van in-process single stream: {vr/1e6:.3f}M rows/s")
        cli.close()
        van.stop()

    base = results[f"{worker_counts[0]}w_{server_counts[0]}s"][
        "aggregate_rows_per_sec"]
    ncpu = os.cpu_count()
    out = {
        "bench": "ps_sd_pushpull_scaling",
        "config": {"rows": args.rows, "dim": args.dim,
                   "batch": args.batch, "iters": args.iters,
                   "van_iters": args.iters * 4,
                   "transport": "tcp-localhost (python PSServer) + native C++ van (van_Kw rows)", "server_opt": "sgd",
                   "id_skew": "zipf(1.05)", "host_cpu_cores": ncpu,
                   "note": "Kw_Ns = K concurrent worker processes vs an "
                           "N-server row-sharded group. On a "
                           f"{ncpu}-core host every process shares the "
                           "same core(s); the sweep demonstrates "
                           "stability of the aggregate under 8x "
                           "concurrency (no collapse), not parallel "
                           "speedup — that needs cores. van_Kw rows: the "
                           "C++ serving loop (ps/van.py) over TCP; "
                           "van_inprocess_single_stream is its service "
                           "rate with no competing client process — the "
                           "ONE measured van headline figure (earlier "
                           "prose claimed ~16M from a different window; "
                           "the results block is authoritative). "
                           "Multi-process van rows are bounded by the "
                           "PYTHON CLIENTS sharing the same core"},
        "results": results,
        "scaling_vs_base": {k: round(r["aggregate_rows_per_sec"] / base, 2)
                            for k, r in results.items()},
    }
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "..", "..", "BENCH_PS_SCALING.json")
    with open(os.path.abspath(path), "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out["scaling_vs_base"]))


def _serve(port):
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))))
    os.environ["HETU_PS_PORT"] = str(port)
    from hetu_tpu.ps.server import PSServer
    PSServer.serve_from_env()


def _wait(port, timeout=20.0):
    t0 = time.time()
    while time.time() - t0 < timeout:
        try:
            s = socket.create_connection(("127.0.0.1", port), timeout=1.0)
            s.close()
            return
        except OSError:
            time.sleep(0.1)
    raise TimeoutError("PS server did not come up")


if __name__ == "__main__":
    main()
