"""Embedding push/pull throughput vs PS server count.

BASELINE.md's embedding metric is rows trainable per chip; the PS side of
that is sparse push/pull row throughput.  This bench spawns N real server
processes (TCP, like `heturun` does), row-shards a table across them with
ShardedPSClient, and measures sd_pushpull rows/sec for N = 1, 2, 4 — the
reference scales the same way by adding ps-lite server processes.

  python examples/ctr/bench_embedding.py --rows 200000 --dim 64
"""

import os
import sys

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.abspath(__file__)), '..', '..'))

import argparse
import json
import time

import numpy as np


def _worker_main(addrs, rows, dim, batch_ids, iters, seed, out_q,
                 barrier):
    import numpy as np  # noqa: F811  (fresh interpreter)
    import time
    from hetu_tpu.ps.sharded import ShardedPSClient

    c = ShardedPSClient(addrs=addrs)
    rng = np.random.RandomState(seed)
    ids = rng.randint(0, rows, batch_ids).astype(np.int64)
    grads = np.ones((batch_ids, dim), np.float32)
    c.sd_pushpull("bench_table", ids, grads)            # warm
    barrier.wait()      # all workers loaded: start together so the
    t0 = time.perf_counter()   # measured windows overlap
    for _ in range(iters):
        c.sd_pushpull("bench_table", ids, grads)
    out_q.put(batch_ids * iters / (time.perf_counter() - t0))


def bench_group(n_servers, n_workers, rows, dim, batch_ids, iters):
    """The scaling scenario that matters: W worker processes hammer the
    N-server group concurrently (one GIL-bound client cannot load more
    than one server; the reference's ps-lite scales the same way)."""
    import multiprocessing as mp
    from hetu_tpu.launcher import _free_port, _start_ps_process, _wait_ps
    from hetu_tpu.ps.sharded import ShardedPSClient

    ports, procs = [], []
    for _ in range(n_servers):
        port = _free_port()
        procs.append(_start_ps_process(port))
        ports.append(port)
    for port in ports:
        _wait_ps("localhost", port)
    addrs = [f"localhost:{p}" for p in ports]
    try:
        c = ShardedPSClient(addrs=addrs)
        c.param_set("bench_table", np.zeros((rows, dim), np.float32),
                    opt="sgd", opt_args={"learning_rate": 0.1})
        ctx = mp.get_context("spawn")
        q = ctx.Queue()
        barrier = ctx.Barrier(n_workers)
        workers = [ctx.Process(target=_worker_main,
                               args=(addrs, rows, dim, batch_ids, iters,
                                     100 + w, q, barrier))
                   for w in range(n_workers)]
        for w in workers:
            w.start()
        rates = []
        for _ in workers:
            try:
                rates.append(q.get(timeout=300))
            except Exception:
                raise RuntimeError(
                    "a bench worker died before reporting (exit codes: "
                    f"{[w.exitcode for w in workers]})")
        for w in workers:
            w.join()
        c.finalize()
        # windows overlap (barrier-synchronized start): sum of rates
        return sum(rates), rates
    finally:
        for w in locals().get("workers", []):
            if w.is_alive():
                w.terminate()
        for p in procs:
            p.terminate()


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=200_000)
    ap.add_argument("--dim", type=int, default=64)
    ap.add_argument("--batch-ids", type=int, default=8192)
    ap.add_argument("--iters", type=int, default=30)
    ap.add_argument("--workers", type=int, default=4)
    ap.add_argument("--servers", type=int, nargs="+", default=[1, 2, 4])
    args = ap.parse_args()

    cores = os.cpu_count() or 1
    results = {}
    for n in args.servers:
        if cores < n + args.workers:
            print(f"NOTE: {cores} host core(s) < {n} servers + "
                  f"{args.workers} workers — processes timeshare, so "
                  f"these numbers measure protocol overhead, not server "
                  f"scaling (run on a multi-core host for the scaling "
                  f"curve)")
        rps, _ = bench_group(n, args.workers, args.rows, args.dim,
                             args.batch_ids, args.iters)
        results[n] = rps
        print(f"servers={n} workers={args.workers}: {rps/1e6:.3f} M "
              f"rows/sec sd_pushpull (dim {args.dim})")
    base = results[min(results)]
    print(json.dumps({
        "metric": "ps_embedding_pushpull_rows_per_sec",
        "value": round(max(results.values()), 1),
        "unit": "rows/sec",
        "scaling": {str(k): round(v / base, 2) for k, v in
                    results.items()},
    }))


if __name__ == "__main__":
    main()
