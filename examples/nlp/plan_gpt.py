"""Per-model planner entry for the decoder-only (GPT) family —
profile -> calibrate -> search -> apply -> run, the Galvatron per-model
pipeline (reference tools/Galvatron/bert/{profile_forward.py,search}*
has one such entry per model family; this is the decoder one; see
plan_bert.py for the encoder one).

    XLA_FLAGS=--xla_force_host_platform_device_count=8 \\
        JAX_PLATFORMS=cpu python examples/nlp/plan_gpt.py
"""

import os
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.join(_HERE, '..', '..'))

import argparse
import json

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--hidden", type=int, default=64)
    ap.add_argument("--layers", type=int, default=4)
    ap.add_argument("--heads", type=int, default=4)
    ap.add_argument("--seq-len", type=int, default=32)
    ap.add_argument("--vocab", type=int, default=1000)
    ap.add_argument("--global-batch", type=int, default=32)
    ap.add_argument("--steps", type=int, default=3)
    ap.add_argument("--hbm-gb", type=float, default=None)
    args = ap.parse_args()

    import jax
    import hetu_tpu as ht
    from hetu_tpu.models import GPTConfig, GPTForCausalLM
    from hetu_tpu.models.gpt import GPTBlock
    from hetu_tpu.parallel.mesh import make_mesh
    from hetu_tpu.planner import (AutoParallel, LayerSpec, PlannerSearch,
                                  calibrate_layers, graph_layer_fn,
                                  measure_cluster, plan_to_json)

    n_dev = jax.device_count()
    probe_mesh = make_mesh({"dp": n_dev})

    # ---- 1-2. profile + calibrate ------------------------------------
    print(f"[profile] {n_dev} devices, backend={jax.default_backend()}")
    cluster = measure_cluster(
        mesh=probe_mesh,
        probe_dim=512 if jax.default_backend() != "tpu" else 4096)
    if args.hbm_gb:
        cluster.hbm_bytes = args.hbm_gb * 1e9
    print(f"[profile] matmul {cluster.flops_per_sec/1e12:.2f} TFLOP/s, "
          f"hbm {cluster.hbm_bytes/1e9:.1f} GB")

    # one REAL decoder block from the graph API, timed end to end
    pcfg = GPTConfig(vocab_size=args.vocab, hidden_size=args.hidden,
                     num_hidden_layers=1, num_attention_heads=args.heads,
                     max_position_embeddings=args.seq_len,
                     seq_len=args.seq_len, batch_size=8,
                     dropout_rate=0.0)
    xin = ht.placeholder_op("profile_gpt_x")
    block_out = GPTBlock(pcfg, name="profile_gpt_block")(xin)
    fn = graph_layer_fn(block_out, xin)
    layers = [LayerSpec.transformer_decoder(args.hidden, args.seq_len,
                                            name=f"l{i}")
              for i in range(args.layers)]
    calibrate_layers(layers, [lambda x: fn(
        x.reshape(-1, args.hidden))], batch=8)
    print(f"[calibrate] fwd/sample "
          f"{layers[0].fwd_time_per_sample*1e6:.1f} us "
          f"(decoder spec: causal flops, tp factor 6)")

    # ---- 3. search ---------------------------------------------------
    search = PlannerSearch(layers, global_batch_size=args.global_batch,
                           cluster=cluster)
    plan = search.search()
    assert plan is not None, "no feasible plan under the memory cap"
    print("[search]", plan.describe())
    print("[search] json:", json.dumps(plan_to_json(plan)))

    # ---- 4-5. apply + run --------------------------------------------
    pp = plan.mesh_axes().get("pp", 1)
    num_mb = 2 * pp if pp > 1 else 1
    mcfg = GPTConfig(vocab_size=args.vocab, hidden_size=args.hidden,
                     num_hidden_layers=args.layers,
                     num_attention_heads=args.heads,
                     max_position_embeddings=args.seq_len,
                     seq_len=args.seq_len,
                     batch_size=args.global_batch // num_mb,
                     dropout_rate=0.0)
    ids = ht.placeholder_op("input_ids")
    labels = ht.placeholder_op("labels")
    model = GPTForCausalLM(mcfg)
    loss, _ = model(ids, labels=labels)
    train = ht.optim.AdamOptimizer(learning_rate=1e-4).minimize(loss)
    ex = ht.Executor({"train": [loss, train]},
                     dist_strategy=AutoParallel(plan))
    sharded = [k for k, n in ex.variables.items()
               if getattr(n, "sharding_spec", None) is not None]
    sub = ex.subexecutor["train"]
    print(f"[apply] mesh={dict(ex.mesh.shape) if ex.mesh else None}, "
          f"pipeline={ex.config.pipeline} "
          f"(spmd={getattr(sub, 'spmd', False)}), "
          f"{len(sharded)} sharded variables")

    rng = np.random.RandomState(0)
    for step in range(args.steps):
        xb = rng.randint(0, args.vocab,
                         (args.global_batch,
                          args.seq_len)).astype(np.int32)
        yb = ((xb + 1) % args.vocab).astype(np.int32)
        out = ex.run("train", feed_dict={ids: xb, labels: yb})
        print(f"[run] step {step} loss {float(np.asarray(out[0])):.4f}")
    print("OK")


if __name__ == "__main__":
    main()
