"""Long-context causal LM training (capability the reference lacks —
SURVEY.md §5.7: no sequence parallelism, BERT capped at seq 512).

Single chip: Pallas flash attention (O(S) memory, fused backward) makes
seq 4k-8k trainable where the unfused softmax(QK^T)V chain would
materialize the S x S score matrix per head.  Sequences beyond one
chip shard over a 'cp' mesh axis (ring attention / Ulysses in
parallel/context_parallel.py; see tests/test_context_parallel.py for the
multi-device drive — this example is the single-chip path).

  python examples/nlp/train_long_context.py --seq-len 4096   # one TPU
  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
      python examples/nlp/train_long_context.py --seq-len 256 --tiny
"""

import os
import sys

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.abspath(__file__)), '..', '..'))

import argparse
import logging
import time

import numpy as np

import hetu_tpu as ht

logging.basicConfig(level=logging.INFO, format="%(asctime)s %(message)s")
logger = logging.getLogger("longctx")


def build_causal_lm(batch, seq, hidden, heads, layers_n, vocab,
                    use_flash=True, block_q=512, block_k=1024):
    ids = ht.placeholder_op("input_ids")
    emb = ht.layers.Embedding(vocab, hidden, name="lc_tok_emb")
    pos = ht.init.random_normal((seq, hidden), stddev=0.02, name="lc_pos")
    h = ht.embedding_lookup_op(emb.embedding_table, ids)
    h = h + ht.broadcast_shape_op(pos, (batch, seq, hidden), add_axes=[0])
    h = ht.array_reshape_op(h, [batch * seq, hidden])
    for i in range(layers_n):
        attn = ht.layers.MultiHeadAttention(
            hidden, heads, seq, batch, use_flash=use_flash, causal=True,
            block_q=block_q, block_k=block_k, name=f"lc{i}_attn")
        h = ht.layers.LayerNorm(hidden, name=f"lc{i}_ln1")(h + attn(h))
        wi = ht.layers.Linear(hidden, 4 * hidden, name=f"lc{i}_ffn_wi")
        wo = ht.layers.Linear(4 * hidden, hidden, name=f"lc{i}_ffn_wo")
        h = ht.layers.LayerNorm(hidden, name=f"lc{i}_ln2")(
            h + wo(ht.gelu_op(wi(h))))
    logits = ht.layers.Linear(hidden, vocab, name="lc_head")(h)
    # next-token prediction: labels = ids shifted left
    labels = ht.placeholder_op("labels")
    loss = ht.reduce_mean_op(
        ht.softmaxcrossentropy_sparse_op(
            logits, ht.array_reshape_op(labels, [batch * seq])), axes=0)
    return ids, labels, loss


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--seq-len", type=int, default=4096)
    p.add_argument("--batch-size", type=int, default=1)
    p.add_argument("--hidden", type=int, default=768)
    p.add_argument("--heads", type=int, default=12)
    p.add_argument("--layers", type=int, default=4)
    p.add_argument("--vocab", type=int, default=32000)
    p.add_argument("--num-steps", type=int, default=10)
    p.add_argument("--no-flash", action="store_true")
    p.add_argument("--tiny", action="store_true",
                   help="CPU-smoke scale")
    args = p.parse_args()

    if args.tiny:
        args.hidden, args.heads, args.layers, args.vocab = 64, 2, 2, 200
        args.batch_size = max(args.batch_size, 2)
        args.num_steps = min(args.num_steps, 5)

    B, S = args.batch_size, args.seq_len
    ids, labels, loss = build_causal_lm(
        B, S, args.hidden, args.heads, args.layers, args.vocab,
        use_flash=not args.no_flash)
    train = ht.optim.AdamOptimizer(learning_rate=3e-4).minimize(loss)
    ex = ht.Executor({"train": [loss, train]}, mixed_precision="bf16")

    rng = np.random.RandomState(0)
    stream = rng.randint(0, args.vocab, (B, S + 1)).astype(np.int32)
    feed = {ids: stream[:, :-1], labels: stream[:, 1:]}

    l0 = float(np.asarray(ex.run("train", feed_dict=feed)[0]))  # compile
    t0 = time.perf_counter()
    for _ in range(args.num_steps):
        out = ex.run("train", feed_dict=feed)
    lN = float(np.asarray(out[0]))
    dt = (time.perf_counter() - t0) / args.num_steps
    toks = B * S / dt
    logger.info("seq %d: step %.1f ms, %.0f tokens/sec, loss %.4f -> %.4f",
                S, dt * 1e3, toks, l0, lN)
    assert np.isfinite(lN)
    return toks


if __name__ == "__main__":
    main()
