"""Decoder-only causal LM pretraining (GPT-2 topology).

The reference zoo is BERT-centric; this example covers the decoder-only
family with the framework's measured-fast defaults (fused QKV, flash
attention from seq 1024, fused chunked tied head).  Trains on a local
token file when --data-path points at one (uint16/uint32 flat token
stream, nanoGPT-style), otherwise on a synthetic next-token task.
DP via --comm-mode AllReduce over all visible devices.
"""

import os
import sys

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.abspath(__file__)), '..', '..'))

import argparse
import logging
import time

import numpy as np

import hetu_tpu as ht
from hetu_tpu.models import GPTConfig, GPTForCausalLM

logging.basicConfig(level=logging.INFO, format="%(asctime)s %(message)s")
logger = logging.getLogger("gpt")


def load_tokens(path, vocab_size):
    """Flat binary token stream (nanoGPT data format: np.uint16)."""
    dtype = np.uint16 if vocab_size < (1 << 16) else np.uint32
    return np.fromfile(path, dtype=dtype).astype(np.int32)


def load_text_corpus(path, vocab_path):
    """Raw text corpus -> flat token stream through the pretraining
    pipeline (hetu_tpu.pretraining_data); builds a wordpiece vocab from
    the corpus when none is given.  Returns (tokens, vocab_size).  The
    FULL stream feeds batches()'s random windows — no fixed-block
    packing, so no tail tokens are lost."""
    from hetu_tpu.pretraining_data import (
        corpus_token_stream, load_or_build_tokenizer)
    tok = load_or_build_tokenizer(path, vocab_path)
    return corpus_token_stream(path, tok), len(tok.vocab)


def batches(tokens, cfg, rng):
    # valid starts: 0 .. len - seq_len - 1 inclusive (targets need one
    # extra token); randint's high bound is exclusive
    n = len(tokens) - cfg.seq_len
    if n < 1:
        raise SystemExit(
            f"--data-path holds {len(tokens)} tokens; need at least "
            f"seq_len+1 = {cfg.seq_len + 1} for one training window")
    while True:
        starts = rng.randint(0, n, cfg.batch_size)
        x = np.stack([tokens[s:s + cfg.seq_len] for s in starts])
        y = np.stack([tokens[s + 1:s + cfg.seq_len + 1] for s in starts])
        yield x.astype(np.int32), y.astype(np.int32)


def synthetic(cfg, rng):
    """Next token = (3 * token + 7) % vocab — learnable, non-trivial."""
    while True:
        x = rng.randint(0, cfg.vocab_size,
                        (cfg.batch_size, cfg.seq_len)).astype(np.int32)
        y = ((3 * x + 7) % cfg.vocab_size).astype(np.int32)
        yield x, y


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--config", default="small",
                        choices=["small", "medium"])
    parser.add_argument("--batch-size", type=int, default=8)
    parser.add_argument("--seq-len", type=int, default=256)
    parser.add_argument("--num-layers", type=int, default=None)
    parser.add_argument("--vocab-size", type=int, default=50257)
    parser.add_argument("--learning-rate", type=float, default=3e-4)
    parser.add_argument("--clip-grad-norm", type=float, default=1.0,
                        help="global gradient-norm bound (<=0 disables)")
    parser.add_argument("--num-steps", type=int, default=30)
    parser.add_argument("--comm-mode", default=None)
    parser.add_argument("--data-path", default=None,
                        help="flat uint16/uint32 token file (nanoGPT "
                             "format) or a raw .txt corpus; synthetic "
                             "task when absent")
    parser.add_argument("--vocab-path", default=None,
                        help="wordpiece vocab.txt for .txt corpora; "
                             "built from the corpus when absent")
    parser.add_argument("--use-flash", action=argparse.BooleanOptionalAction,
                        default=None,
                        help="pin flash on/off; default: auto (flash "
                             "from seq 1024, dropout permitting)")
    parser.add_argument("--demo-generate", type=int, default=0,
                        help="after training, greedy-decode this many "
                             "tokens from a short prompt")
    args = parser.parse_args()

    make = GPTConfig.medium if args.config == "medium" else GPTConfig.small
    kw = dict(batch_size=args.batch_size, seq_len=args.seq_len,
              max_position_embeddings=args.seq_len,
              vocab_size=args.vocab_size, dropout_rate=0.0,
              use_flash=args.use_flash)
    if args.num_layers:
        kw["num_hidden_layers"] = args.num_layers

    corpus_tokens = None
    if args.data_path and args.data_path.endswith(".txt"):
        corpus_tokens, vocab_size = load_text_corpus(
            args.data_path, args.vocab_path)
        kw["vocab_size"] = max(vocab_size, 128)
        logger.info("tokenized %s: %d tokens, vocab %d", args.data_path,
                    len(corpus_tokens), vocab_size)
    cfg = make(**kw)

    model = GPTForCausalLM(cfg)
    ids = ht.placeholder_op("input_ids")
    labels = ht.placeholder_op("labels")
    loss, _logits = model(ids, labels=labels)
    opt = ht.optim.AdamWOptimizer(learning_rate=args.learning_rate,
                                  weight_decay=0.01)
    if args.clip_grad_norm > 0:
        opt.clip_grad_norm = args.clip_grad_norm
    train_op = opt.minimize(loss)
    subgraphs = {"train": [loss, train_op]}
    gen_ids = None
    if args.demo_generate > 0:
        gen_ids = ht.placeholder_op("gen_input_ids")
        # eval subgraph: no optimizer -> tc.training is False -> every
        # DropoutOp is identity (ops_conv.py DropoutOp), regardless of
        # the config's dropout_rate
        subgraphs["gen"] = [model(gen_ids)]
    executor = ht.Executor(subgraphs, comm_mode=args.comm_mode)

    rng = np.random.RandomState(0)
    if corpus_tokens is not None:
        stream = batches(corpus_tokens, cfg, rng)
        logger.info("training on text corpus %s", args.data_path)
    elif args.data_path and os.path.exists(args.data_path):
        stream = batches(load_tokens(args.data_path, cfg.vocab_size),
                         cfg, rng)
        logger.info("training on %s", args.data_path)
    else:
        stream = synthetic(cfg, rng)
        logger.info("no --data-path: synthetic next-token task")

    t0 = time.time()
    for step in range(args.num_steps):
        x, y = next(stream)
        out = executor.run("train", feed_dict={ids: x, labels: y})
        if step % 10 == 0 or step == args.num_steps - 1:
            dt = time.time() - t0
            toks = (step + 1) * cfg.batch_size * cfg.seq_len / dt
            logger.info("step %d loss=%.4f (%.0f tokens/s)", step,
                        float(np.asarray(out[0]).reshape(-1)[0]), toks)

    if args.demo_generate > 0:
        from hetu_tpu.models.gpt import greedy_generate
        prompt = [int(t) % cfg.vocab_size for t in (1, 2, 3)]
        n = min(args.demo_generate, cfg.seq_len - len(prompt))
        seq = greedy_generate(executor, "gen", gen_ids, 0, prompt, n,
                              cfg.seq_len)
        logger.info("greedy continuation of %s: %s", prompt,
                    seq[len(prompt):])
        # same weights through the KV-cached scan (the serving path):
        # O(S) attention per token instead of a full forward per token
        from hetu_tpu.models.gpt_decode import generate_fast
        fast = generate_fast(executor.var_values, cfg, prompt,
                             num_tokens=n)
        logger.info("kv-cached continuation: %s (match=%s)",
                    fast[0, len(prompt):].tolist(),
                    fast[0].tolist() == seq)


if __name__ == "__main__":
    main()
