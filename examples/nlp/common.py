"""Shared helpers for the NLP example scripts."""

import numpy as np


def synthetic_mlm_batch(rng, cfg, mask_prob=0.15):
    """Synthetic BERT pretraining batch: (ids, token_type, attention_mask,
    mlm_labels, nsp_labels).  [MASK] is 103 in the standard vocab; the
    clamp keeps tiny test vocabs in range."""
    ids = rng.randint(0, cfg.vocab_size, (cfg.batch_size, cfg.seq_len))
    token_type = np.zeros((cfg.batch_size, cfg.seq_len), np.int32)
    token_type[:, cfg.seq_len // 2:] = 1
    mask = np.ones((cfg.batch_size, cfg.seq_len), np.float32)
    mlm_labels = np.full((cfg.batch_size, cfg.seq_len), -1, np.int32)
    masked = rng.rand(cfg.batch_size, cfg.seq_len) < mask_prob
    mlm_labels[masked] = ids[masked]
    ids[masked] = min(103, cfg.vocab_size - 1)  # [MASK]
    nsp = rng.randint(0, 2, (cfg.batch_size,))
    return (ids.astype(np.int32), token_type, mask,
            mlm_labels, nsp.astype(np.int32))
