"""Shared helpers for the NLP example scripts."""

import itertools
import os
import tempfile

import numpy as np


def hermetic_tokenizer(text_lines, vocab_path=None):
    """A wordpiece tokenizer from --vocab-path, or built hermetically
    from the dataset's own text (temp corpus + derived vocab cleaned
    up).  Shared by the GLUE and SQuAD fine-tune examples."""
    from hetu_tpu.pretraining_data import load_or_build_tokenizer
    if vocab_path:
        return load_or_build_tokenizer(None, vocab_path)
    fd, corpus = tempfile.mkstemp(suffix=".txt")
    try:
        with os.fdopen(fd, "w") as f:
            for line in text_lines:
                f.write(line + "\n")
        return load_or_build_tokenizer(corpus)
    finally:
        for path in (corpus, corpus + ".vocab.txt"):
            try:
                os.remove(path)
            except OSError:
                pass


def synthetic_mlm_batch(rng, cfg, mask_prob=0.15):
    """Synthetic BERT pretraining batch: (ids, token_type, attention_mask,
    mlm_labels, nsp_labels).  [MASK] is 103 in the standard vocab; the
    clamp keeps tiny test vocabs in range."""
    ids = rng.randint(0, cfg.vocab_size, (cfg.batch_size, cfg.seq_len))
    token_type = np.zeros((cfg.batch_size, cfg.seq_len), np.int32)
    token_type[:, cfg.seq_len // 2:] = 1
    mask = np.ones((cfg.batch_size, cfg.seq_len), np.float32)
    mlm_labels = np.full((cfg.batch_size, cfg.seq_len), -1, np.int32)
    masked = rng.rand(cfg.batch_size, cfg.seq_len) < mask_prob
    mlm_labels[masked] = ids[masked]
    ids[masked] = min(103, cfg.vocab_size - 1)  # [MASK]
    nsp = rng.randint(0, 2, (cfg.batch_size,))
    return (ids.astype(np.int32), token_type, mask,
            mlm_labels, nsp.astype(np.int32))


def corpus_mlm_stream(data_path, vocab_path, batch_size, seq_len,
                      dupe_factor=5, seed=0):
    """Raw-text corpus -> endless (ids, token_type, attention_mask,
    mlm_labels, nsp) batch stream through the real pretraining pipeline
    (hetu_tpu.pretraining_data).  Returns (stream, vocab_size).  Builds
    a wordpiece vocab from the corpus when no vocab file is given."""
    from hetu_tpu.pretraining_data import (
        PretrainingBatches, create_bert_pretraining_data,
        load_or_build_tokenizer)
    tok = load_or_build_tokenizer(data_path, vocab_path)
    data = create_bert_pretraining_data(
        data_path, tok, max_seq_length=seq_len, dupe_factor=dupe_factor,
        seed=seed)
    batches = PretrainingBatches(data, batch_size, seed=seed)

    def stream():
        for b in itertools.chain.from_iterable(itertools.repeat(batches)):
            yield (b["input_ids"], b["token_type_ids"],
                   b["attention_mask"], b["masked_lm_labels"],
                   b["next_sentence_label"])

    return stream(), len(tok.vocab)
