"""Continuous-batching GPT serving demo (hetu_tpu.serving).

Trains a tiny GPT on the synthetic next-token task next = (x+1) % V —
a few hundred steps make greedy decoding reproduce the arithmetic
chain — then serves a mixed-length request burst through the
ServingEngine: short requests retire and free their slots while a long
straggler keeps decoding, tokens stream per-iteration, and the engine's
metrics (TTFT, tok/s, batch occupancy) print at the end.

    python examples/nlp/serve_gpt.py --requests 6 --slots 2

``--spec K`` turns on speculative decoding: a truncated-layer draft
(the trained model's first layer) proposes K tokens per wave, the
target verifies them in one batched step, and the +1-chain outputs
stay token-identical — on the well-trained chain the draft predicts
the arithmetic too, so most waves emit several tokens:

    python examples/nlp/serve_gpt.py --requests 6 --slots 2 --spec 3
"""

import os
import sys

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.abspath(__file__)), '..', '..'))

import argparse
import logging

import numpy as np

import hetu_tpu as ht
from hetu_tpu.models import GPTConfig, GPTForCausalLM
from hetu_tpu.serving import Request, ServingEngine

logging.basicConfig(level=logging.INFO, format="%(asctime)s %(message)s")
logger = logging.getLogger("serve_gpt")


def train_tiny(cfg, steps, lr):
    m = GPTForCausalLM(cfg, name="sg")
    ids = ht.placeholder_op("sg_ids")
    labels = ht.placeholder_op("sg_labels")
    loss, _ = m(ids, labels=labels)
    train = ht.optim.AdamOptimizer(learning_rate=lr).minimize(loss)
    ex = ht.Executor({"train": [loss, train]})
    rng = np.random.RandomState(1)
    lv = None
    for step in range(steps):
        iv = rng.randint(0, cfg.vocab_size,
                         (cfg.batch_size, cfg.seq_len)).astype(np.int32)
        tv = ((iv + 1) % cfg.vocab_size).astype(np.int32)
        lv = ex.run("train", feed_dict={ids: iv, labels: tv})[0]
        if step % 100 == 0:
            logger.info("train step %d loss %.4f", step, float(lv))
    return ex


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--vocab-size", type=int, default=61)
    ap.add_argument("--hidden", type=int, default=32)
    ap.add_argument("--num-layers", type=int, default=2)
    ap.add_argument("--heads", type=int, default=2)
    ap.add_argument("--seq-len", type=int, default=16)
    ap.add_argument("--train-steps", type=int, default=250)
    ap.add_argument("--lr", type=float, default=3e-3)
    ap.add_argument("--slots", type=int, default=2)
    ap.add_argument("--requests", type=int, default=6)
    ap.add_argument("--spec", type=int, default=0,
                    help="speculative decoding: a truncated-layer "
                         "draft proposes up to this many tokens per "
                         "wave (0 = off); outputs stay token-identical")
    ap.add_argument("--spec-draft-layers", type=int, default=1)
    args = ap.parse_args()

    cfg = GPTConfig(vocab_size=args.vocab_size, hidden_size=args.hidden,
                    num_hidden_layers=args.num_layers,
                    num_attention_heads=args.heads,
                    max_position_embeddings=args.seq_len, batch_size=4,
                    seq_len=args.seq_len, dropout_rate=0.0)
    ex = train_tiny(cfg, args.train_steps, args.lr)

    def stream(req, tok):
        logger.info("  %s += %d", req.request_id, tok)

    eng = ServingEngine(ex.var_values, cfg, slots=args.slots,
                        queue_limit=args.requests,
                        spec=args.spec or None,
                        spec_draft_layers=args.spec_draft_layers)
    rng = np.random.RandomState(7)
    reqs = []
    for i in range(args.requests):
        start = int(rng.randint(0, args.vocab_size - 1))
        # one long straggler, the rest short: the shorts cycle through
        # the straggler's slot-mates while it keeps decoding
        n = args.seq_len - 2 if i == 0 else int(rng.randint(2, 6))
        reqs.append(Request(prompt=[start], max_new_tokens=n,
                            stream_cb=stream))
    results = eng.run(reqs)

    ok = 0
    for r in reqs:
        res = results[r.request_id]
        want = [(r.prompt[0] + k) % args.vocab_size
                for k in range(len(res.tokens))]
        good = res.tokens.tolist() == want
        ok += good
        logger.info("%s (%s, %d tokens, ttft %.1f ms): %s%s",
                    r.request_id, res.finish_reason, res.n_generated,
                    res.ttft_s * 1e3, res.tokens.tolist(),
                    "" if good else f"  EXPECTED {want}")
    snap = eng.metrics.snapshot()
    logger.info("served %d requests, %s tokens @ %s tok/s, "
                "mean occupancy %.2f, %d fused steps",
                snap["requests_finished"], snap["tokens_generated"],
                snap["tokens_per_sec"], snap["mean_batch_occupancy"],
                snap["steps"])
    if args.spec:
        logger.info("speculative: %d waves, accepted %d/%d drafts "
                    "(rate %s), %.2f tokens/step",
                    eng.spec_waves, eng.spec_accepted,
                    eng.spec_proposed,
                    round(eng.spec_acceptance, 3)
                    if eng.spec_acceptance is not None else "-",
                    snap["tokens_per_step_mean"] or 0.0)
    return ok / len(reqs)


if __name__ == "__main__":
    main()
