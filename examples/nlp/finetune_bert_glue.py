"""BERT fine-tuning for GLUE-style sentence classification.

Reference: examples/nlp/bert GLUE fine-tune scripts (SST-2/MRPC etc.) —
load pretrained weights into BertForSequenceClassification, train the
classifier (+ backbone) on labeled pairs, report accuracy.

Offline environment: with --data pointing at a TSV of `label<TAB>text`
the wordpiece tokenizer encodes it; otherwise a synthetic, *learnable*
task stands in (label = whether the count of tokens from the first half
of the vocab exceeds half the sequence), so accuracy measurably rises.

Distribution: --comm-mode AllReduce shards the batch over all visible
devices ('dp' mesh axis; XLA inserts the gradient psum).

  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
      python examples/nlp/finetune_bert_glue.py --num-steps 30
"""

import os
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.join(_HERE, '..', '..'))
sys.path.insert(0, _HERE)   # for the shared `common` helpers

import argparse
import logging
import time

import numpy as np

import hetu_tpu as ht
from hetu_tpu.glue import (PROCESSORS, compute_metrics,
                           convert_examples_to_arrays)
from hetu_tpu.models import BertConfig, BertForSequenceClassification
from common import hermetic_tokenizer

logging.basicConfig(level=logging.INFO, format="%(asctime)s %(message)s")
logger = logging.getLogger("glue")


def load_tsv(path, tokenizer_dir, seq_len, vocab_size):
    """label<TAB>text TSV through the offline wordpiece tokenizer."""
    from hetu_tpu.tokenizers import BertWordPieceTokenizer
    tok = BertWordPieceTokenizer.from_pretrained(tokenizer_dir)
    ids, labels = [], []
    with open(path) as f:
        for line in f:
            lab, text = line.rstrip("\n").split("\t", 1)
            enc = tok.encode(text)[:seq_len]
            enc = enc + [0] * (seq_len - len(enc))
            ids.append(enc)
            labels.append(int(lab))
    return (np.asarray(ids, np.int32) % vocab_size,
            np.asarray(labels, np.int32))


def load_glue_task(task, data_dir, vocab_path, seq_len):
    """Official-format GLUE TSVs through the task processor suite
    (reference glue_processor/glue.py).  Returns (train arrays, dev
    arrays, num_labels, vocab_size); each arrays tuple is
    (input_ids, attention_mask, token_type_ids, labels)."""
    proc = PROCESSORS[task.lower()]()
    train_ex = proc.get_train_examples(data_dir)
    dev_ex = proc.get_dev_examples(data_dir)
    if not vocab_path:
        cand = os.path.join(data_dir, "vocab.txt")
        if os.path.exists(cand):
            vocab_path = cand

    def lines():
        for ex in train_ex + dev_ex:
            yield ex.text_a
            if ex.text_b:
                yield ex.text_b
    tok = hermetic_tokenizer(lines(), vocab_path)
    lab = proc.get_labels()
    return (convert_examples_to_arrays(train_ex, lab, seq_len, tok),
            convert_examples_to_arrays(dev_ex, lab, seq_len, tok),
            len(lab), len(tok.vocab))


def synthetic(rng, n, seq_len, vocab_size):
    """Learnable stand-in: label = [more than half the tokens come from
    the first half of the vocabulary]."""
    ids = rng.randint(0, vocab_size, (n, seq_len)).astype(np.int32)
    labels = ((ids < vocab_size // 2).mean(axis=1) > 0.5).astype(np.int32)
    return ids, labels


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--config", default="base", choices=["base", "large"])
    p.add_argument("--num-layers", type=int, default=2,
                   help="encoder depth override (small default: the "
                        "synthetic task needs no 12 layers)")
    p.add_argument("--hidden", type=int, default=128)
    p.add_argument("--heads", type=int, default=4)
    p.add_argument("--vocab", type=int, default=1000)
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--seq-len", type=int, default=32)
    p.add_argument("--num-labels", type=int, default=2)
    p.add_argument("--learning-rate", type=float, default=5e-4)
    p.add_argument("--num-steps", type=int, default=40)
    p.add_argument("--eval-every", type=int, default=10)
    p.add_argument("--data", default=None, help="label<TAB>text TSV")
    p.add_argument("--task", default=None,
                   choices=sorted(PROCESSORS),
                   help="GLUE task name; reads official TSVs from "
                        "--data-dir via the processor suite")
    p.add_argument("--data-dir", default=None)
    p.add_argument("--vocab-path", default=None)
    p.add_argument("--tokenizer-dir", default=None)
    p.add_argument("--init-checkpoint", default=None,
                   help="directory saved by a pretraining Executor; "
                        "backbone weights load by name, heads stay fresh")
    p.add_argument("--comm-mode", default=None,
                   choices=[None, "AllReduce"])
    args = p.parse_args()

    glue_train = glue_dev = None
    if args.task:
        assert args.data_dir, "--task needs --data-dir"
        glue_train, glue_dev, args.num_labels, args.vocab = \
            load_glue_task(args.task, args.data_dir, args.vocab_path,
                           args.seq_len)
        logger.info("task %s: %d train / %d dev examples, vocab %d",
                    args.task, len(glue_train[0]), len(glue_dev[0]),
                    args.vocab)

    import jax
    mesh = None
    if args.comm_mode == "AllReduce" and jax.device_count() > 1:
        from hetu_tpu.parallel.mesh import make_mesh
        mesh = make_mesh({"dp": jax.device_count()})
        assert args.batch_size % jax.device_count() == 0

    cfg = BertConfig(vocab_size=args.vocab, hidden_size=args.hidden,
                     num_hidden_layers=args.num_layers,
                     num_attention_heads=args.heads,
                     intermediate_size=4 * args.hidden,
                     seq_len=args.seq_len, batch_size=args.batch_size,
                     hidden_dropout_prob=0.1,
                     attention_probs_dropout_prob=0.1)
    ids = ht.placeholder_op("input_ids")
    tok_ids = ht.placeholder_op("token_type_ids")
    mask = ht.placeholder_op("attention_mask")
    labels = ht.placeholder_op("labels")
    model = BertForSequenceClassification(cfg, num_labels=args.num_labels)
    loss, logits = model(ids, tok_ids, mask, labels=labels)
    opt = ht.optim.AdamWOptimizer(learning_rate=args.learning_rate,
                                  weight_decay=0.01)
    train = opt.minimize(loss)
    ex = ht.Executor({"train": [loss, train], "eval": [loss, logits]},
                     mesh=mesh)

    if args.init_checkpoint:
        import pickle
        with open(os.path.join(args.init_checkpoint,
                               "checkpoint.pkl"), "rb") as f:
            ckpt = pickle.load(f)
        pre = {k: v for k, v in ckpt["params"].items()
               if k in ex.variables and "classifier" not in k}
        ex.load_dict(pre)
        logger.info("loaded %d backbone tensors from %s",
                    len(pre), args.init_checkpoint)

    rng = np.random.RandomState(0)
    if glue_train is not None:
        tr_ids, tr_m, tr_t, tr_y = glue_train
        ev_ids, ev_m, ev_t, ev_y = glue_dev
        n_dev = len(ev_ids)
        # pad dev to a batch multiple by WRAPPING, and remember each
        # row's example index so metrics count every example exactly
        # once (plain repetition would double-weight an arbitrary
        # prefix and drop tails)
        pad_to = max(args.batch_size,
                     -(-n_dev // args.batch_size) * args.batch_size)
        ev_index = np.arange(pad_to) % n_dev
        ev_ids, ev_m, ev_t, ev_y = (a[ev_index]
                                    for a in (ev_ids, ev_m, ev_t, ev_y))
        reps_t = max(1, -(-2 * args.batch_size // max(len(tr_ids), 1)))
        tr_ids, tr_m, tr_t, tr_y = (np.concatenate([a] * reps_t)
                                    for a in (tr_ids, tr_m, tr_t, tr_y))
    else:
        if args.data:
            all_ids, all_labels = load_tsv(args.data, args.tokenizer_dir,
                                           args.seq_len, args.vocab)
        else:
            all_ids, all_labels = synthetic(rng, 4096, args.seq_len,
                                            args.vocab)
        split = int(0.9 * len(all_ids))
        tr_ids, tr_y = all_ids[:split], all_labels[:split]
        ev_ids, ev_y = all_ids[split:], all_labels[split:]
        tr_m = np.ones(tr_ids.shape, np.float32)
        ev_m = np.ones(ev_ids.shape, np.float32)
        tr_t = np.zeros(tr_ids.shape, np.int32)
        ev_t = np.zeros(ev_ids.shape, np.int32)

    def evaluate():
        preds, gold, idxs = [], [], []
        for i in range(0, len(ev_ids) - args.batch_size + 1,
                       args.batch_size):
            sl = slice(i, i + args.batch_size)
            _, lg = ex.run("eval", feed_dict={
                ids: ev_ids[sl], tok_ids: ev_t[sl], mask: ev_m[sl],
                labels: ev_y[sl]}, convert_to_numpy_ret_vals=True)
            preds.append(lg.argmax(-1))
            gold.append(ev_y[sl])
            if args.task:
                idxs.append(ev_index[sl])
        if not preds:
            return 0.0
        preds = np.concatenate(preds)
        gold = np.concatenate(gold)
        if args.task:
            # deduplicate the wrap-padding: one vote per dev example
            uniq = {}
            for j, pr, gl in zip(np.concatenate(idxs), preds, gold):
                uniq[int(j)] = (pr, gl)
            preds = np.array([v[0] for v in uniq.values()])
            gold = np.array([v[1] for v in uniq.values()])
            m = compute_metrics(args.task, preds, gold)
            logger.info("eval metrics %s (%d examples)", m, len(preds))
            return m["accuracy"]
        return float((preds == gold).mean())

    logger.info("initial eval accuracy %.3f", evaluate())
    t0 = time.time()
    for step in range(args.num_steps):
        j = rng.randint(0, len(tr_ids) - args.batch_size)
        sl = slice(j, j + args.batch_size)
        out = ex.run("train", feed_dict={
            ids: tr_ids[sl], tok_ids: tr_t[sl], mask: tr_m[sl],
            labels: tr_y[sl]})
        if (step + 1) % args.eval_every == 0:
            acc = evaluate()
            logger.info("step %d loss %.4f eval acc %.3f (%.1f s)",
                        step + 1, float(np.asarray(out[0])), acc,
                        time.time() - t0)
    final = evaluate()
    logger.info("final eval accuracy %.3f", final)
    return final


if __name__ == "__main__":
    main()
