"""Transformer machine translation (reference
examples/nlp/hetu_transformer.py / train_hetu_transformer.py).

Offline environment: a synthetic, *learnable* translation task stands in
for WMT — the "translation" of a source sequence is its reversal with a
fixed vocabulary permutation applied, so the encoder-decoder attention
has real structure to learn and token accuracy measurably rises.
Teacher forcing: decoder input is the shifted target.

DP over all visible devices via --comm-mode AllReduce.

  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
      python examples/nlp/train_transformer.py --num-steps 60
"""

import os
import sys

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.abspath(__file__)), '..', '..'))

import argparse
import logging
import time

import numpy as np

import hetu_tpu as ht
from hetu_tpu.models.transformer import Transformer, TransformerConfig

logging.basicConfig(level=logging.INFO, format="%(asctime)s %(message)s")
logger = logging.getLogger("mt")


def synthetic_pairs(rng, n, vocab, src_len, tgt_len, pad_id=0, bos_id=1):
    """tgt = reverse(permute(src)); ids 2..vocab-1 are 'words'."""
    perm = np.arange(vocab)
    perm[2:] = 2 + rng.permutation(vocab - 2)
    src = rng.randint(2, vocab, (n, src_len)).astype(np.int32)
    tgt_core = perm[src[:, ::-1]][:, :tgt_len - 1]

    def pad_to(a, width):
        return np.concatenate(
            [a, np.full((n, width - a.shape[1]), pad_id, np.int32)],
            axis=1) if a.shape[1] < width else a[:, :width]

    dec_in = np.concatenate(
        [np.full((n, 1), bos_id, np.int32), tgt_core[:, :-1]], axis=1)
    labels = np.concatenate(
        [tgt_core, np.full((n, 1), pad_id, np.int32)], axis=1)
    return (src, pad_to(dec_in, tgt_len).astype(np.int32),
            pad_to(labels, tgt_len).astype(np.int32))


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--vocab", type=int, default=64)
    p.add_argument("--hidden", type=int, default=64)
    p.add_argument("--layers", type=int, default=2)
    p.add_argument("--heads", type=int, default=4)
    p.add_argument("--ffn", type=int, default=128)
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--src-len", type=int, default=12)
    p.add_argument("--tgt-len", type=int, default=12)
    p.add_argument("--learning-rate", type=float, default=1e-3)
    p.add_argument("--num-steps", type=int, default=80)
    p.add_argument("--log-every", type=int, default=20)
    p.add_argument("--comm-mode", default=None, choices=[None, "AllReduce"])
    args = p.parse_args()

    import jax
    mesh = None
    if args.comm_mode == "AllReduce" and jax.device_count() > 1:
        from hetu_tpu.parallel.mesh import make_mesh
        mesh = make_mesh({"dp": jax.device_count()})
        assert args.batch_size % jax.device_count() == 0

    cfg = TransformerConfig(
        src_vocab_size=args.vocab, tgt_vocab_size=args.vocab,
        hidden_size=args.hidden, num_layers=args.layers,
        num_heads=args.heads, ffn_size=args.ffn, dropout_rate=0.0,
        batch_size=args.batch_size, src_len=args.src_len,
        tgt_len=args.tgt_len)
    src = ht.placeholder_op("src_ids")
    tgt = ht.placeholder_op("tgt_ids")
    labels = ht.placeholder_op("labels")
    model = Transformer(cfg)
    loss, logits = model(src, tgt, labels=labels)
    train = ht.optim.AdamOptimizer(
        learning_rate=args.learning_rate).minimize(loss)
    ex = ht.Executor({"train": [loss, train], "eval": [logits]},
                     mesh=mesh)

    rng = np.random.RandomState(0)
    S, D, L = synthetic_pairs(rng, 4096, args.vocab, args.src_len,
                              args.tgt_len)

    def token_acc():
        lg = np.asarray(ex.run("eval", feed_dict={
            src: S[:args.batch_size], tgt: D[:args.batch_size],
            labels: L[:args.batch_size]})[0])
        lg = lg.reshape(args.batch_size, args.tgt_len, -1)
        pred = lg.argmax(-1)
        mask = L[:args.batch_size] != 0
        return (pred == L[:args.batch_size])[mask].mean()

    t0 = time.time()
    for step in range(args.num_steps):
        j = rng.randint(0, len(S) - args.batch_size)
        out = ex.run("train", feed_dict={
            src: S[j:j + args.batch_size],
            tgt: D[j:j + args.batch_size],
            labels: L[j:j + args.batch_size]})
        if (step + 1) % args.log_every == 0:
            logger.info("step %d loss %.4f token_acc %.3f (%.1f s)",
                        step + 1, float(np.asarray(out[0])),
                        token_acc(), time.time() - t0)
    acc = token_acc()
    logger.info("final token accuracy %.3f", acc)
    return acc


if __name__ == "__main__":
    main()
