"""BERT pretraining (reference examples/nlp/bert/train_hetu_bert.py).

MLM + NSP on tokenized corpus batches; falls back to synthetic token
streams when no corpus is present.  DP over all visible devices via
--comm-mode AllReduce (mesh sharding, not graph rewrite).
"""

import os
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.join(_HERE, '..', '..'))
sys.path.insert(0, _HERE)   # for the shared `common` helpers

import argparse
import logging
import time

import numpy as np

import hetu_tpu as ht
from hetu_tpu.models import BertConfig, BertForPreTraining

from common import corpus_mlm_stream, synthetic_mlm_batch

logging.basicConfig(level=logging.INFO, format="%(asctime)s %(message)s")
logger = logging.getLogger("bert")


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--config", default="base", choices=["base", "large"])
    parser.add_argument("--batch-size", type=int, default=8)
    parser.add_argument("--seq-len", type=int, default=128)
    parser.add_argument("--num-layers", type=int, default=None)
    parser.add_argument("--learning-rate", type=float, default=1e-4)
    parser.add_argument("--num-steps", type=int, default=30)
    parser.add_argument("--comm-mode", default=None)
    parser.add_argument("--use-flash", action="store_true")
    parser.add_argument("--data-path", default=None,
                        help="raw text corpus (one sentence per line, "
                             "blank line between documents); synthetic "
                             "batches when absent")
    parser.add_argument("--vocab-path", default=None,
                        help="wordpiece vocab.txt; built from the "
                             "corpus when absent")
    parser.add_argument("--dupe-factor", type=int, default=5)
    args = parser.parse_args()

    make = BertConfig.large if args.config == "large" else BertConfig.base
    kw = dict(batch_size=args.batch_size, seq_len=args.seq_len,
              use_flash_attention=args.use_flash)
    if args.num_layers:
        kw["num_hidden_layers"] = args.num_layers

    stream = None
    if args.data_path:
        stream, vocab_size = corpus_mlm_stream(
            args.data_path, args.vocab_path, args.batch_size,
            args.seq_len, dupe_factor=args.dupe_factor)
        kw["vocab_size"] = max(vocab_size, 128)
        logger.info("pretraining on %s (vocab %d)", args.data_path,
                    vocab_size)
    cfg = make(**kw)

    model = BertForPreTraining(cfg)
    ids = ht.placeholder_op("input_ids")
    tok = ht.placeholder_op("token_type_ids")
    mask = ht.placeholder_op("attention_mask")
    mlm = ht.placeholder_op("masked_lm_labels")
    nsp = ht.placeholder_op("next_sentence_label")
    loss, _, _ = model(ids, tok, mask, mlm, nsp)
    opt = ht.optim.AdamWOptimizer(learning_rate=args.learning_rate,
                                  weight_decay=0.01)
    train_op = opt.minimize(loss)
    executor = ht.Executor({"train": [loss, train_op]},
                           comm_mode=args.comm_mode)

    rng = np.random.RandomState(0)
    t0 = time.time()
    last = None
    for step in range(args.num_steps):
        if stream is not None:
            b_ids, b_tok, b_mask, b_mlm, b_nsp = next(stream)
        else:
            b_ids, b_tok, b_mask, b_mlm, b_nsp = synthetic_mlm_batch(
                rng, cfg)
        out = executor.run("train", feed_dict={
            ids: b_ids, tok: b_tok, mask: b_mask, mlm: b_mlm, nsp: b_nsp})
        last = float(np.asarray(out[0]).reshape(-1)[0])
        if step % 10 == 0 or step == args.num_steps - 1:
            dt = time.time() - t0
            sps = (step + 1) * cfg.batch_size / dt
            logger.info("step %d loss=%.4f (%.1f samples/s)", step,
                        last, sps)
    return last


if __name__ == "__main__":
    main()
