"""BERT-MoE pretraining (reference examples/nlp/bert/
train_hetu_bert_dp_moe.py driving hetu_bert_moe.py): the flagship LM
with MoE FFN blocks, trained over a dp x ep device mesh.

The expert stacks shard over 'ep' (GSPMD emits the token all-to-all at
the alltoall markers); everything else data-parallels over 'dp'.
Synthetic MLM/NSP batches — point --data-path at a corpus file for the
real pipeline (same flag surface as train_bert.py).
"""

import os
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.join(_HERE, '..', '..'))
sys.path.insert(0, _HERE)   # for the shared `common` helpers

import argparse
import logging
import time

import numpy as np

import hetu_tpu as ht
from hetu_tpu.models import BertMoEConfig, BertMoEForPreTraining

from common import corpus_mlm_stream, synthetic_mlm_batch

logging.basicConfig(level=logging.INFO, format="%(asctime)s %(message)s")
logger = logging.getLogger("bert_moe")


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--batch-size", type=int, default=8)
    parser.add_argument("--seq-len", type=int, default=128)
    parser.add_argument("--num-layers", type=int, default=12)
    parser.add_argument("--hidden", type=int, default=768)
    parser.add_argument("--heads", type=int, default=12)
    parser.add_argument("--vocab-size", type=int, default=30522)
    parser.add_argument("--num-experts", type=int, default=8)
    parser.add_argument("--top-k", type=int, default=1)
    parser.add_argument("--moe-every", type=int, default=2,
                        help="every Nth block gets the MoE FFN "
                             "(1 = all blocks, the reference placement)")
    parser.add_argument("--ep", type=int, default=1,
                        help="expert-parallel mesh extent")
    parser.add_argument("--dp", type=int, default=1,
                        help="data-parallel mesh extent")
    parser.add_argument("--learning-rate", type=float, default=1e-4)
    parser.add_argument("--num-steps", type=int, default=30)
    parser.add_argument("--data-path", default=None,
                        help="raw text corpus (one sentence per line, "
                             "blank line between documents); synthetic "
                             "batches when absent")
    parser.add_argument("--vocab-path", default=None)
    args = parser.parse_args()

    stream = None
    if args.data_path:
        stream, vocab_size = corpus_mlm_stream(
            args.data_path, args.vocab_path, args.batch_size,
            args.seq_len)
        args.vocab_size = max(vocab_size, 128)
        logger.info("pretraining on %s (vocab %d)", args.data_path,
                    vocab_size)

    cfg = BertMoEConfig(
        vocab_size=args.vocab_size, hidden_size=args.hidden,
        num_hidden_layers=args.num_layers, num_attention_heads=args.heads,
        intermediate_size=4 * args.hidden,
        max_position_embeddings=max(512, args.seq_len),
        batch_size=args.batch_size, seq_len=args.seq_len,
        num_experts=args.num_experts, top_k=args.top_k,
        moe_every=args.moe_every)

    model = BertMoEForPreTraining(cfg)
    ids = ht.placeholder_op("input_ids")
    tok = ht.placeholder_op("token_type_ids")
    mask = ht.placeholder_op("attention_mask")
    mlm = ht.placeholder_op("masked_lm_labels")
    nsp = ht.placeholder_op("next_sentence_label")
    loss, _, _ = model(ids, tok, attention_mask=mask,
                       masked_lm_labels=mlm, next_sentence_label=nsp)
    opt = ht.optim.AdamWOptimizer(learning_rate=args.learning_rate,
                                  weight_decay=0.01)
    train_op = opt.minimize(loss)
    strategy = None
    if args.ep > 1 or args.dp > 1:
        strategy = ht.dist.ExpertParallel(ep=args.ep, dp=args.dp)
    executor = ht.Executor({"train": [loss, train_op]},
                           dist_strategy=strategy)

    rng = np.random.RandomState(0)
    t0 = time.time()
    last = None
    for step in range(args.num_steps):
        if stream is not None:
            b_ids, b_tok, b_mask, b_mlm, b_nsp = next(stream)
        else:
            b_ids, b_tok, b_mask, b_mlm, b_nsp = synthetic_mlm_batch(
                rng, cfg)
        out = executor.run("train", feed_dict={
            ids: b_ids, tok: b_tok, mask: b_mask, mlm: b_mlm,
            nsp: b_nsp})
        last = float(np.asarray(out[0]).reshape(-1)[0])
        if step % 10 == 0 or step == args.num_steps - 1:
            dt = time.time() - t0
            sps = (step + 1) * cfg.batch_size / dt
            logger.info("step %d loss=%.4f (%.1f samples/s)", step,
                        last, sps)
    return last


if __name__ == "__main__":
    main()
