"""BERT fine-tuning for SQuAD-style span extraction.

Reference: the BERT example suite's SQuAD stage
(examples/nlp/bert/data/SquadDownloader.py:1, data/bertPrep.py:1 stage
the official JSON) — load weights into BertForQuestionAnswering, train
start/end span prediction over doc-stride windows, report exact-match
and F1 with the official normalization.

Offline environment: --data points at an official-format SQuAD JSON
(tests/fixtures/squad/train-tiny.json is format-faithful); the vocab
comes from --vocab-path or is built hermetically from the contexts via
the shared bootstrap.

Distribution: --comm-mode AllReduce shards the batch over all visible
devices ('dp' mesh axis; XLA inserts the gradient psum).

  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
      python examples/nlp/finetune_bert_squad.py \
          --data tests/fixtures/squad/train-tiny.json --num-steps 60
"""

import os
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.join(_HERE, '..', '..'))
sys.path.insert(0, _HERE)   # for the shared `common` helpers

import argparse
import logging

import numpy as np

import hetu_tpu as ht
from hetu_tpu.models import BertConfig, BertForQuestionAnswering
from hetu_tpu.squad import (convert_examples_to_features,
                            extract_predictions, features_to_arrays,
                            read_squad_examples, squad_evaluate)
from common import hermetic_tokenizer

logging.basicConfig(level=logging.INFO, format="%(asctime)s %(message)s")
logger = logging.getLogger("squad")


def build_tokenizer(examples, vocab_path):
    def lines():
        for ex in examples:
            yield " ".join(ex.doc_tokens)
            yield ex.question_text
    return hermetic_tokenizer(lines(), vocab_path)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--data", required=True,
                   help="official-format SQuAD JSON (v1.1 or v2.0)")
    p.add_argument("--vocab-path", default=None)
    p.add_argument("--num-layers", type=int, default=2)
    p.add_argument("--hidden", type=int, default=64)
    p.add_argument("--heads", type=int, default=4)
    p.add_argument("--batch-size", type=int, default=8)
    p.add_argument("--seq-len", type=int, default=64)
    p.add_argument("--doc-stride", type=int, default=32)
    p.add_argument("--num-steps", type=int, default=60)
    p.add_argument("--learning-rate", type=float, default=2e-3)
    p.add_argument("--comm-mode", default=None,
                   choices=[None, "AllReduce"])
    args = p.parse_args()

    examples = read_squad_examples(args.data, is_training=True)
    tok = build_tokenizer(examples, args.vocab_path)
    features = convert_examples_to_features(
        examples, tok, max_seq_length=args.seq_len,
        doc_stride=args.doc_stride, max_query_length=16)
    arrays = features_to_arrays(features)
    n = len(features)
    logger.info("examples=%d features=%d vocab=%d",
                len(examples), n, len(tok.vocab))

    cfg = BertConfig(
        vocab_size=len(tok.vocab), hidden_size=args.hidden,
        num_hidden_layers=args.num_layers,
        num_attention_heads=args.heads,
        intermediate_size=4 * args.hidden,
        max_position_embeddings=max(args.seq_len, 64),
        batch_size=args.batch_size, seq_len=args.seq_len,
        hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0)
    model = BertForQuestionAnswering(cfg, name="bert_squad")

    ids = ht.placeholder_op("input_ids")
    mask = ht.placeholder_op("input_mask")
    segs = ht.placeholder_op("segment_ids")
    sp = ht.placeholder_op("start_positions")
    ep = ht.placeholder_op("end_positions")
    loss, start_logits, end_logits = model(
        ids, token_type_ids=segs, attention_mask=mask,
        start_positions=sp, end_positions=ep)
    opt = ht.optim.AdamOptimizer(learning_rate=args.learning_rate)
    train = opt.minimize(loss)
    kw = {}
    if args.comm_mode:
        kw.update(comm_mode=args.comm_mode,
                  dist_strategy=ht.dist.DataParallel())
    ex = ht.Executor({"train": [loss, train],
                      "eval": [start_logits, end_logits]}, **kw)

    rng = np.random.RandomState(0)
    for step in range(args.num_steps):
        take = rng.randint(0, n, args.batch_size)
        out = ex.run("train", feed_dict={
            ids: arrays["input_ids"][take],
            mask: arrays["input_mask"][take],
            segs: arrays["segment_ids"][take],
            sp: arrays["start_positions"][take],
            ep: arrays["end_positions"][take]})
        if step % 20 == 0 or step == args.num_steps - 1:
            logger.info("step %d loss %.4f", step,
                        float(np.asarray(out[0])))

    # eval: run every window through the trained head, extract spans
    all_start, all_end = [], []
    pad_to = (-n) % args.batch_size
    order = list(range(n)) + [0] * pad_to
    for i in range(0, len(order), args.batch_size):
        take = order[i:i + args.batch_size]
        s_l, e_l = ex.run("eval", feed_dict={
            ids: arrays["input_ids"][take],
            mask: arrays["input_mask"][take],
            segs: arrays["segment_ids"][take],
            sp: arrays["start_positions"][take],
            ep: arrays["end_positions"][take]})
        all_start.append(np.asarray(s_l))
        all_end.append(np.asarray(e_l))
    start_logits = np.concatenate(all_start)[:n]
    end_logits = np.concatenate(all_end)[:n]
    preds = extract_predictions(examples, features, start_logits,
                                end_logits)
    metrics = squad_evaluate(examples, preds)
    logger.info("exact_match %.2f f1 %.2f", metrics["exact_match"],
                metrics["f1"])
    return metrics


if __name__ == "__main__":
    main()
