"""Benchmark matrix: per-config JSON artifacts + ONE headline JSON line.

VERDICT r2 item 1: the flagship number must be the TRUE config, not a
proxy, and every BASELINE.md config must persist a per-config artifact.
Configs (BASELINE.md table):

  bert_base     BERT-base TRUE: 12 layers, seq 512, hidden 768, flash
                attention, bf16 — samples/s/chip + MFU   (headline line)
  bert4l        the round-1/2 4-layer seq-128 proxy (round-over-round
                continuity with BENCH_r01/r02)
  resnet18      ResNet-18 / CIFAR-10 shapes                (config 1)
  ctr_hybrid    Wide&Deep Criteo-shape, PS+HET-cache Hybrid: samples/s,
                embedding rows/s, cache hit rate           (config 3)
  moe           MoE MLP top-2 gate: tokens/s               (config 4)
  long_context  32k-token causal flash attention: tokens/s (new-capability
                axis; the reference caps at seq 512)

Every config's full stats land in BENCH_MATRIX.json (written incrementally
— a crash mid-matrix keeps earlier configs).  stdout still carries exactly
ONE JSON line (the driver contract): the bert_base headline with
`"matrix"` carrying each other config's key number.

Robustness: TPU bring-up is probed in a subprocess with a hard timeout
(the axon tunnel's observed failure modes are both a RuntimeError and a
plain hang), retried on a ~9-minute deadline budget — the r2 outage that
cost the round's artifact lasted minutes, not seconds.  On persistent
failure the bench falls back to CPU at verification scale and says so.

Select a subset with HETU_BENCH_CONFIGS=bert_base,moe; force small scale
with HETU_BENCH_SMALL=1.
"""

from __future__ import annotations

import json
import os
import time

from hetu_tpu import envvars

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_TPU_LAST_FILE = os.path.join(_HERE, "BENCH_TPU_LAST.json")
_MATRIX_FILE = os.path.join(_HERE, "BENCH_MATRIX.json")


def _peak_tflops(device_kind: str):
    """bf16 spec peak for the MFU denominator — single source of truth
    lives next to the calibration's physics ceiling."""
    from hetu_tpu.planner.chip_calibration import spec_peak_tflops
    return spec_peak_tflops(device_kind)


_PROBE_SRC = """
import jax, numpy as np, jax.numpy as jnp
jax.devices()
np.asarray(jnp.zeros((8, 8)) + 1.0)  # forces backend bring-up + compile
print(jax.default_backend())
"""


def _bring_up_backend(budget_s=540.0, probe_timeout=150.0):
    """Probe the default backend in a SUBPROCESS with a hard timeout.

    Two TPU failure modes observed (r1 rc=1 and the wedged-tunnel case
    from the verify notes): backend init raises RuntimeError(UNAVAILABLE),
    or jax.devices() simply HANGS when the axon tunnel is down.  An
    in-process probe cannot recover from the hang, so we probe
    out-of-process; only a clean probe lets this process touch the default
    backend.  Retries run against a deadline of ``budget_s`` — the r2
    outage mode lasted minutes (BENCH_r02's probe gave up in ~4), so the
    budget is ~9 minutes with escalating backoff.  On failure we force CPU
    via jax.config (the axon plugin ignores the JAX_PLATFORMS env var, so
    the config call is the only reliable override)."""
    import subprocess
    import sys

    import jax

    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        jax.config.update("jax_platforms", "cpu")
        return "cpu", None

    deadline = time.monotonic() + budget_s
    last_err = None
    attempt = 0
    while True:
        try:
            r = subprocess.run([sys.executable, "-c", _PROBE_SRC],
                               capture_output=True, text=True,
                               timeout=min(probe_timeout,
                                           max(10.0, deadline
                                               - time.monotonic())))
            if r.returncode == 0:
                return r.stdout.strip().splitlines()[-1], last_err
            last_err = (r.stderr.strip().splitlines() or ["?"])[-1][:200]
        except subprocess.TimeoutExpired:
            last_err = f"backend probe hung (tunnel down?)"
        attempt += 1
        backoff = min(120.0, 30.0 * attempt)
        if time.monotonic() + backoff >= deadline:
            break
        time.sleep(backoff)
    jax.config.update("jax_platforms", "cpu")
    return "cpu-fallback", last_err


# --------------------------------------------------------------------- #
# shared timing harness
# --------------------------------------------------------------------- #

def _time_steps(run_step, iters, materialize):
    """Time ``iters`` calls of run_step; host-side dispatch time is
    measured separately (the per-step host work on the critical path —
    outputs only materialize after the loop, forcing the full donated
    chain)."""
    out = run_step()                      # warmup/compile
    materialize(out)
    t_host = 0.0
    t0 = time.perf_counter()
    for _ in range(iters):
        tf0 = time.perf_counter()
        out = run_step()
        t_host += time.perf_counter() - tf0
    materialize(out)
    dt = (time.perf_counter() - t0) / iters
    return dt, t_host / (dt * iters)


def _mfu(flops_per_step, dt, n_chips, platform):
    import jax
    kind = jax.devices()[0].device_kind
    peak = _peak_tflops(kind) if platform not in ("cpu", "cpu-fallback") \
        else None
    tflops_chip = flops_per_step / dt / n_chips / 1e12
    return kind, round(tflops_chip, 2), \
        (round(tflops_chip / peak, 4) if peak else None)


# --------------------------------------------------------------------- #
# config: transformer LM (bert_base / bert4l share the builder)
# --------------------------------------------------------------------- #

def _build_lm(batch, seq, hidden, heads, layers_n, vocab, use_flash, mesh,
              n_batches):
    """Model + input pipeline.  Inputs come through the Dataloader (with
    its background prefetch ring device_putting ahead of need), like the
    reference benches pull from their dataloader — a fixed fed array
    would understate host work and overstate throughput."""
    import hetu_tpu as ht

    rng = np.random.RandomState(0)
    id_data = rng.randint(0, vocab, (batch * n_batches, seq)).astype(
        np.int32)
    label_data = rng.randint(0, vocab, (batch * n_batches, seq)).astype(
        np.int32)
    ids = ht.dataloader_op([ht.Dataloader(id_data, batch, "train")])
    labels = ht.dataloader_op([ht.Dataloader(label_data, batch, "train")])
    emb = ht.layers.Embedding(vocab, hidden, name="tok_emb")
    pos = ht.init.random_normal((seq, hidden), stddev=0.02, name="pos_emb")
    h = ht.embedding_lookup_op(emb.embedding_table, ids)
    h = h + ht.broadcast_shape_op(pos, (batch, seq, hidden), add_axes=[0])
    h = ht.array_reshape_op(h, [batch * seq, hidden])
    for i in range(layers_n):
        attn = ht.layers.MultiHeadAttention(hidden, heads, seq, batch,
                                            use_flash=use_flash,
                                            name=f"l{i}_attn")
        h = ht.layers.LayerNorm(hidden, name=f"l{i}_ln1")(h + attn(h))
        wi = ht.layers.Linear(hidden, hidden * 4, name=f"l{i}_ffn_wi")
        wo = ht.layers.Linear(hidden * 4, hidden, name=f"l{i}_ffn_wo")
        h = ht.layers.LayerNorm(hidden, name=f"l{i}_ln2")(
            h + wo(ht.gelu_op(wi(h))))
    # LM head TIED to the token embedding, as the reference BERT ties its
    # decoder (examples/nlp/bert/hetu_bert.py:421) — and as honest MFU
    # accounting requires: an untied gather-only table would otherwise
    # inflate the 6*P*T numerator with params that never hit the MXU.
    # Default is the materialized head: the chunked fused head
    # (tied_lm_head_xent_op) measured 14% SLOWER at BERT-base scale on
    # the v5e (its fp32 dW scan carry outweighs the saved logits
    # traffic) — it is a MEMORY tool for vocab/batch scales where the
    # [B*S, vocab] chain doesn't fit.  HETU_BENCH_FUSED_HEAD=1 A/Bs it.
    head_bias = ht.init.zeros((vocab,), name="lm_head_bias")
    flat_labels = ht.array_reshape_op(labels, [batch * seq])
    if envvars.get_bool("HETU_BENCH_FUSED_HEAD"):
        loss = ht.reduce_mean_op(
            ht.tied_lm_head_xent_op(h, emb.embedding_table, head_bias,
                                    flat_labels), axes=0)
    else:
        logits = ht.linear_op(h, emb.embedding_table, head_bias,
                              trans_B=True)
        loss = ht.reduce_mean_op(
            ht.softmaxcrossentropy_sparse_op(logits, flat_labels), axes=0)
    train = ht.optim.AdamOptimizer(learning_rate=1e-4).minimize(loss)
    # bf16 compute / fp32 masters: the MXU path
    ex = ht.Executor({"train": [loss, train]}, mixed_precision="bf16",
                     mesh=mesh)
    return ex


def _bench_lm(platform, reduced, *, layers_n, seq, per_chip_batch,
              hidden=768, heads=12, vocab=30522, iters=20,
              keep_batch=False):
    import jax
    from hetu_tpu.parallel.mesh import make_mesh

    n_chips = max(1, jax.device_count())
    if reduced:
        # keep_batch: the sweep varies per_chip_batch as a REAL axis even
        # at reduced scale — overriding it here would make every sweep
        # cell measure the identical workload and the batch ranking
        # fictitious
        if not keep_batch:
            per_chip_batch = 4
        seq, hidden, heads, layers_n, vocab = 64, 128, 4, 2, 1000
        iters = 3
    batch = per_chip_batch * n_chips
    mesh = make_mesh({"dp": n_chips}) if n_chips > 1 else None
    # flash attention wins on long sequences (the 32k config NEEDS it);
    # at seq 512 the fused kernel measured ~8% SLOWER than XLA's batched
    # attention on the v5e (its per-block matmuls contract over only
    # head_dim=64 while the saved probs traffic is ~1 ms/layer), so the
    # crossover is taken at 1024.  Reduced (CPU) scale keeps flash on so
    # the kernel path stays exercised in verification runs.
    use_flash = (platform == "tpu" and seq >= 1024) or reduced
    # sweep/ablation override: pin the attention impl regardless of the
    # crossover default (HETU_BENCH_SWEEP drives both impls per batch)
    forced = envvars.get_str("HETU_BENCH_FORCE_FLASH")
    if forced is not None:
        use_flash = forced == "1"
    flash_err = None
    flash_forced = forced is not None
    try:
        ex = _build_lm(batch, seq, hidden, heads, layers_n, vocab,
                       use_flash, mesh, n_batches=iters + 2)
        dt, host_frac = _time_steps(
            lambda: ex.run("train"),
            iters, lambda out: float(np.asarray(out[0])))
    except Exception as e:
        if not use_flash:
            raise
        flash_err = f"{type(e).__name__}: {e}"[:300]
        use_flash = False
        ex = _build_lm(batch, seq, hidden, heads, layers_n, vocab,
                       False, mesh, n_batches=iters + 2)
        dt, host_frac = _time_steps(
            lambda: ex.run("train"),
            iters, lambda out: float(np.asarray(out[0])))

    # Analytic FLOPs (XLA cost_analysis would require re-lowering and
    # RE-COMPILING the whole step just to read a number — minutes on TPU).
    # Honest MFU accounting: count ONLY matmul-participating weights —
    # 12*H^2 per layer (4 attention projections + 8 FFN) plus the H*V
    # head matmul (whose weight is the tied embedding table, counted
    # once).  Embedding gathers, position adds, LayerNorms, biases and
    # the softmax-xent are real work the numerator deliberately ignores.
    # The attention score/context matmuls add 12*B*S^2*H per layer.
    matmul_params = 12.0 * hidden * hidden * layers_n + hidden * vocab
    flops = 6.0 * matmul_params * (batch * seq) \
        + layers_n * 12.0 * batch * seq * seq * hidden
    kind, tflops_chip, mfu = _mfu(flops, dt, n_chips, platform)
    out = {
        "value": round(batch / dt / n_chips, 2),
        "unit": "samples/sec/chip",
        "step_time_ms": round(dt * 1e3, 3),
        "tflops_per_sec_chip": tflops_chip,
        "mfu": mfu,
        "host_fraction": round(host_frac, 4),
        "device_kind": kind,
        "n_chips": n_chips,
        "flash_attention": use_flash,
        "reduced_scale": reduced,
        "config": {"per_chip_batch": per_chip_batch, "seq": seq,
                   "hidden": hidden, "layers": layers_n, "vocab": vocab},
    }
    if flash_forced:
        # provenance in the artifact itself: this row's attention impl
        # was pinned by HETU_BENCH_FORCE_FLASH, not chosen by the
        # seq-crossover heuristic (ADVICE: a forced bert4l row is
        # otherwise indistinguishable from a default-path measurement)
        out["flash_forced"] = True
    if flash_err:
        out["flash_fallback"] = flash_err
    # physics ceiling: a row claiming more than the silicon can do is a
    # measurement defect, not a result (telemetry/health.py)
    from hetu_tpu.telemetry import health as _health
    ceiling = _health.check_physics_ceiling(
        mfu=mfu, tflops_chip=tflops_chip, platform=platform)
    if not ceiling["ok"]:
        out["health_violation"] = ceiling["violations"]
    return out


_PROBE_LM_SRC = """
import json
import bench
r = bench._bench_lm({platform!r}, False, layers_n=12, seq=512,
                    per_chip_batch={b}, iters=3)
print("PROBE_RESULT " + json.dumps(r["value"]))
"""


def _run_probe(src, deadline, timeout_cap=900.0, min_left=60.0):
    """One subprocess probe under the shared budget policy: returns the
    json-decoded PROBE_RESULT payload, or an error string.  Shared by
    the bert_base batch probes and the ablation sweep so timeout/parse
    fixes land once."""
    import subprocess
    import sys
    left = deadline - time.monotonic()
    if left < min_left:
        return "skipped (probe budget spent)"
    try:
        r = subprocess.run([sys.executable, "-c", src],
                           capture_output=True, text=True,
                           timeout=min(timeout_cap, left), cwd=_HERE)
        val = next((ln.split(" ", 1)[1] for ln in r.stdout.splitlines()
                    if ln.startswith("PROBE_RESULT ")), None)
        if val is not None:
            return json.loads(val)
        return (r.stderr.strip().splitlines() or ["failed"])[-1][:200]
    except subprocess.TimeoutExpired:
        return "probe timed out (tunnel degraded?)"
    except Exception as e:
        return f"{type(e).__name__}"[:60]


def _probe_health(numeric):
    """Telemetry health gate over the batch-probe readings (VERDICT
    next-#1's banking rule): a probe >2x below the median of its
    siblings is a wedged tunnel reading, not a slow batch size.  The
    wedged entries are REMOVED from ``numeric`` (they can neither win
    nor veto), and the verdict dict lands in the artifact so a
    degraded window is visible in the record, never silently banked."""
    if len(numeric) < 2:
        return None
    from hetu_tpu.telemetry import health
    verdict = health.check_sibling_consistency(numeric)
    for b in list(verdict["wedged"]):
        numeric.pop(int(b), None)
    return verdict


def _record_retry_probe(probes, numeric, b, first, retry):
    """Outlier re-probe bookkeeping: keep the better of the two
    readings under ``probes[b]`` and record THE DISCARDED ONE in the
    artifact — ``<b>_first_reading`` when the retry won,
    ``<b>_retry_reading`` when the original stood (ADVICE: the old code
    wrote the kept value twice, making the retry unverifiable)."""
    if not isinstance(retry, (int, float)):
        return          # skipped/failed retry records nothing
    retry = float(retry)
    if retry > first:
        probes[b] = numeric[b] = retry
        probes[f"{b}_first_reading"] = first
    else:
        probes[f"{b}_retry_reading"] = retry


def bench_bert_base(platform, reduced):
    """BERT-base TRUE: 12 layers, seq 512 (BASELINE config 2 for real).

    Auto-tunes the per-chip batch over {32, 48, 64}, each probe in a
    SUBPROCESS with a hard timeout: a large-batch compile can hang
    indefinitely when the axon tunnel degrades (observed: a batch-64
    probe blocked >50 min with zero CPU), and an in-process hang would
    cost the whole matrix.  A timed-out or failed probe is skipped.
    The measured round-3 sweep had batch 32 fastest (258.5 vs ~252
    samples/s at 48/64), so probes run 32 first and the winner falls
    back to 32.  Override with HETU_BENCH_BERT_BATCH to pin a batch."""
    fixed = envvars.get_int("HETU_BENCH_BERT_BATCH")
    if fixed is not None or reduced:
        return _bench_lm(platform, reduced, layers_n=12, seq=512,
                         per_chip_batch=int(fixed or 32), iters=10)
    probes = {}
    deadline = time.monotonic() + 1500.0   # total probe budget
    for b in (32, 48, 64):
        got = _run_probe(_PROBE_LM_SRC.format(platform=platform, b=b),
                         deadline)
        probes[b] = float(got) if isinstance(got, (int, float)) else got
    numeric = {b: v for b, v in probes.items()
               if isinstance(v, (int, float))}
    # re-probe implausible outliers once: a tunnel hiccup inside a
    # 3-iter probe yields a reading several-fold low (observed Aug 2:
    # batch 48 at 64.6 samples/s against 216/223 neighbors), which
    # would silently veto that batch.  Uses the same shared deadline,
    # so a spent budget skips the retry.
    if len(numeric) >= 2:
        top = max(numeric.values())
        for b, v in sorted(numeric.items()):
            if v < 0.5 * top:
                got = _run_probe(
                    _PROBE_LM_SRC.format(platform=platform, b=b),
                    deadline)
                # the presence of a <b>_first_reading / <b>_retry_reading
                # key means "a second probe ran" (its value is whichever
                # reading was discarded); a skipped retry records nothing
                _record_retry_probe(probes, numeric, b, v, got)
    if platform == "tpu" and not numeric:
        # every probe failed — likely the tunnel is wedged (or another
        # config initialized the TPU in-process first; main() orders
        # bert_base first to prevent that).  Raising here lets the
        # matrix record an error instead of hanging on an unprotected
        # in-process measurement.
        raise RuntimeError(f"all batch probes failed: {probes}")
    # health gate: a probe still >2x off its siblings AFTER the retry
    # is a degraded window — exclude it from winner selection and say
    # so in the artifact (the Aug-2 64.6 reading was banked silently)
    health = _probe_health(numeric)
    best = max(numeric, key=numeric.get) if numeric else 32
    out = _bench_lm(platform, reduced, layers_n=12, seq=512,
                    per_chip_batch=best, iters=10)
    out["batch_probe_samples_per_sec"] = probes
    if health is not None:
        out["probe_health"] = health
        if not health["ok"]:
            out["health_warning"] = (
                "degraded measurement window: probe(s) "
                f"{sorted(health['wedged'])} wedged (>2x off siblings) "
                "even after re-probe; row measured from the surviving "
                "batches — treat with suspicion")
    return out


def bench_bert4l(platform, reduced):
    """Round-1/2 proxy (4L, seq 128) for round-over-round continuity."""
    return _bench_lm(platform, reduced, layers_n=4, seq=128,
                     per_chip_batch=64, iters=20)


def bench_gpt_small(platform, reduced):
    """GPT-2-small-shaped decoder-only LM at seq 1024 — the model-zoo
    axis the reference lacks, and the config where flash attention is
    past its measured crossover (>= 1024).  Trains through
    models.GPTForCausalLM (fused QKV, flash causal attention, fused
    chunked tied head + masked mean)."""
    import jax
    import hetu_tpu as ht
    from hetu_tpu.models import GPTConfig, GPTForCausalLM

    B, S, H, L, V, iters = 8, 1024, 768, 12, 50257, 10
    if reduced:
        B, S, H, L, V, iters = 2, 128, 64, 2, 500, 2
    clip = 1.0

    def build(use_flash):
        cfg = GPTConfig(vocab_size=V, hidden_size=H,
                        num_hidden_layers=L,
                        num_attention_heads=max(2, H // 64),
                        max_position_embeddings=S, batch_size=B,
                        seq_len=S, dropout_rate=0.0, use_flash=use_flash)
        m = GPTForCausalLM(cfg)
        ids = ht.placeholder_op("gb_ids")
        labels = ht.placeholder_op("gb_labels")
        loss, _ = m(ids, labels=labels)
        opt = ht.optim.AdamWOptimizer(learning_rate=3e-4,
                                      weight_decay=0.01)
        opt.clip_grad_norm = clip
        train = opt.minimize(loss)
        ex = ht.Executor({"train": [loss, train]},
                         mixed_precision="bf16")
        return ids, labels, ex

    rng = np.random.RandomState(0)
    pool_np = [(rng.randint(0, V, (B, S)).astype(np.int32),
                rng.randint(0, V, (B, S)).astype(np.int32))
               for _ in range(4)]

    def measure(use_flash):
        ids, labels, ex = build(use_flash)
        # device-resident feed ring, consistent with the other
        # device-capability configs
        pool = [(jax.device_put(a), jax.device_put(b))
                for a, b in pool_np]
        it = {"i": 0}

        def step():
            a, b = pool[it["i"] % len(pool)]
            it["i"] += 1
            return ex.run("train", feed_dict={ids: a, labels: b})
        return _time_steps(step, iters,
                           lambda out: float(np.asarray(out[0])))

    # flash stays ON at reduced scale so verification runs exercise the
    # causal kernel path (same policy as _bench_lm); full scale follows
    # the measured crossover (flash at seq >= 1024), with an unfused
    # remeasure if the kernel fails
    use_flash = True if reduced else S >= 1024
    flash_err = None
    try:
        dt, host_frac = measure(use_flash)
    except Exception as e:
        if not use_flash:
            raise
        flash_err = f"{type(e).__name__}: {e}"[:300]
        use_flash = False
        dt, host_frac = measure(False)
    # honest matmul accounting: 12H^2 per block + tied H*V head; causal
    # attention matmuls add 12*B*S^2*H/2 per layer
    matmul_params = 12.0 * H * H * L + H * V
    flops = 6.0 * matmul_params * (B * S) + L * 12.0 * B * S * S * H / 2
    kind, tflops_chip, mfu = _mfu(flops, dt, 1, platform)
    out = {
        "value": round(B * S / dt, 1),
        "unit": "tokens/sec/chip",
        "step_time_ms": round(dt * 1e3, 3),
        "tflops_per_sec_chip": tflops_chip,
        "mfu": mfu,
        "host_fraction": round(host_frac, 4),
        "device_kind": kind,
        "n_chips": 1,
        "flash_attention": use_flash,
        "reduced_scale": reduced,
        "config": {"per_chip_batch": B, "seq": S, "hidden": H,
                   "layers": L, "vocab": V, "clip_grad_norm": clip},
    }
    if flash_err:
        out["flash_fallback"] = flash_err
    return out


# --------------------------------------------------------------------- #
# config: ResNet-18 / CIFAR-10
# --------------------------------------------------------------------- #

def bench_resnet18(platform, reduced):
    """ResNet-18 / CIFAR-10 (BASELINE config 1).

    Reports TWO input paths: the Dataloader path (whatever the host link
    delivers — through the axon tunnel that link is ~0.06 GB/s, a ~50 ms
    floor on a 3 MB/step feed that a real TPU-VM's >10 GB/s PCIe would
    retire in ~0.3 ms) and a device-resident path (inputs pre-staged on
    the chip) that measures what the CHIP does.  The headline value is
    the device-resident one, labeled as such."""
    import jax
    import hetu_tpu as ht
    from hetu_tpu.models.cnn import resnet18

    n_chips = max(1, jax.device_count())
    per_chip_batch, iters = 256, 20
    if reduced:
        per_chip_batch, iters = 8, 2
    batch = per_chip_batch * n_chips
    rng = np.random.RandomState(0)
    n_batches = iters + 2
    xs = rng.randn(batch * n_batches, 3, 32, 32).astype(np.float32)
    ys = np.eye(10, dtype=np.float32)[
        rng.randint(0, 10, batch * n_batches)]
    from hetu_tpu.parallel.mesh import make_mesh
    mesh = make_mesh({"dp": n_chips}) if n_chips > 1 else None

    # path 1: Dataloader + prefetch ring (host link on the feed path)
    x = ht.dataloader_op([ht.Dataloader(xs, batch, "train")])
    y_ = ht.dataloader_op([ht.Dataloader(ys, batch, "train")])
    loss, pred = resnet18(x, y_)
    train = ht.optim.SGDOptimizer(learning_rate=0.1).minimize(loss)
    ex = ht.Executor({"train": [loss, train]}, mixed_precision="bf16",
                     mesh=mesh)
    dt_loader, host_frac = _time_steps(lambda: ex.run("train"), iters,
                                       lambda out: float(np.asarray(out[0])))
    del ex

    # path 2: inputs pre-staged on device (gather_feeds passes
    # jax.Arrays through untouched), cycled through placeholder feeds
    xp = ht.placeholder_op("rn_x")
    yp = ht.placeholder_op("rn_y")
    loss2, _ = resnet18(xp, yp)
    train2 = ht.optim.SGDOptimizer(learning_rate=0.1).minimize(loss2)
    ex2 = ht.Executor({"train": [loss2, train2]}, mixed_precision="bf16",
                      mesh=mesh)
    dev_batches = [(jax.device_put(xs[i * batch:(i + 1) * batch]),
                    jax.device_put(ys[i * batch:(i + 1) * batch]))
                   for i in range(n_batches)]
    it = {"i": 0}

    def step_dev():
        xb, yb = dev_batches[it["i"] % n_batches]
        it["i"] += 1
        return ex2.run("train", feed_dict={xp: xb, yp: yb})
    dt_dev, _ = _time_steps(step_dev, iters,
                            lambda out: float(np.asarray(out[0])))
    return {
        "value": round(batch / dt_dev / n_chips, 2),
        # the unit names the path: this row's value is ~12x the old
        # end-to-end record on the tunnel-fed host link, and a bare
        # "samples/sec/chip" would read as a measurement jump rather
        # than a metric change (the fed-path number is loader_value)
        "unit": "samples/sec/chip (device-resident input)",
        "input_path": "device-resident (chip capability; see loader_*)",
        "step_time_ms": round(dt_dev * 1e3, 3),
        "loader_value": round(batch / dt_loader / n_chips, 2),
        "loader_step_time_ms": round(dt_loader * 1e3, 3),
        "loader_host_fraction": round(host_frac, 4),
        "feed_bytes_per_step": int(batch * (3 * 32 * 32 + 10) * 4),
        "device_kind": jax.devices()[0].device_kind,
        "n_chips": n_chips,
        "reduced_scale": reduced,
        "config": {"per_chip_batch": per_chip_batch, "dataset": "cifar10",
                   "depth": 18},
    }


# --------------------------------------------------------------------- #
# config: Wide&Deep CTR through the PS + HET-cache hybrid path
# --------------------------------------------------------------------- #

def _ctr_hybrid_once(platform, reduced, *, batch=1024, iters=20,
                     feature_dim=1_000_000, subgraph="train",
                     tier="cache"):
    """One measured hybrid CTR config; shared by the matrix entry and
    the rows-per-chip ladder.

    ``tier`` selects the host path: "cache" = HET cache + python sync
    protocol (the staleness-bounded tier); "van" = no cache, phases A/B
    ride the native C++ van through PSClient's fast-tier route (the
    zmq_van role — r5 wiring)."""
    import hetu_tpu as ht
    from hetu_tpu.models import ctr as ctr_models

    if reduced:
        batch, iters, feature_dim = 128, 3, min(feature_dim, 10_000)
    cache_bound = max(feature_dim // 10, 1024)
    rng = np.random.RandomState(0)
    n_pool = iters + 2
    # zipf-skewed ids: the regime the HET cache exists for
    raw = rng.zipf(1.05, size=(n_pool * batch, 26))
    sparse = ((raw - 1) % feature_dim).astype(np.int32)
    dense = rng.randn(n_pool * batch, 13).astype(np.float32)
    label = np.eye(2, dtype=np.float32)[
        rng.randint(0, 2, n_pool * batch)]
    d = ht.dataloader_op([ht.Dataloader(dense, batch, subgraph)])
    s = ht.dataloader_op([ht.Dataloader(sparse, batch, subgraph)])
    y_ = ht.dataloader_op([ht.Dataloader(label, batch, subgraph)])
    loss, pred, _lab, train = ctr_models.wdl_criteo(
        d, s, y_, feature_dimension=feature_dim, embedding_size=16)
    # bf16 wire: phase A casts the gathered rows host-side and the step
    # emits bf16 grads, halving BOTH directions of the host link — the
    # link IS the hybrid path's bottleneck (the PS accumulates fp32
    # regardless).  HETU_BENCH_CTR_FP32=1 pins the old full-width wire.
    mp = None if envvars.get_bool("HETU_BENCH_CTR_FP32") else "bf16"
    from hetu_tpu.ps.server import PSServer
    import hetu_tpu.ps.client as psc
    PSServer._instance = None      # each tier gets a fresh server so
    psc.PSClient._instance = None  # neither inherits the other's state
    if not envvars.is_set("HETU_PS_ADDR"):
        # BOTH tiers get the C++ van (the cache tier's sync_embedding/
        # push_embedding verbs are van ops too — r5); enable BEFORE the
        # init window so a cold g++ build of the .so is not charged to
        # table_init_s.  With HETU_PS_ADDR the executor talks to a
        # REMOTE server a local van can't serve — the row then honestly
        # records van_served=False.
        try:
            PSServer.get().enable_van_autoserve()
        except (RuntimeError, OSError):   # no toolchain / bind denied:
            pass                          # python tier serves
    t_init = time.monotonic()
    if tier == "van":
        ex = ht.Executor({subgraph: [loss, train]}, comm_mode="Hybrid",
                         mixed_precision=mp)
    else:
        ex = ht.Executor({subgraph: [loss, train]}, comm_mode="Hybrid",
                         cstable_policy="lfu", cache_bound=cache_bound,
                         mixed_precision=mp)
    init_s = time.monotonic() - t_init
    dt, host_frac = _time_steps(
        lambda: ex.run(subgraph), iters,
        lambda out: float(np.asarray(out[0]).reshape(-1)[0]))
    hit_rate = None
    if ex.cstables:
        perf = ex.ps_perf_summary()
        hit_rate = round(float(np.mean(
            [p["hit_rate"] for p in perf.values()])), 4)
    srv = PSServer._instance
    van_served = bool(srv is not None
                      and getattr(srv, "_van_keys", {}))
    # real teardown, not just singleton clearing: finalize() closes the
    # client pool + van sockets, shutdown() stops the C++ serve thread
    # and restores the python locks — later bench configs must not
    # inherit live threads or a bound van port
    cli = psc.PSClient._instance
    if cli is not None:
        cli.finalize()
    srv = PSServer._instance
    if srv is not None:
        srv.shutdown()
    PSServer._instance = None
    psc.PSClient._instance = None
    return {
        "value": round(batch / dt, 2),
        "unit": "samples/sec",
        "embedding_rows_per_sec": round(batch * 26 / dt, 1),
        "step_time_ms": round(dt * 1e3, 3),
        "host_fraction": round(host_frac, 4),
        "cache_hit_rate": hit_rate,
        "table_init_s": round(init_s, 2),
        "reduced_scale": reduced,
        "config": {"batch": batch, "feature_dim": feature_dim,
                   "fields": 26, "embedding_size": 16,
                   "tier": tier, "van_served": van_served,
                   "cache_bound": cache_bound if tier == "cache"
                   else None,
                   "policy": "lfu" if tier == "cache" else None,
                   "wire_dtype": mp or "fp32"},
    }


def bench_ctr_hybrid(platform, reduced):
    """Measure BOTH host tiers and headline the faster one: the HET
    cache path and the native-van direct path (r5 — the VERDICT r4
    criterion is host_fraction, and the C++ tier is the fix)."""
    r_cache = _ctr_hybrid_once(platform, reduced)
    r_van = _ctr_hybrid_once(platform, reduced, subgraph="train_van",
                             tier="van")
    best = r_van if r_van["value"] >= r_cache["value"] else r_cache
    out = dict(best)
    out["tiers"] = {
        t: {k: r[k] for k in ("value", "step_time_ms", "host_fraction",
                              "cache_hit_rate")}
        for t, r in (("cache", r_cache), ("van", r_van))}
    for t, r in (("cache", r_cache), ("van", r_van)):
        out["tiers"][t]["van_served"] = r["config"]["van_served"]
    return out


_CTR_ROWS_FILE = os.path.join(_HERE, "BENCH_CTR_ROWS.json")

_PROBE_CTR_ROWS_SRC = """
import json
import bench
r = bench._ctr_hybrid_once({platform!r}, False, feature_dim={rows},
                           iters=8)
print("PROBE_RESULT " + json.dumps(r))
"""


def _persist_artifact(path, art, reduced, has_data):
    """Shared artifact-persistence policy (hetu_tpu/artifact.py): a
    reduced/CPU run never overwrites a full-scale TPU record, and an
    all-error run never overwrites a record that has data."""
    from hetu_tpu.artifact import persist_artifact
    return persist_artifact(path, art, reduced, has_data=has_data)


def sweep_ctr_rows(platform, reduced):
    """BASELINE's third headline metric: max embedding rows trainable
    per chip.  Climb a table-size ladder (each rung a subprocess with a
    hard timeout, so an OOM or wedge costs one rung); max_rows = the
    largest table that completes training steps.  Writes
    BENCH_CTR_ROWS.json with the full rows/s curve."""
    ladder = (1_000_000, 4_000_000, 16_000_000, 64_000_000, 256_000_000)
    if reduced:
        ladder = (10_000, 40_000)
    rungs = []
    deadline = time.monotonic() + 3600.0
    for rows in ladder:
        if reduced:
            try:
                # reduced=False bypasses _ctr_hybrid_once's shape clamp
                # (the ladder IS the variable); tag the rung honestly
                r = _ctr_hybrid_once(platform, False, feature_dim=rows,
                                     iters=3, batch=128,
                                     subgraph=f"rows{rows}")
                r["reduced_scale"] = True
                rungs.append({"rows": rows, **r})
            except Exception as e:
                rungs.append({"rows": rows,
                              "error": f"{type(e).__name__}: {e}"[:200]})
                break
        else:
            got = _run_probe(
                _PROBE_CTR_ROWS_SRC.format(platform=platform, rows=rows),
                deadline, timeout_cap=1800.0, min_left=300.0)
            if isinstance(got, dict):
                rungs.append({"rows": rows, **got})
            else:
                rungs.append({"rows": rows, "error": str(got)})
                break
    ok = [r for r in rungs if "error" not in r]
    art = {
        "platform": platform,
        "reduced_scale": reduced,
        "measured_at": time.strftime("%Y-%m-%d %H:%M UTC", time.gmtime()),
        "metric": "max embedding rows trainable per chip "
                  "(host PS + HET cache, dim 16, fp32 server rows)",
        "max_rows": max((r["rows"] for r in ok), default=0),
        "rungs": rungs,
    }
    _persist_artifact(_CTR_ROWS_FILE, art, reduced, has_data=bool(ok))
    return art


# --------------------------------------------------------------------- #
# config: MoE (top-2 gate)
# --------------------------------------------------------------------- #

def bench_moe(platform, reduced):
    import jax
    import hetu_tpu as ht
    from hetu_tpu.models import moe_mlp

    batch, tokens, model_dim, hidden, experts, iters = 8, 1024, 768, \
        3072, 8, 15
    top_k = 2
    if reduced:
        batch, tokens, model_dim, hidden, experts, iters = 2, 64, 64, \
            128, 4, 2
    # chip-fill tuning knobs for the on-chip re-measure (VERDICT r3
    # item 4: the recorded config underfilled the chip)
    if envvars.is_set("HETU_BENCH_MOE_BATCH"):
        batch = envvars.get_int("HETU_BENCH_MOE_BATCH")
    if envvars.is_set("HETU_BENCH_MOE_TOKENS"):
        tokens = envvars.get_int("HETU_BENCH_MOE_TOKENS")
    rng = np.random.RandomState(0)
    # device-resident feeds: a 25MB host feed per step would measure the
    # tunnel's H2D, not the MoE step (jax.Arrays pass through the feed
    # path untouched)
    xb = jax.device_put(rng.randn(batch, tokens, model_dim)
                        .astype(np.float32))
    yb = jax.device_put(rng.randint(0, model_dim, (batch * tokens,))
                        .astype(np.int32))

    def run_variant(expert_parallel):
        x = ht.placeholder_op("x")
        y_ = ht.placeholder_op("y_")
        loss, _y = moe_mlp(x, y_, batch, tokens, model_dim, hidden,
                           num_local_experts=experts, gate_type="top",
                           top_k=top_k, sparse_labels=True,
                           expert_parallel=expert_parallel)
        train = ht.optim.AdamOptimizer(
            learning_rate=1e-4).minimize(loss)
        ex = ht.Executor({"train": [loss, train]},
                         mixed_precision="bf16")
        return _time_steps(
            lambda: ex.run("train", feed_dict={x: xb, y_: yb}), iters,
            lambda out: float(np.asarray(out[0])))

    # A/B matrix: expert formulation (per-local-expert loop vs stacked
    # batched einsum) x dispatch formulation (GShard one-hot matmul vs
    # row scatter-add) — the right choice is hardware-generation
    # dependent, so measure rather than assume
    variants = {}
    saved_env = envvars.get_raw("HETU_MOE_SCATTER_DISPATCH")
    try:
        for name, ep in (("expert_loop", False), ("stacked", True)):
            for dname, denv in (("matmul_dispatch", None),
                                ("scatter_dispatch", "1")):
                key = f"{name}/{dname}"
                if denv is None:
                    os.environ.pop("HETU_MOE_SCATTER_DISPATCH", None)
                else:
                    os.environ["HETU_MOE_SCATTER_DISPATCH"] = denv
                try:
                    dt_v, hf_v = run_variant(ep)
                    variants[key] = {"step_ms": round(dt_v * 1e3, 3),
                                     "host_fraction": round(hf_v, 4)}
                except Exception as e:
                    variants[key] = {
                        "error": f"{type(e).__name__}: {e}"[:200]}
    finally:
        if saved_env is None:
            os.environ.pop("HETU_MOE_SCATTER_DISPATCH", None)
        else:
            os.environ["HETU_MOE_SCATTER_DISPATCH"] = saved_env
    ok = {k: v for k, v in variants.items() if "step_ms" in v}
    best = min(ok, key=lambda k: ok[k]["step_ms"])
    dt = ok[best]["step_ms"] / 1e3
    # useful-work MFU: expert-FFN matmul flops for ROUTED tokens only
    # (capacity padding does extra real matmul work, so this is a
    # conservative utilization figure), fwd + bwd = 3x, 2 matmuls of
    # d x h each way per routed token
    useful_flops = 3.0 * 2 * (batch * tokens) * 4 * model_dim * hidden
    kind, tflops_chip, mfu = _mfu(useful_flops, dt, 1, platform)
    # A2A accounting (BASELINE config 4 asks for the A2A time fraction).
    # On ONE chip ep=1 and no all-to-all runs, so the single-chip row
    # reports the MODEL-LEVEL a2a volume and an estimated fraction for
    # an ep=experts deployment (one expert per device): the [E, cap, D]
    # dispatch buffer crosses the exchange on dispatch + combine, each
    # again in backward (4x), moving (ep-1)/ep of its bytes over ICI.
    # same static-capacity formula the gate uses (layers/moe.py:44
    # topkgating: k * ceil(num_tokens/num_experts * capacity_factor)),
    # at the bench's default capacity_factor = 1.0
    import math as _math
    cap = top_k * _math.ceil(batch * tokens / experts * 1.0)
    a2a_buffer_bytes = experts * cap * model_dim * 2      # bf16
    ep_deploy = experts
    a2a_bytes = 4.0 * a2a_buffer_bytes * (ep_deploy - 1) / ep_deploy
    from hetu_tpu.planner.cost_model import ClusterSpec
    ici = ClusterSpec().ici_bandwidth
    a2a_est_s = a2a_bytes / ici
    return {
        "value": round(batch * tokens / dt, 1),
        "unit": "tokens/sec/chip",
        "step_time_ms": ok[best]["step_ms"],
        "host_fraction": ok[best]["host_fraction"],
        "expert_tflops_per_sec_chip": tflops_chip,
        "mfu": mfu,
        "best_variant": best,
        "variants": variants,
        "a2a_bytes_per_step": int(a2a_bytes),
        "a2a_fraction_est": round(a2a_est_s / (a2a_est_s + dt), 4),
        "a2a_note": (f"single-chip run has ep=1 (no live all-to-all); "
                     f"estimate assumes ep={ep_deploy} over spec ICI "
                     f"{ici/1e9:.0f} GB/s (spec-assumed, unmeasurable "
                     f"on one chip) against the measured compute step"),
        "reduced_scale": reduced,
        "config": {"batch": batch, "tokens": tokens,
                   "model_dim": model_dim, "hidden": hidden,
                   "experts": experts, "top_k": top_k},
    }


# --------------------------------------------------------------------- #
# config: 32k-token long context (causal flash attention)
# --------------------------------------------------------------------- #

def bench_long_context(platform, reduced):
    import jax
    import jax.numpy as jnp
    from hetu_tpu.kernels.flash_attention import flash_attention

    B, S, H, D, layers_n, iters = 1, 32768, 8, 64, 2, 5
    if reduced:
        B, S, H, D, layers_n, iters = 1, 2048, 2, 32, 1, 2
    hidden = H * D
    key = jax.random.PRNGKey(0)
    x = jax.random.normal(key, (B, S, hidden), jnp.bfloat16)
    ws = [jax.random.normal(jax.random.fold_in(key, i),
                            (hidden, 3 * hidden), jnp.bfloat16) * 0.02
          for i in range(layers_n)]

    # block-size override for on-chip tuning sweeps: the 512x1024
    # default was tuned at seq 4-8k; S/cp-sized and 32k chunks may want
    # different tiles (VERDICT r3 item 2)
    blocks = envvars.get_str("HETU_BENCH_LC_BLOCKS")
    bq, bk = (int(t) for t in blocks.split(",")) if blocks else (512, 1024)
    # record what will actually RUN: the kernel shrinks non-divisor
    # tiles to the largest divisor, and a sweep must not label two
    # identical runs as different tiles
    from hetu_tpu.kernels.flash_attention import _fit_block
    bq, bk = _fit_block(bq, S), _fit_block(bk, S)

    def loss_fn(ws, x):
        h = x
        for w in ws:
            qkv = (h @ w).reshape(B, S, 3, H, D)
            o = flash_attention(qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2],
                                causal=True, block_q=bq, block_k=bk)
            h = h + o.reshape(B, S, hidden)
        return (h.astype(jnp.float32) ** 2).mean()

    step = jax.jit(jax.grad(loss_fn))

    def run():
        return step(ws, x)

    dt, _ = _time_steps(run, iters,
                        lambda out: np.asarray(out[0][:1, :1]))
    # causal attention FLOPs: 2 matmuls * 2BS^2HD/2 (causal half) fwd,
    # x3 with backward; + qkv projection 6*B*S*hidden*3*hidden
    flops = layers_n * (3 * 2 * 2 * B * S * S * H * D / 2
                        + 6 * B * S * hidden * 3 * hidden)
    kind, tflops_chip, mfu = _mfu(flops, dt, 1, platform)
    return {
        "value": round(B * S / dt, 1),
        "unit": "tokens/sec/chip",
        "step_time_ms": round(dt * 1e3, 3),
        "attn_tflops_per_sec_chip": tflops_chip,
        "mfu": mfu,
        "reduced_scale": reduced,
        "config": {"batch": B, "seq": S, "heads": H, "head_dim": D,
                   "layers": layers_n, "kernel": "pallas_flash_causal",
                   "block_q": bq, "block_k": bk},
    }


# --------------------------------------------------------------------- #

_CONFIGS = {
    "bert_base": bench_bert_base,
    "bert4l": bench_bert4l,
    "gpt_small_1k": bench_gpt_small,
    "resnet18": bench_resnet18,
    "ctr_hybrid": bench_ctr_hybrid,
    "moe": bench_moe,
    "long_context": bench_long_context,
}


_DECODE_FILE = os.path.join(_HERE, "BENCH_DECODE.json")


def bench_decode(platform, reduced):
    """KV-cached serving throughput (models/gpt_decode.py): GPT-2-small
    shape, one compiled scan, batched prompts; tokens/s = generated
    tokens per wall second after the compile is warm."""
    import jax
    import hetu_tpu as ht
    from hetu_tpu.models import GPTConfig, GPTForCausalLM
    from hetu_tpu.models.gpt_decode import generate_fast

    # gen = S_max - prompt: the scan always runs S_max-1 positions, so
    # counting fewer generated tokens than the paid compute would
    # understate tokens/s by the unused tail
    S_max, hidden, layers_n, heads, vocab, batch, gen = \
        1024, 768, 12, 12, 50257, 8, 1008
    if reduced:
        S_max, hidden, layers_n, heads, vocab, batch, gen = \
            64, 64, 2, 2, 256, 2, 48
    cfg = GPTConfig(vocab_size=vocab, hidden_size=hidden,
                    num_hidden_layers=layers_n,
                    num_attention_heads=heads,
                    max_position_embeddings=S_max, batch_size=batch,
                    seq_len=S_max, dropout_rate=0.0)
    model = GPTForCausalLM(cfg, name="dec")
    ids = ht.placeholder_op("dec_ids")
    logits = model(ids)
    ex = ht.Executor({"gen": [logits]})     # materializes init params
    del logits
    rng = np.random.RandomState(0)
    prompts = rng.randint(0, vocab, (batch, 16)).astype(np.int32)

    from hetu_tpu.models.gpt_decode import _prep_param
    import jax.numpy as jnp

    def run(dtype):
        # params are cast/placed ONCE outside the timed window (the
        # bf16 variant must not pay the ~500MB f32->bf16 cast inside
        # its measurement; per-call prep is then a no-op)
        dt_ = jnp.float32 if dtype is None else dtype
        prepped = {k: _prep_param(v, dt_)
                   for k, v in ex.var_values.items()}
        generate_fast(prepped, cfg, prompts, num_tokens=4,
                      dtype=dt_)                         # compile
        t0 = time.perf_counter()
        out = generate_fast(prepped, cfg, prompts,
                            num_tokens=gen, dtype=dt_)
        dt = time.perf_counter() - t0
        assert out.shape == (batch, 16 + gen)
        return round(batch * gen / dt, 1), round(dt, 3)

    tps_f32, dt_f32 = run(None)
    # bf16 variant: half the weights AND the KV cache, MXU fast path
    # (the serving configuration of record on TPU)
    tps_bf16, dt_bf16 = run(jnp.bfloat16)
    best = max(tps_f32, tps_bf16)
    art = {
        "platform": platform,
        "reduced_scale": reduced,
        "measured_at": time.strftime("%Y-%m-%d %H:%M UTC", time.gmtime()),
        "tokens_per_sec": best,
        "variants": {
            "f32": {"tokens_per_sec": tps_f32, "seconds": dt_f32},
            "bf16": {"tokens_per_sec": tps_bf16, "seconds": dt_bf16},
        },
        "config": {"batch": batch, "s_max": S_max, "hidden": hidden,
                   "layers": layers_n, "heads": heads, "vocab": vocab,
                   "generated": gen, "kernel": "kv_cached_scan",
                   "headline": "best of f32/bf16"},
    }
    _persist_artifact(_DECODE_FILE, art, reduced, has_data=True)
    return art


_SERVE_FILE = os.path.join(_HERE, "BENCH_SERVE.json")


def bench_serve(platform, reduced):
    """Continuous-batching serving throughput (hetu_tpu/serving): replay
    a seeded mixed-length request trace through the engine AND through
    the static-batch baseline (offline ``generate_fast``: pad to the
    longest request, no early exit) on the same weights, counting the
    same USEFUL tokens for both — the artifact records both rates, the
    engine's TTFT percentiles, and its mean batch occupancy."""
    import jax.numpy as jnp
    import hetu_tpu as ht
    from hetu_tpu.models import GPTConfig, GPTForCausalLM
    from hetu_tpu.models.gpt_decode import _prep_param, generate_fast
    from hetu_tpu.serving import Request, ServingEngine

    # GPT-2-small shape on chip; a 2-layer h128 model on the CPU harness
    # (big enough that compute, not per-step dispatch, dominates)
    vocab, hidden, layers_n, heads, s_max, slots, n_req = \
        50257, 768, 12, 12, 1024, 8, 32
    if reduced:
        vocab, hidden, layers_n, heads, s_max, slots, n_req = \
            256, 128, 2, 2, 256, 4, 16
    cfg = GPTConfig(vocab_size=vocab, hidden_size=hidden,
                    num_hidden_layers=layers_n,
                    num_attention_heads=heads,
                    max_position_embeddings=s_max, batch_size=slots,
                    seq_len=s_max, dropout_rate=0.0)
    model = GPTForCausalLM(cfg, name="srv")
    ids = ht.placeholder_op("srv_ids")
    logits = model(ids)
    ex = ht.Executor({"gen": [logits]})     # materializes init params
    del logits
    dt_ = jnp.bfloat16 if platform == "tpu" else jnp.float32
    params = {k: _prep_param(v, dt_) for k, v in ex.var_values.items()}

    # seeded mixed-length trace: mostly short requests, a long straggler
    # every 8th — the shape continuous batching exists for (static
    # batching pads every batch member to the straggler)
    rng = np.random.RandomState(1234)
    straggle = s_max // 2
    trace = []
    for i in range(n_req):
        P = int(rng.randint(4, 17))
        gen = straggle if i % 8 == 7 else int(rng.randint(8, 33))
        trace.append((rng.randint(0, vocab, P).astype(np.int32), gen))
    useful = sum(g for _, g in trace)

    def make_requests():
        return [Request(prompt=p, max_new_tokens=g) for p, g in trace]

    # ---- warm every compile outside the measured windows: the fused
    # decode step plus ONE prefill per prompt-length bucket the trace
    # hits (a cold bucket compile inside the window would be charged to
    # the engine) ---- #
    warm = ServingEngine(params, cfg, slots=slots, queue_limit=n_req,
                         dtype=dt_)
    buckets = sorted({warm.kv.bucket_prompt(len(p)) for p, _ in trace})
    warm.run([Request(prompt=[1] * b, max_new_tokens=2)
              for b in buckets])
    generate_fast(params, cfg,
                  np.zeros((slots, 8), np.int32), num_tokens=2,
                  dtype=dt_)

    # ---- continuous batching ---- #
    eng = ServingEngine(params, cfg, slots=slots, queue_limit=n_req,
                        dtype=dt_)
    t0 = time.perf_counter()
    res = eng.run(make_requests())
    wall_c = time.perf_counter() - t0
    assert len(res) == n_req
    snap = eng.metrics.snapshot()
    # request-lifecycle observability (ISSUE 7): the same trace-replay
    # run now carries its tail decomposition — which component owns the
    # p99 TTFT — plus the SLO state, into the artifact of record
    tail = eng.metrics.explain_tail()
    observability = {
        "explain_tail": tail,
        "components": snap["components"],
        "ttft_p95_s": snap["ttft_p95_s"],
        "tpot_p50_s": snap["tpot_p50_s"],
        "slo": eng.slo.snapshot(),
        "health": eng.health(),
    }

    # ---- static baseline: batches in arrival order, pad-to-longest,
    # no early exit (the offline scan's whole-batch contract) ---- #
    t0 = time.perf_counter()
    for i in range(0, n_req, slots):
        batch = trace[i:i + slots]
        pmax = max(len(p) for p, _ in batch)
        gmax = max(g for _, g in batch)
        padded = np.zeros((len(batch), pmax), np.int32)
        for j, (p, _) in enumerate(batch):
            padded[j, :len(p)] = p
        generate_fast(params, cfg, padded, num_tokens=gmax, dtype=dt_)
    wall_s = time.perf_counter() - t0

    tps_c = round(useful / wall_c, 1)
    tps_s = round(useful / wall_s, 1)

    def engine_trace(trace_, fast, useful_):
        """Warm-run then measure one engine path over a trace; returns
        the rate plus the per-phase attribution from the step events."""
        reqs = [Request(prompt=p, max_new_tokens=g) for p, g in trace_]
        warm_e = ServingEngine(params, cfg, slots=slots,
                               queue_limit=len(trace_), dtype=dt_,
                               fast_path=fast)
        warm_e.run([Request(prompt=p, max_new_tokens=g)
                    for p, g in trace_])   # full trace: every (group,
        # bucket) compile the measured run will hit is now cached
        e = ServingEngine(params, cfg, slots=slots,
                          queue_limit=len(trace_), dtype=dt_,
                          fast_path=fast)
        t0 = time.perf_counter()
        res = e.run(reqs)
        wall = time.perf_counter() - t0
        snap_ = e.metrics.snapshot()
        return {
            "tokens_per_sec": round(useful_ / wall, 1),
            "wall_s": round(wall, 3),
            "prefill_total_s": snap_["prefill_total_s"],
            "decode_total_s": snap_["decode_total_s"],
            "prefill_ms_p50": snap_["prefill_ms_p50"],
            "decode_ms_p50": snap_["decode_ms_p50"],
            "prefill_dispatches": snap_["prefill_dispatches"],
        }, sorted(r.tokens.tolist() for r in res.values())

    # ---- masked vs ragged fast-path A/B on the same mixed trace;
    # greedy parity between the paths is the acceptance criterion ---- #
    ab = {}
    outs = {}
    for label, fast in (("masked", False), ("ragged", True)):
        ab[label], outs[label] = engine_trace(trace, fast, useful)
    ab["greedy_identical"] = outs["masked"] == outs["ragged"]
    ab["speedup"] = (round(ab["ragged"]["tokens_per_sec"]
                           / ab["masked"]["tokens_per_sec"], 3)
                     if ab["masked"]["tokens_per_sec"] else None)

    # ---- prefill-heavy trace variant: long prompts, short tails —
    # the phase mix where flash prefill carries the win ---- #
    rng2 = np.random.RandomState(4321)
    ptrace = []
    for _ in range(n_req):
        P = int(rng2.randint(s_max // 4, s_max // 2))
        ptrace.append((rng2.randint(0, vocab, P).astype(np.int32),
                       int(rng2.randint(4, 9))))
    useful_p = sum(g for _, g in ptrace)
    heavy = {"trace": {"seed": 4321, "n_requests": n_req,
                       "prompt_len": f"{s_max // 4}..{s_max // 2 - 1}",
                       "new_tokens": "4..8",
                       "useful_tokens": useful_p}}
    houts = {}
    for label, fast in (("masked", False), ("ragged", True)):
        heavy[label], houts[label] = engine_trace(ptrace, fast, useful_p)
    heavy["greedy_identical"] = houts["masked"] == houts["ragged"]
    heavy["speedup"] = (round(heavy["ragged"]["tokens_per_sec"]
                              / heavy["masked"]["tokens_per_sec"], 3)
                        if heavy["masked"]["tokens_per_sec"] else None)

    phase_ab = _serve_phase_ab(params, cfg, dt_, reduced)
    paged_ab = _serve_paged_ab(params, cfg, dt_, slots, s_max, vocab,
                               n_req)
    fleet_ab = _serve_fleet_ab(params, cfg, dt_, platform, slots,
                               vocab, n_req)
    swap_ab = _serve_swap_ab(params, cfg, dt_, platform, slots,
                             vocab, n_req)
    autoscale_ab = _serve_autoscale_ab(params, cfg, dt_, platform,
                                       slots, vocab)
    fleet_prefix_ab = _serve_fleet_prefix_ab(params, cfg, dt_, platform,
                                             slots, s_max, vocab, n_req)
    prefix_storm_ab = _serve_prefix_storm_ab(params, cfg, dt_, platform,
                                             vocab)
    quant_ab = _serve_quant_ab(params, cfg, dt_, slots, s_max, vocab,
                               n_req)
    spec_ab = _serve_spec_ab(params, cfg, dt_, platform, slots, s_max,
                             vocab, n_req)
    ragged_ab = _serve_ragged_ab(params, cfg, dt_, platform, slots,
                                 s_max, vocab, n_req)
    moe_ab = _serve_moe_ab(cfg, dt_, platform, slots, s_max, vocab,
                           n_req)

    art = {
        "platform": platform,
        "reduced_scale": reduced,
        "measured_at": time.strftime("%Y-%m-%d %H:%M UTC", time.gmtime()),
        "continuous": {
            "tokens_per_sec": tps_c,
            "wall_s": round(wall_c, 3),
            "ttft_p50_s": snap["ttft_p50_s"],
            "ttft_p99_s": snap["ttft_p99_s"],
            "mean_batch_occupancy": (round(snap["mean_batch_occupancy"], 4)
                                     if snap["mean_batch_occupancy"]
                                     else None),
            "steps": snap["steps"],
        },
        "static_baseline": {
            "tokens_per_sec": tps_s,
            "wall_s": round(wall_s, 3),
            "batches": -(-n_req // slots),
            "note": "generate_fast, pad-to-longest, no early exit",
        },
        "speedup": round(tps_c / tps_s, 3) if tps_s else None,
        "observability": observability,
        "fast_path_ab": ab,
        "prefill_heavy": heavy,
        "phase_ab": phase_ab,
        "paged_ab": paged_ab,
        "fleet_ab": fleet_ab,
        "swap_ab": swap_ab,
        "autoscale_ab": autoscale_ab,
        "fleet_prefix_ab": fleet_prefix_ab,
        "prefix_storm_ab": prefix_storm_ab,
        "quant_ab": quant_ab,
        "spec_ab": spec_ab,
        "ragged_ab": ragged_ab,
        "moe_ab": moe_ab,
        "trace": {"seed": 1234, "n_requests": n_req,
                  "prompt_len": "4..16", "short_new_tokens": "8..32",
                  "straggler_every": 8, "straggler_new_tokens": straggle,
                  "useful_tokens": useful},
        "config": {"slots": slots, "s_max": s_max, "hidden": hidden,
                   "layers": layers_n, "heads": heads, "vocab": vocab,
                   "dtype": "bf16" if dt_ == jnp.bfloat16 else "f32",
                   "kernel": "fused_slot_decode_step",
                   "fast_path": "flash_prefill + ragged paged decode "
                                "(kernels/decode_attention.py); "
                                "interpret-mode emulation off-TPU — "
                                "stage 4c is the A/B of record"},
    }
    _persist_artifact(_SERVE_FILE, art, reduced, has_data=True)
    return art


def _serve_paged_ab(params, cfg, dt_, slots, s_max, vocab, n_req):
    """Paged-vs-contiguous KV at EQUAL cache bytes on a prefix-heavy
    trace (every request shares one long system prompt, deliberately
    NOT block-aligned so copy-on-write forks are exercised).  The
    contiguous layout pays slots * S_max tokens no matter what; the
    paged pool holds the same bytes as blocks, stores the shared prefix
    ONCE, and reserves only each request's actual span — so it holds
    more concurrent sequences per HBM byte, which is the occupancy
    number that turns into tok/s on chip.  Records
    peak_concurrent_slots and hbm_bytes_per_slot for both layouts plus
    the pool's sharing/COW counters; greedy outputs must be identical
    (this is suite stage 4c's A/B of record alongside masked-vs-ragged).
    """
    from hetu_tpu.serving import Request, ServingEngine

    rng = np.random.RandomState(777)
    block = 16
    prefix = rng.randint(0, vocab, s_max // 4 + 1).astype(np.int32)
    trace = []
    for _ in range(n_req - max(2, n_req // 8)):
        tail = rng.randint(0, vocab,
                           int(rng.randint(4, 9))).astype(np.int32)
        trace.append((np.concatenate([prefix, tail]),
                      int(rng.randint(8, 17))))
    # follow-up turns: extend an earlier request's FULL prompt verbatim
    # (multi-turn shape) — these match a full-length prefix entry
    # mid-block and exercise the copy-on-write fork
    for i in range(max(2, n_req // 8)):
        ext = rng.randint(0, vocab,
                          int(rng.randint(4, 9))).astype(np.int32)
        trace.append((np.concatenate([trace[i][0], ext]),
                      int(rng.randint(8, 17))))
    useful = sum(g for _, g in trace)
    # equal bytes: the contiguous pair is slots * S_max tokens; the
    # pool gets the same token count in blocks (+ the scratch block)
    pool = slots * (s_max // block) + 1

    def run(paged):
        if paged:
            kw = dict(paged=True, kv_block=block, pool_blocks=pool,
                      slots=min(slots * 8, 64), prefix_share=True)
        else:
            kw = dict(paged=False, slots=slots)
        mk = lambda: [Request(prompt=p, max_new_tokens=g)
                      for p, g in trace]
        warm = ServingEngine(params, cfg, queue_limit=n_req, dtype=dt_,
                             **kw)
        warm.run(mk())
        e = ServingEngine(params, cfg, queue_limit=n_req, dtype=dt_,
                          **kw)
        t0 = time.perf_counter()
        res = e.run(mk())
        wall = time.perf_counter() - t0
        bytes_ = int(e.kv.cache_bytes)
        peak = max(e.peak_live, 1)
        row = {
            "tokens_per_sec": round(useful / wall, 1),
            "wall_s": round(wall, 3),
            "peak_concurrent_slots": e.peak_live,
            "cache_bytes": bytes_,
            "hbm_bytes_per_slot": int(bytes_ / peak),
        }
        if paged:
            row["kv"] = e.kv.stats()
            row["prefill_chunks"] = e.prefill_chunks
        return row, sorted(r.tokens.tolist() for r in res.values())

    cont, out_c = run(False)
    pg, out_p = run(True)
    return {
        "trace": {"seed": 777, "n_requests": n_req,
                  "shared_prefix_len": int(len(prefix)),
                  "tail_len": "4..8", "new_tokens": "8..16",
                  "followup_turns": max(2, n_req // 8),
                  "useful_tokens": useful},
        "block": block,
        "pool_blocks": pool,
        "contiguous": cont,
        "paged": pg,
        "greedy_identical": out_c == out_p,
        "slot_capacity_ratio": round(
            pg["peak_concurrent_slots"]
            / max(cont["peak_concurrent_slots"], 1), 2),
        "note": "equal cache bytes (+1 scratch block); paged stores "
                "the shared prefix once and reserves actual spans",
    }


def _serve_quant_ab(params, cfg, dt_, slots, s_max, vocab, n_req):
    """Int8 KV cache vs the exact cache at EQUAL HBM bytes (ISSUE 9
    acceptance).  Both runs are paged; the exact pool's byte budget is
    the denominator, and the int8 pool gets as many blocks as fit in
    the SAME bytes (payload + per-(position, head) scale planes both
    counted) — ~3.7x more tokens per byte at Dh=64.  The trace is
    admission-saturating (every request reserves a long span against a
    small pool, slots generous), so peak_concurrent_slots is bound by
    POOL CAPACITY, which is exactly what int8 buys; the acceptance
    floor is >= 1.9x peak slots with greedy outputs top-1-identical.
    CPU tok/s is recorded honestly (dequant is emulated off-chip); the
    on-chip suite stage is the throughput A/B of record."""
    from hetu_tpu.serving import PagedKVManager, Request, ServingEngine

    rng = np.random.RandomState(991)
    block = 16
    L = cfg.num_hidden_layers
    H = cfg.num_attention_heads
    Dh = cfg.hidden_size // H
    # exact pool: enough blocks for slots//2 brim-full sequences — the
    # trace below oversubscribes it several times over
    import jax.numpy as jnp
    reserve = s_max // 4
    pool_exact = max(slots, 4) * (reserve // block) + 1
    per_block_exact = 2 * L * block * H * Dh * jnp.dtype(dt_).itemsize
    budget = pool_exact * per_block_exact
    per_block_int8 = 2 * L * block * H * (Dh + 4)
    pool_int8 = max(budget // per_block_int8, 2)
    trace = []
    for _ in range(n_req):
        P = int(rng.randint(4, 13))
        trace.append((rng.randint(0, vocab, P).astype(np.int32),
                      reserve - 12))       # every request reserves ~the
    useful = sum(g for _, g in trace)      # same long span

    def run(kv_quant, dtype):
        kw = dict(paged=True, kv_block=block, prefix_share=False,
                  slots=max(slots * 16, 128), queue_limit=n_req,
                  dtype=dtype, kv_quant=kv_quant,
                  pool_blocks=(pool_int8 if kv_quant else pool_exact))
        mk = lambda: [Request(prompt=p, max_new_tokens=g)
                      for p, g in trace]
        warm = ServingEngine(params, cfg, **kw)
        warm.run(mk())
        e = ServingEngine(params, cfg, **kw)
        t0 = time.perf_counter()
        res = e.run(mk())
        wall = time.perf_counter() - t0
        peak = max(e.peak_live, 1)
        row = {
            "kv_quant": kv_quant or "off",
            "dtype": str(jnp.dtype(dtype).name),
            "tokens_per_sec": round(useful / wall, 1),
            "wall_s": round(wall, 3),
            "peak_concurrent_slots": e.peak_live,
            "pool_blocks": e.kv.n_blocks,
            "cache_bytes": int(e.kv.cache_bytes),
            "hbm_bytes_per_slot": int(e.kv.cache_bytes / peak),
        }
        return row, sorted(r.tokens.tolist() for r in res.values())

    # the f32 pool is the capacity denominator of record (acceptance:
    # >= 1.9x vs f32); greedy parity is judged at the SERVING dtype so
    # bf16-vs-f32 compute noise never masquerades as quantization error
    exact, out_e = run(None, jnp.float32)
    if dt_ == jnp.float32:
        out_ref = out_e
    else:
        _, out_ref = run(None, dt_)
    int8, out_q = run("int8", dt_)
    ratio = round(int8["peak_concurrent_slots"]
                  / max(exact["peak_concurrent_slots"], 1), 2)

    # ---- quality gate: greedy top-1-identical under the TOLERANCE-
    # TESTED threshold.  Teacher-force every exact sequence through the
    # fake-quant oracle (arithmetically = int8 store + in-kernel
    # dequant), measure the worst logit perturbation delta, and require
    # every position whose exact top-2 margin exceeds 2*delta to pick
    # the SAME token — positions inside the threshold are genuine
    # near-ties of the underlying model, counted, not hidden.  The
    # free-running engine comparison is recorded alongside (a near-tie
    # flip there changes the continuation, so it may legitimately
    # differ on untrained bench weights). ---- #
    from hetu_tpu.models.gpt_decode import teacher_forced_logits
    import functools
    import jax as _jax
    delta = 0.0
    checked = ties = mismatched = 0
    tf = _jax.jit(functools.partial(
        teacher_forced_logits, params, cfg),
        static_argnames=("kv_fake_quant",))
    for seq in out_ref:
        le = np.asarray(tf(np.asarray(seq, np.int32),
                           kv_fake_quant=False))
        lq = np.asarray(tf(np.asarray(seq, np.int32),
                           kv_fake_quant=True))
        delta = max(delta, float(np.abs(lq - le).max()))
    for seq in out_ref:
        le = np.asarray(tf(np.asarray(seq, np.int32),
                           kv_fake_quant=False))
        lq = np.asarray(tf(np.asarray(seq, np.int32),
                           kv_fake_quant=True))
        top2 = np.sort(le, axis=-1)
        margin = top2[:, -1] - top2[:, -2]
        same = le.argmax(-1) == lq.argmax(-1)
        confident = margin > 2 * delta
        checked += int(confident.sum())
        ties += int((~confident).sum())
        mismatched += int((confident & ~same).sum())

    result = {
        "trace": {"seed": 991, "n_requests": n_req,
                  "prompt_len": "4..12", "reserve_span": reserve,
                  "useful_tokens": useful},
        "block": block,
        "byte_budget": int(budget),
        "exact": exact,
        "int8": int8,
        "slot_capacity_ratio": ratio,
        "greedy_gate": {
            "logit_delta": round(delta, 6),
            "threshold": round(2 * delta, 6),
            "positions_checked": checked,
            "near_ties_excluded": ties,
            "top1_identical_above_threshold": mismatched == 0,
        },
        "greedy_identical_free_running": out_ref == out_q,
        "note": "equal HBM bytes (scale planes counted against the "
                "int8 pool); pool capacity bounds peak concurrency — "
                "the int8 win composes multiplicatively with paged_ab's "
                "prefix sharing; the greedy gate teacher-forces every "
                "sequence through the fake-quant oracle "
                "(gpt_decode.teacher_forced_logits) and requires top-1 "
                "identity wherever the exact margin exceeds the "
                "measured 2*delta tolerance; CPU dequant is "
                "interpret-mode, the on-chip suite stage is the tok/s "
                "A/B of record",
    }
    # the acceptance floors are asserted HERE so a regression in the
    # quantized layout can never bank a quant_ab silently
    assert ratio >= 1.9, (
        f"int8 KV at equal bytes holds only {ratio}x peak slots "
        f"(acceptance floor 1.9x): {exact} vs {int8}")
    assert mismatched == 0 and checked > 0, (
        f"int8 KV flipped {mismatched} greedy tokens whose exact "
        f"margin exceeds the tolerance threshold 2*{delta}")
    return result


def _serve_fleet_ab(params, cfg, dt_, platform, slots, vocab, n_req):
    """Single engine vs an N=2 ServingRouter fleet at EQUAL resources
    (same total slots, so the same total KV cache bytes; the fleet
    splits them across two supervised replicas) on one seeded
    mixed-length trace: aggregate useful tok/s + fleet-clock TTFT p99,
    greedy outputs identical.  A second, deliberately OVERLOADED fleet
    run records the SLO-class shedding contract of record (ISSUE 8
    acceptance): throughput-class traffic is shed first and every
    admitted latency-class request retires with TTFT p95 inside the
    configured SLO.  Both runs are stamped live — the in-process CPU
    harness measures the scheduling/recovery contract; chip fleets are
    per-host."""
    from hetu_tpu.serving import (
        QueueFull, Request, RouterShed, ServingEngine, ServingRouter,
        SLO,
    )

    n_rep = 2
    per = max(slots // n_rep, 1)
    rng = np.random.RandomState(555)
    trace = []
    for _ in range(n_req):
        P = int(rng.randint(4, 17))
        trace.append((rng.randint(0, vocab, P).astype(np.int32),
                      int(rng.randint(8, 25))))
    useful = sum(g for _, g in trace)

    def mk():
        return [Request(prompt=p, max_new_tokens=g) for p, g in trace]

    def run_single():
        warm = ServingEngine(params, cfg, slots=slots,
                             queue_limit=n_req, dtype=dt_)
        warm.run(mk())
        e = ServingEngine(params, cfg, slots=slots, queue_limit=n_req,
                          dtype=dt_)
        t0 = time.perf_counter()
        res = e.run(mk())
        wall = time.perf_counter() - t0
        snap = e.metrics.snapshot()
        return {
            "tokens_per_sec": round(useful / wall, 1),
            "wall_s": round(wall, 3),
            "slots": slots,
            "ttft_p99_s": (round(snap["ttft_p99_s"], 6)
                           if snap["ttft_p99_s"] is not None else None),
        }, sorted(r.tokens.tolist() for r in res.values())

    def run_fleet():
        factory = lambda i: ServingEngine(  # noqa: E731
            params, cfg, slots=per, queue_limit=n_req, dtype=dt_)
        warm = ServingRouter(factory, replicas=n_rep)
        warm.run(mk())
        r = ServingRouter(factory, replicas=n_rep)
        t0 = time.perf_counter()
        res = r.run(mk())
        wall = time.perf_counter() - t0
        snap = r.snapshot()
        return {
            "tokens_per_sec": round(useful / wall, 1),
            "wall_s": round(wall, 3),
            "replicas": n_rep,
            "slots_per_replica": per,
            # fleet clock: router submit -> first token, hops included
            "ttft_p99_s": snap["ttft_p99_s"],
            "routed_per_replica": [row["routed"]
                                   for row in snap["replicas"]],
            "health": snap["health"],
        }, sorted(r_.tokens.tolist() for r_ in res.values())

    single, out_s = run_single()
    fleet, out_f = run_fleet()

    # ---- synthetic overload: tiny queues force pressure past the shed
    # threshold; the router must shed throughput-class traffic FIRST
    # and keep every admitted latency-class request inside the SLO ---- #
    slo_ms = 60000.0   # generous: the CPU harness proves ORDER and the
    # within-budget bound, not chip-scale latency
    factory = lambda i: ServingEngine(  # noqa: E731
        params, cfg, slots=1, queue_limit=2, dtype=dt_,
        slo=[SLO("ttft", "latency", slo_ms)])
    router = ServingRouter(factory, replicas=n_rep, shed_queue=0.5)
    for i in range(n_req):
        cls = "latency" if i % 4 == 0 else "throughput"
        p, g = trace[i]
        try:
            router.submit(Request(prompt=p, max_new_tokens=min(g, 8),
                                  slo_class=cls))
        except RouterShed:
            pass
        except QueueFull:
            router.step()   # hard-full backpressure: drain and move on
    router.run()
    snap = router.snapshot()
    lat = snap["classes"]["latency"]
    overload = {
        "slo_ttft_ms": slo_ms,
        "shed": snap["shed"],
        "shed_by_class": {c: snap["classes"][c]["shed"]
                          for c in snap["classes"]},
        "latency_finished": lat["finished"],
        "latency_ttft_p95_s": lat["ttft_p95_s"],
        "latency_within_slo": (lat["ttft_p95_s"] is not None
                               and lat["ttft_p95_s"] * 1e3 <= slo_ms),
        "queue_pressure": snap["queue_pressure"],
    }

    return {
        "provenance": "live",
        "platform": platform,
        "measured_at": time.strftime("%Y-%m-%d %H:%M UTC",
                                     time.gmtime()),
        "trace": {"seed": 555, "n_requests": n_req,
                  "prompt_len": "4..16", "new_tokens": "8..24",
                  "useful_tokens": useful},
        "single_engine": single,
        "fleet": fleet,
        "greedy_identical": out_s == out_f,
        "overload_shed": overload,
        "note": "equal total slots (same KV cache bytes) split across "
                "2 supervised replicas; in-process CPU harness — the "
                "contract is scheduling + recovery, per-host fleets "
                "are the chip story",
    }


def _serve_swap_ab(params, cfg, dt_, platform, slots, vocab, n_req):
    """Live weight sync A/B at EQUAL fleet slots (ISSUE 15): the same
    seeded trace replayed through two N=2 fleets — ``steady`` (no
    rollout) and ``rolling`` (a v1 -> v2 rollout begins with the trace
    in flight: quiesce -> drain -> swap -> probe -> readmit, one
    replica at a time).  The artifact records tok/s and TTFT p99 for
    both arms plus the availability ratio; the floors asserted here are
    the zero-downtime contract — zero request loss, the rollout lands
    (fleet on v2), every result stamped with its admission version, and
    the mid-swap throughput stays above the one-replica-out floor."""
    from hetu_tpu.serving import (
        Request, ServingEngine, ServingRouter, WeightSyncCoordinator,
    )

    n_rep = 2
    per = max(slots // n_rep, 1)
    rng = np.random.RandomState(1515)
    trace = []
    for _ in range(n_req):
        P = int(rng.randint(4, 17))
        trace.append((rng.randint(0, vocab, P).astype(np.int32),
                      int(rng.randint(8, 25))))
    useful = sum(g for _, g in trace)
    # v2: same pytree shape, visibly different values — the probe
    # decode and the per-result version stamps pin which weights served
    rng2 = np.random.RandomState(1516)
    params_v2 = {k: np.asarray(v, np.float32)
                 + rng2.standard_normal(np.shape(v)).astype(np.float32)
                 * 0.01
                 for k, v in params.items()}

    def mk():
        return [Request(prompt=p, max_new_tokens=g) for p, g in trace]

    def factory(i):
        return ServingEngine(params, cfg, slots=per, queue_limit=n_req,
                             dtype=dt_)

    def run_arm(rolling):
        warm = ServingRouter(factory, replicas=n_rep)
        warm.run(mk())
        r = ServingRouter(factory, replicas=n_rep)
        coord = WeightSyncCoordinator(r, params, version=1)
        t0 = time.perf_counter()
        if rolling:
            assert coord.begin(params_v2, 2)
        res = r.run(mk())
        if rolling:
            coord.drain()
        wall = time.perf_counter() - t0
        snap = r.snapshot()
        row = {
            "tokens_per_sec": round(useful / wall, 1),
            "wall_s": round(wall, 3),
            "ttft_p99_s": snap["ttft_p99_s"],
            "finished": snap["finished"],
            "lost": snap["lost"],
        }
        if rolling:
            row["rollout_state"] = coord.state
            row["fleet_versions"] = coord.fleet_versions()
            row["served_by_version"] = {
                str(v): sum(1 for x in res.values()
                            if x.weight_version == v)
                for v in sorted({x.weight_version
                                 for x in res.values()})}
        return row, res

    steady, _ = run_arm(rolling=False)
    rolling, res_r = run_arm(rolling=True)
    avail = (round(rolling["tokens_per_sec"]
                   / steady["tokens_per_sec"], 3)
             if steady["tokens_per_sec"] else None)

    # the zero-downtime contract, asserted HERE so a regression can
    # never bank a swap_ab silently
    assert rolling["rollout_state"] == "done", rolling
    assert rolling["fleet_versions"] == {i: 2 for i in range(n_rep)}, \
        rolling
    assert steady["lost"] == 0 and rolling["lost"] == 0
    assert steady["finished"] == rolling["finished"] == n_req
    assert all(x.weight_version in (1, 2) for x in res_r.values())
    # one replica is quiesced at a time, so the fleet never drops below
    # half capacity; 0.25 leaves headroom for drain stalls + probe cost
    # on the CPU harness (chip fleets re-measure in the suite gate)
    assert avail is not None and avail >= 0.25, (
        f"rolling swap availability {avail} below floor: "
        f"{rolling} vs {steady}")

    return {
        "provenance": "live",
        "platform": platform,
        "measured_at": time.strftime("%Y-%m-%d %H:%M UTC",
                                     time.gmtime()),
        "trace": {"seed": 1515, "n_requests": n_req,
                  "prompt_len": "4..16", "new_tokens": "8..24",
                  "useful_tokens": useful},
        "steady": steady,
        "rolling": rolling,
        "availability": avail,
        "note": "equal fleet slots, same seeded trace; the rolling arm "
                "starts a v1 -> v2 rollout with the trace in flight — "
                "quiesce/drain/swap/probe/readmit per replica, zero "
                "request loss, every Result version-stamped; CPU "
                "harness — suite stage 00g is the chaos-gated run",
    }


def _serve_autoscale_ab(params, cfg, dt_, platform, slots, vocab):
    """Elastic fleet A/B at EQUAL PEAK CAPACITY (ISSUE 16): one seeded
    diurnal trace (trough -> peak -> trough, zipf sessions, mixed SLO
    classes) replayed against a virtual clock through two fleets —
    ``static`` (pinned at the peak size all day: min = max = N, so the
    autoscaler provably never acts and only integrates the cost) and
    ``autoscaled`` (starts at 1 replica, grows on queue pressure,
    shrinks on sustained idle).  The cost surface is REPLICA-SECONDS —
    what the static fleet burns all day to cover its peak minute — and
    the floors asserted here are the elasticity contract: zero request
    loss in both arms, the autoscaled arm actually scales (>= 1 up and
    >= 1 down), spends FEWER replica-seconds at equal-or-better SLO
    attainment, and greedy outputs stay token-identical between arms
    on every request both finished."""
    from hetu_tpu.serving import (
        SLO, FleetAutoscaler, ServingEngine, ServingRouter,
        TrafficGenerator, replay,
    )

    n_peak = 2
    per = max(slots // n_peak, 1)
    # generous TTFT budget (30s, in ms): the A/B question is cost at
    # EQUAL attainment, so the objective must be attainable by both
    # arms on the CPU harness (tight-budget burn behavior is the chaos
    # gate's subject, not this artifact's)
    gen = TrafficGenerator(seed=2024, vocab=vocab, s_max=32,
                           horizon_s=3.0, base_rps=2.0, peak_rps=80.0,
                           cycle_s=3.0, n_sessions=8, zipf_a=1.4,
                           prefix_len=8)
    specs = gen.trace(dt=0.05)
    step_s = 0.01

    def run_arm(autoscaled):
        mons = []

        def factory(i):
            eng = ServingEngine(params, cfg, slots=per, queue_limit=8,
                                dtype=dt_, paged=True,
                                prefix_share=True,
                                slo=[SLO("ttft", "latency", 30_000.0)])
            mons.append(eng.slo)
            return eng

        r = ServingRouter(factory,
                          replicas=(1 if autoscaled else n_peak),
                          directory=True, shed_on_slo=False)
        auto = FleetAutoscaler(
            r,
            fleet_min=(1 if autoscaled else n_peak),
            fleet_max=n_peak,
            up_pressure=0.2, up_ticks=2, up_burn=10.0,
            down_pressure=0.1, down_ticks=30, cooldown=10,
            warm_prefixes=4)
        t0 = time.perf_counter()
        # one idle diurnal cycle of virtual tail gives the scale-down
        # its sustained-idle window
        res, rep = replay(r, specs, step_s=step_s, tail_s=3.0)
        wall = time.perf_counter() - t0
        snap = r.snapshot()
        viol = sum(m.violations for m in mons)
        obs = sum(m.observed for m in mons)
        return {
            "replicas": (f"1..{n_peak}" if autoscaled else str(n_peak)),
            "wall_s": round(wall, 3),
            "finished": snap["finished"],
            "lost": snap["lost"],
            "shed": len(rep["shed"]),
            "rejected": len(rep["rejected"]),
            "requeued": snap["requeued"],
            # virtual-clock cost: one tick per router.step == step_s of
            # trace time, so this is deterministic where wall-clock
            # replica-seconds (reported too) absorb CPU compile noise
            "replica_seconds": round(auto.replica_ticks * step_s, 4),
            "replica_seconds_wall": auto.snapshot()["replica_seconds"],
            "peak_replicas": auto.snapshot()["peak_replicas"],
            "scale_ups": auto.scale_ups,
            "scale_downs": auto.scale_downs,
            "slo_attainment": round(1.0 - viol / max(obs, 1), 4),
            "ttft_p99_s": snap["ttft_p99_s"],
        }, res

    # warm the jit caches once so neither arm banks compile time as
    # replica-seconds (arm order must not decide the A/B)
    warm = ServingRouter(
        lambda i: ServingEngine(params, cfg, slots=per, queue_limit=8,
                                dtype=dt_, paged=True,
                                prefix_share=True),
        replicas=1, shed_on_slo=False)
    replay(warm, specs[:8], step_s=step_s)

    static, res_s = run_arm(autoscaled=False)
    auto, res_a = run_arm(autoscaled=True)

    # the elasticity contract, asserted HERE so a regression can never
    # bank an autoscale_ab silently
    assert static["lost"] == 0 and auto["lost"] == 0, (static, auto)
    assert static["scale_ups"] == static["scale_downs"] == 0, static
    assert auto["scale_ups"] >= 1 and auto["scale_downs"] >= 1, auto
    assert auto["replica_seconds"] < static["replica_seconds"], (
        f"autoscaled fleet burned {auto['replica_seconds']} "
        f"replica-seconds, static burned {static['replica_seconds']}")
    assert auto["slo_attainment"] >= static["slo_attainment"], (
        static, auto)
    assert auto["slo_attainment"] >= 0.98, auto
    common = set(res_s) & set(res_a)
    assert common, "arms share no finished requests"
    for rid in common:
        assert list(res_s[rid].tokens) == list(res_a[rid].tokens), rid

    return {
        "provenance": "live",
        "platform": platform,
        "measured_at": time.strftime("%Y-%m-%d %H:%M UTC",
                                     time.gmtime()),
        "trace": dict(gen.describe(), n_requests=len(specs)),
        "static": static,
        "autoscaled": auto,
        "replica_seconds_saved": round(
            static["replica_seconds"] - auto["replica_seconds"], 4),
        "token_identical_common": len(common),
        "note": "equal peak capacity (static pinned at N, autoscaled "
                "1..N), same seeded diurnal trace on a virtual clock; "
                "scale-up on queue pressure, scale-down on sustained "
                "idle; CPU harness — suite stage 00h is the "
                "chaos-gated run",
    }


def _serve_fleet_prefix_ab(params, cfg, dt_, platform, slots, s_max,
                           vocab, n_req):
    """Fleet prefix intelligence at EQUAL fleet slots (ISSUE 12): a
    prefix-storm trace (two long shared system prompts, every request
    a DISTINCT session so PR 8 affinity hashing scatters them) replayed
    through three N=2 fleets:

    - ``affinity``  — PR 8 behavior (``directory=False``): each replica
      prefills each system prompt for itself;
    - ``directory`` — the PrefixDirectory routes matching prompts to
      the replica already HOLDING the prefix, so the fleet prefills
      each system prompt once;
    - ``roles``     — directory + prefill/decode disaggregation
      (``roles="prefill,decode"``): cold long prompts prefill on the
      prefill-heavy replica and the KV span hands off to its decode
      home over the int8-capable wire.

    Requests are replayed in WAVES (the storm shape: tenants arriving
    over time, not one atomic batch) so later waves can actually
    consult what earlier waves registered.  Greedy outputs must be
    token-identical across all three arms, and the acceptance floors
    are asserted HERE so a regression can never bank the artifact
    silently: directory tok/s >= affinity tok/s and directory TTFT p99
    <= 1.25x affinity's."""
    from hetu_tpu.serving import Request, ServingEngine, ServingRouter

    n_rep = 2
    per = max(slots // n_rep, 1)
    sys_len = s_max // 2 - 8          # long, deliberately NOT aligned
    rng = np.random.RandomState(777)
    sys_a = rng.randint(0, vocab, sys_len).astype(np.int32)
    sys_b = rng.randint(0, vocab, sys_len).astype(np.int32)
    trace = []
    for i in range(n_req):
        base = sys_a if i % 2 == 0 else sys_b
        tail = rng.randint(0, vocab, 2).astype(np.int32)
        trace.append((np.concatenate([base, tail]),
                      int(rng.randint(4, 9))))
    useful = sum(g for _, g in trace)
    wave = max(n_req // 4, 1)

    def mk():
        return [Request(prompt=p, max_new_tokens=g,
                        session_id=f"tenant-{i}")
                for i, (p, g) in enumerate(trace)]

    def factory(**kw):
        return lambda i: ServingEngine(
            params, cfg, slots=per, queue_limit=n_req, dtype=dt_,
            paged=True, prefix_share=True, **kw)

    def run_arm(**router_kw):
        warm = ServingRouter(factory(), replicas=n_rep, **router_kw)
        warm.run(mk())
        r = ServingRouter(factory(), replicas=n_rep, **router_kw)
        reqs = mk()
        out = {}
        t0 = time.perf_counter()
        for i in range(0, n_req, wave):
            out.update(r.run(reqs[i:i + wave]))
        wall = time.perf_counter() - t0
        snap = r.snapshot()
        row = {
            "tokens_per_sec": round(useful / wall, 1),
            "wall_s": round(wall, 3),
            "ttft_p99_s": snap["ttft_p99_s"],
            "directory": ({k: snap["directory"][k] for k in
                           ("hits", "misses", "steals", "stale",
                            "hit_rate")}
                          if snap["directory"] else None),
            "directory_hit_rate": snap["directory_hit_rate"],
            "handoffs": snap["handoffs"],
            "handoff_bytes": snap["handoff_bytes"],
        }
        return row, sorted(v.tokens.tolist() for v in out.values())

    affinity, out_a = run_arm(directory=False)
    directory, out_d = run_arm()
    roles, out_r = run_arm(roles="prefill,decode")
    if directory["tokens_per_sec"] < affinity["tokens_per_sec"] or \
            (affinity["ttft_p99_s"] and directory["ttft_p99_s"]
             and directory["ttft_p99_s"]
             > affinity["ttft_p99_s"] * 1.25):
        # the wave replay is a WALL-CLOCK measurement on a shared CPU:
        # a load spike during one arm can invert a timing floor with
        # no code regression behind it.  One full remeasure (all arms,
        # same order) decides; a real regression fails both passes.
        # Token identity is deterministic and is never retried.
        affinity, out_a = run_arm(directory=False)
        directory, out_d = run_arm()
        roles, out_r = run_arm(roles="prefill,decode")

    speedup = (round(directory["tokens_per_sec"]
                     / affinity["tokens_per_sec"], 3)
               if affinity["tokens_per_sec"] else None)
    result = {
        "provenance": "live",
        "platform": platform,
        "measured_at": time.strftime("%Y-%m-%d %H:%M UTC",
                                     time.gmtime()),
        "trace": {"seed": 777, "n_requests": n_req,
                  "system_prompts": 2, "system_prompt_len": sys_len,
                  "new_tokens": "4..8", "wave": wave,
                  "useful_tokens": useful},
        "affinity_only": affinity,
        "directory": directory,
        "directory_roles": roles,
        "speedup_directory": speedup,
        "speedup_roles": (round(roles["tokens_per_sec"]
                                / affinity["tokens_per_sec"], 3)
                          if affinity["tokens_per_sec"] else None),
        "greedy_identical": out_a == out_d == out_r,
        "note": "equal fleet slots across all arms; the affinity arm "
                "still has PER-REPLICA prefix caching (PR 6) — the "
                "directory's win is fleet-level placement, each "
                "system prompt prefilled once per FLEET instead of "
                "once per replica",
    }
    # acceptance floors (ISSUE 12): the directory must not lose to
    # affinity-only on its home turf, and greedy outputs must match
    assert result["greedy_identical"], (
        "fleet_prefix_ab arms diverged: directory/role routing "
        "changed greedy tokens")
    assert directory["tokens_per_sec"] >= affinity["tokens_per_sec"], (
        f"directory routing lost throughput on a prefix storm: "
        f"{directory['tokens_per_sec']} vs {affinity['tokens_per_sec']}"
        f" tok/s (floor: >= 1.0x affinity-only)")
    if affinity["ttft_p99_s"] and directory["ttft_p99_s"]:
        assert directory["ttft_p99_s"] <= affinity["ttft_p99_s"] * 1.25, (
            f"directory routing degraded TTFT p99: "
            f"{directory['ttft_p99_s']}s vs affinity "
            f"{affinity['ttft_p99_s']}s (floor: <= 1.25x)")
    assert (directory["directory"] or {}).get("hits", 0) > 0, (
        "prefix storm produced zero directory hits — the directory "
        "is not being consulted")
    assert roles["handoffs"] > 0, (
        "role-split arm produced zero KV handoffs")
    return result


def _serve_prefix_storm_ab(params, cfg, dt_, platform, vocab):
    """Tiered-KV A/B at EQUAL POOL SIZE (ISSUE 17): a zipf-session
    prefix storm whose warm working set (12 distinct 8-token session
    heads plus bodies) deliberately exceeds a starved paged pool
    (2 slots, 8 blocks), replayed on a virtual clock through three
    single-replica fleets:

    - ``drop``    — PR 6 behavior (no tiers): every refcount-zero
      eviction discards the prefix KV, the next request of that
      session re-prefills it;
    - ``tiered``  — the full ladder (host-RAM ring sized to ~2 blocks
      so demotion to the sharded-PS cold store is exercised too):
      evictions spill, admission misses fetch back token-identically;
    - ``tiered_ps_chaos`` — same ladder with ``HETU_CHAOS``
      role=kvtier killing the PS mid-storm: the store must mark the
      cold rung dead and degrade to drop-on-evict with ZERO loss.

    The acceptance floors ride in-bench so a regression can never bank
    silently: greedy outputs identical across all three arms, zero
    request loss everywhere, tiered saves strictly more recompute
    tokens than drop (``prefix_hit_tokens``) without degrading TTFT
    p99 (<= 1.10x), the ladder actually cycles (spills AND fetches),
    and the chaos arm ends with ``ps_dead`` set."""
    from hetu_tpu.ps import faults
    from hetu_tpu.ps.server import PSServer
    from hetu_tpu.ps.sharded import ShardedPSClient
    from hetu_tpu.serving import (
        ServingEngine, ServingRouter, TieredKVStore, TrafficGenerator,
        replay,
    )

    gen = TrafficGenerator(seed=909, vocab=vocab, s_max=32,
                           horizon_s=2.0, base_rps=12.0, peak_rps=12.0,
                           cycle_s=2.0, n_sessions=12, zipf_a=1.3,
                           prefix_len=8)
    specs = gen.trace(dt=0.05)
    step_s = 0.01
    # ~4 spilled prefixes of host ring (a full registered head+body
    # span exports ~16KB here): small enough that the storm overflows
    # the ring and demotes down to the PS rung, large enough that the
    # ring serves fetches of its own
    host_bytes = 65536

    def factory(i):
        return ServingEngine(params, cfg, slots=2, queue_limit=64,
                             dtype=dt_, paged=True, kv_block=8,
                             pool_blocks=8, prefix_share=True)

    def run_arm(mode):
        store = None
        if mode != "drop":
            store = TieredKVStore(
                host_bytes=host_bytes, ps_tier=True,
                ps=ShardedPSClient(servers=[PSServer(), PSServer()]))
        if mode == "tiered_ps_chaos":
            os.environ["HETU_CHAOS"] = "seed=5,kill=2,role=kvtier"
            faults.reset_plans()
        try:
            # kv_tiers=None resolves from_env(), which is OFF here —
            # both registry knobs were popped for the A/B sandbox
            r = ServingRouter(factory, replicas=1, kv_tiers=store)
            t0 = time.perf_counter()
            res, rep = replay(r, specs, step_s=step_s)
            wall = time.perf_counter() - t0
            snap = r.snapshot()
            kv = r.replicas[0].engine.kv
            tiers = snap["kv_tiers"]
            row = {
                "wall_s": round(wall, 3),
                "finished": snap["finished"],
                "lost": snap["lost"],
                "shed": len(rep["shed"]),
                "rejected": len(rep["rejected"]),
                "ttft_p99_s": snap["ttft_p99_s"],
                "recompute_tokens_saved": kv.prefix_hit_tokens,
                "pool_spills": kv.spills,
                "replica_restarts": sum(x["restarts"]
                                        for x in snap["replicas"]),
                "tiers": tiers,
            }
            if store is not None:
                store.close("bench_arm_done")
            return row, sorted(v.tokens.tolist() for v in res.values())
        finally:
            if mode == "tiered_ps_chaos":
                os.environ.pop("HETU_CHAOS", None)
                faults.reset_plans()

    saved_env = {k: os.environ.pop(k, None)
                 for k in ("HETU_KV_HOST_BYTES", "HETU_KV_PS_TIER",
                           "HETU_CHAOS")}
    faults.reset_plans()
    try:
        # warm the jit caches once so arm order cannot decide the A/B.
        # The warm fleet runs WITH tiers over the whole trace: the
        # fetch-resume path prefills residual suffixes (prompt minus
        # the re-admitted head), whose pow2 buckets a plain warm-up
        # never compiles — unwarmed, the tiered arm banks compile
        # pauses as TTFT
        wstore = TieredKVStore(
            host_bytes=host_bytes, ps_tier=True,
            ps=ShardedPSClient(servers=[PSServer(), PSServer()]))
        warm = ServingRouter(factory, replicas=1, kv_tiers=wstore)
        replay(warm, specs, step_s=step_s)
        wstore.close("bench_warmup_done")

        drop, out_d = run_arm("drop")
        tiered, out_t = run_arm("tiered")
        chaos, out_c = run_arm("tiered_ps_chaos")
        if drop["ttft_p99_s"] and tiered["ttft_p99_s"] and \
                tiered["ttft_p99_s"] > drop["ttft_p99_s"] + 0.050:
            # wall-clock TTFT on a shared CPU: one remeasure of the
            # timed arms decides the cap (chaos arm re-runs too so the
            # greedy-identity triple stays one coherent measurement);
            # a real fetch-path stall fails both passes
            drop, out_d = run_arm("drop")
            tiered, out_t = run_arm("tiered")
            chaos, out_c = run_arm("tiered_ps_chaos")
    finally:
        for k, v in saved_env.items():
            if v is not None:
                os.environ[k] = v
        faults.reset_plans()

    result = {
        "provenance": "live",
        "platform": platform,
        "measured_at": time.strftime("%Y-%m-%d %H:%M UTC",
                                     time.gmtime()),
        "trace": dict(gen.describe(), n_requests=len(specs)),
        "pool": {"slots": 2, "pool_blocks": 8, "kv_block": 8,
                 "host_ring_bytes": host_bytes, "ps_shards": 2},
        "drop_on_evict": drop,
        "tiered": tiered,
        "tiered_ps_chaos": chaos,
        "recompute_tokens_saved_delta": (
            tiered["recompute_tokens_saved"]
            - drop["recompute_tokens_saved"]),
        "greedy_identical": out_d == out_t == out_c,
        "note": "equal pool size across all arms (2 slots x 8 blocks "
                "of 8 tokens vs a 12-session zipf working set); the "
                "drop arm still has in-pool prefix caching (PR 6) — "
                "the ladder's win is capacity BEYOND the pool, "
                "measured as recompute tokens saved (the TTFT win is "
                "the on-chip claim; this harness's model re-prefills "
                "a head faster than any fetch); suite stage 00i is "
                "the chaos-gated contract run",
    }
    # acceptance floors (ISSUE 17)
    assert result["greedy_identical"], (
        "prefix_storm_ab arms diverged: tiering changed greedy tokens")
    for name, row in (("drop", drop), ("tiered", tiered),
                      ("chaos", chaos)):
        assert row["lost"] == 0 and row["shed"] == 0 \
            and row["rejected"] == 0, (name, row)
    assert (tiered["recompute_tokens_saved"]
            > drop["recompute_tokens_saved"]), (
        f"tiering saved no recompute over drop-on-evict: "
        f"{tiered['recompute_tokens_saved']} vs "
        f"{drop['recompute_tokens_saved']} prefix-hit tokens")
    if drop["ttft_p99_s"] and tiered["ttft_p99_s"]:
        if platform == "tpu":
            # the TTFT WIN is the on-chip claim: re-prefilling a real
            # system prompt through a real model dwarfs a block fetch
            assert tiered["ttft_p99_s"] <= drop["ttft_p99_s"] * 1.10, (
                f"tiering degraded TTFT p99: {tiered['ttft_p99_s']}s "
                f"vs drop {drop['ttft_p99_s']}s (floor: <= 1.10x)")
        else:
            # CPU harness: the 2-layer h128 model re-prefills an
            # 8-token head in under a millisecond, so the fetch path's
            # fixed cost (~3ms import_blocks) can only lose on wall
            # TTFT here — cap the overhead absolutely instead (a
            # compile pause or PS stall on the fetch path still fails)
            assert (tiered["ttft_p99_s"]
                    <= drop["ttft_p99_s"] + 0.050), (
                f"tier fetch path stalled: TTFT p99 "
                f"{tiered['ttft_p99_s']}s vs drop "
                f"{drop['ttft_p99_s']}s (floor: <= drop + 50ms)")
    t_stats = tiered["tiers"]
    assert sum(t_stats["spills"].values()) > 0 \
        and sum(t_stats["fetches"].values()) > 0, (
        f"the ladder never cycled on the storm: {t_stats}")
    assert t_stats["demotes"] > 0, (
        "the host ring never overflowed into the PS rung — the storm "
        "is not exercising the full ladder", t_stats)
    assert chaos["tiers"]["ps_dead"] is True, (
        "chaos arm never killed the PS rung — kill=2/role=kvtier "
        "did not fire", chaos["tiers"])
    assert chaos["replica_restarts"] == 0, (
        "the PS kill took a REPLICA down with it — tier degradation "
        "must never escape as an engine crash", chaos)
    return result


def _serve_spec_ab(params, cfg, dt_, platform, slots, s_max, vocab,
                   n_req):
    """Speculative vs plain decoding at EQUAL slots (ISSUE 10).

    High-acceptance point: the measured model is the bench model with
    every layer PAST the draft output-zeroed (attn_proj/ffn_wo weights
    and biases set to 0; the reduced 2-layer CPU model is additionally
    DEEPENED to 6 layers by replicating the zeroed block, so the
    target:draft cost ratio resembles a real deployment instead of
    2:1), so the truncated-layer draft's logits equal the target's
    bitwise — greedy acceptance is 1.0 by construction while the
    target still pays full-depth compute per verify, which is the
    regime speculation exists for.  The temperature sweep then
    degrades acceptance honestly: the target SAMPLES while the draft
    proposes greedily, so hotter requests accept fewer drafts — a real
    acceptance-rate sweep on one model.  Token identity spec-vs-plain
    is asserted at EVERY sweep point (greedy and sampled alike: the
    engine's accepted tokens are the target's own sequential samples),
    the wall-clock tok/s floor is asserted at the high-acceptance
    point, and TPOT percentiles come from real per-step token counts in
    both modes.  CPU numbers are stamped live; the on-chip stage 4c
    invocation records this section on chip — the A/B of record."""
    from hetu_tpu.models import GPTConfig
    from hetu_tpu.models.gpt_decode import _infer_name
    from hetu_tpu.serving import Request, ServingEngine

    name = _infer_name(params)
    draft_layers = 1
    spec_k = 4
    L = max(cfg.num_hidden_layers, 6)
    zeroed = ("attn_proj_weight", "attn_proj_bias",
              "ffn_wo_weight", "ffn_wo_bias")
    sp = dict(params)
    for i in range(draft_layers, L):
        src = min(i, cfg.num_hidden_layers - 1)
        for suffix in ("ln1_scale", "ln1_bias", "ln2_scale", "ln2_bias",
                       "attn_q_weight", "attn_q_bias", "attn_k_weight",
                       "attn_k_bias", "attn_v_weight", "attn_v_bias",
                       "ffn_wi_weight", "ffn_wi_bias", *zeroed):
            v = np.asarray(params[f"{name}_h{src}_{suffix}"])
            sp[f"{name}_h{i}_{suffix}"] = (np.zeros_like(v)
                                           if suffix in zeroed else v)
    if L != cfg.num_hidden_layers:
        cfg = GPTConfig(
            vocab_size=cfg.vocab_size, hidden_size=cfg.hidden_size,
            num_hidden_layers=L,
            num_attention_heads=cfg.num_attention_heads,
            max_position_embeddings=cfg.max_position_embeddings,
            batch_size=cfg.batch_size, seq_len=cfg.seq_len,
            dropout_rate=0.0)

    rng = np.random.RandomState(888)
    trace = []
    for _ in range(n_req):
        P = int(rng.randint(4, 13))
        trace.append((rng.randint(0, vocab, P).astype(np.int32),
                      int(rng.randint(16, 33))))
    useful = sum(g for _, g in trace)

    def run(spec, temperature):
        kw = dict(slots=slots, queue_limit=n_req, dtype=dt_,
                  spec=(spec_k if spec else 0), spec_adapt=False,
                  spec_draft_layers=draft_layers)
        mk = lambda: [Request(prompt=p, max_new_tokens=g,  # noqa: E731
                              temperature=temperature, seed=i)
                      for i, (p, g) in enumerate(trace)]
        warm = ServingEngine(sp, cfg, **kw)
        warm.run(mk())
        # best of two measured replays: the speedup floor below is
        # ASSERTED, so a single background-load hiccup must not be
        # able to fail the gate
        best = None
        for _ in range(2):
            e_ = ServingEngine(sp, cfg, **kw)
            t0 = time.perf_counter()
            res_ = e_.run(mk())
            w_ = time.perf_counter() - t0
            if best is None or w_ < best[0]:
                best = (w_, e_, res_)
        wall, e, res = best
        snap = e.metrics.snapshot()
        row = {
            "tokens_per_sec": round(useful / wall, 1),
            "wall_s": round(wall, 3),
            "steps": e.steps,
            "tokens_per_step_mean": (round(snap["tokens_per_step_mean"],
                                           3)
                                     if snap["tokens_per_step_mean"]
                                     else None),
            # TPOT percentiles from REAL per-step emitted-token counts
            # (serving/metrics.py step_tokens) in BOTH modes
            "tpot_p50_s": snap["tpot_p50_s"],
            "tpot_p99_s": snap["tpot_p99_s"],
        }
        if spec:
            row.update({
                "spec_k": spec_k,
                "draft_layers": draft_layers,
                "proposed": e.spec_proposed,
                "accepted": e.spec_accepted,
                "acceptance_rate": round(e.spec_acceptance or 0.0, 4),
                "mean_k": round(e.spec_mean_k or 0.0, 2),
                "waves": e.spec_waves,
            })
        return row, sorted(r.tokens.tolist() for r in res.values())

    plain, out_p = run(False, 0.0)
    spec_hi, out_s = run(True, 0.0)
    speedup = (round(spec_hi["tokens_per_sec"]
                     / plain["tokens_per_sec"], 3)
               if plain["tokens_per_sec"] else None)

    # acceptance-rate sweep via temperature: hotter target sampling
    # accepts fewer greedy draft proposals; token identity must hold
    # at every point (accepted tokens ARE the target's samples).  The
    # greedy headline above is the acceptance-1.0 endpoint; one hot
    # point bounds the other end (more temperatures on chip if wanted)
    sweep = []
    for t in (1.0,):
        srow, souts = run(True, t)
        _, pouts = run(False, t)
        sweep.append({
            "temperature": t,
            "acceptance_rate": srow["acceptance_rate"],
            "tokens_per_sec": srow["tokens_per_sec"],
            "tokens_per_step_mean": srow["tokens_per_step_mean"],
            "identical": souts == pouts,
        })

    result = {
        "provenance": "live",
        "platform": platform,
        "measured_at": time.strftime("%Y-%m-%d %H:%M UTC",
                                     time.gmtime()),
        "trace": {"seed": 888, "n_requests": n_req,
                  "prompt_len": "4..12", "new_tokens": "16..32",
                  "useful_tokens": useful},
        "spec_k": spec_k,
        "draft_layers": draft_layers,
        "target_layers": L,
        "plain": plain,
        "spec": spec_hi,
        "speedup": speedup,
        "greedy_identical": out_p == out_s,
        "acceptance_sweep": sweep,
        "note": "equal slots; layers past the draft output-zeroed (and "
                "the reduced model deepened to 6 layers) so draft "
                "logits == target logits (acceptance 1.0 at greedy) "
                "while verify pays full depth — the high-acceptance "
                "endpoint; sweep temperatures degrade acceptance "
                "honestly (target samples vs greedy draft); CPU "
                "harness runs the verify kernels in interpret mode — "
                "stage 4c on chip is the A/B of record",
    }
    # acceptance floors asserted HERE so a speculative-path regression
    # can never bank a spec_ab silently
    assert result["greedy_identical"], (
        "speculative greedy outputs diverged from the plain engine")
    assert all(r["identical"] for r in sweep), (
        f"speculative sampled outputs diverged in the sweep: {sweep}")
    assert spec_hi["acceptance_rate"] >= 0.95, (
        f"high-acceptance point accepted only "
        f"{spec_hi['acceptance_rate']} of drafts: {spec_hi}")
    assert speedup is not None and speedup > 0
    if (os.cpu_count() or 1) >= 2:
        # the wall-clock floor needs the draft scan and the batched
        # verify to overlap with XLA's intra-op threads; on a 1-core
        # host they serialize onto the same core and the win collapses
        # to noise, so the floor only binds with >= 2 cores (the
        # token-identity + acceptance + tokens/step floors above still
        # bind everywhere)
        assert speedup >= 1.05, (
            f"speculation at acceptance "
            f"{spec_hi['acceptance_rate']} shows no wall-clock win "
            f"(speedup {speedup}): {plain} vs {spec_hi}")
    return result


def _serve_ragged_ab(params, cfg, dt_, platform, slots, s_max, vocab,
                     n_req):
    """Mixed-mode ragged dispatch vs the phase-split scheduler
    (ISSUE 18) on a trace that exercises BOTH regimes at once: half
    the requests are prefill-heavy (long chunked prompts, short
    tails), half decode-heavy (short prompts, long tails), so every
    engine step mixes chunk continuations with decode streams — the
    wave shape the phase barrier penalizes.  Greedy token identity
    between the modes is asserted at the end; the ragged arm's
    chunk_stall tail component must be EXACTLY zero (mixed mode folds
    it at retirement after asserting the residue is bounded), and
    tok/s must be no worse than phase-split (strict speedup floor
    gated to TPU — the CPU harness runs both arms through XLA-batched
    attention, so only dispatch-count savings show here; suite stage
    4c on chip is the A/B of record)."""
    from hetu_tpu.serving import Request, ServingEngine

    chunk = max(8, s_max // 16)
    rng = np.random.RandomState(999)
    trace = []
    for i in range(n_req):
        if i % 2 == 0:      # prefill-heavy: chunked prompt, short tail
            P = int(rng.randint(s_max // 4, s_max // 2))
            gen = int(rng.randint(4, 9))
        else:               # decode-heavy: short prompt, long tail
            P = int(rng.randint(4, 13))
            gen = int(rng.randint(16, 33))
        trace.append((rng.randint(0, vocab, P).astype(np.int32), gen))
    useful = sum(g for _, g in trace)

    def run(ragged):
        kw = dict(slots=slots, queue_limit=n_req, dtype=dt_,
                  paged=True, kv_block=8, prefill_chunk=chunk,
                  ragged=ragged)
        mk = lambda: [Request(prompt=p, max_new_tokens=g,  # noqa: E731
                              seed=i)
                      for i, (p, g) in enumerate(trace)]
        warm = ServingEngine(params, cfg, **kw)
        warm.run(mk())
        # best of two measured replays — the no-worse floor below is
        # ASSERTED, so a background-load hiccup must not fail the gate
        best = None
        for _ in range(2):
            e_ = ServingEngine(params, cfg, **kw)
            t0 = time.perf_counter()
            res_ = e_.run(mk())
            w_ = time.perf_counter() - t0
            if best is None or w_ < best[0]:
                best = (w_, e_, res_)
        wall, e, res = best
        snap = e.metrics.snapshot()
        tail = e.metrics.explain_tail()
        stall = snap["components"].get("chunk_stall_ms")
        row = {
            "tokens_per_sec": round(useful / wall, 1),
            "wall_s": round(wall, 3),
            "steps": e.steps,
            "prefill_dispatches": snap["prefill_dispatches"],
            "ttft_p50_s": snap["ttft_p50_s"],
            "ttft_p99_s": snap["ttft_p99_s"],
            "tpot_p50_s": snap["tpot_p50_s"],
            "chunk_stall_p99_ms": (stall["p99_ms"] if stall else None),
            "tail_dominant": (tail["dominant_component"]
                              if tail else None),
            "tail_components_ms": (tail["components_mean_ms"]
                                   if tail else None),
        }
        return row, sorted(r.tokens.tolist() for r in res.values())

    phase, out_p = run(False)
    mixed, out_m = run(True)
    speedup = (round(mixed["tokens_per_sec"] / phase["tokens_per_sec"],
                     3)
               if phase["tokens_per_sec"] else None)
    result = {
        "provenance": "live",
        "platform": platform,
        "measured_at": time.strftime("%Y-%m-%d %H:%M UTC",
                                     time.gmtime()),
        "trace": {"seed": 999, "n_requests": n_req,
                  "prefill_heavy_prompt": f"{s_max // 4}..{s_max // 2 - 1}",
                  "decode_heavy_prompt": "4..12",
                  "useful_tokens": useful, "prefill_chunk": chunk},
        "phase_split": phase,
        "ragged": mixed,
        "speedup": speedup,
        "greedy_identical": out_p == out_m,
        "note": "ONE ragged wave per step (arrivals + chunk "
                "continuations + decode; kernels/ragged_attention.py) "
                "vs the prefill-then-decode phase-split scheduler; "
                "chunk_stall vanishes by construction in mixed mode; "
                "CPU harness runs masked attention in both arms — "
                "stage 4c on chip is the A/B of record",
    }
    # floors asserted HERE so a mixed-mode regression can never bank a
    # ragged_ab silently
    assert result["greedy_identical"], (
        "mixed-mode greedy outputs diverged from the phase-split engine")
    assert mixed["chunk_stall_p99_ms"] in (None, 0.0), (
        f"ragged arm still shows chunk_stall: {mixed}")
    assert phase["chunk_stall_p99_ms"], (
        "phase-split arm shows NO chunk_stall — the trace no longer "
        "exercises chunked prefill and this A/B is vacuous")
    assert speedup is not None and speedup > 0
    # the CPU masked path computes the UNION wave width for every slot
    # (a 16-token chunk in the wave makes each decode slot pay 16 rows
    # of forward compute), so "no worse" is an on-chip claim — there
    # the ragged kernel skips dead q rows and the dispatch savings are
    # the point.  The CPU floor below is a regression backstop only
    # (catches a mixed-mode scheduler pathology, not a kernel claim)
    assert speedup >= 0.5, (
        f"mixed mode collapsed to {speedup}x phase-split on the mixed "
        f"trace — scheduler regression, not padding overhead: "
        f"{phase} vs {mixed}")
    if platform == "tpu":
        # the strict no-worse floor, gated to the platform the ragged
        # kernel actually runs on (stage 4c banks this on chip)
        assert speedup >= 1.0, (
            f"mixed mode shows no on-chip win (speedup {speedup}): "
            f"{phase} vs {mixed}")
    return result


def _serve_moe_ab(cfg, dt_, platform, slots, s_max, vocab, n_req):
    """MoE vs dense serving at EQUAL ACTIVE PARAMS (ISSUE 20): the
    flagship MoE GPT (top-2 of 4 experts, expert_size = ffn_size /
    top_k, so each token's FFN FLOPs match the dense arm exactly)
    against a dense GPT of the same hidden/layers/heads, replaying the
    same seeded trace through the same engine configuration.  Records
    tok/s + TTFT p99 per arm and the MoE arm's expert telemetry
    (per-expert load, imbalance max/mean, drop rate).

    Floors asserted HERE (and re-asserted on the banked artifact in
    test_serving): the MoE arm's engine outputs are GREEDY-IDENTICAL
    to offline ``generate_fast`` on the same weights; at the serving
    capacity factor the drop rate is EXACTLY zero (capacity
    un-binding — so identity is unconditional, not luck); the
    capacity-binding probe run shows drops while load+drop still
    accounts for every (token, rank); and the attribution invariant
    holds on the measured run.  Throughput parity is an on-chip claim
    (CPU pays the full E-expert einsum regardless of routing; suite
    stage 4c banks ``moe_ab`` on chip) — the CPU floor is a loose
    scheduler-regression backstop only."""
    from hetu_tpu.models import GPTConfig
    from hetu_tpu.models.moe_decode import (MoEDecodeConfig,
                                            init_moe_params,
                                            moe_spec_of)
    from hetu_tpu.models.gpt_decode import generate_fast
    from hetu_tpu.serving import Request, ServingEngine

    hidden, layers_n, heads = (cfg.hidden_size, cfg.num_hidden_layers,
                               cfg.num_attention_heads)
    E, K = 4, 2
    mcfg = MoEDecodeConfig(
        vocab_size=vocab, hidden_size=hidden,
        num_hidden_layers=layers_n, num_attention_heads=heads,
        max_position_embeddings=s_max, batch_size=slots,
        seq_len=s_max, dropout_rate=0.0,
        num_experts=E, top_k=K, capacity_factor=2.0, moe_every=2,
        expert_size=cfg.ffn_size // K)
    mparams = init_moe_params(mcfg, name="moe", seed=7)
    dcfg = GPTConfig(
        vocab_size=vocab, hidden_size=hidden,
        num_hidden_layers=layers_n, num_attention_heads=heads,
        max_position_embeddings=s_max, batch_size=slots,
        seq_len=s_max, dropout_rate=0.0)
    # dense twin: same naming contract and trunk scale; every block
    # carries the full-width dense FFN, so per-token FFN FLOPs match
    # the MoE arm's K * expert_size exactly
    dparams = _dense_twin_params(dcfg, vocab, hidden, layers_n, s_max,
                                 seed=7)

    rng = np.random.RandomState(555)
    trace = []
    for _ in range(n_req):
        P = int(rng.randint(4, 17))
        trace.append((rng.randint(0, vocab, P).astype(np.int32),
                      int(rng.randint(8, 25))))
    useful = sum(g for _, g in trace)

    def run(p_, c_, name_):
        kw = dict(slots=slots, queue_limit=n_req, dtype=dt_,
                  fast_path=True, paged=True, kv_block=8, name=name_)
        mk = lambda: [Request(request_id=str(i),  # noqa: E731
                              prompt=p, max_new_tokens=g, seed=i)
                      for i, (p, g) in enumerate(trace)]
        warm = ServingEngine(p_, c_, **kw)
        warm.run(mk())
        e = ServingEngine(p_, c_, **kw)
        t0 = time.perf_counter()
        res = e.run(mk())
        wall = time.perf_counter() - t0
        snap = e.metrics.snapshot()
        row = {
            "tokens_per_sec": round(useful / wall, 1),
            "wall_s": round(wall, 3),
            "ttft_p99_s": snap["ttft_p99_s"],
            "tpot_p50_s": snap["tpot_p50_s"],
            "steps": e.steps,
        }
        return row, e, res

    dense_row, _, _ = run(dparams, dcfg, "moe")
    moe_row, meng, mres = run(mparams, mcfg, "moe")
    spec = moe_spec_of(mcfg)
    n_moe = spec.moe_layers(layers_n)
    load = meng.expert_load
    moe_row.update({
        "expert_load": load.tolist(),
        "expert_imbalance": (round(float(meng.expert_imbalance), 4)
                             if meng.expert_imbalance is not None
                             else None),
        "drop_rate": (round(float(meng.expert_drop_rate), 6)
                      if meng.expert_drop_rate is not None else None),
    })

    # greedy identity vs offline on a sub-trace (the full trace's
    # offline replay would double the bench wall time for no extra
    # signal — test_moe_serving.py pins the full matrix)
    ident = True
    for i, (p, g) in enumerate(trace[:4]):
        off = generate_fast(mparams, mcfg, [list(map(int, p))], g,
                            temperature=0.0, seed=0, dtype=dt_,
                            name="moe")
        eng_toks = [int(t) for t in
                    np.asarray(mres[str(i)].tokens)[len(p):]]
        if eng_toks != [int(t) for t in np.asarray(off)[0][len(p):]]:
            ident = False
            break

    # capacity-binding probe: a tiny capacity factor MUST drop (the
    # trace contract stage 00l asserts on chip) while the accounting
    # invariant still closes
    bcfg = MoEDecodeConfig(
        vocab_size=vocab, hidden_size=hidden,
        num_hidden_layers=layers_n, num_attention_heads=heads,
        max_position_embeddings=s_max, batch_size=slots,
        seq_len=s_max, dropout_rate=0.0,
        num_experts=E, top_k=K, capacity_factor=0.25, moe_every=2,
        expert_size=cfg.ffn_size // K)
    _, beng, _ = run(mparams, bcfg, "moe")
    binding = {
        "capacity_factor": 0.25,
        "drop_rate": (round(float(beng.expert_drop_rate), 6)
                      if beng.expert_drop_rate is not None else None),
        "invariant_ok": int(beng.expert_load.sum()
                            + beng.expert_drops.sum())
        == beng.moe_tokens * K * n_moe,
    }

    speedup = (round(moe_row["tokens_per_sec"]
                     / dense_row["tokens_per_sec"], 3)
               if dense_row["tokens_per_sec"] else None)
    result = {
        "provenance": "live",
        "platform": platform,
        "measured_at": time.strftime("%Y-%m-%d %H:%M UTC",
                                     time.gmtime()),
        "trace": {"seed": 555, "n_requests": n_req,
                  "prompt_len": "4..16", "new_tokens": "8..24",
                  "useful_tokens": useful},
        "equal_active_params": {
            "experts": E, "top_k": K, "moe_every": 2,
            "expert_size": mcfg.expert_size,
            "dense_ffn_size": dcfg.ffn_size,
            "active_ffn_per_token": K * mcfg.expert_size,
        },
        "dense": dense_row,
        "moe": moe_row,
        "speedup_vs_dense": speedup,
        "greedy_identical": ident,
        "capacity_binding": binding,
        "note": "equal active params: top_k * expert_size == dense "
                "ffn_size; CPU pays the full E-expert einsum whatever "
                "the routing, so tok/s parity is an on-chip claim — "
                "suite stage 4c banks moe_ab on chip",
    }
    # floors asserted HERE so a routing regression can never bank a
    # moe_ab silently (re-asserted on the artifact in test_serving)
    assert ident, "MoE engine diverged from offline generate_fast"
    assert moe_row["drop_rate"] == 0.0, (
        f"serving capacity factor binds on the bench trace "
        f"(drop_rate={moe_row['drop_rate']}) — identity is luck")
    assert moe_row["expert_imbalance"] is not None \
        and moe_row["expert_imbalance"] >= 1.0
    assert sum(moe_row["expert_load"]) > 0
    assert binding["drop_rate"] > 0, (
        "cf=0.25 probe dropped nothing — capacity is not binding and "
        "the drop path is untested")
    assert binding["invariant_ok"], (
        "load+drop no longer accounts for every (token, rank) under "
        "binding capacity")
    assert speedup is not None and speedup > 0.05, (
        f"MoE arm collapsed to {speedup}x dense — scheduler/dispatch "
        f"regression, not expert-compute cost: {dense_row} vs "
        f"{moe_row}")
    return result


def _dense_twin_params(dcfg, vocab, hidden, layers_n, s_max, seed):
    """Dense-GPT params in the serving naming contract, seeded like the
    MoE arm's shared trunk (attention/embeddings match scale, FFN
    carries the full dense width)."""
    rng = np.random.default_rng(seed)
    D, F = hidden, dcfg.ffn_size

    def r(*shape):
        return (rng.standard_normal(shape) * 0.02).astype(np.float32)

    p = {"moe_wte_table": r(vocab, D),
         "moe_wpe": r(s_max, D),
         "moe_ln_f_scale": np.ones(D, np.float32),
         "moe_ln_f_bias": np.zeros(D, np.float32)}
    for i in range(layers_n):
        us = f"moe_h{i}"
        p.update({
            f"{us}_ln1_scale": np.ones(D, np.float32),
            f"{us}_ln1_bias": np.zeros(D, np.float32),
            f"{us}_ln2_scale": np.ones(D, np.float32),
            f"{us}_ln2_bias": np.zeros(D, np.float32),
            f"{us}_attn_q_weight": r(D, D),
            f"{us}_attn_q_bias": np.zeros(D, np.float32),
            f"{us}_attn_k_weight": r(D, D),
            f"{us}_attn_k_bias": np.zeros(D, np.float32),
            f"{us}_attn_v_weight": r(D, D),
            f"{us}_attn_v_bias": np.zeros(D, np.float32),
            f"{us}_attn_proj_weight": r(D, D),
            f"{us}_attn_proj_bias": np.zeros(D, np.float32),
            f"{us}_ffn_wi_weight": r(D, F),
            f"{us}_ffn_wi_bias": np.zeros(F, np.float32),
            f"{us}_ffn_wo_weight": r(F, D),
            f"{us}_ffn_wo_bias": np.zeros(D, np.float32),
        })
    return p


def _serve_phase_ab(params, cfg, dt_, reduced):
    """Per-phase micro A/B outside the scheduler: (a) the fused decode
    step, masked vs ragged, at 25%/50% cache fill — the ragged kernel
    fetches ceil(filled/block_k) KV blocks, so its step time scales
    with fill while masked-S_max stays flat; (b) one-request prefill,
    teacher-forced scan vs flash, at prompt length 128 (the acceptance
    floor).  Engine-free: raw serve_*_fn calls on a standalone cache."""
    import jax
    from hetu_tpu.models.gpt_decode import (
        serve_decode_fn, serve_prefill_batch_fn, serve_prefill_fn,
    )
    from hetu_tpu.serving import KVCacheManager

    Dh = cfg.hidden_size // cfg.num_attention_heads
    kv = KVCacheManager(
        layers=cfg.num_hidden_layers, heads=cfg.num_attention_heads,
        head_dim=Dh, slots=cfg.batch_size,
        max_seq_len=cfg.max_position_embeddings, dtype=dt_)
    cfg_tuple = ("srv", cfg.num_hidden_layers, cfg.num_attention_heads,
                 Dh, kv.s_max)
    B = kv.n_slots
    iters = 5 if reduced else 30
    tok = np.ones(B, np.int32)
    temps = np.zeros(B, np.float32)
    topks = np.zeros(B, np.int32)
    keys = np.stack([np.asarray(jax.random.PRNGKey(i), np.uint32)
                     for i in range(B)])

    def time_decode(attn, filled):
        fn = serve_decode_fn(donate=False, attn=attn)
        pos = np.full(B, filled - 1, np.int32)
        out = fn(params, cfg_tuple, kv.cache_k, kv.cache_v, pos, tok,
                 temps, topks, keys)
        jax.block_until_ready(out[0])              # warm the compile
        t0 = time.perf_counter()
        for _ in range(iters):
            out = fn(params, cfg_tuple, kv.cache_k, kv.cache_v, pos,
                     tok, temps, topks, keys)
        jax.block_until_ready(out[0])
        return round((time.perf_counter() - t0) / iters * 1e3, 3)

    decode_rows = []
    for frac in (0.25, 0.5):
        filled = max(1, int(kv.s_max * frac))
        masked_ms = time_decode("masked", filled)
        ragged_ms = time_decode("ragged", filled)
        decode_rows.append({
            "fill": frac, "filled_len": filled, "s_max": kv.s_max,
            "masked_ms": masked_ms, "ragged_ms": ragged_ms,
            "ragged_speedup": (round(masked_ms / ragged_ms, 3)
                               if ragged_ms else None)})

    P = min(128, kv.s_max // 2)
    prompt = np.arange(1, P + 1, dtype=np.int32) % cfg.vocab_size
    key = np.asarray(jax.random.PRNGKey(0), np.uint32)

    def time_prefill(flash):
        if flash:
            fn = serve_prefill_batch_fn(donate=False)
            args = (params, cfg_tuple, kv.cache_k, kv.cache_v,
                    np.zeros(1, np.int32), prompt[None],
                    np.asarray([P], np.int32), np.zeros(1, np.float32),
                    np.zeros(1, np.int32), key[None])
        else:
            fn = serve_prefill_fn(donate=False)
            args = (params, cfg_tuple, kv.cache_k, kv.cache_v,
                    np.int32(0), prompt, np.int32(P),
                    np.float32(0.0), np.int32(0), key)
        out = fn(*args)
        jax.block_until_ready(out[0])
        t0 = time.perf_counter()
        for _ in range(iters):
            out = fn(*args)
        jax.block_until_ready(out[0])
        return round((time.perf_counter() - t0) / iters * 1e3, 3)

    scan_ms = time_prefill(False)
    flash_ms = time_prefill(True)
    return {
        "decode": decode_rows,
        "prefill": {"prompt_len": P, "scan_ms": scan_ms,
                    "flash_ms": flash_ms,
                    "flash_speedup": (round(scan_ms / flash_ms, 3)
                                      if flash_ms else None)},
    }


_EMBED_SERVE_FILE = os.path.join(_HERE, "BENCH_EMBED_SERVE.json")


def bench_embed_serve(platform, reduced):
    """Embedding-cache recommendation serving (ISSUE 14 tentpole,
    hetu_tpu/serving/embed_engine): replay ONE seeded zipf(1.05) CTR
    scoring trace through the cache-fronted engine at a ladder of
    cache-limit points (p99 latency + QPS + hit rate per point), A/B
    the int8 PS pull wire against exact f32 on ACTUAL transport reply
    payload bytes (``cache.pull_bytes`` counts decoded f32 rows by
    design, so the wire win is metered at the transport seam — the
    byte floor is asserted here, not just recorded), and kill the PS
    for the middle third of a final run to prove the stale/zero
    degradation protocol retires every request anyway."""
    from hetu_tpu.cache.cstable import CacheSparseTable
    from hetu_tpu.ps.client import PSClient, PSConnectionError
    from hetu_tpu.ps.server import PSServer
    from hetu_tpu.ps.sharded import _LocalServerTransport
    from hetu_tpu.quant import QuantArray
    from hetu_tpu.serving import EmbedRequest, EmbedServingEngine

    vocab, e_dim, n_req, pairs, wave = 8192, 16, 256, 4, 8
    if reduced:
        vocab, e_dim, n_req, pairs, wave = 1024, 16, 96, 4, 8

    class _MeteredTransport:
        """_LocalServerTransport + wire accounting + a kill switch.
        Sums the ACTUAL pull-reply row payload (QuantArray int8+scales
        vs f32 rows) — the in-process path never crosses
        ``_TCPTransport``, so the ``ps.rpc.bytes_*`` counters don't
        tick and the A/B must meter here."""

        def __init__(self, server):
            self._inner = _LocalServerTransport(server)
            self.pull_payload_bytes = 0
            self.down = False

        @staticmethod
        def _nb(rows):
            if isinstance(rows, QuantArray):
                return rows.nbytes
            if isinstance(rows, np.ndarray):
                return rows.nbytes
            return 0

        def call(self, method, *a, **kw):
            if self.down:
                raise PSConnectionError("PS down (bench outage)")
            out = self._inner.call(method, *a, **kw)
            if method in ("sync_embedding", "push_sync_embedding"):
                self.pull_payload_bytes += self._nb(out[1])
            elif method == "sparse_pull":
                self.pull_payload_bytes += self._nb(out)
            return out

        def close(self):
            self._inner.close()

    rng = np.random.RandomState(777)
    h = 16
    flat = 26 * e_dim
    params = {"W1": rng.randn(13, h) * 0.3,
              "W2": rng.randn(h, h) * 0.3,
              "W3": rng.randn(h, h) * 0.3,
              "W4": rng.randn(flat + h, 1) * 0.3}
    trace = []
    for _ in range(n_req):
        raw = rng.zipf(1.05, size=(pairs, 26))
        trace.append(((raw - 1) % vocab,
                      rng.randn(pairs, 13).astype(np.float32)))

    def mk_reqs():
        # pinned ids: the A/B compares per-request scores across runs
        return [EmbedRequest(item_ids=ids, dense_features=d,
                             request_id=f"r{i:04d}")
                for i, (ids, d) in enumerate(trace)]

    def mk_engine(limit):
        server = PSServer()
        server.param_init("snd_order_embedding", (vocab, e_dim),
                          "normal", 0.0, 1.0, seed=3)
        meter = _MeteredTransport(server)
        comm = PSClient(transport=meter)
        table = CacheSparseTable(limit=limit, vocab_size=vocab,
                                 width=e_dim,
                                 key="snd_order_embedding", comm=comm,
                                 policy="LRU")
        eng = EmbedServingEngine(params,
                                 {"snd_order_embedding": table},
                                 model="wdl", wave=wave,
                                 queue_limit=n_req)
        return eng, table, meter, comm

    # ---- warm every row-bucket compile outside the measured windows
    # (wave composition is deterministic given the trace, so one full
    # warm pass covers every bucket the ladder runs will hit) ---- #
    warm, _, _, warm_comm = mk_engine(vocab)
    warm.run(mk_reqs())
    warm_comm.finalize()

    def run_point(limit):
        eng, table, meter, comm = mk_engine(limit)
        t0 = time.perf_counter()
        res = eng.run(mk_reqs())
        wall = time.perf_counter() - t0
        assert len(res) == n_req and all(
            r.finish_reason == "scored" for r in res.values()), \
            "embed serve ladder lost requests"
        snap = eng.metrics.snapshot()
        cs = table.perf_summary()
        comm.finalize()
        scores = np.concatenate(
            [res[k].scores for k in sorted(res)])
        return {
            "cache_limit": limit,
            "hit_rate": round(cs["hit_rate"], 4),
            "qps": snap["qps"],
            "pairs_per_sec": snap["pairs_per_sec"],
            "latency_p50_ms": round((snap["latency_p50_s"] or 0) * 1e3,
                                    3),
            "latency_p99_ms": round((snap["latency_p99_s"] or 0) * 1e3,
                                    3),
            "gather_ms_p50": snap["gather_ms_p50"],
            "wave_ms_p50": snap["wave_ms_p50"],
            "pulled_rows": cs["pulled_rows"],
            "pull_bytes_decoded": cs["pull_bytes"],
            "wire_pull_payload_bytes": meter.pull_payload_bytes,
            "wall_s": round(wall, 3),
        }, scores

    # ---- cache-limit ladder: the zipf head fits at every point; how
    # much of the tail fits is what the limit buys ---- #
    ladder = []
    for limit in (vocab // 32, vocab // 8, vocab // 2, vocab):
        row, _ = run_point(limit)
        ladder.append(row)

    # ---- int8 pull wire A/B at full cache (every pull is the cold
    # refill, the byte-bound phase int8 exists for).  Floor asserted:
    # quantized pulls must halve the wire, and scores must agree to
    # the chunked-int8 tolerance ---- #
    saved_q = os.environ.pop("HETU_PS_QUANT", None)
    try:
        exact_row, exact_scores = run_point(vocab)
        os.environ["HETU_PS_QUANT"] = "int8"
        int8_row, int8_scores = run_point(vocab)
    finally:
        os.environ.pop("HETU_PS_QUANT", None)
        if saved_q is not None:
            os.environ["HETU_PS_QUANT"] = saved_q
    byte_ratio = (exact_row["wire_pull_payload_bytes"]
                  / max(int8_row["wire_pull_payload_bytes"], 1))
    score_max_err = float(np.max(np.abs(exact_scores - int8_scores)))
    assert byte_ratio >= 2.0, \
        f"int8 pull wire saved only {byte_ratio:.2f}x (floor 2.0x)"
    assert score_max_err < 0.05, \
        f"int8 pull scores diverged: max |d| {score_max_err}"
    quant_ab = {
        "exact": exact_row,
        "int8": int8_row,
        "wire_byte_ratio": round(byte_ratio, 3),
        "score_max_abs_err": round(score_max_err, 6),
        "floor": "wire_byte_ratio >= 2.0 (asserted in-bench; small "
                 "tail pulls stay f32 below quant.WIRE_MIN_SIZE)",
    }

    # ---- PS-kill chaos: same trace, PS dark for the middle third;
    # stale rows for warm ids, zeros for cold ones, ZERO loss ---- #
    eng, table, meter, comm = mk_engine(vocab // 8)
    reqs = mk_reqs()
    third = n_req // 3
    res = dict(eng.run(reqs[:third]))
    meter.down = True
    res.update(eng.run(reqs[third:2 * third]))
    meter.down = False
    res.update(eng.run(reqs[2 * third:]))
    comm.finalize()
    assert len(res) == n_req and all(
        r.finish_reason == "scored" for r in res.values()), \
        "PS outage lost requests"
    cs = table.perf_summary()
    assert cs["ps_failures"] > 0, "the bench outage never fired"
    chaos = {
        "requests": n_req,
        "scored": sum(1 for r in res.values()
                      if r.finish_reason == "scored"),
        "zero_request_loss": True,
        "ps_failures": cs["ps_failures"],
        "stale_served_rows": cs["stale_served_rows"],
        "zero_served_rows": cs["zero_served_rows"],
        "replayed_rows": cs["replayed_rows"],
        "hit_rate": round(cs["hit_rate"], 4),
        "cache_limit": vocab // 8,
    }

    art = {
        "platform": platform,
        "reduced_scale": reduced,
        "measured_at": time.strftime("%Y-%m-%d %H:%M UTC",
                                     time.gmtime()),
        "workload": "embedding-cache CTR serving (wdl tower, zipf "
                    "sparse ids through CacheSparseTable -> one "
                    "jitted wave forward)",
        "cache_ladder": ladder,
        "quant_ab": quant_ab,
        "ps_kill_chaos": chaos,
        "trace": {"seed": 777, "zipf_a": 1.05, "n_requests": n_req,
                  "pairs_per_request": pairs, "sparse_fields": 26,
                  "dense_fields": 13, "wave": wave},
        "config": {"vocab": vocab, "embed_dim": e_dim, "model": "wdl",
                   "hidden": h, "policy": "LRU",
                   "comm": "PSClient over in-process transport "
                           "(wire bytes metered at the transport "
                           "seam)"},
    }
    _persist_artifact(_EMBED_SERVE_FILE, art, reduced, has_data=True)
    return art


_SWEEP_FILE = os.path.join(_HERE, "SWEEP_BERT_BASE.json")

_PROBE_SWEEP_SRC = """
import json, os
os.environ["HETU_BENCH_FORCE_FLASH"] = {flash!r}
if {fused!r} == "1":
    os.environ["HETU_BENCH_FUSED_HEAD"] = "1"
else:
    os.environ.pop("HETU_BENCH_FUSED_HEAD", None)   # parent env leak
import bench
r = bench._bench_lm({platform!r}, {reduced!r}, layers_n=12, seq=512,
                    per_chip_batch={b}, iters={iters})
print("PROBE_RESULT " + json.dumps(
    {{"step_time_ms": r["step_time_ms"],
      "flash_attention": r["flash_attention"],
      "flash_fallback": r.get("flash_fallback")}}))
"""


def _sweep_cell_from_result(cell, r, want_flash):
    """Record a measured cell, refusing to mislabel a flash fallback as
    a flash measurement (the fitted attention delta would be ~0 and the
    artifact's impl ranking meaningless)."""
    if want_flash and not r.get("flash_attention", want_flash):
        cell["error"] = ("flash fell back to xla: "
                         + str(r.get("flash_fallback"))[:160])
    else:
        cell["step_time_ms"] = r["step_time_ms"]
        if r.get("flash_fallback"):
            cell["flash_fallback"] = r["flash_fallback"]


def sweep_bert(platform, reduced, batches=(16, 32, 48, 64)):
    """On-chip ablation sweep over (per-chip batch x attention impl x
    LM-head variant) -> SWEEP_BERT_BASE.json, the measured strategy
    space the exec-config planner is validated against
    (planner/exec_plan.py; VERDICT r3 item 6).

    Each cell runs in a subprocess with a hard timeout (same rationale
    as bench_bert_base: a wedged tunnel must cost one cell, not the
    sweep).  Reduced mode measures the tiny-graph grid in-process with
    the batch axis kept REAL (keep_batch) — the artifact then records a
    CPU-measured space, still a genuine measured ordering for the
    validation loop to close over."""
    import itertools as _it
    if reduced:
        batches = (2, 4, 8)
    grid = list(_it.product(batches, ("xla", "flash"),
                            ("materialized", "fused")))
    rows = []
    deadline = time.monotonic() + 3600.0
    for b, attn, head in grid:
        cell = {"batch": b, "attention": attn, "head": head}
        if reduced:
            old_flash = envvars.get_raw("HETU_BENCH_FORCE_FLASH")
            old_fused = envvars.get_raw("HETU_BENCH_FUSED_HEAD")
            os.environ["HETU_BENCH_FORCE_FLASH"] = \
                "1" if attn == "flash" else "0"
            if head == "fused":
                os.environ["HETU_BENCH_FUSED_HEAD"] = "1"
            else:
                os.environ.pop("HETU_BENCH_FUSED_HEAD", None)
            try:
                r = _bench_lm(platform, True, layers_n=12, seq=512,
                              per_chip_batch=b, iters=3, keep_batch=True)
                _sweep_cell_from_result(cell, r, attn == "flash")
            except Exception as e:
                cell["error"] = f"{type(e).__name__}: {e}"[:200]
            finally:
                if old_flash is None:
                    os.environ.pop("HETU_BENCH_FORCE_FLASH", None)
                else:
                    os.environ["HETU_BENCH_FORCE_FLASH"] = old_flash
                if old_fused is None:
                    os.environ.pop("HETU_BENCH_FUSED_HEAD", None)
                else:
                    os.environ["HETU_BENCH_FUSED_HEAD"] = old_fused
        else:
            src = _PROBE_SWEEP_SRC.format(
                flash="1" if attn == "flash" else "0",
                fused="1" if head == "fused" else "0",
                platform=platform, reduced=False, b=b, iters=8)
            got = _run_probe(src, deadline, min_left=120.0)
            if isinstance(got, dict):
                _sweep_cell_from_result(cell, got, attn == "flash")
            else:
                cell["error"] = str(got)
        rows.append(cell)

    art = {
        "platform": platform,
        "reduced_scale": reduced,
        "measured_at": time.strftime("%Y-%m-%d %H:%M UTC", time.gmtime()),
        "model": ("bert_base 12L seq 512" if not reduced
                  else "reduced LM 2L seq 64 (batch axis real)"),
        "objective": "samples/sec/chip (throughput = batch / step_time)",
        "configs": rows,
    }
    try:
        from hetu_tpu.planner.exec_plan import validate_against_sweep
        art["planner_validation"] = validate_against_sweep(art)
    except Exception as e:
        art["planner_validation"] = {
            "error": f"{type(e).__name__}: {e}"[:300]}
    _persist_artifact(_SWEEP_FILE, art, reduced,
                      has_data=any("step_time_ms" in r for r in rows))
    return art


def _enable_compile_cache():
    """Persistent XLA compilation cache: the on-chip suite invokes
    bench.py ~10 times with overlapping configs, and each TPU compile
    costs 20-40s through the tunnel — sharing compiled programs across
    invocations shrinks the recovery-window cost substantially.
    HETU_BENCH_NO_COMPILE_CACHE=1 opts out."""
    if envvars.get_bool("HETU_BENCH_NO_COMPILE_CACHE"):
        return
    import jax
    try:
        jax.config.update("jax_compilation_cache_dir",
                          envvars.get_path("HETU_COMPILE_CACHE_DIR"))
    except Exception:
        pass          # older jax without the knob: run uncached


def _provenance_fields(results, ran, head_name, run_platform,
                       prev_platform=None):
    """Live-vs-banked accounting for the ONE headline record (VERDICT
    weak #4): ``platform`` is the platform of the HEADLINE ROW actually
    measured — a cpu-fallback driver run re-emitting banked on-chip
    values now says ``platform: tpu, headline_provenance: banked`` with
    the bring-up platform preserved separately as ``run_platform`` —
    and every row is explicitly listed under ``rows_live`` or
    ``rows_banked`` (banked rows keep their own ``measured_at``)."""
    head = results.get(head_name, {})
    live = sorted(n for n in results if n in ran)
    banked = {n: {"measured_at": results[n].get("measured_at"),
                  "platform": results[n].get("platform")
                  or prev_platform or "unknown"}
              for n in sorted(results) if n not in ran}
    if head_name in ran:
        head_platform = head.get("platform") or run_platform
    else:
        head_platform = head.get("platform") or prev_platform or "unknown"
    return {
        "platform": head_platform,
        "run_platform": run_platform,
        "headline_provenance": "live" if head_name in ran else "banked",
        # quantization provenance: the headline row's quant modes (rows
        # predating the stamp read "off" — they were measured exact)
        "quant": head.get("quant", "off"),
        "rows_live": live,
        "rows_banked": banked,
    }


def main():
    platform, bringup_err = _bring_up_backend()
    _enable_compile_cache()
    reduced = envvars.get_bool("HETU_BENCH_SMALL") or \
        platform in ("cpu", "cpu-fallback")

    if envvars.get_bool("HETU_BENCH_DECODE"):
        art = bench_decode(platform, reduced)
        print(json.dumps({
            "metric": "gpt_decode_tokens_per_sec",
            "value": art["tokens_per_sec"], "unit": "tokens/sec",
            "vs_baseline": None, "platform": platform,
            "batch": art["config"]["batch"],
            "s_max": art["config"]["s_max"],
            **({"not_written": art["not_written"]}
               if "not_written" in art else
               {"decode_file": os.path.basename(_DECODE_FILE)})}))
        return

    if envvars.get_bool("HETU_BENCH_SERVE"):
        art = bench_serve(platform, reduced)
        cont = art["continuous"]
        print(json.dumps({
            "metric": "serve_continuous_tokens_per_sec",
            "value": cont["tokens_per_sec"], "unit": "tokens/sec",
            # vs_baseline here = speedup over static batching on the
            # same trace (the serving acceptance ratio, not the north
            # star target)
            "vs_baseline": art["speedup"], "platform": platform,
            "static_tokens_per_sec":
                art["static_baseline"]["tokens_per_sec"],
            "ttft_p50_s": cont["ttft_p50_s"],
            "ttft_p99_s": cont["ttft_p99_s"],
            "mean_batch_occupancy": cont["mean_batch_occupancy"],
            **({"not_written": art["not_written"]}
               if "not_written" in art else
               {"serve_file": os.path.basename(_SERVE_FILE)})}))
        return

    if envvars.get_bool("HETU_BENCH_EMBED_SERVE"):
        art = bench_embed_serve(platform, reduced)
        best = art["cache_ladder"][-1]
        print(json.dumps({
            "metric": "embed_serve_qps",
            "value": best["qps"], "unit": "requests/sec",
            # vs_baseline here = the int8 pull wire ratio on the same
            # trace (the ISSUE 14 byte-floor acceptance, asserted
            # in-bench)
            "vs_baseline": art["quant_ab"]["wire_byte_ratio"],
            "platform": platform,
            "hit_rate_ladder": [
                {"cache_limit": r["cache_limit"],
                 "hit_rate": r["hit_rate"],
                 "latency_p99_ms": r["latency_p99_ms"],
                 "qps": r["qps"]} for r in art["cache_ladder"]],
            "ps_kill_zero_loss":
                art["ps_kill_chaos"]["zero_request_loss"],
            **({"not_written": art["not_written"]}
               if "not_written" in art else
               {"embed_serve_file":
                    os.path.basename(_EMBED_SERVE_FILE)})}))
        return

    if envvars.get_bool("HETU_BENCH_CTR_ROWS"):
        art = sweep_ctr_rows(platform, reduced)
        best = max((r for r in art["rungs"] if "error" not in r),
                   key=lambda r: r["rows"], default=None)
        print(json.dumps({
            "metric": "ctr_max_embedding_rows_per_chip",
            "value": art["max_rows"], "unit": "rows",
            "vs_baseline": None, "platform": platform,
            "rows_per_sec_at_max": (best or {}).get(
                "embedding_rows_per_sec"),
            "rungs": [{"rows": r["rows"],
                       **({"error": r["error"]} if "error" in r else
                          {"rows_per_sec": r["embedding_rows_per_sec"]})}
                      for r in art["rungs"]],
            **({"not_written": art["not_written"]}
               if "not_written" in art else
               {"rows_file": os.path.basename(_CTR_ROWS_FILE)})}))
        return

    if envvars.get_bool("HETU_BENCH_SWEEP"):
        art = sweep_bert(platform, reduced)
        pv = art.get("planner_validation", {})
        print(json.dumps({
            "metric": "bert_sweep_planner_choice_ok",
            "value": (1.0 if pv.get("ok") else 0.0),
            "unit": "bool", "vs_baseline": None,
            "platform": platform,
            "argmax_match": pv.get("argmax_match"),
            "regret": pv.get("regret"),
            "spearman_rho": pv.get("spearman_rho"),
            "measured_best": pv.get("measured_best"),
            "predicted_best": pv.get("predicted_best"),
            **({"not_written": art["not_written"]}
               if "not_written" in art else
               {"sweep_file": os.path.basename(_SWEEP_FILE)})}))
        return

    sel = envvars.get_str("HETU_BENCH_CONFIGS")
    names = [n.strip() for n in sel.split(",")] if sel else list(_CONFIGS)
    # bert_base FIRST: its batch probes run in subprocesses, which only
    # work before any in-process config initializes (and exclusively
    # holds) the TPU backend
    if "bert_base" in names:
        names = ["bert_base"] + [n for n in names if n != "bert_base"]

    # MERGE into the existing matrix: a HETU_BENCH_CONFIGS subset run (or
    # a reduced CPU run) must not wipe other configs' recorded numbers —
    # full-scale same-platform runs replace their own entries only
    matrix = {}
    try:
        with open(_MATRIX_FILE) as f:
            matrix = json.load(f)
    except (OSError, ValueError):
        pass
    # the previous capture's platform is the provenance fallback for
    # merged rows that predate per-row platform stamps
    prev_platform = matrix.get("platform")
    results = dict(matrix.get("configs", {}))
    if reduced and any(
            not r.get("reduced_scale") and "error" not in r
            for r in results.values()):
        # never overwrite full-scale records with reduced-scale ones
        results = dict(results)
        names = [n for n in names
                 if results.get(n, {}).get("reduced_scale", True)
                 or "error" in results.get(n, {})]
    matrix["platform"] = platform
    matrix["measured_at"] = time.strftime("%Y-%m-%d %H:%M UTC",
                                          time.gmtime())
    # this note DESCRIBES the current accounting; it must not be
    # merge-carried from an older file whose rows it was written about
    # (per-row measured_at is the provenance for any one entry)
    matrix["accounting_note"] = (
        "MFU = 6*P*T/peak over matmul-participating weights only "
        "(12*H^2/layer + the H*V tied head counted once) plus the "
        "attention score/context matmuls; embedding gathers, LayerNorm, "
        "biases and softmax-xent are excluded from the numerator. Rows "
        "carry their own measured_at: subset runs (HETU_BENCH_CONFIGS) "
        "merge-preserve other rows, so entries may predate the "
        "top-level measured_at.")
    if bringup_err:
        matrix["bringup_retried"] = bringup_err
    ran = set()
    for name in names:
        try:
            results[name] = _CONFIGS[name](platform, reduced)
        except Exception as e:
            results[name] = {"error": f"{type(e).__name__}: {e}"[:300]}
        ran.add(name)
        # per-row stamp: merge keeps rows from older runs/platforms, so
        # the top-level measured_at says nothing about THIS row (the
        # tpu_watchdog's fresh-capture check keys on bert_base's own)
        # and the platform must travel WITH the row it describes
        results[name]["measured_at"] = time.strftime(
            "%Y-%m-%d %H:%M UTC", time.gmtime())
        results[name]["platform"] = platform
        from hetu_tpu import quant, telemetry
        # quant rides every bench row (and the headline provenance):
        # an int8-wire/int8-KV run can never be compared against an
        # exact run silently — hetu_trace --check rejects mixed rows
        results[name]["quant"] = quant.active_modes()
        telemetry.emit("bench_row", config=name, platform=platform,
                       value=results[name].get("value"),
                       mfu=results[name].get("mfu"),
                       quant=results[name]["quant"],
                       **({"error": results[name]["error"]}
                          if "error" in results[name] else {}))
        matrix["configs"] = results
        try:
            # atomic: a stage timeout mid-dump must not truncate the
            # matrix of record (later runs would discard + overwrite)
            from hetu_tpu.artifact import atomic_json_dump
            atomic_json_dump(_MATRIX_FILE, matrix)
        except OSError:
            pass
    matrix["configs"] = results

    if platform == "tpu" and not reduced:
        try:
            from hetu_tpu.artifact import atomic_json_dump
            atomic_json_dump(_TPU_LAST_FILE, matrix)
        except OSError:
            pass

    # ---- the ONE headline line (driver contract) ---- #
    head_name = "bert_base" if "bert_base" in results else \
        (names[0] if names else next(iter(results), "bert_base"))
    head = results.get(head_name, {})
    target = 100.0      # driver-defined north star, samples/sec/chip
    value = head.get("value")
    head_reduced = head.get("reduced_scale", reduced)
    from hetu_tpu.telemetry.health import stamp_provenance
    out = {
        "metric": ("bert_base_seq512_train_throughput"
                   if not head_reduced and head_name == "bert_base"
                   else f"{head_name}_reduced_train_throughput"
                   if head_reduced else f"{head_name}_train_throughput"),
        "value": value,
        "unit": head.get("unit", "samples/sec/chip"),
        "vs_baseline": (round(value / target, 3)
                        if value and not head_reduced
                        and head_name == "bert_base" else None),
        # platform = the headline ROW's platform; rows_live/rows_banked
        # make every row's provenance explicit (VERDICT weak #4: no
        # more "cpu-fallback" wrapped around on-chip values)
        **_provenance_fields(results, ran, head_name, platform,
                             prev_platform),
        "mfu": head.get("mfu"),
        "device_kind": head.get("device_kind"),
        "matrix": {n: stamp_provenance(
            {"value": r.get("value"), "unit": r.get("unit"),
             "mfu": r.get("mfu"),
             **({"error": r["error"]} if "error" in r else {})},
            live=n in ran, measured_at=r.get("measured_at"))
            for n, r in results.items()},
        "matrix_file": os.path.basename(_MATRIX_FILE),
    }
    if "error" in head:
        out["headline_error"] = head["error"]
    if "health_warning" in head:
        # the probe gate's degraded-window flag must surface on the
        # headline, not just deep in the matrix row
        out["headline_health"] = head["health_warning"]
    if bringup_err:
        out["bringup_retried"] = bringup_err
    if platform == "cpu-fallback" and os.path.exists(_TPU_LAST_FILE):
        # context for a tunnel-down bench run: the most recent REAL-chip
        # matrix this working tree produced (self-recorded, dated — NOT a
        # claim about the current run)
        try:
            with open(_TPU_LAST_FILE) as f:
                last = json.load(f)
            out["tpu_last_recorded_run"] = {
                "measured_at": last.get("measured_at"),
                "configs": {n: {"value": r.get("value"),
                                "unit": r.get("unit"),
                                "mfu": r.get("mfu")}
                            for n, r in last.get("configs", {}).items()}}
        except (OSError, ValueError):
            pass
    print(json.dumps(out))


if __name__ == "__main__":
    main()
