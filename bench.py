"""Benchmark: BERT-style transformer training throughput, samples/sec/chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...extras}.
BASELINE config 2 (BERT-base-ish DP).  Robustness contract (round-2 fix for
the r1 rc=1): TPU backend bring-up is probed with retries before any graph
is built; on persistent backend failure the bench falls back to CPU and
says so in the "platform" field rather than dying with rc=1.  The flash
attention path is benchmarked by default, with automatic fallback to the
unfused chain if the Pallas kernel fails to compile on the local chip.

Extras reported: step_time_ms, achieved TFLOP/s/chip, MFU vs the chip's
bf16 peak (when the device kind is recognized), host-side feed fraction,
platform, device count.
"""

from __future__ import annotations

import json
import os
import time

import numpy as np

# bf16 peak TFLOP/s per chip by device kind substring (public specs)
_PEAK_TFLOPS = [
    ("v6", 918.0),          # Trillium / v6e
    ("v5p", 459.0),
    ("v5", 197.0),          # v5e / "TPU v5 lite"
    ("v4", 275.0),
    ("v3", 123.0),
    ("v2", 45.0),
]


def _peak_tflops(device_kind: str):
    kind = device_kind.lower()
    for sub, peak in _PEAK_TFLOPS:
        if sub in kind:
            return peak
    return None


_TPU_LAST_FILE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              "BENCH_TPU_LAST.json")

_PROBE_SRC = """
import jax, numpy as np, jax.numpy as jnp
jax.devices()
np.asarray(jnp.zeros((8, 8)) + 1.0)  # forces backend bring-up + compile
print(jax.default_backend())
"""


def _bring_up_backend(retries=3, probe_timeout=150.0):
    """Probe the default backend in a SUBPROCESS with a hard timeout.

    Two TPU failure modes observed (r1 rc=1 and the wedged-tunnel case from
    the verify notes): backend init raises RuntimeError(UNAVAILABLE), or
    jax.devices() simply HANGS when the axon tunnel is down.  An in-process
    probe cannot recover from the hang, so we probe out-of-process; only a
    clean probe lets this process touch the default backend.  On failure we
    force CPU via jax.config (the axon plugin ignores the JAX_PLATFORMS env
    var, so the config call is the only reliable override).
    """
    import subprocess
    import sys

    import jax

    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        jax.config.update("jax_platforms", "cpu")
        return "cpu", None

    last_err = None
    for attempt in range(retries):
        try:
            r = subprocess.run([sys.executable, "-c", _PROBE_SRC],
                               capture_output=True, text=True,
                               timeout=probe_timeout)
            if r.returncode == 0:
                return r.stdout.strip().splitlines()[-1], last_err
            last_err = (r.stderr.strip().splitlines() or ["?"])[-1][:200]
        except subprocess.TimeoutExpired:
            last_err = f"backend probe hung >{probe_timeout}s (tunnel down?)"
        if attempt < retries - 1:
            # the tunnel has been observed to recover after minutes; a
            # longer backoff buys one more real-TPU shot per round
            time.sleep(45.0 * (attempt + 1))
    jax.config.update("jax_platforms", "cpu")
    return "cpu-fallback", last_err


def _build(batch, seq, hidden, heads, layers_n, vocab, use_flash, mesh,
           n_batches):
    """Model + input pipeline.  Inputs come through the Dataloader (with
    its background prefetch ring device_putting ahead of need), like the
    reference benches pull from their dataloader — a fixed fed array
    would understate host work and overstate throughput."""
    import hetu_tpu as ht

    rng = np.random.RandomState(0)
    id_data = rng.randint(0, vocab, (batch * n_batches, seq)).astype(
        np.int32)
    label_data = rng.randint(0, vocab, (batch * n_batches, seq)).astype(
        np.int32)
    ids = ht.dataloader_op([ht.Dataloader(id_data, batch, "train")])
    labels = ht.dataloader_op([ht.Dataloader(label_data, batch, "train")])
    emb = ht.layers.Embedding(vocab, hidden, name="tok_emb")
    pos = ht.init.random_normal((seq, hidden), stddev=0.02, name="pos_emb")
    h = ht.embedding_lookup_op(emb.embedding_table, ids)
    h = h + ht.broadcast_shape_op(pos, (batch, seq, hidden), add_axes=[0])
    h = ht.array_reshape_op(h, [batch * seq, hidden])
    for i in range(layers_n):
        attn = ht.layers.MultiHeadAttention(hidden, heads, seq, batch,
                                            use_flash=use_flash,
                                            name=f"l{i}_attn")
        h = ht.layers.LayerNorm(hidden, name=f"l{i}_ln1")(h + attn(h))
        wi = ht.layers.Linear(hidden, hidden * 4, name=f"l{i}_ffn_wi")
        wo = ht.layers.Linear(hidden * 4, hidden, name=f"l{i}_ffn_wo")
        h = ht.layers.LayerNorm(hidden, name=f"l{i}_ln2")(
            h + wo(ht.gelu_op(wi(h))))
    logits = ht.layers.Linear(hidden, vocab, name="lm_head")(h)
    loss = ht.reduce_mean_op(
        ht.softmaxcrossentropy_sparse_op(
            logits, ht.array_reshape_op(labels, [batch * seq])), axes=0)
    train = ht.optim.AdamOptimizer(learning_rate=1e-4).minimize(loss)
    # bf16 compute / fp32 masters: the MXU path
    ex = ht.Executor({"train": [loss, train]}, mixed_precision="bf16",
                     mesh=mesh)
    return ex


def _run_once(use_flash, platform):
    import jax
    import hetu_tpu as ht  # noqa: F401  (import checked before timing)
    from hetu_tpu.parallel.mesh import make_mesh

    n_chips = max(1, jax.device_count())
    # BERT-base-ish proxy scaled to bench quickly: hidden 768, 12 heads,
    # 4 layers (1/3 of BERT-base depth), seq 128; DP over all chips.
    # Batch 64/chip measured best on v5e (32: -19%, 128: +2% but 2x mem).
    per_chip_batch, seq, hidden, heads, layers_n, vocab = \
        64, 128, 768, 12, 4, 30522
    iters = 30
    reduced = bool(os.environ.get("HETU_BENCH_SMALL")) or \
        platform in ("cpu", "cpu-fallback")
    if reduced:
        # CPU-verification scale: exercises every code path cheaply.
        # Also used on TPU-bringup failure — a full-scale CPU number
        # is meaningless and would eat the driver's time budget.
        per_chip_batch, seq, hidden, heads, layers_n, vocab = \
            4, 64, 128, 4, 2, 1000
        iters = 3
    batch = per_chip_batch * n_chips
    mesh = make_mesh({"dp": n_chips}) if n_chips > 1 else None

    ex = _build(batch, seq, hidden, heads, layers_n, vocab,
                use_flash, mesh, n_batches=iters + 2)

    # warmup (compile) — materialize to host: block_until_ready does not
    # reliably wait on the tunneled TPU platform in this image
    float(np.asarray(ex.run("train")[0]))

    t_host = 0.0
    t0 = time.perf_counter()
    for _ in range(iters):
        # ex.run returns after host-side feed prep (ring pop of a
        # device-put batch) + async dispatch — outputs are not
        # materialized until after the loop, so its duration IS the
        # per-step host work on the critical path
        tf0 = time.perf_counter()
        out = ex.run("train")
        t_host += time.perf_counter() - tf0
    # the final loss depends on every prior step's params (donated chain),
    # so materializing it forces the full sequence
    float(np.asarray(out[0]))
    dt = (time.perf_counter() - t0) / iters

    # Analytic FLOPs (XLA cost_analysis would require re-lowering and
    # RE-COMPILING the whole step just to read a number — minutes on TPU).
    # 6*P*T covers the parameter matmuls fwd+bwd; the attention
    # score/context matmuls add 12*B*S^2*H per layer (2*2*B*S^2*H fwd, x3
    # with bwd).
    n_params = sum(int(np.prod(v.shape)) for v in ex.var_values.values())
    flops = 6.0 * n_params * (batch * seq) \
        + layers_n * 12.0 * batch * seq * seq * hidden

    kind = jax.devices()[0].device_kind
    peak = _peak_tflops(kind) if platform not in ("cpu", "cpu-fallback") \
        else None
    tflops_chip = flops / dt / n_chips / 1e12
    mfu = round(tflops_chip / peak, 4) if peak else None

    return {
        "samples_per_sec_chip": batch / dt / n_chips,
        "step_time_ms": round(dt * 1e3, 3),
        "tflops_per_sec_chip": round(tflops_chip, 2),
        "mfu": mfu,
        "host_fraction": round(t_host / (dt * iters), 4),
        "device_kind": kind,
        "n_chips": n_chips,
        "flash_attention": use_flash,
        "reduced_scale": reduced,
        "config": {"per_chip_batch": per_chip_batch, "seq": seq,
                   "hidden": hidden, "layers": layers_n, "vocab": vocab},
    }


def main():
    platform, bringup_err = _bring_up_backend()

    # flash is the TPU path; in interpret mode (CPU fallback) it is
    # orders-of-magnitude slower than the fused XLA chain, so don't bench it
    # there except at verification scale
    want_flash = platform == "tpu" or bool(os.environ.get("HETU_BENCH_SMALL"))
    stats, flash_err = None, None
    if want_flash:
        try:
            stats = _run_once(use_flash=True, platform=platform)
        except Exception as e:  # Pallas kernel may fail on an untested chip
            flash_err = f"{type(e).__name__}: {e}"[:300]
    if stats is None:
        stats = _run_once(use_flash=False, platform=platform)

    # target: BASELINE.json north star for the full-scale 4-layer proxy
    # — no published reference numbers exist (BASELINE.md), so the target
    # is the driver-defined 100 samples/sec/chip; vs_baseline tracks
    # rounds and is only meaningful at full scale.
    target = 100.0
    reduced = stats.get("reduced_scale", False)
    metric = "bert4L_seq128_train_throughput" if not reduced \
        else "bert_proxy_reduced_train_throughput"
    out = {
        "metric": metric,
        "value": round(stats.pop("samples_per_sec_chip"), 2),
        "unit": "samples/sec/chip",
        "vs_baseline": None,
        "platform": platform,
        **stats,
    }
    if not reduced:
        out["vs_baseline"] = round(out["value"] / target, 3)
    if bringup_err:
        out["bringup_retried"] = bringup_err
    if flash_err:
        out["flash_fallback"] = flash_err
    if platform == "tpu" and not reduced:
        # persist for tunnel-down rounds (read back below)
        try:
            with open(_TPU_LAST_FILE, "w") as f:
                json.dump({"value": out["value"], "unit": out["unit"],
                           "device_kind": out.get("device_kind"),
                           "mfu": out.get("mfu"),
                           "measured_at": time.strftime(
                               "%Y-%m-%d %H:%M UTC", time.gmtime())}, f)
        except OSError:
            pass
    if platform == "cpu-fallback" and os.path.exists(_TPU_LAST_FILE):
        # context for a tunnel-down bench run: the most recent REAL-chip
        # measurement this working tree produced (self-recorded above,
        # with its date — NOT a claim about the current run)
        with open(_TPU_LAST_FILE) as f:
            out["tpu_last_recorded_run"] = json.load(f)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
