"""Benchmark: BERT-style transformer training throughput, samples/sec/chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
BASELINE config 2 (BERT-base-ish DP); runs on whatever devices exist
(1 real TPU chip under the driver).  vs_baseline is measured/target where
target comes from BASELINE.json-derived expectations; with no published
reference numbers (BASELINE.md) we report vs_baseline=1.0 at the defined
target throughput and track our own trajectory across rounds.
"""

from __future__ import annotations

import json
import time

import numpy as np


def main():
    import jax
    import hetu_tpu as ht

    # BERT-base-ish block stack scaled to fit one chip quickly:
    # hidden 768, 12 heads, 4 layers (1/3 of BERT-base depth), seq 128
    batch, seq, hidden, heads, layers_n, vocab = 32, 128, 768, 12, 4, 30522

    ids = ht.placeholder_op("input_ids")
    labels = ht.placeholder_op("labels")
    emb = ht.layers.Embedding(vocab, hidden, name="tok_emb")
    pos = ht.init.random_normal((seq, hidden), stddev=0.02, name="pos_emb")
    h = ht.embedding_lookup_op(emb.embedding_table, ids)
    h = h + ht.broadcast_shape_op(pos, (batch, seq, hidden), add_axes=[0])
    h = ht.array_reshape_op(h, [batch * seq, hidden])
    for i in range(layers_n):
        attn = ht.layers.MultiHeadAttention(hidden, heads, seq, batch,
                                            name=f"l{i}_attn")
        h = ht.layers.LayerNorm(hidden, name=f"l{i}_ln1")(h + attn(h))
        wi = ht.layers.Linear(hidden, hidden * 4, name=f"l{i}_ffn_wi")
        wo = ht.layers.Linear(hidden * 4, hidden, name=f"l{i}_ffn_wo")
        h = ht.layers.LayerNorm(hidden, name=f"l{i}_ln2")(
            h + wo(ht.gelu_op(wi(h))))
    logits = ht.layers.Linear(hidden, vocab, name="lm_head")(h)
    loss = ht.reduce_mean_op(
        ht.softmaxcrossentropy_sparse_op(
            logits, ht.array_reshape_op(labels, [batch * seq])), axes=0)
    train = ht.optim.AdamOptimizer(learning_rate=1e-4).minimize(loss)
    # bf16 compute / fp32 masters: the MXU path
    ex = ht.Executor({"train": [loss, train]}, mixed_precision="bf16")

    rng = np.random.RandomState(0)
    feed = {
        ids: rng.randint(0, vocab, (batch, seq)).astype(np.int32),
        labels: rng.randint(0, vocab, (batch, seq)).astype(np.int32),
    }

    # warmup (compile) — materialize to host: block_until_ready does not
    # reliably wait on the tunneled TPU platform in this image
    float(np.asarray(ex.run("train", feed_dict=feed)[0]))

    iters = 20
    t0 = time.perf_counter()
    for _ in range(iters):
        out = ex.run("train", feed_dict=feed)
    # the final loss depends on every prior step's params (donated chain),
    # so materializing it forces the full sequence
    float(np.asarray(out[0]))
    dt = (time.perf_counter() - t0) / iters

    n_chips = max(1, jax.device_count())
    samples_per_sec_chip = batch / dt / n_chips
    # target: BASELINE.json north star scaled to this 4-layer proxy —
    # no published reference number exists (BASELINE.md), so the target is
    # our own round-1 figure; vs_baseline tracks improvement across rounds.
    target = 100.0
    print(json.dumps({
        "metric": "bert4L_seq128_train_throughput",
        "value": round(samples_per_sec_chip, 2),
        "unit": "samples/sec/chip",
        "vs_baseline": round(samples_per_sec_chip / target, 3),
    }))


if __name__ == "__main__":
    main()
