"""Optimizer correctness vs closed-form numpy updates (reference
tests/test_optimizer.py)."""

import numpy as np
import pytest

import hetu_tpu as ht

# smoke tier: this module is part of the <3-min verification
# battery (`pytest -m smoke`; ROADMAP tier-1 note)
pytestmark = pytest.mark.smoke


def _train_quadratic(opt, steps=3):
    """loss = 0.5*sum(w^2); grad = w. Track w trajectory."""
    w0 = np.array([[1.0, -2.0], [3.0, 0.5]], np.float32)
    w = ht.Variable("w_q", value=w0.copy())
    loss = ht.mul_byconst_op(ht.reduce_sum_op(ht.mul_op(w, w), [0, 1]), 0.5)
    train = opt.minimize(loss)
    ex = ht.Executor({"train": [loss, train]})
    traj = [w0.copy()]
    for _ in range(steps):
        ex.run("train")
        traj.append(np.asarray(ex.var_values["w_q"]))
    return traj


def test_sgd():
    traj = _train_quadratic(ht.optim.SGDOptimizer(learning_rate=0.1))
    expect = traj[0]
    for t in traj[1:]:
        expect = expect - 0.1 * expect
        np.testing.assert_allclose(t, expect, rtol=1e-5)


def test_momentum():
    traj = _train_quadratic(
        ht.optim.MomentumOptimizer(learning_rate=0.1, momentum=0.9))
    w, v = traj[0], np.zeros_like(traj[0])
    for t in traj[1:]:
        v = 0.9 * v - 0.1 * w
        w = w + v
        np.testing.assert_allclose(t, w, rtol=1e-5)


def test_adagrad():
    traj = _train_quadratic(
        ht.optim.AdaGradOptimizer(learning_rate=0.1, eps=1e-7))
    w, acc = traj[0], np.zeros_like(traj[0])
    for t in traj[1:]:
        acc = acc + w * w
        w = w - 0.1 * w / (np.sqrt(acc) + 1e-7)
        np.testing.assert_allclose(t, w, rtol=1e-5)


def test_adam():
    traj = _train_quadratic(
        ht.optim.AdamOptimizer(learning_rate=0.1, beta1=0.9, beta2=0.999,
                               epsilon=1e-7))
    w = traj[0]
    m = np.zeros_like(w)
    v = np.zeros_like(w)
    for i, t in enumerate(traj[1:]):
        g = w
        m = 0.9 * m + 0.1 * g
        v = 0.999 * v + 0.001 * g * g
        mhat = m / (1 - 0.9 ** (i + 1))
        vhat = v / (1 - 0.999 ** (i + 1))
        w = w - 0.1 * mhat / (np.sqrt(vhat) + 1e-7)
        np.testing.assert_allclose(t, w, rtol=1e-4)


def test_adamw():
    traj = _train_quadratic(
        ht.optim.AdamWOptimizer(learning_rate=0.1, weight_decay=0.01))
    w = traj[0]
    m = np.zeros_like(w)
    v = np.zeros_like(w)
    for i, t in enumerate(traj[1:]):
        g = w
        m = 0.9 * m + 0.1 * g
        v = 0.999 * v + 0.001 * g * g
        mhat = m / (1 - 0.9 ** (i + 1))
        vhat = v / (1 - 0.999 ** (i + 1))
        w = w - 0.1 * (mhat / (np.sqrt(vhat) + 1e-7) + 0.01 * w)
        np.testing.assert_allclose(t, w, rtol=1e-4)


def test_lamb():
    traj = _train_quadratic(
        ht.optim.LambOptimizer(learning_rate=0.1, weight_decay=0.01))
    w = traj[0]
    m = np.zeros_like(w)
    v = np.zeros_like(w)
    for t in traj[1:]:
        g = w
        m = 0.9 * m + 0.1 * g
        v = 0.999 * v + 0.001 * g * g
        upd = m / (np.sqrt(v) + 1e-7) + 0.01 * w
        ratio = np.linalg.norm(w) / np.linalg.norm(upd)
        w = w - 0.1 * ratio * upd
        np.testing.assert_allclose(t, w, rtol=1e-4)


def test_lr_scheduler_in_optimizer():
    sched = ht.lr.StepScheduler(0.1, step_size=2, gamma=0.5)
    traj = _train_quadratic(ht.optim.SGDOptimizer(learning_rate=sched),
                            steps=4)
    w = traj[0]
    lrs = [0.1, 0.1, 0.05, 0.05]
    for lr_t, t in zip(lrs, traj[1:]):
        w = w - lr_t * w
        np.testing.assert_allclose(t, w, rtol=1e-5)


def test_checkpoint_roundtrip(tmp_path):
    opt = ht.optim.AdamOptimizer(learning_rate=0.05)
    w = ht.Variable("w_ckpt", value=np.ones((3, 3), np.float32))
    loss = ht.reduce_sum_op(ht.mul_op(w, w), [0, 1])
    train = opt.minimize(loss)
    ex = ht.Executor({"train": [loss, train]})
    ex.run("train")
    ex.run("train")
    ex.save(str(tmp_path), "ck.pkl")
    after_2 = np.asarray(ex.var_values["w_ckpt"])

    # fresh executor, load, continue — must match uninterrupted run
    w2 = ht.Variable("w_ckpt", value=np.ones((3, 3), np.float32))
    loss2 = ht.reduce_sum_op(ht.mul_op(w2, w2), [0, 1])
    train2 = ht.optim.AdamOptimizer(learning_rate=0.05).minimize(loss2)
    ex2 = ht.Executor({"train": [loss2, train2]})
    ex2.load(str(tmp_path), "ck.pkl")
    np.testing.assert_allclose(np.asarray(ex2.var_values["w_ckpt"]), after_2)
    ex.run("train")
    ex2.run("train")
    np.testing.assert_allclose(np.asarray(ex2.var_values["w_ckpt"]),
                               np.asarray(ex.var_values["w_ckpt"]), rtol=1e-6)


def test_clip_grad_norm_matches_manual():
    """opt.clip_grad_norm clips by GLOBAL norm across all params; the
    clipped step equals a hand-computed clipped SGD step, and a
    large-enough bound is a no-op."""
    import numpy as np
    import hetu_tpu as ht

    rng = np.random.RandomState(0)
    xv = rng.randn(16, 6).astype(np.float32) * 3.0
    yv = np.eye(3, dtype=np.float32)[rng.randint(0, 3, 16)]

    def run(clip):
        x = ht.placeholder_op("cg_x")
        y = ht.placeholder_op("cg_y")
        w = ht.Variable("cg_w", value=np.ones((6, 3), np.float32) * 0.5)
        b = ht.Variable("cg_b", value=np.zeros(3, np.float32))
        loss = ht.reduce_mean_op(ht.softmaxcrossentropy_op(
            ht.linear_op(x, w, b), y), axes=0)
        opt = ht.optim.SGDOptimizer(learning_rate=1.0)
        opt.clip_grad_norm = clip
        train = opt.minimize(loss)
        ex = ht.Executor({"train": [loss, train]})
        ex.run("train", feed_dict={x: xv, y: yv})
        return (np.asarray(ex.var_values["cg_w"]),
                np.asarray(ex.var_values["cg_b"]))

    w_unc, b_unc = run(None)
    w_big, b_big = run(1e6)        # bound never binds -> identical
    np.testing.assert_allclose(w_big, w_unc, rtol=1e-6)
    np.testing.assert_allclose(b_big, b_unc, rtol=1e-6)

    # manual reference: raw grad = (w0 - w_unclipped) / lr
    w0, b0 = np.ones((6, 3), np.float32) * 0.5, np.zeros(3, np.float32)
    gw, gb = (w0 - w_unc), (b0 - b_unc)
    gnorm = np.sqrt((gw ** 2).sum() + (gb ** 2).sum())
    clip = float(gnorm) / 2.0       # binds: factor = 0.5
    w_clip, b_clip = run(clip)
    factor = clip / (gnorm + 1e-6)
    np.testing.assert_allclose(w_clip, w0 - factor * gw,
                               rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(b_clip, b0 - factor * gb,
                               rtol=1e-4, atol=1e-6)


def test_clip_grad_norm_dp_equivalence():
    """Clipping composes with data parallelism: the norm is taken over
    the GLOBAL (psum'd) gradients inside the sharded step, so a dp8 run
    must track the 1-device trajectory."""
    import numpy as np
    import hetu_tpu as ht

    rng = np.random.RandomState(0)
    feeds = []
    for _ in range(6):
        xv = rng.randn(16, 6).astype(np.float32) * 3.0
        yv = np.eye(3, dtype=np.float32)[rng.randint(0, 3, 16)]
        feeds.append((xv, yv))

    def run(strategy):
        x = ht.placeholder_op("cd_x")
        y = ht.placeholder_op("cd_y")
        w = ht.Variable("cd_w", value=np.ones((6, 3), np.float32) * 0.5)
        b = ht.Variable("cd_b", value=np.zeros(3, np.float32))
        loss = ht.reduce_mean_op(ht.softmaxcrossentropy_op(
            ht.linear_op(x, w, b), y), axes=0)
        opt = ht.optim.AdamOptimizer(learning_rate=0.05)
        opt.clip_grad_norm = 0.1          # binds on these feeds
        train = opt.minimize(loss)
        ex = ht.Executor({"train": [loss, train]},
                         dist_strategy=strategy)
        return [float(np.asarray(ex.run("train",
                                        feed_dict={x: a, y: b_})[0]))
                for a, b_ in feeds]

    base = run(None)
    dp = run(ht.dist.DataParallel(num_devices=8))
    np.testing.assert_allclose(dp, base, atol=1e-5)
