"""Concurrency sanitizer (ISSUE 19): lockdep + the deterministic
interleaving fuzzer, end to end.

Three layers under test:

- **lockdep** (``hetu_tpu/locks.py``, ``HETU_LOCKDEP=1``): the
  acquisition-order graph must catch a seeded lock-order inversion and
  the held-across seams (PS RPC, multi-MB wire encode) — naming both
  lock sites and both stacks — and must be a no-op with the knob off.
- **fuzzer** (``HETU_SCHED_FUZZ`` / ``run_interleaved(seed=)``): a
  planted race must reproduce EXACTLY on the same seed, twice, and on
  the pinned CI seed — "flaky" is banned from this suite's vocabulary.
- **hammers**: the threaded core (CacheSparseTable, PrefixDirectory,
  TieredKVStore, FlightRecorder) under seeded interleavings across a
  seed sweep, invariants checked after every seed, with lockdep armed
  so any ordering bug the sweep surfaces is named, not just crashed.
"""

import threading
import time

import numpy as np
import pytest

from hetu_tpu import locks, telemetry
from hetu_tpu.analysis.concurrency import (
    LockdepError, assert_lockdep_clean, run_interleaved)
from hetu_tpu.cache.cstable import CacheSparseTable
from hetu_tpu.ps import wire
from hetu_tpu.ps.server import PSServer
from hetu_tpu.ps.sharded import ShardedPSClient
from hetu_tpu.serving.kv_tiers import TieredKVStore
from hetu_tpu.serving.prefix_directory import PrefixDirectory
from hetu_tpu.telemetry.events import validate_record
from hetu_tpu.telemetry.flight import FlightRecorder
from hetu_tpu.telemetry.trace import check_lockdep

pytestmark = pytest.mark.smoke

W = 4
VOCAB = 64
# the pinned CI seed: seed 3 loses 19 of 30 increments in the planted
# race below, reproducibly (see test_fuzzer_reproduces_planted_race)
CI_SEED = 3


@pytest.fixture(autouse=True)
def _clean_lockdep():
    locks.lockdep_reset()
    yield
    locks.lockdep_reset()


# ------------------------------------------------------------------ #
# lockdep
# ------------------------------------------------------------------ #

def test_lockdep_detects_order_inversion(monkeypatch):
    monkeypatch.setenv("HETU_LOCKDEP", "1")
    a = locks.TracedLock("test.A")
    b = locks.TracedLock("test.B")
    with a:
        with b:
            pass
    assert locks.lockdep_violations() == []   # one order is fine
    with b:
        with a:                               # the inversion
            pass
    vs = locks.lockdep_violations()
    assert len(vs) == 1 and vs[0]["kind"] == "order"
    report = locks.format_violation(vs[0])
    # the diagnostic names BOTH locks and carries BOTH acquisition
    # stacks (each pointing into this test file)
    assert "test.A" in report and "test.B" in report
    assert report.count("test_concurrency.py") >= 2
    with pytest.raises(LockdepError) as ei:
        assert_lockdep_clean("inversion test")
    assert "test.A" in str(ei.value)


def test_lockdep_duplicate_inversions_dedupe(monkeypatch):
    monkeypatch.setenv("HETU_LOCKDEP", "1")
    a = locks.TracedLock("test.A")
    b = locks.TracedLock("test.B")
    for _ in range(5):
        with a:
            with b:
                pass
        with b:
            with a:
                pass
    assert len(locks.lockdep_violations()) == 1


def test_lockdep_held_across_rpc_seam(monkeypatch):
    """The instrumented PS-RPC seam: blocking while holding any traced
    lock is reported with the held lock's name and acquisition
    stack."""
    monkeypatch.setenv("HETU_LOCKDEP", "1")
    mu = locks.TracedLock("test.holder")
    locks.note_blocking("ps_rpc", method="pull")
    assert locks.lockdep_violations() == []   # not held: fine
    with mu:
        locks.note_blocking("ps_rpc", method="pull")
    vs = locks.lockdep_violations()
    assert len(vs) == 1 and vs[0]["kind"] == "held_across"
    report = locks.format_violation(vs[0])
    assert "test.holder" in report and "ps_rpc" in report
    assert "test_concurrency.py" in report


def test_lockdep_wire_dumps_seam(monkeypatch):
    """wire.dumps of a multi-MB payload under a held lock is the other
    blocking seam (the join/copy is real wall time in someone's
    critical section)."""
    monkeypatch.setenv("HETU_LOCKDEP", "1")
    big = np.zeros(1 << 19, np.float32)       # 2 MiB
    wire.dumps(big)                           # unheld: fine
    assert locks.lockdep_violations() == []
    mu = locks.TracedLock("test.wire_holder")
    with mu:
        wire.dumps(big)
    vs = locks.lockdep_violations()
    assert len(vs) == 1 and vs[0]["kind"] == "held_across"
    assert "wire_dumps" in locks.format_violation(vs[0])
    # small payloads never trip it, held or not
    locks.lockdep_reset()
    with mu:
        wire.dumps(np.zeros(16, np.float32))
    assert locks.lockdep_violations() == []


def test_lockdep_event_contract_and_trace_rule(monkeypatch):
    """The emitted record is contract-valid and hetu_trace --check's
    lockdep rule flags it."""
    monkeypatch.setenv("HETU_LOCKDEP", "1")
    a = locks.TracedLock("test.ev_A")
    b = locks.TracedLock("test.ev_B")
    with a:
        with b:
            pass
    with b:
        with a:
            pass
    (v,) = locks.lockdep_violations()
    rec = {"t": 0.0, "event": "lockdep_violation",
           "kind": v["kind"], "lock": v["lock"], "other": v["other"],
           "site": v["site"], "msg": v["msg"]}
    assert validate_record(rec) == []
    problems = check_lockdep([rec])
    assert len(problems) == 1
    assert "test.ev_A" in problems[0] or "test.ev_B" in problems[0]
    assert check_lockdep([{"t": 0.0, "event": "serve_step"}]) == []


def test_lockdep_long_hold(monkeypatch):
    monkeypatch.setenv("HETU_LOCKDEP", "1")
    monkeypatch.setenv("HETU_LOCKDEP_HOLD_MS", "1")
    mu = locks.TracedLock("test.long_holder")
    with mu:
        time.sleep(0.02)
    vs = locks.lockdep_violations()
    assert len(vs) == 1 and vs[0]["kind"] == "long_hold"
    assert "test.long_holder" in locks.format_violation(vs[0])


def test_lockdep_hold_histogram(monkeypatch):
    monkeypatch.setenv("HETU_LOCKDEP", "1")
    monkeypatch.setenv("HETU_TELEMETRY", "1")
    telemetry.reset()
    mu = locks.TracedLock("test.hist_lock")
    for _ in range(3):
        with mu:
            pass
    hists = telemetry.snapshot()["histograms"]
    h = hists.get("lock.hold_ms.test.hist_lock")
    assert h is not None and h["count"] == 3


def test_lockdep_rlock_reentrancy(monkeypatch):
    monkeypatch.setenv("HETU_LOCKDEP", "1")
    mu = locks.TracedRLock("test.re")
    other = locks.TracedLock("test.re_other")
    with mu:
        with mu:          # re-entry: no self-edge, no violation
            with other:
                pass
    assert locks.lockdep_violations() == []
    assert ("test.re", "test.re_other") in locks.lockdep_edges()


def test_lockdep_off_is_inert():
    """Knob off (the default): no graph, no violations, and the
    wrapper stays cheap enough for hot paths."""
    a = locks.TracedLock("test.off_A")
    b = locks.TracedLock("test.off_B")
    with a:
        with b:
            pass
    with b:
        with a:
            pass
    assert locks.lockdep_violations() == []
    assert locks.lockdep_edges() == {}
    n = 20000
    t0 = time.perf_counter()
    for _ in range(n):
        with a:
            pass
    traced = time.perf_counter() - t0
    # generous absolute bound: ~100x headroom over observed cost, but
    # catches the class of regression where the off-path starts doing
    # per-acquire graph work (which measures in ms, not us)
    assert traced < 0.075 * n / 1000 + 0.5, \
        f"TracedLock off-path cost {traced / n * 1e6:.2f}us/acquire"


# ------------------------------------------------------------------ #
# deterministic interleaving fuzzer
# ------------------------------------------------------------------ #

def _racy_counter(seed):
    """Three workers x 10 unprotected read-modify-write increments
    with the fuzzer's preemption point inside the window."""
    state = {"n": 0}

    def worker():
        for _ in range(10):
            v = state["n"]
            locks.sched_point()
            state["n"] = v + 1

    run_interleaved(worker, worker, worker, seed=seed)
    return state["n"]


def _locked_counter(seed):
    state = {"n": 0}
    mu = locks.TracedLock("test.counter")

    def worker():
        for _ in range(10):
            with mu:
                v = state["n"]
                locks.sched_point()
                state["n"] = v + 1

    run_interleaved(worker, worker, worker, seed=seed)
    return state["n"]


def test_fuzzer_reproduces_planted_race():
    """The acceptance criterion itself: the planted lost-update race
    reproduces on the same seed twice and on the pinned CI seed, and
    the TracedLock'd variant is exact on every seed."""
    for seed in range(6):
        first, second = _racy_counter(seed), _racy_counter(seed)
        assert first == second, f"seed {seed} not reproducible"
        assert _locked_counter(seed) == 30
    # the pinned CI seed demonstrably loses updates (30 would mean the
    # schedule happened to serialize — seed 3 does not)
    assert _racy_counter(CI_SEED) == 11


def test_fuzzer_seeds_differ():
    """Different seeds explore different interleavings (else the sweep
    is one schedule run N times)."""
    assert len({_racy_counter(s) for s in range(8)}) >= 2


def test_fuzzer_env_knob(monkeypatch):
    """HETU_SCHED_FUZZ=<seed> arms run_interleaved without code
    changes; unset means free OS threads."""
    monkeypatch.setenv("HETU_SCHED_FUZZ", str(CI_SEED))
    assert _racy_counter(None) == 11
    monkeypatch.delenv("HETU_SCHED_FUZZ")
    state = {"n": 0}
    mu = threading.Lock()

    def worker():
        for _ in range(10):
            with mu:
                state["n"] += 1

    run_interleaved(worker, worker, seed=None)
    assert state["n"] == 20
    assert locks.current_scheduler() is None


def test_fuzzer_reraises_thunk_error():
    def boom():
        raise ValueError("planted")

    with pytest.raises(ValueError, match="planted"):
        run_interleaved(boom, lambda: None, seed=0)


def test_planted_cstable_race_reproduces(monkeypatch):
    """Re-introduce the bug class the cstable lock prevents — a public
    method doing a counter read-modify-write OUTSIDE the lock — and
    pin it: same seed -> same (wrong) count, twice; guarded variant ->
    exact on every seed.  comm=None keeps the real update path from
    touching the planted counter."""
    real_update = CacheSparseTable.embedding_update

    def planted(self, ids, deltas, assume_unique=False):
        n = self.num_pushed_rows
        locks.sched_point()                  # the preemption window
        self.num_pushed_rows = n + len(ids)
        real_update(self, ids, deltas, assume_unique)

    def guarded(self, ids, deltas, assume_unique=False):
        with self._lock:
            n = self.num_pushed_rows
            locks.sched_point()
            self.num_pushed_rows = n + len(ids)
        real_update(self, ids, deltas, assume_unique)

    def hammer(seed):
        t = CacheSparseTable(limit=16, vocab_size=VOCAB, width=W,
                             key="emb", comm=None)

        def worker():
            for _ in range(5):
                t.embedding_update([1, 2], np.zeros((2, W), np.float32))

        run_interleaved(worker, worker, worker, seed=seed)
        return t.num_pushed_rows

    monkeypatch.setattr(CacheSparseTable, "embedding_update", planted)
    runs = [(hammer(s), hammer(s)) for s in (0, 1, CI_SEED)]
    assert all(a == b for a, b in runs), runs   # seed-exact, wrong ok
    assert runs[2][0] < 30, "CI seed failed to surface the plant"
    monkeypatch.setattr(CacheSparseTable, "embedding_update", guarded)
    assert all(hammer(s) == 30 for s in (0, 1, CI_SEED))


# ------------------------------------------------------------------ #
# seeded hammers over the threaded core (lockdep armed throughout)
# ------------------------------------------------------------------ #

class _YieldingComm:
    """PS comm that hands the scheduler token away inside every RPC —
    preemption lands mid-transaction, where the bugs live."""

    def __init__(self, server):
        self._server = server

    def __getattr__(self, name):
        fn = getattr(self._server, name)

        def wrapper(*a, **kw):
            locks.sched_point()
            return fn(*a, **kw)
        return wrapper


def test_cstable_hammer_seed_sweep(monkeypatch):
    monkeypatch.setenv("HETU_LOCKDEP", "1")
    for seed in range(4):
        server = PSServer()
        server.param_init("emb", (VOCAB, W), "normal", 0.0, 1.0, seed=3)
        t = CacheSparseTable(limit=32, vocab_size=VOCAB, width=W,
                             key="emb", comm=_YieldingComm(server),
                             policy="LRU", push_bound=0)
        rngs = [np.random.RandomState(100 * seed + i) for i in range(2)]

        def lookups(rng=rngs[0]):
            for _ in range(6):
                rows = t.embedding_lookup(rng.randint(0, VOCAB, 8))
                assert rows.shape == (8, W)

        def updates(rng=rngs[1]):
            for _ in range(6):
                ids = rng.randint(0, VOCAB, 4)
                t.embedding_update(
                    ids, rng.randn(4, W).astype(np.float32) * .01)

        run_interleaved(lookups, updates, seed=seed)
        t.flush()
        # every delta landed exactly once: cache == PS row for row
        ids = np.arange(VOCAB)
        np.testing.assert_allclose(t.embedding_lookup(ids),
                                   server.sparse_pull("emb", ids),
                                   rtol=1e-4, atol=1e-5)
    assert_lockdep_clean("cstable hammer")


def test_prefix_directory_hammer_seed_sweep(monkeypatch):
    """register/evict/drop_replica vs lookup: pre-lock, lookup's dict
    comprehension over e.replicas raced the register callbacks
    (RuntimeError: dict changed size during iteration)."""
    monkeypatch.setenv("HETU_LOCKDEP", "1")
    prefixes = [list(range(8 * (i + 1))) for i in range(4)]
    for seed in range(4):
        d = PrefixDirectory(ttl=0)
        d._block = 8
        registers = 12

        def churn():
            for i in range(registers):
                p = prefixes[i % len(prefixes)]
                d.register(f"r{i % 2}", p)
                locks.sched_point()
                if i % 3 == 2:
                    d.evict(f"r{i % 2}", p)

        def reaper():
            for i in range(6):
                locks.sched_point()
                d.drop_replica(f"r{i % 2}")

        def prober():
            for _ in range(10):
                hint, outcome = d.lookup(list(range(17)))
                assert outcome in (None, "miss", "stale", "tier")
                locks.sched_point()
                assert d.snapshot()["entries"] >= 0

        run_interleaved(churn, reaper, prober, seed=seed)
        assert d.snapshot()["registrations"] == registers
        d.drop_replica("r0")
        d.drop_replica("r1")
        assert d.snapshot()["entries"] == 0
    assert_lockdep_clean("prefix directory hammer")


def _payload(n, nbytes=64):
    return {"nbytes": nbytes, "length": 8, "blob": b"x" * nbytes,
            "tag": n}


def test_kv_tiers_hammer_seed_sweep(monkeypatch):
    """spill/fetch/demote vs a mid-hammer PS kill: the residency
    ledger must balance after close on EVERY seed (each spill ends in
    exactly one fetch or drop), with zero host-ring residue."""
    monkeypatch.setenv("HETU_LOCKDEP", "1")
    prefixes = [tuple(range(8 * (i + 1))) for i in range(4)]
    for seed in range(4):
        store = TieredKVStore(
            host_bytes=160, ps_tier=True,    # ~2 entries: forces
            ps=ShardedPSClient(servers=[PSServer(), PSServer()]))
        store.block = 8                      # demotes to the PS rung

        def spiller():
            for i in range(10):
                store.spill(prefixes[i % len(prefixes)], _payload(i))
                locks.sched_point()

        def fetcher():
            for i in range(10):
                locks.sched_point()
                hit = store.lookup(list(prefixes[-1]) + [99])
                if hit is not None:
                    store.fetch(hit[0])
                store.stats()

        def killer():
            for _ in range(3):
                locks.sched_point()
            store.kill_ps("hammer chaos")

        run_interleaved(spiller, fetcher, killer, seed=seed)
        store.close("hammer done")
        st = store.stats()
        assert st["ps_dead"] is True
        assert sum(st["spills"].values()) == \
            sum(st["fetches"].values()) + sum(st["drops"].values()), st
        assert st["host_entries"] == 0 and st["host_used_bytes"] == 0
        assert st["ps_entries"] == 0
    assert_lockdep_clean("kv tiers hammer")


def test_flight_ring_hammer_seed_sweep(monkeypatch):
    """The PR's thread-safety fix: record() vs recent()/dump() used to
    be a lock-free deque append racing list(deque) — RuntimeError at
    exactly the moment a dying process snapshots its black box."""
    monkeypatch.setenv("HETU_FLIGHT_DEPTH", "32")
    for seed in range(4):
        rec = FlightRecorder(depth=32)

        def writer():
            for i in range(20):
                rec.record({"t": 0.0, "event": "span", "i": i})
                locks.sched_point()

        def snapshotter():
            for _ in range(15):
                got = rec.recent()
                assert all(r["event"] == "span" for r in got)
                locks.sched_point()

        run_interleaved(writer, writer, snapshotter, seed=seed)
        assert len(rec.recent()) == 32       # ring full, intact
