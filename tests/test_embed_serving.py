"""Embedding serving engine (ISSUE 14 tentpole): the model-agnostic
serving substrate's second workload.

The acceptance spine: EmbedServingEngine scores (user, item, dense)
requests through the HET cache + one jitted dense-tower wave and its
scores match a pure-numpy oracle forward for all three towers
(wdl/dcn/ncf); a zipf-skewed trace against a capacity-limited cache
clears a hit-rate floor; the fleet router hosts embedding replicas and
sheds throughput-class traffic first; a mid-trace PS kill loses ZERO
requests (stale/zero degradation, replay on recovery); and the serve
stream stays span- AND gather-balanced.  Around it: the regression that
matters most — the GPT engine + router are token-identical to offline
``generate_fast`` across paged/int8/spec configs AFTER the
model-agnostic refactor.

All CPU-harness, all smoke-tier (tiny random-weight towers — the
contract is scheduling, caching and degradation, not model quality).
"""

import numpy as np
import pytest

import hetu_tpu as ht  # noqa: F401  (platform forcing + compat shims)
from hetu_tpu import telemetry
from hetu_tpu.cache.cstable import CacheSparseTable
from hetu_tpu.models import GPTConfig
from hetu_tpu.models.gpt_decode import generate_fast
from hetu_tpu.ps.client import PSConnectionError
from hetu_tpu.ps.server import PSServer
from hetu_tpu.serving import (
    EmbedRequest, EmbedServingEngine, QueueFull, Request, RouterShed,
    ServingEngine, ServingRouter, SLO,
)
from hetu_tpu.telemetry import top
from hetu_tpu.telemetry.trace import (check_gather_balance,
                                      check_span_balance, read_events)

pytestmark = pytest.mark.smoke

E = 4          # embedding width of the CTR tables under test
NCF_W = 8      # user/item latent width (embed_dim=4 GMF + 4 MLP)
VOCAB = 64


@pytest.fixture(autouse=True)
def _fresh(monkeypatch):
    monkeypatch.setenv("HETU_TELEMETRY", "1")
    telemetry.reset()
    yield
    telemetry.reset()


def _sigmoid(x):
    return 1.0 / (1.0 + np.exp(-x))


def _server(tables):
    """PSServer with one embedding matrix per (key, vocab, width)."""
    server = PSServer()
    for key, vocab, width in tables:
        server.param_init(key, (vocab, width), "normal", 0.0, 1.0,
                          seed=3)
    return server


def _table(server, key, vocab=VOCAB, width=E, limit=256, **kw):
    return CacheSparseTable(limit=limit, vocab_size=vocab, width=width,
                            key=key, comm=server, policy="LRU", **kw)


def _rng(seed=0):
    return np.random.RandomState(seed)


# --------------------------------------------------------------------- #
# tower params + numpy oracles (the engine's jax towers must match)
# --------------------------------------------------------------------- #

def _wdl_params(rng, h=8):
    return {"W1": rng.randn(13, h) * 0.3, "W2": rng.randn(h, h) * 0.3,
            "W3": rng.randn(h, h) * 0.3,
            "W4": rng.randn(26 * E + h, 1) * 0.3}


def _dcn_params(rng, h=8):
    D = 26 * E + 13
    p = {"W1": rng.randn(D, h) * 0.1, "W2": rng.randn(h, h) * 0.1,
         "W3": rng.randn(h, h) * 0.1, "W4": rng.randn(D + h, 1) * 0.1}
    for i in range(3):
        p[f"cross{i}_weight"] = rng.randn(D, 1) * 0.1
        p[f"cross{i}_bias"] = rng.randn(D) * 0.1
    return p


def _ncf_params(rng, h=8):
    # embed_dim=4 GMF factors; MLP input = 2 * (NCF_W - 4) = 8
    return {"W1": rng.randn(8, h) * 0.3, "W2": rng.randn(h, h) * 0.3,
            "W3": rng.randn(h, h) * 0.3, "W4": rng.randn(4 + h, 1) * 0.3}


def _np_tower(x, p):
    y = np.maximum(x @ p["W1"], 0.0)
    y = np.maximum(y @ p["W2"], 0.0)
    return y @ p["W3"]


def _np_wdl(p, emb_flat, dense):
    y3 = _np_tower(dense, p)
    return _sigmoid(np.concatenate([emb_flat, y3], axis=1)
                    @ p["W4"])[:, 0]


def _np_dcn(p, emb_flat, dense):
    x = np.concatenate([emb_flat, dense], axis=1)
    cross = x
    for i in range(3):
        cross = x * (cross @ p[f"cross{i}_weight"]) + cross \
            + p[f"cross{i}_bias"]
    y3 = _np_tower(x, p)
    return _sigmoid(np.concatenate([cross, y3], axis=1) @ p["W4"])[:, 0]


def _np_ncf(p, u_lat, i_lat, ed=4):
    gmf = u_lat[:, :ed] * i_lat[:, :ed]
    x = np.concatenate([u_lat[:, ed:], i_lat[:, ed:]], axis=1)
    for i in range(1, 4):
        x = np.maximum(x @ p[f"W{i}"], 0.0)
    return _sigmoid(np.concatenate([gmf, x], axis=1) @ p["W4"])[:, 0]


def _f32(params):
    return {k: np.asarray(v, np.float32) for k, v in params.items()}


def _ctr_requests(rng, n, pairs=(1, 4), vocab=VOCAB, cls=None):
    out = []
    for i in range(n):
        np_ = int(rng.randint(pairs[0], pairs[1] + 1))
        out.append(EmbedRequest(
            item_ids=rng.randint(0, vocab, (np_, 26)),
            dense_features=rng.randn(np_, 13).astype(np.float32),
            slo_class=cls or "throughput"))
    return out


def _mk_ctr_engine(model="wdl", seed=0, **kw):
    server = _server([("snd_order_embedding", VOCAB, E)])
    table = _table(server, "snd_order_embedding")
    params = _f32((_wdl_params if model == "wdl"
                   else _dcn_params)(_rng(seed)))
    eng = EmbedServingEngine(params,
                             {"snd_order_embedding": table},
                             model=model, **kw)
    return eng, server, params


# --------------------------------------------------------------------- #
# tower parity vs the numpy oracle
# --------------------------------------------------------------------- #

class TestOracleParity:
    @pytest.mark.parametrize("model", ["wdl", "dcn"])
    def test_ctr_engine_matches_numpy(self, model):
        """Engine scores (cache gather + jitted padded wave) equal the
        oracle forward over exact PS rows, across ragged wave sizes."""
        eng, server, params = _mk_ctr_engine(model, wave=3)
        rng = _rng(7)
        reqs = _ctr_requests(rng, 7)
        res = eng.run(reqs)
        assert len(res) == 7
        oracle = _np_wdl if model == "wdl" else _np_dcn
        for r in reqs:
            emb = np.asarray(
                server.sparse_pull("snd_order_embedding",
                                   r.item_ids.reshape(-1)),
                np.float32).reshape(r.n_pairs, 26 * E)
            want = oracle(params, emb, r.dense_features)
            got = res[r.request_id]
            assert got.finish_reason == "scored"
            assert got.scores.shape == (r.n_pairs,)
            np.testing.assert_allclose(got.scores, want,
                                       rtol=1e-4, atol=1e-6)

    def test_ncf_engine_matches_numpy(self):
        server = _server([("user_embed", VOCAB, NCF_W),
                          ("item_embed", VOCAB, NCF_W)])
        tables = {"user_embed": _table(server, "user_embed",
                                       width=NCF_W),
                  "item_embed": _table(server, "item_embed",
                                       width=NCF_W)}
        params = _f32(_ncf_params(_rng(5)))
        eng = EmbedServingEngine(params, tables, model="ncf",
                                 embed_dim=4, mlp_layers=(8, 8, 8, 8),
                                 wave=4)
        rng = _rng(11)
        reqs = [EmbedRequest(user_ids=rng.randint(0, VOCAB, n),
                             item_ids=rng.randint(0, VOCAB, n))
                for n in (1, 3, 2, 4, 1)]
        res = eng.run(reqs)
        for r in reqs:
            u = np.asarray(server.sparse_pull("user_embed", r.user_ids),
                           np.float32)
            it = np.asarray(server.sparse_pull("item_embed", r.item_ids),
                            np.float32)
            np.testing.assert_allclose(res[r.request_id].scores,
                                       _np_ncf(params, u, it),
                                       rtol=1e-4, atol=1e-6)

    def test_results_identical_across_wave_sizes(self):
        """Bucket padding + wave batching never change a score: the
        same trace through wave=1 and wave=8 engines agrees exactly."""
        rng = _rng(3)
        ids = rng.randint(0, VOCAB, (6, 2, 26))
        dense = rng.randn(6, 2, 13).astype(np.float32)
        outs = []
        for wave in (1, 8):
            eng, _, _ = _mk_ctr_engine("wdl", wave=wave)
            reqs = [EmbedRequest(item_ids=ids[i], dense_features=dense[i])
                    for i in range(6)]
            res = eng.run(reqs)
            outs.append(np.concatenate(
                [res[r.request_id].scores for r in reqs]))
        np.testing.assert_array_equal(outs[0], outs[1])


# --------------------------------------------------------------------- #
# cache behavior under load
# --------------------------------------------------------------------- #

class TestCacheBehavior:
    def test_zipf_hit_rate_floor(self):
        """The bench regime in miniature: zipf(1.05) ids against a
        cache holding 25% of the vocabulary keep the hit rate above a
        floor — the HET cache thesis applied to serving."""
        vocab = 256
        server = _server([("snd_order_embedding", vocab, E)])
        table = _table(server, "snd_order_embedding", vocab=vocab,
                       limit=128)
        eng = EmbedServingEngine(
            _f32(_wdl_params(_rng(0))),
            {"snd_order_embedding": table}, model="wdl", wave=8,
            queue_limit=256)
        rng = _rng(42)
        raw = rng.zipf(1.05, size=(96, 2, 26))
        reqs = [EmbedRequest(item_ids=((raw[i] - 1) % vocab))
                for i in range(96)]
        res = eng.run(reqs)
        assert len(res) == 96
        s = table.perf_summary()
        assert s["hit_rate"] >= 0.3
        assert s["pull_bytes"] > 0
        # per-result + snapshot surfacing of the same signal
        assert eng.metrics.snapshot()["cache_hit_rate_mean"] >= 0.3
        assert any(r.cache_hit_rate > 0.3 for r in res.values())
        assert "snd_order_embedding" in eng.cache_summary()

    def test_queue_full_backpressure(self):
        eng, _, _ = _mk_ctr_engine("wdl", wave=2, queue_limit=2)
        rng = _rng(1)
        for r in _ctr_requests(rng, 2):
            eng.submit(r)
        with pytest.raises(QueueFull):
            eng.submit(_ctr_requests(rng, 1)[0])
        assert eng.metrics.rejected == 1
        eng.run()
        assert eng.pending == 0


# --------------------------------------------------------------------- #
# PS outage: zero request loss (the chaos spine)
# --------------------------------------------------------------------- #

class _FlakyPS:
    """PSServer wrapper whose every verb raises while ``down`` — the
    serving-side twin of tests/test_faults.py's comm failure rig."""

    def __init__(self, server):
        self._server = server
        self.down = False

    def __getattr__(self, name):
        fn = getattr(self._server, name)

        def wrapper(*a, **kw):
            if self.down:
                raise PSConnectionError("PS down (test)")
            return fn(*a, **kw)
        return wrapper


class TestPSOutage:
    def test_ps_kill_zero_request_loss(self, tmp_path):
        """Mid-trace PS kill: warm requests serve stale, cold requests
        serve zeros, NOTHING is lost, and recovery resumes pulls — the
        training degradation protocol doing serving duty."""
        log = str(tmp_path / "serve.jsonl")
        server = _server([("snd_order_embedding", VOCAB, E)])
        flaky = _FlakyPS(server)
        table = CacheSparseTable(limit=64, vocab_size=VOCAB, width=E,
                                 key="snd_order_embedding", comm=flaky,
                                 policy="LRU")
        eng = EmbedServingEngine(
            _f32(_wdl_params(_rng(0))),
            {"snd_order_embedding": table}, model="wdl", wave=2,
            log_path=log)
        rng = _rng(9)
        warm = [EmbedRequest(item_ids=rng.randint(0, 32, (2, 26)))
                for _ in range(4)]
        res = eng.run(warm)

        flaky.down = True           # ---- the kill ----
        hot = [EmbedRequest(item_ids=rng.randint(0, 32, (2, 26)))
               for _ in range(2)]   # ids seen above -> stale hits
        cold = [EmbedRequest(item_ids=rng.randint(32, VOCAB, (2, 26)))
                for _ in range(2)]  # never cached -> zero vectors
        res.update(eng.run(hot + cold))

        flaky.down = False          # ---- recovery ----
        again = [EmbedRequest(item_ids=c.item_ids) for c in cold]
        res.update(eng.run(again))

        all_reqs = warm + hot + cold + again
        assert len(res) == len(all_reqs)          # ZERO loss
        for r in all_reqs:
            assert res[r.request_id].finish_reason == "scored"
        s = table.perf_summary()
        assert s["ps_failures"] > 0
        assert s["stale_served_rows"] > 0
        assert s["zero_served_rows"] > 0
        # cold scores during the outage came from zero embeddings;
        # after recovery the same ids score through real rows
        for c, a in zip(cold, again):
            assert not np.array_equal(res[c.request_id].scores,
                                      res[a.request_id].scores)
        # the serve stream stayed contract-clean through the chaos
        events, _ = read_events([log])
        assert check_span_balance(events) == []
        assert check_gather_balance(events) == []

    def test_outage_past_budget_surfaces(self, monkeypatch):
        """Degradation is BOUNDED: past HETU_CACHE_MAX_STALE failed
        RPCs the outage escapes (and the engine dumps its black box)."""
        monkeypatch.setenv("HETU_CACHE_MAX_STALE", "1")
        server = _server([("snd_order_embedding", VOCAB, E)])
        flaky = _FlakyPS(server)
        table = CacheSparseTable(limit=16, vocab_size=VOCAB, width=E,
                                 key="snd_order_embedding", comm=flaky)
        eng = EmbedServingEngine(
            _f32(_wdl_params(_rng(0))),
            {"snd_order_embedding": table}, model="wdl", wave=1)
        flaky.down = True
        rng = _rng(2)
        with pytest.raises(ConnectionError):
            eng.run(_ctr_requests(rng, 3))


# --------------------------------------------------------------------- #
# fleet: embedding replicas behind the router
# --------------------------------------------------------------------- #

def _embed_factory(seed=0, **kw):
    params = _f32(_wdl_params(_rng(seed)))
    server = _server([("snd_order_embedding", VOCAB, E)])

    def factory(i):
        return EmbedServingEngine(
            params, {"snd_order_embedding": _table(
                server, "snd_order_embedding")},
            model="wdl", **kw)
    return factory


class TestEmbedFleet:
    def test_router_hosts_embed_replicas(self):
        router = ServingRouter(_embed_factory(wave=2, queue_limit=16),
                               replicas=2)
        rng = _rng(4)
        reqs = _ctr_requests(rng, 8)
        res = router.run(reqs)
        assert len(res) == 8
        for r in reqs:
            assert res[r.request_id].finish_reason == "scored"
        snap = router.snapshot()
        assert snap["finished"] == 8 and snap["lost"] == 0

    def test_throughput_sheds_first(self):
        """The GPT shed ordering holds verbatim for the embedding
        workload: throughput-class waves are shed under pressure while
        latency-class requests all admit and finish."""
        factory = _embed_factory(wave=1, queue_limit=2,
                                 slo=[SLO("ttft", "latency", 60000.0)])
        router = ServingRouter(factory, replicas=2, shed_queue=0.5)
        rng = _rng(6)
        lat, shed, res = [], 0, {}
        for i in range(16):
            cls = "latency" if i % 4 == 0 else "throughput"
            req = _ctr_requests(rng, 1, cls=cls)[0]
            try:
                router.submit(req)
                if cls == "latency":
                    lat.append(req)
            except RouterShed:
                shed += 1
                assert cls == "throughput"   # sheds throughput FIRST
            except QueueFull:
                # embed waves retire synchronously: keep what the
                # backpressure step scores
                for out in router.step():
                    res[out.request_id] = out
        res.update(router.run())
        snap = router.snapshot()
        assert shed > 0 and snap["shed"] == shed
        assert snap["classes"]["latency"]["shed"] == 0
        assert snap["classes"]["throughput"]["shed"] == shed
        for r in lat:
            assert r.request_id in res
        assert snap["classes"]["latency"]["finished"] == len(lat)


# --------------------------------------------------------------------- #
# telemetry: the embed stream speaks the fleet vocabulary
# --------------------------------------------------------------------- #

class TestEmbedTelemetry:
    def test_stream_balanced_and_workload_tagged(self, tmp_path):
        log = str(tmp_path / "serve.jsonl")
        eng, _, _ = _mk_ctr_engine("wdl", wave=2, log_path=log)
        eng.run(_ctr_requests(_rng(8), 5))
        events, bad = read_events([log])
        assert not bad
        assert check_span_balance(events) == []
        assert check_gather_balance(events) == []
        kinds = {e["event"] for e in events}
        assert {"serve_submit", "serve_gather", "serve_admit",
                "serve_step", "serve_finish", "req_span",
                "req_retire"} <= kinds
        # every retire carries the gather/forward breakdown
        for e in events:
            if e["event"] == "req_retire":
                assert "gather_ms" in e and "forward_ms" in e
        stats = top.summarize(events, window=0)
        assert stats["workload"] == "embed"
        frame = top.render(stats, clock=0.0)
        assert "workload embed" in frame

    def test_snapshot_explains_the_wave(self):
        eng, _, _ = _mk_ctr_engine("wdl", wave=4)
        eng.run(_ctr_requests(_rng(12), 8))
        snap = eng.metrics.snapshot()
        assert snap["requests_finished"] == 8
        assert snap["requests_rejected"] == 0
        assert snap["pairs_per_sec"] > 0
        assert snap["gather_ms_p50"] is not None
        assert "gather_ms" in snap["components"]
        tail = eng.metrics.explain_tail()
        assert tail is not None
        assert eng.health() in ("ok", "degraded", "breach")


# --------------------------------------------------------------------- #
# the refactor regression: GPT serving is token-identical to offline
# across paged / int8-KV / speculative configs
# --------------------------------------------------------------------- #

def _rand_gpt(name="em", L=2, H=2, Dh=8, V=61, S=32, seed=0):
    rng = np.random.RandomState(seed)
    hd = H * Dh
    p = {f"{name}_wte_table": rng.randn(V, hd) * 0.05,
         f"{name}_wpe": rng.randn(S, hd) * 0.05,
         f"{name}_ln_f_scale": np.ones(hd),
         f"{name}_ln_f_bias": np.zeros(hd)}
    for i in range(L):
        us = f"{name}_h{i}"
        for w, shp in [("attn_q", (hd, hd)), ("attn_k", (hd, hd)),
                       ("attn_v", (hd, hd)), ("attn_proj", (hd, hd)),
                       ("ffn_wi", (hd, 4 * hd)), ("ffn_wo", (4 * hd, hd))]:
            p[f"{us}_{w}_weight"] = rng.randn(*shp) * 0.05
            p[f"{us}_{w}_bias"] = np.zeros(shp[1])
        for ln in ("ln1", "ln2"):
            p[f"{us}_{ln}_scale"] = np.ones(hd)
            p[f"{us}_{ln}_bias"] = np.zeros(hd)
    cfg = GPTConfig(vocab_size=V, hidden_size=hd, num_hidden_layers=L,
                    num_attention_heads=H, max_position_embeddings=S,
                    batch_size=1, seq_len=S, dropout_rate=0.0)
    return p, cfg


@pytest.fixture(scope="module")
def gpt_model():
    return _rand_gpt()


class TestGPTByteIdentity:
    @pytest.mark.parametrize("kw", [
        dict(),
        dict(paged=True, kv_block=4),
        dict(kv_quant="int8"),
        dict(spec=3, spec_adapt=False, spec_draft_layers=1),
    ], ids=["contiguous", "paged", "int8", "spec"])
    def test_router_matches_offline(self, gpt_model, kw):
        """Every token the refactored substrate serves equals offline
        ``generate_fast`` — per config, through the fleet router."""
        p, cfg = gpt_model
        factory = lambda i: ServingEngine(   # noqa: E731
            p, cfg, slots=2, queue_limit=16, fast_path=False, **kw)
        router = ServingRouter(factory, replicas=2)
        rng = np.random.RandomState(17)
        reqs = [Request(prompt=[int(t) for t in
                                rng.randint(0, 61, rng.randint(1, 5))],
                        max_new_tokens=int(rng.randint(3, 7)))
                for _ in range(4)]
        res = router.run(reqs)
        for r in reqs:
            want = generate_fast(p, cfg, [r.prompt],
                                 num_tokens=r.max_new_tokens)[0]
            assert res[r.request_id].tokens.tolist() == want.tolist()
        assert router.snapshot()["lost"] == 0

    def test_mixed_request_types_rejected_cleanly(self, gpt_model):
        """Workload mismatch is a TypeError at submit, not a corrupted
        wave: the GPT engine refuses EmbedRequests and vice versa."""
        p, cfg = gpt_model
        eng = ServingEngine(p, cfg, slots=1, fast_path=False)
        with pytest.raises((TypeError, AttributeError)):
            eng.submit(EmbedRequest(
                item_ids=np.zeros((1, 26), np.int64)))
        emb, _, _ = _mk_ctr_engine("wdl")
        with pytest.raises(TypeError):
            emb.submit(Request(prompt=[1, 2], max_new_tokens=2))
