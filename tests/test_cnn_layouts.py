"""CNN layout-equivalence suite (reference all_cnn_tests.sh: the same
fixed-weight CNNs under every parallel layout must reproduce the 1-GPU
loss trajectory; here 1-device vs dp8/fsdp8 through the Executor).

BatchNorm makes this the interesting CNN case: batch statistics must be
GLOBAL means under dp sharding (GSPMD inserts the cross-device reduction
from the sharding annotations alone — the pjit equivalent of sync-BN),
otherwise the trajectories diverge."""

import numpy as np
import pytest

import hetu_tpu as ht
from hetu_tpu.models import cnn as zoo


BATCH = 16
N_STEPS = 5


def build(model_name):
    x = ht.placeholder_op("x")
    y = ht.placeholder_op("y")
    loss, pred = getattr(zoo, model_name)(x, y)
    train = ht.optim.SGDOptimizer(learning_rate=0.05).minimize(loss)
    return x, y, loss, train


def batches(shape, n=N_STEPS, classes=10, seed=9):
    rng = np.random.RandomState(seed)
    out = []
    for _ in range(n):
        xb = rng.randn(BATCH, *shape).astype(np.float32)
        yb = np.eye(classes, dtype=np.float32)[rng.randint(0, classes,
                                                           BATCH)]
        out.append((xb, yb))
    return out


CASES = {
    # model -> input shape (NCHW for convs, flat for mlp)
    "mlp": (784,),
    "cnn_3_layers": (1, 28, 28),
    "lenet": (1, 28, 28),
    "resnet18": (3, 32, 32),
}

LAYOUTS = {
    "dp8": lambda: ht.dist.DataParallel(num_devices=8),
    "fsdp8": lambda: ht.dist.FSDP(dp=8, min_size=64),
}


class TestCNNLayouts:
    @pytest.mark.parametrize("model", sorted(CASES), ids=sorted(CASES))
    @pytest.mark.parametrize("layout", sorted(LAYOUTS),
                             ids=sorted(LAYOUTS))
    def test_trajectory_matches_single_device(self, model, layout):
        shape = CASES[model]
        # resnet18: 20 stacked BNs amplify psum summation-order noise
        # (each rsqrt(var+eps) renormalizes), so compare fewer steps
        n_steps = 3 if model == "resnet18" else N_STEPS
        x, y, loss, train = build(model)
        ex1 = ht.Executor({"train": [loss, train]})
        w0 = ex1.return_tensor_values()
        bs = batches(shape, n=n_steps)
        base = [float(np.asarray(ex1.run(
            "train", feed_dict={x: a, y: b})[0])) for a, b in bs]

        x, y, loss, train = build(model)
        ex2 = ht.Executor({"train": [loss, train]},
                          dist_strategy=LAYOUTS[layout]())
        ex2.load_dict(w0)
        tr = [float(np.asarray(ex2.run(
            "train", feed_dict={x: a, y: b})[0])) for a, b in bs]
        tol = dict(rtol=5e-3, atol=1e-4) if model == "resnet18" \
            else dict(rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(tr, base, **tol)

    def test_bn_running_stats_global_under_dp(self):
        """After dp8 training, BN running stats equal the single-device
        run's (batch statistics were reduced across devices — the pjit
        equivalent of sync-BN)."""
        x, y, loss, train = build("resnet18")
        ex1 = ht.Executor({"train": [loss, train]})
        w0 = ex1.return_tensor_values()
        # ONE step: a sync-BN failure (per-device 2-sample stats vs the
        # global 16-sample batch) is a large first-step error, while
        # later steps only accumulate fp drift of the params
        bs = batches(CASES["resnet18"], n=1)
        for a, b in bs:
            ex1.run("train", feed_dict={x: a, y: b})
        ref = ex1.return_tensor_values()

        x, y, loss, train = build("resnet18")
        ex2 = ht.Executor({"train": [loss, train]},
                          dist_strategy=ht.dist.DataParallel(
                              num_devices=8))
        ex2.load_dict(w0)
        for a, b in bs:
            ex2.run("train", feed_dict={x: a, y: b})
        got = ex2.return_tensor_values()
        stats = [k for k in ref if "running" in k]
        assert stats, "model has no BN running stats?"
        for k in stats:
            np.testing.assert_allclose(got[k], ref[k], rtol=1e-4,
                                       atol=1e-5, err_msg=k)
