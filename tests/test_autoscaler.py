"""Elastic fleet (ISSUE 16 tentpole): SLO-burn-driven autoscaler with
chaos-gated live scale-up/scale-down and the seeded traffic generator.

The acceptance spine: a ServingRouter's membership is DYNAMIC —
``add_replica`` brings a replica up gated on committed-version
admission, prefix warming, and a half-open probe decode;
``retire_replica`` drains one out with zero request loss (its in-flight
requests requeue onto peers, its hot prefixes export first).  The
FleetAutoscaler rides ``router.step()`` and drives both off SLO burn +
queue pressure with tick-counted hysteresis and a cooldown window, and
``enabled=False`` is byte-identical to a router with no autoscaler at
all (the degradation contract).  Chaos (``HETU_CHAOS role=autoscale``)
kills the busiest peer mid-scale-up or the draining replica mid-drain:
zero loss must hold anyway.

All CPU-harness, all smoke-tier (tiny random-weight GPTs — the
contract under test is elasticity orchestration, not model quality).
"""

import numpy as np
import pytest

import hetu_tpu as ht  # noqa: F401  (platform forcing + compat shims)
from hetu_tpu import telemetry
from hetu_tpu.models import GPTConfig
from hetu_tpu.ps import faults
from hetu_tpu.serving import (
    SLO, FleetAutoscaler, Request, ServingEngine, ServingRouter,
    TrafficGenerator, WeightSyncCoordinator, replay,
)
from hetu_tpu.serving.replica import RETIRED, UP

pytestmark = pytest.mark.smoke


def _rand_gpt(name="as", L=1, H=2, Dh=8, V=61, S=32, seed=0):
    """Deterministic random params in generate_fast's naming contract."""
    rng = np.random.RandomState(seed)
    hd = H * Dh
    p = {f"{name}_wte_table": rng.randn(V, hd) * 0.05,
         f"{name}_wpe": rng.randn(S, hd) * 0.05,
         f"{name}_ln_f_scale": np.ones(hd),
         f"{name}_ln_f_bias": np.zeros(hd)}
    for i in range(L):
        us = f"{name}_h{i}"
        for w, shp in [("attn_q", (hd, hd)), ("attn_k", (hd, hd)),
                       ("attn_v", (hd, hd)), ("attn_proj", (hd, hd)),
                       ("ffn_wi", (hd, 4 * hd)), ("ffn_wo", (4 * hd, hd))]:
            p[f"{us}_{w}_weight"] = rng.randn(*shp) * 0.05
            p[f"{us}_{w}_bias"] = np.zeros(shp[1])
        for ln in ("ln1", "ln2"):
            p[f"{us}_{ln}_scale"] = np.ones(hd)
            p[f"{us}_{ln}_bias"] = np.zeros(hd)
    cfg = GPTConfig(vocab_size=V, hidden_size=hd, num_hidden_layers=L,
                    num_attention_heads=H, max_position_embeddings=S,
                    batch_size=1, seq_len=S, dropout_rate=0.0)
    return p, cfg


@pytest.fixture(scope="module")
def model():
    # v1 and v2 share shapes/keys but not values, so version-stamped
    # admission is observable in the committed-version test
    p1, cfg = _rand_gpt(seed=0)
    p2, _ = _rand_gpt(seed=1)
    return p1, p2, cfg


@pytest.fixture(autouse=True)
def _fresh(monkeypatch):
    monkeypatch.setenv("HETU_TELEMETRY", "1")
    monkeypatch.delenv("HETU_CHAOS", raising=False)
    faults.reset_plans()
    telemetry.reset()
    yield
    faults.reset_plans()
    telemetry.reset()


def _mk_router(p, cfg, *, replicas=2, slo_ms=None, **rkw):
    """The verified elastic-fleet harness config: paged prefix-share
    engines, directory on, deterministic shedding OFF (shed_on_slo
    reads wall-clock TTFT, which is noise on a virtual-clock replay)."""

    def factory(i):
        slo = ([SLO("ttft", "latency", slo_ms)]
               if slo_ms is not None else None)
        return ServingEngine(p, cfg, slots=4, queue_limit=8,
                             max_seq_len=32, paged=True, kv_block=4,
                             prefix_share=True, slo=slo)

    rkw.setdefault("shed_on_slo", False)
    rkw.setdefault("restart_backoff", 0.01)
    rkw.setdefault("directory", True)
    return ServingRouter(factory, replicas=replicas, **rkw)


def _traffic(**kw):
    kw.setdefault("seed", 7)
    kw.setdefault("vocab", 61)
    kw.setdefault("s_max", 32)
    kw.setdefault("horizon_s", 2.0)
    kw.setdefault("base_rps", 2.0)
    kw.setdefault("peak_rps", 40.0)
    kw.setdefault("cycle_s", 2.0)
    kw.setdefault("n_sessions", 4)
    kw.setdefault("prefix_len", 8)
    return TrafficGenerator(**kw)


# --------------------------------------------------------------------- #
# the control loop: hysteresis, cooldown, rollout deferral
# --------------------------------------------------------------------- #

class TestControlLoop:
    def test_hysteresis_and_cooldown(self, model):
        """Scale-up needs UP_TICKS consecutive hot ticks, every action
        opens a cooldown window that absorbs the signal, the fleet
        clamps to [min, max], and a sustained idle signal walks it back
        down one replica per cooldown."""
        p1, _, cfg = model
        r = _mk_router(p1, cfg, replicas=1)
        auto = FleetAutoscaler(r, fleet_min=1, fleet_max=3, up_ticks=3,
                               down_ticks=4, cooldown=4)
        auto.worst_burn = lambda: 5.0    # hot from burn alone
        r.queue_pressure = lambda: 0.0
        t = [0.0]

        def tk(n=1):
            for _ in range(n):
                t[0] += 0.01
                auto.tick(now=t[0])

        tk(2)
        assert auto.scale_ups == 0 and auto.actual() == 1
        tk()   # third consecutive hot tick
        assert auto.scale_ups == 1 and auto.actual() == 2
        assert auto.last_action["action"] == "scale_up"
        assert auto.last_action["reason"] == "burn"
        tk(4)  # the cooldown window absorbs 4 hot ticks
        assert auto.scale_ups == 1
        tk(3)  # streak rebuilds from zero after the action
        assert auto.scale_ups == 2 and auto.actual() == 3
        tk(10)  # at fleet_max: hot forever, no further growth
        assert auto.scale_ups == 2 and auto.peak_replicas == 3
        auto.worst_burn = lambda: 0.0   # now sustained idle
        tk(40)
        # 4 idle ticks -> retire, 4 cooldown + 4 idle -> retire again,
        # then the fleet_min floor holds
        assert auto.scale_downs == 2 and auto.actual() == 1
        assert auto.last_action["action"] == "scale_down"
        assert sum(1 for x in r.replicas if x.state == RETIRED) == 2
        snap = auto.snapshot()
        assert snap["min"] == 1 and snap["max"] == 3
        assert snap["replica_ticks"] > 0
        assert len(auto.timeline) == 4

    def test_scale_down_deferred_mid_rollout(self, model):
        """A scale-down never fires while a weight rollout is in
        flight (the commit is defined over the fleet), and a replica
        added mid-rollout admits on the COMMITTED version and is
        adopted into the rollout order — the fleet still lands on v2."""
        p1, p2, cfg = model
        r = _mk_router(p1, cfg, replicas=2)
        coord = WeightSyncCoordinator(r, p1, version=1)
        auto = FleetAutoscaler(r, fleet_min=1, fleet_max=4,
                               up_ticks=100, down_ticks=1, cooldown=0)
        auto.worst_burn = lambda: 0.0
        r.queue_pressure = lambda: 0.0
        assert coord.begin(p2, 2)
        auto.tick(now=0.01)
        assert auto.deferred_rollout == 1 and auto.scale_downs == 0
        idx = r.add_replica()
        assert idx == 2
        assert r.replicas[idx].engine.weight_version \
            == coord.committed_version == 1
        auto.enabled = False   # the drain below is the rollout's story
        coord.drain()
        assert coord.state == "done"
        assert coord.fleet_versions() == {0: 2, 1: 2, 2: 2}


# --------------------------------------------------------------------- #
# membership changes under live traffic
# --------------------------------------------------------------------- #

class TestElasticity:
    def test_scale_up_down_zero_loss_under_traffic(self, model):
        """One diurnal cycle through a pressure-driven autoscaler: the
        fleet grows at the peak, shrinks in the idle tail, loses
        nothing, and every finished request is token-identical to a
        lone offline engine decoding the same specs."""
        p1, _, cfg = model
        r = _mk_router(p1, cfg, replicas=1)
        auto = FleetAutoscaler(r, fleet_min=1, fleet_max=2,
                               up_pressure=0.2, up_ticks=2,
                               down_pressure=0.1, down_ticks=30,
                               cooldown=10)
        specs = _traffic(seed=2024, horizon_s=3.0, peak_rps=80.0,
                         cycle_s=3.0, n_sessions=8).trace(dt=0.05)
        res, rep = replay(r, specs, step_s=0.01, tail_s=3.0)
        snap = r.snapshot()
        assert snap["lost"] == 0
        assert auto.scale_ups >= 1 and auto.scale_downs >= 1
        assert auto.peak_replicas == 2
        # every admitted request retired exactly once
        assert len(res) + len(rep["shed"]) + len(rep["rejected"]) \
            == len(specs)
        eng = ServingEngine(p1, cfg, slots=4,
                            queue_limit=len(specs) + 1, max_seq_len=32)
        off = eng.run([sp.to_request() for sp in specs
                       if sp.request_id in res])
        for rid, x in res.items():
            assert list(x.tokens) == list(off[rid].tokens), rid

    def test_warm_prefix_handoff_on_scale_up(self, model):
        """A joining replica prefix-warms from its peers through the
        export/import handoff codec BEFORE taking traffic: the peers'
        hottest directory-known prefixes exist in its paged pool the
        moment it is ready."""
        p1, _, cfg = model
        r = _mk_router(p1, cfg, replicas=1)
        head = [3, 4, 5, 6, 7, 8, 9, 10]   # two full kv blocks
        r.run([Request(prompt=head + [11 + i], max_new_tokens=4,
                       request_id=f"w{i}") for i in range(4)])
        assert r.replicas[0].engine.kv._prefix
        before = r.handoffs
        idx = r.add_replica(warm_prefixes=4)
        rep = r.replicas[idx]
        assert rep.lifecycle == "serving"
        warmed = list(rep.engine.kv._prefix)
        assert warmed, "no prefix warmed onto the joining replica"
        assert any(list(k) == head[:len(k)] for k in warmed)
        assert r.handoffs > before

    def test_retire_requeues_in_flight_zero_loss(self, model):
        """Retiring a replica with requests in flight requeues them
        onto peers through the drain path: every request retires
        exactly once, the victim ends RETIRED (not respawned — intent,
        not failure), and its directory entries are gone."""
        p1, _, cfg = model
        r = _mk_router(p1, cfg, replicas=2)
        reqs = [Request(prompt=[1 + i, 2, 3], max_new_tokens=6,
                        request_id=f"d{i}") for i in range(8)]
        for q in reqs:
            r.submit(q)
        out = {}
        for _ in range(3):
            for res in r.step():
                out[res.request_id] = res
        requeued = r.retire_replica(1, reason="scale_down")
        for _ in range(4000):
            if not r.pending:
                break
            for res in r.step():
                out[res.request_id] = res
        snap = r.snapshot()
        assert snap["lost"] == 0
        assert set(out) == {q.request_id for q in reqs}
        assert r.replicas[1].state == RETIRED
        assert r.replicas[1].restarts == 0
        assert snap["requeued"] == requeued
        # the victim's directory claims are purged with it
        assert all(1 not in e.replicas
                   for e in r.directory._entries.values())

    def test_retire_last_up_replica_refused(self, model):
        p1, _, cfg = model
        r = _mk_router(p1, cfg, replicas=1)
        with pytest.raises(ValueError, match="no UP peer"):
            r.retire_replica(0)


# --------------------------------------------------------------------- #
# chaos: the seams fire, zero loss holds anyway
# --------------------------------------------------------------------- #

class TestChaos:
    def test_kill_busiest_peer_mid_scale_up(self, model, monkeypatch):
        """role=autoscale kill during bring-up takes out the BUSIEST
        peer: the joining replica absorbs the requeued load and the
        trace still retires every admitted request exactly once."""
        p1, _, cfg = model
        monkeypatch.setenv("HETU_CHAOS", "seed=11,kill=1,role=autoscale")
        faults.reset_plans()
        # a tight TTFT budget makes any traffic burn the error budget,
        # so scale-up is burn-driven and fires early in the trace
        r = _mk_router(p1, cfg, replicas=2, slo_ms=0.001)
        auto = FleetAutoscaler(r, fleet_min=1, fleet_max=3, up_ticks=2,
                               down_ticks=10_000, cooldown=3)
        specs = _traffic().trace(dt=0.05)
        res, rep = replay(r, specs, step_s=0.01, tail_s=1.0)
        snap = r.snapshot()
        assert auto.scale_ups >= 1
        assert snap["lost"] == 0
        assert len(res) + len(rep["shed"]) + len(rep["rejected"]) \
            == len(specs)
        # the seam fired and the supervisor respawned the victim
        assert any(row["restarts"] >= 1 for row in snap["replicas"])

    def test_kill_draining_replica_mid_drain(self, model, monkeypatch):
        """role=autoscale kill during a drain takes out the retiring
        replica itself: the requeue reads the router's own assignment
        records, never the corpse, so zero loss holds anyway."""
        p1, _, cfg = model
        r = _mk_router(p1, cfg, replicas=2)
        reqs = [Request(prompt=[2 + i, 5, 9], max_new_tokens=6,
                        request_id=f"c{i}") for i in range(8)]
        for q in reqs:
            r.submit(q)
        out = {}
        for _ in range(3):
            for res in r.step():
                out[res.request_id] = res
        monkeypatch.setenv("HETU_CHAOS", "seed=12,kill=1,role=autoscale")
        faults.reset_plans()
        r.retire_replica(1, reason="scale_down")
        assert "chaos autoscale kill" in (r.replicas[1].exit_error or "")
        for _ in range(4000):
            if not r.pending:
                break
            for res in r.step():
                out[res.request_id] = res
        assert r.snapshot()["lost"] == 0
        assert set(out) == {q.request_id for q in reqs}


# --------------------------------------------------------------------- #
# the traffic generator
# --------------------------------------------------------------------- #

class TestTraffic:
    def test_trace_is_a_pure_function_of_the_seed(self):
        kw = dict(seed=5, horizon_s=1.0, base_rps=10.0, peak_rps=30.0,
                  cycle_s=1.0, n_sessions=4, prefix_len=6)
        t1 = _traffic(**kw).trace(dt=0.05)
        t2 = _traffic(**kw).trace(dt=0.05)
        assert len(t1) > 0

        def key(s):
            return (s.t, s.request_id, tuple(s.prompt),
                    s.max_new_tokens, s.workload, s.slo_class,
                    s.session_id, s.seed)

        assert [key(s) for s in t1] == [key(s) for s in t2]
        t3 = _traffic(**dict(kw, seed=6)).trace(dt=0.05)
        assert [key(s) for s in t1] != [key(s) for s in t3]

    def test_diurnal_flash_and_sessions(self):
        g = _traffic(seed=5, horizon_s=1.0, base_rps=10.0,
                     peak_rps=30.0, cycle_s=1.0)
        gf = _traffic(seed=5, horizon_s=1.0, base_rps=10.0,
                      peak_rps=30.0, cycle_s=1.0,
                      flash=((0.5, 0.2, 4.0),))
        # the diurnal curve spans base..peak
        assert g.rate(0.0) < g.rate(0.25)
        # the flash crowd multiplies the curve inside its window only
        assert gf.rate(0.6) == pytest.approx(g.rate(0.6) * 4.0)
        assert gf.rate(0.1) == pytest.approx(g.rate(0.1))
        # zipf sessions share a seeded prefix head (the prefix-cache
        # workload shape): same session => same first tokens
        specs = g.trace(dt=0.05)
        by_sess = {}
        for s in specs:
            by_sess.setdefault(s.session_id, []).append(s)
        multi = [v for v in by_sess.values() if len(v) >= 2]
        assert multi
        for group in multi:
            heads = {tuple(s.prompt[:g.prefix_len]) for s in group}
            assert len(heads) == 1
        # workload classes carry their SLO class end to end
        assert {s.slo_class for s in specs} <= {"latency", "throughput"}

    def test_describe_is_jsonable_provenance(self):
        import json
        d = _traffic().describe()
        assert json.loads(json.dumps(d))["seed"] == 7


# --------------------------------------------------------------------- #
# the degradation contract
# --------------------------------------------------------------------- #

def test_disabled_autoscaler_is_byte_identical_to_static(model):
    """enabled=False is a STRICT no-op: same results, same tokens, same
    counters, same step count as a router with no autoscaler at all."""
    p1, _, cfg = model
    specs = _traffic(seed=9, horizon_s=1.0, peak_rps=30.0,
                     cycle_s=1.0).trace(dt=0.05)

    def run(with_auto):
        r = _mk_router(p1, cfg, replicas=2)
        auto = (FleetAutoscaler(r, fleet_min=1, fleet_max=3,
                                enabled=False) if with_auto else None)
        res, rep = replay(r, specs, step_s=0.01, tail_s=0.2)
        return res, rep, r.snapshot(), auto

    r1, rep1, s1, _ = run(False)
    r2, rep2, s2, auto = run(True)
    assert set(r1) == set(r2)
    for rid in r1:
        assert list(r1[rid].tokens) == list(r2[rid].tokens), rid
    for k in ("finished", "lost", "shed", "requeued", "submitted",
              "handoffs"):
        assert s1[k] == s2[k], k
    assert rep1["steps"] == rep2["steps"]
    assert auto.ticks == 0 and auto.scale_ups == 0
    assert s1["autoscaler"] is None
    assert s2["autoscaler"]["enabled"] is False
