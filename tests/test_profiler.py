"""Profiler surface (reference profiler.py:55-120 HetuProfiler +
Executor.profile entry executor.py:432-440): step timing, XLA
cost-analysis FLOPs, and the memory-analysis dry-run that replaces the
reference memory planner's test_memory simulation (memory_pool.py:142)."""

import numpy as np

import hetu_tpu as ht
from hetu_tpu.profiler import HetuProfiler

B, IN, HID, OUT = 16, 8, 32, 4


def _build():
    x = ht.placeholder_op("px")
    y = ht.placeholder_op("py")
    w1 = ht.init.xavier_uniform((IN, HID), name="pf_w1")
    w2 = ht.init.xavier_uniform((HID, OUT), name="pf_w2")
    loss = ht.reduce_mean_op(ht.softmaxcrossentropy_op(
        ht.matmul_op(ht.relu_op(ht.matmul_op(x, w1)), w2), y), axes=0)
    train = ht.optim.AdamOptimizer(learning_rate=0.01).minimize(loss)
    ex = ht.Executor({"train": [loss, train]})
    return x, y, ex


def _feeds(x, y):
    rng = np.random.RandomState(0)
    return {x: rng.randn(B, IN).astype(np.float32),
            y: np.eye(OUT, dtype=np.float32)[rng.randint(0, OUT, B)]}


class TestProfiler:
    def test_step_timing_and_analyses(self):
        x, y, ex = _build()
        fd = _feeds(x, y)
        prof = HetuProfiler(ex, feed_shapes={"px": (B, IN), "py": (B, OUT)})
        dt = prof.profile_step("train", feed_dict=fd, warmup=1, iters=2)
        assert dt > 0
        assert prof.records and prof.records[-1]["step_time_s"] == dt

        cost = prof.cost_analysis("train")
        assert cost is not None and float(cost["flops"]) > 0

        mem = prof.memory_analysis("train")
        assert mem is not None
        # params+opt slots+feeds are real argument bytes
        n_param_bytes = 4 * (IN * HID + HID * OUT)
        assert mem["argument_size_in_bytes"] >= n_param_bytes
        assert mem["peak_estimate_bytes"] >= mem["argument_size_in_bytes"]

    def test_memory_analysis_before_compile_is_none(self):
        _, _, ex = _build()
        prof = HetuProfiler(ex, feed_shapes={})
        assert prof.memory_analysis("train") is None


def test_cost_analysis_with_dataloader_and_node_keys():
    """cost_analysis must work when the graph feeds from Dataloader ops
    and feed_shapes is keyed by placeholder NODES (regression: the
    synthetic feeds went to the compiled step un-converted, which can't
    even sort as a jax pytree, so every analysis silently returned
    None)."""
    import numpy as np
    import hetu_tpu as ht
    from hetu_tpu.profiler import HetuProfiler

    B, IN, OUT = 8, 6, 3
    rng = np.random.RandomState(0)
    xs = rng.randn(B * 4, IN).astype(np.float32)
    ys = np.eye(OUT, dtype=np.float32)[rng.randint(0, OUT, B * 4)]
    x = ht.dataloader_op([ht.Dataloader(xs, B, "train")])
    y = ht.dataloader_op([ht.Dataloader(ys, B, "train")])
    w = ht.init.xavier_uniform((IN, OUT), name="cap_w")
    loss = ht.reduce_mean_op(
        ht.softmaxcrossentropy_op(ht.matmul_op(x, w), y), axes=0)
    train = ht.optim.SGDOptimizer(learning_rate=0.1).minimize(loss)
    ex = ht.Executor({"train": [loss, train]})
    ex.run("train")

    prof = HetuProfiler(ex, feed_shapes={})
    cost = prof.cost_analysis("train")
    assert cost is not None and float(cost["flops"]) > 0
    mem = prof.memory_analysis("train")
    assert mem is not None and mem["argument_size_in_bytes"] > 0
