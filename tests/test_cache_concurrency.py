"""CacheSparseTable thread-safety audit (ISSUE 14 satellite): the
locking contract in cstable.__init__ under fire.

Serving waves read the cache from engine threads while training-style
updates land from others, and a PS outage in the middle exercises the
backlog machinery (_push_or_buffer/_replay — lock-held-only internals)
on every path.  The regression here: two threads hammering
lookup+update across a simulated outage window finish with no escaped
exception, a consistent counter snapshot, a drained backlog, and the
staleness/pull-bytes observables populated in ``perf_summary()``.
"""

import threading

import numpy as np
import pytest

from hetu_tpu import telemetry
from hetu_tpu.cache.cstable import CacheSparseTable
from hetu_tpu.ps.client import PSConnectionError
from hetu_tpu.ps.server import PSServer

pytestmark = pytest.mark.smoke

W = 4
VOCAB = 64
ITERS = 40        # per thread per phase (healthy / outage / recovered)


class _FlakyPS:
    """Every PS verb raises while ``down`` (same rig as the serving
    outage tests — the cache only sees ConnectionError)."""

    def __init__(self, server):
        self._server = server
        self.down = False

    def __getattr__(self, name):
        fn = getattr(self._server, name)

        def wrapper(*a, **kw):
            if self.down:
                raise PSConnectionError("PS down (test)")
            return fn(*a, **kw)
        return wrapper


def _mk_table(monkeypatch, **kw):
    # budgets high enough that the hammer degrades instead of surfacing
    monkeypatch.setenv("HETU_CACHE_MAX_STALE", "1000000")
    monkeypatch.setenv("HETU_CACHE_BACKLOG_ROWS", "1000000")
    server = PSServer()
    server.param_init("emb", (VOCAB, W), "normal", 0.0, 1.0, seed=3)
    flaky = _FlakyPS(server)
    t = CacheSparseTable(limit=32, vocab_size=VOCAB, width=W,
                         key="emb", comm=flaky, policy="LRU", **kw)
    return t, flaky, server


def test_two_thread_hammer_across_outage(monkeypatch):
    """Lookup thread + update thread, three phases (healthy -> PS down
    -> recovered), main thread polling perf_summary throughout: no
    exception escapes, the backlog drains on recovery, and the outage
    observables are populated."""
    telemetry.reset()
    monkeypatch.setenv("HETU_TELEMETRY", "1")
    t, flaky, server = _mk_table(monkeypatch, push_bound=0)
    t.embedding_lookup(np.arange(VOCAB))      # warm everything hot
    errors = []
    barrier = threading.Barrier(3, timeout=60)

    def run_phases(op):
        rng = np.random.RandomState(hash(op.__name__) % 2**31)
        for _phase in range(3):
            barrier.wait()
            for _ in range(ITERS):
                try:
                    op(rng)
                except Exception as e:   # noqa: BLE001 — the assert
                    errors.append(e)
            barrier.wait()

    def lookup_op(rng):
        rows = t.embedding_lookup(rng.randint(0, VOCAB, 8))
        assert rows.shape == (8, W)

    def update_op(rng):
        ids = rng.randint(0, VOCAB, 4)
        t.embedding_update(ids, rng.randn(4, W).astype(np.float32) * .01)

    threads = [threading.Thread(target=run_phases, args=(op,))
               for op in (lookup_op, update_op)]
    for th in threads:
        th.start()

    barrier.wait()           # phase 0: healthy
    barrier.wait()
    flaky.down = True
    barrier.wait()           # phase 1: outage — summary reads race the
    mid = [t.perf_summary() for _ in range(10)]   # hammer on the lock
    barrier.wait()
    during = t.perf_summary()
    flaky.down = False
    barrier.wait()           # phase 2: recovered
    barrier.wait()
    for th in threads:
        th.join(timeout=60)
        assert not th.is_alive()

    assert errors == []      # nothing escaped the degradation budget
    assert all(isinstance(s, dict) for s in mid)
    # the outage was real and the backlog machinery engaged:
    # push_bound=0 updates buffered, lookups served stale
    assert during["ps_failures"] > 0
    assert during["stale_served_rows"] > 0
    assert during["backlog_rows"] > 0
    assert during["staleness_s"] > 0.0
    # recovery drained the backlog (replay on next PS contact)
    t.flush()
    final = t.perf_summary()
    assert final["backlog_rows"] == 0
    assert final["staleness_s"] == 0.0
    assert final["replayed_rows"] > 0
    assert final["pull_bytes"] > 0
    assert final["pushed_rows"] > 0
    # and the cache still agrees with the PS after a final flush: the
    # hammer's deltas all landed exactly once
    ids = np.arange(VOCAB)
    cached = t.embedding_lookup(ids)
    want = server.sparse_pull("emb", ids)
    np.testing.assert_allclose(cached, want, rtol=1e-4, atol=1e-5)


def test_async_variants_during_outage(monkeypatch):
    """The pool-thread async API (the serving prefetch path) degrades
    identically: futures resolve during the outage, replay happens on
    recovery, counters stay consistent."""
    telemetry.reset()
    monkeypatch.setenv("HETU_TELEMETRY", "1")
    t, flaky, _ = _mk_table(monkeypatch, push_bound=0)
    rng = np.random.RandomState(0)
    t.embedding_lookup(np.arange(32))
    flaky.down = True
    futs = []
    for _ in range(20):
        ids = rng.randint(0, 32, 8)
        futs.append(t.embedding_lookup_async(ids))
        futs.append(t.embedding_update_async(
            ids[:4], rng.randn(4, W).astype(np.float32) * .01))
    for f in futs:
        r = f.result(timeout=30)
        if r is not None:
            assert r.shape == (8, W)
    s = t.perf_summary()
    assert s["ps_failures"] > 0 and s["backlog_rows"] > 0
    assert s["staleness_s"] > 0.0
    flaky.down = False
    t.flush()
    assert t.perf_summary()["backlog_rows"] == 0
    # the registry observables mirrored the instance counters
    snap = telemetry.snapshot()
    assert snap["counters"].get("cache.pull_bytes", 0) > 0
