"""Raw-corpus pretraining pipeline (reference
examples/nlp/bert/create_pretraining_data.py + load_data.py): corpus ->
masked-LM/NSP instance arrays -> the models, hermetically from a
checked-in text fixture."""

import os

import numpy as np
import pytest

import hetu_tpu as ht
from hetu_tpu.pretraining_data import (
    IGNORE_INDEX, PretrainingBatches, build_wordpiece_vocab,
    create_bert_pretraining_data, create_gpt_pretraining_data,
    read_documents,
)
from hetu_tpu.tokenizers import BertTokenizer

CORPUS = os.path.join(os.path.dirname(__file__), "fixtures",
                      "tiny_corpus.txt")


@pytest.fixture(scope="module")
def tokenizer(tmp_path_factory):
    vocab = str(tmp_path_factory.mktemp("vocab") / "vocab.txt")
    build_wordpiece_vocab(CORPUS, out_path=vocab)
    return BertTokenizer.from_pretrained(vocab)


@pytest.fixture(scope="module")
def bert_data(tokenizer):
    return create_bert_pretraining_data(CORPUS, tokenizer,
                                        max_seq_length=48, dupe_factor=3)


class TestCorpusParsing:
    def test_blank_lines_split_documents(self, tokenizer):
        docs = read_documents(CORPUS, tokenizer)
        assert len(docs) == 6          # fixture has 6 paragraphs
        assert all(len(d) >= 4 for d in docs)   # sentences per doc

    def test_vocab_builder_roundtrip(self, tokenizer):
        # specials present and corpus words tokenize without [UNK]
        for sp in ("[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]"):
            assert sp in tokenizer.vocab
        toks = tokenizer.tokenize("the river carried cold water")
        ids = tokenizer.convert_tokens_to_ids(toks)
        assert tokenizer.vocab["[UNK]"] not in ids


class TestBertInstances:
    def test_shapes_and_ranges(self, bert_data, tokenizer):
        ids = bert_data["input_ids"]
        n, s = ids.shape
        assert s == 48 and n >= 20
        assert ids.min() >= 0 and ids.max() < len(tokenizer.vocab)
        for key in ("token_type_ids", "attention_mask",
                    "masked_lm_labels"):
            assert bert_data[key].shape == (n, s)
        assert bert_data["next_sentence_label"].shape == (n,)

    def test_instance_structure(self, bert_data, tokenizer):
        """[CLS] a [SEP] b [SEP] with segment ids 0/1 and padding."""
        v = tokenizer.vocab
        ids = bert_data["input_ids"]
        seg = bert_data["token_type_ids"]
        mask = bert_data["attention_mask"]
        assert (ids[:, 0] == v["[CLS]"]).all()
        for j in range(ids.shape[0]):
            valid = int(mask[j].sum())
            # exactly two [SEP]s among valid positions, last valid is one
            seps = np.where(ids[j, :valid] == v["[SEP]"])[0]
            assert len(seps) == 2 and seps[-1] == valid - 1
            # segment 1 exactly between the two seps
            assert (seg[j, :seps[0] + 1] == 0).all()
            assert (seg[j, seps[0] + 1:valid] == 1).all()
            # padding after valid
            assert (ids[j, valid:] == v["[PAD]"]).all()
            assert (mask[j, valid:] == 0).all()

    def test_masking_statistics(self, bert_data, tokenizer):
        """~15% of tokens masked (<= max_predictions), labels only at
        corrupted-or-kept positions, and most corrupted positions are
        the [MASK] token (80/10/10)."""
        v = tokenizer.vocab
        ids = bert_data["input_ids"]
        mlm = bert_data["masked_lm_labels"]
        labeled = mlm != IGNORE_INDEX
        per_row = labeled.sum(axis=1)
        assert (per_row >= 1).all() and (per_row <= 20).all()
        frac_mask_tok = (ids[labeled] == v["[MASK]"]).mean()
        assert 0.6 < frac_mask_tok < 0.95      # 80% +/- sampling noise
        # labels are real vocab ids, never specials like [PAD]
        assert mlm[labeled].min() >= 0
        assert (mlm[labeled] < len(v)).all()

    def test_nsp_labels_are_mixed(self, bert_data):
        m = bert_data["next_sentence_label"].mean()
        assert 0.1 < m < 0.9

    def test_deterministic_given_seed(self, tokenizer):
        a = create_bert_pretraining_data(CORPUS, tokenizer,
                                         max_seq_length=32, dupe_factor=1,
                                         seed=7)
        b = create_bert_pretraining_data(CORPUS, tokenizer,
                                         max_seq_length=32, dupe_factor=1,
                                         seed=7)
        np.testing.assert_array_equal(a["input_ids"], b["input_ids"])
        np.testing.assert_array_equal(a["masked_lm_labels"],
                                      b["masked_lm_labels"])


class TestGptPacking:
    def test_blocks_and_shifted_labels(self, tokenizer):
        g = create_gpt_pretraining_data(CORPUS, tokenizer, seq_len=32)
        ids, labels = g["input_ids"], g["labels"]
        assert ids.shape == labels.shape and ids.shape[0] >= 5
        np.testing.assert_array_equal(labels[:, :-1], ids[:, 1:])
        assert (labels[:, -1] == IGNORE_INDEX).all()

    def test_too_small_corpus_raises(self, tokenizer):
        with pytest.raises(ValueError):
            create_gpt_pretraining_data(CORPUS, tokenizer, seq_len=10 ** 6)


class TestBatches:
    def test_epoch_covers_all_and_reshuffles(self, bert_data):
        bs = 4
        it = PretrainingBatches(bert_data, bs, seed=3)
        e1 = [b["input_ids"] for b in it]
        e2 = [b["input_ids"] for b in it]
        n = bert_data["input_ids"].shape[0]
        assert len(e1) == n // bs          # drop-last epoch length
        # reshuffled between epochs (drop-last may also rotate which
        # rows are kept, so only the ordering difference is asserted)
        assert not np.array_equal(np.concatenate(e1), np.concatenate(e2))

    def test_batch_too_large_raises(self, bert_data):
        with pytest.raises(ValueError):
            PretrainingBatches(bert_data, 10 ** 6)


class TestEndToEnd:
    def test_bert_pretrains_on_fixture_corpus(self, tokenizer, bert_data):
        """The reference's integration bar (train_hetu_bert.py on real
        data): loss on real masked-LM batches from the corpus drops."""
        from hetu_tpu.models import BertConfig, BertForPreTraining
        cfg = BertConfig(vocab_size=len(tokenizer.vocab), hidden_size=32,
                         num_hidden_layers=2, num_attention_heads=2,
                         intermediate_size=64, batch_size=8, seq_len=48,
                         hidden_dropout_prob=0.0,
                         attention_probs_dropout_prob=0.0)
        m = BertForPreTraining(cfg, name="corpus_bert")
        ids = ht.placeholder_op("c_ids")
        tt = ht.placeholder_op("c_tt")
        am = ht.placeholder_op("c_am")
        mlm = ht.placeholder_op("c_mlm")
        nsp = ht.placeholder_op("c_nsp")
        loss, _, _ = m(ids, tt, am, mlm, nsp)
        train = ht.optim.AdamOptimizer(learning_rate=3e-3).minimize(loss)
        ex = ht.Executor({"train": [loss, train]})
        first = last = None
        for epoch in range(30):
            for b in PretrainingBatches(bert_data, 8, seed=epoch):
                out = ex.run("train", feed_dict={
                    ids: b["input_ids"], tt: b["token_type_ids"],
                    am: b["attention_mask"],
                    mlm: b["masked_lm_labels"],
                    nsp: b["next_sentence_label"]})
                last = float(np.asarray(out[0]))
                if first is None:
                    first = last
        assert last < first * 0.6, (first, last)

    def test_train_bert_example_with_data_path(self):
        import importlib.util
        import sys
        path = os.path.join(os.path.dirname(__file__), "..", "examples",
                            "nlp", "train_bert.py")
        spec = importlib.util.spec_from_file_location("ex_bert_corpus",
                                                      path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        old = sys.argv
        sys.argv = ["prog", "--data-path", CORPUS, "--batch-size", "4",
                    "--seq-len", "32", "--num-layers", "1",
                    "--num-steps", "3"]
        try:
            last = mod.main()
        finally:
            sys.argv = old
        assert np.isfinite(last)

    def test_train_gpt_example_with_text_corpus(self):
        import importlib.util
        import sys
        path = os.path.join(os.path.dirname(__file__), "..", "examples",
                            "nlp", "train_gpt.py")
        spec = importlib.util.spec_from_file_location("ex_gpt_corpus",
                                                      path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        old = sys.argv
        sys.argv = ["prog", "--data-path", CORPUS, "--batch-size", "2",
                    "--seq-len", "32", "--num-layers", "1",
                    "--num-steps", "3"]
        try:
            mod.main()
        finally:
            sys.argv = old
