"""Sharded PS client (reference: ps-lite key-range partitioning across a
server group — storage and push/pull traffic scale with server count)."""

import numpy as np
import pytest

import hetu_tpu as ht
from hetu_tpu.ps.server import PSServer
from hetu_tpu.ps.sharded import ShardedPSClient

# smoke tier: this module is part of the <3-min verification
# battery (`pytest -m smoke`; ROADMAP tier-1 note)
pytestmark = pytest.mark.smoke


def _group(n=2):
    servers = [PSServer() for _ in range(n)]
    return servers, ShardedPSClient(servers=servers)


class TestRowSharding:
    def test_round_robin_rows(self):
        servers, c = _group(2)
        table = np.arange(24, dtype=np.float32).reshape(8, 3)
        c.param_set("t", table)
        # each server holds only its residue class
        np.testing.assert_array_equal(
            np.asarray(servers[0].pull("t")), table[0::2])
        np.testing.assert_array_equal(
            np.asarray(servers[1].pull("t")), table[1::2])
        np.testing.assert_array_equal(c.pull("t"), table)

    def test_sparse_pull_push_routes_by_id(self):
        servers, c = _group(3)
        table = np.random.RandomState(0).randn(9, 4).astype(np.float32)
        c.param_set("t", table)      # no server optimizer: push adds
        ids = np.array([2, 7, 7, 0, 5], np.int64)
        got = c.sparse_pull("t", ids)
        np.testing.assert_allclose(got, table[ids])
        rows = np.ones((5, 4), np.float32)
        c.sparse_push("t", ids, rows)
        out = c.pull("t")
        want = table.copy()
        # duplicate id 7 accumulates twice
        np.add.at(want, ids, rows)
        np.testing.assert_allclose(out, want, rtol=1e-6)

    def test_1d_param_routes_whole(self):
        servers, c = _group(2)
        v = np.arange(5, dtype=np.float32)
        c.param_set("bias", v)
        held = [s for s in servers if "bias" in s.params]
        assert len(held) == 1
        np.testing.assert_array_equal(c.pull("bias"), v)

    def test_fresh_client_discovers_sharding(self):
        servers, c = _group(2)
        table = np.random.RandomState(1).randn(6, 2).astype(np.float32)
        c.param_set("t2", table)
        c2 = ShardedPSClient(servers=servers)   # did not create the table
        np.testing.assert_allclose(c2.pull("t2"), table)
        np.testing.assert_allclose(
            c2.sparse_pull("t2", np.array([1, 4], np.int64)),
            table[[1, 4]])

    def test_dense_push_through_server_opt(self):
        servers, c = _group(2)
        table = np.zeros((4, 2), np.float32)
        c.param_set("t3", table, opt="sgd",
                    opt_args={"learning_rate": 1.0})
        c.push("t3", -np.ones((4, 2), np.float32))   # sgd: p -= lr*g
        np.testing.assert_allclose(c.pull("t3"), np.ones((4, 2)))


class TestExecutorHybridSharded:
    def _build(self, prefix):
        ids = ht.placeholder_op("ids")
        y = ht.placeholder_op("y")
        emb = ht.layers.Embedding(32, 8, name=f"{prefix}_emb")
        h = ht.embedding_lookup_op(emb.embedding_table, ids)
        h = ht.reduce_mean_op(h, [1])
        logits = ht.matmul_op(h, ht.init.xavier_uniform(
            (8, 2), name=f"{prefix}_head"))
        loss = ht.reduce_mean_op(
            ht.softmaxcrossentropy_op(logits, y), axes=0)
        train = ht.optim.SGDOptimizer(learning_rate=0.2).minimize(loss)
        return ids, y, loss, train

    def _batches(self, n=6):
        rng = np.random.RandomState(5)
        return [(rng.randint(0, 32, (8, 4)).astype(np.int32),
                 np.eye(2, dtype=np.float32)[rng.randint(0, 2, 8)])
                for _ in range(n)]

    def test_sharded_trajectory_matches_single_server(self):
        bs = self._batches()
        ids, y, loss, train = self._build("shA")
        ex1 = ht.Executor({"train": [loss, train]}, comm_mode="Hybrid",
                          ps_comm=ShardedPSClient(servers=[PSServer()]))
        w0 = ex1.return_tensor_values()
        base = [float(np.asarray(ex1.run(
            "train", feed_dict={ids: a, y: b})[0])) for a, b in bs]

        ids, y, loss, train = self._build("shA")   # same names/shapes
        _, c = _group(3)
        ex2 = ht.Executor({"train": [loss, train]}, comm_mode="Hybrid",
                          ps_comm=c)
        ex2.load_dict(w0)
        tr = [float(np.asarray(ex2.run(
            "train", feed_dict={ids: a, y: b})[0])) for a, b in bs]
        np.testing.assert_allclose(tr, base, atol=1e-5)

    def test_cache_path_uses_home_server(self):
        bs = self._batches()
        ids, y, loss, train = self._build("shC")
        servers, c = _group(2)
        ex = ht.Executor({"train": [loss, train]}, comm_mode="Hybrid",
                         ps_comm=c, cstable_policy="LRU",
                         cache_bound=16)
        for a, b in bs:
            out = ex.run("train", feed_dict={ids: a, y: b})
            assert np.isfinite(float(np.asarray(out[0])))
        # the cached table lives WHOLE on exactly one server of the group
        held = [s for s in servers if "shC_emb_table" in s.params]
        assert len(held) == 1
        assert held[0].params["shC_emb_table"].value.shape[0] == 32


class TestReviewRegressions:
    def test_async_lookup_does_not_deadlock_fan_pool(self):
        """External async submissions (executor ps_lookup_async duck-types
        _pool) must not starve the internal per-shard fan-out pool."""
        servers, c = _group(2)
        for t in ("tA", "tB", "tC"):
            c.param_set(t, np.random.RandomState(0).randn(
                8, 4).astype(np.float32))
        ids = np.arange(8, dtype=np.int64)
        # saturate the external pool with tasks that each fan out
        futs = [c._pool.submit(c.sparse_pull, t, ids)
                for t in ("tA", "tB", "tC", "tA", "tB", "tC")]
        import concurrent.futures
        done, not_done = concurrent.futures.wait(futs, timeout=30)
        assert not not_done, "fan-out deadlocked behind external tasks"
        for f in done:
            assert f.result().shape == (8, 4)

    def test_load_preserves_server_optimizer(self, tmp_path):
        servers, c = _group(2)
        c.param_set("lp", np.zeros((4, 2), np.float32), opt="sgd",
                    opt_args={"learning_rate": 1.0})
        c.save("lp", str(tmp_path))
        c.push("lp", np.ones((4, 2), np.float32))    # sgd: -= lr*g
        c.load("lp", str(tmp_path))                  # back to zeros...
        np.testing.assert_allclose(c.pull("lp"), 0.0)
        c.push("lp", np.ones((4, 2), np.float32))
        # ...and the optimizer survived the load: SGD applied, not raw add
        np.testing.assert_allclose(c.pull("lp"), -1.0)

    def test_empty_ids_sparse_pull(self):
        servers, c = _group(2)
        c.param_set("ei", np.ones((6, 3), np.float32))
        out = c.sparse_pull("ei", np.array([], np.int64))
        assert out.shape == (0, 3)

    def test_fused_sd_pushpull_single_round_trip(self):
        servers, c = _group(2)
        table = np.zeros((8, 2), np.float32)
        c.param_set("fp", table, opt="sgd", opt_args={"learning_rate": 1.0})
        ids = np.array([0, 3, 5], np.int64)
        rows = np.ones((3, 2), np.float32)
        out = c.sd_pushpull("fp", ids, rows, pull_ids=np.array(
            [1, 5, 0], np.int64))
        # pushes applied (sgd lr=1: -=1), pulls see post-push values
        np.testing.assert_allclose(out, [[0, 0], [-1, -1], [-1, -1]])


class TestShardedVan:
    """r5: van routing composes with row sharding — each home PSClient
    discovers ITS server's van and routes that shard's traffic through
    it; results must equal the python-tier sharded run."""

    def test_sharded_group_with_vans_matches_python_tier(self):
        from hetu_tpu.ps.van import van_available
        if not van_available():
            pytest.skip("no C++ toolchain")
        rng = np.random.RandomState(3)
        table = rng.randn(12, 4).astype(np.float32)
        ids = np.array([2, 7, 7, 0, 5, 11], np.int64)
        rows = rng.randn(6, 4).astype(np.float32)

        # python-tier reference result
        servers_py, c_py = _group(2)
        c_py.param_set("t", table, opt="sgd",
                       opt_args={"learning_rate": 0.5})
        want = c_py.sd_pushpull("t", ids, rows)

        # van-enabled group: every shard's table autoserves (inside
        # the try: a failing second enable must still shut down the
        # first server's bound van)
        servers_v, c_v = _group(2)
        try:
            for s in servers_v:
                s.enable_van_autoserve()
            c_v.param_set("t", table, opt="sgd",
                          opt_args={"learning_rate": 0.5})
            got = c_v.sd_pushpull("t", ids, rows)
            np.testing.assert_allclose(got, np.asarray(want),
                                       rtol=1e-6, atol=1e-6)
            # both shards really serve their half from the van, and
            # EVERY home client opened a fast-tier socket (the ids
            # route traffic to both shards — a single silent python-
            # tier fallback is exactly the regression under test)
            assert all(s._van_keys for s in servers_v)
            assert all(cl._van_clients for cl in c_v.clients)
            np.testing.assert_allclose(c_v.pull("t"),
                                       np.asarray(c_py.pull("t")),
                                       rtol=1e-6, atol=1e-6)
        finally:
            for s in servers_v:
                s.shutdown()
