"""Model-zoo smoke + convergence tests.

Mirrors the reference's example-level integration testing (SURVEY.md §4):
every model family builds, runs a jitted train step, produces a finite
loss, and the loss decreases over a few steps on random-but-fixed data.
"""

import numpy as np
import pytest

import hetu_tpu as ht
from hetu_tpu import models


def _train_steps(loss, train_op, feeds, n_steps=3):
    ex = ht.Executor({"train": [loss, train_op]})
    losses = []
    for _ in range(n_steps):
        out = ex.run("train", feed_dict=feeds)
        losses.append(float(np.asarray(out[0]).reshape(-1)[0]))
    return losses


def _onehot(labels, n):
    return np.eye(n, dtype=np.float32)[labels]


def _check(losses):
    assert all(np.isfinite(l) for l in losses), losses
    assert losses[-1] < losses[0], losses


class TestDenseModels:
    def _run(self, builder, in_dim=784, n_cls=10, bs=16, **kw):
        rng = np.random.RandomState(0)
        x = ht.placeholder_op("x")
        y_ = ht.placeholder_op("y_")
        loss, pred = builder(x, y_, **kw)
        opt = ht.optim.SGDOptimizer(learning_rate=0.1)
        train = opt.minimize(loss)
        feeds = {x: rng.randn(bs, in_dim).astype(np.float32),
                 y_: _onehot(rng.randint(0, n_cls, bs), n_cls)}
        _check(_train_steps(loss, train, feeds, n_steps=4))

    def test_mlp(self):
        self._run(models.mlp)

    def test_logreg(self):
        self._run(models.logreg)

    def test_rnn(self):
        self._run(models.rnn)

    def test_lstm(self):
        self._run(models.lstm)


class TestConvModels:
    def _run(self, builder, shape=(4, 3, 32, 32), n_cls=10, lr=0.01, **kw):
        rng = np.random.RandomState(0)
        x = ht.placeholder_op("x")
        y_ = ht.placeholder_op("y_")
        loss, pred = builder(x, y_, **kw)
        opt = ht.optim.SGDOptimizer(learning_rate=lr)
        train = opt.minimize(loss)
        feeds = {x: rng.randn(*shape).astype(np.float32) * 0.1,
                 y_: _onehot(rng.randint(0, n_cls, shape[0]), n_cls)}
        _check(_train_steps(loss, train, feeds, n_steps=4))

    def test_cnn_3_layers(self):
        self._run(models.cnn_3_layers, shape=(4, 784))

    def test_lenet(self):
        self._run(models.lenet, shape=(4, 784))

    def test_resnet18(self):
        self._run(models.resnet18)

    def test_resnet34_builds(self):
        # build-only (34 layers is slow to run repeatedly on CPU CI)
        x = ht.placeholder_op("x")
        y_ = ht.placeholder_op("y_")
        loss, pred = models.resnet34(x, y_)
        assert loss is not None

    def test_resnet50_bottleneck_builds(self):
        x = ht.placeholder_op("x")
        y_ = ht.placeholder_op("y_")
        loss, pred = models.resnet50(x, y_)
        assert loss is not None

    def test_resnet101_and_152_build(self):
        # full reference depth coverage (ResNet.py plans table)
        for fn in (models.resnet101, models.resnet152):
            x = ht.placeholder_op("x")
            y_ = ht.placeholder_op("y_")
            loss, pred = fn(x, y_)
            assert loss is not None

    def test_alexnet(self):
        self._run(models.alexnet, lr=1e-4)

    def test_vgg16_builds(self):
        x = ht.placeholder_op("x")
        y_ = ht.placeholder_op("y_")
        loss, pred = models.vgg16(x, y_)
        assert loss is not None


class TestBert:
    def test_pretraining_loss_decreases(self):
        cfg = models.BertConfig(
            vocab_size=128, hidden_size=32, num_hidden_layers=2,
            num_attention_heads=2, intermediate_size=64,
            max_position_embeddings=32, batch_size=2, seq_len=16,
            hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0)
        model = models.BertForPreTraining(cfg)
        rng = np.random.RandomState(0)
        ids = ht.placeholder_op("input_ids")
        tok = ht.placeholder_op("token_type_ids")
        mask = ht.placeholder_op("attention_mask")
        mlm = ht.placeholder_op("masked_lm_labels")
        nsp = ht.placeholder_op("next_sentence_label")
        loss, _, _ = model(ids, tok, mask, mlm, nsp)
        train = ht.optim.AdamOptimizer(learning_rate=1e-3).minimize(loss)
        feeds = {
            ids: rng.randint(0, 128, (2, 16)).astype(np.int32),
            tok: np.zeros((2, 16), np.int32),
            mask: np.ones((2, 16), np.float32),
            mlm: rng.randint(0, 128, (2, 16)).astype(np.int32),
            nsp: rng.randint(0, 2, (2,)).astype(np.int32),
        }
        _check(_train_steps(loss, train, feeds, n_steps=5))

    def test_kv_lens_flash_matches_additive_mask(self):
        """Padded BERT: the flash kernel's kv_lens path, the unfused
        lens->mask fallback, and the reference-style additive (B,S) 0/1
        mask must all produce the same trajectory."""
        B, S = 4, 32
        rng = np.random.RandomState(0)
        IDS = rng.randint(0, 100, (B, S)).astype(np.int32)
        LENS = np.array([32, 20, 7, 1], np.int32)
        PREFIX = (np.arange(S)[None, :] < LENS[:, None]).astype(np.float32)
        LBL = rng.randint(0, 2, (B,)).astype(np.int32)

        def run(flash, use_lens):
            cfg = models.BertConfig(
                vocab_size=100, hidden_size=32, num_hidden_layers=1,
                num_attention_heads=2, intermediate_size=64,
                seq_len=S, batch_size=B, hidden_dropout_prob=0.0,
                attention_probs_dropout_prob=0.0,
                use_flash_attention=flash)
            ids = ht.placeholder_op("ids")
            lbl = ht.placeholder_op("lbl")
            model = models.BertForSequenceClassification(cfg, num_labels=2)
            feeds = {ids: IDS, lbl: LBL}
            if use_lens:
                lens = ht.placeholder_op("lens")
                loss, _ = model(ids, labels=lbl, kv_lens=lens)
                feeds[lens] = LENS
            else:
                mask = ht.placeholder_op("mask")
                loss, _ = model(ids, labels=lbl, attention_mask=mask)
                feeds[mask] = PREFIX
            train = ht.optim.SGDOptimizer(learning_rate=0.1).minimize(loss)
            ex = ht.Executor({"train": [loss, train]}, seed=1)
            return [float(ex.run("train", feed_dict=feeds)[0])
                    for _ in range(4)]

        flash_lens = run(True, True)
        unfused_lens = run(False, True)
        additive = run(False, False)
        np.testing.assert_allclose(flash_lens, unfused_lens,
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(flash_lens, additive,
                                   rtol=1e-3, atol=1e-4)

    def test_sequence_classification(self):
        cfg = models.BertConfig(
            vocab_size=64, hidden_size=16, num_hidden_layers=1,
            num_attention_heads=2, intermediate_size=32,
            max_position_embeddings=16, batch_size=2, seq_len=8,
            hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0)
        model = models.BertForSequenceClassification(cfg, num_labels=3)
        rng = np.random.RandomState(0)
        ids = ht.placeholder_op("input_ids")
        labels = ht.placeholder_op("labels")
        loss, logits = model(ids, labels=labels)
        train = ht.optim.AdamOptimizer(learning_rate=1e-3).minimize(loss)
        feeds = {ids: rng.randint(0, 64, (2, 8)).astype(np.int32),
                 labels: rng.randint(0, 3, (2,)).astype(np.int32)}
        _check(_train_steps(loss, train, feeds, n_steps=5))


class TestTransformer:
    def test_mt_loss_decreases(self):
        cfg = models.TransformerConfig(
            src_vocab_size=64, tgt_vocab_size=64, hidden_size=16,
            num_layers=1, num_heads=2, ffn_size=32, dropout_rate=0.0,
            batch_size=2, src_len=8, tgt_len=8)
        model = models.Transformer(cfg)
        rng = np.random.RandomState(0)
        src = ht.placeholder_op("src")
        tgt = ht.placeholder_op("tgt")
        labels = ht.placeholder_op("labels")
        loss, logits = model(src, tgt, labels)
        train = ht.optim.AdamOptimizer(learning_rate=1e-3).minimize(loss)
        feeds = {src: rng.randint(1, 64, (2, 8)).astype(np.int32),
                 tgt: rng.randint(1, 64, (2, 8)).astype(np.int32),
                 labels: rng.randint(1, 64, (2, 8)).astype(np.int32)}
        _check(_train_steps(loss, train, feeds, n_steps=5))


class TestCTRModels:
    def test_wdl_adult(self):
        rng = np.random.RandomState(0)
        bs = 8
        X_deep = [ht.placeholder_op(f"xd{i}") for i in range(12)]
        X_wide = ht.placeholder_op("x_wide")
        y_ = ht.placeholder_op("y_")
        loss, pred, _, train = models.wdl_adult(X_deep, X_wide, y_)
        feeds = {X_wide: rng.randn(bs, 809).astype(np.float32),
                 y_: _onehot(rng.randint(0, 2, bs), 2)}
        for i in range(8):
            feeds[X_deep[i]] = rng.randint(0, 50, (bs,)).astype(np.int32)
        for i in range(8, 12):
            feeds[X_deep[i]] = rng.randn(bs).astype(np.float32)
        _check(_train_steps(loss, train, feeds, n_steps=4))

    def _run_criteo(self, builder, **kw):
        rng = np.random.RandomState(0)
        bs = 8
        dense = ht.placeholder_op("dense")
        sparse = ht.placeholder_op("sparse")
        y_ = ht.placeholder_op("y_")
        loss, pred, _, train = builder(
            dense, sparse, y_, feature_dimension=1000, embedding_size=8,
            **kw)
        feeds = {dense: rng.randn(bs, 13).astype(np.float32),
                 sparse: rng.randint(0, 1000, (bs, 26)).astype(np.int32),
                 y_: rng.randint(0, 2, (bs, 1)).astype(np.float32)}
        _check(_train_steps(loss, train, feeds, n_steps=4))

    def test_wdl_criteo(self):
        self._run_criteo(models.wdl_criteo)

    def test_dcn_criteo(self):
        self._run_criteo(models.dcn_criteo)

    def test_deepfm_criteo(self):
        self._run_criteo(models.deepfm_criteo)

    def test_dc_criteo(self):
        self._run_criteo(models.dc_criteo)


class TestNCF:
    def test_neural_mf(self):
        rng = np.random.RandomState(0)
        bs = 16
        user = ht.placeholder_op("user")
        item = ht.placeholder_op("item")
        y_ = ht.placeholder_op("y_")
        loss, pred, train = models.neural_mf(user, item, y_, num_users=100,
                                             num_items=200, lr=0.5)
        feeds = {user: rng.randint(0, 100, (bs,)).astype(np.int32),
                 item: rng.randint(0, 200, (bs,)).astype(np.int32),
                 y_: rng.randint(0, 2, (bs, 1)).astype(np.float32)}
        _check(_train_steps(loss, train, feeds, n_steps=4))


class TestMoEModels:
    @pytest.mark.parametrize("gate_type", ["top", "hash"])
    def test_moe_mlp(self, gate_type):
        rng = np.random.RandomState(0)
        bs, toks, dim, n_cls = 2, 8, 16, 16
        x = ht.placeholder_op("x")
        y_ = ht.placeholder_op("y_")
        loss, y = models.moe_mlp(
            x, y_, batch_size=bs, num_tokens=toks, model_dim=dim,
            hidden_size=32, num_local_experts=2, gate_type=gate_type)
        train = ht.optim.SGDOptimizer(learning_rate=0.1).minimize(loss)
        feeds = {x: rng.randn(bs, toks, dim).astype(np.float32),
                 y_: _onehot(rng.randint(0, n_cls, bs * toks), n_cls)}
        losses = _train_steps(loss, train, feeds, n_steps=4)
        assert all(np.isfinite(l) for l in losses)

    def test_moe_transformer_block(self):
        rng = np.random.RandomState(0)
        bs, seq, dim = 2, 8, 16
        x = ht.placeholder_op("x")
        out = models.moe_transformer_block(
            x, batch_size=bs, seq_len=seq, model_dim=dim, num_heads=2,
            hidden_size=32, num_local_experts=2)
        ex = ht.Executor({"fwd": [out]})
        res = ex.run("fwd", feed_dict={
            x: rng.randn(bs * seq, dim).astype(np.float32)})
        assert np.isfinite(np.asarray(res[0])).all()
        assert np.asarray(res[0]).shape == (bs * seq, dim)
