"""HuggingFace checkpoint import parity (hetu_tpu/hf.py): the SAME
random transformers weights produce the SAME outputs through torch and
through this framework's executor — numerical validation of the BERT
and GPT-2 families against the canonical implementations (beyond the
reference, which has no pretrained-weight interop)."""

import numpy as np
import pytest

import hetu_tpu as ht

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")


def _bert_pair(hf_cls=None, max_pos=16, batch=2, seq=8, seed=0, **hf_kw):
    """Matched (HF model, our BertConfig) pair — ONE source of truth for
    the parity-critical knobs (sizes pinned, dropout 0, gelu_new)."""
    from transformers import BertConfig as HFC
    from transformers import BertModel as HFM
    hf_cfg = HFC(vocab_size=120, hidden_size=32, num_hidden_layers=2,
                 num_attention_heads=2, intermediate_size=64,
                 max_position_embeddings=max_pos, hidden_act="gelu_new",
                 hidden_dropout_prob=0.0,
                 attention_probs_dropout_prob=0.0, **hf_kw)
    torch.manual_seed(seed)
    hf = (hf_cls or HFM)(hf_cfg).eval()
    from hetu_tpu.models import BertConfig
    cfg = BertConfig(vocab_size=120, hidden_size=32, num_hidden_layers=2,
                     num_attention_heads=2, intermediate_size=64,
                     max_position_embeddings=max_pos, batch_size=batch,
                     seq_len=seq, hidden_dropout_prob=0.0,
                     attention_probs_dropout_prob=0.0)
    return hf, cfg


def _feed():
    rng = np.random.RandomState(0)
    ids = rng.randint(0, 120, (2, 8))
    tt = np.zeros((2, 8))
    return ids, tt


class TestBertImport:
    def test_backbone_forward_parity(self):
        hf, cfg = _bert_pair()
        ids_np, tt_np = _feed()
        with torch.no_grad():
            o = hf(input_ids=torch.tensor(ids_np),
                   token_type_ids=torch.tensor(tt_np.astype(np.int64)))
        from hetu_tpu.models import BertModel
        m = BertModel(cfg, name="hfb")
        ids = ht.placeholder_op("hfb_ids")
        tt = ht.placeholder_op("hfb_tt")
        seq, pooled = m(ids, tt)
        ex = ht.Executor({"fwd": [seq, pooled]})
        params = ht.hf.convert_bert(hf.state_dict(), name="hfb")
        ex.load_dict(params)   # load_dict skips unknown keys itself
        got_seq, got_pool = ex.run(
            "fwd", feed_dict={ids: ids_np.astype(np.int32),
                              tt: tt_np.astype(np.int32)},
            convert_to_numpy_ret_vals=True)
        np.testing.assert_allclose(
            got_seq, o.last_hidden_state.numpy().reshape(16, 32),
            atol=2e-4)
        np.testing.assert_allclose(got_pool, o.pooler_output.numpy(),
                                   atol=2e-4)

    def test_pretraining_heads_logit_parity(self):
        from transformers import BertForPreTraining as HFPre
        hf, cfg = _bert_pair(hf_cls=HFPre)
        ids_np, tt_np = _feed()
        with torch.no_grad():
            o = hf(input_ids=torch.tensor(ids_np),
                   token_type_ids=torch.tensor(tt_np.astype(np.int64)))
        from hetu_tpu.models import BertForPreTraining
        m = BertForPreTraining(cfg, name="hfp")
        ids = ht.placeholder_op("hfp_ids")
        tt = ht.placeholder_op("hfp_tt")
        logits, nsp_logits = m(ids, tt)
        ex = ht.Executor({"fwd": [logits, nsp_logits]})
        params = ht.hf.convert_bert_pretraining_heads(hf.state_dict(),
                                                      name="hfp")
        missing = set(ex.var_values) - set(params)
        assert not missing, missing
        ex.load_dict(params)
        got_mlm, got_nsp = ex.run(
            "fwd", feed_dict={ids: ids_np.astype(np.int32),
                              tt: tt_np.astype(np.int32)},
            convert_to_numpy_ret_vals=True)
        # fp32 accumulation through the [*, vocab] head matmul widens
        # the backbone's ~1e-4 to ~1e-3 on logit scale
        np.testing.assert_allclose(
            got_mlm, o.prediction_logits.numpy().reshape(16, 120),
            atol=2e-3)
        np.testing.assert_allclose(
            got_nsp, o.seq_relationship_logits.numpy(), atol=2e-4)


def _gpt2_pair(lm=False, seed=1):
    """Matched (HF GPT-2 model, our GPTConfig) — one source of truth."""
    from transformers import GPT2Config as HFC
    from transformers import GPT2LMHeadModel as HFLM
    from transformers import GPT2Model as HFM
    hf_cfg = HFC(vocab_size=130, n_embd=32, n_layer=2, n_head=2,
                 n_positions=16, resid_pdrop=0.0, embd_pdrop=0.0,
                 attn_pdrop=0.0)
    torch.manual_seed(seed)
    hf = (HFLM if lm else HFM)(hf_cfg).eval()
    from hetu_tpu.models import GPTConfig
    cfg = GPTConfig(vocab_size=130, hidden_size=32,
                    num_hidden_layers=2, num_attention_heads=2,
                    max_position_embeddings=16, batch_size=2,
                    seq_len=8, dropout_rate=0.0)
    return hf, cfg


class TestGPT2Import:
    def _pair(self, lm=False):
        return _gpt2_pair(lm=lm)

    def test_backbone_forward_parity(self):
        hf, cfg = self._pair()
        rng = np.random.RandomState(0)
        ids_np = rng.randint(0, 130, (2, 8))
        with torch.no_grad():
            o = hf(input_ids=torch.tensor(ids_np))
        from hetu_tpu.models import GPTModel
        m = GPTModel(cfg, name="hfg")
        ids = ht.placeholder_op("hfg_ids")
        h = m(ids)
        ex = ht.Executor({"fwd": [h]})
        params = ht.hf.convert_gpt2(hf.state_dict(), name="hfg")
        missing = set(ex.var_values) - set(params)
        assert not missing, missing
        ex.load_dict(params)
        got = ex.run("fwd", feed_dict={ids: ids_np.astype(np.int32)},
                     convert_to_numpy_ret_vals=True)[0]
        np.testing.assert_allclose(
            got, o.last_hidden_state.numpy().reshape(16, 32), atol=2e-5)

    def test_lm_logits_parity_through_tied_head(self):
        hf, cfg = self._pair(lm=True)
        rng = np.random.RandomState(0)
        ids_np = rng.randint(0, 130, (2, 8))
        with torch.no_grad():
            o = hf(input_ids=torch.tensor(ids_np))
        from hetu_tpu.models import GPTForCausalLM
        m = GPTForCausalLM(cfg, name="hfl")
        ids = ht.placeholder_op("hfl_ids")
        logits = m(ids)
        ex = ht.Executor({"fwd": [logits]})
        params = ht.hf.convert_gpt2(hf.state_dict(), name="hfl",
                                    prefix="transformer.")
        # our head bias is a fresh zero param; HF's tied head has none
        ex.load_dict(params)
        got = ex.run("fwd", feed_dict={ids: ids_np.astype(np.int32)},
                     convert_to_numpy_ret_vals=True)[0]
        np.testing.assert_allclose(
            got, o.logits.numpy().reshape(16, 130), atol=5e-4)


class TestBertClassifierImport:
    def test_seqclass_logit_parity_and_finetune(self):
        """The real user story: an HF classification checkpoint imports
        with logit parity AND then fine-tunes through our GLUE pipeline
        (loss drops on the SST-2 fixture)."""
        import os
        from transformers import BertForSequenceClassification as HFSC
        from hetu_tpu.models import BertForSequenceClassification
        hf, cfg = _bert_pair(hf_cls=HFSC, max_pos=32, batch=4, seq=16,
                             seed=5, num_labels=2)
        m = BertForSequenceClassification(cfg, num_labels=2, name="hfc")
        ids = ht.placeholder_op("hfc_ids")
        tt = ht.placeholder_op("hfc_tt")
        mask = ht.placeholder_op("hfc_mask")
        labels = ht.placeholder_op("hfc_y")
        loss, logits = m(ids, tt, mask, labels=labels)
        train = ht.optim.AdamOptimizer(learning_rate=2e-3).minimize(loss)
        ex = ht.Executor({"train": [loss, train], "eval": [logits]})
        params = ht.hf.convert_bert_classifier(hf.state_dict(),
                                               name="hfc")
        missing = set(ex.var_values) - set(params)
        assert not missing, missing
        ex.load_dict(params)

        rng = np.random.RandomState(0)
        iv = rng.randint(0, 120, (4, 16))
        tv = np.zeros((4, 16))
        with torch.no_grad():
            want = hf(input_ids=torch.tensor(iv),
                      token_type_ids=torch.tensor(
                          tv.astype(np.int64))).logits.numpy()
        got = ex.run("eval", feed_dict={
            ids: iv.astype(np.int32), tt: tv.astype(np.int32),
            mask: np.ones((4, 16), np.float32)},
            convert_to_numpy_ret_vals=True)[0]
        np.testing.assert_allclose(got, want, atol=3e-4)

        # fine-tune the imported weights on the SST-2 fixture
        from hetu_tpu.glue import (Sst2Processor,
                                   convert_examples_to_arrays)
        from hetu_tpu.tokenizers import BertTokenizer
        FIX = os.path.join(os.path.dirname(__file__), "fixtures", "glue")
        tok = BertTokenizer.from_pretrained(
            os.path.join(FIX, "vocab.txt"))
        proc = Sst2Processor()
        exs = proc.get_train_examples(os.path.join(FIX, "SST-2"))
        g_ids, g_mask, g_seg, g_y = convert_examples_to_arrays(
            exs, proc.get_labels(), 16, tok)
        g_ids = g_ids % 120                 # fixture vocab -> model vocab
        losses = []
        srng = np.random.RandomState(2)
        for step in range(150):
            sel = srng.choice(len(g_ids), 4, replace=False)
            out = ex.run("train", feed_dict={
                ids: g_ids[sel], tt: g_seg[sel], mask: g_mask[sel],
                labels: g_y[sel]})
            losses.append(float(np.asarray(out[0])))
        assert all(np.isfinite(v) for v in losses)
        assert np.mean(losses[-10:]) < np.mean(losses[:10]), (
            losses[:5], losses[-5:])

        # r5: the fine-tuned classifier exports back — re-import is
        # bit-exact and torch serves the trained model
        ours = ex.return_tensor_values()
        sd = ht.hf.export_bert_classifier(ours, name="hfc")
        back_params = ht.hf.convert_bert_classifier(sd, name="hfc")
        for k, v in ours.items():
            np.testing.assert_array_equal(np.asarray(back_params[k]),
                                          np.asarray(v), err_msg=k)
        missing, unexpected = hf.load_state_dict(sd, strict=False)
        assert not unexpected, unexpected
        with torch.no_grad():
            back = hf(input_ids=torch.tensor(iv),
                      token_type_ids=torch.tensor(
                          tv.astype(np.int64))).logits.numpy()
        ours_logits = ex.run("eval", feed_dict={
            ids: iv.astype(np.int32), tt: tv.astype(np.int32),
            mask: np.ones((4, 16), np.float32)},
            convert_to_numpy_ret_vals=True)[0]
        np.testing.assert_allclose(back, ours_logits, atol=2e-2)


class TestExportToHF:
    """The reverse trip: OUR parameters load into transformers and
    torch reproduces our forward — models trained here are usable in
    the HF ecosystem."""

    def test_gpt2_roundtrip_through_torch(self):
        from hetu_tpu.models import GPTModel
        hf, cfg = _gpt2_pair()       # hf is reloaded from OUR weights
        m = GPTModel(cfg, name="xg")
        ids = ht.placeholder_op("xg_ids")
        h = m(ids)
        ex = ht.Executor({"fwd": [h]})     # OUR random init
        rng = np.random.RandomState(4)
        iv = rng.randint(0, 130, (2, 8))
        ours = ex.run("fwd", feed_dict={ids: iv.astype(np.int32)},
                      convert_to_numpy_ret_vals=True)[0]

        sd = ht.hf.export_gpt2(ex.var_values, name="xg")
        missing, unexpected = hf.load_state_dict(sd, strict=False)
        assert not unexpected, unexpected
        # ONLY the causal-mask buffers may be absent — a dropped
        # parameter (e.g. a real *.bias) must fail here, not fall back
        # to HF init
        assert all(k.endswith(("attn.bias", "attn.masked_bias"))
                   for k in missing), missing
        with torch.no_grad():
            theirs = hf(input_ids=torch.tensor(iv)).last_hidden_state
        np.testing.assert_allclose(ours,
                                   theirs.numpy().reshape(16, 32),
                                   atol=2e-5)

    def test_bert_export_is_exact_inverse_of_import(self):
        hf, _cfg = _bert_pair()
        params = ht.hf.convert_bert(hf.state_dict(), name="rb")
        back = ht.hf.export_bert(params, name="rb")
        want = hf.state_dict()
        # completeness: every non-buffer HF key must be exported (a
        # silently-partial export would pass a values-only comparison)
        want_keys = {k for k in want
                     if not k.endswith(("attn.bias",
                                        "attn.masked_bias"))}
        assert set(back) == want_keys, \
            want_keys.symmetric_difference(back)
        for k, v in back.items():
            np.testing.assert_array_equal(
                v.numpy(), want[k].numpy(), err_msg=k)


class TestQAImport:
    """r5: the SQuAD half of the HF fine-tune story — a
    BertForQuestionAnswering checkpoint imports with start/end logit
    parity and then trains through our span head."""

    def test_qa_logit_parity_and_span_training(self):
        from transformers import BertForQuestionAnswering as HFQA
        from hetu_tpu.models import BertForQuestionAnswering
        hf, cfg = _bert_pair(hf_cls=HFQA, max_pos=32, batch=4, seq=16,
                             seed=7)
        m = BertForQuestionAnswering(cfg, name="hfq")
        ids = ht.placeholder_op("hfq_ids")
        tt = ht.placeholder_op("hfq_tt")
        mask = ht.placeholder_op("hfq_mask")
        sp = ht.placeholder_op("hfq_sp")
        ep = ht.placeholder_op("hfq_ep")
        loss, s_log, e_log = m(ids, tt, mask, start_positions=sp,
                               end_positions=ep)
        train = ht.optim.AdamOptimizer(learning_rate=2e-3).minimize(loss)
        ex = ht.Executor({"train": [loss, train],
                          "eval": [s_log, e_log]})
        params = ht.hf.convert_bert_qa(hf.state_dict(), name="hfq")
        missing = set(ex.var_values) - set(params)
        assert not missing, missing
        ex.load_dict(params)

        rng = np.random.RandomState(0)
        iv = rng.randint(0, 120, (4, 16))
        tv = np.zeros((4, 16))
        with torch.no_grad():
            want = hf(input_ids=torch.tensor(iv),
                      token_type_ids=torch.tensor(tv.astype(np.int64)))
        feed = {ids: iv.astype(np.int32), tt: tv.astype(np.int32),
                mask: np.ones((4, 16), np.float32),
                sp: np.zeros(4, np.int32), ep: np.zeros(4, np.int32)}
        got_s, got_e = ex.run("eval", feed_dict=feed,
                              convert_to_numpy_ret_vals=True)
        np.testing.assert_allclose(got_s, want.start_logits.numpy(),
                                   atol=3e-4)
        np.testing.assert_allclose(got_e, want.end_logits.numpy(),
                                   atol=3e-4)

        # span supervision flows: training on fixed gold spans drops
        # the loss from the imported initialization
        spans_s = rng.randint(1, 8, 4).astype(np.int32)
        spans_e = (spans_s + rng.randint(0, 4, 4)).astype(np.int32)
        losses = []
        for _ in range(60):
            out = ex.run("train", feed_dict={
                ids: iv.astype(np.int32), tt: tv.astype(np.int32),
                mask: np.ones((4, 16), np.float32),
                sp: spans_s, ep: spans_e})
            losses.append(float(np.asarray(out[0])))
        assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])

        # ...and the TRAINED span head exports back.  The export is an
        # exact inverse (re-importing reproduces our arrays bit-for-
        # bit); the forward comparison is looser because the tiny
        # gelu_new/LN implementation deltas (3e-6 at init) are
        # amplified by 60 Adam steps' weight growth.
        ours = ex.return_tensor_values()
        sd = ht.hf.export_bert_qa(ours, name="hfq")
        back_params = ht.hf.convert_bert_qa(sd, name="hfq")
        for k, v in ours.items():
            np.testing.assert_array_equal(np.asarray(back_params[k]),
                                          np.asarray(v), err_msg=k)
        missing, unexpected = hf.load_state_dict(sd, strict=False)
        assert not unexpected, unexpected
        with torch.no_grad():
            back = hf(input_ids=torch.tensor(iv),
                      token_type_ids=torch.tensor(tv.astype(np.int64)))
        ours_s, ours_e = ex.run("eval", feed_dict=feed,
                                convert_to_numpy_ret_vals=True)
        np.testing.assert_allclose(back.start_logits.numpy(), ours_s,
                                   atol=2e-2)
        np.testing.assert_allclose(back.end_logits.numpy(), ours_e,
                                   atol=2e-2)
