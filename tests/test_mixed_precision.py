"""Mixed-precision (bf16 compute, fp32 masters) tests.

TPU-first feature with no reference counterpart (Hetu trains fp32; the
MXU wants bf16 matmuls — task brief 'keep them large, batched, bfloat16').
"""

import numpy as np
import pytest

import jax.numpy as jnp
import hetu_tpu as ht


def _model(tag):
    x = ht.placeholder_op(f"x_{tag}")
    y = ht.placeholder_op(f"y_{tag}")
    w1 = ht.Variable(f"w1_{tag}", value=np.linspace(
        -0.5, 0.5, 32 * 64).reshape(32, 64).astype(np.float32))
    w2 = ht.Variable(f"w2_{tag}", value=np.linspace(
        0.5, -0.5, 64 * 4).reshape(64, 4).astype(np.float32))
    h = ht.relu_op(ht.matmul_op(x, w1))
    logits = ht.matmul_op(h, w2)
    loss = ht.reduce_mean_op(ht.softmaxcrossentropy_op(logits, y), axes=0)
    train = ht.optim.AdamOptimizer(learning_rate=0.01).minimize(loss)
    return x, y, loss, train


class TestMixedPrecision:
    def test_masters_stay_fp32_loss_reports_fp32(self):
        x, y, loss, train = _model("a")
        ex = ht.Executor({"train": [loss, train]}, mixed_precision="bf16")
        rng = np.random.RandomState(0)
        X = rng.randn(16, 32).astype(np.float32)
        Y = np.eye(4)[rng.randint(0, 4, 16)].astype(np.float32)
        out = ex.run("train", feed_dict={x: X, y: Y})
        assert np.asarray(out[0]).dtype == np.float32
        assert ex.var_values["w1_a"].dtype == jnp.float32

    def test_bf16_trains_close_to_fp32(self):
        rng = np.random.RandomState(1)
        X = rng.randn(64, 32).astype(np.float32)
        Y = np.eye(4)[rng.randint(0, 4, 64)].astype(np.float32)

        x1, y1, l1, t1 = _model("fp32")
        ex1 = ht.Executor({"train": [l1, t1]})
        x2, y2, l2, t2 = _model("bf16")
        ex2 = ht.Executor({"train": [l2, t2]}, mixed_precision="bf16")
        tr1 = [float(ex1.run("train", feed_dict={x1: X, y1: Y})[0])
               for _ in range(30)]
        tr2 = [float(ex2.run("train", feed_dict={x2: X, y2: Y})[0])
               for _ in range(30)]
        # both converge; trajectories agree loosely (bf16 rounding)
        assert tr2[-1] < tr2[0] * 0.8
        assert abs(tr1[-1] - tr2[-1]) < 0.15 * max(tr1[0], 1.0)

    def test_int_feeds_untouched(self):
        ids = ht.placeholder_op("mp_ids")
        table = ht.Variable("mp_table",
                            value=np.random.RandomState(2)
                            .randn(20, 8).astype(np.float32))
        emb = ht.embedding_lookup_op(table, ids)
        out = ht.reduce_sum_op(ht.reduce_sum_op(emb, [2]), [1])
        ex = ht.Executor({"f": [out]}, mixed_precision="bf16")
        res = ex.run("f", feed_dict={
            ids: np.array([[1, 2], [3, 4]], np.int32)})
        assert np.asarray(res[0]).dtype == np.float32

    def test_bf16_conv_bn_trains(self):
        """Conv + BatchNorm under bf16: the conv transpose rule rejects a
        preferred_element_type=f32 cotangent against a bf16 filter
        (caught benching ResNet-18 bf16) — pin the whole conv/BN train
        step working under the policy."""
        x = ht.placeholder_op("cmp_x")
        y = ht.placeholder_op("cmp_y")
        h = ht.conv2d_op(x, ht.init.xavier_uniform((8, 3, 3, 3),
                                                   name="cmp_k"),
                         stride=1, padding=1)
        h = ht.layers.BatchNorm(8, name="cmp_bn")(h)
        h = ht.relu_op(h)
        h = ht.reduce_mean_op(h, [2, 3])
        logits = ht.matmul_op(h, ht.init.xavier_uniform(
            (8, 4), name="cmp_w"))
        loss = ht.reduce_mean_op(
            ht.softmaxcrossentropy_op(logits, y), axes=0)
        train = ht.optim.SGDOptimizer(learning_rate=0.1).minimize(loss)
        ex = ht.Executor({"train": [loss, train]},
                         mixed_precision="bf16")
        rng = np.random.RandomState(0)
        xb = rng.randn(8, 3, 16, 16).astype(np.float32)
        yb = np.eye(4, dtype=np.float32)[rng.randint(0, 4, 8)]
        tr = [float(np.asarray(ex.run("train", feed_dict={x: xb, y: yb})[0]))
              for _ in range(5)]
        assert np.all(np.isfinite(tr))
        assert tr[-1] < tr[0]

    def test_batchnorm_running_stats_stay_fp32(self):
        x = ht.placeholder_op("mp_bn_x")
        bn = ht.layers.BatchNorm(4, name="mp_bn")
        h = bn(x)
        loss = ht.reduce_mean_op(ht.reduce_sum_op(ht.mul_op(h, h), [1]),
                                 [0])
        train = ht.optim.SGDOptimizer(learning_rate=0.01).minimize(loss)
        ex = ht.Executor({"train": [loss, train]}, mixed_precision="bf16")
        X = np.random.RandomState(3).randn(8, 4).astype(np.float32)
        ex.run("train", feed_dict={x: X})
        for name, v in ex.var_values.items():
            if "mp_bn" in name:
                assert v.dtype == jnp.float32, (name, v.dtype)
