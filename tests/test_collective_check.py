"""Collective-ordering validator tests (SURVEY.md §5.2 — the one
sanitizer worth building on TPU: catch shard_map cond-branch collective
divergence before running)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from hetu_tpu.parallel.mesh import make_mesh
from hetu_tpu.parallel.collective_check import (CollectiveOrderError,
                                                check_collective_order)


@pytest.fixture(scope="module")
def mesh():
    return make_mesh({"dp": 8})


class TestCheck:
    def test_straightline_sequence_reported(self, mesh):
        def body(x):
            y = jax.lax.psum(x, "dp")
            z = jax.lax.ppermute(y, "dp",
                                 [(i, (i + 1) % 8) for i in range(8)])
            return z

        seq = check_collective_order(body, mesh, P("dp"), P("dp"),
                                     [jnp.ones(8)])
        prims = [s[0] for s in seq]
        assert any("psum" in p for p in prims)
        assert "ppermute" in prims

    def test_divergent_cond_branch_flagged(self, mesh):
        # jax's varying-manual-axes type check rejects this at trace time
        # (TypeError); our checker flags anything that slips past as
        # CollectiveOrderError — either way the deadlock is caught before
        # running
        def body(x):
            i = jax.lax.axis_index("dp")
            return jax.lax.cond(i < 4,
                                lambda v: jax.lax.psum(v, "dp"),
                                lambda v: v * 2.0, x)

        with pytest.raises((CollectiveOrderError, TypeError)):
            check_collective_order(body, mesh, P("dp"), P("dp"),
                                   [jnp.ones(8)])

    def test_same_type_different_order_flagged(self, mesh):
        # both branches type-check (jax accepts) but issue collectives in
        # different orders — only this checker catches it
        perm = [(i, (i + 1) % 8) for i in range(8)]

        def b0(v):
            return jax.lax.ppermute(jax.lax.psum(v, "dp") + 0 * v,
                                    "dp", perm)

        def b1(v):
            return jax.lax.psum(jax.lax.ppermute(v, "dp", perm),
                                "dp") * 0 + 0 * v + \
                jax.lax.ppermute(0 * v, "dp", perm)

        def body(x):
            i = jax.lax.axis_index("dp")
            return jax.lax.cond(i < 4, b0, b1, x)

        try:
            with pytest.raises(CollectiveOrderError):
                check_collective_order(body, mesh, P("dp"), P("dp"),
                                       [jnp.ones(8)])
        except TypeError:
            pytest.skip("jax rejected at trace time (also acceptable)")

    def test_matching_cond_branches_pass(self, mesh):
        def body(x):
            i = jax.lax.axis_index("dp")
            return jax.lax.cond(i < 4,
                                lambda v: jax.lax.psum(v * 2, "dp"),
                                lambda v: jax.lax.psum(v + 1, "dp"), x)

        seq = check_collective_order(body, mesh, P("dp"), P("dp"),
                                     [jnp.ones(8)])
        assert len([s for s in seq if "psum" in s[0]]) == 1

    def test_scan_bodies_walked(self, mesh):
        def body(x):
            def tick(c, _):
                # psum output is axis-invariant; pcast restores the carry's
                # varying-axes type so scan's carry typing is stable
                return jax.lax.pcast(jax.lax.psum(c, "dp"), "dp",
                                     to="varying"), None
            out, _ = jax.lax.scan(tick, x, jnp.arange(3))
            return out

        seq = check_collective_order(body, mesh, P("dp"), P("dp"),
                                     [jnp.ones(8)])
        assert any("psum" in s[0] for s in seq)

    def test_spmd_pipeline_body_is_clean(self, mesh):
        """The framework's own scan pipeline must pass its own check."""
        pp_mesh = make_mesh({"pp": 4, "dp": 2})

        def body(x):
            return jax.lax.ppermute(
                x, "pp", [(i, (i + 1) % 4) for i in range(4)])

        seq = check_collective_order(body, pp_mesh, P("pp"), P("pp"),
                                     [jnp.ones((4, 2))])
        assert seq[0][0] == "ppermute"
