"""Tier-1 numeric coverage for op factories the main suites exercise only
indirectly (reference pattern: tests/test_ops.py HetuTester vs numpy,
test_ops.py:7-80 — every factory gets a direct numpy-oracle check).

Each case builds the op on placeholders, runs it through the Executor,
and asserts allclose against a numpy oracle.
"""

import numpy as np
import pytest

import hetu_tpu as ht


def _run(build, feeds_np, n_out=1):
    """build(placeholders...) -> node; returns numpy output."""
    phs = [ht.placeholder_op(f"c{i}") for i in range(len(feeds_np))]
    out = build(*phs)
    ex = ht.Executor({"t": [out]})
    (res,) = ex.run("t", feed_dict=dict(zip(phs, feeds_np)),
                    convert_to_numpy_ret_vals=True)
    return res


R = np.random.RandomState(0)
A = R.uniform(0.2, 1.5, (4, 6)).astype(np.float32)       # positive
B_ = R.uniform(-1, 1, (4, 6)).astype(np.float32)
G = R.uniform(-1, 1, (4, 6)).astype(np.float32)
M3 = R.uniform(-1, 1, (2, 3, 4)).astype(np.float32)
N3 = R.uniform(-1, 1, (2, 4, 5)).astype(np.float32)
I6 = R.randint(0, 6, (4, 6)).astype(np.int32)
MASK = (R.rand(4, 6) > 0.5).astype(np.float32)


def _sigmoid(x):
    return 1.0 / (1.0 + np.exp(-x))


CASES = [
    # ---- math / elementwise ----
    ("log", lambda x: ht.log_op(x), [A], lambda x: np.log(x)),
    ("log_grad", lambda g, x: ht.log_grad_op(g, x), [G, A],
     lambda g, x: g / x),
    ("rsqrt", lambda x: ht.rsqrt_op(x), [A], lambda x: 1 / np.sqrt(x)),
    ("ceil", lambda x: ht.ceil_op(x), [B_], np.ceil),
    ("sign", lambda x: ht.sign_op(x), [B_], np.sign),
    ("minus_byconst", lambda x: ht.minus_byconst_op(2.0, x), [B_],
     lambda x: 2.0 - x),
    ("div_const", lambda x: ht.div_const_op(3.0, x), [A],
     lambda x: 3.0 / x),
    ("const_pow", lambda x: ht.const_pow_op(2.0, x), [B_],
     lambda x: np.power(2.0, x)),
    ("const_pow_grad", lambda g, x: ht.const_pow_gradient_op(g, x, 2.0),
     [G, B_], lambda g, x: g * np.power(2.0, x) * np.log(2.0)),
    ("pow_grad", lambda g, x: ht.pow_gradient_op(g, x, 3.0), [G, A],
     lambda g, x: g * 3.0 * np.power(x, 2.0)),
    ("abs_grad", lambda g, x: ht.abs_gradient_op(g, x), [G, B_],
     lambda g, x: g * np.sign(x)),
    ("relu_grad", lambda x, g: ht.relu_gradient_op(x, g), [B_, G],
     lambda x, g: g * (x > 0)),
    ("leaky_relu_grad", lambda x, g: ht.leaky_relu_gradient_op(x, g, 0.1),
     [B_, G], lambda x, g: g * np.where(x > 0, 1.0, 0.1)),
    ("tanh_grad", lambda y, g: ht.tanh_gradient_op(y, g), [B_, G],
     lambda y, g: g * (1 - y * y)),
    ("min", lambda x, y: ht.min_op(x, y), [A, B_], np.minimum),
    ("bool_lt", lambda x, y: ht.bool_op(x, y, cond=1), [B_, A],
     lambda x, y: (x < y).astype(np.float32)),
    ("where_const", lambda c, x: ht.where_const_op(c, x, 7.0), [MASK, B_],
     lambda c, x: np.where(c.astype(bool), x, 7.0)),
    ("masked_fill", lambda x, m: ht.masked_fill_op(x, m, val=9.0),
     [B_, MASK], lambda x, m: np.where(m.astype(bool), 9.0, x)),
    ("log_softmax", lambda x: ht.log_softmax_op(x), [B_],
     lambda x: x - x.max(-1, keepdims=True)
     - np.log(np.exp(x - x.max(-1, keepdims=True)).sum(-1, keepdims=True))),
    ("softmax_grad", lambda y, g: ht.softmax_gradient_op(y, g),
     [MASK / MASK.sum(-1, keepdims=True), G],
     lambda y, g: y * (g - (g * y).sum(-1, keepdims=True))),
    ("gelu_grad", lambda x, g: ht.gelu_gradient_op(x, g), [B_, G], None),
    # ---- matmul family ----
    ("addmm", lambda i, x, y: ht.addmm_op(i, x, y, alpha=2.0, beta=0.5),
     [R.randn(4, 5).astype(np.float32), R.randn(4, 3).astype(np.float32),
      R.randn(3, 5).astype(np.float32)],
     lambda i, x, y: 0.5 * i + 2.0 * (x @ y)),
    ("baddbmm", lambda i, x, y: ht.baddbmm_op(i, x, y, alpha=1.5, beta=2.0),
     [R.randn(2, 3, 5).astype(np.float32), M3, N3],
     lambda i, x, y: 2.0 * i + 1.5 * np.matmul(x, y)),
    ("matrix_dot", lambda x, y: ht.matrix_dot_op(x, y), [A, B_],
     lambda x, y: x * y),
    ("outer", lambda x, y: ht.outer_op(x, y),
     [R.randn(4).astype(np.float32), R.randn(5).astype(np.float32)],
     np.outer),
    # ---- losses ----
    ("bce_logits", lambda z, y: ht.binarycrossentropywithlogits_op(z, y),
     [B_, MASK],
     lambda z, y: np.maximum(z, 0) - z * y + np.log1p(np.exp(-np.abs(z)))),
    ("nll", lambda lp, y: ht.nll_loss_op(lp, y),
     [np.log(A / A.sum(-1, keepdims=True)),
      R.randint(0, 6, (4,)).astype(np.int32)],
     lambda lp, y: -lp[np.arange(4), y]),
    ("mse", lambda p, y: ht.mseloss_op(p, y), [B_, G],
     lambda p, y: np.mean((p - y) ** 2)),
    # ---- shape / index ----
    ("reduce_min", lambda x: ht.reduce_min_op(x, axes=[1]), [B_],
     lambda x: x.min(1)),
    ("reduce_norm1", lambda x: ht.reduce_norm1_op(x, axes=[0]), [B_],
     lambda x: np.abs(x).sum(0)),
    ("reduce_norm2", lambda x: ht.reduce_norm2_op(x, axes=[1]), [B_],
     lambda x: np.sqrt((x ** 2).sum(1))),
    ("reducesumaxiszero", lambda x: ht.reducesumaxiszero_op(x), [B_],
     lambda x: x.sum(0)),
    ("norm", lambda x: ht.norm_op(x, axis=1, p=2), [B_],
     lambda x: np.sqrt((x ** 2).sum(1))),
    ("flatten", lambda x: ht.flatten_op(x), [M3],
     lambda x: x.reshape(2, -1)),
    ("tile", lambda x: ht.tile_op(x, (2, 3)), [B_],
     lambda x: np.tile(x, (2, 3))),
    ("repeat", lambda x: ht.repeat_op(x, 3, axis=1), [B_],
     lambda x: np.repeat(x, 3, axis=1)),
    ("roll", lambda x: ht.roll_op(x, 2, axis=1), [B_],
     lambda x: np.roll(x, 2, axis=1)),
    ("concatenate", lambda x, y: ht.concatenate_op([x, y], axis=1),
     [B_, A], lambda x, y: np.concatenate([x, y], 1)),
    ("gather", lambda x, i: ht.gather_op(x, 1, i), [B_, I6],
     lambda x, i: np.take_along_axis(x, i, axis=1)),
    ("scatter", lambda x, i, s: ht.scatter_op(x, 1, i, s), [B_, I6, G],
     None),
    ("scatter1d",
     lambda x, i, s: ht.scatter1d_op(x, i, s),
     [R.randn(6).astype(np.float32), np.array([1, 4], np.int32),
      np.array([9.0, 8.0], np.float32)], None),
    ("argsort", lambda x: ht.argsort_op(x, dim=1), [B_],
     lambda x: np.argsort(x, axis=1).astype(np.float32)),
    ("argmax_partial", lambda x, m: ht.argmax_partial_op(x, m, dim=1),
     [B_, MASK], None),
    ("cumsum", lambda x: ht.cumsum_op(x, dim=1), [B_],
     lambda x: np.cumsum(x, axis=1)),
    ("interpolate", lambda x: ht.interpolate_op(x, scale_factor=2),
     [R.randn(1, 2, 4, 4).astype(np.float32)], None),
    ("instance_norm", lambda x: ht.instance_normalization2d_op(x),
     [R.randn(2, 3, 5, 5).astype(np.float32)],
     lambda x: (x - x.mean((2, 3), keepdims=True))
     / np.sqrt(x.var((2, 3), keepdims=True) + 1e-7)),
    # ---- sparse matmul ----
    ("csrmv", lambda d, r, c, v: ht.csrmv_op(d, r, c, (3, 4), v),
     [np.array([1.0, 2.0, 3.0], np.float32),
      np.array([0, 1, 2], np.int32), np.array([1, 2, 3], np.int32),
      R.randn(4).astype(np.float32)], None),
    ("csrmm", lambda d, r, c, m: ht.csrmm_op(d, r, c, (3, 4), m),
     [np.array([1.0, 2.0, 3.0], np.float32),
      np.array([0, 1, 2], np.int32), np.array([1, 2, 3], np.int32),
      R.randn(4, 5).astype(np.float32)], None),
]


ORACLES = {
    "gelu_grad": lambda x, g: g * (
        0.5 * (1 + np.tanh(np.sqrt(2 / np.pi) * (x + 0.044715 * x ** 3)))
        + 0.5 * x * (1 - np.tanh(
            np.sqrt(2 / np.pi) * (x + 0.044715 * x ** 3)) ** 2)
        * np.sqrt(2 / np.pi) * (1 + 3 * 0.044715 * x ** 2)),
}


def _scatter_oracle(x, i, s):
    out = x.copy()
    np.put_along_axis(out, i, s, axis=1)
    return out


def _scatter1d_oracle(x, i, s):
    out = x.copy()
    out[i] = s
    return out


def _argmax_partial_oracle(x, m):
    neg = np.finfo(x.dtype).min
    return np.argmax(np.where(m.astype(bool), x, neg),
                     axis=1).astype(np.float32)


def _csr_dense():
    d = np.zeros((3, 4), np.float32)
    d[0, 1], d[1, 2], d[2, 3] = 1.0, 2.0, 3.0
    return d


@pytest.mark.parametrize("name,build,feeds,oracle",
                         CASES, ids=[c[0] for c in CASES])
def test_op_matches_numpy(name, build, feeds, oracle):
    if oracle is None:
        oracle = {
            "gelu_grad": ORACLES["gelu_grad"],
            "scatter": _scatter_oracle,
            "scatter1d": _scatter1d_oracle,
            "argmax_partial": _argmax_partial_oracle,
            "csrmv": lambda d, r, c, v: _csr_dense() @ v,
            "csrmm": lambda d, r, c, m: _csr_dense() @ m,
            "interpolate": None,
        }[name]
    got = _run(build, feeds)
    if name == "interpolate":
        # bilinear 2x upsample: just pin shape + corner values (exact
        # bilinear oracles vary by align_corners convention)
        assert got.shape == (1, 2, 8, 8)
        np.testing.assert_allclose(got[..., 0, 0], feeds[0][..., 0, 0],
                                   rtol=1e-5)
        return
    want = oracle(*feeds)
    np.testing.assert_allclose(got, np.asarray(want, np.float32),
                               rtol=2e-4, atol=2e-5)


class TestNullaryOps:
    def test_arange_full_fulllike_ones_zeros(self):
        x = ht.placeholder_op("x")
        outs = [ht.arange_op(2, 10, 2), ht.full_op((3, 2), 5.0),
                ht.full_like_op(x, 3.0), ht.oneslike_op(x),
                ht.zeroslike_op(x)]
        ex = ht.Executor({"t": outs})
        res = ex.run("t", feed_dict={x: B_}, convert_to_numpy_ret_vals=True)
        np.testing.assert_allclose(res[0], np.arange(2, 10, 2))
        np.testing.assert_allclose(res[1], np.full((3, 2), 5.0))
        np.testing.assert_allclose(res[2], np.full_like(B_, 3.0))
        np.testing.assert_allclose(res[3], np.ones_like(B_))
        np.testing.assert_allclose(res[4], np.zeros_like(B_))

    def test_rand_shape_and_range(self):
        out = ht.rand_op((16, 8))
        ex = ht.Executor({"t": [out]})
        (r1,) = ex.run("t", convert_to_numpy_ret_vals=True)
        (r2,) = ex.run("t", convert_to_numpy_ret_vals=True)
        assert r1.shape == (16, 8)
        assert (r1 >= 0).all() and (r1 < 1).all()
        assert not np.array_equal(r1, r2)  # advances with the step rng


class TestCommOpsIdentityOffMesh:
    """Annotation-mode comm ops are identities under a plain (no-mesh)
    executor — the dual-mode contract (ops_comm.py docstring)."""

    def test_identity(self):
        x = ht.placeholder_op("x")
        outs = [ht.allreduceCommunicate_op(x),
                ht.allreduceCommunicatep2p_op(x),
                ht.allgatherCommunicate_op(x),
                ht.reducescatterCommunicate_op(x),
                ht.broadcastCommunicate_op(x),
                ht.reduceCommunicate_op(x),
                ht.groupallreduceCommunicate_op(x)]
        ex = ht.Executor({"t": outs})
        res = ex.run("t", feed_dict={x: B_}, convert_to_numpy_ret_vals=True)
        for r in res:
            np.testing.assert_allclose(r, B_)


class TestMoEOps:
    """Direct numerics for the dispatch/gating kernels' op surface
    (reference LayoutTransform.cu / ReverseLayoutTransform.cu /
    GroupTopKIdx.cu / SamGroupSum.cu / SamMax.cu semantics)."""

    N, E, CAP, D = 4, 2, 2, 3
    TOK = R.randn(4, 3).astype(np.float32)
    IDX = np.array([0, 1, 0, 1], np.float32)     # top-1 expert per token
    LOC = np.array([0, 0, 1, 1], np.float32)     # slot within expert

    def test_layout_roundtrip(self):
        x = ht.placeholder_op("x")
        i = ht.placeholder_op("i")
        l = ht.placeholder_op("l")
        disp = ht.layout_transform_op(x, [i], [l], self.CAP, self.E)
        comb = ht.reverse_layout_transform_no_gate_op(
            disp, [i], [l], self.CAP, self.E)
        ex = ht.Executor({"t": [disp, comb]})
        d, c = ex.run("t", feed_dict={x: self.TOK, i: self.IDX,
                                      l: self.LOC},
                      convert_to_numpy_ret_vals=True)
        want = np.zeros((self.E * self.CAP, self.D), np.float32)
        for t in range(self.N):
            want[int(self.IDX[t]) * self.CAP + int(self.LOC[t])] = \
                self.TOK[t]
        np.testing.assert_allclose(d, want)
        np.testing.assert_allclose(c, self.TOK)   # combine inverts

    def test_reverse_layout_gate_weighted(self):
        x = ht.placeholder_op("x")
        i = ht.placeholder_op("i")
        l = ht.placeholder_op("l")
        g = ht.placeholder_op("g")
        gates = np.array([0.5, 1.0, 0.25, 2.0], np.float32)
        disp = ht.layout_transform_op(x, [i], [l], self.CAP, self.E)
        comb = ht.reverse_layout_transform_op(
            disp, [i], [l], [g], self.CAP, self.E)
        ex = ht.Executor({"t": [comb]})
        (c,) = ex.run("t", feed_dict={x: self.TOK, i: self.IDX,
                                      l: self.LOC, g: gates},
                      convert_to_numpy_ret_vals=True)
        np.testing.assert_allclose(c, gates[:, None] * self.TOK)

    def test_capacity_overflow_drops(self):
        x = ht.placeholder_op("x")
        i = ht.placeholder_op("i")
        l = ht.placeholder_op("l")
        idx = np.zeros(4, np.float32)             # all to expert 0
        loc = np.array([0, 1, 2, 3], np.float32)  # 2 overflow (cap=2)
        disp = ht.layout_transform_op(x, [i], [l], self.CAP, self.E)
        ex = ht.Executor({"t": [disp]})
        (d,) = ex.run("t", feed_dict={x: self.TOK, i: idx, l: loc},
                      convert_to_numpy_ret_vals=True)
        np.testing.assert_allclose(d[0], self.TOK[0])
        np.testing.assert_allclose(d[1], self.TOK[1])
        np.testing.assert_allclose(d[2:], 0.0)    # dropped, not wrapped

    def test_topk_and_group_gating_ops(self):
        scores = R.randn(4, 8).astype(np.float32)
        grp = np.array([0, 1, 1, 0], np.float32)
        s = ht.placeholder_op("s")
        gp = ht.placeholder_op("gp")
        outs = [ht.topk_idx_op(s, topk=2),
                ht.group_topk_idx_op(s, gp, topk=1, num_local_gpus=4),
                ht.sam_group_sum_op(s, 2),
                ht.unique_indices_op(gp)]
        ex = ht.Executor({"t": outs})
        tk, gtk, sgs, uq = ex.run("t", feed_dict={s: scores, gp: grp},
                                  convert_to_numpy_ret_vals=True)
        want_tk = np.argsort(-scores, axis=1)[:, :2]
        np.testing.assert_allclose(np.sort(tk, 1), np.sort(want_tk, 1))
        # group top-1 searches only [g*4, (g+1)*4)
        gtk_flat = np.asarray(gtk).reshape(-1)
        for t in range(4):
            lo = int(grp[t]) * 4
            assert lo <= gtk_flat[t] < lo + 4
            assert scores[t, int(gtk_flat[t])] == \
                scores[t, lo:lo + 4].max()
        np.testing.assert_allclose(
            sgs, scores.reshape(4, 2, 4).sum(-1), rtol=1e-5)
        np.testing.assert_allclose(np.sort(uq[:2]), [0.0, 1.0])
        np.testing.assert_allclose(uq[2:], -1.0)

    def test_sam_max(self):
        scores = R.randn(3, 8).astype(np.float32)
        grp = np.array([0, 1, 0], np.float32)
        tki = np.array([1, 5, 2], np.float32)
        s = ht.placeholder_op("s")
        gp = ht.placeholder_op("gp")
        tk = ht.placeholder_op("tk")
        out = ht.sam_max_op(s, gp, tk, 4)
        ex = ht.Executor({"t": [out]})
        (res,) = ex.run("t", feed_dict={s: scores, gp: grp, tk: tki},
                        convert_to_numpy_ret_vals=True)
        for t in range(3):
            ref = scores[t, int(tki[t])]
            lo = int(grp[t]) * 4
            for e in range(8):
                in_grp = lo <= e < lo + 4
                want = 0.0 if in_grp or scores[t, e] <= ref \
                    else scores[t, e] - ref
                np.testing.assert_allclose(res[t, e], want, rtol=1e-5)


class TestConvAndNormHelpers:
    def test_conv2d_add_bias(self):
        x = R.randn(2, 3, 5, 5).astype(np.float32)
        w = R.randn(4, 3, 3, 3).astype(np.float32)
        b = R.randn(4).astype(np.float32)
        xn, wn, bn = (ht.placeholder_op(n) for n in "xwb")
        out = ht.conv2d_add_bias_op(xn, wn, bn, stride=1, padding=1)
        base = ht.conv2d_op(xn, wn, stride=1, padding=1)
        ex = ht.Executor({"t": [out, base]})
        got, plain = ex.run("t", feed_dict={xn: x, wn: w, bn: b},
                            convert_to_numpy_ret_vals=True)
        np.testing.assert_allclose(got, plain + b.reshape(1, -1, 1, 1),
                                   rtol=1e-4, atol=1e-5)

    def test_conv2d_broadcast_and_reducesum(self):
        b = R.randn(3).astype(np.float32)
        t = R.randn(2, 3, 4, 4).astype(np.float32)
        bn, tn = ht.placeholder_op("b"), ht.placeholder_op("t")
        outs = [ht.conv2d_broadcastto_op(bn, tn),
                ht.conv2d_reducesum_op(tn),
                ht.addmm_gradient_op(tn, axis=0)]
        ex = ht.Executor({"t": outs})
        bc, rs, ag = ex.run("t", feed_dict={bn: b, tn: t},
                            convert_to_numpy_ret_vals=True)
        np.testing.assert_allclose(
            bc, np.broadcast_to(b.reshape(1, 3, 1, 1), t.shape))
        np.testing.assert_allclose(rs, t.sum((0, 2, 3)), rtol=1e-5)
        np.testing.assert_allclose(ag, t.sum(0), rtol=1e-5)

    def test_batch_norm_train_vs_eval_stats(self):
        x = R.randn(8, 3, 4, 4).astype(np.float32)
        xn = ht.placeholder_op("x")
        sc = ht.Variable("bn_scale", value=np.ones(3, np.float32))
        bi = ht.Variable("bn_bias", value=np.zeros(3, np.float32))
        out = ht.batch_normalization_op(xn, sc, bi, eps=1e-5)
        # eval subgraph (no optimizer): running stats = fresh (0 mean,
        # 1 var) -> identity up to eps
        ex = ht.Executor({"t": [out]})
        (res,) = ex.run("t", feed_dict={xn: x},
                        convert_to_numpy_ret_vals=True)
        np.testing.assert_allclose(res, x / np.sqrt(1 + 1e-5),
                                   rtol=1e-4, atol=1e-5)
        # train subgraph: batch statistics
        loss = ht.reduce_mean_op(ht.mul_op(out, out), axes=[0, 1, 2, 3])
        tr = ht.optim.SGDOptimizer(learning_rate=0.0).minimize(loss)
        ex2 = ht.Executor({"train": [out, tr]})
        res2 = np.asarray(ex2.run("train", feed_dict={xn: x})[0])
        mean = x.mean((0, 2, 3), keepdims=True)
        var = x.var((0, 2, 3), keepdims=True)
        np.testing.assert_allclose(res2, (x - mean) / np.sqrt(var + 1e-5),
                                   rtol=1e-3, atol=1e-4)

    def test_dropout2d_masks_whole_channels(self):
        x = np.ones((4, 8, 5, 5), np.float32)
        xn = ht.placeholder_op("x")
        out = ht.dropout2d_op(xn, 0.5)
        loss = ht.reduce_mean_op(out, axes=[0, 1, 2, 3])
        tr = ht.optim.SGDOptimizer(learning_rate=0.0).minimize(
            ht.reduce_mean_op(ht.mul_op(out, out), axes=[0, 1, 2, 3]))
        ex = ht.Executor({"t": [out, loss, tr]})
        res = np.asarray(ex.run("t", feed_dict={xn: x})[0])
        # spatial dropout: each (n, c) channel is all-zero or all-scaled
        per_chan = res.reshape(4 * 8, -1)
        assert all(np.all(r == 0) or np.all(r == r[0]) for r in per_chan)


class TestTransferAndPSAnnotations:
    def test_identity_shims(self):
        x = ht.placeholder_op("x")
        outs = [ht.datah2d_op(x), ht.datad2h_op(x),
                ht.parameterServerCommunicate_op(x)]
        ex = ht.Executor({"t": outs})
        res = ex.run("t", feed_dict={x: B_}, convert_to_numpy_ret_vals=True)
        for r in res:
            np.testing.assert_allclose(r, B_)

    def test_ps_sparse_pull_is_gather(self):
        table = ht.Variable("pspull_table", value=A)
        ids = ht.placeholder_op("ids")
        out = ht.parameterServerSparsePull_op(table, ids)
        ex = ht.Executor({"t": [out]})
        ii = np.array([3, 0, 1], np.int32)
        (res,) = ex.run("t", feed_dict={ids: ii},
                        convert_to_numpy_ret_vals=True)
        np.testing.assert_allclose(res, A[ii])


def test_slice_assign_matrix():
    a = ht.placeholder_op("a")
    b = ht.placeholder_op("b")
    out = ht.slice_assign_matrix_op(a, b, (0, 1), (2, 2), (1, 0))
    ex = ht.Executor({"t": [out]})
    other = R.randn(4, 6).astype(np.float32)
    (res,) = ex.run("t", feed_dict={a: B_, b: other},
                    convert_to_numpy_ret_vals=True)
    want = B_.copy()
    want[0:2, 1:3] = other[1:3, 0:2]
    np.testing.assert_allclose(res, want)


def test_slice_assign_and_by_matrix():
    x = ht.placeholder_op("x")
    out = ht.slice_assign_op(x, 9.0, (1, 2), (2, 3))
    ex = ht.Executor({"t": [out]})
    (res,) = ex.run("t", feed_dict={x: B_}, convert_to_numpy_ret_vals=True)
    want = B_.copy()
    want[1:3, 2:5] = 9.0
    np.testing.assert_allclose(res, want)

    a = ht.placeholder_op("a")
    i0 = ht.placeholder_op("i0")
    i1 = ht.placeholder_op("i1")
    out2 = ht.slice_by_matrix_op(a, i0, i1)
    ex2 = ht.Executor({"t": [out2]})
    idx0 = np.array([0, 2], np.int32)
    idx1 = np.array([1, 3], np.int32)
    (res2,) = ex2.run("t", feed_dict={a: B_, i0: idx0, i1: idx1},
                      convert_to_numpy_ret_vals=True)
    np.testing.assert_allclose(res2, B_[idx0, idx1])
