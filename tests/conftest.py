"""Test config: force a virtual 8-device CPU platform BEFORE jax initializes.

This is the TPU build's substitute for the reference's multi-process local
clusters (SURVEY.md §4 tier-2/3): N-device semantics on CPU so the
equivalence suite runs anywhere.  Note: the TPU plugin in this image ignores
the JAX_PLATFORMS env var, so we force via jax.config, which wins.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

# static checks default-ON for the whole suite: every Executor/
# ServingEngine build runs the pre-trace verifier + parallelism checker
# (hetu_tpu/analysis/), so a graph regression fails with the node named
# instead of an XLA stack dump.  Explicit HETU_VALIDATE=0 still wins.
os.environ.setdefault("HETU_VALIDATE", "1")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# jax-version shims (e.g. pre-0.5 runtimes lack top-level jax.shard_map)
# must land before any test module runs `from jax import shard_map`
from hetu_tpu._compat import ensure_jax_compat  # noqa: E402

ensure_jax_compat()


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running tier (excluded from tier-1 runs)")
    config.addinivalue_line(
        "markers",
        "smoke: <3-min verification tier (run with -m smoke; see "
        "ROADMAP.md tier-1 line)")
