"""Layer-level training tests incl. the BatchNorm-under-jit regression and
the MoE layer graph (reference tests/test_resnet_block.py pattern)."""

import numpy as np
import pytest

import hetu_tpu as ht


def test_batchnorm_training_and_eval():
    """BN must train (running stats updated via threaded state) and switch
    to running stats in eval mode — regression for the VJP tracer leak."""
    rng = np.random.RandomState(0)
    X = (rng.randn(16, 4, 8, 8) * 2 + 1).astype(np.float32)
    Y = np.eye(2, dtype=np.float32)[rng.randint(0, 2, 16)]

    x = ht.placeholder_op("x")
    y = ht.placeholder_op("y")
    bn = ht.layers.BatchNorm(4, momentum=0.9, eps=1e-5, name="bn_t")
    h = ht.relu_op(bn(x))
    h = ht.array_reshape_op(h, [-1, 4 * 8 * 8])
    logits = ht.layers.Linear(4 * 8 * 8, 2, name="fc_bn")(h)
    loss = ht.reduce_mean_op(ht.softmaxcrossentropy_op(logits, y), axes=0)
    train = ht.optim.SGDOptimizer(learning_rate=0.05).minimize(loss)
    ex = ht.Executor({"train": [loss, train], "eval": [loss, logits]})

    losses = []
    for _ in range(10):
        l, _ = ex.run("train", feed_dict={x: X, y: Y})
        losses.append(float(l))
    assert losses[-1] < losses[0]

    # running stats moved toward batch stats
    rm_name = [k for k in ex.var_values if "running_mean" in k][0]
    rm = np.asarray(ex.var_values[rm_name])
    assert not np.allclose(rm, 0.0), "running mean never updated"

    # eval uses running stats (no crash, finite)
    el, _ = ex.run("eval", feed_dict={x: X, y: Y})
    assert np.isfinite(float(el))


def test_dropout_train_vs_eval():
    x = ht.placeholder_op("x")
    d = ht.dropout_op(x, 0.5)
    s = ht.reduce_sum_op(d, [0, 1])
    # training subgraph needs an optimizer to enable training mode; use a
    # dummy variable so minimize has a target
    w = ht.Variable("w_do", value=np.ones((1,), np.float32))
    loss = s + ht.reduce_sum_op(ht.mul_op(w, w), [0])
    train = ht.optim.SGDOptimizer(learning_rate=0.0).minimize(loss)
    ex = ht.Executor({"train": [s, train], "eval": [s]})
    X = np.ones((32, 32), np.float32)
    strain, _ = ex.run("train", feed_dict={x: X})
    seval, = ex.run("eval", feed_dict={x: X})
    assert float(seval) == pytest.approx(1024.0)       # identity in eval
    assert float(strain) != pytest.approx(1024.0)      # masked in train


def test_moe_layer_trains():
    """Single-device MoE: gate + dispatch + experts + combine must train."""
    num_tokens, embed_dim, n_exp = 64, 8, 4
    rng = np.random.RandomState(0)
    X = rng.randn(num_tokens, embed_dim).astype(np.float32)
    Y = np.eye(2, dtype=np.float32)[rng.randint(0, 2, num_tokens)]

    gate = ht.layers.TopKGate(embed_dim, num_tokens, n_exp, k=2,
                              capacity_factor=2.0, name="gate_t")
    experts = [ht.layers.Expert(embed_dim, 16, activation="relu",
                                name=f"expert_t{i}") for i in range(n_exp)]
    moe = ht.layers.MoELayer(gate=gate, experts=experts,
                             num_tokens=num_tokens, embed_dim=embed_dim,
                             all2all_size=1, top=2)
    x = ht.placeholder_op("x")
    y = ht.placeholder_op("y")
    out, l_aux = moe(x)
    logits = ht.layers.Linear(embed_dim, 2, name="fc_moe")(out)
    loss = ht.reduce_mean_op(ht.softmaxcrossentropy_op(logits, y), axes=0)
    loss = loss + ht.mul_byconst_op(l_aux, 0.01)
    train = ht.optim.AdamOptimizer(learning_rate=1e-2).minimize(loss)
    ex = ht.Executor({"train": [loss, train]})
    losses = []
    for _ in range(30):
        l, _ = ex.run("train", feed_dict={x: X, y: Y})
        losses.append(float(l))
    assert losses[-1] < losses[0], (losses[0], losses[-1])


def test_balance_assignment_is_balanced_permutation():
    import jax
    import jax.numpy as jnp
    scores_np = np.random.RandomState(0).randn(32, 4).astype(np.float32)
    s = ht.placeholder_op("s")
    out = ht.balance_assignment_op(s)
    ex = ht.Executor({"t": [out]})
    (perm,) = ex.run("t", feed_dict={s: scores_np},
                     convert_to_numpy_ret_vals=True)
    perm = perm.astype(int)
    # must be a permutation of 0..31
    assert sorted(perm.tolist()) == list(range(32))


def test_dataloader_pairing_and_partial_batch():
    n = 10
    X = np.arange(n * 2, dtype=np.float32).reshape(n, 2)
    Y = np.arange(n, dtype=np.float32)
    dlx = ht.Dataloader(X, 4, "train", shuffle=True, drop_last=False)
    dly = ht.Dataloader(Y, 4, "train", shuffle=True, drop_last=False)
    seen = 0
    for _ in range(6):  # 2+ epochs
        bx = dlx.get_arr()
        by = dly.get_arr()
        assert bx.shape[0] == by.shape[0]
        # pairing invariant: x row i corresponds to label by[i]
        np.testing.assert_allclose(bx[:, 0], by * 2)
        seen += bx.shape[0]
    # partial batch of 2 was served (10 = 4+4+2)
    assert seen == 4 + 4 + 2 + 4 + 4 + 2


def test_fused_qkv_matches_unfused():
    """fused_qkv=True (one [H,3H] matmul over concat'd weights) must
    match the three-matmul form through training: identical parameter
    names/init, near-identical trajectories (same math, XLA may
    reassociate)."""
    import numpy as np
    import hetu_tpu as ht

    rng = np.random.RandomState(3)
    B, S, H, NH = 2, 8, 16, 2
    xv = rng.randn(B * S, H).astype(np.float32)
    yv = rng.randint(0, H, (B * S,)).astype(np.int32)

    def build(fused):
        x = ht.placeholder_op("x")
        y = ht.placeholder_op("y")
        attn = ht.layers.MultiHeadAttention(H, NH, S, B, name="fqa",
                                            fused_qkv=fused)
        out = attn(x)
        loss = ht.reduce_mean_op(
            ht.softmaxcrossentropy_sparse_op(out, y), axes=0)
        train = ht.optim.AdamOptimizer(learning_rate=1e-3).minimize(loss)
        ex = ht.Executor({"train": [loss, train]})
        return [float(np.asarray(ex.run("train",
                                        feed_dict={x: xv, y: yv})[0]))
                for _ in range(4)]

    np.testing.assert_allclose(build(False), build(True),
                               rtol=1e-5, atol=1e-6)
