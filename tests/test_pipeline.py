"""Pipeline parallelism tests (tier-2 equivalence, SURVEY.md §4):
N-stage pipeline output/training must equal the single-device ground truth.

Reference patterns: examples/runner/parallel/all_mlp_tests.sh PP configs +
validate_results.py allclose assertions.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from hetu_tpu.parallel.mesh import make_mesh
from hetu_tpu.parallel.pipeline import (
    spmd_pipeline, stack_stage_params, shard_stacked_params,
    gpipe_schedule, one_f_one_b_schedule, PipelineStage, PipelineTrainer,
    FWD, BWD,
)

HID = 16
S = 4   # stages
M = 8   # microbatches
MB = 4  # microbatch size


def _stage_fn(params, x):
    return jnp.tanh(x @ params["w"] + params["b"])


def _make_params(seed):
    rng = np.random.RandomState(seed)
    return [{"w": jnp.asarray(rng.randn(HID, HID) * 0.3, jnp.float32),
             "b": jnp.asarray(rng.randn(HID) * 0.1, jnp.float32)}
            for _ in range(S)]


def _sequential_fwd(per_stage, mb):
    out = []
    for m in range(mb.shape[0]):
        h = mb[m]
        for p in per_stage:
            h = _stage_fn(p, h)
        out.append(h)
    return jnp.stack(out)


def test_spmd_pipeline_matches_sequential():
    mesh = make_mesh({"pp": S})
    per_stage = _make_params(0)
    stacked = shard_stacked_params(stack_stage_params(per_stage), mesh)
    mb = jnp.asarray(np.random.RandomState(1).randn(M, MB, HID), jnp.float32)
    got = spmd_pipeline(_stage_fn, stacked, mb, mesh=mesh)
    want = _sequential_fwd(per_stage, mb)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_spmd_pipeline_grads_match_sequential():
    mesh = make_mesh({"pp": S})
    per_stage = _make_params(2)
    stacked = stack_stage_params(per_stage)
    mb = jnp.asarray(np.random.RandomState(3).randn(M, MB, HID), jnp.float32)
    tgt = jnp.asarray(np.random.RandomState(4).randn(M, MB, HID), jnp.float32)

    def loss_pipe(stacked_params):
        sp = shard_stacked_params(stacked_params, mesh)
        y = spmd_pipeline(_stage_fn, sp, mb, mesh=mesh)
        return jnp.mean((y - tgt) ** 2)

    def loss_seq(stacked_params):
        per = [jax.tree_util.tree_map(lambda p: p[i], stacked_params)
               for i in range(S)]
        y = _sequential_fwd(per, mb)
        return jnp.mean((y - tgt) ** 2)

    g_pipe = jax.grad(loss_pipe)(stacked)
    g_seq = jax.grad(loss_seq)(stacked)
    for a, b in zip(jax.tree_util.tree_leaves(g_pipe),
                    jax.tree_util.tree_leaves(g_seq)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_gpipe_schedule_order():
    sched = gpipe_schedule(4)
    assert sched[:4] == [(0, FWD), (1, FWD), (2, FWD), (3, FWD)]
    assert sched[4:] == [(3, BWD), (2, BWD), (1, BWD), (0, BWD)]


def test_1f1b_schedule_validity():
    for stage in range(4):
        sched = one_f_one_b_schedule(6, stage, 4)
        fwd_seen = set()
        for m, d in sched:
            if d == FWD:
                fwd_seen.add(m)
            else:
                assert m in fwd_seen, "bwd before fwd"
        assert len([1 for _, d in sched if d == BWD]) == 6
        # fwds before the first bwd = warmup + the first steady-state fwd
        warm = 0
        for _, d in sched:
            if d == FWD:
                warm += 1
            else:
                break
        assert warm == min(4 - stage - 1, 6) + 1


def _trainer_setup(mode, seed=0):
    per_stage = _make_params(seed)
    stages = [PipelineStage(apply=_stage_fn, params=dict(p))
              for p in per_stage]
    def loss_fn(y, t):
        return jnp.mean((y - t) ** 2)
    return PipelineTrainer(stages, mode=mode, loss_fn=loss_fn)


def test_gpipe_trainer_matches_plain_sgd():
    """gpipe over M microbatches == one SGD step on the mean-of-microbatch
    losses (the reference's single optimizer apply after all microbatches,
    gpipe_subexecutor.py:84-89)."""
    rng = np.random.RandomState(7)
    x = jnp.asarray(rng.randn(M, MB, HID), jnp.float32)
    t = jnp.asarray(rng.randn(M, MB, HID), jnp.float32)

    trainer = _trainer_setup("gpipe", seed=5)
    ref_params = [dict(st.params) for st in trainer.stages]
    trainer.train_batch(list(x), list(t))

    def total_loss(params_list):
        losses = []
        for m in range(M):
            h = x[m]
            for p in params_list:
                h = _stage_fn(p, h)
            losses.append(jnp.mean((h - t[m]) ** 2))
        return jnp.mean(jnp.stack(losses))

    grads = jax.grad(total_loss)(ref_params)
    want = [jax.tree_util.tree_map(lambda p, g: p - 0.1 * g, pl, gr)
            for pl, gr in zip(ref_params, grads)]
    for st, w in zip(trainer.stages, want):
        for k in w:
            np.testing.assert_allclose(np.asarray(st.params[k]),
                                       np.asarray(w[k]), rtol=1e-5, atol=1e-6)


def test_1f1b_trainer_matches_gpipe_math():
    """Synchronous 1F1B computes the same update as gpipe."""
    rng = np.random.RandomState(9)
    x = list(jnp.asarray(rng.randn(M, MB, HID), jnp.float32))
    t = list(jnp.asarray(rng.randn(M, MB, HID), jnp.float32))
    tr_a = _trainer_setup("gpipe", seed=11)
    tr_b = _trainer_setup("1f1b", seed=11)
    la = tr_a.train_batch(x, t)
    lb = tr_b.train_batch(x, t)
    assert abs(la - lb) < 1e-6
    for sa, sb in zip(tr_a.stages, tr_b.stages):
        for k in sa.params:
            np.testing.assert_allclose(np.asarray(sa.params[k]),
                                       np.asarray(sb.params[k]),
                                       rtol=1e-5, atol=1e-6)


def test_pipedream_trainer_descends():
    """PipeDream (per-microbatch updates w/ stashed weights) reduces loss."""
    rng = np.random.RandomState(13)
    trainer = _trainer_setup("pipedream", seed=13)
    losses = []
    for it in range(5):
        x = list(jnp.asarray(rng.randn(M, MB, HID), jnp.float32))
        t = [jnp.zeros((MB, HID), jnp.float32)] * M
        losses.append(trainer.train_batch(x, t))
    assert losses[-1] < losses[0]


def test_hetpipe_ps_sync():
    """HetPipe pushes to a PS every sync_every batches."""
    class FakePS:
        """Accumulating store, same contract as ps/server.py push()."""
        def __init__(self):
            self.store = {}
            self.pushes = 0
        def push(self, k, v):
            self.pushes += 1
            self.store[k] = self.store.get(k, 0) + np.asarray(v)
        def pull(self, k):
            return self.store[k]

    ps = FakePS()
    rng = np.random.RandomState(17)
    per_stage = _make_params(17)
    stages = [PipelineStage(apply=_stage_fn, params=dict(p))
              for p in per_stage]
    trainer = PipelineTrainer(
        stages, mode="hetpipe",
        loss_fn=lambda y, t: jnp.mean((y - t) ** 2),
        sync_every=2, ps=ps)
    for _ in range(4):
        x = list(jnp.asarray(rng.randn(M, MB, HID), jnp.float32))
        t = [jnp.zeros((MB, HID), jnp.float32)] * M
        trainer.train_batch(x, t)
    assert ps.pushes == 2 * S * 2  # 2 syncs x S stages x 2 tensors
    # after the final sync the PS view and worker view agree
    for i, st in enumerate(trainer.stages):
        for k in st.params:
            np.testing.assert_allclose(np.asarray(st.params[k]),
                                       ps.store[f"stage{i}/{k}"],
                                       rtol=1e-6, atol=1e-6)


def test_trainer_honors_real_optimizer():
    """PipelineTrainer uses Optimizer.update_one (momentum state advances),
    not silent vanilla SGD."""
    import hetu_tpu as ht
    rng = np.random.RandomState(21)
    per_stage = _make_params(21)
    stages = [PipelineStage(apply=_stage_fn, params=dict(p))
              for p in per_stage]
    opt = ht.optim.MomentumOptimizer(learning_rate=0.05, momentum=0.9)
    trainer = PipelineTrainer(
        stages, optimizer=opt, mode="gpipe",
        loss_fn=lambda y, t: jnp.mean((y - t) ** 2))
    ref_params = [dict(st.params) for st in trainer.stages]
    x = jnp.asarray(rng.randn(M, MB, HID), jnp.float32)
    t = jnp.asarray(rng.randn(M, MB, HID), jnp.float32)
    trainer.train_batch(list(x), list(t))

    def total_loss(params_list):
        losses = []
        for m in range(M):
            h = x[m]
            for p in params_list:
                h = _stage_fn(p, h)
            losses.append(jnp.mean((h - t[m]) ** 2))
        return jnp.mean(jnp.stack(losses))

    grads = jax.grad(total_loss)(ref_params)
    step = jnp.zeros((), jnp.int32)
    for st, pl, gr in zip(trainer.stages, ref_params, grads):
        for k in pl:
            s0 = opt.init_state_one(pl[k])
            want, _ = opt.update_one(pl[k], gr[k], s0,
                                     opt.lr_value(step), step)
            np.testing.assert_allclose(np.asarray(st.params[k]),
                                       np.asarray(want),
                                       rtol=1e-5, atol=1e-6)
    assert trainer._opt_states is not None
