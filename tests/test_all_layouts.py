"""All-layouts numerical-equivalence suite (the reference's tier-2 test
pattern: examples/runner/parallel/all_mlp_tests.sh:14-40 drives one
fixed-weight MLP under base/PP/MP-left/middle/right/MP+PP layouts and
validate_results.py:11-17 asserts allclose vs the 1-device run).

Here: one fixed-weight MLP driven through the *Executor* under every
mesh layout; loss trajectories must match the single-device run to 1e-5.
PP layouts (scan pipeline via Executor(pipeline=...), incl. composed
dp x pp and dp x tp + microbatching) are in TestPipelineLayouts below;
expert parallelism in test_moe_mesh.py."""

import numpy as np
import pytest

from jax.sharding import PartitionSpec as P

import hetu_tpu as ht


BATCH, IN, HID, OUT = 16, 8, 32, 4
N_STEPS = 8


def build_mlp(opt=None):
    x = ht.placeholder_op("x")
    y = ht.placeholder_op("y")
    w1 = ht.init.xavier_uniform((IN, HID), name="mlp_fc1_weight")
    b1 = ht.init.zeros((HID,), name="mlp_fc1_bias")
    w2 = ht.init.xavier_uniform((HID, IN), name="mlp_fc2_weight")
    b2 = ht.init.zeros((IN,), name="mlp_fc2_bias")
    wh = ht.init.xavier_uniform((IN, OUT), name="mlp_head_weight")
    h = ht.gelu_op(ht.linear_op(x, w1, b1))
    h = ht.linear_op(h, w2, b2)
    logits = ht.matmul_op(h, wh)
    loss = ht.reduce_mean_op(
        ht.softmaxcrossentropy_op(logits, y), axes=0)
    train = (opt or ht.optim.SGDOptimizer(learning_rate=0.1)).minimize(loss)
    return x, y, loss, train


def make_batches(n=N_STEPS, seed=3):
    rng = np.random.RandomState(seed)
    out = []
    for _ in range(n):
        xb = rng.randn(BATCH, IN).astype(np.float32)
        # learnable: label = argmax of the first OUT features
        yb = np.eye(OUT, dtype=np.float32)[xb[:, :OUT].argmax(axis=1)]
        out.append((xb, yb))
    return out


def run_traj(ex, x, y, batches):
    return [float(np.asarray(ex.run("train", feed_dict={x: a, y: b})[0]))
            for a, b in batches]


TP_SPECS = {
    "mlp_fc1_weight": P(None, "tp"),   # column split
    "mlp_fc1_bias": P("tp"),
    "mlp_fc2_weight": P("tp", None),   # row split
}


@pytest.fixture(scope="module")
def baseline():
    x, y, loss, train = build_mlp()
    ex = ht.Executor({"train": [loss, train]})
    w0 = ex.return_tensor_values()
    batches = make_batches()
    base = run_traj(ex, x, y, batches)
    assert base[-1] < base[0]  # it actually trains
    return w0, batches, base


LAYOUTS = {
    "dp8": lambda: ht.dist.DataParallel(num_devices=8),
    "dp2": lambda: ht.dist.DataParallel(num_devices=2),
    "tp2": lambda: ht.dist.ModelParallel4LM(tp=2, dp=1, specs=TP_SPECS),
    "tp2_patterns": lambda: ht.dist.ModelParallel4LM(tp=2, dp=1),
    "tp2xdp4": lambda: ht.dist.ModelParallel4LM(tp=2, dp=4,
                                                specs=TP_SPECS),
    "fsdp8": lambda: ht.dist.FSDP(dp=8, min_size=16),
    "explicit_plan": lambda: ht.dist.ShardingPlan(
        TP_SPECS, mesh_axes={"dp": 4, "tp": 2}),
}


class TestAllLayouts:
    @pytest.mark.parametrize("layout", sorted(LAYOUTS), ids=sorted(LAYOUTS))
    def test_trajectory_matches_single_device(self, baseline, layout):
        w0, batches, base = baseline
        x, y, loss, train = build_mlp()
        ex = ht.Executor({"train": [loss, train]},
                         dist_strategy=LAYOUTS[layout]())
        ex.load_dict(w0)
        tr = run_traj(ex, x, y, batches)
        np.testing.assert_allclose(tr, base, atol=1e-5)

    def test_adam_composed_layout(self, baseline):
        """Optimizer slot state must shard correctly too (Adam m/v inherit
        the param sharding) — composed dp x tp layout."""
        _, batches, _ = baseline
        x, y, loss, train = build_mlp(
            ht.optim.AdamOptimizer(learning_rate=0.01))
        ex1 = ht.Executor({"train": [loss, train]})
        w0 = ex1.return_tensor_values()
        base = run_traj(ex1, x, y, batches)

        x, y, loss, train = build_mlp(
            ht.optim.AdamOptimizer(learning_rate=0.01))
        ex2 = ht.Executor(
            {"train": [loss, train]},
            dist_strategy=ht.dist.ModelParallel4LM(tp=2, dp=4,
                                                   specs=TP_SPECS))
        ex2.load_dict(w0)
        tr = run_traj(ex2, x, y, batches)
        np.testing.assert_allclose(tr, base, atol=1e-5)

    def test_sharding_plan_rejects_typos(self):
        x, y, loss, train = build_mlp()
        with pytest.raises(KeyError):
            ht.Executor({"train": [loss, train]},
                        dist_strategy=ht.dist.ShardingPlan(
                            {"mlp_fc1_weihgt": P(None, "tp")},
                            mesh_axes={"tp": 2}))

    def test_eval_subgraph_same_layout(self, baseline):
        """Train + eval subgraphs share sharded params."""
        w0, batches, base = baseline
        x, y, loss, train = build_mlp()
        ex = ht.Executor({"train": [loss, train], "eval": [loss]},
                         dist_strategy=ht.dist.ModelParallel4LM(
                             tp=2, dp=4, specs=TP_SPECS))
        ex.load_dict(w0)
        for k, (a, b) in enumerate(batches[:3]):
            ev = float(np.asarray(
                ex.run("eval", feed_dict={x: a, y: b})[0]))
            tr = float(np.asarray(
                ex.run("train", feed_dict={x: a, y: b})[0]))
            # eval before the step sees the same params the step consumes
            np.testing.assert_allclose(ev, tr, atol=1e-6)
            np.testing.assert_allclose(tr, base[k], atol=1e-5)


class TestPipelineLayouts:
    """PP rows of the layout matrix: the pipeline-capable residual MLP
    from test_pipeline_executor driven through Executor(pipeline='gpipe')
    under pp-only, dp x pp (SPMD scan pipeline), and dp x tp with
    microbatching (GSPMD path)."""

    @pytest.fixture(scope="class")
    def pp_baseline(self):
        from test_pipeline_executor import build_model, make_batches
        x, y, loss, train = build_model()
        ex = ht.Executor({"train": [loss, train]})
        w0 = ex.return_tensor_values()
        batches = make_batches()
        base = [float(np.asarray(
            ex.run("train", feed_dict={x: a, y: b})[0]))
            for a, b in batches]
        return w0, batches, base

    # body-layer tp specs must be uniform across layers (the SPMD path
    # stacks them); Megatron col/row split on every block
    BODY_TP = {f"l{i}_{n}": s for i in range(4) for n, s in
               [("w1", P(None, "tp")), ("b1", P("tp")),
                ("w2", P("tp", None))]}

    PP_LAYOUTS = {
        "pp4": ({"pp": 4}, None),
        "pp2xdp4": ({"pp": 2, "dp": 4}, None),
        "dp2xtp2_mb": ({"dp": 2, "tp": 2},
                       {"l0_w1": P(None, "tp"), "l0_b1": P("tp"),
                        "l0_w2": P("tp", None),
                        "l2_w1": P(None, "tp"), "l2_b1": P("tp"),
                        "l2_w2": P("tp", None)}),
        # the full 3-D composition: scan pipeline manual over 'pp', GSPMD
        # partitioning the in-stage matmuls over 'tp' and the batch over
        # 'dp' (BASELINE config 5's layout class)
        "dp2xtp2xpp2": ({"pp": 2, "dp": 2, "tp": 2}, BODY_TP),
    }

    @pytest.mark.parametrize("layout", sorted(PP_LAYOUTS),
                             ids=sorted(PP_LAYOUTS))
    def test_pp_trajectory_matches(self, pp_baseline, layout):
        from test_pipeline_executor import build_model
        from hetu_tpu.parallel.mesh import make_mesh
        w0, batches, base = pp_baseline
        axes, specs = self.PP_LAYOUTS[layout]
        x, y, loss, train = build_model()
        mesh = make_mesh(axes)
        strategy = ht.dist.ShardingPlan(specs) if specs else None
        kw = dict(pipeline="gpipe", num_microbatches=4, mesh=mesh)
        if strategy is not None:
            kw["dist_strategy"] = strategy
        if "pp" not in axes:
            kw["num_stages"] = 2
        ex = ht.Executor({"train": [loss, train]}, **kw)
        if "pp" in axes:
            assert ex.subexecutor["train"].spmd
        ex.load_dict(w0)
        tr = [float(np.asarray(
            ex.run("train", feed_dict={x: a, y: b})[0]))
            for a, b in batches]
        np.testing.assert_allclose(tr, base, atol=1e-5)


class TestGPTLayouts:
    """The decoder-only family through dp/fsdp/tp layouts: trajectory ==
    1-device (tier-2 pattern).  tp splits the fused-QKV projections
    column-wise and the output/FFN-out projections row-wise; the
    concat-of-sharded-weights [H,3H] matmul must propagate under
    GSPMD."""

    GPT_TP_SPECS = {
        "g_h0_attn_q_weight": P(None, "tp"),
        "g_h0_attn_k_weight": P(None, "tp"),
        "g_h0_attn_v_weight": P(None, "tp"),
        "g_h0_attn_proj_weight": P("tp", None),
        "g_h0_ffn_wi_weight": P(None, "tp"),
        "g_h0_ffn_wo_weight": P("tp", None),
        "g_h1_attn_q_weight": P(None, "tp"),
        "g_h1_attn_k_weight": P(None, "tp"),
        "g_h1_attn_v_weight": P(None, "tp"),
        "g_h1_attn_proj_weight": P("tp", None),
        "g_h1_ffn_wi_weight": P(None, "tp"),
        "g_h1_ffn_wo_weight": P("tp", None),
    }

    def _build(self):
        from hetu_tpu.models import GPTConfig, GPTForCausalLM
        cfg = GPTConfig(vocab_size=61, hidden_size=32,
                        num_hidden_layers=2, num_attention_heads=2,
                        max_position_embeddings=16, batch_size=8,
                        seq_len=16, dropout_rate=0.0)
        m = GPTForCausalLM(cfg, name="g")
        ids = ht.placeholder_op("g_ids")
        labels = ht.placeholder_op("g_labels")
        loss, _ = m(ids, labels=labels)
        train = ht.optim.AdamOptimizer(learning_rate=3e-3).minimize(loss)
        return ids, labels, loss, train

    def _batches(self, n=6):
        rng = np.random.RandomState(2)
        out = []
        for _ in range(n):
            iv = rng.randint(0, 61, (8, 16)).astype(np.int32)
            out.append((iv, ((iv + 1) % 61).astype(np.int32)))
        return out

    @pytest.fixture(scope="class")
    def gpt_baseline(self):
        ids, labels, loss, train = self._build()
        ex0 = ht.Executor({"train": [loss, train]})
        w0 = ex0.return_tensor_values()
        batches = self._batches()
        base = run_traj(ex0, ids, labels, batches)
        assert base[-1] < base[0]
        return w0, batches, base

    @pytest.mark.parametrize("layout", ["dp8", "fsdp8", "tp2", "tp2xdp4"])
    def test_gpt_trajectory_matches(self, gpt_baseline, layout):
        w0, batches, base = gpt_baseline
        strategies = {
            "dp8": lambda: ht.dist.DataParallel(num_devices=8),
            "fsdp8": lambda: ht.dist.FSDP(dp=8, min_size=16),
            "tp2": lambda: ht.dist.ModelParallel4LM(
                tp=2, dp=1, specs=self.GPT_TP_SPECS),
            "tp2xdp4": lambda: ht.dist.ModelParallel4LM(
                tp=2, dp=4, specs=self.GPT_TP_SPECS),
        }
        ids2, labels2, loss2, train2 = self._build()
        ex = ht.Executor({"train": [loss2, train2]},
                         dist_strategy=strategies[layout]())
        ex.load_dict(w0)
        tr = run_traj(ex, ids2, labels2, batches)
        np.testing.assert_allclose(tr, base, atol=2e-4)
