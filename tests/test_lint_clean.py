"""The repo itself must pass its own lint gate (tier-1 guard).

``bin/hetu_lint.py hetu_tpu/ bench.py bin/`` exiting 0 is an acceptance
criterion of the static-analysis subsystem: the env-registry rule is
what KEEPS the 60-raw-read migration from regressing, and the
trace-body rules keep JAX footguns out of ``Op.compute``.  Runs the
rules in-process (no subprocess jax startup) plus one CLI smoke pass.
"""

import os
import subprocess
import sys

import pytest

from hetu_tpu.analysis.lint import RULES, lint_paths

pytestmark = pytest.mark.smoke

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TARGETS = [os.path.join(REPO, "hetu_tpu"),
           os.path.join(REPO, "bench.py"),
           os.path.join(REPO, "bin")]


def test_repo_lints_clean():
    findings = lint_paths(TARGETS)
    assert findings == [], "\n".join(str(f) for f in findings)


def test_cli_exits_zero_on_repo():
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bin", "hetu_lint.py"),
         *TARGETS], capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_cli_exits_nonzero_on_fixture():
    fixture = os.path.join(REPO, "tests", "fixtures", "lint",
                           "trip_env_registry.py")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bin", "hetu_lint.py"),
         fixture], capture_output=True, text=True, timeout=120)
    assert proc.returncode == 1
    assert "env-registry" in proc.stdout


def test_cli_env_table():
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bin", "hetu_lint.py"),
         "--env-table"], capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0
    assert "`HETU_VALIDATE`" in proc.stdout
    assert "| Variable | Type | Default | Description |" in proc.stdout


def test_readme_env_table_in_sync():
    """The drift gate for the knob table: README's env-var section must
    be byte-for-byte the registry's generated table (``hetu_lint
    --env-table``).  A knob added without regenerating the table — or
    documented by hand-editing the README — fails here; the dead-knob
    lint rule covers the other direction (registered but never
    read)."""
    from hetu_tpu.envvars import env_table
    with open(os.path.join(REPO, "README.md"), encoding="utf-8") as f:
        lines = f.read().splitlines()
    start = lines.index("| Variable | Type | Default | Description |")
    table = []
    for ln in lines[start:]:
        if not ln.startswith("|"):
            break
        table.append(ln)
    generated = env_table().splitlines()
    assert table == generated, (
        "README env table drifted from the registry — regenerate with "
        "`python bin/hetu_lint.py --env-table` and paste it in")


def test_every_rule_documented():
    # the CLI help names each rule's purpose via the module docstring
    from hetu_tpu.analysis import lint as lint_mod
    for rule in RULES:
        assert f"``{rule}``" in lint_mod.__doc__
