"""ONNX interop tests (reference tests/onnx/test_nodes.py round-trips
hetu->onnx->TF; here: hetu->onnx->hetu numerics, plus protobuf wire-format
round-trips since the proto layer is ours)."""

import os
import tempfile

import numpy as np
import pytest

import hetu_tpu as ht
from hetu_tpu.onnx import export, load_onnx, load_model
from hetu_tpu.onnx import proto as P


class TestProtoWire:
    def test_varint_roundtrip(self):
        for v in (0, 1, 127, 128, 300, 2 ** 40, -1, -42):
            data = P._enc_varint(P._zz(v))
            out, pos = P._dec_varint(data, 0)
            assert P._unzz(out) == v and pos == len(data)

    def test_tensor_roundtrip(self):
        for arr in (np.random.randn(3, 4).astype(np.float32),
                    np.arange(6, dtype=np.int64).reshape(2, 3),
                    np.array([True, False])):
            t = P.tensor_from_numpy(arr, "w")
            t2 = P.TensorProto.decode(t.encode())
            np.testing.assert_array_equal(P.tensor_to_numpy(t2), arr)
            assert t2.name == "w"

    def test_model_roundtrip(self):
        g = P.GraphProto(
            name="g",
            node=[P.NodeProto(op_type="Relu", input=["x"], output=["y"],
                              name="r")],
            input=[P.value_info("x", [2, "batch"])],
            output=[P.value_info("y", [2, 3])],
            initializer=[P.tensor_from_numpy(np.zeros((2, 2), np.float32),
                                             "w")])
        m = P.ModelProto(ir_version=8, producer_name="t", graph=g,
                         opset_import=[P.OperatorSetIdProto(version=17)])
        m2 = P.ModelProto.decode(m.encode())
        assert m2.graph.node[0].op_type == "Relu"
        assert m2.graph.input[0].name == "x"
        assert m2.graph.input[0].type.tensor_type.shape.dim[1].dim_param \
            == "batch"
        assert m2.opset_import[0].version == 17

    def test_attribute_kinds(self):
        for v in (3, 2.5, "hi", [1, 2, 3], [1.5, 2.5],
                  np.ones((2,), np.float32)):
            a = P.attr("a", v)
            a2 = P.AttributeProto.decode(a.encode())
            got = P.attr_value(a2)
            if isinstance(v, np.ndarray):
                np.testing.assert_array_equal(got, v)
            elif isinstance(v, list):
                assert list(got) == pytest.approx(v)
            else:
                assert got == pytest.approx(v) if isinstance(v, float) \
                    else got == v


def _roundtrip(outputs, inputs, feeds, rtol=1e-5):
    """Export the graph, re-import, run both, compare numerics."""
    ex = ht.Executor({"fwd": list(outputs)})
    ref = ex.run("fwd", feed_dict={n: feeds[n.name] for n in inputs})

    path = os.path.join(tempfile.mkdtemp(), "m.onnx")
    export(ex, inputs, outputs, path,
           feed_shapes={n.name: feeds[n.name].shape for n in inputs})

    outs2, phs, _ = load_onnx(path)
    ex2 = ht.Executor({"fwd": outs2})
    got = ex2.run("fwd", feed_dict={
        phs[n.name]: feeds[n.name] for n in inputs})
    for r, g in zip(ref, got):
        np.testing.assert_allclose(np.asarray(r), np.asarray(g),
                                   rtol=rtol, atol=1e-5)
    return path


class TestRoundTrip:
    def test_mlp(self):
        rng = np.random.RandomState(0)
        x = ht.placeholder_op("x")
        w1 = ht.Variable("w1", value=rng.randn(16, 32).astype(np.float32))
        b1 = ht.Variable("b1", value=np.zeros(32, np.float32))
        w2 = ht.Variable("w2", value=rng.randn(32, 4).astype(np.float32))
        h = ht.relu_op(ht.matmul_op(x, w1) + ht.broadcastto_op(
            b1, ht.matmul_op(x, w1)))
        y = ht.softmax_op(ht.matmul_op(h, w2))
        path = _roundtrip([y], [x],
                          {"x": rng.randn(8, 16).astype(np.float32)})
        # the file is a real protobuf ModelProto
        m = load_model(path)
        assert m.producer_name == "hetu_tpu"
        assert any(n.op_type == "Einsum" for n in m.graph.node)

    def test_conv_pool_bn(self):
        rng = np.random.RandomState(1)
        x = ht.placeholder_op("x")
        w = ht.Variable("w", value=(rng.randn(8, 3, 3, 3) * 0.1)
                        .astype(np.float32))
        c = ht.conv2d_op(x, w, padding=1, stride=1)
        r = ht.relu_op(c)
        p = ht.max_pool2d_op(r, 2, 2, stride=2)
        _roundtrip([p], [x],
                   {"x": rng.randn(2, 3, 8, 8).astype(np.float32)})

    def test_elementwise_chain(self):
        rng = np.random.RandomState(2)
        x = ht.placeholder_op("x")
        y = ht.tanh_op(ht.exp_op(ht.mul_byconst_op(x, 0.1)))
        z = ht.sigmoid_op(y + y)
        _roundtrip([z], [x],
                   {"x": rng.randn(4, 5).astype(np.float32)})

    def test_embedding_gather(self):
        rng = np.random.RandomState(3)
        ids = ht.placeholder_op("ids")
        table = ht.Variable("table",
                            value=rng.randn(50, 8).astype(np.float32))
        emb = ht.embedding_lookup_op(table, ids)
        out = ht.reduce_sum_op(emb, axes=[1])
        ex = ht.Executor({"fwd": [out]})
        feed = rng.randint(0, 50, (4, 6)).astype(np.int32)
        ref = ex.run("fwd", feed_dict={ids: feed})

        path = os.path.join(tempfile.mkdtemp(), "emb.onnx")
        ex.config.feed_dtypes = {"ids": np.int32}
        export(ex, [ids], [out], path, feed_shapes={"ids": feed.shape})
        outs2, phs, _ = load_onnx(path)
        ex2 = ht.Executor({"fwd": outs2})
        got = ex2.run("fwd", feed_dict={phs["ids"]: feed})
        np.testing.assert_allclose(np.asarray(ref[0]),
                                   np.asarray(got[0]), rtol=1e-5)

    def test_transformer_block(self):
        rng = np.random.RandomState(4)
        bs, seq, dim = 2, 8, 16
        x = ht.placeholder_op("x")
        attn = ht.layers.MultiHeadAttention(dim, 2, seq, bs, name="attn")
        h = attn(x)
        ln = ht.layers.LayerNorm(dim, name="ln")
        out = ln(h + x)
        _roundtrip([out], [x],
                   {"x": rng.randn(bs * seq, dim).astype(np.float32)},
                   rtol=1e-4)

    def test_isfinite_clip_roundtrip(self):
        # regression: is_finite must not export as bare IsInf; Clip with
        # initializer bounds must import them
        import jax.numpy as jnp
        from hetu_tpu.graph.ops_math import _simple
        x = ht.placeholder_op("x")
        y = _simple("F", lambda a: jnp.where(
            jnp.isfinite(a), jnp.clip(a, -2.0, 2.0), -1.0), x)
        X = np.array([[1.5, -7.0, np.inf, np.nan]], np.float32)
        _roundtrip([y], [x], {"x": X})

    def test_avgpool_with_padding_roundtrip(self):
        # regression: reduce_window_sum export must count included pads
        rng = np.random.RandomState(7)
        x = ht.placeholder_op("x")
        p = ht.avg_pool2d_op(x, 3, 3, padding=1, stride=2)
        _roundtrip([p], [x],
                   {"x": rng.randn(2, 3, 9, 9).astype(np.float32)})

    def test_equal_params_get_unique_names(self):
        # regression: two identical param tensors must not collide
        x = ht.placeholder_op("x")
        b1 = ht.Variable("b1", value=np.zeros((4,), np.float32))
        b2 = ht.Variable("b2", value=np.zeros((4,), np.float32))
        y = (x + ht.broadcastto_op(b1, x)) * ht.broadcastto_op(b2, x)
        ex = ht.Executor({"f": [y]})
        path = os.path.join(tempfile.mkdtemp(), "dup.onnx")
        export(ex, [x], [y], path, feed_shapes={"x": (2, 4)})
        names = [t.name for t in load_model(path).graph.initializer]
        assert len(names) == len(set(names)), names

    def test_imported_model_is_trainable(self):
        rng = np.random.RandomState(5)
        x = ht.placeholder_op("x")
        w = ht.Variable("w", value=rng.randn(4, 2).astype(np.float32))
        y = ht.matmul_op(x, w)
        ex = ht.Executor({"fwd": [y]})
        path = os.path.join(tempfile.mkdtemp(), "t.onnx")
        export(ex, [x], [y], path, feed_shapes={"x": (8, 4)})

        outs, phs, _ = load_onnx(path)
        y_ = ht.placeholder_op("y_")
        loss = ht.reduce_mean_op(ht.reduce_sum_op(
            ht.mul_op(outs[0] - y_, outs[0] - y_), [1]), [0])
        train = ht.optim.SGDOptimizer(learning_rate=0.05).minimize(loss)
        ex2 = ht.Executor({"train": [loss, train]})
        X = rng.randn(8, 4).astype(np.float32)
        Y = X @ rng.randn(4, 2).astype(np.float32)
        losses = [float(ex2.run("train", feed_dict={
            phs["x"]: X, y_: Y})[0]) for _ in range(60)]
        assert losses[-1] < losses[0] * 0.1


class TestCrossFramework:
    """VERDICT r2 item 10: ONNX files exported by ANOTHER framework
    (genuine torch-serialized protos, checked-in fixtures generated by
    torch's C++ exporter) must import into trainable hetu_tpu graphs
    with matching numerics; our exports must round-trip across opset
    versions (reference tests/onnx/cnn_hetu_onnx_tf.py role)."""

    FIX = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "fixtures")

    def _import_and_check(self, model_file, in_file, out_file, atol):
        from hetu_tpu.onnx.onnx2hetu import load_onnx
        outputs, placeholders, weights = load_onnx(
            os.path.join(self.FIX, model_file))
        x = np.load(os.path.join(self.FIX, in_file))
        want = np.load(os.path.join(self.FIX, out_file))
        ex = ht.Executor({"fwd": outputs})
        ex.load_dict(weights)
        got = np.asarray(ex.run(
            "fwd", feed_dict={placeholders["x"]: x})[0])
        np.testing.assert_allclose(got, want, atol=atol)
        return outputs, placeholders, weights, x

    def test_torch_cnn_forward_parity(self):
        """Conv/BN/Relu/MaxPool/Flatten/Gemm exported by torch at opset
        13 -> same outputs as torch, to fp32 tolerance."""
        self._import_and_check("torch_cnn_opset13.onnx",
                               "torch_cnn_input.npy",
                               "torch_cnn_output.npy", atol=2e-5)

    def test_torch_transformer_forward_parity(self):
        """A full attention block (MatMul/Softmax/LayerNormalization at
        opset 17/Gelu/Transpose/Reshape) exported by torch."""
        self._import_and_check("torch_transformer_opset17.onnx",
                               "torch_transformer_input.npy",
                               "torch_transformer_output.npy", atol=2e-5)

    def test_torch_cnn_imports_trainable(self):
        """The imported torch model TRAINS: attach a loss, run steps,
        weights move and the loss drops (reference onnx2hetu's trainable
        import contract)."""
        from hetu_tpu.onnx.onnx2hetu import load_onnx
        outputs, placeholders, weights = load_onnx(
            os.path.join(self.FIX, "torch_cnn_opset13.onnx"))
        y = ht.placeholder_op("labels")
        loss = ht.reduce_mean_op(
            ht.softmaxcrossentropy_op(outputs[0], y), axes=0)
        train = ht.optim.SGDOptimizer(learning_rate=0.05).minimize(loss)
        ex = ht.Executor({"train": [loss, train]})
        ex.load_dict(weights)
        rng = np.random.RandomState(0)
        x = np.load(os.path.join(self.FIX, "torch_cnn_input.npy"))
        yb = np.eye(10, dtype=np.float32)[rng.randint(0, 10, len(x))]
        conv_w_name = next(k for k in weights if "conv" in k.lower()
                           or k.endswith("weight"))
        before = np.array(ex.var_values[conv_w_name], copy=True)
        tr = [float(np.asarray(ex.run(
            "train", feed_dict={placeholders["x"]: x, y: yb})[0]))
            for _ in range(8)]
        assert np.all(np.isfinite(tr))
        assert tr[-1] < tr[0], tr
        assert not np.allclose(ex.var_values[conv_w_name], before)

    @pytest.mark.parametrize("opset", [13, 17, 18])
    def test_export_reimport_across_opsets(self, tmp_path, opset):
        """Our exporter stamps any of opset 13-18 and the file re-imports
        with identical numerics.  softmax forces a reduce_max, whose
        axes moved from attribute (<=17) to input (18) — assert the
        emitted NodeProto uses the form the stamped opset allows."""
        from hetu_tpu.onnx import hetu2onnx
        from hetu_tpu.onnx.onnx2hetu import load_onnx, load_model
        x = ht.placeholder_op("x")
        w1 = ht.init.xavier_uniform((6, 16), name=f"xw1_{opset}")
        w2 = ht.init.xavier_uniform((16, 3), name=f"xw2_{opset}")
        out = ht.softmax_op(
            ht.matmul_op(ht.gelu_op(ht.matmul_op(x, w1)), w2))
        ex = ht.Executor({"fwd": [out]})
        xb = np.random.RandomState(0).randn(4, 6).astype(np.float32)
        want = np.asarray(ex.run("fwd", feed_dict={x: xb})[0])
        p = str(tmp_path / f"m{opset}.onnx")
        hetu2onnx.export(ex, [x], [out], p, feed_shapes={"x": (4, 6)},
                         opset=opset)
        model = load_model(p)
        assert model.opset_import[0].version == opset
        reduces = [n for n in model.graph.node
                   if n.op_type in ("ReduceMax", "ReduceMin",
                                    "ReduceProd")]
        assert reduces, "softmax should have emitted a ReduceMax"
        for n in reduces:
            has_axes_attr = any(a.name == "axes" for a in n.attribute)
            if opset >= 18:
                assert len(n.input) == 2 and not has_axes_attr
            else:
                assert len(n.input) == 1 and has_axes_attr
        outs2, ph2, w2_ = load_onnx(p)
        ex2 = ht.Executor({"fwd": outs2})
        ex2.load_dict(w2_)
        got = np.asarray(ex2.run("fwd", feed_dict={ph2["x"]: xb})[0])
        np.testing.assert_allclose(got, want, atol=1e-5)


class TestTFCrossFramework:
    """VERDICT r3 item 10 (reference tests/onnx/cnn_hetu_onnx_tf.py):
    a TENSORFLOW-side model crosses ONNX into a trainable hetu graph.
    The checked-in fixture (tests/fixtures/gen_tf_fixture.py) carries a
    tf2onnx-shaped graph — NHWC input, Transpose->NCHW around Conv/Pool,
    NHWC flatten — and tf_cnn_output.npy is TensorFlow's OWN forward
    output, so parity here is parity WITH TF EXECUTION."""

    FIX = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "fixtures")

    def test_tf_cnn_forward_parity(self):
        from hetu_tpu.onnx.onnx2hetu import load_onnx
        outputs, placeholders, weights = load_onnx(
            os.path.join(self.FIX, "tf_cnn.onnx"))
        x = np.load(os.path.join(self.FIX, "tf_cnn_input.npy"))
        want = np.load(os.path.join(self.FIX, "tf_cnn_output.npy"))
        ex = ht.Executor({"fwd": outputs})
        ex.load_dict(weights)
        got = np.asarray(ex.run("fwd",
                                feed_dict={placeholders["x"]: x})[0])
        np.testing.assert_allclose(got, want, atol=2e-5)

    def test_tf_cnn_imports_trainable(self):
        from hetu_tpu.onnx.onnx2hetu import load_onnx
        outputs, placeholders, weights = load_onnx(
            os.path.join(self.FIX, "tf_cnn.onnx"))
        y = ht.placeholder_op("tf_labels")
        loss = ht.reduce_mean_op(
            ht.softmaxcrossentropy_op(outputs[0], y), axes=0)
        train = ht.optim.SGDOptimizer(learning_rate=0.05).minimize(loss)
        ex = ht.Executor({"train": [loss, train]})
        ex.load_dict(weights)
        rng = np.random.RandomState(0)
        x = np.load(os.path.join(self.FIX, "tf_cnn_input.npy"))
        yb = np.eye(10, dtype=np.float32)[rng.randint(0, 10, len(x))]
        wname = next(k for k in weights if "conv" in k)
        before = np.array(ex.var_values[wname], copy=True)
        tr = [float(np.asarray(ex.run("train", feed_dict={
            placeholders["x"]: x, y: yb})[0])) for _ in range(8)]
        assert np.all(np.isfinite(tr))
        assert tr[-1] < tr[0], tr
        assert not np.allclose(ex.var_values[wname], before)

    def test_fixture_regenerates_against_live_tf(self):
        """When TensorFlow is importable (it is in this image), rebuild
        the fixture from scratch and assert the checked-in TF reference
        output matches a LIVE TF forward — guards fixture rot."""
        tf = pytest.importorskip("tensorflow")
        del tf
        import importlib.util
        spec = importlib.util.spec_from_file_location(
            "gen_tf_fixture", os.path.join(self.FIX, "gen_tf_fixture.py"))
        gen = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(gen)
        _model, x, y = gen.build_and_run_tf()
        np.testing.assert_allclose(
            x, np.load(os.path.join(self.FIX, "tf_cnn_input.npy")),
            atol=0)
        np.testing.assert_allclose(
            y, np.load(os.path.join(self.FIX, "tf_cnn_output.npy")),
            atol=1e-6)
