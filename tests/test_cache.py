"""HET embedding cache tests: policies, native/python equivalence, and the
CacheSparseTable sync protocol against an in-process PS (reference pattern:
tests/hetu_cache/hetu_cache_test.py drives CacheSparseTable against a local
PS)."""

import numpy as np
import pytest

from hetu_tpu.cache.cache import PythonCache, NativeCache, EmbeddingCache
from hetu_tpu.cache.cstable import CacheSparseTable
from hetu_tpu.ps.server import PSServer

# smoke tier: this module is part of the <3-min verification
# battery (`pytest -m smoke`; ROADMAP tier-1 note)
pytestmark = pytest.mark.smoke

W = 4


def impls():
    out = [PythonCache]
    if NativeCache.load_lib() is not None:
        out.append(NativeCache)
    return out


@pytest.mark.parametrize("Cache", impls())
def test_lru_eviction_order(Cache):
    c = Cache(limit=2, width=W, policy="LRU")
    c.insert([1], np.ones((1, W)))
    c.insert([2], np.full((1, W), 2.0))
    c.lookup([1])                      # 1 now most recent
    c.insert([3], np.full((1, W), 3.0))  # evicts 2
    _, hit = c.lookup([1, 2, 3])
    assert list(hit) == [True, False, True]


@pytest.mark.parametrize("Cache", impls())
def test_lfu_eviction_order(Cache):
    c = Cache(limit=2, width=W, policy="LFU")
    c.insert([1], np.ones((1, W)))
    c.insert([2], np.full((1, W), 2.0))
    for _ in range(3):
        c.lookup([1])                  # freq(1) >> freq(2)
    c.insert([3], np.full((1, W), 3.0))  # evicts 2 (lowest freq)
    _, hit = c.lookup([1, 2, 3])
    assert list(hit) == [True, False, True]


@pytest.mark.parametrize("Cache", impls())
def test_dirty_eviction_reports_grads(Cache):
    c = Cache(limit=1, width=W, policy="LRU")
    c.insert([1], np.ones((1, W)))
    c.update([1], np.full((1, W), 0.5))
    ev_ids, ev_grads = c.insert([2], np.zeros((1, W)))
    assert list(ev_ids) == [1]
    np.testing.assert_allclose(ev_grads[0], np.full(W, 0.5))


@pytest.mark.parametrize("Cache", impls())
def test_update_writeback_and_collect(Cache):
    c = Cache(limit=4, width=W, policy="LRU")
    c.insert([1, 2], np.ones((2, W)))
    c.update([1], np.full((1, W), 0.25))
    rows, hit = c.lookup([1])
    np.testing.assert_allclose(rows[0], np.full(W, 1.25))
    assert c.max_updates() == 1
    ids, grads = c.collect_dirty()
    assert list(ids) == [1]
    np.testing.assert_allclose(grads[0], np.full(W, 0.25))
    assert c.max_updates() == 0
    ids2, _ = c.collect_dirty()
    assert len(ids2) == 0


@pytest.mark.skipif(NativeCache.load_lib() is None,
                    reason="no C++ toolchain")
def test_native_python_equivalence_random_workload():
    rng = np.random.RandomState(0)
    nc = NativeCache(limit=8, width=W, policy="LRU")
    pc = PythonCache(limit=8, width=W, policy="LRU")
    for step in range(200):
        op = rng.randint(3)
        ids = rng.randint(0, 32, size=rng.randint(1, 5))
        ids = np.unique(ids)
        if op == 0:
            rows = rng.randn(len(ids), W).astype(np.float32)
            nc.insert(ids, rows)
            pc.insert(ids, rows)
        elif op == 1:
            r1, h1 = nc.lookup(ids)
            r2, h2 = pc.lookup(ids)
            np.testing.assert_array_equal(h1, h2)
            np.testing.assert_allclose(r1[h1], r2[h2], rtol=1e-6)
        else:
            d = rng.randn(len(ids), W).astype(np.float32)
            assert nc.update(ids, d) == pc.update(ids, d)
    assert nc.size() == pc.size()


def _server_with_table(key="emb", vocab=64):
    server = PSServer()
    server.param_init(key, (vocab, W), "normal", 0.0, 1.0, seed=3)
    return server


def test_cstable_lookup_update_flush():
    server = _server_with_table()
    t = CacheSparseTable(limit=16, vocab_size=64, width=W, key="emb",
                         comm=server, policy="LRU", push_bound=10)
    ids = np.array([3, 5, 3, 9])
    rows = t.embedding_lookup(ids)
    want = server.sparse_pull("emb", ids)
    np.testing.assert_allclose(rows, want, rtol=1e-6)
    # local update visible immediately (write-back)
    t.embedding_update([3], np.full((1, W), -0.5))
    rows2 = t.embedding_lookup([3])
    np.testing.assert_allclose(rows2[0], want[0] - 0.5, rtol=1e-6)
    # server not yet updated (push_bound=10)
    np.testing.assert_allclose(server.sparse_pull("emb", [3])[0], want[0],
                               rtol=1e-6)
    t.flush()
    np.testing.assert_allclose(server.sparse_pull("emb", [3])[0],
                               want[0] - 0.5, rtol=1e-6)


def test_cstable_push_bound_zero_pushes_immediately():
    server = _server_with_table(key="emb2")
    t = CacheSparseTable(limit=16, vocab_size=64, width=W, key="emb2",
                         comm=server, push_bound=0)
    base = server.sparse_pull("emb2", [7]).copy()
    t.embedding_lookup([7])
    t.embedding_update([7], np.full((1, W), 1.0))
    np.testing.assert_allclose(server.sparse_pull("emb2", [7]),
                               base + 1.0, rtol=1e-6)


def test_cstable_staleness_sync_two_clients():
    """Worker B's push bumps server versions; worker A's next lookup
    re-syncs rows beyond its pull bound (the HET bounded-staleness loop)."""
    server = _server_with_table(key="emb3")
    a = CacheSparseTable(limit=16, vocab_size=64, width=W, key="emb3",
                         comm=server, pull_bound=0, push_bound=0)
    b = CacheSparseTable(limit=16, vocab_size=64, width=W, key="emb3",
                         comm=server, pull_bound=0, push_bound=0)
    a.embedding_lookup([11])            # A caches row 11
    b.embedding_lookup([11])
    b.embedding_update([11], np.full((1, W), 2.0))   # bumps server version
    rows = a.embedding_lookup([11])     # A must see B's update
    np.testing.assert_allclose(rows[0], server.sparse_pull("emb3", [11])[0],
                               rtol=1e-6)
    assert a.num_synced_rows >= 1


def test_cstable_perf_counters():
    server = _server_with_table(key="emb4")
    t = CacheSparseTable(limit=4, vocab_size=64, width=W, key="emb4",
                         comm=server)
    t.embedding_lookup([1, 2, 3])
    t.embedding_lookup([1, 2, 3])
    s = t.perf_summary()
    assert s["pulled_rows"] == 3
    assert s["hit_rate"] > 0
    assert s["cache_size"] == 3


def test_cstable_eviction_flushes_to_ps():
    server = _server_with_table(key="emb5")
    t = CacheSparseTable(limit=2, vocab_size=64, width=W, key="emb5",
                         comm=server, policy="LRU", push_bound=100)
    base = server.sparse_pull("emb5", [1]).copy()
    t.embedding_lookup([1, 2])
    t.embedding_update([1], np.full((1, W), 3.0))
    # cache full: pulling two new ids evicts id 1 (dirty) -> push to PS
    t.embedding_lookup([4, 5])
    np.testing.assert_allclose(server.sparse_pull("emb5", [1]),
                               base + 3.0, rtol=1e-6)


def test_cstable_read_your_writes_under_sync():
    """A's unpushed local update must survive another worker's push (dirty
    lines are excluded from staleness refresh)."""
    server = _server_with_table(key="emb6")
    a = CacheSparseTable(limit=16, vocab_size=64, width=W, key="emb6",
                         comm=server, pull_bound=0, push_bound=5)
    b = CacheSparseTable(limit=16, vocab_size=64, width=W, key="emb6",
                         comm=server, pull_bound=0, push_bound=0)
    base = server.sparse_pull("emb6", [7])[0].copy()
    a.embedding_lookup([7])
    a.embedding_update([7], np.full((1, W), -0.5))   # unpushed (bound=5)
    b.embedding_lookup([7])
    b.embedding_update([7], np.full((1, W), 2.0))    # pushed immediately
    rows = a.embedding_lookup([7])                   # must keep A's -0.5
    np.testing.assert_allclose(rows[0], base - 0.5, rtol=1e-6)
    # after A flushes, everyone converges to base + 2.0 - 0.5
    a.flush()
    rows_a = a.embedding_lookup([7])
    np.testing.assert_allclose(server.sparse_pull("emb6", [7])[0],
                               base + 1.5, rtol=1e-6)
    np.testing.assert_allclose(rows_a[0], base + 1.5, rtol=1e-6)


def test_cstable_flush_without_comm_preserves_state():
    t = CacheSparseTable(limit=4, vocab_size=8, width=W, key="x", comm=None)
    t.cache.insert([1], np.ones((1, W)))
    t.cache.update([1], np.full((1, W), 0.5))
    t.flush()   # no comm: must NOT drain the accumulators
    ids, grads = t.cache.collect_dirty()
    assert list(ids) == [1]
    np.testing.assert_allclose(grads[0], np.full(W, 0.5))


def test_cstable_async_overlap_consistency():
    """Async lookups interleaved with sync updates serialize on the lock
    and end in a consistent state."""
    server = _server_with_table(key="emb7")
    t = CacheSparseTable(limit=32, vocab_size=64, width=W, key="emb7",
                         comm=server, push_bound=1)
    rng = np.random.RandomState(0)
    futs = []
    for step in range(50):
        ids = rng.randint(0, 64, size=8)
        futs.append(t.embedding_lookup_async(ids))
        t.embedding_update(ids, rng.randn(8, W).astype(np.float32) * 0.01)
    for f in futs:
        assert f.result().shape == (8, W)
    t.flush()
    s = t.perf_summary()
    assert s["lookups"] == 50


def test_update_assume_unique_matches_default():
    """The executor's phase B passes device-deduped unique rows with
    assume_unique=True; result must equal the default dedup path."""
    from hetu_tpu.cache.cstable import CacheSparseTable
    from hetu_tpu.ps.server import PSServer
    W = 4
    PSServer._instance = None
    srv = PSServer.get()
    for key, flag in (("au_a", False), ("au_b", True)):
        srv.param_init(key, (32, W), "constant", 1.0)
        t = CacheSparseTable(16, 32, W, key, comm=srv)
        ids = np.array([3, 7, 11])
        t.embedding_lookup(ids)
        t.embedding_update(ids, np.full((3, W), 0.25, np.float32),
                           assume_unique=flag)
        t.flush()
    a = srv.sparse_pull("au_a", np.array([3, 7, 11]))
    b = srv.sparse_pull("au_b", np.array([3, 7, 11]))
    np.testing.assert_allclose(a, b)
    PSServer._instance = None


def test_fetch_rows_alignment_with_shuffled_server_order():
    """_fetch_rows must realign rows when the server returns ids in a
    different order than requested (the vectorized argsort/searchsorted
    path)."""
    from hetu_tpu.cache.cstable import CacheSparseTable

    class ShufflingComm:
        """sync_embedding answering in REVERSED id order."""
        def __init__(self, table):
            self.table = table
        def sync_embedding(self, key, ids, stored, bound):
            ids = np.asarray(ids, np.int64)[::-1]
            return ids, self.table[ids], np.ones(len(ids), np.int64)
        def push_embedding(self, key, ids, rows, versions=None):
            pass

    table = np.arange(64, dtype=np.float32).reshape(16, 4)
    t = CacheSparseTable(8, 16, 4, "shuf", comm=ShufflingComm(table))
    ids = np.array([2, 9, 5])
    rows = t.embedding_lookup(ids)
    np.testing.assert_allclose(rows, table[ids])
