"""Continuous-batching serving engine (hetu_tpu/serving): the
iteration-level scheduler, slot-structured KV cache, masking
correctness, and backpressure — each pinned separately.

The load-bearing contract: engine outputs are a pure function of each
Request (prompt, seed, settings) — token-identical to offline
``generate_fast`` for greedy, identical across arrival orders and slot
assignments for sampling — while short requests leave the batch early
and new ones take their slots between fused decode steps.

Weights are a deterministic random GPT parameter dict (the engine's
contract is numeric parity, not model quality), so the whole file runs
in seconds; it is part of the ``smoke`` battery except the bench
speedup measurement.
"""

import json
import os

import numpy as np
import pytest

import hetu_tpu as ht  # noqa: F401  (platform forcing + compat shims)
from hetu_tpu.models import GPTConfig
from hetu_tpu.models.gpt_decode import generate_fast, tp_shard_params
from hetu_tpu.serving import (
    KVCacheManager, QueueFull, Request, ServingEngine, ServingMetrics,
    round_up_pow2,
)

def _rand_gpt(name="sv", L=2, H=2, Dh=8, V=61, S=32, seed=0):
    """Deterministic random params in generate_fast's naming contract."""
    rng = np.random.RandomState(seed)
    hd = H * Dh
    p = {f"{name}_wte_table": rng.randn(V, hd) * 0.05,
         f"{name}_wpe": rng.randn(S, hd) * 0.05,
         f"{name}_ln_f_scale": np.ones(hd),
         f"{name}_ln_f_bias": np.zeros(hd)}
    for i in range(L):
        us = f"{name}_h{i}"
        for w, shp in [("attn_q", (hd, hd)), ("attn_k", (hd, hd)),
                       ("attn_v", (hd, hd)), ("attn_proj", (hd, hd)),
                       ("ffn_wi", (hd, 4 * hd)), ("ffn_wo", (4 * hd, hd))]:
            p[f"{us}_{w}_weight"] = rng.randn(*shp) * 0.05
            p[f"{us}_{w}_bias"] = np.zeros(shp[1])
        for ln in ("ln1", "ln2"):
            p[f"{us}_{ln}_scale"] = np.ones(hd)
            p[f"{us}_{ln}_bias"] = np.zeros(hd)
    cfg = GPTConfig(vocab_size=V, hidden_size=hd, num_hidden_layers=L,
                    num_attention_heads=H, max_position_embeddings=S,
                    batch_size=1, seq_len=S, dropout_rate=0.0)
    return p, cfg


@pytest.fixture(scope="module")
def model():
    return _rand_gpt()


@pytest.mark.smoke
class TestKVCacheManager:
    def test_pow2_bucketing(self):
        assert round_up_pow2(5) == 8
        assert round_up_pow2(8) == 8
        assert round_up_pow2(3, floor=8) == 8
        m = KVCacheManager(layers=1, heads=1, head_dim=4, slots=3,
                           max_seq_len=20)
        assert m.n_slots == 4 and m.s_max == 32
        assert m.cache_k.shape == (1, 4, 32, 1, 4)
        assert m.bucket_prompt(3) == 8 and m.bucket_prompt(9) == 16

    def test_pos_cap_bounds_bucket(self):
        m = KVCacheManager(layers=1, heads=1, head_dim=4, slots=2,
                           max_seq_len=16, pos_cap=16)
        assert m.s_max == 16          # bucket never exceeds the wpe table
        with pytest.raises(ValueError):
            KVCacheManager(layers=1, heads=1, head_dim=4, slots=2,
                           max_seq_len=24, pos_cap=16)

    def test_alloc_release_cycle(self):
        m = KVCacheManager(layers=1, heads=1, head_dim=4, slots=2,
                           max_seq_len=16)
        a = m.alloc("r0", 3)
        b = m.alloc("r1", 5)
        assert {a, b} == {0, 1} and m.alloc("r2", 1) is None
        assert m.occupancy == 1.0 and m.live() == [0, 1]
        m.advance(a, 2)
        assert m.lengths[a] == 5
        m.release(a)
        assert m.free_slots == 1 and m.owner[a] is None
        with pytest.raises(ValueError):
            m.release(a)              # double free
        assert m.alloc("r3", 4) == a  # recycled
        assert m.total_allocs == 3
        with pytest.raises(ValueError):
            m.alloc("r4", 99)         # longer than S_max


@pytest.mark.smoke
class TestEngineParity:
    def test_greedy_matches_generate_fast_any_order(self, model):
        """Acceptance: per-request engine output token-identical to the
        offline path, for mixed lengths, any arrival order, any slot."""
        p, cfg = model
        trace = [([7, 8, 9], 6), ([3, 4], 11), ([1, 2, 3, 4, 5], 4),
                 ([11], 7), ([20, 21, 22, 23], 9), ([40], 3)]
        want = {tuple(pr): generate_fast(p, cfg, [pr], num_tokens=n)[0]
                for pr, n in trace}
        for order, slots in [(trace, 2), (trace[::-1], 2), (trace, 4)]:
            eng = ServingEngine(p, cfg, slots=slots, queue_limit=16)
            reqs = [Request(prompt=pr, max_new_tokens=n)
                    for pr, n in order]
            res = eng.run(reqs)
            assert len(res) == len(reqs)
            for r in reqs:
                got = res[r.request_id]
                assert got.finish_reason == "length"
                assert got.tokens.tolist() == \
                    want[tuple(r.prompt)].tolist()

    def test_eos_stops_engine_and_matches_offline(self, model):
        """EOS retirement: the engine emits the EOS then frees the slot;
        tokens equal the offline eos_id run up to the EOS (offline pads
        the remainder of its fixed span)."""
        p, cfg = model
        prompt, n = [7, 8, 9], 8
        plain = generate_fast(p, cfg, [prompt], num_tokens=n)[0]
        eos = int(plain[len(prompt)])     # first generated token
        off = generate_fast(p, cfg, [prompt], num_tokens=n, eos_id=eos,
                            pad_id=0)[0]
        eng = ServingEngine(p, cfg, slots=2)
        res = eng.run([Request(prompt=prompt, max_new_tokens=n,
                               eos_id=eos)])
        got = next(iter(res.values()))
        assert got.finish_reason == "eos"
        assert got.tokens[-1] == eos
        k = len(got.tokens)
        assert got.tokens.tolist() == off[:k].tolist()
        assert (off[k:] == 0).all()       # offline padded the tail

    def test_sampling_deterministic_across_arrival_orders(self, model):
        """Per-request rng streams + traced per-slot settings: sampled
        outputs identical no matter the submission order or slot."""
        p, cfg = model
        spec = [([3, 4], 0.9, 5, 11), ([7, 8, 9], 0.7, 3, 22),
                ([11], 1.1, 0, 33), ([5, 6], 0.8, 4, 44)]

        def run(order, slots):
            eng = ServingEngine(p, cfg, slots=slots, queue_limit=16)
            reqs = [Request(prompt=pr, max_new_tokens=6, temperature=t,
                            top_k=k, seed=s) for pr, t, k, s in order]
            res = eng.run(reqs)
            return {tuple(r.prompt): res[r.request_id].tokens.tolist()
                    for r in reqs}

        a = run(spec, 2)
        b = run(spec[::-1], 2)
        c = run(spec[1:] + spec[:1], 4)
        assert a == b == c

    def test_streaming_callback_order(self, model):
        p, cfg = model
        seen = []
        eng = ServingEngine(p, cfg, slots=2)
        req = Request(prompt=[7, 8, 9], max_new_tokens=5,
                      stream_cb=lambda r, t: seen.append((r.request_id, t)))
        res = eng.run([req])
        got = res[req.request_id]
        assert [t for _, t in seen] == got.generated
        assert all(rid == req.request_id for rid, _ in seen)

    def test_bf16_cache_composes(self, model):
        """dtype=bfloat16 halves weights AND the slot cache; greedy
        outputs match the offline bf16 path token-for-token."""
        import jax.numpy as jnp
        p, cfg = model
        want = generate_fast(p, cfg, [[7, 8, 9]], num_tokens=6,
                             dtype=jnp.bfloat16)[0]
        eng = ServingEngine(p, cfg, slots=2, dtype=jnp.bfloat16)
        assert eng.kv.cache_k.dtype == jnp.bfloat16
        res = eng.run([Request(prompt=[7, 8, 9], max_new_tokens=6)])
        got = next(iter(res.values()))
        assert got.tokens.tolist() == want.tolist()

    def test_tp_sharded_params_compose(self, model):
        """tp_shard_params placements survive into the fused serving
        step (GSPMD propagates the Megatron split through the per-slot
        scatter + attention); outputs identical to unsharded."""
        from hetu_tpu.parallel.mesh import make_mesh
        p, cfg = _rand_gpt(name="tps", H=4, Dh=8)
        base = ServingEngine(p, cfg, slots=2).run(
            [Request(prompt=[7, 8, 9], max_new_tokens=6),
             Request(prompt=[3, 4], max_new_tokens=8)])
        mesh = make_mesh({"tp": 4})
        sharded = tp_shard_params(p, mesh, cfg)
        res = ServingEngine(sharded, cfg, slots=2).run(
            [Request(prompt=[7, 8, 9], max_new_tokens=6),
             Request(prompt=[3, 4], max_new_tokens=8)])
        assert sorted(r.tokens.tolist() for r in base.values()) == \
            sorted(r.tokens.tolist() for r in res.values())


@pytest.mark.smoke
class TestSchedulerEdgeCases:
    def test_queue_full_backpressure(self, model):
        p, cfg = model
        eng = ServingEngine(p, cfg, slots=1, queue_limit=2)
        a = eng.submit(Request(prompt=[1], max_new_tokens=2))
        b = eng.submit(Request(prompt=[2], max_new_tokens=2))
        with pytest.raises(QueueFull):
            eng.submit(Request(prompt=[3], max_new_tokens=2))
        assert eng.metrics.rejected == 1
        # draining re-opens admission; everything accepted completes
        while eng.pending:
            eng.step()
        c = eng.submit(Request(prompt=[3], max_new_tokens=2))
        out = eng.run()
        assert set(out) == {c.request_id}
        assert eng.metrics.finished == 3
        # an impossible request is rejected outright, not queued
        with pytest.raises(ValueError):
            eng.submit(Request(prompt=[1] * 30, max_new_tokens=10))

    def test_same_length_degenerates_to_static_batching(self, model):
        """All requests the same shape, submitted together: one
        admission wave, full batch every step, one retirement wave —
        exactly static batching."""
        p, cfg = model
        eng = ServingEngine(p, cfg, slots=4, queue_limit=8)
        reqs = [Request(prompt=[i + 1, i + 2, i + 3], max_new_tokens=6)
                for i in range(4)]
        res = eng.run(reqs)
        assert len(res) == 4
        snap = eng.metrics.snapshot()
        assert snap["mean_batch_occupancy"] == 1.0
        # prefill emits token 1; the remaining 5 come from 5 fused steps
        assert eng.steps == 5
        assert eng.kv.total_allocs == 4   # no slot ever recycled

    def test_long_straggler_slots_cycle(self, model):
        """One long request pins a slot while short ones cycle through
        the other: iteration-level retirement admits mid-flight."""
        p, cfg = model
        eng = ServingEngine(p, cfg, slots=2, queue_limit=16)
        straggler = Request(prompt=[1], max_new_tokens=14)
        shorts = [Request(prompt=[7, 8], max_new_tokens=2)
                  for _ in range(5)]
        res = eng.run([straggler] + shorts)
        assert len(res) == 6
        assert res[straggler.request_id].n_generated == 14
        # every short rode the straggler's window through recycled slots
        assert eng.kv.total_allocs == 6
        snap = eng.metrics.snapshot()
        assert snap["mean_batch_occupancy"] > 0.6
        # engine outputs still match offline per-request
        want = generate_fast(p, cfg, [straggler.prompt],
                             num_tokens=14)[0]
        assert res[straggler.request_id].tokens.tolist() == want.tolist()

    def test_short_circuit_finish_at_prefill(self, model):
        """max_new_tokens=1 (or instant EOS) retires at admission — the
        slot frees before the fused step even runs."""
        p, cfg = model
        eng = ServingEngine(p, cfg, slots=1)
        res = eng.run([Request(prompt=[7, 8, 9], max_new_tokens=1),
                       Request(prompt=[3, 4], max_new_tokens=1)])
        assert all(r.n_generated == 1 for r in res.values())
        assert eng.steps == 0             # never needed a decode step
        assert eng.kv.total_allocs == 2


@pytest.mark.smoke
class TestServingMetrics:
    def test_jsonl_events_follow_launcher_convention(self, model,
                                                     tmp_path):
        p, cfg = model
        log = str(tmp_path / "serve.jsonl")
        eng = ServingEngine(p, cfg, slots=2, log_path=log)
        eng.run([Request(prompt=[7, 8], max_new_tokens=3),
                 Request(prompt=[9], max_new_tokens=4)])
        with open(log) as f:
            recs = [json.loads(line) for line in f]
        kinds = [r["event"] for r in recs]
        assert kinds.count("serve_submit") == 2
        assert kinds.count("serve_admit") == 2
        assert kinds.count("serve_finish") == 2
        # the launcher's record shape: numeric epoch "t" + "event"
        assert all(isinstance(r["t"], float) and "event" in r
                   for r in recs)
        fin = [r for r in recs if r["event"] == "serve_finish"]
        assert {r["reason"] for r in fin} == {"length"}

    def test_snapshot_aggregates(self, model):
        p, cfg = model
        eng = ServingEngine(p, cfg, slots=2)
        eng.run([Request(prompt=[7, 8], max_new_tokens=4),
                 Request(prompt=[9], max_new_tokens=6)])
        s = eng.metrics.snapshot()
        assert s["requests_finished"] == 2
        assert s["tokens_generated"] == 10
        assert s["tokens_per_sec"] > 0
        assert s["ttft_p50_s"] is not None \
            and s["ttft_p99_s"] >= s["ttft_p50_s"]
        assert 0 < s["mean_batch_occupancy"] <= 1.0
        assert s["steps"] == eng.steps

    def test_env_log_path(self, model, tmp_path, monkeypatch):
        log = str(tmp_path / "env.jsonl")
        monkeypatch.setenv("HETU_SERVE_LOG", log)
        m = ServingMetrics()
        m.record_submit("r", 1)
        assert os.path.exists(log)


def test_bench_serve_continuous_beats_static(tmp_path, monkeypatch):
    """Acceptance: under the seeded mixed-length trace, continuous
    batching measures higher useful-token throughput than the static
    pad-to-longest baseline on the same harness, the masked-vs-ragged
    fast-path A/B records per-phase timings with GREEDY-IDENTICAL
    outputs, and flash prefill beats the scan prefill at prompt length
    128 — all recorded in the artifact."""
    import bench
    monkeypatch.setattr(bench, "_SERVE_FILE",
                        str(tmp_path / "BENCH_SERVE.json"))
    art = bench.bench_serve("cpu", reduced=True)
    cont = art["continuous"]["tokens_per_sec"]
    stat = art["static_baseline"]["tokens_per_sec"]
    assert cont > stat, (cont, stat)
    assert art["speedup"] > 1.0
    assert art["continuous"]["ttft_p50_s"] is not None
    assert art["continuous"]["mean_batch_occupancy"] > 0
    # request-lifecycle observability rides the same replay (ISSUE 7):
    # the artifact records the tail decomposition + SLO state
    obs = art["observability"]
    assert obs["explain_tail"]["dominant_component"] in obs["components"]
    assert obs["health"] in ("ok", "degraded", "breach")
    assert obs["slo"]["health"] == obs["health"]
    # fast-path A/B: acceptance is greedy parity + per-phase numbers
    # (the ragged-vs-masked WIN is an on-chip claim — interpret-mode
    # emulation pays per-block overhead on CPU; suite stage 4c measures)
    for section in ("fast_path_ab", "prefill_heavy"):
        ab = art[section]
        assert ab["greedy_identical"] is True
        for path in ("masked", "ragged"):
            assert ab[path]["tokens_per_sec"] > 0
            assert ab[path]["prefill_total_s"] is not None
            assert ab[path]["decode_total_s"] is not None
    # flash prefill beats the teacher-forced scan at P=128 even on the
    # CPU harness (the scan pays P sequential [1, D] dispatch rounds)
    pf = art["phase_ab"]["prefill"]
    assert pf["prompt_len"] >= 128
    assert pf["flash_ms"] < pf["scan_ms"], pf
    assert len(art["phase_ab"]["decode"]) == 2
    for row in art["phase_ab"]["decode"]:
        assert row["masked_ms"] > 0 and row["ragged_ms"] > 0
    # paged-vs-contiguous at equal cache bytes on the prefix-heavy
    # trace: identical greedy outputs, and the paged pool holds >= 2x
    # the concurrent slots (the shared system prompt is stored once and
    # requests reserve actual spans, not S_max)
    pg = art["paged_ab"]
    assert pg["greedy_identical"] is True
    assert pg["slot_capacity_ratio"] >= 2.0, pg
    assert pg["paged"]["hbm_bytes_per_slot"] * 2 <= \
        pg["contiguous"]["hbm_bytes_per_slot"], pg
    assert pg["paged"]["kv"]["prefix_hits"] > 0
    assert pg["paged"]["kv"]["cow_copies"] > 0
    # fleet A/B at equal resources (ISSUE 8): greedy parity single
    # engine vs the 2-replica router, both rates + fleet TTFT p99
    # recorded live, and the overload run proves the shedding contract
    # — throughput-class shed first, admitted latency-class TTFT p95
    # inside the configured SLO
    fl = art["fleet_ab"]
    assert fl["provenance"] == "live" and fl["platform"] == "cpu"
    assert fl["greedy_identical"] is True
    assert fl["single_engine"]["tokens_per_sec"] > 0
    assert fl["fleet"]["tokens_per_sec"] > 0
    assert fl["fleet"]["ttft_p99_s"] is not None
    assert all(n > 0 for n in fl["fleet"]["routed_per_replica"])
    # rolling-swap A/B (ISSUE 15): the v1 -> v2 rollout lands mid-trace
    # with zero loss, every result version-stamped, and the mid-swap
    # throughput above the availability floor (also asserted in-bench)
    sw = art["swap_ab"]
    assert sw["provenance"] == "live" and sw["platform"] == "cpu"
    assert sw["rolling"]["rollout_state"] == "done"
    assert sw["rolling"]["lost"] == 0 and sw["steady"]["lost"] == 0
    assert sw["rolling"]["fleet_versions"] == {0: 2, 1: 2}
    assert sum(sw["rolling"]["served_by_version"].values()) == \
        sw["rolling"]["finished"]
    assert sw["availability"] is not None and sw["availability"] >= 0.25
    # elastic-fleet A/B (ISSUE 16): at equal peak capacity over the
    # same diurnal trace, the autoscaled arm actually scales (>= 1 up
    # and >= 1 down), loses nothing, spends fewer virtual
    # replica-seconds at equal-or-better SLO attainment, and stays
    # token-identical to the static arm (floors also asserted in-bench)
    asc = art["autoscale_ab"]
    assert asc["provenance"] == "live" and asc["platform"] == "cpu"
    assert asc["static"]["lost"] == 0 and asc["autoscaled"]["lost"] == 0
    assert asc["static"]["scale_ups"] == 0 \
        and asc["static"]["scale_downs"] == 0
    assert asc["autoscaled"]["scale_ups"] >= 1
    assert asc["autoscaled"]["scale_downs"] >= 1
    assert asc["autoscaled"]["replica_seconds"] < \
        asc["static"]["replica_seconds"]
    assert asc["replica_seconds_saved"] > 0
    assert asc["autoscaled"]["slo_attainment"] >= \
        asc["static"]["slo_attainment"] >= 0.98
    assert asc["autoscaled"]["peak_replicas"] == 2
    assert asc["token_identical_common"] > 0
    ov = fl["overload_shed"]
    assert ov["shed"] > 0
    assert ov["shed_by_class"]["latency"] == 0
    assert ov["shed_by_class"]["throughput"] == ov["shed"]
    assert ov["latency_within_slo"] is True
    # speculative A/B (ISSUE 10): greedy token-identity spec-vs-plain,
    # a wall-clock tok/s win at the acceptance-1.0 endpoint (floor also
    # asserted in-bench), acceptance + mean-k stamped on the row, the
    # temperature sweep degrading acceptance with identity intact, and
    # TPOT percentiles from real per-step token counts in both modes
    sa = art["spec_ab"]
    assert sa["provenance"] == "live" and sa["platform"] == "cpu"
    assert sa["greedy_identical"] is True
    assert sa["speedup"] > 0
    if (os.cpu_count() or 1) >= 2:
        # 1-core hosts serialize draft + batched verify onto the same
        # core, so the wall-clock floor only binds with >= 2 cores
        # (mirrors the in-bench gate; identity/acceptance floors below
        # bind everywhere)
        assert sa["speedup"] >= 1.05
    assert sa["spec"]["acceptance_rate"] >= 0.95
    assert sa["spec"]["mean_k"] > 0
    assert sa["spec"]["tokens_per_step_mean"] > \
        sa["plain"]["tokens_per_step_mean"]
    for row in (sa["plain"], sa["spec"]):
        assert row["tpot_p50_s"] is not None
        assert row["tpot_p99_s"] >= row["tpot_p50_s"]
    for srow in sa["acceptance_sweep"]:
        assert srow["identical"] is True
        assert srow["acceptance_rate"] <= sa["spec"]["acceptance_rate"]
    # tiered-KV prefix storm (ISSUE 17): at equal pool size, the
    # ladder saves strictly more recompute tokens than drop-on-evict
    # with zero loss and greedy identity everywhere, the full ladder
    # cycles (spills, fetches, ring -> PS demotions), and the
    # PS-chaos arm degrades (ps_dead) without taking a replica down
    # (floors also asserted in-bench)
    storm = art["prefix_storm_ab"]
    assert storm["provenance"] == "live" and storm["platform"] == "cpu"
    assert storm["greedy_identical"] is True
    for arm in ("drop_on_evict", "tiered", "tiered_ps_chaos"):
        row = storm[arm]
        assert row["lost"] == 0 and row["shed"] == 0 \
            and row["rejected"] == 0, (arm, row)
        assert row["replica_restarts"] == 0, (arm, row)
    assert storm["recompute_tokens_saved_delta"] > 0, storm
    assert storm["tiered"]["recompute_tokens_saved"] > \
        storm["drop_on_evict"]["recompute_tokens_saved"]
    tst = storm["tiered"]["tiers"]
    assert sum(tst["spills"].values()) > 0
    assert sum(tst["fetches"].values()) > 0
    assert tst["demotes"] > 0
    cst = storm["tiered_ps_chaos"]["tiers"]
    assert cst["ps_dead"] is True and cst["ps_entries"] == 0
    assert storm["drop_on_evict"]["tiers"] is None
    # mixed-mode ragged dispatch (ISSUE 18): greedy token-identity
    # ragged-vs-phase-split on the mixed trace, chunk_stall EXACTLY
    # zero in the ragged arm while the phase-split arm still pays it,
    # and tok/s no worse (strict speedup is an on-chip claim — stage
    # 4c; floors also asserted in-bench)
    ra = art["ragged_ab"]
    assert ra["provenance"] == "live" and ra["platform"] == "cpu"
    assert ra["greedy_identical"] is True
    assert ra["ragged"]["chunk_stall_p99_ms"] in (None, 0.0), ra
    assert ra["phase_split"]["chunk_stall_p99_ms"] > 0, ra
    assert ra["speedup"] > 0
    assert ra["ragged"]["tail_dominant"] != "chunk_stall_ms"
    for arm in ("phase_split", "ragged"):
        assert ra[arm]["tokens_per_sec"] > 0
        assert ra[arm]["ttft_p99_s"] is not None
    # MoE vs dense at equal active params (ISSUE 20): greedy identity
    # vs offline at un-binding capacity (drop rate exactly zero), the
    # binding probe drops while load+drop still accounts for every
    # (token, rank), and expert telemetry rides the artifact (floors
    # also asserted in-bench; stage 4c banks moe_ab on chip)
    ma = art["moe_ab"]
    assert ma["provenance"] == "live" and ma["platform"] == "cpu"
    assert ma["greedy_identical"] is True
    assert ma["moe"]["drop_rate"] == 0.0
    assert ma["moe"]["expert_imbalance"] >= 1.0
    assert len(ma["moe"]["expert_load"]) == \
        ma["equal_active_params"]["experts"]
    assert sum(ma["moe"]["expert_load"]) > 0
    assert ma["equal_active_params"]["active_ffn_per_token"] == \
        ma["equal_active_params"]["dense_ffn_size"]
    assert ma["capacity_binding"]["drop_rate"] > 0
    assert ma["capacity_binding"]["invariant_ok"] is True
    for arm in ("dense", "moe"):
        assert ma[arm]["tokens_per_sec"] > 0
        assert ma[arm]["ttft_p99_s"] is not None
    assert ma["speedup_vs_dense"] > 0
    with open(tmp_path / "BENCH_SERVE.json") as f:
        on_disk = json.load(f)
    assert on_disk["continuous"]["tokens_per_sec"] == cont
    assert on_disk["static_baseline"]["tokens_per_sec"] == stat
    assert on_disk["fast_path_ab"]["greedy_identical"] is True
    assert on_disk["fleet_ab"]["greedy_identical"] is True
    assert on_disk["prefix_storm_ab"]["greedy_identical"] is True
