"""MoE expert-parallel serving (ISSUE 20): the flagship MoE GPT decodes
through the ONE compiled core.

The load-bearing contracts:

- **Identity**: an ``MoEDecodeConfig`` model decodes TOKEN-IDENTICALLY
  through ServingEngine and offline ``generate_fast`` across every
  cache configuration — contiguous (ref + fast), block-table paged,
  int8-quantized KV, speculative (draft skips routing), ragged mixed
  wave, and chunked prefill.
- **Dense oracle**: ``top_k == num_experts`` at non-binding capacity
  with replicated experts (``convert_dense_to_moe``) reproduces the
  dense model's greedy stream exactly — raw softmax combine weights
  sum to 1, so any gate renormalization bug breaks this test.
- **Attribution**: routed + dropped == wave tokens x top_k x MoE
  layers, per serve_step record — enforced live by the engine counters
  and offline by ``hetu_trace --check``.
- **Static rejection**: a malformed expert mesh (axis missing, E not
  divisible) and a broken dispatch/combine a2a pairing fail in
  ``analysis.shard_check`` before any compile.
- **EP parity**: the explicit shard_map + lax.all_to_all reference
  formulation matches the local ``moe_ffn`` at non-binding capacity,
  with and without the int8 wire (``HETU_MOE_QUANT``).
"""

import json

import numpy as np
import pytest

import hetu_tpu as ht  # noqa: F401  (platform forcing + compat shims)
import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from hetu_tpu.models import GPTConfig
from hetu_tpu.models.gpt_decode import generate_fast
from hetu_tpu.models.moe_decode import (
    MoEDecodeConfig, MoESpec, convert_dense_to_moe, ep_shard_params,
    init_moe_params, moe_capacity, moe_ffn, moe_ffn_ep_reference,
    moe_spec_of,
)
from hetu_tpu.serving import Request, ServingEngine
from hetu_tpu.analysis.shard_check import (
    ShardCheckError, check_expert_alltoall, check_expert_mesh,
)


PROMPTS = [[5, 9, 2], [7, 1, 4, 3, 8], [11, 6]]
MAX_NEW = 8


def _moe_cfg(**kw):
    base = dict(vocab_size=97, hidden_size=32, num_hidden_layers=4,
                num_attention_heads=2, ffn_mult=2, seq_len=48,
                dropout_rate=0.0, max_position_embeddings=48,
                num_experts=4, top_k=2, capacity_factor=2.0, moe_every=2)
    base.update(kw)
    return MoEDecodeConfig(**base)


@pytest.fixture(scope="module")
def model():
    cfg = _moe_cfg()
    params = init_moe_params(cfg, name="moe", seed=0)
    return params, cfg


@pytest.fixture(scope="module")
def offline_ref(model):
    params, cfg = model
    ref = {}
    for i, p in enumerate(PROMPTS):
        toks = generate_fast(params, cfg, [p], MAX_NEW,
                             temperature=0.0, seed=0, name="moe")
        ref[i] = [int(t) for t in np.asarray(toks)[0][len(p):]]
    return ref


def _dense_params(rng, name, L, D, F, V, S):
    p = {f"{name}_wte_table": rng.randn(V, D).astype(np.float32) * 0.05,
         f"{name}_wpe": rng.randn(S, D).astype(np.float32) * 0.05,
         f"{name}_ln_f_scale": np.ones(D, np.float32),
         f"{name}_ln_f_bias": np.zeros(D, np.float32)}
    for i in range(L):
        us = f"{name}_h{i}"
        for w, shp in [("attn_q", (D, D)), ("attn_k", (D, D)),
                       ("attn_v", (D, D)), ("attn_proj", (D, D)),
                       ("ffn_wi", (D, F)), ("ffn_wo", (F, D))]:
            p[f"{us}_{w}_weight"] = \
                rng.randn(*shp).astype(np.float32) * 0.05
            p[f"{us}_{w}_bias"] = np.zeros(shp[1], np.float32)
        for ln in ("ln1", "ln2"):
            p[f"{us}_{ln}_scale"] = np.ones(D, np.float32)
            p[f"{us}_{ln}_bias"] = np.zeros(D, np.float32)
    return p


def _mk(n=len(PROMPTS)):
    return [Request(request_id=str(i), prompt=PROMPTS[i],
                    max_new_tokens=MAX_NEW, temperature=0.0, seed=0)
            for i in range(n)]


def _run_engine(params, cfg, **kw):
    eng = ServingEngine(params, cfg, slots=4, name="moe", **kw)
    out = eng.run(_mk())
    got = {int(i): [int(t) for t in
                    np.asarray(r.tokens)[r.prompt_len:]]
           for i, r in out.items()}
    return eng, got


ENGINE_MATRIX = [
    ("contiguous_ref", dict(fast_path=False, paged=False, ragged=False)),
    ("contiguous_fast", dict(fast_path=True, paged=False, ragged=False)),
    ("paged", dict(fast_path=True, paged=16, ragged=False)),
    ("paged_int8", dict(fast_path=True, paged=16, kv_quant="int8",
                        ragged=False)),
    ("spec", dict(fast_path=True, paged=False, spec=2, ragged=False)),
    ("ragged", dict(fast_path=True, paged=16, ragged=True)),
    ("ragged_chunked", dict(fast_path=True, paged=16, prefill_chunk=2,
                            ragged=True)),
]


class TestEngineIdentity:
    @pytest.mark.parametrize("label,kw", ENGINE_MATRIX,
                             ids=[m[0] for m in ENGINE_MATRIX])
    def test_engine_matches_offline(self, model, offline_ref, label, kw):
        params, cfg = model
        eng, got = _run_engine(params, cfg, **kw)
        assert got == offline_ref, label
        # MoE accounting closed THE invariant: every valid token was
        # either granted an expert slot or dropped, k slots per token
        # per MoE layer (draft proposals route nothing)
        n_moe = moe_spec_of(cfg).moe_layers(cfg.num_hidden_layers)
        total = int(eng.expert_load.sum() + eng.expert_drops.sum())
        assert total == eng.moe_tokens * cfg.top_k * n_moe
        assert eng.moe_tokens > 0
        assert eng.expert_imbalance is not None
        assert eng.expert_drop_rate is not None

    def test_dense_engine_has_no_moe_counters(self):
        cfg = GPTConfig(vocab_size=61, hidden_size=16,
                        num_hidden_layers=2, num_attention_heads=2,
                        max_position_embeddings=32, batch_size=1,
                        seq_len=32, dropout_rate=0.0)
        params = _dense_params(np.random.RandomState(0), "dn", L=2,
                               D=16, F=64, V=61, S=32)
        eng = ServingEngine(params, cfg, slots=2, name="dn")
        assert eng.moe is None
        assert eng.expert_imbalance is None
        assert eng.expert_drop_rate is None


class TestDenseOracle:
    def test_k_equals_E_replicated_experts_reproduce_dense(self):
        """convert_dense_to_moe + top_k == num_experts at non-binding
        capacity is the dense model bit-for-bit (greedy)."""
        dense_cfg = GPTConfig(vocab_size=97, hidden_size=32,
                              num_hidden_layers=2,
                              num_attention_heads=2, ffn_mult=2,
                              max_position_embeddings=48, batch_size=1,
                              seq_len=48, dropout_rate=0.0)
        p = _dense_params(np.random.RandomState(1), "or", L=2, D=32,
                          F=64, V=97, S=48)
        moe_cfg = _moe_cfg(num_hidden_layers=2, num_experts=4, top_k=4,
                           capacity_factor=8.0, moe_every=1)
        mp = convert_dense_to_moe(p, dense_cfg, moe_cfg, name="or")

        for prompt in PROMPTS:
            want = generate_fast(p, dense_cfg, [prompt], MAX_NEW,
                                 temperature=0.0, seed=0, name="or")
            got = generate_fast(mp, moe_cfg, [prompt], MAX_NEW,
                                temperature=0.0, seed=0, name="or")
            np.testing.assert_array_equal(np.asarray(want),
                                          np.asarray(got))

    def test_moe_ffn_dense_oracle_direct(self):
        """The FFN function itself: replicated experts + k=E at
        non-binding capacity == plain dense gelu FFN numerically."""
        rng = np.random.RandomState(2)
        D, F, E, T = 16, 32, 4, 12
        wi = rng.randn(D, F).astype(np.float32) * 0.1
        wo = rng.randn(F, D).astype(np.float32) * 0.1
        bi = rng.randn(F).astype(np.float32) * 0.1
        bo = rng.randn(D).astype(np.float32) * 0.1
        params = {
            "m_h0_moe_gate_weight": np.zeros((D, E), np.float32),
            "m_h0_moe_expert_stack_w1": np.broadcast_to(
                wi, (E, D, F)).copy(),
            "m_h0_moe_expert_stack_b1": np.broadcast_to(
                bi, (E, F)).copy(),
            "m_h0_moe_expert_stack_w2": np.broadcast_to(
                wo, (E, F, D)).copy(),
            "m_h0_moe_expert_stack_b2": np.broadcast_to(
                bo, (E, D)).copy(),
        }
        spec = MoESpec(num_experts=E, top_k=E, capacity_factor=8.0,
                       moe_every=1)
        x = rng.randn(T, D).astype(np.float32)
        y = moe_ffn(params, "m_h0", jnp.asarray(x), spec)
        from hetu_tpu.models.moe_decode import _gelu_tanh
        want = _gelu_tanh(x @ wi + bi) @ wo + bo
        np.testing.assert_allclose(np.asarray(y), np.asarray(want),
                                   atol=2e-5)

    def test_capacity_binding_drops_to_residual(self):
        """A token past capacity contributes EXACTLY zero (residual
        carries), and load+drop accounts for every (token, rank)."""
        rng = np.random.RandomState(3)
        D, F, E, T = 16, 32, 4, 16
        params = {
            "m_h0_moe_gate_weight":
                rng.randn(D, E).astype(np.float32) * 5.0,
            "m_h0_moe_expert_stack_w1":
                rng.randn(E, D, F).astype(np.float32) * 0.1,
            "m_h0_moe_expert_stack_w2":
                rng.randn(E, F, D).astype(np.float32) * 0.1,
        }
        x = rng.randn(T, D).astype(np.float32)
        # cap tiny: cf such that capacity binds hard
        spec = MoESpec(num_experts=E, top_k=1, capacity_factor=0.25,
                       moe_every=1)
        cap = moe_capacity(spec, T)
        stats = {}
        y = np.asarray(moe_ffn(params, "m_h0", jnp.asarray(x), spec,
                               stats=stats))
        load = np.asarray(stats["load"])
        drop = np.asarray(stats["drop"])
        assert int(load.sum() + drop.sum()) == T * spec.top_k
        assert np.all(load <= cap)
        assert int(drop.sum()) > 0  # the fixture actually binds
        # recompute who got dropped, assert their output rows are 0
        gates = np.asarray(jax.nn.softmax(
            x @ params["m_h0_moe_gate_weight"], axis=-1))
        top1 = gates.argmax(1)
        arrival = np.zeros(E, int)
        for t in range(T):
            e = top1[t]
            if arrival[e] >= cap:
                np.testing.assert_allclose(y[t], 0.0, atol=1e-7)
            arrival[e] += 1

    def test_valid_mask_excludes_rows_from_capacity(self):
        """An invalid row neither routes nor claims a slot a valid
        token needed (batch-company independence)."""
        rng = np.random.RandomState(4)
        D, F, E, T = 16, 32, 4, 8
        params = {
            "m_h0_moe_gate_weight":
                rng.randn(D, E).astype(np.float32),
            "m_h0_moe_expert_stack_w1":
                rng.randn(E, D, F).astype(np.float32) * 0.1,
            "m_h0_moe_expert_stack_w2":
                rng.randn(E, F, D).astype(np.float32) * 0.1,
        }
        spec = MoESpec(num_experts=E, top_k=2, capacity_factor=8.0,
                       moe_every=1)
        x = rng.randn(T, D).astype(np.float32)
        valid = np.ones(T, bool)
        valid[T // 2:] = False
        stats = {}
        y = np.asarray(moe_ffn(params, "m_h0", jnp.asarray(x), spec,
                               valid=jnp.asarray(valid), stats=stats))
        # invalid rows produce exactly zero and claim zero slots
        np.testing.assert_allclose(y[T // 2:], 0.0, atol=1e-7)
        assert int(np.asarray(stats["load"]).sum()
                   + np.asarray(stats["drop"]).sum()) == \
            (T // 2) * spec.top_k
        # valid rows equal the all-valid run's rows (no interference)
        y_full = np.asarray(moe_ffn(params, "m_h0",
                                    jnp.asarray(x[:T // 2]), spec))
        np.testing.assert_allclose(y[:T // 2], y_full, atol=1e-5)


class TestTraceAttribution:
    def _trace(self, model, tmp_path, **kw):
        params, cfg = model
        log = str(tmp_path / "moe.jsonl")
        eng = ServingEngine(params, cfg, slots=4, name="moe",
                            log_path=log, **kw)
        eng.run(_mk())
        with open(log) as f:
            return log, [json.loads(ln) for ln in f]

    def test_green_stream_passes_check(self, model, tmp_path):
        from hetu_tpu.telemetry import trace as trace_mod
        log, recs = self._trace(model, tmp_path, fast_path=True,
                                paged=16)
        steps = [r for r in recs if r.get("event") == "serve_step"
                 and "moe_routed" in r]
        assert steps, "serve_step records must carry MoE attribution"
        for r in steps:
            assert r["moe_routed"] + r["moe_dropped"] == \
                r["moe_tokens"] * r["moe_k"] * r["moe_layers"]
        assert trace_mod.main([log, "--check"]) == 0
        assert trace_mod.check_moe_attribution(recs) == []

    def test_spec_stream_passes_check(self, model, tmp_path):
        from hetu_tpu.telemetry import trace as trace_mod
        log, recs = self._trace(model, tmp_path, fast_path=True,
                                spec=2)
        assert trace_mod.main([log, "--check"]) == 0

    def test_tampered_step_flagged(self, model, tmp_path):
        from hetu_tpu.telemetry import trace as trace_mod
        _, recs = self._trace(model, tmp_path, fast_path=True)
        step = next(r for r in recs if r.get("event") == "serve_step"
                    and "moe_routed" in r)
        bad = dict(step)
        bad["moe_routed"] = bad["moe_routed"] + 7
        problems = trace_mod.check_moe_attribution(recs + [bad])
        assert len(problems) == 1

    def test_dense_steps_exempt(self):
        from hetu_tpu.telemetry import trace as trace_mod
        assert trace_mod.check_moe_attribution(
            [{"event": "serve_step", "t": 0.0, "batch": 2,
              "new_tokens": 2}]) == []

    def test_malformed_companions_flagged(self):
        from hetu_tpu.telemetry import trace as trace_mod
        rec = {"event": "serve_step", "t": 0.0, "batch": 1,
               "new_tokens": 1, "moe_routed": 4,
               "moe_dropped": "zero", "moe_tokens": 2, "moe_k": 2,
               "moe_layers": 1}
        assert len(trace_mod.check_moe_attribution([rec])) == 1


class TestShardCheckExpertMesh:
    def test_valid_mesh_accepted(self):
        mesh = Mesh(np.array(jax.devices()[:4]), ("ep",))
        assert check_expert_mesh(mesh, 4, "ep") == 4
        assert check_expert_mesh(mesh, 8, "ep") == 4

    def test_indivisible_experts_rejected(self):
        mesh = Mesh(np.array(jax.devices()[:4]), ("ep",))
        with pytest.raises(ShardCheckError) as e:
            check_expert_mesh(mesh, 3, "ep")
        assert e.value.kind == "expert_mesh"

    def test_missing_axis_rejected(self):
        mesh = Mesh(np.array(jax.devices()[:4]), ("dp",))
        with pytest.raises(ShardCheckError) as e:
            check_expert_mesh(mesh, 4, "ep")
        assert e.value.kind == "expert_mesh"

    def test_no_mesh_rejected(self):
        with pytest.raises(ShardCheckError) as e:
            check_expert_mesh(None, 4, "ep")
        assert e.value.kind == "expert_mesh"

    def test_ep_shard_params_rejects_bad_mesh_before_placement(self):
        cfg = _moe_cfg(num_experts=3)
        params = init_moe_params(cfg, name="moe", seed=0)
        mesh = Mesh(np.array(jax.devices()[:4]), ("ep",))
        with pytest.raises(ShardCheckError):
            ep_shard_params(params, mesh, cfg, axis="ep", name="moe")


class TestShardCheckA2APairing:
    """Graph fixtures for check_expert_alltoall — the quant-pair
    analog: dispatch without combine / odd exchange chain / mixed axes
    all fail statically with kind='a2a_pair'."""

    E, CAP, T, D = 4, 4, 8, 8

    def _gate_feeds(self):
        idx = ht.graph.ops_misc.Variable(
            "a2a_idx", value=(np.arange(self.T) % self.E)
            .astype(np.float32).reshape(-1, 1), trainable=False)
        loc = ht.graph.ops_misc.Variable(
            "a2a_loc", value=(np.arange(self.T) // self.E)
            .astype(np.float32), trainable=False)
        gts = ht.graph.ops_misc.Variable(
            "a2a_gts", value=np.ones(self.T, np.float32),
            trainable=False)
        return idx, loc, gts

    def _dispatch(self, x):
        from hetu_tpu.graph.ops_moe import layout_transform_op
        idx, loc, _ = self._gate_feeds()
        return layout_transform_op(x, [idx], [loc], self.CAP, self.E)

    def test_green_full_span(self):
        from hetu_tpu.graph.ops_moe import (
            alltoall_op, reverse_layout_transform_op)
        x = ht.placeholder_op("x")
        d = self._dispatch(x)
        a1 = alltoall_op(d, axis="ep")
        a2 = alltoall_op(a1, axis="ep")
        idx, loc, gts = self._gate_feeds()
        c = reverse_layout_transform_op(a2, [idx], [loc], [gts],
                                        self.CAP, self.E)
        spans = check_expert_alltoall([c])
        assert len(spans) == 1
        assert len(spans[0][1]) == 2

    def test_green_layer_graph(self):
        """The real MoELayer graph (gate + dispatch + a2a + combine)
        is a green fixture end to end."""
        gate = ht.layers.TopKGate(self.D, self.T, self.E, k=1,
                                  capacity_factor=2.0)
        experts = ht.layers.StackedExperts(self.E, self.D, 16,
                                           activation="relu")
        moe = ht.layers.MoELayer(gate=gate, experts=experts,
                                 num_tokens=self.T, embed_dim=self.D)
        out, l_aux = moe(ht.placeholder_op("x"))
        check_expert_alltoall([out, l_aux])

    def test_uncombined_dispatch_rejected(self):
        x = ht.placeholder_op("x")
        d = self._dispatch(x)
        y = ht.reduce_mean_op(d, axes=0)
        with pytest.raises(ShardCheckError) as e:
            check_expert_alltoall([y])
        assert e.value.kind == "a2a_pair"

    def test_odd_exchange_chain_rejected(self):
        from hetu_tpu.graph.ops_moe import (
            alltoall_op, reverse_layout_transform_op)
        x = ht.placeholder_op("x")
        d = self._dispatch(x)
        a1 = alltoall_op(d, axis="ep")
        idx, loc, gts = self._gate_feeds()
        c = reverse_layout_transform_op(a1, [idx], [loc], [gts],
                                        self.CAP, self.E)
        with pytest.raises(ShardCheckError) as e:
            check_expert_alltoall([c])
        assert e.value.kind == "a2a_pair"

    def test_mixed_axes_rejected(self):
        from hetu_tpu.graph.ops_moe import (
            alltoall_op, reverse_layout_transform_op)
        x = ht.placeholder_op("x")
        d = self._dispatch(x)
        a1 = alltoall_op(d, axis="ep")
        a2 = alltoall_op(a1, axis="dp")
        idx, loc, gts = self._gate_feeds()
        c = reverse_layout_transform_op(a2, [idx], [loc], [gts],
                                        self.CAP, self.E)
        with pytest.raises(ShardCheckError) as e:
            check_expert_alltoall([c])
        assert e.value.kind == "a2a_pair"

    def test_orphan_combine_rejected(self):
        from hetu_tpu.graph.ops_moe import reverse_layout_transform_op
        x = ht.placeholder_op("x")
        idx, loc, gts = self._gate_feeds()
        c = reverse_layout_transform_op(x, [idx], [loc], [gts],
                                        self.CAP, self.E)
        with pytest.raises(ShardCheckError) as e:
            check_expert_alltoall([c])
        assert e.value.kind == "a2a_pair"


class TestTelemetryAndTop:
    def test_counters_and_top_sections(self, model, tmp_path):
        from hetu_tpu import telemetry
        from hetu_tpu.telemetry.top import (render, render_fleet,
                                            summarize, summarize_fleet)
        from hetu_tpu.telemetry.trace import read_events
        params, cfg = model
        telemetry.reset()
        log = str(tmp_path / "top.jsonl")
        eng = ServingEngine(params, cfg, slots=4, name="moe",
                            fast_path=True, paged=16, log_path=log,
                            tags={"replica": 0})
        eng.run(_mk())
        snap = telemetry.snapshot()
        assert snap["counters"].get("serve.expert_load", 0) > 0
        assert "serve.expert_imbalance" in snap["gauges"]
        assert "serve.expert_drop_rate" in snap["gauges"]
        events, bad = read_events([log])
        assert bad == 0
        s = summarize(events)
        assert s["moe"] is not None
        assert s["moe"]["routed"] == int(eng.expert_load.sum())
        assert s["moe"]["dropped"] == int(eng.expert_drops.sum())
        text = render(s)
        assert "experts" in text and "imbalance" in text
        fleet = summarize_fleet(events)
        row = fleet["replicas"][0]
        assert row["moe_routed"] == int(eng.expert_load.sum())
        assert row["moe_drop_rate"] is not None
        ftext = render_fleet(fleet)
        assert "imb" in ftext and "drop%" in ftext

    def test_dense_fleet_rows_render_dashes(self, tmp_path):
        from hetu_tpu.telemetry.top import render_fleet, summarize_fleet
        fleet = summarize_fleet([
            {"event": "serve_step", "t": 0.0, "batch": 1,
             "new_tokens": 1, "replica": 0}])
        assert "-" in render_fleet(fleet)

    def test_validate_serving_rejects_missing_expert_stack(self, model):
        from hetu_tpu.analysis import validate_serving
        from hetu_tpu.analysis.verify import GraphVerifyError
        params, cfg = model
        bad = dict(params)
        bad.pop("moe_h1_moe_expert_stack_w1")
        with pytest.raises(GraphVerifyError):
            validate_serving(bad, cfg, "moe")

    def test_validate_serving_rejects_wrong_expert_count(self, model):
        """The corrupt rolling-swap payload: a per-expert leaf whose
        leading dim disagrees with config.num_experts."""
        from hetu_tpu.analysis import validate_serving
        from hetu_tpu.analysis.verify import GraphVerifyError
        params, cfg = model
        bad = dict(params)
        bad["moe_h1_moe_expert_stack_w1"] = \
            bad["moe_h1_moe_expert_stack_w1"][:2]
        with pytest.raises(GraphVerifyError):
            validate_serving(bad, cfg, "moe")


class TestExpertParallel:
    CF_UNBINDING = 8.0

    def _fixture(self):
        cfg = _moe_cfg(num_hidden_layers=2, seq_len=32,
                       max_position_embeddings=32,
                       capacity_factor=self.CF_UNBINDING)
        params = init_moe_params(cfg, name="moe", seed=0)
        spec = moe_spec_of(cfg)
        mesh = Mesh(np.array(jax.devices()[:4]), ("ep",))
        x = np.random.RandomState(0).randn(16, 32).astype(np.float32)
        return cfg, params, spec, mesh, x

    def test_ep_reference_matches_local(self):
        cfg, params, spec, mesh, x = self._fixture()
        y_local = moe_ffn(params, "moe_h1", jnp.asarray(x), spec)
        placed = ep_shard_params(params, mesh, cfg, axis="ep",
                                 name="moe")
        y_ep = moe_ffn_ep_reference(placed, "moe_h1", jnp.asarray(x),
                                    spec, mesh)
        np.testing.assert_allclose(np.asarray(y_local),
                                   np.asarray(y_ep), atol=1e-4)

    def test_int8_wire_within_quant_tolerance(self):
        cfg, params, spec, mesh, x = self._fixture()
        y_local = moe_ffn(params, "moe_h1", jnp.asarray(x), spec)
        placed = ep_shard_params(params, mesh, cfg, axis="ep",
                                 name="moe")
        y_q = moe_ffn_ep_reference(placed, "moe_h1", jnp.asarray(x),
                                   spec, mesh, quant="int8")
        assert float(jnp.max(jnp.abs(y_local - y_q))) < 0.2

    def test_expert_stacks_actually_sharded(self):
        cfg, params, _, mesh, _ = self._fixture()
        placed = ep_shard_params(params, mesh, cfg, axis="ep",
                                 name="moe")
        w1 = placed["moe_h1_moe_expert_stack_w1"]
        shard_shapes = {s.data.shape for s in w1.addressable_shards}
        E, D, F = w1.shape
        assert shard_shapes == {(E // 4, D, F)}
        # gate replicates
        gw = placed["moe_h1_moe_gate_weight"]
        assert {s.data.shape for s in gw.addressable_shards} == \
            {tuple(gw.shape)}


class TestSwapAndSpec:
    def test_draft_spec_skips_routing(self, model):
        params, cfg = model
        spec = moe_spec_of(cfg, draft=True)
        assert spec.draft is True
        eng = ServingEngine(params, cfg, slots=4, name="moe",
                            fast_path=True, spec=2)
        assert eng.cfg_tuple_draft[-1].draft is True
        assert eng.cfg_tuple[-1].draft is False

    def test_capacity_env_override(self, model, monkeypatch):
        from hetu_tpu.models.moe_decode import resolve_moe_capacity
        monkeypatch.setenv("HETU_MOE_CAPACITY", "3.5")
        assert resolve_moe_capacity() == 3.5
        _, cfg = model
        assert moe_spec_of(cfg).capacity_factor == 3.5
        monkeypatch.setenv("HETU_MOE_CAPACITY", "")
        assert moe_spec_of(cfg).capacity_factor == \
            cfg.capacity_factor

    def test_version_stamped_swap_covers_expert_leaves(self, model,
                                                       offline_ref):
        """PR 15 rolling swap: a full-dict swap with identical values
        but bumped version keeps decoding identically, and the swap
        validates per-expert leaf shapes."""
        params, cfg = model
        eng = ServingEngine(params, cfg, slots=4, name="moe",
                            fast_path=True, paged=16)
        if not hasattr(eng, "swap_params"):
            pytest.skip("engine has no swap_params")
        eng.swap_params({k: np.asarray(v) for k, v in params.items()})
        out = eng.run(_mk())
        got = {int(i): [int(t) for t in
                        np.asarray(r.tokens)[r.prompt_len:]]
               for i, r in out.items()}
        assert got == offline_ref
