"""Launcher tests (reference: runner.py spawns PS+workers from yaml;
tests/pstests/test_apis.py exercises multi-worker push/pull through a
launched local cluster)."""

import os
import subprocess
import sys
import tempfile

import numpy as np
import pytest

from hetu_tpu.context import DistConfig
from hetu_tpu.launcher import launch, main, run_cluster


class TestDistConfigYaml:
    def test_yaml_parse(self):
        d = tempfile.mkdtemp()
        p = os.path.join(d, "cluster.yml")
        with open(p, "w") as f:
            f.write("""
nodes:
  - host: localhost
    chief: true
    servers: 1
    workers: 2
""")
        c = DistConfig(file=p)
        assert c.chief == "localhost"
        assert c.enable_PS and c.num_servers == 1 and c.num_workers == 2


class TestLaunch:
    def test_launch_runs_target_against_fresh_ps(self):
        def target():
            from hetu_tpu.ps.client import PSClient
            c = PSClient.get()
            c.parameter_init("w", (4,), init_type="constant", arg1=1.0)
            c.push("w", np.ones(4, np.float32))
            return np.asarray(c.pull("w"))

        out = launch(target)
        # constant-1 init, one push of ones with default server opt
        assert out.shape == (4,)
        assert np.all(np.isfinite(out))

    def test_launch_restores_env(self):
        before = os.environ.get("HETU_PS_ADDR")
        launch(lambda: None)
        assert os.environ.get("HETU_PS_ADDR") == before


class TestRunCluster:
    def test_two_workers_accumulate_on_shared_ps(self):
        """The reference's tier-3 pattern (test_apis.py:22-50): N worker
        processes push to one PS; total reflects both."""
        d = tempfile.mkdtemp()
        script = os.path.join(d, "worker.py")
        with open(script, "w") as f:
            f.write("""
import os, numpy as np
from hetu_tpu.ps.client import PSClient
c = PSClient.get()
rank = c.rank
c.parameter_init("acc", (2,), init_type="constant", arg1=0.0,
                 opt="sgd", opt_args={"learning_rate": 1.0})
c.BarrierWorker("init")
c.push("acc", -np.ones(2, np.float32))   # sgd lr=1: value += 1 per push
c.BarrierWorker("pushed")
val = np.asarray(c.pull("acc"))
assert np.allclose(val, 2.0), val
open(os.path.join(%r, f"ok{rank}"), "w").write("1")
""" % d)
        os.environ["HETU_PS_PORT"] = "23981"
        try:
            config = DistConfig(num_servers=1, num_workers=2)
            codes = run_cluster(config, [sys.executable, script])
        finally:
            os.environ.pop("HETU_PS_PORT", None)
        assert codes == [0, 0]
        assert os.path.exists(os.path.join(d, "ok0"))
        assert os.path.exists(os.path.join(d, "ok1"))


class TestCLI:
    def test_cli_no_command_errors(self):
        with pytest.raises(SystemExit):
            main(["-s", "0"])

    def test_cli_runs_local_worker(self):
        d = tempfile.mkdtemp()
        marker = os.path.join(d, "ran")
        code = main(["-w", "1", sys.executable, "-c",
                     f"open({marker!r}, 'w').write('1')"])
        assert code == 0
        assert os.path.exists(marker)
