"""Launcher tests (reference: runner.py spawns PS+workers from yaml;
tests/pstests/test_apis.py exercises multi-worker push/pull through a
launched local cluster)."""

import os
import subprocess
import sys
import tempfile

import numpy as np
import pytest

from hetu_tpu.context import DistConfig
from hetu_tpu.launcher import launch, main, run_cluster


class TestDistConfigYaml:
    def test_yaml_parse(self):
        d = tempfile.mkdtemp()
        p = os.path.join(d, "cluster.yml")
        with open(p, "w") as f:
            f.write("""
nodes:
  - host: localhost
    chief: true
    servers: 1
    workers: 2
""")
        c = DistConfig(file=p)
        assert c.chief == "localhost"
        assert c.enable_PS and c.num_servers == 1 and c.num_workers == 2


class TestLaunch:
    def test_launch_runs_target_against_fresh_ps(self):
        def target():
            from hetu_tpu.ps.client import PSClient
            c = PSClient.get()
            c.parameter_init("w", (4,), init_type="constant", arg1=1.0)
            c.push("w", np.ones(4, np.float32))
            return np.asarray(c.pull("w"))

        out = launch(target)
        # constant-1 init, one push of ones with default server opt
        assert out.shape == (4,)
        assert np.all(np.isfinite(out))

    def test_launch_restores_env(self):
        before = os.environ.get("HETU_PS_ADDR")
        launch(lambda: None)
        assert os.environ.get("HETU_PS_ADDR") == before


class TestRunCluster:
    def test_two_workers_accumulate_on_shared_ps(self):
        """The reference's tier-3 pattern (test_apis.py:22-50): N worker
        processes push to one PS; total reflects both."""
        d = tempfile.mkdtemp()
        script = os.path.join(d, "worker.py")
        with open(script, "w") as f:
            f.write("""
import os, numpy as np
from hetu_tpu.ps.client import PSClient
c = PSClient.get()
rank = c.rank
c.parameter_init("acc", (2,), init_type="constant", arg1=0.0,
                 opt="sgd", opt_args={"learning_rate": 1.0})
c.BarrierWorker("init")
c.push("acc", -np.ones(2, np.float32))   # sgd lr=1: value += 1 per push
c.BarrierWorker("pushed")
val = np.asarray(c.pull("acc"))
assert np.allclose(val, 2.0), val
open(os.path.join(%r, f"ok{rank}"), "w").write("1")
""" % d)
        os.environ["HETU_PS_PORT"] = "23981"
        try:
            config = DistConfig(num_servers=1, num_workers=2)
            codes = run_cluster(config, [sys.executable, script])
        finally:
            os.environ.pop("HETU_PS_PORT", None)
        assert codes == [0, 0]
        assert os.path.exists(os.path.join(d, "ok0"))
        assert os.path.exists(os.path.join(d, "ok1"))


class TestCLI:
    def test_cli_no_command_errors(self):
        with pytest.raises(SystemExit):
            main(["-s", "0"])

    def test_cli_runs_local_worker(self):
        d = tempfile.mkdtemp()
        marker = os.path.join(d, "ran")
        code = main(["-w", "1", sys.executable, "-c",
                     f"open({marker!r}, 'w').write('1')"])
        assert code == 0
        assert os.path.exists(marker)


class TestHeturnTrainEndToEnd:
    """The full reference tier-3 flow: `heturun -c cluster.yml python
    train.py` — yaml cluster config, launcher spawns the PS and two
    worker processes, each worker builds an Executor in Hybrid mode and
    TRAINS against the shared PS with a BSP barrier per step; both
    workers' embedding updates land in the one table."""

    @pytest.mark.parametrize("bsp,van", [(0, False), (1, False),
                                         (0, True)],
                             ids=["bsp", "ssp1", "bsp-van"])
    def test_cluster_yaml_hybrid_training(self, bsp, van):
        from hetu_tpu.ps.van import van_available
        if van and not van_available():
            pytest.skip("no C++ toolchain")
        from hetu_tpu.launcher import _free_port
        d = tempfile.mkdtemp()
        yml = os.path.join(d, "cluster.yml")
        with open(yml, "w") as f:
            f.write("""
nodes:
  - host: localhost
    chief: true
    servers: 1
    workers: 2
""")
        script = os.path.join(d, "train.py")
        with open(script, "w") as f:
            f.write("""
import os, sys
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
import hetu_tpu as ht
from hetu_tpu.ps.client import PSClient

OUT = %r
BSP = %d
V, D, B, STEPS = 16, 8, 8, 4
rank = int(os.environ["HETU_PS_RANK"])

ids_node = ht.placeholder_op("ids")
y = ht.placeholder_op("y")
emb = ht.layers.Embedding(V, D, name="e2e_table")
h = ht.embedding_lookup_op(emb.embedding_table, ids_node)
h = ht.reduce_mean_op(h, [1])
logits = ht.matmul_op(h, ht.init.xavier_uniform((D, 2), name="e2e_head"))
loss = ht.reduce_mean_op(ht.softmaxcrossentropy_op(logits, y), axes=0)
train = ht.optim.SGDOptimizer(learning_rate=0.5).minimize(loss)

# bsp=0: per-step BSP barrier across the two workers (reference
# BarrierWorker, ParameterServerCommunicate.py:49-53); bsp=k: SSP with
# staleness bound k (reference ssp_init/ssp_sync)
ex = ht.Executor({"train": [loss, train]}, comm_mode="Hybrid", bsp=BSP)
c = PSClient.get()
c.BarrierWorker("post_init")     # both executors finished param_set

rng = np.random.RandomState(100 + rank)
half = V // 2
losses = []
for _ in range(STEPS):
    # worker r touches only its half of the vocabulary
    idb = rng.randint(rank * half, (rank + 1) * half,
                      (B, 4)).astype(np.int32)
    yb = np.eye(2, dtype=np.float32)[rng.randint(0, 2, B)]
    out = ex.run("train", feed_dict={ids_node: idb, y: yb})
    losses.append(float(np.asarray(out[0])))
assert all(np.isfinite(l) for l in losses), losses
if os.environ.get("HETU_PS_VAN"):
    # the deployment-shaped proof: the server advertised its C++ van
    # and this worker's sparse traffic actually opened fast-tier
    # sockets (phase A/B may run in pool threads; the process-wide
    # registry sees every one)
    vport, vkeys = c.t.call("van_info")
    assert vport and "e2e_table_table" in vkeys, (vport, vkeys)
    assert len(c._van_clients) > 0
c.BarrierWorker("trained")

table = np.asarray(c.pull("e2e_table_table"))
init = np.asarray(ex.variables["e2e_table_table"].init_value(0))
delta = np.abs(table - init).sum(axis=1)
# MY half moved (I trained it)...
mine = slice(rank * half, (rank + 1) * half)
assert delta[mine].sum() > 1e-6, delta
# ...and the OTHER worker's half moved too: cross-process updates
# through the one shared PS table
other = slice((1 - rank) * half, (2 - rank) * half)
assert delta[other].sum() > 1e-6, delta
open(os.path.join(OUT, f"trained{rank}"), "w").write(
    repr(losses))
""" % (d, bsp))
        port = _free_port()
        env_old = os.environ.get("HETU_PS_PORT")
        van_old = os.environ.get("HETU_PS_VAN")
        os.environ["HETU_PS_PORT"] = str(port)
        if van:
            os.environ["HETU_PS_VAN"] = "1"
        else:
            # an ambient HETU_PS_VAN must not leak into the non-van
            # variants (the launcher copies os.environ into children)
            os.environ.pop("HETU_PS_VAN", None)
        try:
            code = main(["-c", yml, sys.executable, script])
        finally:
            if env_old is None:
                os.environ.pop("HETU_PS_PORT", None)
            else:
                os.environ["HETU_PS_PORT"] = env_old
            if van_old is None:
                os.environ.pop("HETU_PS_VAN", None)
            else:
                os.environ["HETU_PS_VAN"] = van_old
        assert code == 0
        assert os.path.exists(os.path.join(d, "trained0"))
        assert os.path.exists(os.path.join(d, "trained1"))


class TestSchedulerHeartbeat:
    """ps-lite Postoffice heartbeat-map parity (SURVEY §5.3): liveness
    DETECTION at the scheduler; recovery stays checkpoint/restart, as
    in the reference (no elastic replacement there either)."""

    def test_health_marks_silent_nodes_dead(self):
        import time as _t
        from hetu_tpu.ps.server import Scheduler
        sched = Scheduler()
        sched.heartbeat("worker", 0)
        sched.heartbeat("worker", 1)
        sched.heartbeat("server", 0)
        h = sched.health(stale_after=15.0)
        assert set(h) == {"worker:0", "worker:1", "server:0"}
        assert all(v["alive"] for v in h.values())
        # worker:1 goes silent; a tight staleness window flags it
        _t.sleep(0.25)
        sched.heartbeat("worker", 0)
        h = sched.health(stale_after=0.2)
        assert h["worker:0"]["alive"]
        assert not h["worker:1"]["alive"]

    def test_client_heartbeat_thread_over_tcp(self):
        import os
        import time as _t
        from hetu_tpu.ps.server import Scheduler
        from hetu_tpu.ps.client import PSClient, _LocalTransport
        import socket as _sock
        sched = Scheduler()
        srv = _sock.socket()
        srv.bind(("127.0.0.1", 0))
        port = srv.getsockname()[1]
        srv.close()
        sched.serve_tcp(port, block=False)
        old = os.environ.get("HETU_SCHEDULER_ADDR")
        os.environ["HETU_SCHEDULER_ADDR"] = f"127.0.0.1:{port}"
        try:
            c = PSClient(transport=_LocalTransport())
            assert c.start_heartbeat(interval=0.1, node_id=7)
            deadline = _t.time() + 10
            while _t.time() < deadline:
                if "worker:7" in sched.health():
                    break
                _t.sleep(0.05)
            h = sched.health(stale_after=5.0)
            assert h["worker:7"]["alive"]
            c.stop_heartbeat()
        finally:
            if old is None:
                os.environ.pop("HETU_SCHEDULER_ADDR", None)
            else:
                os.environ["HETU_SCHEDULER_ADDR"] = old
            sched.shutdown()
