"""GLUE processor suite (reference
examples/nlp/bert/glue_processor/glue.py): official TSV layouts ->
examples -> dense arrays -> fine-tuning, hermetically from checked-in
format-faithful fixtures."""

import os
import sys

import numpy as np
import pytest

from hetu_tpu.glue import (PROCESSORS, ColaProcessor, MnliProcessor,
                           MrpcProcessor, Sst2Processor, accuracy,
                           compute_metrics, convert_examples_to_arrays,
                           f1, matthews_corr)
from hetu_tpu.tokenizers import BertTokenizer

FIX = os.path.join(os.path.dirname(__file__), "fixtures", "glue")


@pytest.fixture(scope="module")
def tokenizer():
    return BertTokenizer.from_pretrained(os.path.join(FIX, "vocab.txt"))


class TestProcessors:
    def test_registry_covers_reference_tasks(self):
        # reference PROCESSORS = {cola, mnli, mrpc, sst-2}; qqp added
        for task in ("cola", "mnli", "mrpc", "sst-2", "qqp"):
            assert task in PROCESSORS

    def test_sst2_single_sentence(self):
        proc = Sst2Processor()
        train = proc.get_train_examples(os.path.join(FIX, "SST-2"))
        dev = proc.get_dev_examples(os.path.join(FIX, "SST-2"))
        assert len(train) == 80 and len(dev) == 16
        assert all(ex.text_b is None for ex in train)
        assert {ex.label for ex in train} == {"0", "1"}

    def test_cola_no_header_col3(self):
        proc = ColaProcessor()
        train = proc.get_train_examples(os.path.join(FIX, "CoLA"))
        assert len(train) == 8
        assert all(" " in ex.text_a for ex in train)   # real sentences
        assert {ex.label for ex in train} <= {"0", "1"}

    def test_mrpc_pairs(self):
        proc = MrpcProcessor()
        train = proc.get_train_examples(os.path.join(FIX, "MRPC"))
        assert len(train) == 6
        assert all(ex.text_b for ex in train)

    def test_mnli_three_way_and_dev_matched(self):
        proc = MnliProcessor()
        train = proc.get_train_examples(os.path.join(FIX, "MNLI"))
        dev = proc.get_dev_examples(os.path.join(FIX, "MNLI"))
        assert len(train) == 4 and len(dev) == 2
        assert proc.get_labels() == ["contradiction", "entailment",
                                     "neutral"]
        assert all(ex.label in proc.get_labels() for ex in train + dev)


class TestFeatureConversion:
    def test_pair_layout_and_padding(self, tokenizer):
        proc = MrpcProcessor()
        exs = proc.get_train_examples(os.path.join(FIX, "MRPC"))
        ids, mask, seg, labels = convert_examples_to_arrays(
            exs, proc.get_labels(), 24, tokenizer)
        v = tokenizer.vocab
        assert ids.shape == (6, 24)
        assert (ids[:, 0] == v["[CLS]"]).all()
        for j in range(len(exs)):
            valid = int(mask[j].sum())
            seps = np.where(ids[j, :valid] == v["[SEP]"])[0]
            assert len(seps) == 2 and seps[-1] == valid - 1
            assert (seg[j, :seps[0] + 1] == 0).all()
            assert (seg[j, seps[0] + 1:valid] == 1).all()
            assert (ids[j, valid:] == v["[PAD]"]).all()
        assert labels.dtype == np.int32

    def test_single_sentence_truncation(self, tokenizer):
        proc = Sst2Processor()
        exs = proc.get_train_examples(os.path.join(FIX, "SST-2"))
        ids, mask, seg, _ = convert_examples_to_arrays(
            exs, proc.get_labels(), 5, tokenizer)     # force truncation
        assert (mask.sum(axis=1) <= 5).all()
        assert (seg == 0).all()                        # no pair -> seg 0

    def test_mnli_label_map(self, tokenizer):
        proc = MnliProcessor()
        exs = proc.get_train_examples(os.path.join(FIX, "MNLI"))
        _, _, _, labels = convert_examples_to_arrays(
            exs, proc.get_labels(), 24, tokenizer)
        assert set(labels) <= {0, 1, 2}


class TestMetrics:
    def test_accuracy(self):
        assert accuracy([1, 0, 1], [1, 1, 1]) == pytest.approx(2 / 3)

    def test_matthews_known_value(self):
        # perfect prediction -> 1; inverted -> -1; constant -> 0
        assert matthews_corr([1, 0, 1, 0], [1, 0, 1, 0]) == 1.0
        assert matthews_corr([0, 1, 0, 1], [1, 0, 1, 0]) == -1.0
        assert matthews_corr([1, 1, 1, 1], [1, 0, 1, 0]) == 0.0

    def test_f1_known_value(self):
        # preds [1,1,0,0] vs gold [1,0,1,0]: tp=1 fp=1 fn=1 -> f1=0.5
        assert f1([1, 1, 0, 0], [1, 0, 1, 0]) == pytest.approx(0.5)

    def test_per_task_selection(self):
        m = compute_metrics("cola", [1, 0], [1, 0])
        assert "matthews_corr" in m
        m = compute_metrics("mrpc", [1, 0], [1, 0])
        assert "f1" in m
        m = compute_metrics("sst-2", [1, 0], [1, 0])
        assert set(m) == {"accuracy"}


class TestEndToEnd:
    def test_finetune_example_on_sst2_fixture(self):
        """The example script drives a real task end-to-end: SST-2
        fixture through the processor suite; the tiny task (good/bad
        word polarity) must be learned above chance."""
        import importlib.util
        path = os.path.join(os.path.dirname(__file__), "..", "examples",
                            "nlp", "finetune_bert_glue.py")
        spec = importlib.util.spec_from_file_location("ex_glue_task",
                                                      path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        old = sys.argv
        sys.argv = ["prog", "--task", "sst-2", "--data-dir",
                    os.path.join(FIX, "SST-2"), "--vocab-path",
                    os.path.join(FIX, "vocab.txt"),
                    "--num-layers", "1", "--hidden", "32", "--heads", "2",
                    "--batch-size", "8", "--seq-len", "16",
                    "--num-steps", "120", "--eval-every", "120",
                    "--learning-rate", "2e-3"]
        try:
            acc = mod.main()
        finally:
            sys.argv = old
        assert acc > 0.7, acc


def test_finetune_example_mnli_three_way_smoke():
    """The 3-label path end-to-end: MNLI's processor (dev_matched split,
    three-way labels) drives the example; num_labels comes from the
    processor, not the flag."""
    import importlib.util
    path = os.path.join(os.path.dirname(__file__), "..", "examples",
                        "nlp", "finetune_bert_glue.py")
    spec = importlib.util.spec_from_file_location("ex_glue_mnli", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    old = sys.argv
    sys.argv = ["prog", "--task", "mnli", "--data-dir",
                os.path.join(FIX, "MNLI"), "--vocab-path",
                os.path.join(FIX, "vocab.txt"),
                "--num-layers", "1", "--hidden", "32", "--heads", "2",
                "--batch-size", "4", "--seq-len", "24",
                "--num-steps", "4", "--eval-every", "4"]
    try:
        acc = mod.main()
    finally:
        sys.argv = old
    assert np.isfinite(acc) and 0.0 <= acc <= 1.0
