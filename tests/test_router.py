"""Serving fleet resilience (ISSUE 8 tentpole): the multi-replica
router over supervised engines.

The acceptance spine: a seeded ``HETU_CHAOS`` kill of one replica in an
N=2 fleet loses ZERO requests — everything the corpse held requeues to
the peer and retires exactly once, token-identical to offline
``generate_fast`` (outputs are a pure function of the Request), with
``router_hop`` attribution in the peer's ``ServingMetrics.snapshot()``,
contract-valid failure events and a flight dump on the killed replica,
and a span-balanced serve stream.  Around it: health-aware routing,
session affinity + remap prefix-miss counting, the per-replica circuit
breaker (ejection, half-open probe readmission), wedge detection by
stale heartbeat, SLO-class load shedding (throughput first,
latency-class TTFT inside the configured SLO), QueueFull backpressure
propagation, deadlines, retry exhaustion as a terminal failure, the
extended span-balance rule, and ``hetu_top --fleet``.

All CPU-harness, all smoke-tier (the engines are tiny random-weight
GPTs — the fleet's contract is scheduling and recovery, not model
quality).
"""

import json
import os
import time

import numpy as np
import pytest

import hetu_tpu as ht  # noqa: F401  (platform forcing + compat shims)
from hetu_tpu import telemetry
from hetu_tpu.models import GPTConfig
from hetu_tpu.models.gpt_decode import generate_fast
from hetu_tpu.ps import faults
from hetu_tpu.serving import (
    QueueFull, Request, RouterShed, ServingEngine, ServingRouter, SLO,
)
from hetu_tpu.serving.router import _session_hash
from hetu_tpu.telemetry import top
from hetu_tpu.telemetry.trace import check_span_balance, read_events

pytestmark = pytest.mark.smoke


def _rand_gpt(name="fl", L=2, H=2, Dh=8, V=61, S=32, seed=0):
    """Deterministic random params in generate_fast's naming contract."""
    rng = np.random.RandomState(seed)
    hd = H * Dh
    p = {f"{name}_wte_table": rng.randn(V, hd) * 0.05,
         f"{name}_wpe": rng.randn(S, hd) * 0.05,
         f"{name}_ln_f_scale": np.ones(hd),
         f"{name}_ln_f_bias": np.zeros(hd)}
    for i in range(L):
        us = f"{name}_h{i}"
        for w, shp in [("attn_q", (hd, hd)), ("attn_k", (hd, hd)),
                       ("attn_v", (hd, hd)), ("attn_proj", (hd, hd)),
                       ("ffn_wi", (hd, 4 * hd)), ("ffn_wo", (4 * hd, hd))]:
            p[f"{us}_{w}_weight"] = rng.randn(*shp) * 0.05
            p[f"{us}_{w}_bias"] = np.zeros(shp[1])
        for ln in ("ln1", "ln2"):
            p[f"{us}_{ln}_scale"] = np.ones(hd)
            p[f"{us}_{ln}_bias"] = np.zeros(hd)
    cfg = GPTConfig(vocab_size=V, hidden_size=hd, num_hidden_layers=L,
                    num_attention_heads=H, max_position_embeddings=S,
                    batch_size=1, seq_len=S, dropout_rate=0.0)
    return p, cfg


@pytest.fixture(scope="module")
def model():
    return _rand_gpt()


@pytest.fixture(autouse=True)
def _fresh(monkeypatch):
    monkeypatch.setenv("HETU_TELEMETRY", "1")
    monkeypatch.delenv("HETU_CHAOS", raising=False)
    faults.reset_plans()
    telemetry.reset()
    yield
    faults.reset_plans()
    telemetry.reset()


def _factory(model, **kw):
    p, cfg = model
    kw.setdefault("slots", 2)
    kw.setdefault("queue_limit", 16)
    kw.setdefault("fast_path", False)
    return lambda i: ServingEngine(p, cfg, **kw)


def _trace(n=6, seed=7, vocab=61, s_max=32):
    rng = np.random.RandomState(seed)
    out = []
    for _ in range(n):
        P = int(rng.randint(1, 5))
        out.append(([int(t) for t in rng.randint(0, vocab, P)],
                    int(rng.randint(3, 9))))
    return out


def _offline(model, req):
    p, cfg = model
    return generate_fast(p, cfg, [req.prompt],
                         num_tokens=req.max_new_tokens)[0].tolist()


# --------------------------------------------------------------------- #
# routing + affinity units
# --------------------------------------------------------------------- #

class TestRouting:
    def test_fleet_matches_offline_and_spreads_load(self, model):
        """Results are per-request identical to the offline path and
        every replica takes traffic (health-weighted placement prefers
        the idler replica as queues build)."""
        router = ServingRouter(_factory(model), replicas=2)
        reqs = [Request(prompt=pr, max_new_tokens=n)
                for pr, n in _trace(8)]
        res = router.run(reqs)
        assert len(res) == 8
        for r in reqs:
            assert res[r.request_id].tokens.tolist() == \
                _offline(model, r)
        snap = router.snapshot()
        assert snap["finished"] == 8 and snap["lost"] == 0
        assert all(row["routed"] > 0 for row in snap["replicas"])
        assert snap["health"] == "ok"

    def test_session_affinity_pins_home_replica(self, model):
        """All of one session's requests land on its stable-hash home
        replica while it is routable."""
        router = ServingRouter(_factory(model), replicas=2,
                               session_affinity=True)
        home = _session_hash("user-42", 2)
        for _ in range(4):
            router.submit(Request(prompt=[3, 4], max_new_tokens=3,
                                  session_id="user-42"))
        assert router._session_last["user-42"] == home
        assert router._placed[home] == 4
        assert router.prefix_misses == 0
        router.run()

    def test_affinity_remap_counts_prefix_miss(self, model):
        """The home replica is down: the session is remapped to a peer
        and the cold start is counted (prefix_misses)."""
        router = ServingRouter(_factory(model), replicas=2,
                               restart_backoff=5.0)
        home = _session_hash("sess", 2)
        router.submit(Request(prompt=[1, 2], max_new_tokens=2,
                              session_id="sess"))
        router.run()
        router.replicas[home].die(rc=1, error="test")
        router.step()   # drain + schedule respawn (long backoff)
        router.submit(Request(prompt=[1, 2], max_new_tokens=2,
                              session_id="sess"))
        assert router._session_last["sess"] != home
        assert router.prefix_misses == 1
        router.run()

    def test_submit_rejects_impossible_request(self, model):
        router = ServingRouter(_factory(model), replicas=1)
        with pytest.raises(ValueError):
            router.submit(Request(prompt=[1] * 30, max_new_tokens=10))

    def test_env_replica_count(self, model, monkeypatch):
        monkeypatch.setenv("HETU_REPLICAS", "3")
        router = ServingRouter(_factory(model))
        assert len(router.replicas) == 3


# --------------------------------------------------------------------- #
# circuit breaker
# --------------------------------------------------------------------- #

class TestCircuitBreaker:
    def test_open_half_open_close_cycle(self, model):
        """Consecutive-failure ejection, cooldown, half-open probe,
        readmission on the probe's retirement."""
        router = ServingRouter(_factory(model), replicas=2,
                               breaker_threshold=1,
                               breaker_cooldown=0.05,
                               restart_backoff=0.0)
        router.replicas[1].die(rc=1, error="test")
        router.step()
        assert router._breaker[1]["state"] == "open"
        assert not router._breaker_allows(1, time.perf_counter())
        time.sleep(0.06)
        router.step()   # respawn happened; breaker cooled down
        assert router._breaker_allows(1, time.perf_counter())
        assert router._breaker[1]["state"] == "half_open"
        # force the probe onto replica 1 by saturating replica 0
        for _ in range(router.replicas[0].engine.queue_limit):
            router.replicas[0].engine.submit(
                Request(prompt=[9], max_new_tokens=1))
        probe = Request(prompt=[5, 6], max_new_tokens=3)
        router.submit(probe)
        assert router._breaker[1]["probe"] == probe.request_id
        router.run()
        assert router._breaker[1]["state"] == "closed"
        kinds = [e.get("event") for e in telemetry.get_sink().recent()]
        assert "router_breaker" in kinds

    def test_open_breaker_ejects_from_routing(self, model):
        """While open, a healthy-looking replica takes no traffic."""
        router = ServingRouter(_factory(model), replicas=2,
                               breaker_threshold=1,
                               breaker_cooldown=30.0,
                               restart_backoff=0.0)
        router.replicas[1].die(rc=1, error="test")
        router.step()          # drain + respawn scheduling
        router.step()          # respawn (zero backoff)
        assert router.replicas[1].state == "up"
        for _ in range(4):
            router.submit(Request(prompt=[2, 3], max_new_tokens=2))
        assert router._placed[1] == 0     # breaker holds it out
        router.run()


# --------------------------------------------------------------------- #
# the acceptance spine: seeded chaos kill, zero loss
# --------------------------------------------------------------------- #

class TestChaosKillIntegration:
    def test_kill_a_replica_loses_nothing(self, model, tmp_path,
                                          monkeypatch):
        """Seeded HETU_CHAOS kills replica 1 mid-trace: every request
        retires exactly once (requeued, never lost or double-counted),
        token-identical to offline; the hop is attributed in the peer
        engine's snapshot; the killed replica leaves contract-valid
        failure events and a flight dump; the serve stream span-checks
        clean."""
        flog = str(tmp_path / "flight.jsonl")
        slog = str(tmp_path / "serve.jsonl")
        flg = str(tmp_path / "failure.jsonl")
        monkeypatch.setenv("HETU_FLIGHT_LOG", flog)
        monkeypatch.setenv("HETU_SERVE_LOG", slog)
        monkeypatch.setenv("HETU_FAILURE_LOG", flg)
        monkeypatch.setenv("HETU_CHAOS", "seed=3,kill=4,role=replica1")
        faults.reset_plans()
        router = ServingRouter(_factory(model), replicas=2,
                               restart_backoff=0.01)
        reqs = [Request(prompt=pr, max_new_tokens=n)
                for pr, n in _trace(8, seed=11)]
        res = router.run(reqs)
        # supervision continues past the drain: step until the killed
        # replica's backoff elapses and it respawns
        deadline = time.time() + 5.0
        while router.replicas[1].state != "up" and \
                time.time() < deadline:
            router.step()
            time.sleep(0.005)
        assert router.replicas[1].state == "up"
        # exactly once, zero loss, deterministic outputs
        assert len(res) == len(reqs)
        for r in reqs:
            assert res[r.request_id].tokens.tolist() == \
                _offline(model, r), r.request_id
        snap = router.snapshot()
        assert snap["requeued"] >= 1
        assert snap["lost"] == 0 and snap["duplicates"] == 0
        assert snap["finished"] == len(reqs)
        assert snap["replicas"][1]["restarts"] == 1
        # requeue/hop attribution: the peer's lifecycle components
        comp = router.replicas[0].engine.metrics.snapshot()["components"]
        assert comp["router_hop_ms"]["p99_ms"] > 0
        # failure events in the launcher's record shape
        events, bad = read_events([flg])
        assert bad == 0
        kinds = [e["event"] for e in events]
        assert "replica_exit" in kinds
        assert "replica_drain" in kinds
        assert "replica_restart" in kinds
        for e in events:
            assert telemetry.validate_record(e) == [], e
        # the kill's black box: a contract-valid flight dump
        fevents, fbad = read_events([flog])
        assert fbad == 0
        headers = [e for e in fevents if e["event"] == "flight_dump"]
        assert any(h["reason"] == "replica_chaos_kill" and
                   h.get("replica") == 1 for h in headers)
        for e in fevents:
            assert telemetry.validate_record(e) == [], e
        # the serve stream balances: every routed admit has a finish on
        # SOME replica (the hop exemption covers the killed one)
        sevents, sbad = read_events([slog])
        assert sbad == 0
        assert check_span_balance(sevents) == []
        hops = [e for e in sevents if e["event"] == "router_hop"]
        assert hops and all(e["to_replica"] == 0 for e in hops)

    def test_wedged_replica_detected_and_drained(self, model,
                                                 monkeypatch):
        """A chaos wedge (alive, silent) is caught by the stale
        heartbeat, killed, drained, and its requests retire on the
        peer."""
        monkeypatch.setenv("HETU_CHAOS", "seed=1,wedge=2,role=replica0")
        faults.reset_plans()
        router = ServingRouter(_factory(model), replicas=2,
                               stale=0.05, restart_backoff=0.05)
        reqs = [Request(prompt=[i + 1, i + 2], max_new_tokens=5)
                for i in range(6)]
        res = router.run(reqs)
        assert len(res) == 6
        for r in reqs:
            assert res[r.request_id].tokens.tolist() == \
                _offline(model, r)
        kinds = [e.get("event") for e in telemetry.get_sink().recent()]
        assert "replica_wedged_kill" in kinds
        assert router.snapshot()["lost"] == 0


# --------------------------------------------------------------------- #
# SLO-class shedding + backpressure + deadlines + terminal failures
# --------------------------------------------------------------------- #

class TestSheddingAndBackpressure:
    def test_throughput_sheds_first_latency_inside_slo(self, model):
        """Synthetic overload (tiny queues): throughput-class traffic
        is shed while every latency-class request admits and its fleet
        TTFT p95 stays inside the configured SLO."""
        slo_ms = 60000.0   # the configured latency SLO (generous: the
        # CPU harness proves ORDER and bounds, not chip latency)
        p, cfg = model
        factory = lambda i: ServingEngine(   # noqa: E731
            p, cfg, slots=1, queue_limit=2, fast_path=False,
            slo=[SLO("ttft", "latency", slo_ms)])
        router = ServingRouter(factory, replicas=2, shed_queue=0.5)
        lat, thr, shed = [], [], 0
        for i in range(16):
            cls = "latency" if i % 4 == 0 else "throughput"
            req = Request(prompt=[1, 2], max_new_tokens=3,
                          slo_class=cls)
            try:
                router.submit(req)
                (lat if cls == "latency" else thr).append(req)
            except RouterShed:
                shed += 1
                assert cls == "throughput"   # sheds throughput FIRST
            except QueueFull:
                # hard-full backpressure: drain one step and move on
                router.step()
        res = router.run()
        snap = router.snapshot()
        assert snap["shed"] == shed and shed > 0
        assert snap["classes"]["latency"]["shed"] == 0
        assert snap["classes"]["throughput"]["shed"] == shed
        # every admitted latency-class request finished, inside SLO
        for r in lat:
            assert r.request_id in res
        assert snap["classes"]["latency"]["finished"] == len(lat)
        assert snap["classes"]["latency"]["ttft_p95_s"] is not None
        assert snap["classes"]["latency"]["ttft_p95_s"] * 1e3 <= slo_ms
        shed_events = [e for e in telemetry.get_sink().recent()
                       if e.get("event") == "router_shed"]
        assert len(shed_events) == shed
        assert all(e["slo_class"] == "throughput" for e in shed_events)

    def test_hard_full_propagates_queuefull(self, model):
        """Latency-class traffic is never shed — at true capacity the
        replicas' QueueFull propagates up through the router."""
        p, cfg = model
        factory = lambda i: ServingEngine(   # noqa: E731
            p, cfg, slots=1, queue_limit=1, fast_path=False)
        router = ServingRouter(factory, replicas=2, shed_queue=0.99)
        with pytest.raises(QueueFull) as ei:
            for _ in range(8):
                router.submit(Request(prompt=[1], max_new_tokens=2,
                                      slo_class="latency"))
        assert not isinstance(ei.value, RouterShed)
        router.run()

    def test_deadline_expires_router_held_requests(self, model):
        """A request the router holds past its deadline expires with a
        router_deadline event instead of serving uselessly late."""
        router = ServingRouter(_factory(model), replicas=1,
                               restart_backoff=30.0)
        req = Request(prompt=[1, 2], max_new_tokens=4,
                      deadline_s=0.001)
        router.submit(req)
        router.replicas[0].die(rc=1, error="test")
        time.sleep(0.005)
        router.step()   # drain -> pending -> deadline check
        snap = router.snapshot()
        assert snap["expired"] == 1 and snap["pending"] == 0
        kinds = [e.get("event") for e in telemetry.get_sink().recent()]
        assert "router_deadline" in kinds

    def test_retry_exhaustion_is_terminal_with_flight_dump(
            self, model, tmp_path, monkeypatch):
        """Nowhere to place a held request past the retry budget: it is
        declared lost (loudly — event + flight dump), and a terminally
        dead fleet refuses new submissions."""
        flog = str(tmp_path / "flight.jsonl")
        monkeypatch.setenv("HETU_FLIGHT_LOG", flog)
        router = ServingRouter(_factory(model), replicas=1,
                               restart_limit=0, retry_limit=1,
                               retry_backoff=0.001)
        req = Request(prompt=[1, 2], max_new_tokens=4)
        router.submit(req)
        router.replicas[0].die(rc=1, error="test")
        deadline = time.time() + 5.0
        while router.pending and time.time() < deadline:
            router.step()
            time.sleep(0.002)
        snap = router.snapshot()
        assert snap["lost"] == 1 and snap["pending"] == 0
        assert router.replicas[0].terminal
        headers = [json.loads(l) for l in open(flog)
                   if '"flight_dump"' in l]
        reasons = {h["reason"] for h in headers}
        assert "router_retry_exhausted" in reasons
        assert "replica_budget_spent" in reasons
        with pytest.raises(RuntimeError):
            router.submit(Request(prompt=[1], max_new_tokens=2))

    def test_per_replica_queue_storm_dumps_flight(self, model,
                                                  tmp_path,
                                                  monkeypatch):
        """Sustained rejection by ONE replica dumps the flight ring
        with that replica attributed (the engine-global detector can't
        name the drowning replica)."""
        flog = str(tmp_path / "storm.jsonl")
        monkeypatch.setenv("HETU_FLIGHT_LOG", flog)
        p, cfg = model
        factory = lambda i: ServingEngine(   # noqa: E731
            p, cfg, slots=1, queue_limit=1, fast_path=False)
        router = ServingRouter(factory, replicas=1, shed_queue=2.0)
        router.submit(Request(prompt=[1], max_new_tokens=8))
        for _ in range(10):   # streak past the storm threshold (8)
            with pytest.raises(QueueFull):
                router.submit(Request(prompt=[3], max_new_tokens=2))
        headers = [json.loads(l) for l in open(flog)
                   if '"flight_dump"' in l]
        assert any(h["reason"] == "replica_queue_storm" and
                   h.get("replica") == 0 for h in headers)
        router.run()


# --------------------------------------------------------------------- #
# span balance (fleet rule) + hetu_top --fleet
# --------------------------------------------------------------------- #

class TestFleetObservability:
    def _rec(self, kind, **f):
        return {"t": 1.0, "event": kind, **f}

    def test_span_balance_flags_leaked_replica_admit(self):
        """An admit on replica 0 that finishes on replica 1 with NO
        router_hop is a leaked slot; the hop record exempts it."""
        stream = [self._rec("serve_admit", request="r1", slot=0,
                            ttft_s=0.1, replica=0),
                  self._rec("serve_admit", request="r1", slot=0,
                            ttft_s=0.1, replica=1),
                  self._rec("serve_finish", request="r1",
                            reason="length", n_generated=2, replica=1)]
        problems = check_span_balance(stream)
        assert len(problems) == 1 and "replica 0" in problems[0]
        exempt = stream + [self._rec("router_hop", request="r1",
                                     to_replica=1)]
        assert check_span_balance(exempt) == []

    def test_span_balance_unfinished_still_fails_fleetwide(self):
        stream = [self._rec("serve_admit", request="r2", slot=0,
                            ttft_s=0.1, replica=0),
                  self._rec("router_hop", request="r2", to_replica=1)]
        problems = check_span_balance(stream)
        assert problems and "never finished" in problems[0]

    def test_legacy_untagged_stream_unchanged(self):
        stream = [self._rec("serve_admit", request="r3", slot=0,
                            ttft_s=0.1),
                  self._rec("serve_finish", request="r3",
                            reason="eos", n_generated=2)]
        assert check_span_balance(stream) == []

    def test_hetu_top_fleet_rows(self, model, tmp_path, monkeypatch,
                                 capsys):
        """--fleet renders one row per replica (health/occupancy/queue/
        breaker) plus fleet totals from the merged stream alone."""
        slog = str(tmp_path / "serve.jsonl")
        flg = str(tmp_path / "failure.jsonl")
        monkeypatch.setenv("HETU_SERVE_LOG", slog)
        monkeypatch.setenv("HETU_FAILURE_LOG", flg)
        router = ServingRouter(_factory(model), replicas=2,
                               restart_backoff=0.01)
        reqs = [Request(prompt=pr, max_new_tokens=n)
                for pr, n in _trace(6, seed=23)]
        for r in reqs[:4]:
            router.submit(r)
        router.replicas[1].die(rc=1, error="test")
        router.run(reqs[4:])
        stats = top.summarize_fleet(read_events([slog, flg])[0])
        rows = {r["replica"]: r for r in stats["replicas"]}
        assert set(rows) == {0, 1}
        assert rows[1]["deaths"] == 1
        assert rows[0]["routed"] > 0
        assert stats["requeues"] >= 1   # the corpse's requests hopped
        rc = top.main([slog, flg, "--fleet", "--once"])
        out = capsys.readouterr().out
        assert rc == 0
        assert "hetu_top --fleet" in out
        assert "breaker" in out and "requeued" in out
        # per-replica rows present
        assert "\n  0 " in out and "\n  1 " in out
