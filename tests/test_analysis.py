"""Static-analysis subsystem (hetu_tpu/analysis/): graph verifier,
parallelism checker, lint rules, and the HETU_VALIDATE wiring.

The verifier's contract under test: a deliberately miswired graph —
shape mismatch (one case per ops family), bad mesh axis, uneven pp
stages — fails at BUILD time with the offending node named in the
error, never as a jit traceback; structural defects (cycles, duplicate
names, missing rng) and advisory findings (dead nodes, f32 creep in
bf16 subgraphs) are detected on the same walk; and every validation
emits JSONL records in the launcher's failure-log shape.
"""

import json
import os

import numpy as np
import pytest

import hetu_tpu as ht
from hetu_tpu import envvars
from hetu_tpu.analysis import (
    GraphVerifyError, ShardCheckError, check_collective_order_static,
    check_cycles, check_divisibility, check_mesh_axes,
    check_pipeline_stages, check_stage_assignment, collective_sequence,
    verify_graph,
)
from hetu_tpu.analysis.lint import RULES, lint_paths, lint_source
from hetu_tpu.graph import ops_comm
from hetu_tpu.graph.node import ShapeInferenceError, SimpleOp
from hetu_tpu.parallel.mesh import make_mesh
from jax.sharding import PartitionSpec as P

pytestmark = pytest.mark.smoke

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "fixtures", "lint")


def var(name, shape, dtype=np.float32):
    return ht.Variable(name, value=np.zeros(shape, dtype))


# --------------------------------------------------------------------- #
# graph verifier: one deliberate mismatch per ops family
# --------------------------------------------------------------------- #

class TestVerifierMismatches:
    def _expect(self, nodes, needle=None, **kw):
        with pytest.raises(GraphVerifyError) as ei:
            verify_graph(nodes, **kw)
        msg = str(ei.value)
        if needle:
            assert needle in msg, msg
        return ei.value

    def test_math_family(self):
        bad = ht.add_op(var("m_a", (4, 3)), var("m_b", (4, 4)))
        err = self._expect([bad], needle=bad.name)
        assert "float32(4, 3)" in str(err) and "float32(4, 4)" in str(err)
        assert err.node is bad

    def test_matmul_family(self):
        bad = ht.matmul_op(var("mm_a", (4, 3)), var("mm_b", (5, 6)))
        err = self._expect([bad], needle=bad.name)
        # producers are named too
        assert "mm_a" in str(err) and "mm_b" in str(err)

    def test_conv_family(self):
        bad = ht.conv2d_op(var("c_x", (2, 3, 8, 8)),
                           var("c_w", (4, 7, 3, 3)))
        self._expect([bad], needle=bad.name)

    def test_attention_family(self):
        from hetu_tpu.graph.ops_attention import flash_attention_op
        bad = flash_attention_op(var("q", (1, 2, 8, 4)),
                                 var("k", (1, 2, 8, 6)),
                                 var("v", (1, 2, 8, 4)))
        self._expect([bad], needle=bad.name)

    def test_moe_family(self):
        from hetu_tpu.graph.ops_moe import layout_transform_gradient_op
        bad = layout_transform_gradient_op(
            var("g", (8, 4)), var("idx", (8,), np.int32),
            var("loc", (6,), np.int32), capacity=2)
        self._expect([bad], needle=bad.name)

    def test_comm_family_bad_axis(self):
        mesh = make_mesh({"dp": 4})
        bad = ops_comm.allgatherCommunicate_op(var("cm_x", (8, 4)),
                                               axis="tp")
        with pytest.raises(ShardCheckError) as ei:
            check_mesh_axes([bad], mesh)
        assert bad.name in str(ei.value) and "'tp'" in str(ei.value)

    def test_good_graph_table(self):
        y = ht.matmul_op(var("g_a", (4, 3)), var("g_b", (3, 2)))
        loss = ht.reduce_mean_op(y, axes=0)
        rep = verify_graph([loss])
        assert rep.shape_of(y) == (4, 2)
        assert rep.shape_of(loss) == (2,)
        assert str(rep.dtype_of(y)) == "float32"


class TestVerifierStructural:
    def test_cycle_detected(self):
        a = var("cy_a", (2, 2))
        n1 = SimpleOp(lambda x, y: x + y, a, a, name="cy_n1")
        n2 = SimpleOp(lambda x: x * 2.0, n1, name="cy_n2")
        n1.inputs[1] = n2          # deliberate back edge
        with pytest.raises(GraphVerifyError) as ei:
            check_cycles([n2])
        assert ei.value.kind == "cycle"
        assert "cy_n1" in str(ei.value) and "cy_n2" in str(ei.value)

    def test_duplicate_names(self):
        a, b = var("dup_v", (2,)), var("dup_v", (2,))
        bad = ht.add_op(a, b)
        with pytest.raises(GraphVerifyError) as ei:
            verify_graph([bad])
        assert ei.value.kind == "duplicate_name"

    def test_dead_node_finding(self):
        live = ht.mul_byconst_op(var("dn_a", (2,)), 2.0)
        dead = ht.mul_byconst_op(var("dn_b", (2,)), 3.0)
        rep = verify_graph([live], all_nodes=[live, dead])
        kinds = {(f["kind"], f["node"]) for f in rep.findings}
        assert ("dead_node", dead.name) in kinds

    def test_rng_missing(self):
        drop = ht.dropout_op(var("rm_x", (4, 4)), 0.5)
        out = ht.reduce_mean_op(drop, axes=0)
        with pytest.raises(GraphVerifyError) as ei:
            verify_graph([out], rng_available=False)
        assert ei.value.kind == "rng_missing"
        assert drop.name in str(ei.value)
        # with an rng the same graph verifies and records the consumer
        rep = verify_graph([out], rng_available=True)
        assert drop.name in rep.rng_consumers

    def test_dtype_creep_in_bf16(self):
        x = var("cr_x", (4, 4))
        crept = SimpleOp(lambda v: v.astype(np.float32), x,
                         name="cr_upcast")
        out = ht.mul_byconst_op(crept, 1.0)
        rep = verify_graph([out], mixed_precision="bf16")
        assert any(f["kind"] == "dtype_creep"
                   and f["node"] == crept.name for f in rep.findings)
        # without the policy there is nothing to creep from
        rep2 = verify_graph([out])
        assert not any(f["kind"] == "dtype_creep" for f in rep2.findings)

    def test_unknown_feed_shapes_skip_downstream(self):
        x = ht.placeholder_op("uf_x")     # shape unknown until fed
        y = ht.matmul_op(x, var("uf_w", (3, 2)))
        rep = verify_graph([y])           # must not raise
        assert rep.shape_of(y) is None
        # and with the feed shape supplied, mismatches surface
        with pytest.raises(GraphVerifyError):
            verify_graph([y], feed_shapes={"uf_x": (4, 5)})
        rep = verify_graph([y], feed_shapes={"uf_x": (4, 3)})
        assert rep.shape_of(y) == (4, 2)


# --------------------------------------------------------------------- #
# satellite: Op.infer_shape standalone error + override parity
# --------------------------------------------------------------------- #

class TestInferShape:
    def test_base_error_names_node_and_inputs(self):
        bad = ht.matmul_op(var("is_a", (4, 3)), var("is_b", (5, 6)))
        with pytest.raises(ShapeInferenceError) as ei:
            bad.infer_shape([(4, 3), (5, 6)])
        msg = str(ei.value)
        assert bad.name in msg and "float32(4, 3)" in msg \
            and "float32(5, 6)" in msg
        assert "is_a" in msg and "is_b" in msg

    def test_base_path_still_returns_shape(self):
        ok = ht.matmul_op(var("is_c", (4, 3)), var("is_d", (3, 2)))
        assert tuple(ok.infer_shape([(4, 3), (3, 2)])) == (4, 2)

    def test_placeholder_override_parity(self):
        # the one hand-written override (graph/ops_misc.py): a
        # placeholder's infer_shape is its declared shape, and the
        # graph-wide verifier must agree with it
        v = var("is_v", (7, 5))
        assert tuple(v.infer_shape([])) == (7, 5)
        rep = verify_graph([ht.mul_byconst_op(v, 2.0)])
        assert rep.shape_of(v) == (7, 5)
        unfed = ht.placeholder_op("is_unfed")
        with pytest.raises(AssertionError):
            unfed.infer_shape([])


# --------------------------------------------------------------------- #
# parallelism checker
# --------------------------------------------------------------------- #

class TestShardCheck:
    def test_divisibility_accept(self):
        mesh = make_mesh({"dp": 2, "tp": 4})
        w = var("sc_w", (6, 8))
        w.sharding_spec = P(None, "tp")
        out = ht.mul_byconst_op(w, 2.0)
        assert check_divisibility([out], mesh) == []

    def test_divisibility_reject_nondivisible(self):
        mesh = make_mesh({"dp": 2, "tp": 4})
        w = var("sc_w2", (6, 9))          # 9 % tp(4) != 0
        w.sharding_spec = P(None, "tp")
        with pytest.raises(ShardCheckError) as ei:
            check_divisibility([ht.mul_byconst_op(w, 2.0)], mesh)
        assert "sc_w2" in str(ei.value) and ei.value.kind == "divisibility"

    def test_divisibility_reject_missing_axis(self):
        mesh = make_mesh({"dp": 8})
        w = var("sc_w3", (8, 8))
        w.sharding_spec = P("tp", None)
        with pytest.raises(ShardCheckError):
            check_divisibility([ht.mul_byconst_op(w, 2.0)], mesh)

    def test_feed_divisibility_finding(self):
        mesh = make_mesh({"dp": 8})
        out = ht.mul_byconst_op(var("sc_x", (8, 2)), 1.0)
        findings = check_divisibility([out], mesh,
                                      feed_shapes={"batch_x": (12, 2)})
        assert any(f["kind"] == "feed_not_dp_divisible"
                   and f["node"] == "batch_x" for f in findings)

    def test_mesh_axes_accept(self):
        mesh = make_mesh({"dp": 2, "tp": 2, "pp": 2})
        x = var("ma_x", (8, 4))
        chain = ops_comm.pipeline_send_op(
            ops_comm.reducescatterCommunicate_op(
                ops_comm.allreduceCommunicate_op(x, axis="dp"),
                axis="tp"))
        assert len(check_mesh_axes([chain], mesh)) == 3

    def _stacked_mlp(self, layers, hid=4):
        # distinct entry projection so the repeated layers (not the
        # input block) form the uniform body the partitioner detects
        x = var("pp_x", (8, 6))
        h = ht.relu_op(ht.matmul_op(
            x, ht.init.xavier_uniform((6, hid), name="pp_w_in")))
        for i in range(layers):
            w = ht.init.xavier_uniform((hid, hid), name=f"pp_l{i}_w")
            h = ht.relu_op(ht.matmul_op(h, w))
        return ht.reduce_mean_op(h, axes=0)

    def test_pipeline_accept_even(self):
        loss = self._stacked_mlp(4)
        assert check_pipeline_stages(loss, 2) == []

    def test_pipeline_reject_uneven(self):
        loss = self._stacked_mlp(3)
        with pytest.raises(ShardCheckError) as ei:
            check_pipeline_stages(loss, 2)
        assert ei.value.kind == "pipeline"
        assert "3" in str(ei.value) and "2" in str(ei.value)

    def test_pipeline_fallback_finding(self):
        # no uniform body at all: advisory, not fatal (the microbatch
        # scan fallback is trajectory-correct)
        loss = ht.reduce_mean_op(
            ht.matmul_op(var("pf_a", (4, 3)), var("pf_b", (3, 2))),
            axes=0)
        findings = check_pipeline_stages(loss, 2)
        assert any(f["kind"] == "pipeline_no_uniform_body"
                   for f in findings)

    def test_stage_assignment_accept(self):
        a = var("sa_a", (4, 4))
        h0 = ht.relu_op(a)
        snd = ops_comm.pipeline_send_op(h0)
        rcv = ops_comm.pipeline_receive_op(snd)
        h1 = ht.relu_op(rcv)
        stages = {a.name: 0, h0.name: 0, snd.name: 0,
                  rcv.name: 1, h1.name: 1}
        check_stage_assignment([h1], stages, num_stages=2)

    def test_stage_assignment_reject_bypass(self):
        a = var("sb_a", (4, 4))
        h0 = ht.relu_op(a)
        h1 = ht.relu_op(h0)               # crosses 0 -> 1 with no comm op
        with pytest.raises(ShardCheckError) as ei:
            check_stage_assignment(
                [h1], {a.name: 0, h0.name: 0, h1.name: 1}, num_stages=2)
        assert ei.value.kind == "stage_assignment"

    def test_stage_assignment_reject_backward(self):
        a = var("sm_a", (4, 4))
        h0 = ht.relu_op(a)
        with pytest.raises(ShardCheckError) as ei:
            check_stage_assignment(
                [h0], {a.name: 1, h0.name: 0}, num_stages=2)
        assert "monotone" in str(ei.value)

    def test_stage_assignment_reject_gap(self):
        a = var("sg_a", (4, 4))
        h0 = ht.relu_op(a)
        with pytest.raises(ShardCheckError) as ei:
            check_stage_assignment([h0], {a.name: 0, h0.name: 0},
                                   num_stages=3)
        assert "contiguous" in str(ei.value)

    def test_collective_order_static(self):
        def seq(axis_then):
            x = var(f"co_{axis_then}", (8, 4))
            return [ops_comm.reducescatterCommunicate_op(
                ops_comm.allreduceCommunicate_op(x, axis="dp"),
                axis=axis_then)]
        ok = check_collective_order_static(
            {"g0": seq("tp"), "g1": seq("tp")})
        assert [op for op, _ in ok] == ["AllReduceCommunicateOp",
                                       "ReduceScatterCommunicateOp"]
        with pytest.raises(ShardCheckError) as ei:
            check_collective_order_static(
                {"g0": seq("tp"), "g1": seq("dp")})
        assert ei.value.kind == "collective_order"

    def test_collective_sequence_records_axes(self):
        x = var("cs_x", (8, 4))
        n = ops_comm.allgatherCommunicate_op(x, axis="tp")
        assert collective_sequence([n]) == [("AllGatherCommunicateOp",
                                            "tp")]


# --------------------------------------------------------------------- #
# executor + serving wiring (HETU_VALIDATE=1; conftest defaults it on)
# --------------------------------------------------------------------- #

class TestExecutorWiring:
    def test_build_time_shape_mismatch_named(self):
        bad = ht.matmul_op(var("ew_a", (4, 3)), var("ew_b", (5, 6)))
        loss = ht.reduce_mean_op(bad, axes=0)
        with pytest.raises(GraphVerifyError) as ei:
            ht.Executor({"train": [loss]})
        assert bad.name in str(ei.value)

    def test_feed_time_mismatch_named_before_trace(self):
        x = ht.placeholder_op("ew_x")     # unshaped until fed
        w = var("ew_w", (3, 2))
        out = ht.matmul_op(x, w)
        ex = ht.Executor({"eval": [out]})  # builds fine (shape unknown)
        with pytest.raises(GraphVerifyError) as ei:
            ex.run("eval", feed_dict={x: np.zeros((4, 5), np.float32)})
        assert out.name in str(ei.value)

    def test_bad_mesh_axis_fails_at_build(self):
        mesh = make_mesh({"dp": 4})
        x = var("ew_mx", (8, 4))
        ar = ops_comm.allreduceCommunicate_op(x, axis="tp")
        loss = ht.reduce_mean_op(ar, axes=0)
        with pytest.raises(ShardCheckError):
            ht.Executor({"train": [loss]}, mesh=mesh)

    def test_validate_off_skips(self, monkeypatch):
        monkeypatch.setenv("HETU_VALIDATE", "0")
        bad = ht.matmul_op(var("off_a", (4, 3)), var("off_b", (5, 6)))
        loss = ht.reduce_mean_op(bad, axes=0)
        ht.Executor({"train": [loss]})    # no build-time error

    def test_jsonl_report_record_shape(self, tmp_path, monkeypatch):
        # the event-log contract is uniform with PR 1's failure log:
        # every line is {"t": <float>, "event": <str>, **fields}
        log = tmp_path / "validate.jsonl"
        monkeypatch.setenv("HETU_VALIDATE_LOG", str(log))
        y = ht.matmul_op(var("rl_a", (4, 3)), var("rl_b", (3, 2)))
        ht.Executor({"eval": [ht.reduce_mean_op(y, axes=0)]})
        recs = [json.loads(line) for line in log.read_text().splitlines()]
        assert recs, "no validation records written"
        for rec in recs:
            assert isinstance(rec["t"], float) and isinstance(
                rec["event"], str)
        assert any(r["event"] == "graph_verified" for r in recs)

    def test_training_graph_verifies(self):
        # full forward+backward+optimizer graph walks clean
        x = ht.placeholder_op("tr_x")
        w = ht.init.xavier_uniform((6, 4), name="tr_w")
        loss = ht.reduce_mean_op(ht.matmul_op(x, w), axes=0)
        loss = ht.reduce_mean_op(loss, axes=0)
        train = ht.optim.SGDOptimizer(learning_rate=0.1).minimize(loss)
        ex = ht.Executor({"train": [loss, train]})
        out = ex.run("train",
                     feed_dict={x: np.ones((8, 6), np.float32)})
        assert np.isfinite(float(np.asarray(out[0])))


class TestServingWiring:
    def _params(self, name="sv", hd=16, V=32, S=16):
        rng = np.random.RandomState(0)
        return {f"{name}_wte_table": rng.randn(V, hd).astype(np.float32),
                f"{name}_wpe": rng.randn(S, hd).astype(np.float32)}

    def test_heads_divisibility_rejected(self):
        from hetu_tpu.analysis import validate_serving
        from hetu_tpu.models import GPTConfig
        cfg = GPTConfig(vocab_size=32, hidden_size=16,
                        num_hidden_layers=1, num_attention_heads=3,
                        max_position_embeddings=16, seq_len=16)
        with pytest.raises(ShardCheckError):
            validate_serving(self._params(), cfg, "sv")

    def test_param_shape_mismatch_rejected(self):
        from hetu_tpu.analysis import validate_serving
        from hetu_tpu.models import GPTConfig
        cfg = GPTConfig(vocab_size=32, hidden_size=24,
                        num_hidden_layers=1, num_attention_heads=2,
                        max_position_embeddings=16, seq_len=16)
        with pytest.raises(GraphVerifyError) as ei:
            validate_serving(self._params(hd=16), cfg, "sv")
        assert "wte_table" in str(ei.value)

    def test_consistent_params_accepted(self, tmp_path, monkeypatch):
        from hetu_tpu.analysis import validate_serving
        from hetu_tpu.models import GPTConfig
        log = tmp_path / "serve_validate.jsonl"
        monkeypatch.setenv("HETU_VALIDATE_LOG", str(log))
        cfg = GPTConfig(vocab_size=32, hidden_size=16,
                        num_hidden_layers=1, num_attention_heads=2,
                        max_position_embeddings=16, seq_len=16)
        validate_serving(self._params(), cfg, "sv")
        recs = [json.loads(line) for line in log.read_text().splitlines()]
        assert recs[-1]["event"] == "serving_verified"


# --------------------------------------------------------------------- #
# lint rules: every rule must trip on its fixture and stay quiet on
# clean code
# --------------------------------------------------------------------- #

class TestLint:
    def _rules_hit(self, fname):
        findings = lint_paths([os.path.join(FIXTURES, fname)])
        return {f.rule for f in findings}

    def test_fixture_env_registry(self):
        assert "env-registry" in self._rules_hit("trip_env_registry.py")

    def test_fixture_np_in_compute(self):
        assert "np-in-compute" in self._rules_hit("trip_np_compute.py")

    def test_fixture_time_in_jit(self):
        assert "time-in-jit" in self._rules_hit("trip_time_jit.py")

    def test_fixture_jit_donate(self):
        assert "jit-donate" in self._rules_hit("trip_jit_donate.py")

    def test_fixture_event_emit(self):
        assert "event-emit" in self._rules_hit("trip_event_emit.py")

    def test_event_emit_allowed_inside_telemetry(self):
        # the sink itself is the one legal JSONL writer
        src = ('import json\n'
               'def w(f, rec):\n'
               '    f.write(json.dumps(rec) + "\\n")\n')
        flagged = lint_source(src, path="hetu_tpu/other/mod.py")
        assert any(f.rule == "event-emit" for f in flagged)
        assert lint_source(src, path="hetu_tpu/telemetry/events.py") == []

    def test_event_emit_ignores_plain_json_writes(self):
        # whole-file json dumps (artifacts) are not JSONL event streams
        src = ('import json\n'
               'def save(path, obj):\n'
               '    with open(path, "w") as f:\n'
               '        f.write(json.dumps(obj))\n')
        assert lint_source(src) == []

    def test_clean_fixture_quiet(self):
        assert self._rules_hit("clean.py") == set()

    def test_env_writes_allowed(self):
        src = 'import os\nos.environ["HETU_VALIDATE"] = "1"\n' \
              'os.environ.pop("HETU_VALIDATE", None)\n'
        assert lint_source(src) == []

    def test_unregistered_getter_flagged(self):
        src = 'from hetu_tpu import envvars\n' \
              'x = envvars.get_str("HETU_NOT_A_REAL_KNOB")\n'
        assert any(f.rule == "env-registry" for f in lint_source(src))

    def test_np_static_helpers_allowed(self):
        src = ('class AOp:\n'
               '    def compute(self, input_vals, tc):\n'
               '        n = np.prod((2, 3))\n'
               '        return input_vals[0]\n')
        assert lint_source(src) == []

    def test_rule_subset_selection(self):
        path = os.path.join(FIXTURES, "trip_env_registry.py")
        only = lint_paths([path], rules=("jit-donate",))
        assert only == []

    def test_all_rules_have_fixtures(self):
        # keep the fixture battery in sync with the rule list
        fixture_rules = set()
        for f in sorted(os.listdir(FIXTURES)):
            if f.startswith("trip_"):
                fixture_rules |= {x.rule for x in lint_paths(
                    [os.path.join(FIXTURES, f)])}
        assert set(RULES) <= fixture_rules


# --------------------------------------------------------------------- #
# env registry
# --------------------------------------------------------------------- #

class TestEnvVars:
    def test_unregistered_read_raises(self):
        with pytest.raises(KeyError):
            envvars.get_str("HETU_NOT_A_REAL_KNOB")

    def test_bool_parsing(self, monkeypatch):
        for raw, want in [("1", True), ("true", True), ("on", True),
                          ("0", False), ("false", False), ("off", False),
                          ("", False)]:
            monkeypatch.setenv("HETU_VALIDATE", raw)
            assert envvars.get_bool("HETU_VALIDATE") is want
        monkeypatch.delenv("HETU_VALIDATE", raising=False)
        assert envvars.get_bool("HETU_VALIDATE") is False

    def test_typed_defaults(self, monkeypatch):
        monkeypatch.delenv("HETU_PS_TIMEOUT", raising=False)
        assert envvars.get_float("HETU_PS_TIMEOUT") == 60.0
        monkeypatch.setenv("HETU_PS_TIMEOUT", "2.5")
        assert envvars.get_float("HETU_PS_TIMEOUT") == 2.5
        monkeypatch.setenv("HETU_PS_ADDRS", "a:1, b:2,")
        assert envvars.get_list("HETU_PS_ADDRS") == ["a:1", "b:2"]

    def test_env_table_covers_registry(self):
        table = envvars.env_table()
        for name in envvars.REGISTRY:
            assert f"`{name}`" in table
        # every registered var documents itself
        assert all(v.help for v in envvars.REGISTRY.values())
