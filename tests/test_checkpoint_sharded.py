"""Checkpoint robustness (VERDICT weak #7): checkpoint-stable optimizer
naming and orbax sharded/async save-restore with cross-layout resharding
(reference saves a rank-0 pickle of params only, executor.py:461-485 —
this is the strictly-better path SURVEY §5.4 called for)."""

import numpy as np
import pytest

from jax.sharding import PartitionSpec as P

import hetu_tpu as ht


BATCH, IN, HID, OUT = 16, 8, 32, 4

TP_SPECS = {
    "ck_fc1_weight": P(None, "tp"),
    "ck_fc1_bias": P("tp"),
    "ck_fc2_weight": P("tp", None),
}


def build(prefix="ck"):
    x = ht.placeholder_op("x")
    y = ht.placeholder_op("y")
    w1 = ht.init.xavier_uniform((IN, HID), name=f"{prefix}_fc1_weight")
    b1 = ht.init.zeros((HID,), name=f"{prefix}_fc1_bias")
    w2 = ht.init.xavier_uniform((HID, IN), name=f"{prefix}_fc2_weight")
    wh = ht.init.xavier_uniform((IN, OUT), name=f"{prefix}_head")
    h = ht.gelu_op(ht.linear_op(x, w1, b1))
    h = ht.matmul_op(h, w2)
    loss = ht.reduce_mean_op(
        ht.softmaxcrossentropy_op(ht.matmul_op(h, wh), y), axes=0)
    train = ht.optim.AdamOptimizer(learning_rate=0.01).minimize(loss)
    return x, y, loss, train


def batches(n, seed=3):
    rng = np.random.RandomState(seed)
    out = []
    for _ in range(n):
        xb = rng.randn(BATCH, IN).astype(np.float32)
        yb = np.eye(OUT, dtype=np.float32)[xb[:, :OUT].argmax(1)]
        out.append((xb, yb))
    return out


class TestStableOptNames:
    def test_name_stable_across_builds(self):
        _, _, _, t1 = build()
        _, _, _, t2 = build()      # fresh nodes, different node ids
        assert t1.name == t2.name
        assert t1.name.startswith("opt_AdamOptimizer_")

    def test_duplicate_optimizers_rejected(self):
        x, y, loss, _ = build("dup")
        opt_a = ht.optim.SGDOptimizer(learning_rate=0.1).minimize(loss)
        opt_b = ht.optim.SGDOptimizer(learning_rate=0.2).minimize(loss)
        with pytest.raises(ValueError, match="same variable set"):
            ht.Executor({"a": [loss, opt_a], "b": [loss, opt_b]})

    def test_stable_names_restore_by_key(self, tmp_path):
        x, y, loss, train = build("sn")
        ex = ht.Executor({"train": [loss, train]})
        bs = batches(6)
        for a, b in bs[:3]:
            ex.run("train", feed_dict={x: a, y: b})
        ex.save(str(tmp_path))
        base = [float(np.asarray(ex.run(
            "train", feed_dict={x: a, y: b})[0])) for a, b in bs[3:]]

        x, y, loss, train = build("sn")
        ex2 = ht.Executor({"train": [loss, train]})
        ex2.load(str(tmp_path))
        # Adam moments restored by the stable name — trajectory continues
        got = [float(np.asarray(ex2.run(
            "train", feed_dict={x: a, y: b})[0])) for a, b in bs[3:]]
        np.testing.assert_allclose(got, base, atol=1e-6)


class TestRngResume:
    def test_dropout_trajectory_exact_across_rebuilds(self, tmp_path):
        """RNG streams are keyed by topo position, not the global node-id
        counter: a graph rebuilt later in the same process (shifted ids)
        must resume a dropout model's trajectory bit-exactly, and the VJP
        recompute must see the same mask as the primal forward."""
        def build_do():
            x = ht.placeholder_op("xr")
            w = ht.init.xavier_uniform((IN, IN), name="rr_w")
            h = ht.dropout_op(ht.matmul_op(x, w), 0.5)
            loss = ht.reduce_mean_op(ht.mul_op(h, h), axes=[0, 1])
            train = ht.optim.AdamOptimizer(learning_rate=0.01).minimize(
                loss)
            return x, ht.Executor({"train": [loss, train]}, seed=7)

        X = np.random.RandomState(0).randn(BATCH, IN).astype(np.float32)
        x, ex = build_do()
        for _ in range(3):
            ex.run("train", feed_dict={x: X})
        ex.save(str(tmp_path), "rng_ck.pkl")
        base = [float(ex.run("train", feed_dict={x: X})[0])
                for _ in range(3)]

        x, ex2 = build_do()          # fresh nodes, shifted id counter
        ex2.load(str(tmp_path), "rng_ck.pkl")
        got = [float(ex2.run("train", feed_dict={x: X})[0])
               for _ in range(3)]
        np.testing.assert_allclose(got, base, atol=1e-7)


class TestShardedCheckpoint:
    def test_sharded_roundtrip_reshards_across_layouts(self, tmp_path):
        """Save under tp2 x dp4, restore onto fsdp8 — the trajectory must
        continue exactly; orbax reshards without a host bounce."""
        bs = batches(8)
        x, y, loss, train = build("sc")
        ex = ht.Executor({"train": [loss, train]},
                         dist_strategy=ht.dist.ShardingPlan(
                             {"sc_fc1_weight": P(None, "tp"),
                              "sc_fc1_bias": P("tp"),
                              "sc_fc2_weight": P("tp", None)},
                             mesh_axes={"dp": 4, "tp": 2}))
        for a, b in bs[:4]:
            ex.run("train", feed_dict={x: a, y: b})
        ex.save(str(tmp_path), sharded=True)
        base = [float(np.asarray(ex.run(
            "train", feed_dict={x: a, y: b})[0])) for a, b in bs[4:]]

        x, y, loss, train = build("sc")
        ex2 = ht.Executor({"train": [loss, train]},
                          dist_strategy=ht.dist.FSDP(dp=8, min_size=16))
        ex2.load(str(tmp_path))       # auto-detects the orbax dir
        got = [float(np.asarray(ex2.run(
            "train", feed_dict={x: a, y: b})[0])) for a, b in bs[4:]]
        np.testing.assert_allclose(got, base, atol=1e-5)

    def test_async_save(self, tmp_path):
        bs = batches(5)
        x, y, loss, train = build("as")
        ex = ht.Executor({"train": [loss, train]})
        for a, b in bs[:2]:
            ex.run("train", feed_dict={x: a, y: b})
        ex.save(str(tmp_path), async_=True)
        # training continues while the write flushes in the background
        base = [float(np.asarray(ex.run(
            "train", feed_dict={x: a, y: b})[0])) for a, b in bs[2:]]
        ex.wait_for_checkpoint()

        x, y, loss, train = build("as")
        ex2 = ht.Executor({"train": [loss, train]})
        ex2.load(str(tmp_path))
        got = [float(np.asarray(ex2.run(
            "train", feed_dict={x: a, y: b})[0])) for a, b in bs[2:]]
        np.testing.assert_allclose(got, base, atol=1e-6)
        ex.close()
        ex2.close()

    def test_restore_tolerates_extra_on_disk_keys(self, tmp_path):
        """Forward compat: a checkpoint written by a build that stored
        extra non-trainable Variables (e.g. materialized causal masks,
        superseded by in-trace ops) must still restore — the superset
        path rebuilds the target from orbax metadata and discards the
        extras."""
        bs = batches(6)

        def build_extra(with_mask):
            x = ht.placeholder_op("x")
            y = ht.placeholder_op("y")
            w1 = ht.init.xavier_uniform((IN, HID), name="xk_fc1_weight")
            b1 = ht.init.zeros((HID,), name="xk_fc1_bias")
            wh = ht.init.xavier_uniform((HID, OUT), name="xk_head")
            h = ht.gelu_op(ht.linear_op(x, w1, b1))
            if with_mask:
                from hetu_tpu.graph.ops_misc import Variable
                mask = Variable("xk_legacy_mask",
                                value=np.zeros((1, HID), np.float32),
                                trainable=False)
                h = h + ht.broadcastto_op(mask, h)
            loss = ht.reduce_mean_op(
                ht.softmaxcrossentropy_op(ht.matmul_op(h, wh), y), axes=0)
            train = ht.optim.AdamOptimizer(learning_rate=0.01).minimize(loss)
            return x, y, loss, train

        x, y, loss, train = build_extra(True)
        ex = ht.Executor({"train": [loss, train]})
        for a, b in bs[:3]:
            ex.run("train", feed_dict={x: a, y: b})
        ex.save(str(tmp_path), sharded=True)
        base = [float(np.asarray(ex.run(
            "train", feed_dict={x: a, y: b})[0])) for a, b in bs[3:]]

        x, y, loss, train = build_extra(False)   # mask key gone
        ex2 = ht.Executor({"train": [loss, train]})
        ex2.load(str(tmp_path))
        got = [float(np.asarray(ex2.run(
            "train", feed_dict={x: a, y: b})[0])) for a, b in bs[3:]]
        np.testing.assert_allclose(got, base, atol=1e-6)

    def test_restore_rejects_missing_on_disk_keys(self, tmp_path):
        """The superset path must NOT mask a checkpoint that lacks current
        params (renamed param / wrong model) — that is a real error."""
        bs = batches(2)
        x, y, loss, train = build("mk")
        ex = ht.Executor({"train": [loss, train]})
        ex.run("train", feed_dict={x: bs[0][0], y: bs[0][1]})
        ex.save(str(tmp_path), sharded=True)

        def build_renamed():
            x = ht.placeholder_op("x")
            y = ht.placeholder_op("y")
            w1 = ht.init.xavier_uniform((IN, HID), name="mk_fc1_weight")
            b1 = ht.init.zeros((HID,), name="mk_fc1_bias")
            w2 = ht.init.xavier_uniform((HID, IN), name="mk_fc2_RENAMED")
            wh = ht.init.xavier_uniform((IN, OUT), name="mk_head")
            h = ht.gelu_op(ht.linear_op(x, w1, b1))
            h = ht.matmul_op(h, w2)
            loss = ht.reduce_mean_op(
                ht.softmaxcrossentropy_op(ht.matmul_op(h, wh), y), axes=0)
            train = ht.optim.AdamOptimizer(
                learning_rate=0.01).minimize(loss)
            return x, y, loss, train

        x, y, loss, train = build_renamed()
        ex2 = ht.Executor({"train": [loss, train]})
        with pytest.raises(Exception, match="(?i)match|structure|diff"):
            ex2.load(str(tmp_path))


def test_gpt_checkpoint_roundtrip_resumes_exactly(tmp_path):
    """The decoder-only family through save -> rebuild -> load: the
    resumed run's next steps match the uninterrupted run exactly
    (params + Adam slots + step + rng)."""
    from hetu_tpu.models import GPTConfig, GPTForCausalLM

    def build():
        cfg = GPTConfig(vocab_size=61, hidden_size=32,
                        num_hidden_layers=2, num_attention_heads=2,
                        max_position_embeddings=16, batch_size=4,
                        seq_len=16, dropout_rate=0.1)   # dropout: rng too
        m = GPTForCausalLM(cfg, name="ck")
        ids = ht.placeholder_op("ck_ids")
        labels = ht.placeholder_op("ck_labels")
        loss, _ = m(ids, labels=labels)
        train = ht.optim.AdamOptimizer(learning_rate=3e-3).minimize(loss)
        return ids, labels, ht.Executor({"train": [loss, train]})

    rng = np.random.RandomState(7)
    feeds = []
    for _ in range(8):
        iv = rng.randint(0, 61, (4, 16)).astype(np.int32)
        feeds.append((iv, ((iv + 1) % 61).astype(np.int32)))

    ids, labels, ex = build()
    for a, b in feeds[:4]:
        ex.run("train", feed_dict={ids: a, labels: b})
    ex.save(str(tmp_path), "gpt_ck.pkl")
    cont = [float(np.asarray(ex.run("train",
                                    feed_dict={ids: a, labels: b})[0]))
            for a, b in feeds[4:]]

    ids2, labels2, ex2 = build()
    ex2.load(str(tmp_path), "gpt_ck.pkl")
    resumed = [float(np.asarray(ex2.run("train",
                                        feed_dict={ids2: a,
                                                   labels2: b})[0]))
               for a, b in feeds[4:]]
    np.testing.assert_allclose(resumed, cont, rtol=1e-6, atol=1e-7)
