"""Gradient correctness: graph-level gradients vs jax.grad ground truth and
numeric checks (reference has per-op grad kernels exercised via training
tests; we verify against jax autodiff directly)."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

import hetu_tpu as ht

# smoke tier: this module is part of the <3-min verification
# battery (`pytest -m smoke`; ROADMAP tier-1 note)
pytestmark = pytest.mark.smoke


def _graph_grads(build_fn, inputs_np):
    """Build graph with variables from inputs_np, return loss grads."""
    vars_ = [ht.Variable(f"v{i}", value=v) for i, v in enumerate(inputs_np)]
    loss = build_fn(*vars_)
    grads = ht.gradients(loss, vars_)
    ex = ht.Executor({"g": grads + [loss]})
    out = ex.run("g", convert_to_numpy_ret_vals=True)
    return out[:-1], out[-1]


def _check(build_graph, build_jax, inputs_np, rtol=1e-4, atol=1e-5):
    grads, loss = _graph_grads(build_graph, inputs_np)
    jg = jax.grad(build_jax, argnums=tuple(range(len(inputs_np))))(
        *[jnp.asarray(v) for v in inputs_np])
    for g, jgi in zip(grads, jg):
        np.testing.assert_allclose(g, np.asarray(jgi), rtol=rtol, atol=atol)


def test_matmul_grad():
    rng = np.random.RandomState(0)
    a = rng.randn(4, 5).astype(np.float32)
    b = rng.randn(5, 3).astype(np.float32)
    _check(
        lambda x, y: ht.reduce_sum_op(ht.matmul_op(x, y), [0, 1]),
        lambda x, y: jnp.sum(x @ y),
        [a, b])


def test_mlp_grad():
    rng = np.random.RandomState(0)
    x = rng.randn(6, 4).astype(np.float32)
    w1 = rng.randn(4, 8).astype(np.float32)
    w2 = rng.randn(8, 2).astype(np.float32)

    def graph(xv, w1v, w2v):
        h = ht.relu_op(ht.matmul_op(xv, w1v))
        return ht.reduce_mean_op(
            ht.reduce_sum_op(ht.mul_op(ht.matmul_op(h, w2v),
                                       ht.matmul_op(h, w2v)), [1]), [0])

    def jf(xv, w1v, w2v):
        h = jax.nn.relu(xv @ w1v)
        o = h @ w2v
        return jnp.mean(jnp.sum(o * o, 1))

    _check(graph, jf, [x, w1, w2])


def test_broadcast_grad():
    rng = np.random.RandomState(0)
    a = rng.randn(4, 5).astype(np.float32)
    b = rng.randn(5).astype(np.float32)
    _check(
        lambda x, y: ht.reduce_sum_op(ht.mul_op(ht.add_op(x, ht.broadcastto_op(y, x)),
                                                ht.add_op(x, ht.broadcastto_op(y, x))), [0, 1]),
        lambda x, y: jnp.sum((x + y) ** 2),
        [a, b])


def test_softmax_ce_grad():
    rng = np.random.RandomState(0)
    logits = rng.randn(6, 10).astype(np.float32)
    labels = np.eye(10, dtype=np.float32)[rng.randint(0, 10, 6)]
    _check(
        lambda x: ht.reduce_mean_op(
            ht.softmaxcrossentropy_op(x, ht.Variable("lab", value=labels,
                                                     trainable=False)), [0]),
        lambda x: jnp.mean(-jnp.sum(labels * jax.nn.log_softmax(x), -1)),
        [logits])


def test_conv_grad():
    rng = np.random.RandomState(0)
    x = rng.randn(2, 3, 6, 6).astype(np.float32)
    w = rng.randn(4, 3, 3, 3).astype(np.float32)
    _check(
        lambda xv, wv: ht.reduce_sum_op(
            ht.mul_op(ht.conv2d_op(xv, wv, 1, 1), ht.conv2d_op(xv, wv, 1, 1)),
            [0, 1, 2, 3]),
        lambda xv, wv: jnp.sum(jax.lax.conv_general_dilated(
            xv, wv, (1, 1), [(1, 1), (1, 1)],
            dimension_numbers=("NCHW", "OIHW", "NCHW")) ** 2),
        [x, w], rtol=1e-3, atol=1e-3)


def test_embedding_sparse_grad_matches_dense():
    """IndexedSlices sparse update must equal the dense-scatter update."""
    rng = np.random.RandomState(0)
    table_np = rng.randn(20, 4).astype(np.float32)
    ids_np = np.array([1, 3, 3, 7], np.int32)

    table = ht.Variable("emb_table", value=table_np.copy())
    ids = ht.placeholder_op("ids")
    emb = ht.embedding_lookup_op(table, ids)
    loss = ht.reduce_sum_op(ht.mul_op(emb, emb), [0, 1])
    opt = ht.optim.SGDOptimizer(learning_rate=0.1)
    train = opt.minimize(loss)
    ex = ht.Executor({"train": [loss, train]})
    ex.run("train", feed_dict={ids: ids_np})
    updated = np.asarray(ex.var_values["emb_table"])

    # dense ground truth via jax
    def jloss(t):
        e = t[ids_np]
        return jnp.sum(e * e)
    g = np.asarray(jax.grad(jloss)(jnp.asarray(table_np)))
    expected = table_np - 0.1 * g
    np.testing.assert_allclose(updated, expected, rtol=1e-5, atol=1e-6)
