"""ISSUE 9: quantized bytes everywhere — one int8 layer, three seams.

The load-bearing claims, each pinned separately:

- codec: symmetric per-chunk int8 round-trips within the documented
  ``amax / 254`` per-element bound (numpy and jax halves agree), and
  the wire codec carries the (int8 payload, scales) pair natively —
  property-tested alongside the pre-existing edge dtypes, because the
  codec is now load-bearing for quantized payloads;
- PS transport: ``HETU_PS_QUANT=int8`` push/pull parity within the
  bound, >= 3.5x wire-byte reduction on the PR 5 counters, replication
  and resync move the quantized form (under ``HETU_CHAOS`` too), and
  training through the PS stays on the exact loss curve within a bound;
- collectives: the quantize→all_gather→dequantize trio sums correctly
  under real shard_map execution, shard_check REJECTS a quantize
  without its paired dequantize across the collective, and
  collective_check sees int8 legs as first-class signatures;
- serving KV: the int8 kernels match their dequantize oracles, the
  engine with ``kv_quant="int8"`` is greedy-identical to offline f32
  on the parity model (contiguous, paged, fast path, chunked prefill,
  shared prefixes), and the teacher-forced margin gate holds;
- defaults: with every knob unset, nothing changes a byte.

Everything runs on the CPU harness (kernels interpret-mode) — smoke.
"""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import hetu_tpu as ht  # noqa: F401  (platform forcing + compat shims)
from hetu_tpu import quant, telemetry
from hetu_tpu.ps import wire
from hetu_tpu.ps.client import PSClient, _LocalTransport, _TCPTransport
from hetu_tpu.ps.server import PSServer

pytestmark = pytest.mark.smoke


def fresh_ps():
    PSServer._instance = None
    PSClient._instance = None


def _err_bound(x):
    """The documented per-element bound for one flat-chunk encode of
    ``x``: half a quantization step of the worst chunk."""
    m = float(np.abs(x).max()) if np.asarray(x).size else 0.0
    return m / 254.0 + 1e-7


# --------------------------------------------------------------------- #
# codec
# --------------------------------------------------------------------- #

class TestCodec:
    def test_roundtrip_error_bound(self):
        rng = np.random.RandomState(0)
        for shape in [(1000,), (7, 13), (4, 256), (1,), (3, 1, 5)]:
            x = (rng.randn(*shape) * rng.uniform(0.01, 30)).astype(
                np.float32)
            qa = quant.QuantArray.encode(x)
            back = qa.decode()
            assert back.shape == x.shape and back.dtype == np.float32
            assert np.abs(back - x).max() <= _err_bound(x)

    def test_outlier_poisons_only_its_chunk(self):
        # per-CHUNK scales: a 1e3 outlier in chunk 0 must not blow up
        # chunk 1's precision
        x = np.full(512, 0.01, np.float32)
        x[3] = 1000.0
        back = quant.QuantArray.encode(x, chunk=256).decode()
        assert np.abs(back[256:] - 0.01).max() <= 0.01 / 200

    def test_zero_and_empty_and_0d(self):
        for x in [np.zeros((4, 8), np.float32),
                  np.zeros((0,), np.float32),
                  np.asarray(2.5, np.float32)]:
            back = quant.QuantArray.encode(x).decode()
            np.testing.assert_allclose(back, x, atol=_err_bound(x))
        # all-zero chunks decode to exact zero (scale 1.0, q 0)
        np.testing.assert_array_equal(
            quant.QuantArray.encode(np.zeros(300, np.float32)).decode(),
            0.0)

    def test_jax_and_np_halves_agree(self):
        rng = np.random.RandomState(1)
        x = rng.randn(4, 512).astype(np.float32)
        qn, sn = quant.quantize_np(x, 256)
        qj, sj = quant.quantize_jax(jnp.asarray(x), 256)
        np.testing.assert_array_equal(qn.reshape(4, 512), np.asarray(qj))
        np.testing.assert_allclose(sn, np.asarray(sj).reshape(-1),
                                   rtol=1e-6)
        np.testing.assert_allclose(
            np.asarray(quant.dequantize_jax(qj, sj, 256)),
            quant.dequantize_np(qn, sn, 256).reshape(4, 512), rtol=1e-6)

    def test_kv_encode_per_position_head_scales(self):
        rng = np.random.RandomState(2)
        x = jnp.asarray(rng.randn(3, 5, 2, 8).astype(np.float32))
        q, s = quant.kv_encode(x)
        assert q.dtype == jnp.int8 and q.shape == x.shape
        assert s.shape == x.shape[:-1]
        back = quant.kv_decode(q, s)
        # bound per (position, head) row
        amax = np.abs(np.asarray(x)).max(axis=-1, keepdims=True)
        assert np.all(np.abs(np.asarray(back) - np.asarray(x))
                      <= amax / 254 + 1e-7)

    def test_mode_grammar(self, monkeypatch):
        assert quant.resolve_quant("int8", "HETU_PS_QUANT") == "int8"
        assert quant.resolve_quant("0", "HETU_PS_QUANT") is None
        assert quant.resolve_quant(None, "HETU_PS_QUANT") is None
        monkeypatch.setenv("HETU_PS_QUANT", "int8")
        assert quant.ps_quant() == "int8"
        assert quant.active_modes() == "ps=int8"
        with pytest.raises(ValueError):
            quant.resolve_quant("int3", "HETU_PS_QUANT")


# --------------------------------------------------------------------- #
# wire codec: the scales-bearing pair + edge dtypes (satellite)
# --------------------------------------------------------------------- #

class TestWireQuant:
    def test_quant_pair_property_roundtrip(self):
        """Seeded property test: arbitrary float arrays survive the
        encode → dumps → loads → decode trip with q/scales/shape/chunk
        preserved EXACTLY (the pair is the payload of record; decode
        happens at the far end)."""
        rng = np.random.RandomState(3)
        for _ in range(25):
            nd = rng.randint(0, 4)
            shape = tuple(int(rng.randint(0, 9)) for _ in range(nd))
            x = np.asarray(rng.randn(*shape) * rng.uniform(0.001, 100),
                           np.float32)
            chunk = int(rng.choice([16, 64, 256]))
            qa = quant.QuantArray.encode(x, chunk)
            back = wire.loads(wire.dumps(qa))
            assert isinstance(back, quant.QuantArray)
            assert back.shape == x.shape and back.chunk == chunk
            np.testing.assert_array_equal(np.asarray(back.q),
                                          np.asarray(qa.q))
            np.testing.assert_array_equal(np.asarray(back.scales),
                                          np.asarray(qa.scales))
            np.testing.assert_allclose(back.decode(), qa.decode(),
                                       rtol=1e-6, atol=1e-7)

    def test_quant_pair_composes_in_envelope(self):
        qa = quant.QuantArray.encode(np.ones(2000, np.float32) * 3)
        msg = ("__req2__", "cid", 7, "push", ("key", qa),
               {"async_": False})
        back = wire.loads(wire.dumps(msg))
        assert back[3] == "push"
        assert isinstance(back[4][1], quant.QuantArray)
        np.testing.assert_allclose(back[4][1].decode(), 3.0,
                                   atol=3 / 200)

    def test_edge_dtypes_roundtrip(self):
        """int8/uint8/0-d/empty arrays — the raw-array tags the quant
        payloads lean on — keep exact dtype + contents."""
        cases = [np.arange(-5, 5, dtype=np.int8),
                 np.arange(9, dtype=np.uint8).reshape(3, 3),
                 np.asarray(7, np.int8),                  # 0-d int8
                 np.zeros((0, 4), np.float32),            # empty
                 np.zeros((), np.float64),                # 0-d f64
                 np.asarray([], np.int64)]
        for x in cases:
            back = wire.loads(wire.dumps(x))
            assert back.dtype == x.dtype and back.shape == x.shape
            np.testing.assert_array_equal(back, x)

    def test_wire_bytes_reduction(self):
        x = np.random.RandomState(4).randn(4096).astype(np.float32)
        plain = len(wire.dumps(x))
        packed = len(wire.dumps(quant.QuantArray.encode(x)))
        assert plain / packed >= 3.5


# --------------------------------------------------------------------- #
# PS transport
# --------------------------------------------------------------------- #

class TestPSQuant:
    def _sgd_client(self, key="w", shape=(64, 64), lr=0.1):
        fresh_ps()
        c = PSClient(transport=_LocalTransport())
        c.param_set(key, np.zeros(shape, np.float32), opt="sgd",
                    opt_args={"learning_rate": lr})
        return c

    def test_push_pull_parity_within_bound(self, monkeypatch):
        g = np.random.RandomState(5).randn(64, 64).astype(np.float32)
        c = self._sgd_client()
        monkeypatch.setenv("HETU_PS_QUANT", "int8")
        c.push("w", g)
        out = c.pull("w")
        ref = -0.1 * g
        # push quantizes g once; pull quantizes the value once
        assert np.abs(out - ref).max() <= 2 * 0.1 * _err_bound(g) \
            + _err_bound(ref)
        fresh_ps()

    def test_default_off_is_exact(self):
        g = np.random.RandomState(6).randn(64, 64).astype(np.float32)
        c = self._sgd_client()
        c.push("w", g)
        np.testing.assert_array_equal(c.pull("w"), -0.1 * g)
        fresh_ps()

    def test_small_payloads_stay_exact(self, monkeypatch):
        """Control-plane arrays under the WIRE_MIN_SIZE floor must
        round-trip bit-perfectly even with quantization on (row-shard
        metadata would misroute otherwise)."""
        monkeypatch.setenv("HETU_PS_QUANT", "int8")
        c = self._sgd_client("tiny", shape=(4, 3))
        g = np.random.RandomState(7).randn(4, 3).astype(np.float32)
        c.push("tiny", g)
        np.testing.assert_array_equal(c.pull("tiny"), -0.1 * g)
        fresh_ps()

    def test_tcp_wire_reduction_on_counters(self, monkeypatch):
        """The acceptance measurement: per push/pull wire bytes via the
        PR 5 ps.rpc.bytes_sent/recv counters drop >= 3.5x with int8 on,
        and ps.rpc.bytes_saved accounts the delta."""
        import socket
        fresh_ps()
        server = PSServer.get()
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        server.serve_tcp(port, block=False)
        try:
            g = np.random.RandomState(8).randn(128, 128).astype(
                np.float32)
            t = _TCPTransport("127.0.0.1", port)
            c = PSClient(transport=t)
            c.param_set("big", np.zeros((128, 128), np.float32),
                        opt="sgd", opt_args={"learning_rate": 0.1})
            c.push("big", g)                      # warm

            def bytes_for(n):
                telemetry.reset()
                for _ in range(n):
                    c.push("big", g)
                    c.pull("big")
                snap = telemetry.snapshot()["counters"]
                return (snap["ps.rpc.bytes_sent"]
                        + snap["ps.rpc.bytes_recv"],
                        snap.get("ps.rpc.bytes_saved", 0))

            exact, saved0 = bytes_for(3)
            assert saved0 == 0
            monkeypatch.setenv("HETU_PS_QUANT", "int8")
            packed, saved = bytes_for(3)
            assert exact / packed >= 3.5
            assert saved > 0
            c.finalize()
        finally:
            server.shutdown()
            fresh_ps()

    def test_sparse_verbs_quantized_parity(self, monkeypatch):
        fresh_ps()
        c = PSClient(transport=_LocalTransport())
        rows, dim = 64, 32
        c.param_set("emb", np.zeros((rows, dim), np.float32),
                    opt="sgd", opt_args={"learning_rate": 0.5})
        rng = np.random.RandomState(9)
        ids = rng.randint(0, rows, 48).astype(np.int64)
        grads = rng.randn(48, dim).astype(np.float32)
        ref = np.zeros((rows, dim), np.float32)
        uniq, inv = np.unique(ids, return_inverse=True)
        merged = np.zeros((len(uniq), dim), np.float32)
        np.add.at(merged, inv, grads)
        ref[uniq] -= 0.5 * merged
        monkeypatch.setenv("HETU_PS_QUANT", "int8")
        out = c.sd_pushpull("emb", ids, grads,
                            pull_ids=np.arange(rows))
        assert np.abs(out - ref).max() <= \
            0.5 * 3 * _err_bound(grads) + _err_bound(ref) + 1e-5
        fresh_ps()

    def test_replication_resync_under_chaos_moves_quantized(
            self, monkeypatch):
        """Satellite + tentpole: with int8 wire AND seeded chaos drops
        active, a replicated group's failover + resync walks the exact
        same trajectory as a fault-free quantized run — both sides
        dequantize the identical frames, and resync ships the table
        back through the quantized pull/param_set pair."""
        from hetu_tpu.ps.client import PSConnectionError
        from hetu_tpu.ps.sharded import (REPLICA_PREFIX, ShardedPSClient,
                                         _LocalServerTransport)
        monkeypatch.setenv("HETU_PS_QUANT", "int8")

        def steps(client, n, skip=0):
            rng = np.random.RandomState(10)
            for i in range(n):
                ids = rng.randint(0, 8, 5).astype(np.int64)
                grads = rng.randn(5, 3).astype(np.float32)
                if i >= skip:
                    client.sd_pushpull("t", ids, grads)

        def mk(replicate):
            servers = [PSServer(), PSServer()]
            c = ShardedPSClient(servers=servers, replicate=replicate)
            c.param_set("t", np.zeros((8, 3), np.float32), opt="sgd",
                        opt_args={"learning_rate": 0.5})
            return servers, c

        _, base = mk(False)
        steps(base, 12)
        want = base.pull("t")

        monkeypatch.setenv("HETU_CHAOS", "seed=5,drop=0.15")
        try:
            servers, c = mk(True)
            steps(c, 6)
            c.drain_replication()
            np.testing.assert_allclose(
                np.asarray(servers[1].pull(REPLICA_PREFIX + "t")),
                np.asarray(servers[0].pull("t")))

            class _Dead:
                def call(self, method, *a, **kw):
                    raise PSConnectionError("server gone (test)")

                def close(self):
                    pass

            c.clients[0].t = _Dead()
            steps(c, 12, skip=6)
            assert c.failed_shards() == [0]
            np.testing.assert_allclose(c.pull("t"), want, atol=1e-5)
            fresh = PSServer()
            c.clients[0].t = _LocalServerTransport(fresh)
            restored = c.resync_shard(0)
            assert "t" in restored and c.failed_shards() == []
            # the resynced primary's shard came back through the
            # quantized wire: equal within one encode/decode of the
            # table values
            np.testing.assert_allclose(
                np.asarray(fresh.pull("t")), np.asarray(want)[0::2],
                atol=float(np.abs(np.asarray(want)).max()) / 100)
        finally:
            monkeypatch.delenv("HETU_CHAOS", raising=False)
            fresh_ps()

    def test_ps_training_loss_curve_within_bound(self, monkeypatch):
        """Training parity gate: the SAME model trained through
        comm_mode='PS' (dense params server-optimized, every grad and
        pull crossing the wire) with int8 on tracks the exact run's
        loss curve within a small absolute band."""
        def train(quant_on):
            fresh_ps()
            if quant_on:
                monkeypatch.setenv("HETU_PS_QUANT", "int8")
            else:
                monkeypatch.delenv("HETU_PS_QUANT", raising=False)
            x = ht.placeholder_op("x")
            y = ht.placeholder_op("y")
            # SAME names in both runs: init_value seeds per name, so
            # distinct names would compare different models
            w = ht.init.xavier_uniform((64, 64), name="qw")
            w2 = ht.init.xavier_uniform((64, 2), name="qw2")
            h = ht.relu_op(ht.matmul_op(x, w))
            loss = ht.reduce_mean_op(
                ht.softmaxcrossentropy_op(ht.matmul_op(h, w2), y),
                axes=0)
            train_op = ht.optim.SGDOptimizer(
                learning_rate=0.1).minimize(loss)
            ex = ht.Executor({"train": [loss, train_op]},
                             comm_mode="PS", seed=11)
            rng = np.random.RandomState(12)
            losses = []
            for _ in range(15):
                a = rng.randn(16, 64).astype(np.float32)
                lab = (a[:, 0] > 0).astype(np.int64)
                c = np.eye(2, dtype=np.float32)[lab]
                losses.append(float(np.asarray(
                    ex.run("train", feed_dict={x: a, y: c})[0])))
            return np.asarray(losses)

        exact = train(False)
        q = train(True)
        fresh_ps()
        assert exact[-1] < exact[0]          # it actually trains
        assert q[-1] < q[0]
        assert np.abs(q - exact).max() < 0.05, (exact, q)


# --------------------------------------------------------------------- #
# quantized collective pair
# --------------------------------------------------------------------- #

class TestCommQuantPair:
    def _trio(self, shape=(8, 32)):
        g = ht.placeholder_op("qgrad")
        return ht.quantized_allreduce_op(g, shape=shape)

    def test_shard_map_numerics_and_int8_on_wire(self):
        from jax import shard_map
        from jax.sharding import PartitionSpec as P
        from hetu_tpu.graph.node import TraceContext
        from hetu_tpu.parallel.collective_check import (
            check_collective_order, quantized_collectives)
        from hetu_tpu.parallel.mesh import make_mesh
        if jax.device_count() < 2:
            pytest.skip("needs >= 2 devices (host platform count)")
        n = jax.device_count()
        mesh = make_mesh({"dp": n})
        trio = self._trio()

        def body(x):
            tc = TraceContext(axis_env=("dp",))
            gth = trio.inputs[0]
            q = gth.inputs[0]
            return trio.compute(
                [gth.compute([q.compute([x], tc)], tc)], tc)

        seq = check_collective_order(body, mesh, P(), P("dp"),
                                     [jnp.ones((8, 32))])
        assert quantized_collectives(seq), \
            "no int8 collective in the traced program"
        f = shard_map(body, mesh=mesh, in_specs=P(), out_specs=P(),
                      check_rep=False)
        x = np.random.RandomState(13).randn(8, 32).astype(np.float32)
        out = np.asarray(jax.jit(f)(x))
        ref = n * x
        assert np.abs(out - ref).max() <= n * _err_bound(x) * 1.5

    def test_pjit_mode_is_fake_quant(self):
        from hetu_tpu.graph.node import TraceContext
        trio = self._trio()
        tc = TraceContext()                    # no axis env: pjit mode
        gth = trio.inputs[0]
        q = gth.inputs[0]
        x = jnp.asarray(
            np.random.RandomState(14).randn(8, 32).astype(np.float32))
        out = trio.compute([gth.compute([q.compute([x], tc)], tc)], tc)
        assert out.shape == (8, 32)
        assert np.abs(np.asarray(out) - np.asarray(x)).max() \
            <= _err_bound(np.asarray(x))

    def test_shard_check_accepts_paired_rejects_unpaired(self):
        from hetu_tpu.analysis.shard_check import (
            ShardCheckError, check_quantized_collectives)
        from hetu_tpu.graph.ops_comm import (
            DequantizeCommOp, QuantAllReduceCommunicateOp,
            QuantizeCommOp)
        trio = self._trio()
        assert len(check_quantized_collectives([trio])) == 1
        # quantize whose pair never crosses a collective
        q = QuantizeCommOp(ht.placeholder_op("g1"))
        d = DequantizeCommOp(q, (4, 4))
        with pytest.raises(ShardCheckError, match="quant"):
            check_quantized_collectives([d])
        # collective with no dequantize consumer
        gth = QuantAllReduceCommunicateOp(
            QuantizeCommOp(ht.placeholder_op("g2")))
        with pytest.raises(ShardCheckError, match="paired"):
            check_quantized_collectives([gth])
        # collective over a raw (unquantized) input
        gth2 = QuantAllReduceCommunicateOp(ht.placeholder_op("g3"))
        d2 = DequantizeCommOp(gth2, (4, 4))
        with pytest.raises(ShardCheckError, match="QuantizeCommOp"):
            check_quantized_collectives([d2])
        # axis disagreement inside one trio
        q3 = QuantizeCommOp(ht.placeholder_op("g4"), axis="dp")
        g3 = QuantAllReduceCommunicateOp(q3, axis="dp")
        d3 = DequantizeCommOp(g3, (4, 4), axis="tp")
        with pytest.raises(ShardCheckError, match="axis"):
            check_quantized_collectives([d3])

    def test_check_parallelism_wires_the_pairing(self):
        from hetu_tpu.analysis.shard_check import (ShardCheckError,
                                                   check_parallelism)
        from hetu_tpu.graph.ops_comm import (
            QuantAllReduceCommunicateOp, QuantizeCommOp)
        gth = QuantAllReduceCommunicateOp(
            QuantizeCommOp(ht.placeholder_op("g5")))
        with pytest.raises(ShardCheckError):
            check_parallelism([gth], None)

    def test_strategy_splices_and_trains(self, monkeypatch):
        from hetu_tpu.graph.ops_comm import DequantizeCommOp
        from hetu_tpu.parallel.distributed_strategies import DataParallel

        def build_and_train(aggregate):
            x = ht.placeholder_op("x")
            # same name across runs: same seeded init, comparable curves
            w = ht.init.xavier_uniform((32, 32), name="dpq_w")
            h = ht.relu_op(ht.matmul_op(x, w))
            loss = ht.reduce_mean_op(
                ht.reduce_mean_op(h, axes=1), axes=0)
            train = ht.optim.SGDOptimizer(
                learning_rate=0.1).minimize(loss)
            ex = ht.Executor(
                {"train": [loss, train]}, seed=3,
                dist_strategy=DataParallel(aggregate=aggregate,
                                           num_devices=1))
            feed = np.ones((8, 32), np.float32)
            losses = [float(np.asarray(
                ex.run("train", feed_dict={x: feed})[0]))
                for _ in range(6)]
            return ex, losses

        ex_q, lq = build_and_train("quant_allreduce")
        opt = next(n for nodes in ex_q.eval_node_dict.values()
                   for n in nodes
                   if type(n).__name__ == "OptimizerOp")
        assert all(isinstance(g, DequantizeCommOp) for g in opt.inputs)
        _, le = build_and_train(None)
        assert lq[-1] < lq[0]
        assert abs(lq[-1] - le[-1]) < 0.05

    def test_env_knob_activates_splice(self, monkeypatch):
        from hetu_tpu.parallel.distributed_strategies import DataParallel
        monkeypatch.setenv("HETU_COMM_QUANT", "int8")
        assert DataParallel()._quantized()
        monkeypatch.delenv("HETU_COMM_QUANT")
        assert not DataParallel()._quantized()
        assert DataParallel(aggregate="allreduce")._quantized() is False


# --------------------------------------------------------------------- #
# int8 KV cache
# --------------------------------------------------------------------- #

def _rand_gpt(name="qg", L=2, H=2, Dh=8, V=61, S=32, seed=0):
    rng = np.random.RandomState(seed)
    hd = H * Dh
    p = {f"{name}_wte_table": rng.randn(V, hd) * 0.05,
         f"{name}_wpe": rng.randn(S, hd) * 0.05,
         f"{name}_ln_f_scale": np.ones(hd),
         f"{name}_ln_f_bias": np.zeros(hd)}
    for i in range(L):
        us = f"{name}_h{i}"
        for w, shp in [("attn_q", (hd, hd)), ("attn_k", (hd, hd)),
                       ("attn_v", (hd, hd)), ("attn_proj", (hd, hd)),
                       ("ffn_wi", (hd, 4 * hd)), ("ffn_wo", (4 * hd, hd))]:
            p[f"{us}_{w}_weight"] = rng.randn(*shp) * 0.05
            p[f"{us}_{w}_bias"] = np.zeros(shp[1])
        for ln in ("ln1", "ln2"):
            p[f"{us}_{ln}_scale"] = np.ones(hd)
            p[f"{us}_{ln}_bias"] = np.zeros(hd)
    from hetu_tpu.models import GPTConfig
    cfg = GPTConfig(vocab_size=V, hidden_size=hd, num_hidden_layers=L,
                    num_attention_heads=H, max_position_embeddings=S,
                    batch_size=1, seq_len=S, dropout_rate=0.0)
    return p, cfg


@pytest.fixture(scope="module")
def model():
    return _rand_gpt()


class TestKVQuantKernels:
    def test_contiguous_kernel_matches_oracle(self):
        rng = np.random.RandomState(20)
        B, S, H, Dh = 4, 64, 2, 8
        from hetu_tpu.kernels.decode_attention import (
            masked_decode_reference, paged_decode_attention)
        q = jnp.asarray(rng.randn(B, H, Dh).astype(np.float32))
        k = jnp.asarray(rng.randn(B, S, H, Dh).astype(np.float32))
        v = jnp.asarray(rng.randn(B, S, H, Dh).astype(np.float32))
        lens = jnp.asarray(np.array([5, 0, 33, 64], np.int32))
        qk, sk = quant.kv_encode(k)
        qv, sv = quant.kv_encode(v)
        out = paged_decode_attention(q, qk, qv, lens, block_k=16,
                                     k_scale=sk, v_scale=sv)
        ref = masked_decode_reference(q, qk, qv, lens, k_scale=sk,
                                      v_scale=sv)
        assert float(jnp.abs(out - ref).max()) < 2e-5
        # and the quantization error itself is bounded vs exact f32
        exact = masked_decode_reference(q, k, v, lens)
        assert float(jnp.abs(ref - exact).max()) < 0.05

    def test_block_table_kernel_matches_oracle(self):
        rng = np.random.RandomState(21)
        B, H, Dh, N, bs, T = 4, 2, 8, 20, 8, 8
        from hetu_tpu.kernels.decode_attention import (
            paged_block_decode_attention, paged_block_decode_reference)
        q = jnp.asarray(rng.randn(B, H, Dh).astype(np.float32))
        pk = jnp.asarray(rng.randn(N, bs, H, Dh).astype(np.float32))
        pv = jnp.asarray(rng.randn(N, bs, H, Dh).astype(np.float32))
        bt = jnp.asarray(rng.randint(1, N, (B, T)).astype(np.int32))
        lens = jnp.asarray(np.array([3, 17, 0, 61], np.int32))
        qk, sk = quant.kv_encode(pk)
        qv, sv = quant.kv_encode(pv)
        out = paged_block_decode_attention(q, qk, qv, lens, bt,
                                           k_scale=sk, v_scale=sv)
        ref = paged_block_decode_reference(q, qk, qv, lens, bt,
                                           k_scale=sk, v_scale=sv)
        assert float(jnp.abs(out - ref).max()) < 2e-5


class TestKVQuantEngine:
    def _offline(self, model, prompts, n=6):
        from hetu_tpu.models.gpt_decode import generate_fast
        p, cfg = model
        return sorted(
            generate_fast(p, cfg, np.asarray([pr], np.int32),
                          num_tokens=n)[0].tolist()
            for pr in prompts)

    def _engine(self, model, prompts, n=6, **kw):
        from hetu_tpu.serving import Request, ServingEngine
        p, cfg = model
        eng = ServingEngine(p, cfg, slots=4, **kw)
        res = eng.run([Request(prompt=pr, max_new_tokens=n, seed=i)
                       for i, pr in enumerate(prompts)])
        return eng, sorted(r.tokens.tolist() for r in res.values())

    PROMPTS = [[7, 8, 9, 10], [3, 1, 4], [11, 12, 13, 14, 15]]

    def test_engine_int8_greedy_identical_to_offline(self, model):
        ref = self._offline(model, self.PROMPTS)
        for kw in [dict(paged=False, fast_path=False),
                   dict(paged=True, kv_block=8, fast_path=False),
                   dict(paged=True, kv_block=8, fast_path=True),
                   dict(paged=False, fast_path=True)]:
            eng, out = self._engine(model, self.PROMPTS,
                                    kv_quant="int8", **kw)
            assert out == ref, kw
            assert eng.kv.quant == "int8"
            assert isinstance(eng.kv.cache_k, tuple)
            assert eng.kv.cache_k[0].dtype == jnp.int8

    def test_env_knob_and_stats(self, model, monkeypatch):
        monkeypatch.setenv("HETU_KV_QUANT", "int8")
        eng, out = self._engine(model, self.PROMPTS, paged=True,
                                kv_block=8, fast_path=False)
        assert eng.kv.quant == "int8"
        assert eng.kv.stats()["quant"] == "int8"
        assert out == self._offline(model, self.PROMPTS)

    def test_chunked_prefill_shared_prefix_cow_int8(self, model):
        pre = [5, 6, 7, 8, 9, 10, 11, 12, 13]   # straddles block 4
        prompts = [pre + [20 + i] for i in range(3)]
        _, a = self._engine(model, prompts, kv_quant="int8",
                            paged=True, kv_block=4, fast_path=False,
                            prefix_share=True, prefill_chunk=4)
        eng_b, b = self._engine(model, prompts, paged=True, kv_block=4,
                                fast_path=False, prefix_share=False)
        assert a == b

    def test_cache_bytes_reduced(self, model):
        from hetu_tpu.serving import ServingEngine
        p, cfg = model
        exact = ServingEngine(p, cfg, slots=4).kv.cache_bytes
        int8 = ServingEngine(p, cfg, slots=4,
                             kv_quant="int8").kv.cache_bytes
        # Dh=8 here: (8 + 4) / 32 per value — bigger heads do better
        assert int8 < exact / 2

    def test_manager_accepts_dtype_int8(self):
        from hetu_tpu.serving import KVCacheManager, PagedKVManager
        m = KVCacheManager(layers=1, heads=2, head_dim=8, slots=2,
                           max_seq_len=32, dtype="int8")
        assert m.quant == "int8" and isinstance(m.cache_k, tuple)
        pm = PagedKVManager(layers=1, heads=2, head_dim=8, slots=2,
                            max_seq_len=32, block=8, dtype=jnp.int8)
        assert pm.quant == "int8"
        assert pm.cache_k[1].dtype == jnp.float32

    def test_teacher_forced_margin_gate(self, model):
        from hetu_tpu.models.gpt_decode import teacher_forced_logits
        p, cfg = model
        seq = np.asarray([7, 8, 9, 10, 11, 3, 1, 4, 2], np.int32)
        le = np.asarray(teacher_forced_logits(p, cfg, seq))
        lq = np.asarray(teacher_forced_logits(p, cfg, seq,
                                              kv_fake_quant=True))
        delta = float(np.abs(lq - le).max())
        assert delta < 0.1
        top2 = np.sort(le, axis=-1)
        margin = top2[:, -1] - top2[:, -2]
        confident = margin > 2 * delta
        assert confident.any()
        assert (le.argmax(-1) == lq.argmax(-1))[confident].all()

    def test_bf16_params_follow_into_cache(self, model):
        """Satellite regression: no dtype argument + bf16 params must
        give a bf16 cache (the docstring's 'follow the weights'), not a
        silent f32 upcast."""
        from hetu_tpu.serving import ServingEngine
        p, cfg = model
        pbf = {k: jnp.asarray(np.asarray(v), jnp.bfloat16)
               for k, v in p.items()}
        eng = ServingEngine(pbf, cfg, slots=2, fast_path=False,
                            paged=False)
        assert eng.kv.cache_k.dtype == jnp.bfloat16
        assert eng.params[f"qg_wte_table"].dtype == jnp.bfloat16

    def test_default_off_cache_is_plain_f32(self, model):
        from hetu_tpu.serving import ServingEngine
        p, cfg = model
        eng = ServingEngine(p, cfg, slots=2)
        assert not isinstance(eng.kv.cache_k, tuple)
        assert eng.kv.cache_k.dtype == jnp.float32
        assert eng.kv.quant is None


# --------------------------------------------------------------------- #
# provenance
# --------------------------------------------------------------------- #

class TestQuantProvenance:
    def test_active_modes_composes(self, monkeypatch):
        assert quant.active_modes() == "off"
        monkeypatch.setenv("HETU_KV_QUANT", "int8")
        monkeypatch.setenv("HETU_PS_QUANT", "int8")
        assert quant.active_modes() == "ps=int8,kv=int8"

    def test_trace_check_rejects_mixed_bench_rows(self):
        from hetu_tpu.telemetry.trace import check_quant_consistency
        rows = [{"event": "bench_row", "config": "a", "quant": "off"},
                {"event": "bench_row", "config": "b",
                 "quant": "kv=int8"}]
        assert check_quant_consistency(rows)
        assert not check_quant_consistency(rows[:1])
        # a legacy row with no stamp counts as "off" and clashes with
        # a quantized row — never compared silently
        legacy = [{"event": "bench_row", "config": "old"},
                  rows[1]]
        assert check_quant_consistency(legacy)
        assert not check_quant_consistency(
            [{"event": "bench_row", "config": "old"}, rows[0]])
