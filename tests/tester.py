"""HetuTester: build op -> run executor -> compare to numpy reference.

Mirrors the reference test harness (tests/tester.py: HetuTester runs the
GPU executor and asserts allclose against a numpy function).
"""

from __future__ import annotations

import numpy as np

import hetu_tpu as ht


class HetuTester:
    def __init__(self, op_factory, num_inputs, *args, shapes=None,
                 dtypes=None, **kwargs):
        self.op_factory = op_factory
        self.num_inputs = num_inputs
        self.args = args
        self.kwargs = kwargs
        self.shapes = shapes
        self.dtypes = dtypes

    def build(self, shapes):
        feeds = [ht.placeholder_op(f"input_{i}") for i in range(self.num_inputs)]
        out = self.op_factory(*feeds, *self.args, **self.kwargs)
        executor = ht.Executor({"test": [out]})
        return feeds, out, executor

    def make_inputs(self, shapes, seed=0):
        rng = np.random.RandomState(seed)
        inputs = []
        for i, s in enumerate(shapes):
            dt = self.dtypes[i] if self.dtypes else np.float32
            if np.issubdtype(dt, np.integer):
                inputs.append(rng.randint(0, 10, size=s).astype(dt))
            else:
                inputs.append(rng.uniform(-1, 1, size=s).astype(dt))
        return inputs

    def test(self, shapes, numpy_fn, rtol=1e-5, atol=1e-6, seed=0):
        feeds, out, executor = self.build(shapes)
        inputs = self.make_inputs(shapes, seed)
        (result,) = executor.run(
            "test", feed_dict=dict(zip(feeds, inputs)),
            convert_to_numpy_ret_vals=True)
        expected = numpy_fn(*inputs)
        np.testing.assert_allclose(result, expected, rtol=rtol, atol=atol)
        return result
