"""Speculative decoding (ISSUE 10): draft-propose / batched-verify on
the serving engine — the multi-token verify kernel, the one-dispatch
draft scan, longest-prefix acceptance + bonus token, and KV rollback.

The load-bearing contract: speculative outputs are TOKEN-IDENTICAL to
the non-speculative engine and to offline ``generate_fast`` — greedy
trivially, and SAMPLED too, because every emitted token is the target's
own sequential sample from the request's rng stream (the verify returns
the stream state after every split, so the host resumes at exactly the
accepted count).  Identity must hold across every cache configuration:
contiguous, block-table paged (with prefix sharing and chunked
prefill), int8-quantized, and the ragged fast path.

Rollback property tests (the ISSUE's satellite): randomized
propose/accept/reject sequences must leave the cache's live bytes equal
to a never-speculated replay on contiguous, paged (including COW-shared
prefixes — rollback must never free a block another holder still
references), and int8 variants (scale planes truncated in lockstep).

Weights are deterministic random GPTs (the contract is numeric parity,
not model quality); everything here is ``smoke``-tier.
"""

import json

import numpy as np
import pytest

import hetu_tpu as ht  # noqa: F401  (platform forcing + compat shims)
import jax
import jax.numpy as jnp

from hetu_tpu.models import GPTConfig
from hetu_tpu.models.gpt_decode import (
    _decode_step, _kv_scatter, _verify_step, generate_fast,
    resolve_draft_layers, resolve_spec_k,
)
from hetu_tpu.serving import (
    KVCacheManager, PagedKVManager, Request, ServingEngine,
    ServingMetrics,
)


def _rand_gpt(name="sp", L=2, H=2, Dh=8, V=61, S=32, seed=0):
    """Deterministic random params in generate_fast's naming contract."""
    rng = np.random.RandomState(seed)
    hd = H * Dh
    p = {f"{name}_wte_table": rng.randn(V, hd) * 0.05,
         f"{name}_wpe": rng.randn(S, hd) * 0.05,
         f"{name}_ln_f_scale": np.ones(hd),
         f"{name}_ln_f_bias": np.zeros(hd)}
    for i in range(L):
        us = f"{name}_h{i}"
        for w, shp in [("attn_q", (hd, hd)), ("attn_k", (hd, hd)),
                       ("attn_v", (hd, hd)), ("attn_proj", (hd, hd)),
                       ("ffn_wi", (hd, 4 * hd)), ("ffn_wo", (4 * hd, hd))]:
            p[f"{us}_{w}_weight"] = rng.randn(*shp) * 0.05
            p[f"{us}_{w}_bias"] = np.zeros(shp[1])
        for ln in ("ln1", "ln2"):
            p[f"{us}_{ln}_scale"] = np.ones(hd)
            p[f"{us}_{ln}_bias"] = np.zeros(hd)
    cfg = GPTConfig(vocab_size=V, hidden_size=hd, num_hidden_layers=L,
                    num_attention_heads=H, max_position_embeddings=S,
                    batch_size=1, seq_len=S, dropout_rate=0.0)
    return p, cfg


def _zero_late_layers(p, name="sp", first=1, L=2):
    """Output-zero layers >= first: the truncated-layer draft's logits
    then equal the target's bitwise — greedy acceptance 1.0 while the
    target still pays full-depth compute (the high-acceptance fixture)."""
    hp = dict(p)
    for i in range(first, L):
        for wn in ("attn_proj_weight", "attn_proj_bias",
                   "ffn_wo_weight", "ffn_wo_bias"):
            hp[f"{name}_h{i}_{wn}"] = np.zeros_like(p[f"{name}_h{i}_{wn}"])
    return hp


@pytest.fixture(scope="module")
def model():
    return _rand_gpt()


TRACE = [([7, 8, 9], 6), ([3, 4], 8), ([1, 2, 3, 4, 5], 4), ([11], 7)]


def _mk(trace=TRACE, **kw):
    return [Request(prompt=pr, max_new_tokens=n, **kw)
            for pr, n in trace]


def _outs(res):
    return sorted(r.tokens.tolist() for r in res.values())


# ------------------------------------------------------------------- #
# verify kernels
# ------------------------------------------------------------------- #


@pytest.mark.smoke
class TestVerifyKernel:
    def _data(self, B=4, Q=4, H=2, Dh=8, S=64, seed=0):
        rng = np.random.RandomState(seed)
        q = rng.randn(B, Q, H, Dh).astype(np.float32)
        k = rng.randn(B, S, H, Dh).astype(np.float32)
        v = rng.randn(B, S, H, Dh).astype(np.float32)
        qlens = np.array([Q, Q - 1, 1, 0], np.int32)[:B]
        lens = np.array([17, 33, 5, 0], np.int32)[:B]
        return q, k, v, lens, qlens

    def test_matches_masked_reference(self):
        from hetu_tpu.kernels.decode_attention import (
            masked_verify_reference, paged_verify_attention,
        )
        q, k, v, lens, qlens = self._data()
        got = paged_verify_attention(q, k, v, lens, qlens, block_k=16)
        want = masked_verify_reference(q, k, v, lens, qlens)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-5)

    def test_q1_degenerates_to_decode_kernel(self):
        """A q_len=1 verify block scores exactly what the single-query
        decode kernel scores."""
        from hetu_tpu.kernels.decode_attention import (
            paged_decode_attention, paged_verify_attention,
        )
        q, k, v, lens, _ = self._data()
        lens = np.maximum(lens, 1)
        got = paged_verify_attention(q[:, :1], k, v, lens,
                                     np.ones_like(lens), block_k=16)
        want = paged_decode_attention(q[:, 0], k, v, lens, block_k=16)
        np.testing.assert_allclose(np.asarray(got[:, 0]),
                                   np.asarray(want), rtol=1e-5,
                                   atol=1e-5)

    def test_block_table_variant_permuted_pool(self):
        from hetu_tpu.kernels.decode_attention import (
            paged_block_verify_attention, paged_block_verify_reference,
        )
        rng = np.random.RandomState(1)
        B, Q, H, Dh, bs, T = 3, 3, 2, 8, 8, 6
        N = B * T + 1
        pool_k = rng.randn(N, bs, H, Dh).astype(np.float32)
        pool_v = rng.randn(N, bs, H, Dh).astype(np.float32)
        q = rng.randn(B, Q, H, Dh).astype(np.float32)
        perm = rng.permutation(np.arange(1, N))[:B * T]
        tables = perm.reshape(B, T).astype(np.int32)
        lens = np.array([bs * 2 + 3, bs * T, 2], np.int32)
        qlens = np.array([Q, Q - 1, 1], np.int32)
        got = paged_block_verify_attention(q, pool_k, pool_v, lens,
                                           qlens, tables)
        want = paged_block_verify_reference(q, pool_k, pool_v, lens,
                                            qlens, tables)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-5)

    def test_int8_variants(self):
        from hetu_tpu.kernels.decode_attention import (
            masked_verify_reference, paged_verify_attention,
        )
        from hetu_tpu.quant import kv_encode
        q, k, v, lens, qlens = self._data()
        kq, ks = kv_encode(jnp.asarray(k))
        vq, vs = kv_encode(jnp.asarray(v))
        got = paged_verify_attention(q, kq, vq, lens, qlens,
                                     block_k=16, k_scale=ks, v_scale=vs)
        want = masked_verify_reference(q, kq, vq, lens, qlens,
                                       k_scale=ks, v_scale=vs)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-5)

    def test_zero_length_slot_outputs_zero(self):
        from hetu_tpu.kernels.decode_attention import (
            paged_verify_attention,
        )
        q, k, v, lens, qlens = self._data()
        got = np.asarray(paged_verify_attention(q, k, v, lens, qlens,
                                                block_k=16))
        assert np.all(got[3] == 0.0)       # lens[3] == 0


@pytest.mark.smoke
class TestVerifyStep:
    def test_matches_sequential_decode_steps(self, model):
        """``_verify_step`` over a Q-block == Q sequential
        ``_decode_step`` calls: logits bitwise, cache bitwise."""
        p, cfg = model
        name, L, H = "sp", 2, 2
        Dh, S = 8, 32
        cfgt = (name, L, H, Dh, S)
        from hetu_tpu.models.gpt_decode import _prep_param
        params = {k: _prep_param(v) for k, v in p.items()}
        B = 3
        rng = np.random.RandomState(0)
        prompt = rng.randint(0, 61, (B, 6)).astype(np.int32)
        ck = jnp.zeros((L, B, S, H, Dh))
        cv = jnp.zeros_like(ck)
        for t in range(6):
            _, ck, cv = _decode_step(params, cfgt, ck, cv,
                                     jnp.int32(t), prompt[:, t])
        tokens = rng.randint(0, 61, (B, 4)).astype(np.int32)
        pos = np.full(B, 6, np.int32)
        qlen = np.array([4, 2, 1], np.int32)
        lv, ckv, cvv = _verify_step(params, cfgt, ck, cv, pos,
                                    jnp.asarray(tokens),
                                    jnp.asarray(qlen))
        lv = np.asarray(lv)
        ck2, cv2 = ck, cv
        p2 = pos.copy()
        for j in range(4):
            l2, ck2, cv2 = _decode_step(params, cfgt, ck2, cv2, p2,
                                        tokens[:, j])
            l2 = np.asarray(l2)
            for b in range(B):
                if j < qlen[b]:
                    np.testing.assert_array_equal(lv[b, j], l2[b])
            p2 = p2 + 1
        # live cache region bitwise equal (dead verify positions land
        # beyond each slot's live length)
        for b in range(B):
            n = 6 + int(qlen[b])
            np.testing.assert_array_equal(
                np.asarray(ckv)[:, b, :n], np.asarray(ck2)[:, b, :n])


# ------------------------------------------------------------------- #
# engine identity across cache configurations
# ------------------------------------------------------------------- #


@pytest.mark.smoke
class TestEngineIdentity:
    # contiguous spec-vs-plain is covered by test_sampled_identity and
    # spec-vs-offline below; these pin the non-trivial cache layouts
    # (the ISSUE's contiguous/paged/int8/chunked/shared-prefix matrix,
    # fast_path exercising the verify KERNELS in interpret mode)
    CONFIGS = [
        ("paged_shared", {"paged": True, "kv_block": 4,
                          "prefix_share": True}),
        ("paged_chunked", {"paged": True, "kv_block": 4,
                           "prefill_chunk": 3}),
        ("int8", {"kv_quant": "int8"}),
        ("paged_fast", {"paged": True, "kv_block": 4,
                        "fast_path": True}),
    ]

    @pytest.mark.parametrize("label,kw",
                             CONFIGS, ids=[c[0] for c in CONFIGS])
    def test_greedy_identity(self, model, label, kw):
        """Acceptance: speculative greedy outputs token-identical to
        the plain engine under every cache configuration."""
        p, cfg = model
        plain = ServingEngine(p, cfg, slots=2, queue_limit=16,
                              **kw).run(_mk())
        eng = ServingEngine(p, cfg, slots=2, queue_limit=16, spec=3,
                            spec_adapt=False, spec_draft_layers=1, **kw)
        res = eng.run(_mk())
        assert _outs(plain) == _outs(res)
        assert eng.spec_waves > 0 and eng.spec_proposed > 0

    def test_greedy_identity_vs_offline(self, model):
        """Engine speculative greedy == offline generate_fast — the
        cross-path acceptance criterion."""
        p, cfg = model
        eng = ServingEngine(p, cfg, slots=2, queue_limit=16, spec=3,
                            spec_adapt=False, spec_draft_layers=1)
        res = eng.run(_mk())
        for pr, n in TRACE:
            want = generate_fast(p, cfg, [pr], num_tokens=n)[0]
            got = [r for r in res.values()
                   if r.tokens[:len(pr)].tolist() == list(pr)
                   and r.n_generated == n]
            assert any(g.tokens.tolist() == want.tolist() for g in got)

    def test_sampled_identity(self, model):
        """Sampling identity, not just distributional correctness:
        accepted tokens ARE the target's own sequential samples, so
        temperature/top_k/seed mixes reproduce the plain engine's
        outputs token for token."""
        p, cfg = model
        spec = [([3, 4], 0.9, 5, 11), ([7, 8, 9], 0.7, 3, 22),
                ([11], 1.1, 0, 33), ([5, 6], 0.8, 4, 44)]

        def run(spec_on):
            kw = (dict(spec=3, spec_adapt=False, spec_draft_layers=1)
                  if spec_on else {})
            eng = ServingEngine(p, cfg, slots=2, queue_limit=16, **kw)
            reqs = [Request(prompt=pr, max_new_tokens=6, temperature=t,
                            top_k=k, seed=s) for pr, t, k, s in spec]
            res = eng.run(reqs)
            return {tuple(r.prompt): res[r.request_id].tokens.tolist()
                    for r in reqs}

        assert run(False) == run(True)

    def test_eos_mid_wave(self, model):
        """An EOS inside the accepted span cuts the emission there and
        rolls the cache back to the cut; finish_reason and tokens match
        the plain engine."""
        p, cfg = model
        plain0 = generate_fast(p, cfg, [[7, 8, 9]], num_tokens=8)[0]
        eos = int(plain0[5])   # a mid-generation token becomes the EOS
        req = lambda: [Request(prompt=[7, 8, 9], max_new_tokens=8,  # noqa: E731
                               eos_id=eos)]
        pl = next(iter(ServingEngine(p, cfg, slots=2).run(req()).values()))
        sp = next(iter(ServingEngine(
            p, cfg, slots=2, spec=3, spec_adapt=False,
            spec_draft_layers=1).run(req()).values()))
        assert sp.tokens.tolist() == pl.tokens.tolist()
        assert sp.finish_reason == pl.finish_reason

    def test_high_acceptance_waves_and_attribution(self, model):
        """With the post-draft layers output-zeroed (draft logits ==
        target logits), every draft is accepted and the engine emits
        multiple tokens per wave — fewer waves than tokens; each
        Result's accepted/proposed attribution accounts for every
        generated token."""
        p, cfg = model
        hp = _zero_late_layers(p)
        eng = ServingEngine(hp, cfg, slots=2, queue_limit=16, spec=3,
                            spec_adapt=False, spec_draft_layers=1)
        res = eng.run(_mk())
        total = sum(r.n_generated for r in res.values())
        assert eng.spec_accepted == eng.spec_proposed > 0
        assert eng.spec_acceptance == 1.0
        assert eng.spec_waves < total
        snap = eng.metrics.snapshot()
        assert snap["tokens_per_step_mean"] > 1.0
        saw_accept = False
        for r in res.values():
            assert r.spec_proposed >= r.spec_accepted >= 0
            assert r.spec_accepted <= r.n_generated - 1
            saw_accept |= r.spec_accepted > 0
        assert saw_accept

    def test_adaptive_k_ramps_and_backs_off(self, model):
        """The sliding-window controller grows k to the cap under
        sustained full acceptance and collapses it to 1 under
        near-zero acceptance."""
        p, cfg = model
        hp = _zero_late_layers(p)
        eng = ServingEngine(hp, cfg, slots=2, queue_limit=64, spec=4,
                            spec_adapt=True, spec_draft_layers=1)
        assert eng._spec_kcur == 2    # ramp-up start: spec_k // 2
        eng.run([Request(prompt=[i % 50 + 1], max_new_tokens=18,
                         seed=i) for i in range(6)])
        assert eng._spec_kcur == 4
        # near-zero acceptance: hot sampling vs a greedy draft
        eng2 = ServingEngine(p, cfg, slots=2, queue_limit=64, spec=4,
                             spec_adapt=True, spec_draft_layers=1)
        eng2.run([Request(prompt=[i % 50 + 1], max_new_tokens=12,
                          temperature=2.0, seed=i) for i in range(6)])
        assert eng2._spec_kcur == 1
        assert eng2.spec_mean_k < 4

    def test_spec_env_knobs(self, model, monkeypatch):
        """$HETU_SPEC_K / $HETU_SPEC_DRAFT_LAYERS drive the engine and
        resolvers; explicit arguments win."""
        monkeypatch.setenv("HETU_SPEC_K", "3")
        monkeypatch.setenv("HETU_SPEC_DRAFT_LAYERS", "1")
        assert resolve_spec_k(None) == 3
        assert resolve_spec_k(5) == 5
        assert resolve_draft_layers(None, 8) == 1
        monkeypatch.delenv("HETU_SPEC_DRAFT_LAYERS")
        assert resolve_draft_layers(None, 8) == 2     # auto: L // 4
        assert resolve_draft_layers(99, 8) == 8       # clamped
        p, cfg = model
        sub = TRACE[:2]
        eng = ServingEngine(p, cfg, slots=2)
        assert eng.spec_k == 3 and eng.spec_draft_layers == 1
        plain_env = eng.run(_mk(sub))
        monkeypatch.setenv("HETU_SPEC_K", "0")
        plain = ServingEngine(p, cfg, slots=2).run(_mk(sub))
        assert _outs(plain_env) == _outs(plain)


@pytest.mark.smoke
class TestOfflineSpec:
    def test_generate_fast_spec_identity(self, model):
        p, cfg = model
        prompts = [[7, 8, 9], [3, 4, 5]]
        want = generate_fast(p, cfg, prompts, num_tokens=8)
        got = generate_fast(p, cfg, prompts, num_tokens=8, spec=3,
                            spec_draft_layers=1)
        assert want.tolist() == got.tolist()

    def test_generate_fast_spec_eos(self, model):
        p, cfg = model
        plain0 = generate_fast(p, cfg, [[7, 8, 9]], num_tokens=8)[0]
        eos = int(plain0[5])
        want = generate_fast(p, cfg, [[7, 8, 9]], num_tokens=8,
                             eos_id=eos, pad_id=0)
        got = generate_fast(p, cfg, [[7, 8, 9]], num_tokens=8,
                            eos_id=eos, pad_id=0, spec=3,
                            spec_draft_layers=1)
        assert want.tolist() == got.tolist()

    def test_generate_fast_spec_num_tokens_1(self, model):
        p, cfg = model
        want = generate_fast(p, cfg, [[7, 8, 9]], num_tokens=1)
        got = generate_fast(p, cfg, [[7, 8, 9]], num_tokens=1, spec=3,
                            spec_draft_layers=1)
        assert want.tolist() == got.tolist()


# ------------------------------------------------------------------- #
# KV rollback (truncate) property tests
# ------------------------------------------------------------------- #


def _write_positions(m, slot, positions, values, L=1, H=1, Dh=4,
                     paged=True):
    """Write one [H, Dh] slab per position through the manager's
    layout (block tables or slot rows), mirroring the verify write."""
    for pos, val in zip(positions, values):
        v = jnp.asarray(np.full((1, H, Dh), val, np.float32))
        for i in range(L):
            if paged:
                b = int(m.tables[slot, pos // m.block])
                off = pos % m.block
                m.cache_k = _kv_scatter(m.cache_k,
                                        (i, np.array([b]),
                                         np.array([off])), v)
                m.cache_v = _kv_scatter(m.cache_v,
                                        (i, np.array([b]),
                                         np.array([off])), v)
            else:
                m.cache_k = _kv_scatter(
                    m.cache_k, (i, np.array([slot]), np.array([pos])), v)
                m.cache_v = _kv_scatter(
                    m.cache_v, (i, np.array([slot]), np.array([pos])), v)


def _live_bytes(m, slot, paged=True):
    """The slot's live-region cache content (payload + scale planes for
    quantized layouts), gathered position by position."""
    out = []
    n = int(m.lengths[slot])
    quant = isinstance(m.cache_k, tuple)
    for pos in range(n):
        if paged:
            b = int(m.tables[slot, pos // m.block])
            off = pos % m.block
            idx = (slice(None), b, off)
        else:
            idx = (slice(None), slot, pos)
        if quant:
            out.append((np.asarray(m.cache_k[0][idx]).tobytes(),
                        np.asarray(m.cache_k[1][idx]).tobytes(),
                        np.asarray(m.cache_v[0][idx]).tobytes(),
                        np.asarray(m.cache_v[1][idx]).tobytes()))
        else:
            out.append((np.asarray(m.cache_k[idx]).tobytes(),
                        np.asarray(m.cache_v[idx]).tobytes()))
    return out


@pytest.mark.smoke
class TestKVRollback:
    def _mgr(self, paged, dtype=jnp.float32):
        if paged:
            return PagedKVManager(layers=1, heads=1, head_dim=4,
                                  slots=2, max_seq_len=64, block=4,
                                  dtype=dtype, prefix_share=False)
        return KVCacheManager(layers=1, heads=1, head_dim=4, slots=2,
                              max_seq_len=64, dtype=dtype)

    @pytest.mark.parametrize("paged", [False, True],
                             ids=["contiguous", "paged"])
    @pytest.mark.parametrize("dtype", [jnp.float32, "int8"],
                             ids=["f32", "int8"])
    def test_speculate_rollback_equals_replay(self, paged, dtype):
        """Property: after randomized propose/accept/reject rounds,
        the live cache region equals a never-speculated replay byte
        for byte — on both layouts and the int8 variant (whose scale
        planes must truncate in lockstep)."""
        rng = np.random.RandomState(7)
        spec = self._mgr(paged, dtype)
        replay = self._mgr(paged, dtype)
        if paged:
            slot_s, _ = spec.alloc("r", [1, 2, 3], 40)
            slot_r, _ = replay.alloc("r", [1, 2, 3], 40)
        else:
            slot_s = spec.alloc("r", 0)
            slot_r = replay.alloc("r", 0)
        canonical = lambda pos: float(np.sin(pos + 1))  # noqa: E731
        n = 0
        for rnd in range(10):
            q = int(rng.randint(1, 5))
            if n + q > 40:
                break
            keep = int(rng.randint(1, q + 1))
            vals = [canonical(n + j) if j < keep
                    else 1e3 + rnd * 10 + j          # rejected garbage
                    for j in range(q)]
            _write_positions(spec, slot_s, range(n, n + q), vals,
                             paged=paged)
            spec.advance(slot_s, q)
            spec.truncate(slot_s, n + keep)
            _write_positions(replay, slot_r, range(n, n + keep),
                             [canonical(n + j) for j in range(keep)],
                             paged=paged)
            replay.advance(slot_r, keep)
            n += keep
        assert int(spec.lengths[slot_s]) == n
        assert _live_bytes(spec, slot_s, paged) == \
            _live_bytes(replay, slot_r, paged)
        if paged:
            assert spec.free_blocks == replay.free_blocks

    def test_truncate_errors(self):
        m = self._mgr(False)
        slot = m.alloc("r", 5)
        with pytest.raises(ValueError):
            m.truncate(slot, 6)        # beyond filled
        with pytest.raises(ValueError):
            m.truncate(slot, -1)
        m.truncate(slot, 3)
        assert int(m.lengths[slot]) == 3
        m.release(slot)
        with pytest.raises(ValueError):
            m.truncate(slot, 0)        # free slot

    def test_paged_truncate_never_frees_shared_blocks(self):
        """COW discipline: truncating INTO a shared region detaches the
        shared blocks from the truncating slot (fork-on-boundary, fresh
        swap past it) and never frees a block the prefix cache or
        another request still references."""
        m = PagedKVManager(layers=1, heads=1, head_dim=4, slots=3,
                           max_seq_len=64, block=4, prefix_share=True)
        prompt = list(range(1, 11))                      # 10 tokens
        s0, cached = m.alloc("a", prompt, 16)
        assert cached == 0
        _write_positions(m, s0, range(10),
                         [float(t) for t in prompt], paged=True)
        m.advance(s0, 10)
        m.register_prefix(np.asarray(prompt), s0)
        # a second request attaches the shared prefix
        s1, cached = m.alloc("b", prompt + [30, 31], 20)
        assert cached > 0
        shared = [int(b) for b in m.tables[s1, :cached // m.block]]
        assert all(m.ref[b] >= 2 for b in shared)
        m.advance(s1, 12 - cached)   # pretend the tail got written
        before = _live_bytes(m, s0, True)
        cow0 = m.cow_copies
        # roll s1 back INTO the shared region (mid-block: position 6)
        m.truncate(s1, 6)
        # every surviving table entry s1 will write is now private
        for j in range(6 // m.block, int(m.n_table[s1])):
            assert m.ref[int(m.tables[s1, j])] == 1
        # the boundary block (positions 4..7, live below 6) was FORKED
        assert m.cow_copies == cow0 + 1
        # the shared blocks survive for every other holder, unharmed
        for b in shared:
            assert m.ref[b] >= 1
            assert b not in m._free
        assert _live_bytes(m, s0, True) == before
        # s1's live content below the cut is intact too
        got = _live_bytes(m, s1, True)
        want = [np.full((1, 1, 4), float(t), np.float32).tobytes()
                for t in prompt[:6]]
        assert [g[0] for g in got] == want

    def test_engine_rollback_leaves_pool_consistent(self, model):
        """End to end: a paged speculative run releases every block it
        reserved — refcounts return to zero, the free list to full."""
        p, cfg = model
        eng = ServingEngine(p, cfg, slots=2, queue_limit=16, spec=3,
                            spec_adapt=False, spec_draft_layers=1,
                            paged=True, kv_block=4, prefix_share=False)
        eng.run(_mk())
        assert eng.kv.free_blocks == eng.kv.n_blocks - 1
        assert int(np.sum(eng.kv.ref[1:])) == 0


# ------------------------------------------------------------------- #
# TPOT accounting + observability
# ------------------------------------------------------------------- #


@pytest.mark.smoke
class TestTpotAccounting:
    def test_tpot_from_per_step_token_counts(self, tmp_path):
        """The satellite fix: TPOT percentiles come from real per-step
        emitted-token counts, not decode_ms / (n_generated - 1)."""
        from hetu_tpu import telemetry
        m = ServingMetrics(log_path=str(tmp_path / "s.jsonl"))
        m.record_step(live=2, slots=4, queue_depth=0, dt_s=0.2,
                      new_tokens=4)
        m.record_step(live=2, slots=4, queue_depth=0, dt_s=0.2,
                      new_tokens=1)
        snap = m.snapshot()
        # 5 tokens: four at 0.05 s/tok, one at 0.2 -> p50 is 0.05
        assert abs(snap["tpot_p50_s"] - 0.05) < 1e-9
        assert snap["tpot_p99_s"] > 0.05
        assert snap["tokens_per_step_mean"] == 2.5
        steps = [e for e in m.events if e["event"] == "serve_step"]
        assert [e["new_tokens"] for e in steps] == [4, 1]
        hist = telemetry.snapshot()["histograms"].get(
            "serve.tokens_per_step")
        assert hist is not None and hist["count"] >= 2

    def test_spec_fields_on_step_events(self, model, tmp_path):
        p, cfg = model
        hp = _zero_late_layers(p)
        log = str(tmp_path / "spec.jsonl")
        eng = ServingEngine(hp, cfg, slots=2, queue_limit=16, spec=3,
                            spec_adapt=False, spec_draft_layers=1,
                            log_path=log)
        eng.run(_mk())
        with open(log) as f:
            recs = [json.loads(ln) for ln in f]
        steps = [r for r in recs if r["event"] == "serve_step"]
        assert steps and all("spec_k" in r and "spec_proposed" in r
                             and "spec_accepted" in r and
                             "new_tokens" in r for r in steps)
        assert sum(r["spec_accepted"] for r in steps) == \
            eng.spec_accepted
        retires = [r for r in recs if r["event"] == "req_retire"]
        for r in retires:
            assert r["spec_accepted"] + r["spec_bonus"] + 1 == \
                r["n_generated"]

    def test_trace_check_spec_attribution_rule(self, model, tmp_path):
        """hetu_trace --check passes on a real speculative stream and
        flags a tampered req_retire whose accounting no longer sums."""
        from hetu_tpu.telemetry import trace as trace_mod
        p, cfg = model
        log = str(tmp_path / "spec.jsonl")
        eng = ServingEngine(p, cfg, slots=2, queue_limit=16, spec=3,
                            spec_adapt=False, spec_draft_layers=1,
                            log_path=log)
        eng.run(_mk())
        assert trace_mod.main([log, "--check"]) == 0
        with open(log) as f:
            recs = [json.loads(ln) for ln in f]
        bad = next(r for r in recs if r["event"] == "req_retire")
        bad = dict(bad)
        bad["spec_accepted"] = bad["spec_accepted"] + 5
        bad["request"] = "req-tampered"
        problems = trace_mod.check_spec_attribution(recs + [bad])
        assert len(problems) == 1 and "req-tampered" in problems[0]
        # non-speculative records are exempt
        assert trace_mod.check_spec_attribution(
            [{"event": "req_retire", "request": "r", "t": 0.0,
              "ttft_ms": 1.0, "n_generated": 4}]) == []

    def test_hetu_top_spec_columns(self, model, tmp_path):
        from hetu_tpu.telemetry.top import (render, render_fleet,
                                            summarize, summarize_fleet)
        from hetu_tpu.telemetry.trace import read_events
        p, cfg = model
        hp = _zero_late_layers(p)
        log = str(tmp_path / "top.jsonl")
        eng = ServingEngine(hp, cfg, slots=2, queue_limit=16, spec=3,
                            spec_adapt=False, spec_draft_layers=1,
                            log_path=log, tags={"replica": 0})
        eng.run(_mk())
        events, bad = read_events([log])
        assert bad == 0
        stats = summarize(events)
        sp = stats["spec"]
        assert sp["drafted"] == eng.spec_proposed
        assert sp["accepted"] == eng.spec_accepted
        assert sp["acceptance"] == 1.0
        assert sp["mean_k"] == 3.0
        assert stats["tpot_p50_ms"] is not None
        frame = render(stats)
        assert "acceptance" in frame and "mean_k" in frame
        fleet = summarize_fleet(events)
        row = fleet["replicas"][0]
        assert row["drafted"] == eng.spec_proposed
        assert row["acceptance"] == 1.0
        assert "drafted" in render_fleet(fleet)
