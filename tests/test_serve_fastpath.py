"""Ragged KV serving fast path: flash prefill + the paged
decode-attention kernel (kernels/decode_attention.py), pinned against
the masked/scan reference in interpret mode.

The load-bearing contracts, each tested separately:
- kernel parity: ``paged_decode_attention`` equals the masked-S_max
  oracle to tolerance across fill fractions, pow2 buckets, bf16, and
  ragged per-slot lengths (every slot a different filled length);
- prefill parity: one batched flash-prefill dispatch writes the same
  cache rows and samples the same first token as the teacher-forced
  per-request scan;
- end-to-end greedy parity: engine outputs with ``fast_path=True`` are
  token-identical to the masked reference path (and to offline
  ``generate_fast`` on both of ITS prefill modes) for mixed lengths,
  bf16, and tensor-parallel params;
- batched admission: a burst of k same-bucket arrivals costs ONE
  jitted prefill dispatch on the fast path (k on the reference).

Everything runs on the forced 8-device CPU mesh via interpret mode —
``smoke`` tier.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import hetu_tpu as ht  # noqa: F401  (platform forcing + compat shims)
from hetu_tpu.kernels.decode_attention import (
    masked_decode_reference, paged_decode_attention,
)
from hetu_tpu.models import GPTConfig
from hetu_tpu.models.gpt_decode import (
    _resolve_fast, generate_fast, tp_shard_params,
)
from hetu_tpu.serving import Request, ServingEngine


def _rand_gpt(name="fp", L=2, H=2, Dh=8, V=61, S=32, seed=0):
    """Deterministic random params in generate_fast's naming contract
    (mirrors test_serving's helper; kept local so the files stay
    independently runnable)."""
    rng = np.random.RandomState(seed)
    hd = H * Dh
    p = {f"{name}_wte_table": rng.randn(V, hd) * 0.05,
         f"{name}_wpe": rng.randn(S, hd) * 0.05,
         f"{name}_ln_f_scale": np.ones(hd),
         f"{name}_ln_f_bias": np.zeros(hd)}
    for i in range(L):
        us = f"{name}_h{i}"
        for w, shp in [("attn_q", (hd, hd)), ("attn_k", (hd, hd)),
                       ("attn_v", (hd, hd)), ("attn_proj", (hd, hd)),
                       ("ffn_wi", (hd, 4 * hd)), ("ffn_wo", (4 * hd, hd))]:
            p[f"{us}_{w}_weight"] = rng.randn(*shp) * 0.05
            p[f"{us}_{w}_bias"] = np.zeros(shp[1])
        for ln in ("ln1", "ln2"):
            p[f"{us}_{ln}_scale"] = np.ones(hd)
            p[f"{us}_{ln}_bias"] = np.zeros(hd)
    cfg = GPTConfig(vocab_size=V, hidden_size=hd, num_hidden_layers=L,
                    num_attention_heads=H, max_position_embeddings=S,
                    batch_size=1, seq_len=S, dropout_rate=0.0)
    return p, cfg


@pytest.fixture(scope="module")
def model():
    return _rand_gpt()


@pytest.mark.smoke
class TestPagedDecodeKernel:
    """The kernel against the masked-S_max oracle."""

    def _rand_qkv(self, B, S, H, Dh, dtype=jnp.float32, seed=0):
        rng = np.random.RandomState(seed)
        q = jnp.asarray(rng.randn(B, H, Dh), dtype)
        k = jnp.asarray(rng.randn(B, S, H, Dh), dtype)
        v = jnp.asarray(rng.randn(B, S, H, Dh), dtype)
        return q, k, v

    @pytest.mark.parametrize("S", [16, 64, 256])
    def test_fill_fraction_sweep_f32(self, S):
        """Every fill fraction from one token to brim-full, including
        block-boundary straddles."""
        B, H, Dh = 4, 2, 8
        q, k, v = self._rand_qkv(B, S, H, Dh)
        for fill in (1, 2, S // 4, S // 2, S // 2 + 1, S - 1, S):
            lens = jnp.full((B,), fill, jnp.int32)
            got = paged_decode_attention(q, k, v, lens)
            want = masked_decode_reference(q, k, v, lens)
            np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                       rtol=1e-5, atol=1e-5)

    def test_ragged_per_slot_lengths(self):
        """Each slot a different filled length — the serving shape."""
        B, S, H, Dh = 8, 128, 2, 8
        q, k, v = self._rand_qkv(B, S, H, Dh, seed=3)
        lens = jnp.asarray([1, 7, 16, 17, 63, 64, 100, 128], jnp.int32)
        got = paged_decode_attention(q, k, v, lens)
        want = masked_decode_reference(q, k, v, lens)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-5)

    def test_bf16_accumulates_f32(self):
        """bf16 caches: scores/output accumulate f32 in the kernel, so
        the kernel tracks the f32 oracle to bf16 resolution."""
        B, S, H, Dh = 4, 64, 2, 8
        q, k, v = self._rand_qkv(B, S, H, Dh, jnp.bfloat16, seed=5)
        lens = jnp.asarray([3, 17, 40, 64], jnp.int32)
        got = paged_decode_attention(q, k, v, lens)
        assert got.dtype == jnp.bfloat16
        want = masked_decode_reference(q, k, v, lens)
        np.testing.assert_allclose(np.asarray(got, np.float32),
                                   np.asarray(want), rtol=0.05, atol=0.05)

    def test_zero_length_slot_returns_zeros(self):
        """lengths 0 (no live positions) matches the oracle's dead-row
        convention: exact zeros, no NaN from the empty softmax."""
        B, S, H, Dh = 2, 32, 2, 8
        q, k, v = self._rand_qkv(B, S, H, Dh, seed=7)
        lens = jnp.asarray([0, 9], jnp.int32)
        got = np.asarray(paged_decode_attention(q, k, v, lens))
        assert np.all(got[0] == 0.0) and np.all(np.isfinite(got))
        want = masked_decode_reference(q, k, v, lens)
        np.testing.assert_allclose(got, np.asarray(want),
                                   rtol=1e-5, atol=1e-5)

    def test_under_jit_and_under_scan(self):
        """The serving engine calls the kernel from inside jit; the
        offline path could call it from inside lax.scan — both trace."""
        B, S, H, Dh = 2, 32, 2, 8
        q, k, v = self._rand_qkv(B, S, H, Dh, seed=9)
        lens = jnp.asarray([5, 30], jnp.int32)
        jitted = jax.jit(paged_decode_attention)(q, k, v, lens)
        want = masked_decode_reference(q, k, v, lens)
        np.testing.assert_allclose(np.asarray(jitted), np.asarray(want),
                                   rtol=1e-5, atol=1e-5)


@pytest.mark.smoke
class TestFlashPrefillParity:
    """Batched flash prefill vs the teacher-forced scan prefill."""

    def test_cache_rows_and_first_token_match_scan(self, model):
        """The two prefill implementations must write numerically
        matching K/V into the slot rows and sample the same first
        token, across prompt lengths straddling bucket boundaries."""
        from hetu_tpu.models.gpt_decode import (
            _prep_param, serve_prefill_batch_fn, serve_prefill_fn,
        )
        from hetu_tpu.serving import KVCacheManager
        p, cfg = model
        params = {k: _prep_param(v) for k, v in p.items()}
        Dh = cfg.hidden_size // cfg.num_attention_heads
        cfg_tuple = ("fp", cfg.num_hidden_layers,
                     cfg.num_attention_heads, Dh,
                     cfg.max_position_embeddings)
        scan = serve_prefill_fn(donate=False)
        flash = serve_prefill_batch_fn(donate=False)
        for P in (1, 3, 7, 8, 9, 16):
            kv = KVCacheManager(
                layers=cfg.num_hidden_layers,
                heads=cfg.num_attention_heads, head_dim=Dh, slots=2,
                max_seq_len=cfg.max_position_embeddings)
            pb = kv.bucket_prompt(P)
            prompt = np.arange(1, P + 1, dtype=np.int32) % 60
            padded = np.zeros(pb, np.int32)
            padded[:P] = prompt
            key = np.asarray(jax.random.PRNGKey(0), np.uint32)
            f_scan, ck_s, cv_s, _ = scan(
                params, cfg_tuple, kv.cache_k, kv.cache_v,
                np.int32(1), padded, np.int32(P),
                np.float32(0.0), np.int32(0), key)
            f_flash, ck_f, cv_f, _ = flash(
                params, cfg_tuple, kv.cache_k, kv.cache_v,
                np.asarray([1], np.int32), padded[None],
                np.asarray([P], np.int32),
                np.zeros(1, np.float32), np.zeros(1, np.int32),
                key[None])
            assert int(f_scan) == int(f_flash[0]), P
            # only the FILLED prefix of the slot row is contractual
            # (the scan skips pad positions, flash writes pad garbage
            # there — decode overwrites each before the mask admits it)
            np.testing.assert_allclose(
                np.asarray(ck_s[:, 1, :P]), np.asarray(ck_f[:, 1, :P]),
                rtol=2e-5, atol=2e-5)
            np.testing.assert_allclose(
                np.asarray(cv_s[:, 1, :P]), np.asarray(cv_f[:, 1, :P]),
                rtol=2e-5, atol=2e-5)

    def test_generate_fast_flash_equals_scan(self, model):
        """Offline unification: prefill="flash" greedy outputs are
        token-identical to the teacher-forced reference, eos included."""
        p, cfg = model
        for prompt, n in [([7, 8, 9], 6), ([3, 4], 11), ([11], 7),
                          ([1, 2, 3, 4, 5, 6, 7, 8, 9], 4)]:
            a = generate_fast(p, cfg, [prompt], num_tokens=n,
                              prefill="scan")[0]
            b = generate_fast(p, cfg, [prompt], num_tokens=n,
                              prefill="flash")[0]
            assert a.tolist() == b.tolist(), prompt
        plain = generate_fast(p, cfg, [[7, 8, 9]], num_tokens=8,
                              prefill="scan")[0]
        eos = int(plain[3])
        a = generate_fast(p, cfg, [[7, 8, 9]], num_tokens=8, eos_id=eos,
                          prefill="scan")[0]
        b = generate_fast(p, cfg, [[7, 8, 9]], num_tokens=8, eos_id=eos,
                          prefill="flash")[0]
        assert a.tolist() == b.tolist()
        # num_tokens=1: the scan contributes nothing — prefill-only
        a = generate_fast(p, cfg, [[5, 6]], num_tokens=1,
                          prefill="flash")[0]
        b = generate_fast(p, cfg, [[5, 6]], num_tokens=1,
                          prefill="scan")[0]
        assert a.tolist() == b.tolist()

    def test_generate_fast_flash_bf16(self, model):
        p, cfg = model
        a = generate_fast(p, cfg, [[7, 8, 9]], num_tokens=6,
                          dtype=jnp.bfloat16, prefill="scan")[0]
        b = generate_fast(p, cfg, [[7, 8, 9]], num_tokens=6,
                          dtype=jnp.bfloat16, prefill="flash")[0]
        assert a.tolist() == b.tolist()


@pytest.mark.smoke
class TestEngineFastPathParity:
    """End-to-end: ragged fast-path engine vs masked reference engine."""

    TRACE = [([7, 8, 9], 6), ([3, 4], 11), ([1, 2, 3, 4, 5], 4),
             ([11], 7), ([20, 21, 22, 23], 9), ([40], 3),
             ([9, 8, 7, 6, 5, 4, 3, 2, 1], 5)]

    def _run(self, p, cfg, fast, slots=2, **kw):
        eng = ServingEngine(p, cfg, slots=slots, queue_limit=16,
                            fast_path=fast, **kw)
        reqs = [Request(prompt=pr, max_new_tokens=n)
                for pr, n in self.TRACE]
        res = eng.run(reqs)
        return eng, {tuple(r.prompt): res[r.request_id].tokens.tolist()
                     for r in reqs}

    def test_greedy_identical_to_masked_reference(self, model):
        """Acceptance: mixed-length greedy trace, fast == reference,
        token for token — at 2 slots (heavy recycling) and 4."""
        p, cfg = model
        _, ref = self._run(p, cfg, fast=False)
        for slots in (2, 4):
            _, fast = self._run(p, cfg, fast=True, slots=slots)
            assert fast == ref
        # and both match offline generate_fast on its reference path
        for pr, n in self.TRACE:
            want = generate_fast(p, cfg, [pr], num_tokens=n,
                                 prefill="scan")[0]
            assert ref[tuple(pr)] == want.tolist()

    def test_eos_and_sampling_on_fast_path(self, model):
        p, cfg = model
        plain = generate_fast(p, cfg, [[7, 8, 9]], num_tokens=8)[0]
        eos = int(plain[3])
        outs = []
        for fast in (False, True):
            eng = ServingEngine(p, cfg, slots=2, fast_path=fast)
            res = eng.run([Request(prompt=[7, 8, 9], max_new_tokens=8,
                                   eos_id=eos),
                           Request(prompt=[3, 4], max_new_tokens=6,
                                   temperature=0.9, top_k=5, seed=11)])
            outs.append(sorted(r.tokens.tolist() for r in res.values()))
            assert {r.finish_reason for r in res.values()} == \
                {"eos", "length"}
        assert outs[0] == outs[1]

    def test_bf16_fast_path(self, model):
        p, cfg = model
        _, ref = self._run(p, cfg, fast=False, dtype=jnp.bfloat16)
        _, fast = self._run(p, cfg, fast=True, dtype=jnp.bfloat16)
        assert fast == ref

    def test_tp_sharded_params_compose(self):
        """tp_shard_params + fast path: flash prefill and the ragged
        kernel run under GSPMD-placed weights (interpret mode) with
        outputs identical to the unsharded fast path."""
        from hetu_tpu.parallel.mesh import make_mesh
        p, cfg = _rand_gpt(name="fpt", H=4, Dh=8)
        reqs = lambda: [Request(prompt=[7, 8, 9], max_new_tokens=6),
                        Request(prompt=[3, 4], max_new_tokens=8)]
        base = ServingEngine(p, cfg, slots=2, fast_path=True).run(reqs())
        mesh = make_mesh({"tp": 4})
        sharded = tp_shard_params(p, mesh, cfg)
        res = ServingEngine(sharded, cfg, slots=2,
                            fast_path=True).run(reqs())
        assert sorted(r.tokens.tolist() for r in base.values()) == \
            sorted(r.tokens.tolist() for r in res.values())


@pytest.mark.smoke
class TestBatchedAdmission:
    def test_burst_costs_one_dispatch(self, model):
        """A burst of k same-bucket arrivals: ONE batched prefill
        dispatch on the fast path, k on the reference — with identical
        outputs."""
        p, cfg = model
        burst = [Request(prompt=[i + 1, i + 2, i + 3], max_new_tokens=4)
                 for i in range(4)]

        def run(fast):
            eng = ServingEngine(p, cfg, slots=4, queue_limit=8,
                                fast_path=fast)
            res = eng.run(burst if fast else [
                Request(prompt=r.prompt, max_new_tokens=4)
                for r in burst])
            return eng, sorted(r.tokens.tolist() for r in res.values())

        ref_eng, ref = run(False)
        fast_eng, fast = run(True)
        assert fast == ref
        assert ref_eng.prefill_dispatches == 4
        assert fast_eng.prefill_dispatches == 1
        assert fast_eng.metrics.prefill_batched == 1

    def test_mixed_buckets_group_per_bucket(self, model):
        """Arrivals spanning two prompt buckets: one dispatch per
        bucket, not per request; non-pow2 group sizes pad safely."""
        p, cfg = model
        reqs = [Request(prompt=[1, 2], max_new_tokens=3),          # b8
                Request(prompt=[3, 4, 5], max_new_tokens=3),       # b8
                Request(prompt=[6, 7, 8], max_new_tokens=3),       # b8
                Request(prompt=list(range(1, 10)), max_new_tokens=3)]
        eng = ServingEngine(p, cfg, slots=4, queue_limit=8,
                            fast_path=True)
        res = eng.run(reqs)
        assert len(res) == 4
        assert eng.prefill_dispatches == 2     # bucket 8 + bucket 16
        ref = ServingEngine(p, cfg, slots=4, queue_limit=8,
                            fast_path=False)
        res_ref = ref.run([Request(prompt=r.prompt, max_new_tokens=3)
                           for r in reqs])
        assert sorted(r.tokens.tolist() for r in res.values()) == \
            sorted(r.tokens.tolist() for r in res_ref.values())

    def test_finish_at_prefill_frees_slot_same_step(self, model):
        """The admission-wave loop preserves the reference semantics:
        max_new_tokens=1 retires at admission and the freed slot admits
        the next queued request within the same step()."""
        p, cfg = model
        eng = ServingEngine(p, cfg, slots=1, fast_path=True)
        res = eng.run([Request(prompt=[7, 8, 9], max_new_tokens=1),
                       Request(prompt=[3, 4], max_new_tokens=1)])
        assert all(r.n_generated == 1 for r in res.values())
        assert eng.steps == 0


@pytest.mark.smoke
class TestSelectionAndMetrics:
    def test_resolve_fast_precedence(self, monkeypatch):
        assert _resolve_fast(True) is True
        assert _resolve_fast(False) is False
        assert _resolve_fast("ragged") is True
        assert _resolve_fast("masked") is False
        monkeypatch.setenv("HETU_SERVE_FAST", "1")
        assert _resolve_fast(None) is True
        assert _resolve_fast(False) is False      # explicit arg wins
        monkeypatch.setenv("HETU_SERVE_FAST", "0")
        assert _resolve_fast(None) is False
        monkeypatch.delenv("HETU_SERVE_FAST")
        # auto: reference off-TPU (this harness is CPU)
        assert _resolve_fast(None) is (jax.default_backend() == "tpu")

    def test_engine_honors_env(self, model, monkeypatch):
        p, cfg = model
        monkeypatch.setenv("HETU_SERVE_FAST", "1")
        assert ServingEngine(p, cfg, slots=2).fast_path is True
        monkeypatch.setenv("HETU_SERVE_FAST", "0")
        assert ServingEngine(p, cfg, slots=2).fast_path is False
        assert ServingEngine(p, cfg, slots=2,
                             fast_path=True).fast_path is True

    def test_per_step_phase_events(self, model, tmp_path):
        """serve_step events carry prefill_ms/decode_ms; serve_prefill
        events carry the dispatch batch size — the A/B's attribution."""
        import json
        p, cfg = model
        log = str(tmp_path / "fast.jsonl")
        eng = ServingEngine(p, cfg, slots=2, log_path=log,
                            fast_path=True)
        eng.run([Request(prompt=[7, 8], max_new_tokens=3),
                 Request(prompt=[9], max_new_tokens=4)])
        with open(log) as f:
            recs = [json.loads(line) for line in f]
        steps = [r for r in recs if r["event"] == "serve_step"]
        pre = [r for r in recs if r["event"] == "serve_prefill"]
        assert steps and pre
        assert all("prefill_ms" in r and "decode_ms" in r for r in steps)
        assert all(r["decode_ms"] >= 0 for r in steps)
        assert sum(r["n"] for r in pre) == 2
        assert all(r["batched"] for r in pre)
        snap = eng.metrics.snapshot()
        assert snap["prefill_dispatches"] == len(pre)
        assert snap["decode_ms_p50"] is not None
        assert snap["prefill_ms_p50"] is not None
