"""Unified telemetry subsystem (hetu_tpu/telemetry): the one event
pipeline, spans/metrics, health gates, and trace export.

The acceptance spine (ISSUE 5): a training step, a serving request, and
a validate failure all land in ONE merged JSONL stream via the sink;
``bin/hetu_trace.py`` exports a loadable Perfetto trace from it; with
``HETU_TELEMETRY=0`` the instrumentation is a no-op; and the health
gate rejects a synthetic wedged probe (>2x off siblings) while passing
a clean one.  Plus the shared EVENT CONTRACT test covering all four
streams — ``{"t", "event"}`` + per-kind required fields as a single
schema instead of four conventions.
"""

import json
import os
import threading
import time

import numpy as np
import pytest

import hetu_tpu as ht
from hetu_tpu import telemetry
from hetu_tpu.telemetry import health
from hetu_tpu.telemetry.trace import (
    main as trace_main, read_events, to_chrome_trace,
)

pytestmark = pytest.mark.smoke


@pytest.fixture(autouse=True)
def _fresh_registry(monkeypatch):
    # instrumentation on for this file regardless of the ambient env
    # (the disabled-path tests set HETU_TELEMETRY=0 themselves, which
    # wins over this autouse default)
    monkeypatch.setenv("HETU_TELEMETRY", "1")
    telemetry.reset()
    yield
    telemetry.reset()


@pytest.fixture()
def merged_log(tmp_path, monkeypatch):
    log = str(tmp_path / "telemetry.jsonl")
    monkeypatch.setenv("HETU_TELEMETRY_LOG", log)
    return log


def _read(path):
    with open(path) as f:
        return [json.loads(ln) for ln in f if ln.strip()]


# --------------------------------------------------------------------- #
# the sink + event contract
# --------------------------------------------------------------------- #

class TestSink:
    def test_emit_shape_and_buffer(self):
        rec = telemetry.emit("worker_exit", _stream="failure", rank=0,
                             rc=1)
        assert isinstance(rec["t"], float) and rec["event"] == "worker_exit"
        assert telemetry.get_sink().recent(kind="worker_exit") == [rec]

    def test_stream_lands_in_legacy_and_merged(self, tmp_path,
                                               monkeypatch, merged_log):
        legacy = str(tmp_path / "failures.jsonl")
        monkeypatch.setenv("HETU_FAILURE_LOG", legacy)
        telemetry.emit("worker_exit", _stream="failure", rank=0, rc=-9)
        assert [r["event"] for r in _read(legacy)] == ["worker_exit"]
        assert [r["event"] for r in _read(merged_log)] == ["worker_exit"]

    def test_explicit_path_overrides_stream_env(self, tmp_path,
                                                monkeypatch):
        monkeypatch.setenv("HETU_SERVE_LOG", str(tmp_path / "env.jsonl"))
        override = str(tmp_path / "explicit.jsonl")
        telemetry.emit("serve_submit", _stream="serve", _path=override,
                       request="r0", queue_depth=0)
        assert not os.path.exists(str(tmp_path / "env.jsonl"))
        assert len(_read(override)) == 1

    def test_unwritable_log_never_raises(self, monkeypatch):
        monkeypatch.setenv("HETU_TELEMETRY_LOG",
                           "/nonexistent-dir/x/y.jsonl")
        telemetry.emit("span", name="x", ms=1.0)   # must not raise
        assert telemetry.snapshot()["dropped_writes"] >= 1

    def test_contract_validates_known_kinds(self):
        good = telemetry.make_record("serve_step", live=2, queue_depth=0,
                                     decode_ms=1.2)
        assert telemetry.validate_record(good) == []
        bad = telemetry.make_record("serve_step", live=2)
        assert any("queue_depth" in p
                   for p in telemetry.validate_record(bad))
        assert telemetry.validate_record({"event": "x"})  # missing t
        # unknown kinds only need the base shape
        assert telemetry.validate_record(
            telemetry.make_record("some_new_kind", foo=1)) == []

    def test_event_contract_all_streams(self, merged_log, model):
        """THE shared schema test: generate real records from all four
        streams and validate every one against the single contract."""
        # failure stream: a launcher-family record
        telemetry.emit("ps_shard_failover", _stream="failure", shard=0,
                       backup=1)
        # serve stream: a real engine request (fixture below)
        params, cfg = model
        from hetu_tpu.serving import Request, ServingEngine
        eng = ServingEngine(params, cfg, slots=2, fast_path=False)
        eng.run([Request(prompt=[1, 2, 3], max_new_tokens=2, seed=0)])
        # validate stream: a real verifier report
        from hetu_tpu.analysis.report import emit_records, make_record
        emit_records([make_record("graph_verified", subgraph="train",
                                  phase="build", nodes=3, verified=3,
                                  findings=[])])
        # telemetry stream: a span
        with telemetry.span("exec.step", subgraph="train"):
            pass
        recs = _read(merged_log)
        kinds = {r["event"] for r in recs}
        assert {"ps_shard_failover", "serve_submit", "serve_finish",
                "graph_verified", "span"} <= kinds
        for rec in recs:
            assert telemetry.validate_record(rec) == [], rec


# --------------------------------------------------------------------- #
# metrics + spans + the disabled no-op contract
# --------------------------------------------------------------------- #

class TestMetrics:
    def test_counter_gauge_histogram_snapshot(self):
        telemetry.inc("a.count", 3)
        telemetry.inc("a.count")
        telemetry.set_gauge("a.depth", 7)
        for v in (1.0, 2.0, 9.0):
            telemetry.observe("a.ms", v)
        s = telemetry.snapshot()
        assert s["counters"]["a.count"] == 4
        assert s["gauges"]["a.depth"] == 7
        h = s["histograms"]["a.ms"]
        assert h["count"] == 3 and h["min"] == 1.0 and h["max"] == 9.0

    def test_thread_safety(self):
        def work():
            for _ in range(1000):
                telemetry.inc("t.count")
        ts = [threading.Thread(target=work) for _ in range(8)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert telemetry.snapshot()["counters"]["t.count"] == 8000

    def test_type_collision_raises(self):
        telemetry.counter("x.y")
        with pytest.raises(TypeError):
            telemetry.gauge("x.y")

    def test_span_records_histogram_and_jsonl(self, merged_log):
        with telemetry.span("exec.phase_a", subgraph="train"):
            time.sleep(0.002)
        h = telemetry.snapshot()["histograms"]["span.exec.phase_a"]
        assert h["count"] == 1 and h["min"] >= 1.0   # >= 1 ms
        [rec] = _read(merged_log)
        assert rec["event"] == "span" and rec["name"] == "exec.phase_a"
        assert rec["subgraph"] == "train" and rec["ms"] >= 1.0
        assert "pid" in rec and "tid" in rec

    def test_disabled_is_noop(self, monkeypatch, merged_log):
        monkeypatch.setenv("HETU_TELEMETRY", "0")
        with telemetry.span("exec.step"):
            pass
        telemetry.inc("c")
        telemetry.observe("h", 1.0)
        telemetry.set_gauge("g", 1)
        s = telemetry.snapshot()
        assert s["counters"] == {} and s["histograms"] == {} \
            and s["gauges"] == {}
        assert not os.path.exists(merged_log)

    def test_disabled_span_overhead_tiny(self, monkeypatch):
        """The HETU_TELEMETRY=0 contract: a disabled span is an env
        read + a shared no-op object — generous bound of 50us each so
        the assertion never flakes while still catching an accidental
        always-on JSONL write (orders of magnitude slower)."""
        monkeypatch.setenv("HETU_TELEMETRY", "0")
        t0 = time.perf_counter()
        for _ in range(1000):
            with telemetry.span("x"):
                pass
        dt = time.perf_counter() - t0
        assert dt < 0.05, f"1000 disabled spans took {dt * 1e3:.1f} ms"


# --------------------------------------------------------------------- #
# health gates (the ISSUE's acceptance pair: reject wedged, pass clean)
# --------------------------------------------------------------------- #

class TestHealthGates:
    def test_rejects_synthetic_wedged_probe(self):
        # the observed Aug-2 window: batch 48 wedged at 64.6 against
        # 216.5/223 neighbors
        v = health.check_sibling_consistency({32: 216.5, 48: 64.6,
                                              64: 223.0})
        assert v["ok"] is False
        assert list(v["wedged"]) == ["48"]
        assert v["wedged"]["48"]["ratio"] > 2.0
        assert set(v["clean"]) == {"32", "64"}

    def test_passes_clean_probe_set(self):
        v = health.check_sibling_consistency({32: 258.5, 48: 252.0,
                                              64: 251.0})
        assert v["ok"] is True and v["wedged"] == {}

    def test_two_probe_low_outlier(self):
        v = health.check_sibling_consistency({32: 100.0, 64: 40.0})
        assert list(v["wedged"]) == ["64"]

    def test_gate_emits_event(self):
        health.check_sibling_consistency({1: 1.0, 2: 1.0})
        recs = telemetry.get_sink().recent(kind="bench_probe_health")
        assert recs and recs[-1]["ok"] is True

    def test_physics_ceiling_rejects_impossible_mfu(self):
        v = health.check_physics_ceiling(mfu=1.2, platform="tpu")
        assert v["ok"] is False and "MFU" in v["violations"][0]

    def test_physics_ceiling_rejects_above_calibrated_peak(self):
        peak = health._calibrated_peak_tflops()
        if peak is None:
            pytest.skip("no CALIBRATION_TPU.json in tree")
        v = health.check_physics_ceiling(tflops_chip=peak * 2,
                                         platform="tpu")
        assert v["ok"] is False and "calibrated" in v["violations"][0]

    def test_physics_ceiling_passes_sane_and_cpu(self):
        assert health.check_physics_ceiling(mfu=0.48, tflops_chip=95.0,
                                            platform="tpu")["ok"]
        assert health.check_physics_ceiling(mfu=None,
                                            platform="cpu")["ok"]

    def test_provenance_stamp(self):
        live = health.stamp_provenance({"value": 1.0}, live=True)
        assert live["provenance"] == "live" and "measured_at" not in live
        banked = health.stamp_provenance({"value": 1.0}, live=False,
                                         measured_at="2026-07-30")
        assert banked["provenance"] == "banked"
        assert banked["measured_at"] == "2026-07-30"


# --------------------------------------------------------------------- #
# bench wiring (satellite #1: headline semantics + probe gate)
# --------------------------------------------------------------------- #

class TestBenchWiring:
    def test_probe_health_drops_wedged_from_selection(self):
        import bench
        numeric = {32: 216.5, 48: 64.6, 64: 223.0}
        v = bench._probe_health(numeric)
        assert v["ok"] is False and 48 not in numeric
        assert max(numeric, key=numeric.get) == 64

    def test_probe_health_keeps_clean(self):
        import bench
        numeric = {32: 258.5, 48: 252.0}
        v = bench._probe_health(numeric)
        assert v["ok"] is True and set(numeric) == {32, 48}

    def test_headline_never_wraps_banked_onchip_in_fallback(self):
        """VERDICT weak #4: a cpu-fallback driver run re-emitting banked
        on-chip rows must say platform=tpu + provenance=banked, with
        the bring-up platform kept separately."""
        import bench
        results = {
            "bert_base": {"value": 221.7, "mfu": 0.407,
                          "platform": "tpu",
                          "measured_at": "2026-08-02 10:00 UTC"},
            "bert4l": {"value": 630.0, "measured_at":
                       "2026-08-02 10:30 UTC"},
        }
        f = bench._provenance_fields(results, ran=set(),
                                     head_name="bert_base",
                                     run_platform="cpu-fallback",
                                     prev_platform="tpu")
        assert f["platform"] == "tpu"
        assert f["run_platform"] == "cpu-fallback"
        assert f["headline_provenance"] == "banked"
        assert f["rows_live"] == []
        assert f["rows_banked"]["bert_base"]["measured_at"] == \
            "2026-08-02 10:00 UTC"
        # rows without a per-row platform stamp inherit the previous
        # capture's platform, not the current run's
        assert f["rows_banked"]["bert4l"]["platform"] == "tpu"

    def test_headline_live_rows(self):
        import bench
        results = {"bert_base": {"value": 9.0, "platform": "cpu",
                                 "measured_at": "now"}}
        f = bench._provenance_fields(results, ran={"bert_base"},
                                     head_name="bert_base",
                                     run_platform="cpu")
        assert f["platform"] == "cpu"
        assert f["headline_provenance"] == "live"
        assert f["rows_live"] == ["bert_base"]
        assert f["rows_banked"] == {}


# --------------------------------------------------------------------- #
# instrumentation integration: one merged stream, end to end
# --------------------------------------------------------------------- #

def _rand_gpt(name="tl", L=1, H=2, Dh=8, V=61, S=32, seed=0):
    from hetu_tpu.models import GPTConfig
    rng = np.random.RandomState(seed)
    hd = H * Dh
    p = {f"{name}_wte_table": rng.randn(V, hd) * 0.05,
         f"{name}_wpe": rng.randn(S, hd) * 0.05,
         f"{name}_ln_f_scale": np.ones(hd),
         f"{name}_ln_f_bias": np.zeros(hd)}
    for i in range(L):
        us = f"{name}_h{i}"
        for w, shp in [("attn_q", (hd, hd)), ("attn_k", (hd, hd)),
                       ("attn_v", (hd, hd)), ("attn_proj", (hd, hd)),
                       ("ffn_wi", (hd, 4 * hd)), ("ffn_wo", (4 * hd, hd))]:
            p[f"{us}_{w}_weight"] = rng.randn(*shp) * 0.05
            p[f"{us}_{w}_bias"] = np.zeros(shp[1])
        for ln in ("ln1", "ln2"):
            p[f"{us}_{ln}_scale"] = np.ones(hd)
            p[f"{us}_{ln}_bias"] = np.zeros(hd)
    cfg = GPTConfig(vocab_size=V, hidden_size=hd, num_hidden_layers=L,
                    num_attention_heads=H, max_position_embeddings=S,
                    batch_size=1, seq_len=S, dropout_rate=0.0)
    return p, cfg


@pytest.fixture(scope="module")
def model():
    return _rand_gpt()


def _tiny_train_step(n_steps=2):
    x = ht.placeholder_op("x")
    w = ht.init.xavier_uniform((16, 16), name=f"tl_w_{time.time_ns()}")
    h = ht.relu_op(ht.matmul_op(x, w))
    loss = ht.reduce_mean_op(ht.reduce_mean_op(h, axes=1), axes=0)
    train = ht.optim.SGDOptimizer(learning_rate=0.1).minimize(loss)
    ex = ht.Executor({"train": [loss, train]})
    for _ in range(n_steps):
        ex.run("train", feed_dict={x: np.ones((4, 16), np.float32)})
    return ex


class TestMergedStream:
    def test_train_serve_validate_one_stream(self, merged_log, model,
                                             monkeypatch):
        """ISSUE acceptance: a training step, a serving request, and a
        validate failure all land in a single merged JSONL stream."""
        monkeypatch.setenv("HETU_VALIDATE", "1")
        _tiny_train_step()
        params, cfg = model
        from hetu_tpu.serving import Request, ServingEngine
        eng = ServingEngine(params, cfg, slots=2, fast_path=False)
        eng.run([Request(prompt=[1, 2], max_new_tokens=2, seed=1)])
        # a validate FAILURE (shape mismatch fails the pre-trace check)
        x = ht.placeholder_op("x")
        w = ht.init.xavier_uniform((8, 8), name="tl_bad_w")
        bad = ht.matmul_op(x, w)
        from hetu_tpu.analysis import GraphVerifyError
        ex = ht.Executor({"bad": [bad]})
        with pytest.raises(GraphVerifyError):
            ex.run("bad", feed_dict={x: np.ones((4, 5), np.float32)})
        kinds = {r["event"] for r in _read(merged_log)}
        assert "span" in kinds                  # training step spans
        assert "serve_finish" in kinds          # serving request
        assert "graph_verify_error" in kinds    # validate failure
        assert "graph_verified" in kinds

    def test_executor_spans_and_counters(self, merged_log):
        _tiny_train_step(n_steps=3)
        s = telemetry.snapshot()
        assert s["counters"]["exec.steps"] == 3
        assert s["counters"]["exec.compile_cache_miss"] == 1
        names = {r.get("name") for r in _read(merged_log)
                 if r["event"] == "span"}
        assert {"exec.phase_a", "exec.compile",
                "exec.dispatch"} <= names
        # the cache-miss step's dispatch is marked compiled=True
        dispatches = [r for r in _read(merged_log)
                      if r.get("name") == "exec.dispatch"]
        assert dispatches[0]["compiled"] is True
        assert all(d["compiled"] is False for d in dispatches[1:])

    def test_ps_rpc_metrics_local(self):
        from hetu_tpu.ps.client import PSClient
        from hetu_tpu.ps.server import PSServer
        PSServer._instance = None
        c = PSClient()
        try:
            c.parameter_init("tl_table", (8, 4), "constant", 0.0)
            c.push("tl_table", np.ones((8, 4), np.float32))
            c.pull("tl_table")
            s = telemetry.snapshot()
            assert s["counters"]["ps.rpc.calls[local]"] >= 3
            assert "ps.rpc_ms.pull" in s["histograms"]
        finally:
            PSServer._instance = None

    def test_ps_rpc_metrics_tcp_bytes(self):
        import socket
        from hetu_tpu.ps.client import PSClient, _TCPTransport
        from hetu_tpu.ps.server import PSServer
        s_ = socket.socket()
        s_.bind(("", 0))
        port = s_.getsockname()[1]
        s_.close()
        srv = PSServer()
        srv.serve_tcp(port, block=False)
        c = None
        try:
            c = PSClient(transport=_TCPTransport("127.0.0.1", port))
            c.parameter_init("tl_tcp", (4, 4), "constant", 0.0)
            c.pull("tl_tcp")
            s = telemetry.snapshot()
            shard = f"127.0.0.1:{port}"
            assert s["counters"][f"ps.rpc.calls[{shard}]"] >= 2
            assert s["counters"]["ps.rpc.bytes_sent"] > 0
            assert s["counters"]["ps.rpc.bytes_recv"] > 0
            assert s["counters"]["ps.server.requests"] >= 2
            assert s["counters"]["ps.server.bytes_in"] > 0
            assert "ps.server.handle_ms.pull" in s["histograms"]
        finally:
            srv.shutdown()

    def test_cache_counters(self):
        from hetu_tpu.cache.cstable import CacheSparseTable
        from hetu_tpu.ps.server import PSServer
        srv = PSServer()
        srv.param_init("tl_emb", (64, 4), init_type="constant", arg1=0.5)
        t = CacheSparseTable(limit=8, vocab_size=64, width=4,
                             key="tl_emb", comm=srv,
                             prefer_native=False)
        t.embedding_lookup(np.arange(8))           # 8 misses
        t.embedding_lookup(np.arange(8))           # 8 hits
        t.embedding_lookup(np.arange(8, 12))       # evictions begin
        t.embedding_update(np.arange(8, 12), np.ones((4, 4)))
        t.flush()
        s = telemetry.snapshot()["counters"]
        assert s["cache.hits"] >= 8
        assert s["cache.misses"] >= 12
        assert s["cache.evictions"] >= 4
        assert s["cache.writeback_rows"] >= 4

    def test_dataloader_ring_metrics(self):
        from hetu_tpu.dataloader import Dataloader
        dl = Dataloader(np.arange(64).reshape(16, 4), 4, "tl")
        dl.start_prefetch(depth=2)
        try:
            for _ in range(4):
                dl.get_arr()
        finally:
            dl.stop_prefetch()
        s = telemetry.snapshot()
        assert s["histograms"]["dataloader.wait_ms"]["count"] == 4
        assert s["gauges"]["dataloader.ring_depth"] is not None

    def test_serving_engine_wave_counter_and_stream(self, merged_log,
                                                    model):
        params, cfg = model
        from hetu_tpu.serving import Request, ServingEngine
        eng = ServingEngine(params, cfg, slots=2, fast_path=False)
        eng.run([Request(prompt=[1, 2], max_new_tokens=2, seed=s)
                 for s in range(3)])
        assert telemetry.snapshot()["counters"]["serve.admission_waves"] \
            >= 2
        kinds = [r["event"] for r in _read(merged_log)]
        assert "serve_step" in kinds and "serve_prefill" in kinds


# --------------------------------------------------------------------- #
# trace merge/export CLI
# --------------------------------------------------------------------- #

class TestTraceExport:
    def _populate(self, merged_log):
        with telemetry.span("exec.dispatch", subgraph="train"):
            time.sleep(0.001)
        telemetry.emit("serve_step", _stream="serve", live=2,
                       queue_depth=0, prefill_ms=0.5, decode_ms=2.0)
        telemetry.emit("worker_exit", _stream="failure", rank=0, rc=1)

    def test_merge_is_time_sorted_across_files(self, tmp_path):
        a = tmp_path / "a.jsonl"
        b = tmp_path / "b.jsonl"
        a.write_text(json.dumps({"t": 2.0, "event": "late"}) + "\n")
        b.write_text(json.dumps({"t": 1.0, "event": "early"}) + "\n"
                     + "not json\n")
        events, bad = read_events([str(a), str(b)])
        assert [e["event"] for e in events] == ["early", "late"]
        assert bad == 1

    def test_chrome_trace_spans_and_instants(self, merged_log):
        self._populate(merged_log)
        events, _ = read_events([merged_log])
        trace, n_spans = to_chrome_trace(events)
        assert n_spans == 2       # the span + serve_step(decode_ms)
        xs = [e for e in trace["traceEvents"] if e.get("ph") == "X"]
        names = {e["name"] for e in xs}
        assert "exec.dispatch" in names and "serve.decode" in names
        for e in xs:
            assert e["dur"] > 0 and isinstance(e["ts"], float)
        # instants for the point events
        assert any(e.get("ph") == "i" and e["name"] == "worker_exit"
                   for e in trace["traceEvents"])

    def test_cli_export_loadable(self, merged_log, tmp_path, capsys):
        self._populate(merged_log)
        out = str(tmp_path / "trace.json")
        rc = trace_main([merged_log, "--export", out])
        assert rc == 0
        summary = json.loads(capsys.readouterr().out.strip())
        assert summary["spans"] >= 2
        trace = json.load(open(out))     # loadable = the acceptance bar
        assert trace["traceEvents"]
        assert trace["displayTimeUnit"] == "ms"

    def test_cli_merge_and_filters(self, merged_log, capsys):
        self._populate(merged_log)
        rc = trace_main([merged_log, "--events", "worker_exit"])
        assert rc == 0
        lines = [json.loads(ln) for ln in
                 capsys.readouterr().out.strip().splitlines()]
        assert len(lines) == 1 and lines[0]["event"] == "worker_exit"

    def test_cli_contract_check(self, merged_log, tmp_path, capsys):
        self._populate(merged_log)
        assert trace_main([merged_log, "--check"]) == 0
        capsys.readouterr()
        bad = tmp_path / "bad.jsonl"
        bad.write_text(json.dumps({"t": 1.0, "event": "serve_step",
                                   "live": 1}) + "\n")
        assert trace_main([str(bad), "--check"]) == 1

    def test_cli_default_paths_from_env(self, merged_log, capsys):
        self._populate(merged_log)
        rc = trace_main([])          # falls back to HETU_TELEMETRY_LOG
        assert rc == 0
        assert capsys.readouterr().out.strip()


# --------------------------------------------------------------------- #
# launcher/report compatibility (the migrated emitters keep their
# contracts: in-memory lists + legacy files)
# --------------------------------------------------------------------- #

class TestMigratedEmitters:
    def test_serving_metrics_keeps_event_list(self, tmp_path):
        from hetu_tpu.serving import ServingMetrics
        log = str(tmp_path / "serve.jsonl")
        m = ServingMetrics(log_path=log)
        m.record_submit("r1", 0)
        assert m.events[0]["event"] == "serve_submit"
        assert _read(log)[0]["event"] == "serve_submit"

    def test_report_emit_records_path_override(self, tmp_path):
        from hetu_tpu.analysis.report import emit_records, make_record
        p = str(tmp_path / "v.jsonl")
        recs = [make_record("graph_verified", subgraph="s", phase="build")]
        emit_records(recs, path=p)
        assert _read(p) == recs

    def test_sharded_event_reaches_failure_stream(self, tmp_path,
                                                  monkeypatch):
        legacy = str(tmp_path / "fail.jsonl")
        monkeypatch.setenv("HETU_FAILURE_LOG", legacy)
        from hetu_tpu.ps import sharded
        c = sharded.ShardedPSClient.__new__(sharded.ShardedPSClient)
        c.failure_events = []
        c._event("ps_shard_failover", shard=1, backup=2, error="x")
        assert c.failure_events[0]["event"] == "ps_shard_failover"
        assert _read(legacy)[0]["shard"] == 1
