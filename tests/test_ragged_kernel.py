"""Mixed-mode ragged dispatch (ISSUE 18): ONE kernel and ONE engine
wave for the whole serving hot loop.

Kernel tier: ``ragged_attention`` / ``ragged_paged_attention`` (one
parameterized Pallas body across contiguous/block-table x f32/int8)
must match the ONE masked-gather oracle (``ragged_masked_reference``)
on decode-only, verify-only, prefill-only, and freely mixed ``q_len``
waves — including arbitrarily permuted pools and int8 scale planes —
and must degenerate exactly to the per-mode kernels the phase-split
engine still runs (those stay behind as parity oracles).

Engine tier: the load-bearing contract is TOKEN IDENTITY — a
``ragged=True`` engine (``$HETU_SERVE_RAGGED``) that packs admissions,
chunk continuations, spec-verify, and decode into one wave per step
must emit exactly the tokens the phase-split scheduler emits, greedy
AND sampled, across contiguous/paged/int8/chunked/prefix-shared/
speculative configurations, while the ``chunk_stall`` lifecycle
component collapses to exactly 0.

Everything runs on CPU via interpret mode; ``smoke``-tier.
"""

import numpy as np
import pytest

import hetu_tpu as ht  # noqa: F401  (platform forcing + compat shims)
import jax.numpy as jnp

from hetu_tpu.kernels.decode_attention import (
    masked_decode_reference, masked_verify_reference,
    paged_block_decode_attention, paged_block_verify_attention,
    paged_decode_attention, paged_verify_attention,
)
from hetu_tpu.kernels.ragged_attention import (
    ragged_attention, ragged_masked_reference, ragged_paged_attention,
    ragged_paged_reference,
)
from hetu_tpu.models import GPTConfig
from hetu_tpu.models.gpt_decode import resolve_serve_ragged
from hetu_tpu.serving import Request, ServingEngine


# ------------------------------------------------------------------- #
# kernel parity
# ------------------------------------------------------------------- #


def _wave(B=4, Q=4, H=2, Dh=8, S=64, seed=0, qlens=(4, 1, 2, 0),
          lens=(17, 33, 5, 0)):
    rng = np.random.RandomState(seed)
    q = rng.randn(B, Q, H, Dh).astype(np.float32)
    k = rng.randn(B, S, H, Dh).astype(np.float32)
    v = rng.randn(B, S, H, Dh).astype(np.float32)
    return (q, k, v, np.asarray(lens, np.int32)[:B],
            np.asarray(qlens, np.int32)[:B])


def _to_pool(k, v, bs=16, seed=1):
    """Scatter [B, S] logical KV into a permuted [N, bs] pool."""
    B, S = k.shape[:2]
    T = S // bs
    rng = np.random.RandomState(seed)
    N = B * T + 3
    perm = rng.permutation(N)[:B * T]
    tables = perm.reshape(B, T).astype(np.int32)
    pk = np.zeros((N, bs) + k.shape[2:], k.dtype)
    pv = np.zeros((N, bs) + v.shape[2:], v.dtype)
    for b in range(B):
        for j in range(T):
            pk[tables[b, j]] = k[b, j * bs:(j + 1) * bs]
            pv[tables[b, j]] = v[b, j * bs:(j + 1) * bs]
    return pk, pv, tables


def _quantize(x, axis=-1):
    """Int8 payload + per-(..., head) f32 scale planes."""
    amax = np.abs(x).max(axis=axis) + 1e-6
    scale = (amax / 127.0).astype(np.float32)
    q = np.clip(np.round(x / scale[..., None]), -127, 127).astype(np.int8)
    return q, scale


@pytest.mark.smoke
class TestRaggedKernel:
    # decode-only, spec-verify-only, full-prompt prefill, and freely
    # mixed waves — all one kernel, selected purely by per-slot data
    @pytest.mark.parametrize("qlens", [
        (1, 1, 1, 1), (4, 4, 4, 4), (4, 1, 2, 0), (2, 0, 4, 1)])
    def test_contiguous_matches_reference(self, qlens):
        q, k, v, lens, ql = _wave(qlens=qlens)
        got = ragged_attention(q, k, v, lens, ql, block_k=16,
                               interpret=True)
        want = ragged_masked_reference(q, k, v, lens, ql)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=2e-5, rtol=2e-5)

    @pytest.mark.parametrize("qlens", [
        (1, 1, 1, 1), (4, 1, 2, 0)])
    def test_permuted_pool_matches_reference(self, qlens):
        q, k, v, lens, ql = _wave(qlens=qlens)
        pk, pv, tables = _to_pool(k, v)
        got = ragged_paged_attention(q, pk, pv, lens, ql, tables,
                                     interpret=True)
        want = ragged_paged_reference(q, pk, pv, lens, ql, tables)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=2e-5, rtol=2e-5)
        # the pool gather is the only paged/contiguous difference
        contig = ragged_masked_reference(q, k, v, lens, ql)
        np.testing.assert_allclose(np.asarray(want), np.asarray(contig),
                                   atol=1e-6, rtol=1e-6)

    def test_int8_twin_contiguous(self):
        q, k, v, lens, ql = _wave()
        k8, ks = _quantize(k)
        v8, vs = _quantize(v)
        got = ragged_attention(q, k8, v8, lens, ql, block_k=16,
                               k_scale=ks, v_scale=vs, interpret=True)
        want = ragged_masked_reference(q, k8, v8, lens, ql,
                                       k_scale=ks, v_scale=vs)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=2e-5, rtol=2e-5)

    def test_int8_twin_paged(self):
        q, k, v, lens, ql = _wave()
        pk, pv, tables = _to_pool(k, v)
        pk8, pks = _quantize(pk)
        pv8, pvs = _quantize(pv)
        got = ragged_paged_attention(q, pk8, pv8, lens, ql, tables,
                                     k_scale=pks, v_scale=pvs,
                                     interpret=True)
        want = ragged_paged_reference(q, pk8, pv8, lens, ql, tables,
                                      k_scale=pks, v_scale=pvs)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=2e-5, rtol=2e-5)

    def test_zero_length_slot_returns_zeros(self):
        q, k, v, lens, ql = _wave(qlens=(4, 1, 2, 0), lens=(17, 33, 5, 0))
        got = np.asarray(ragged_attention(q, k, v, lens, ql, block_k=16,
                                          interpret=True))
        assert np.all(got[3] == 0.0)

    def test_bf16_accumulates_f32(self):
        q, k, v, lens, ql = _wave()
        got = ragged_attention(q.astype(jnp.bfloat16),
                               k.astype(jnp.bfloat16),
                               v.astype(jnp.bfloat16), lens, ql,
                               block_k=16, interpret=True)
        assert got.dtype == jnp.bfloat16
        want = ragged_masked_reference(q, k, v, lens, ql)
        np.testing.assert_allclose(
            np.asarray(got, np.float32), np.asarray(want),
            atol=3e-2, rtol=3e-2)

    # q_len = 1 IS the decode kernel; q_lens = spec widths IS the
    # verify kernel — the phase-split kernels stay as parity oracles
    def test_degenerates_to_decode_kernel(self):
        q, k, v, lens, _ = _wave()
        ones = np.ones_like(lens)
        got = np.asarray(ragged_attention(
            q[:, :1], k, v, lens, ones, block_k=16, interpret=True))
        old = np.asarray(paged_decode_attention(
            q[:, 0], k, v, lens, block_k=16, interpret=True))
        np.testing.assert_allclose(got[:, 0], old, atol=2e-5, rtol=2e-5)
        pk, pv, tables = _to_pool(k, v)
        gotp = np.asarray(ragged_paged_attention(
            q[:, :1], pk, pv, lens, ones, tables, interpret=True))
        oldp = np.asarray(paged_block_decode_attention(
            q[:, 0], pk, pv, lens, tables, interpret=True))
        np.testing.assert_allclose(gotp[:, 0], oldp, atol=2e-5,
                                   rtol=2e-5)

    def test_degenerates_to_verify_kernel(self):
        q, k, v, lens, ql = _wave()
        got = np.asarray(ragged_attention(q, k, v, lens, ql, block_k=16,
                                          interpret=True))
        old = np.asarray(paged_verify_attention(q, k, v, lens, ql,
                                                block_k=16,
                                                interpret=True))
        np.testing.assert_allclose(got, old, atol=2e-5, rtol=2e-5)
        pk, pv, tables = _to_pool(k, v)
        gotp = np.asarray(ragged_paged_attention(
            q, pk, pv, lens, ql, tables, interpret=True))
        oldp = np.asarray(paged_block_verify_attention(
            q, pk, pv, lens, ql, tables, interpret=True))
        np.testing.assert_allclose(gotp, oldp, atol=2e-5, rtol=2e-5)

    # the four old per-mode references are now delegates of the ONE
    # parameterized oracle — pin the degenerate-mode equivalences
    def test_unified_reference_subsumes_old(self):
        q, k, v, lens, ql = _wave()
        np.testing.assert_allclose(
            np.asarray(masked_verify_reference(q, k, v, lens, ql)),
            np.asarray(ragged_masked_reference(q, k, v, lens, ql)),
            atol=0, rtol=0)
        np.testing.assert_allclose(
            np.asarray(masked_decode_reference(q[:, 0], k, v, lens)),
            np.asarray(ragged_masked_reference(
                q[:, :1], k, v, lens,
                np.ones_like(lens)))[:, 0],
            atol=0, rtol=0)


# ------------------------------------------------------------------- #
# engine: one ragged wave per step, token-identical to phase-split
# ------------------------------------------------------------------- #


def _rand_gpt(name="rg", L=2, H=2, Dh=8, V=61, S=64, seed=0):
    rng = np.random.RandomState(seed)
    hd = H * Dh
    p = {f"{name}_wte_table": rng.randn(V, hd) * 0.05,
         f"{name}_wpe": rng.randn(S, hd) * 0.05,
         f"{name}_ln_f_scale": np.ones(hd),
         f"{name}_ln_f_bias": np.zeros(hd)}
    for i in range(L):
        us = f"{name}_h{i}"
        for w, shp in [("attn_q", (hd, hd)), ("attn_k", (hd, hd)),
                       ("attn_v", (hd, hd)), ("attn_proj", (hd, hd)),
                       ("ffn_wi", (hd, 4 * hd)), ("ffn_wo", (4 * hd, hd))]:
            p[f"{us}_{w}_weight"] = rng.randn(*shp) * 0.05
            p[f"{us}_{w}_bias"] = np.zeros(shp[1])
        for ln in ("ln1", "ln2"):
            p[f"{us}_{ln}_scale"] = np.ones(hd)
            p[f"{us}_{ln}_bias"] = np.zeros(hd)
    cfg = GPTConfig(vocab_size=V, hidden_size=hd, num_hidden_layers=L,
                    num_attention_heads=H, max_position_embeddings=S,
                    batch_size=1, seq_len=S, dropout_rate=0.0)
    return p, cfg


# greedy and sampled, short and long prompts, a prompt longer than the
# chunk size, and more requests than slots (queue + requeue pressure)
TRACE = [([7, 8, 9], 6, 0.0, 0), ([3, 4], 8, 0.0, 0),
         ([1, 2, 3, 4, 5], 4, 0.0, 0), ([11], 7, 0.0, 0),
         ([7, 8, 9, 10, 11, 12, 13, 14, 15, 16, 17], 5, 0.0, 0),
         ([2, 3], 6, 0.9, 5), ([9, 9, 9], 5, 0.7, 3)]


@pytest.fixture(scope="module")
def model():
    return _rand_gpt()


def _run(params, cfg, **kw):
    reqs = [Request(prompt=pr, max_new_tokens=n, temperature=t,
                    top_k=k, seed=i)
            for i, (pr, n, t, k) in enumerate(TRACE)]
    eng = ServingEngine(params, cfg, slots=4, **kw)
    res = eng.run(reqs)
    return sorted(r.tokens.tolist() for r in res.values()), eng


@pytest.mark.smoke
class TestMixedModeEngine:
    @pytest.mark.parametrize("cfg_kw", [
        dict(paged=False),
        dict(paged=False, kv_quant="int8"),
        dict(paged=True, kv_block=8),
        dict(paged=True, kv_block=8, prefill_chunk=4, kv_quant="int8"),
        dict(paged=True, kv_block=8, prefix_share=True, prefill_chunk=4),
    ], ids=["contig", "contig-int8", "paged", "paged-chunk-int8",
            "paged-prefix-chunk"])
    def test_token_identity_vs_phase_split(self, model, cfg_kw):
        p, cfg = model
        base, _ = _run(p, cfg, ragged=False, **cfg_kw)
        mix, eng = _run(p, cfg, ragged=True, **cfg_kw)
        assert eng.ragged
        assert base == mix

    def test_spec_decode_composes(self, model):
        p, cfg = model
        kw = dict(paged=True, kv_block=8, kv_quant="int8",
                  prefill_chunk=4)
        plain, _ = _run(p, cfg, ragged=False, **kw)
        mix, eng = _run(p, cfg, ragged=True, spec=2, **kw)
        assert eng.spec_k == 2 and eng.spec_waves > 0
        assert plain == mix

    def test_chunk_stall_folds_to_zero(self, model):
        p, cfg = model
        _, eng = _run(p, cfg, ragged=True, paged=True, kv_block=8,
                      prefill_chunk=4)
        cs = eng.metrics.components["chunk_stall_ms"]
        assert cs and all(v == 0.0 for v in cs)
        # kept in the schema for back-compat dashboards
        snap = eng.metrics.snapshot()
        assert snap["components"]["chunk_stall_ms"]["p99_ms"] == 0.0
        rep = eng.metrics.explain_tail()
        assert rep["mixed_mode"] and "mixed-mode" in rep["summary"]

    def test_serve_step_carries_mode_split(self, model):
        p, cfg = model
        # prefix_share off: every prompt token is then COMPUTED in some
        # wave, so the q_prefill ledger must sum to the trace exactly
        # (shared prefixes would legitimately skip their cached tokens)
        _, eng = _run(p, cfg, ragged=True, paged=True, kv_block=8,
                      prefix_share=False)
        steps = [e for e in eng.metrics.events
                 if e["event"] == "serve_step"]
        assert steps
        assert all({"q_prefill", "q_verify", "q_decode"} <= set(e)
                   for e in steps)
        assert sum(e["q_prefill"] for e in steps) == \
            sum(len(pr) for pr, *_ in TRACE)
        assert sum(e["q_decode"] for e in steps) > 0

    def test_env_resolution(self, monkeypatch, model):
        for val, want in [("1", True), ("mixed", True), ("ragged", True),
                          ("0", False), ("phase", False), ("off", False)]:
            monkeypatch.setenv("HETU_SERVE_RAGGED", val)
            assert resolve_serve_ragged() is want, val
        monkeypatch.setenv("HETU_SERVE_RAGGED", "auto")
        assert resolve_serve_ragged() is False   # CPU backend
        assert resolve_serve_ragged(True) is True
        monkeypatch.setenv("HETU_SERVE_RAGGED", "1")
        p, cfg = model
        eng = ServingEngine(p, cfg, slots=4, paged=True, kv_block=8)
        assert eng.ragged and eng.metrics.mixed_mode
