"""PartialReduce (SIGMOD'21 straggler tolerance) + FSDP strategy tests.

Reference: tests/test_ps_preduce.py (partner matching) and preduce.py
subgroup allreduce; FSDP is the SURVEY §2.5 first-class addition."""

import threading

import numpy as np
import pytest

import hetu_tpu as ht
from hetu_tpu.parallel.preduce import PartialReduce
from hetu_tpu.ps.client import PSClient
from hetu_tpu.ps.server import PSServer


@pytest.fixture()
def fresh_ps():
    PSServer._instance = None
    PSClient._instance = None
    yield PSServer.get()
    PSServer._instance = None
    PSClient._instance = None


class TestPartialReduce:
    def test_two_ready_workers_form_group_and_average(self, fresh_ps):
        results = {}

        def worker(rank):
            c = PSClient(rank=rank, nrank=2)
            pr = PartialReduce(max_worker=2, wait_time=5.0, client=c)
            partner = pr.get_partner()
            out = pr.preduce(np.full(4, float(rank + 1), np.float32),
                             partner)
            results[rank] = (partner, out)

        ts = [threading.Thread(target=worker, args=(r,)) for r in (0, 1)]
        [t.start() for t in ts]
        [t.join(timeout=30) for t in ts]
        assert set(results) == {0, 1}
        p0, out0 = results[0]
        p1, out1 = results[1]
        assert p0 == p1 == (0, 1)
        # mean of [1,1,1,1] and [2,2,2,2]
        np.testing.assert_allclose(out0, 1.5)
        np.testing.assert_allclose(out1, 1.5)

    def test_mixed_group_histories_share_scratch_keys(self, fresh_ps):
        """Regression: after a (0,1)-only round, a later (0,1,2) round
        must still converge — scratch keys come from the server match
        seq, not a local counter that diverges across members."""
        prs = {}
        for r in (0, 1, 2):
            c = PSClient(rank=r, nrank=3)
            prs[r] = PartialReduce(max_worker=2, wait_time=5.0, client=c)

        out01 = {}

        def round1(rank):
            out01[rank] = prs[rank].preduce(
                np.full(2, 1.0, np.float32))

        ts = [threading.Thread(target=round1, args=(r,)) for r in (0, 1)]
        [t.start() for t in ts]
        [t.join(timeout=30) for t in ts]
        np.testing.assert_allclose(out01[0], 1.0)

        out012 = {}
        for pr in prs.values():
            pr.max_worker = 3

        def round2(rank):
            out012[rank] = prs[rank].preduce(
                np.full(2, float(rank), np.float32))

        ts = [threading.Thread(target=round2, args=(r,))
              for r in (0, 1, 2)]
        [t.start() for t in ts]
        [t.join(timeout=30) for t in ts]
        for r in (0, 1, 2):
            np.testing.assert_allclose(out012[r], 1.0)  # mean(0,1,2)

    def test_single_member_group_is_identity(self, fresh_ps):
        c = PSClient(rank=0, nrank=1)
        pr = PartialReduce(max_worker=1, wait_time=0.1, client=c)
        x = np.arange(4, dtype=np.float32)
        np.testing.assert_allclose(pr.preduce(x, (0,)), x)


class TestFSDP:
    def test_large_params_sharded_small_replicated(self):
        x = ht.placeholder_op("x")
        big = ht.init.xavier_uniform((64, 128), name="big_w")
        h = ht.matmul_op(x, big)
        h = h + ht.broadcastto_op(
            ht.init.zeros((128,), name="b128"), h)
        w2 = ht.init.xavier_uniform((128, 8), name="w2")
        h2 = ht.matmul_op(h, w2)
        tiny = ht.init.zeros((8,), name="tiny_b")
        h2 = h2 + ht.broadcastto_op(tiny, h2)
        loss = ht.reduce_mean_op(ht.reduce_sum_op(ht.mul_op(h2, h2), [1]),
                                 [0])
        train = ht.optim.AdamOptimizer(learning_rate=0.01).minimize(loss)
        ex = ht.Executor({"train": [loss, train]},
                         dist_strategy=ht.dist.FSDP(dp=8, min_size=100))
        out = ex.run("train", feed_dict={
            x: np.random.RandomState(0).randn(16, 64).astype(np.float32)})
        assert np.isfinite(float(np.asarray(out[0])))
        from jax.sharding import PartitionSpec as P
        assert ex.variables["big_w"].sharding_spec == P(None, "dp")
        assert ex.variables["tiny_b"].sharding_spec is None

    def test_fsdp_training_matches_replicated(self):
        """Tier-2 equivalence: FSDP trajectories == unsharded."""
        def build(tag):
            x = ht.placeholder_op(f"x_{tag}")
            w = ht.Variable(f"w_{tag}", value=np.linspace(
                -1, 1, 64 * 16).reshape(64, 16).astype(np.float32))
            y_ = ht.placeholder_op(f"y_{tag}")
            logits = ht.matmul_op(x, w)
            loss = ht.reduce_mean_op(
                ht.softmaxcrossentropy_op(logits, y_), axes=0)
            train = ht.optim.SGDOptimizer(learning_rate=0.1).minimize(loss)
            return x, y_, loss, train

        rng = np.random.RandomState(0)
        X = rng.randn(32, 64).astype(np.float32)
        Y = np.eye(16)[rng.randint(0, 16, 32)].astype(np.float32)

        x1, y1, l1, t1 = build("a")
        ex1 = ht.Executor({"train": [l1, t1]})
        x2, y2, l2, t2 = build("b")
        ex2 = ht.Executor({"train": [l2, t2]},
                          dist_strategy=ht.dist.FSDP(dp=8, min_size=1))
        tr1 = [float(ex1.run("train", feed_dict={x1: X, y1: Y})[0])
               for _ in range(8)]
        tr2 = [float(ex2.run("train", feed_dict={x2: X, y2: Y})[0])
               for _ in range(8)]
        np.testing.assert_allclose(tr1, tr2, rtol=2e-5)
