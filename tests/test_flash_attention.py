"""Flash attention kernel tests (interpret mode on CPU) vs exact oracle."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from hetu_tpu.kernels.flash_attention import flash_attention, mha_reference

B, S, H, D = 2, 64, 2, 16


def _qkv(seed):
    rng = np.random.RandomState(seed)
    return tuple(jnp.asarray(rng.randn(B, S, H, D), jnp.float32)
                 for _ in range(3))


@pytest.mark.parametrize("causal", [False, True])
def test_flash_matches_exact(causal):
    q, k, v = _qkv(0)
    got = flash_attention(q, k, v, causal=causal, block_q=16, block_k=16)
    want = mha_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_kv_lens_matches_exact(causal):
    """Padding mask (BERT-style): keys/values past kv_lens[b] are dead;
    forward AND all three grads must match the masked oracle."""
    q, k, v = _qkv(7)
    lens = jnp.asarray([13, 0], jnp.int32)   # partial + fully padded
    got = flash_attention(q, k, v, causal=causal, kv_lens=lens,
                          block_q=16, block_k=16)
    want = mha_reference(q, k, v, causal=causal, kv_lens=lens)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-5)

    def loss_f(q, k, v):
        return (flash_attention(q, k, v, causal=causal, kv_lens=lens,
                                block_q=16, block_k=16) ** 2).sum()

    def loss_r(q, k, v):
        return (mha_reference(q, k, v, causal=causal,
                              kv_lens=lens) ** 2).sum()

    gf = jax.grad(loss_f, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_r, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=3e-4, atol=3e-4)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_layer_kv_lens_matches_unfused(causal):
    """MultiHeadAttention(kv_lens=...): the flash path and the unfused
    lens->additive-mask fallback must train identically — including the
    causal triangle, which the unfused chain must apply explicitly."""
    import hetu_tpu as ht

    hidden, nh = 32, 2
    rng = np.random.RandomState(0)
    X = rng.randn(B * S, hidden).astype(np.float32)
    # one partial and one fully-padded sequence: the empty row must emit
    # zero context (and zero grads) on BOTH paths
    L = np.array([13, 0], np.int32)

    def run(use_flash):
        x = ht.placeholder_op("x")
        lens = ht.placeholder_op("l")
        attn = ht.layers.MultiHeadAttention(
            hidden, nh, S, B, use_flash=use_flash, causal=causal,
            block_q=16, block_k=16, name="mkv")
        out = attn(x, kv_lens=lens)
        loss = ht.reduce_mean_op(ht.mul_op(out, out), axes=[0, 1])
        train = ht.optim.SGDOptimizer(learning_rate=0.05).minimize(loss)
        ex = ht.Executor({"train": [loss, train]}, seed=3)
        return [float(ex.run("train", feed_dict={x: X, lens: L})[0])
                for _ in range(4)]

    np.testing.assert_allclose(run(True), run(False),
                               rtol=1e-4, atol=1e-5)


def test_flash_single_block():
    q, k, v = _qkv(1)
    got = flash_attention(q, k, v, block_q=128, block_k=128)
    want = mha_reference(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_grads_match_exact(causal):
    q, k, v = _qkv(2)

    def loss_f(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=causal,
                                       block_q=16, block_k=16) ** 2)

    def loss_r(q, k, v):
        return jnp.sum(mha_reference(q, k, v, causal=causal) ** 2)

    gf = jax.grad(loss_f, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_r, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-3, atol=2e-4)


def test_flash_in_ulysses():
    """Flash kernel as the local attention inside Ulysses CP."""
    from hetu_tpu.parallel.mesh import make_mesh
    from hetu_tpu.parallel.context_parallel import ulysses_attention
    mesh = make_mesh({"cp": 2})
    q, k, v = _qkv(3)

    def attn(q, k, v, causal):
        return flash_attention(q, k, v, causal=causal,
                               block_q=16, block_k=16)

    got = ulysses_attention(q, k, v, mesh=mesh, causal=True, attn_fn=attn)
    want = mha_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-5)


def test_flash_layer_matches_dense_layer():
    """MultiHeadAttention(use_flash=True) == the op-compositional path."""
    import hetu_tpu as ht
    B_, S_, H_, NH = 2, 32, 64, 4
    x = ht.placeholder_op('x')
    attn_a = ht.layers.MultiHeadAttention(H_, NH, S_, B_, name="fa",
                                          use_flash=False)
    attn_b = ht.layers.MultiHeadAttention(H_, NH, S_, B_, name="fb",
                                          use_flash=True)
    ya, yb = attn_a(x), attn_b(x)
    ex = ht.Executor({"t": [ya, yb]})
    vals = ex.return_tensor_values()
    ex.load_dict({k.replace("fa_", "fb_"): v for k, v in vals.items()
                  if k.startswith("fa_")})
    X = np.random.RandomState(5).randn(B_ * S_, H_).astype(np.float32)
    ra, rb = ex.run("t", feed_dict={x: X}, convert_to_numpy_ret_vals=True)
    np.testing.assert_allclose(ra, rb, rtol=2e-4, atol=2e-5)


def test_flash_non_divisible_seq():
    """Odd sequence lengths shrink blocks instead of asserting; numerics
    still match exact attention (the review's S%block failure case)."""
    rng = np.random.RandomState(11)
    q, k, v = (jnp.asarray(rng.randn(1, 17, 2, 8), jnp.float32)
               for _ in range(3))
    got = flash_attention(q, k, v, causal=True, block_q=8, block_k=8)
    want = mha_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-5)
    # blockwise oracle (the backward path) on ragged tails
    from hetu_tpu.parallel.context_parallel import blockwise_attention
    got2 = blockwise_attention(q, k, v, block_size=8, causal=True)
    np.testing.assert_allclose(np.asarray(got2), np.asarray(want),
                               rtol=2e-4, atol=2e-5)


def test_flash_grads_rectangular():
    """Cross-attention shape (Sq != Sk) through the fused backward."""
    rng = np.random.RandomState(13)
    q = jnp.asarray(rng.randn(2, 32, 2, 16), jnp.float32)
    k = jnp.asarray(rng.randn(2, 64, 2, 16), jnp.float32)
    v = jnp.asarray(rng.randn(2, 64, 2, 16), jnp.float32)

    def loss_f(q, k, v):
        return jnp.sum(flash_attention(q, k, v, block_q=16, block_k=16) ** 2)

    def loss_r(q, k, v):
        return jnp.sum(mha_reference(q, k, v) ** 2)

    gf = jax.grad(loss_f, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_r, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-3, atol=2e-4)


def test_flash_bwd_is_fused_pallas():
    """The backward is the fused Pallas path: the grad jaxpr must contain
    the forward kernel AND the two backward kernels (dkv + dq), i.e. at
    least 3 pallas_calls — the old oracle-recompute backward had only the
    forward's single pallas_call."""

    q, k, v = _qkv(4)
    jaxpr = jax.make_jaxpr(
        jax.grad(lambda q, k, v: jnp.sum(
            flash_attention(q, k, v, causal=True, block_q=16,
                            block_k=16))))(q, k, v)
    n = str(jaxpr).count("pallas_call")
    assert n >= 3, f"expected fwd + dkv + dq pallas kernels, found {n}"


def test_flash_grads_rectangular_causal():
    """Causal cross-attention with Sk > Sq: every q row of the later kv
    columns is dead, which exercises the dkv kernel's upper-clamped
    dead-row index map (out-of-range block DMA regression)."""
    rng = np.random.RandomState(17)
    q = jnp.asarray(rng.randn(2, 32, 2, 16), jnp.float32)
    k = jnp.asarray(rng.randn(2, 64, 2, 16), jnp.float32)
    v = jnp.asarray(rng.randn(2, 64, 2, 16), jnp.float32)

    def loss_f(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True,
                                       block_q=16, block_k=16) ** 2)

    def loss_r(q, k, v):
        return jnp.sum(mha_reference(q, k, v, causal=True) ** 2)

    gf = jax.grad(loss_f, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_r, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-3, atol=2e-4)


class TestFlashCarry:
    """flash_attention_with_carry: the fused ring-merge prologue
    (VERDICT r3 item 2).  Chaining carry calls over split key sets must
    EXACTLY equal one attention over the concatenated keys, fwd and
    grad — including the carry path's own cotangents."""

    def _qkv(self, B=2, S=32, Sk=64, H=2, D=8, seed=3):
        rng = np.random.RandomState(seed)
        return (jnp.asarray(rng.randn(B, S, H, D).astype(np.float32)),
                jnp.asarray(rng.randn(B, Sk, H, D).astype(np.float32)),
                jnp.asarray(rng.randn(B, Sk, H, D).astype(np.float32)))

    def test_chain_equals_full(self):
        from hetu_tpu.kernels.flash_attention import (
            flash_attention_with_carry, mha_reference)
        q, k, v = self._qkv()
        B, S, H, D = q.shape
        o0 = jnp.zeros((B, S, H, D), jnp.float32)
        lse0 = jnp.full((B, H, S), -1e30, jnp.float32)
        o1, lse1 = flash_attention_with_carry(q, k[:, :S], v[:, :S],
                                              o0, lse0,
                                              block_q=16, block_k=16)
        o2, _ = flash_attention_with_carry(q, k[:, S:], v[:, S:],
                                           o1, lse1,
                                           block_q=16, block_k=16)
        np.testing.assert_allclose(np.asarray(o2),
                                   np.asarray(mha_reference(q, k, v)),
                                   atol=1e-5)

    def test_chain_grads_match_reference(self):
        from hetu_tpu.kernels.flash_attention import (
            flash_attention_with_carry, mha_reference)
        q, k, v = self._qkv()
        B, S, H, D = q.shape
        o0 = jnp.zeros((B, S, H, D), jnp.float32)
        lse0 = jnp.full((B, H, S), -1e30, jnp.float32)

        def loss_chain(q, k, v):
            o1, l1 = flash_attention_with_carry(
                q, k[:, :S], v[:, :S], o0, lse0, block_q=16, block_k=16)
            o2, _ = flash_attention_with_carry(
                q, k[:, S:], v[:, S:], o1, l1, block_q=16, block_k=16)
            return jnp.sum(jnp.sin(o2))

        def loss_ref(q, k, v):
            return jnp.sum(jnp.sin(mha_reference(q, k, v)))

        ga = jax.grad(loss_chain, argnums=(0, 1, 2))(q, k, v)
        gb = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(ga, gb):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=2e-4)

    def test_empty_carry_equals_plain_flash(self):
        from hetu_tpu.kernels.flash_attention import (
            flash_attention_with_carry, flash_attention_with_lse)
        q, k, v = self._qkv(Sk=32)
        B, S, H, D = q.shape
        o0 = jnp.zeros((B, S, H, D), jnp.float32)
        lse0 = jnp.full((B, H, S), -1e30, jnp.float32)
        oc, lc = flash_attention_with_carry(q, k, v, o0, lse0,
                                            causal=True,
                                            block_q=16, block_k=16)
        op, lp = flash_attention_with_lse(q, k, v, causal=True,
                                          block_q=16, block_k=16)
        np.testing.assert_allclose(np.asarray(oc), np.asarray(op),
                                   atol=1e-5)
        np.testing.assert_allclose(np.asarray(lc), np.asarray(lp),
                                   atol=1e-5)

    def test_carry_cotangents_flow(self):
        """d(loss)/d(o_carry, lse_carry) must be nonzero and correct:
        compare against autodiff through the explicit streaming merge."""
        from hetu_tpu.kernels.flash_attention import (
            flash_attention_with_carry, flash_attention_with_lse)
        q, k, v = self._qkv(Sk=32)
        B, S, H, D = q.shape
        rng = np.random.RandomState(9)
        o_c = jnp.asarray(rng.randn(B, S, H, D).astype(np.float32))
        lse_c = jnp.asarray(rng.randn(B, H, S).astype(np.float32))

        def loss_kernel(o_c, lse_c):
            o, _ = flash_attention_with_carry(q, k, v, o_c, lse_c,
                                              block_q=16, block_k=16)
            return jnp.sum(jnp.sin(o))

        def loss_explicit(o_c, lse_c):
            o_i, lse_i = flash_attention_with_lse(q, k, v,
                                                  block_q=16, block_k=16)
            m = jnp.maximum(lse_c, lse_i)
            a_old = jnp.exp(lse_c - m)
            a_new = jnp.exp(lse_i - m)
            denom = a_old + a_new
            w_old = (a_old / denom).transpose(0, 2, 1)[..., None]
            w_new = (a_new / denom).transpose(0, 2, 1)[..., None]
            o = o_c * w_old + o_i.astype(jnp.float32) * w_new
            return jnp.sum(jnp.sin(o))

        ga = jax.grad(loss_kernel, argnums=(0, 1))(o_c, lse_c)
        gb = jax.grad(loss_explicit, argnums=(0, 1))(o_c, lse_c)
        assert float(jnp.abs(ga[0]).max()) > 0
        for a, b in zip(ga, gb):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=2e-4)
