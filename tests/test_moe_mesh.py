"""Expert parallelism actually running over an expert mesh.

Reference behavior being matched: MoE dispatch runs all-to-all across
devices (python/hetu/layers/moe_layer.py:45-93, gpu_ops/AllToAll.py:8-50);
hierarchical A2A composes intra- then inter-node exchanges
(src/communication/mpi_nccl_communication.cu:152-243).

TPU-native: expert weights stacked [E, D, F] and sharded over 'ep'
(StackedExperts); alltoall_op pins expert-major sharding so GSPMD emits
the exchange inside the one jitted step.  Tests assert (a) numerical
equivalence with the single-device run, (b) the compiled HLO actually
partitions the expert compute and contains a cross-device exchange, and
(c) the shard_map execution path runs real lax.all_to_all, flat and
hierarchical."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

import hetu_tpu as ht
from hetu_tpu.parallel.mesh import make_mesh


E, D, F, B = 4, 8, 16, 32


def build_moe(num_tokens):
    x = ht.placeholder_op("x")
    y = ht.placeholder_op("y")
    gate = ht.layers.TopKGate(D, num_tokens, E, k=1, capacity_factor=1.0)
    experts = ht.layers.StackedExperts(E, D, F, activation="relu")
    moe = ht.layers.MoELayer(gate=gate, experts=experts, num_tokens=num_tokens,
                             embed_dim=D)
    out, l_aux = moe(x)
    head = ht.init.xavier_uniform((D, 2), name="moe_head")
    logits = ht.matmul_op(out, head)
    loss = ht.reduce_mean_op(
        ht.softmaxcrossentropy_op(logits, y), axes=0) \
        + ht.mul_byconst_op(l_aux, 0.01)
    train = ht.optim.SGDOptimizer(learning_rate=0.1).minimize(loss)
    return x, y, loss, train


def batches(n=6, seed=0):
    rng = np.random.RandomState(seed)
    out = []
    for _ in range(n):
        xb = rng.randn(B, D).astype(np.float32)
        yb = np.eye(2, dtype=np.float32)[(xb[:, 0] > 0).astype(int)]
        out.append((xb, yb))
    return out


class TestExpertParallelExecutor:
    def test_ep_trajectory_matches_single_device(self):
        x, y, loss, train = build_moe(B)
        ex = ht.Executor({"train": [loss, train]})
        w0 = ex.return_tensor_values()
        bs = batches()
        base = [float(np.asarray(ex.run("train", feed_dict={x: a, y: b})[0]))
                for a, b in bs]

        x, y, loss, train = build_moe(B)
        ex2 = ht.Executor({"train": [loss, train]},
                          dist_strategy=ht.dist.ExpertParallel(ep=4, dp=1))
        ex2.load_dict(w0)
        tr = [float(np.asarray(ex2.run("train", feed_dict={x: a, y: b})[0]))
              for a, b in bs]
        np.testing.assert_allclose(tr, base, atol=1e-5)

    def test_ep_times_dp_trajectory(self):
        x, y, loss, train = build_moe(B)
        ex = ht.Executor({"train": [loss, train]})
        w0 = ex.return_tensor_values()
        bs = batches()
        base = [float(np.asarray(ex.run("train", feed_dict={x: a, y: b})[0]))
                for a, b in bs]

        x, y, loss, train = build_moe(B)
        ex2 = ht.Executor({"train": [loss, train]},
                          dist_strategy=ht.dist.ExpertParallel(ep=2, dp=4))
        ex2.load_dict(w0)
        tr = [float(np.asarray(ex2.run("train", feed_dict={x: a, y: b})[0]))
              for a, b in bs]
        np.testing.assert_allclose(tr, base, atol=1e-5)

    def test_expert_weights_actually_sharded(self):
        x, y, loss, train = build_moe(B)
        ex = ht.Executor({"train": [loss, train]},
                         dist_strategy=ht.dist.ExpertParallel(ep=4, dp=1))
        w1 = None
        for name, v in ex.var_values.items():
            if "expert_stack_w1" in name:
                w1 = v
        assert w1 is not None
        # leading expert dim split 4 ways: each shard holds E/4 experts
        shard_shapes = {s.data.shape for s in w1.addressable_shards}
        assert shard_shapes == {(E // 4, D, F)}

    def test_compiled_hlo_partitions_expert_compute(self):
        """The proof the EP path is real: compiled HLO of the executor step
        must (a) run expert matmuls at per-shard size E/ep and (b) contain
        a cross-partition exchange feeding them (all-to-all, or
        collective-permute when XLA lowers the reshard that way)."""
        x, y, loss, train = build_moe(B)
        ex = ht.Executor({"train": [loss, train]},
                         dist_strategy=ht.dist.ExpertParallel(ep=4, dp=1))
        bs = batches(1)
        a, b = bs[0]
        ex.run("train", feed_dict={x: a, y: b})   # compile
        sub = ex.subexecutor["train"]
        fn = next(iter(sub._compiled.values()))
        feeds = {"x": a, "y": b}
        txt = fn.lower(ex.var_values, ex.opt_states, ex.step, ex.rng,
                       {k: np.asarray(v) for k, v in feeds.items()}
                       ).compile().as_text()
        assert "all-to-all" in txt or "collective-permute" in txt or \
            "all-gather" in txt, "no cross-device exchange in HLO"
        # expert batched matmul appears at per-shard expert count (dim E/4)
        per_shard = f"f32[{E // 4},{B // E},{F}]"
        assert per_shard in txt.replace(" ", ""), (
            f"expected per-shard expert activation {per_shard} in HLO")


class TestShardMapA2A:
    def test_flat_alltoall_executes(self):
        mesh = make_mesh({"ep": 4})
        from hetu_tpu.graph.ops_moe import alltoall_op
        from hetu_tpu.graph.node import TraceContext
        from jax import shard_map

        node = ht.placeholder_op("t")
        a2a = alltoall_op(node, axis="ep")
        xs = jnp.arange(16 * 3, dtype=jnp.float32).reshape(16, 3)

        def body(x):
            tc = TraceContext(axis_env=("ep",))
            return a2a.compute([x], tc)

        out = jax.jit(shard_map(body, mesh=mesh, in_specs=P("ep"),
                                out_specs=P("ep")))(xs)
        # all_to_all over blocks: involution — applying twice restores
        out2 = jax.jit(shard_map(body, mesh=mesh, in_specs=P("ep"),
                                 out_specs=P("ep")))(out)
        np.testing.assert_array_equal(np.asarray(out2), np.asarray(xs))
        # and it is NOT the identity (devices exchanged rows)
        assert not np.array_equal(np.asarray(out), np.asarray(xs))

    def test_hierarchical_alltoall_over_ici_dcn(self):
        """('dcn','ici') mesh: halltoall composes per-axis exchanges; the
        composition must be an involution and must move data across both
        axes (reference mpi_nccl_communication.cu:152-243 semantics)."""
        mesh = make_mesh({"dcn": 2, "ici": 2})
        assert mesh.axis_names == ("dcn", "ici")
        from hetu_tpu.graph.ops_moe import halltoall_op
        from hetu_tpu.graph.node import TraceContext
        from jax import shard_map

        node = ht.placeholder_op("t")
        h = halltoall_op(node, axes=("ici", "dcn"))
        xs = jnp.arange(16 * 2, dtype=jnp.float32).reshape(16, 2)

        def body(x):
            tc = TraceContext(axis_env=("ici", "dcn"))
            return h.compute([x], tc)

        run = jax.jit(shard_map(body, mesh=mesh,
                                in_specs=P(("dcn", "ici")),
                                out_specs=P(("dcn", "ici"))))
        out = run(xs)
        out2 = run(out)
        np.testing.assert_array_equal(np.asarray(out2), np.asarray(xs))
        assert not np.array_equal(np.asarray(out), np.asarray(xs))

        # the hierarchical two-stage exchange must equal ONE flat
        # all-to-all over the combined ('dcn','ici') superaxis
        def flat(x):
            n = 4
            parts = x.reshape(n, x.shape[0] // n, *x.shape[1:])
            return jax.lax.all_to_all(
                parts, ("dcn", "ici"), split_axis=0,
                concat_axis=0).reshape(x.shape)

        flat_out = jax.jit(shard_map(flat, mesh=mesh,
                                     in_specs=P(("dcn", "ici")),
                                     out_specs=P(("dcn", "ici"))))(xs)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(flat_out))

    def test_hierarchical_moe_trains_on_ici_dcn_mesh(self):
        """MoE with hierarchical=True through the Executor on a
        ('dcn','ici') mesh (pjit mode: constraint spans both axes)."""
        x = ht.placeholder_op("x")
        y = ht.placeholder_op("y")
        gate = ht.layers.TopKGate(D, B, E, k=1, capacity_factor=1.0)
        experts = ht.layers.StackedExperts(E, D, F, activation="relu",
                                           name="hier")
        moe = ht.layers.MoELayer(gate=gate, experts=experts, num_tokens=B,
                                 embed_dim=D, hierarchical=True)
        out, l_aux = moe(x)
        head = ht.init.xavier_uniform((D, 2), name="hier_head")
        loss = ht.reduce_mean_op(ht.softmaxcrossentropy_op(
            ht.matmul_op(out, head), y), axes=0) \
            + ht.mul_byconst_op(l_aux, 0.01)
        train = ht.optim.SGDOptimizer(learning_rate=0.1).minimize(loss)

        mesh = make_mesh({"dcn": 2, "ici": 2})
        ex = ht.Executor({"train": [loss, train]}, mesh=mesh)
        for name, node in ex.variables.items():
            if "expert_stack" in name:
                node.sharding_spec = P(("dcn", "ici"), None, None)
        ex.var_values = {k: jax.device_put(v, ex.param_sharding(k))
                         for k, v in ex.var_values.items()}
        for a, b in batches(3):
            out_v = ex.run("train", feed_dict={x: a, y: b})
            assert np.isfinite(float(np.asarray(out_v[0])))


def test_dispatch_formulations_agree():
    """The one-hot-matmul and row-scatter dispatch forms must produce
    identical expert buffers and identical combine-data gradients."""
    from hetu_tpu.graph.ops_moe import _scatter_rows

    rng = np.random.RandomState(5)
    N, D, slots = 64, 16, 24
    src = jnp.asarray(rng.randn(N, D).astype(np.float32))
    pos = jnp.asarray(rng.randint(0, slots + 4, N).astype(np.int32))
    valid = pos < slots            # some dropped
    gates = jnp.asarray(rng.rand(N).astype(np.float32))

    for terms in ([(pos, valid, None)],
                  [(pos, valid, gates)],
                  [(pos, valid, None), ((pos + 3) % slots,
                                        jnp.ones_like(valid), gates)]):
        a = _scatter_rows(terms, slots, src, jnp.float32,
                          force_scatter=False)
        b = _scatter_rows(terms, slots, src, jnp.float32,
                          force_scatter=True)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-5)


class TestBertMoEFlagship:
    """MoE composed into the flagship LM (reference
    examples/nlp/bert/hetu_bert_moe.py + train_hetu_bert_dp_moe.py):
    alternating MoE FFN blocks, aux balance loss in the total, trained
    through a dp x ep mesh with single-device-equivalent trajectories."""

    CFG = dict(vocab_size=97, hidden_size=32, num_hidden_layers=2,
               num_attention_heads=2, intermediate_size=64,
               max_position_embeddings=16, batch_size=4, seq_len=8,
               num_experts=4, top_k=1, moe_every=2,
               hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0)

    def _build(self):
        from hetu_tpu.models import BertMoEConfig, BertMoEForPreTraining
        cfg = BertMoEConfig(**self.CFG)
        m = BertMoEForPreTraining(cfg)
        ids = ht.placeholder_op("bm_ids")
        tt = ht.placeholder_op("bm_tt")
        mlm = ht.placeholder_op("bm_mlm")
        nsp = ht.placeholder_op("bm_nsp")
        loss, _logits, _nspl = m(ids, tt, masked_lm_labels=mlm,
                                 next_sentence_label=nsp)
        train = ht.optim.AdamOptimizer(learning_rate=1e-3).minimize(loss)
        return cfg, (ids, tt, mlm, nsp), loss, train

    def _batches(self, n=5, seed=0):
        rng = np.random.RandomState(seed)
        out = []
        for _ in range(n):
            iv = rng.randint(0, 97, (4, 8)).astype(np.int32)
            tv = np.zeros((4, 8), np.int32)
            mv = np.where(rng.rand(4, 8) < 0.3, iv, -1).astype(np.int32)
            nv = rng.randint(0, 2, (4,)).astype(np.int32)
            out.append((iv, tv, mv, nv))
        return out

    def test_moe_blocks_alternate_and_aux_loss_present(self):
        from hetu_tpu.models import BertMoEConfig, BertMoEModel
        from hetu_tpu.models.bert_moe import BertMoELayer
        cfg = BertMoEConfig(**{**self.CFG, "num_hidden_layers": 4})
        model = BertMoEModel(cfg)
        kinds = [isinstance(l, BertMoELayer) for l in model.encoder_layers]
        assert kinds == [False, True, False, True]
        _cfg, nodes, loss, train = self._build()
        ids, tt, mlm, nsp = nodes
        ex = ht.Executor({"train": [loss, train]})
        iv, tv, mv, nv = self._batches(1)[0]
        out = ex.run("train", feed_dict={ids: iv, tt: tv, mlm: mv,
                                         nsp: nv})
        assert np.isfinite(float(np.asarray(out[0])))

    def test_ep_times_dp_trajectory_matches_single_device(self):
        _cfg, nodes, loss, train = self._build()
        ids, tt, mlm, nsp = nodes
        ex = ht.Executor({"train": [loss, train]})
        w0 = ex.return_tensor_values()
        bs = self._batches()
        base = [float(np.asarray(ex.run("train", feed_dict={
            ids: a, tt: b, mlm: c, nsp: d})[0])) for a, b, c, d in bs]

        _cfg, nodes, loss, train = self._build()
        ids, tt, mlm, nsp = nodes
        ex2 = ht.Executor({"train": [loss, train]},
                          dist_strategy=ht.dist.ExpertParallel(ep=4, dp=2))
        ex2.load_dict(w0)
        tr = [float(np.asarray(ex2.run("train", feed_dict={
            ids: a, tt: b, mlm: c, nsp: d})[0])) for a, b, c, d in bs]
        np.testing.assert_allclose(tr, base, atol=2e-5)

    def test_expert_stacks_sharded_dense_ffn_replicated(self):
        _cfg, nodes, loss, train = self._build()
        ids, tt, mlm, nsp = nodes
        ex = ht.Executor({"train": [loss, train]},
                         dist_strategy=ht.dist.ExpertParallel(ep=4, dp=2))
        stack = dense = None
        for name, v in ex.var_values.items():
            if "_moe_expert_stack_w1" in name:
                stack = v
            if "_intermediate_weight" in name:
                dense = v
        assert stack is not None and dense is not None
        # 4 experts split over ep=4: each shard holds exactly 1 expert
        assert {s.data.shape for s in stack.addressable_shards} == \
            {(1, 32, 64)}
        # the dense block's FFN replicates across the expert axis
        assert {s.data.shape for s in dense.addressable_shards} == \
            {(32, 64)}


def test_bert_moe_under_pipeline_trains():
    """Composition row: the MoE flagship through Executor(pipeline=
    'gpipe').  EXACT trajectory equality with the full-batch run is
    deliberately NOT the contract here: TopKGate's static capacity is
    k*ceil(tokens/E) of the COMPILED batch, so each microbatch routes
    against its own (smaller) capacity pool and token-drop patterns
    differ from full-batch routing — the same per-chunk semantics every
    capacity-based MoE has under gradient accumulation (and the same
    caveat bert.py documents for the masked mean).  The contract: the
    composition runs and trains."""
    from hetu_tpu.models import BertMoEConfig, BertMoEForPreTraining

    # the graph bakes the MICROBATCH size (global batch 8 / M=2); the
    # pipeline splits each fed global batch across microbatches
    cfg = BertMoEConfig(
        vocab_size=64, hidden_size=32, num_hidden_layers=4,
        num_attention_heads=2, intermediate_size=64,
        batch_size=4, seq_len=8, num_experts=4, top_k=1,
        moe_every=2, hidden_dropout_prob=0.0,
        attention_probs_dropout_prob=0.0)
    m = BertMoEForPreTraining(cfg, name="plb")
    nodes = tuple(ht.placeholder_op(f"plb_{nm}")
                  for nm in ("ids", "tt", "mlm", "nsp"))
    loss, _, _ = m(nodes[0], nodes[1], masked_lm_labels=nodes[2],
                   next_sentence_label=nodes[3])
    train = ht.optim.SGDOptimizer(learning_rate=0.05).minimize(loss)

    def batches(n=4):
        rng = np.random.RandomState(3)
        out = []
        for _ in range(n):
            iv = rng.randint(0, 64, (8, 8)).astype(np.int32)
            mv = np.where(rng.rand(8, 8) < 0.3, iv, -1).astype(np.int32)
            out.append((iv, np.zeros((8, 8), np.int32), mv,
                        np.zeros((8,), np.int32)))
        return out

    ex2 = ht.Executor({"train": [loss, train]}, pipeline="gpipe",
                      num_microbatches=2)
    tr = []
    for iv, tv, mv, nv in batches(8):
        out = ex2.run("train", feed_dict=dict(zip(nodes,
                                                  (iv, tv, mv, nv))))
        tr.append(float(np.asarray(out[0]).reshape(-1)[0]))
    assert all(np.isfinite(v) for v in tr)
    assert np.mean(tr[-3:]) < np.mean(tr[:3]), tr
