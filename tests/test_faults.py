"""Chaos harness + failure-survival suite (ISSUE 1 tentpole).

Layers under test:

- ``ps/faults.py``: seed-deterministic FaultPlan + the HETU_CHAOS env
  activation at the transport seam;
- exactly-once under loss/duplication: the (client_id, seq) replay
  cache absorbs injected drop/dup faults on the real TCP wire;
- ``ps/sharded.py`` replica groups: primary loss mid-training fails
  over to the ring backup with a trajectory equal to the fault-free
  run; a restarted primary re-syncs from its replica before rejoining;
- ``launcher.run_cluster`` supervisor: dead workers restart from the
  latest checkpoint with an exponential-backoff budget and a structured
  failure-event log; dead PS servers respawn;
- ``cache/cstable.py`` graceful degradation: bounded-stale serving and
  push replay across a PS outage.

All CPU-harness; nothing here needs a chip or a cluster.
"""

import json
import os
import sys
import tempfile
import time

import numpy as np
import pytest

from hetu_tpu.ps import faults
from hetu_tpu.ps.faults import FaultPlan
from hetu_tpu.ps.client import (PSClient, PSConnectionError,
                                _TCPTransport)
from hetu_tpu.ps.server import PSServer
from hetu_tpu.ps.sharded import (ShardedPSClient, REPLICA_PREFIX,
                                 _LocalServerTransport)


@pytest.fixture(autouse=True)
def _fresh_plans():
    """Per-test decision streams: a cached plan's counter must not leak
    across tests reusing a spec string."""
    faults.reset_plans()
    yield
    faults.reset_plans()


def _free_port():
    import socket
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.mark.smoke
class TestFaultPlan:
    def test_spec_parse(self):
        p = FaultPlan.from_spec(
            "seed=7,drop=0.1,dup=0.05,delay=0.02:0.5,reset=0.01,"
            "slow=0.1:0.2,kill=9,role=server")
        assert p.seed == 7 and p.drop == 0.1 and p.dup == 0.05
        assert p.delay == (0.02, 0.5) and p.reset == 0.01
        assert p.slow == (0.1, 0.2) and p.kill == 9
        assert p.role == "server"

    def test_reorder_is_dup_alias(self):
        p = FaultPlan.from_spec("dup=0.1,reorder=0.2")
        assert p.dup == pytest.approx(0.3)

    def test_bad_spec_raises(self):
        with pytest.raises(ValueError):
            FaultPlan.from_spec("drop")
        with pytest.raises(ValueError):
            FaultPlan.from_spec("warp=0.1")

    def test_deterministic_stream(self):
        mk = lambda: FaultPlan(seed=5, drop=0.3, dup=0.2,  # noqa: E731
                               delay=(0.1, 0.0))
        a = [mk().draw().kind for _ in range(1)]  # fresh plan each draw
        p1, p2 = mk(), mk()
        s1 = [p1.draw().kind for _ in range(300)]
        s2 = [p2.draw().kind for _ in range(300)]
        assert s1 == s2
        assert a[0] == s1[0]
        p3 = FaultPlan(seed=6, drop=0.3, dup=0.2, delay=(0.1, 0.0))
        assert [p3.draw().kind for _ in range(300)] != s1

    def test_rates_approximate_probabilities(self):
        p = FaultPlan(seed=1, drop=0.25)
        kinds = [p.draw().kind for _ in range(4000)]
        frac = kinds.count("drop") / 4000
        assert 0.2 < frac < 0.3
        assert p.fired["drop"] == kinds.count("drop")

    def test_kinds_filter_masks_but_advances(self):
        p1 = FaultPlan(seed=2, drop=0.5)
        masked = [p1.draw(kinds=("slow",)).kind for _ in range(100)]
        assert set(masked) == {"none"}
        # the restricted caller consumed the same stream positions
        p2 = FaultPlan(seed=2, drop=0.5)
        assert sum(k.kind == "drop" for k in
                   (p2.draw() for _ in range(100))) > 30

    def test_role_gating(self, monkeypatch):
        p = FaultPlan(seed=0, drop=1.0, role="server")
        monkeypatch.delenv("HETU_CHAOS_ROLE", raising=False)
        assert p.draw().kind == "none"          # wrong role: inert
        monkeypatch.setenv("HETU_CHAOS_ROLE", "server:3")
        assert p.draw().kind == "drop"          # prefix match fires

    def test_env_activation_caches_one_plan(self, monkeypatch):
        monkeypatch.setenv("HETU_CHAOS", "seed=4,drop=0.5")
        a, b = faults.plan_from_env(), faults.plan_from_env()
        assert a is b and a.drop == 0.5
        monkeypatch.delenv("HETU_CHAOS")
        assert faults.plan_from_env() is None


@pytest.mark.smoke
class TestReplicaScopedChaos:
    """The serving-fleet seam additions (ISSUE 8): wedge events, the
    explicit draw(role=...) override for seams hosting several roles in
    one process, and inline kills that return instead of SIGKILLing the
    whole fleet."""

    def test_wedge_spec_parse(self):
        p = FaultPlan.from_spec("seed=2,wedge=3,role=replica0")
        assert p.wedge == 3 and p.role == "replica0"

    def test_wedge_fires_only_at_optin_seams(self):
        """A transport (no "wedge" in kinds) walks straight past the
        wedge position; a replica step seam draws it exactly once."""
        p1 = FaultPlan(seed=0, wedge=2)
        kinds = [p1.draw(kinds=("drop",)).kind for _ in range(5)]
        assert "wedge" not in kinds and p1.fired["wedge"] == 0
        p2 = FaultPlan(seed=0, wedge=2)
        kinds = [p2.draw(kinds=("kill", "wedge")).kind
                 for _ in range(5)]
        assert kinds[1] == "wedge" and kinds.count("wedge") == 1
        assert p2.fired["wedge"] == 1

    def test_explicit_role_overrides_env(self, monkeypatch):
        """draw(role=...) gates the plan per call — the fleet's
        replicas share one process, so HETU_CHAOS_ROLE cannot tell
        them apart."""
        monkeypatch.setenv("HETU_CHAOS_ROLE", "replica1")
        p = FaultPlan(seed=0, drop=1.0, role="replica0")
        assert p.draw().kind == "none"               # env role: no match
        assert p.draw(role="replica0").kind == "drop"   # explicit: fires
        assert p.draw(role="replica1").kind == "none"

    def test_nonmatching_role_never_advances_counter(self):
        """Each replica's step stream is independently deterministic:
        other replicas' draws must not consume positions."""
        p = FaultPlan(seed=9, kill=2, role="replica1")
        for _ in range(10):   # replica0 hammers the plan — inert
            assert p.draw(role="replica0", kinds=("kill", "wedge"),
                          inline=True).kind == "none"
        assert p._n == 0
        # replica1's own 2nd step is still the kill
        assert p.draw(role="replica1", kinds=("kill",),
                      inline=True).kind == "none"
        assert p.draw(role="replica1", kinds=("kill",),
                      inline=True).kind == "kill"

    def test_inline_kill_returns_instead_of_sigkill(self):
        """inline=True hands the death to the caller (the replica
        harness) — the test process surviving IS the assertion."""
        p = FaultPlan(seed=0, kill=1)
        f = p.draw(kinds=("kill",), inline=True)
        assert f.kind == "kill" and p.fired["kill"] == 1
        # one-shot: the position is consumed
        assert p.draw(kinds=("kill",), inline=True).kind == "none"


@pytest.mark.smoke
class TestChaosLocalTier:
    def test_local_transport_drops_retry_exactly_once(self, monkeypatch):
        """In-process tier under loss: every push applies exactly once
        (drops retry immediately; there is no response to lose)."""
        srv = PSServer()
        c = PSClient(transport=_LocalServerTransport(srv))
        c.param_set("w", np.zeros(4, np.float32), opt="sgd",
                    opt_args={"learning_rate": 1.0})
        # seed picked so no call loses all 3 attempts (deterministic)
        monkeypatch.setenv("HETU_CHAOS", "seed=3,drop=0.1")
        for _ in range(60):
            c.push("w", -np.ones(4, np.float32))
        plan = faults.plan_from_env()
        monkeypatch.delenv("HETU_CHAOS")
        np.testing.assert_allclose(np.asarray(c.pull("w")), 60.0)
        assert plan.fired["drop"] > 0   # the chaos actually fired

    def test_local_transport_surfaces_total_loss(self, monkeypatch):
        srv = PSServer()
        c = PSClient(transport=_LocalServerTransport(srv))
        c.param_set("w2", np.zeros(2, np.float32))
        monkeypatch.setenv("HETU_CHAOS", "seed=0,drop=1.0")
        with pytest.raises(PSConnectionError):
            c.pull("w2")


class TestChaosTCPExactlyOnce:
    def test_drop_dup_replay_cache_applies_once(self, monkeypatch):
        """The acceptance fault mix on the REAL wire: ~10% dropped
        requests and ~10% lost-after-apply responses.  The retries and
        the server's (client_id, seq) replay cache must deliver every
        push exactly once."""
        srv = PSServer()
        port = _free_port()
        tcp = srv.serve_tcp(port, block=False)
        try:
            t = _TCPTransport("127.0.0.1", port, timeout=5,
                              connect_timeout=2, retries=8)
            c = PSClient(transport=t)
            c.param_set("w", np.zeros(4, np.float32), opt="sgd",
                        opt_args={"learning_rate": 1.0})
            monkeypatch.setenv("HETU_CHAOS", "seed=11,drop=0.1,dup=0.1")
            for _ in range(40):
                c.push("w", -np.ones(4, np.float32))
            plan = faults.plan_from_env()
            monkeypatch.delenv("HETU_CHAOS")
            np.testing.assert_allclose(np.asarray(c.pull("w")), 40.0)
            assert plan.fired["drop"] > 0 and plan.fired["dup"] > 0
        finally:
            tcp.shutdown()


def _train_steps(client, key, steps, rng_seed=0, rows=8, width=3,
                 skip=0):
    """Deterministic sd_pushpull workload shared by the failover tests
    and their fault-free baselines."""
    rng = np.random.RandomState(rng_seed)
    out = []
    for i in range(steps):
        ids = rng.randint(0, rows, 5).astype(np.int64)
        grads = rng.randn(5, width).astype(np.float32)
        if i >= skip:
            out.append(np.asarray(client.sd_pushpull(key, ids, grads)))
    return out


class TestShardFailoverLocal:
    ROWS, WIDTH = 8, 3

    def _mk(self, replicate):
        servers = [PSServer(), PSServer()]
        c = ShardedPSClient(servers=servers, replicate=replicate)
        table = np.zeros((self.ROWS, self.WIDTH), np.float32)
        c.param_set("t", table, opt="sgd",
                    opt_args={"learning_rate": 0.5})
        return servers, c

    def test_replica_tracks_primary(self):
        servers, c = self._mk(True)
        _train_steps(c, "t", 6)
        c.drain_replication()
        # each backup's replica equals its partner shard exactly
        np.testing.assert_allclose(
            np.asarray(servers[1].pull(REPLICA_PREFIX + "t")),
            np.asarray(servers[0].pull("t")))
        np.testing.assert_allclose(
            np.asarray(servers[0].pull(REPLICA_PREFIX + "t")),
            np.asarray(servers[1].pull("t")))

    def test_failover_matches_fault_free_and_resync_rejoins(self):
        _, base = self._mk(False)
        _train_steps(base, "t", 12)
        want = base.pull("t")

        servers, c = self._mk(True)
        _train_steps(c, "t", 6)                       # healthy half
        c.drain_replication()

        class _Dead:
            def call(self, method, *a, **kw):
                raise PSConnectionError("server gone (test)")

            def close(self):
                pass
        live_transport = c.clients[0].t
        c.clients[0].t = _Dead()                      # primary 0 dies
        _train_steps(c, "t", 12, skip=6)              # failed-over half
        assert c.failed_shards() == [0]
        assert any(e["event"] == "ps_shard_failover"
                   for e in c.failure_events)
        np.testing.assert_allclose(c.pull("t"), want, atol=1e-5)

        # "restart" the primary empty and re-seed it from the replica
        fresh = PSServer()
        c.clients[0].t = _LocalServerTransport(fresh)
        restored = c.resync_shard(0)
        assert "t" in restored and c.failed_shards() == []
        np.testing.assert_allclose(c.pull("t"), want, atol=1e-5)
        # the restored primary really holds its shard again...
        np.testing.assert_allclose(np.asarray(fresh.pull("t")),
                                   np.asarray(want)[0::2], atol=1e-5)
        # ...including its hosted replica of the OTHER shard
        np.testing.assert_allclose(
            np.asarray(fresh.pull(REPLICA_PREFIX + "t")),
            np.asarray(want)[1::2], atol=1e-5)
        del live_transport

    def test_unreplicated_group_still_surfaces_loss(self):
        _, c = self._mk(False)

        class _Dead:
            def call(self, *a, **kw):
                raise PSConnectionError("gone")

            def close(self):
                pass
        c.clients[0].t = _Dead()
        with pytest.raises(PSConnectionError):
            c.pull("t")


class TestShardFailoverSIGKILL:
    """The acceptance scenario: a 2-shard replicated TCP group, the
    shard-0 primary SIGKILLed by a seeded FaultPlan mid-training while
    ~10% of the client's requests are dropped/duplicated.  The run must
    complete with a final table matching the fault-free trajectory, and
    the restarted primary must re-sync and rejoin."""

    STEPS = 12

    def test_sigkill_failover_equivalence(self, monkeypatch):
        from hetu_tpu.launcher import _start_ps_process, _wait_ps

        # fault-free baseline, in-process
        base_servers = [PSServer(), PSServer()]
        base = ShardedPSClient(servers=base_servers, replicate=False)
        base.param_set("t", np.zeros((8, 3), np.float32), opt="sgd",
                       opt_args={"learning_rate": 0.5})
        _train_steps(base, "t", self.STEPS)
        want = base.pull("t")

        ports = [_free_port(), _free_port()]
        addrs = [f"localhost:{p}" for p in ports]
        # the seeded plan SIGKILLs the shard-0 primary at its 13th
        # served request (~mid-training: setup costs ~3 requests, each
        # step costs ~2 — its own shard op + the shard-1 replica write)
        procs = [
            _start_ps_process(ports[0], {
                "HETU_CHAOS": "seed=1,kill=13,role=server:0",
                "HETU_CHAOS_ROLE": "server:0"}),
            _start_ps_process(ports[1], {"HETU_CHAOS_ROLE": "server:1"}),
        ]
        try:
            for p in ports:
                _wait_ps("localhost", p)
            # fast failure detection: short timeouts, generous retries
            # (chaos losses retry without backoff)
            monkeypatch.setenv("HETU_PS_TIMEOUT", "5")
            monkeypatch.setenv("HETU_PS_CONNECT_TIMEOUT", "1")
            monkeypatch.setenv("HETU_PS_RETRIES", "6")
            c = ShardedPSClient(addrs=addrs, replicate=True)
            c.param_set("t", np.zeros((8, 3), np.float32), opt="sgd",
                        opt_args={"learning_rate": 0.5})
            monkeypatch.setenv("HETU_CHAOS", "seed=2,drop=0.1,dup=0.1")
            _train_steps(c, "t", self.STEPS)
            monkeypatch.delenv("HETU_CHAOS")
            c.drain_replication()

            assert c.failed_shards() == [0], \
                "the seeded kill did not fire (or hit the wrong shard)"
            np.testing.assert_allclose(c.pull("t"), want, atol=1e-4)

            # restart the dead primary (no kill this time) + resync
            procs.append(_start_ps_process(
                ports[0], {"HETU_CHAOS_ROLE": "server:0"}))
            _wait_ps("localhost", ports[0])
            restored = c.resync_shard(0)
            assert "t" in restored
            assert c.failed_shards() == []
            np.testing.assert_allclose(c.pull("t"), want, atol=1e-4)
            # traffic really returned to the primary: its python tier
            # serves the shard again
            direct = PSClient(transport=_TCPTransport(
                "localhost", ports[0], retries=2))
            np.testing.assert_allclose(
                np.asarray(direct.pull("t")), np.asarray(want)[0::2],
                atol=1e-4)
            direct.finalize()
            c.finalize()
        finally:
            for p in procs:
                if p.is_alive():
                    p.terminate()
                p.join(timeout=10)


class TestSupervisorWorkerRestart:
    """Acceptance: kill a worker mid-epoch under run_cluster; it must
    resume from the latest checkpoint and finish with the expected step
    count, with the restart budget and backoff visible in the
    failure-event log."""

    def test_worker_sigkill_resumes_from_checkpoint(self, monkeypatch):
        from hetu_tpu.context import DistConfig
        from hetu_tpu.launcher import run_cluster

        d = tempfile.mkdtemp()
        script = os.path.join(d, "train.py")
        with open(script, "w") as f:
            f.write("""
import os, json, signal
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import hetu_tpu as ht

D = %r
TOTAL = 6
x = ht.placeholder_op("x")
y = ht.placeholder_op("y")
w1 = ht.Variable("w1", value=np.eye(4, dtype=np.float32))
loss = ht.reduce_mean_op(ht.softmaxcrossentropy_op(
    ht.matmul_op(x, w1), y), axes=0)
train = ht.optim.SGDOptimizer(learning_rate=0.1).minimize(loss)
ex = ht.Executor({"train": [loss, train]})
if os.path.exists(os.path.join(D, "ckpt", "checkpoint.pkl")):
    ex.load(os.path.join(D, "ckpt"))
rng = np.random.RandomState(0)
batches = [(rng.randn(8, 4).astype(np.float32),
            np.eye(4, dtype=np.float32)[rng.randint(0, 4, 8)])
           for _ in range(TOTAL)]
losses = []
for step in range(int(ex.step), TOTAL):
    a, b = batches[step]
    out = ex.run("train", feed_dict={x: a, y: b})
    losses.append(float(np.asarray(out[0])))
    ex.save(os.path.join(D, "ckpt"))
    if step == 2 and os.environ.get("HETU_RESTART_COUNT", "0") == "0":
        os.kill(os.getpid(), signal.SIGKILL)   # die mid-epoch
with open(os.path.join(D, "out.json"), "w") as f:
    json.dump({"final_step": int(ex.step),
               "restart_count": os.environ.get("HETU_RESTART_COUNT"),
               "losses_this_life": losses}, f)
""" % d)
            f.flush()
        log = os.path.join(d, "failures.jsonl")
        monkeypatch.setenv("HETU_FAILURE_LOG", log)
        monkeypatch.setenv("HETU_RESTART_BACKOFF", "0.3")
        codes = run_cluster(DistConfig(num_servers=0, num_workers=1),
                            [sys.executable, script])
        assert codes == [0]
        with open(os.path.join(d, "out.json")) as f:
            out = json.load(f)
        # the resumed incarnation continued at step 3 and finished 6
        assert out["final_step"] == 6
        assert out["restart_count"] == "1"
        assert len(out["losses_this_life"]) == 3
        events = [json.loads(ln) for ln in open(log)]
        kinds = [e["event"] for e in events]
        assert "worker_exit" in kinds and "worker_restart" in kinds
        exit_ev = next(e for e in events if e["event"] == "worker_exit")
        assert exit_ev["rc"] == -9
        sched = next(e for e in events
                     if e["event"] == "worker_restart_scheduled")
        assert sched["backoff_s"] == pytest.approx(0.3)
        assert sched["attempt"] == 1

    def test_restart_budget_exhausts(self, monkeypatch):
        """A worker that always fails consumes the budget and surfaces
        its exit code — the supervisor must not loop forever."""
        from hetu_tpu.context import DistConfig
        from hetu_tpu.launcher import run_cluster, last_failure_events

        monkeypatch.setenv("HETU_RESTART_LIMIT", "2")
        monkeypatch.setenv("HETU_RESTART_BACKOFF", "0.05")
        monkeypatch.delenv("HETU_FAILURE_LOG", raising=False)
        codes = run_cluster(DistConfig(num_servers=0, num_workers=1),
                            [sys.executable, "-c", "raise SystemExit(3)"])
        assert codes == [3]
        from hetu_tpu import launcher
        kinds = [e["event"] for e in launcher.last_failure_events]
        assert kinds.count("worker_exit") == 3      # 1 first + 2 retries
        assert "worker_failed" in kinds


class TestSupervisorPSRestart:
    def test_ps_server_sigkill_is_respawned(self, monkeypatch):
        """A chaos-killed PS server is respawned by the supervisor and
        the cluster still completes (the worker rides through or is
        itself restarted within budget)."""
        from hetu_tpu.context import DistConfig
        from hetu_tpu.launcher import run_cluster

        d = tempfile.mkdtemp()
        script = os.path.join(d, "worker.py")
        with open(script, "w") as f:
            f.write("""
import os, time
import numpy as np
from hetu_tpu.ps.client import PSClient
c = PSClient.get()
c.param_set("w", np.zeros(4, np.float32), opt="sgd",
            opt_args={"learning_rate": 1.0})
for i in range(40):
    c.push("w", -np.ones(4, np.float32))
    time.sleep(0.02)
open(os.path.join(%r, "done"), "w").write("1")
""" % d)
        log = os.path.join(d, "failures.jsonl")
        port = _free_port()
        monkeypatch.setenv("HETU_PS_PORT", str(port))
        monkeypatch.setenv("HETU_FAILURE_LOG", log)
        monkeypatch.setenv("HETU_RESTART_BACKOFF", "0.3")
        monkeypatch.setenv("HETU_RESTART_LIMIT", "5")
        monkeypatch.setenv("HETU_PS_TIMEOUT", "3")
        monkeypatch.setenv("HETU_PS_CONNECT_TIMEOUT", "1")
        monkeypatch.setenv("HETU_PS_RETRIES", "3")
        # the kill plan reaches the server child through the launcher's
        # env inheritance; role-scoping keeps every other process inert
        monkeypatch.setenv("HETU_CHAOS", "seed=5,kill=25,role=server:0")
        codes = run_cluster(DistConfig(num_servers=1, num_workers=1),
                            [sys.executable, script])
        monkeypatch.delenv("HETU_CHAOS")
        assert codes == [0]
        assert os.path.exists(os.path.join(d, "done"))
        events = [json.loads(ln) for ln in open(log)]
        kinds = [e["event"] for e in events]
        assert "ps_server_exit" in kinds
        assert "ps_restart" in kinds


class _FlakyComm:
    """PSServer facade whose RPCs fail while ``down`` (PS outage
    stand-in).  ``down_methods`` restricts the outage to a method
    subset (e.g. only the push seam)."""

    def __init__(self, srv, down_methods=None):
        self._srv = srv
        self.down = False
        self._down_methods = down_methods

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)
        fn = getattr(self._srv, name)

        def call(*a, **kw):
            if self.down and (self._down_methods is None
                              or name in self._down_methods):
                raise PSConnectionError("PS down (test)")
            return fn(*a, **kw)
        return call


@pytest.mark.smoke
class TestCacheOutage:
    def _mk(self):
        from hetu_tpu.cache.cstable import CacheSparseTable
        srv = PSServer()
        table = np.arange(64, dtype=np.float32).reshape(16, 4)
        srv.param_set("emb", table)
        comm = _FlakyComm(srv)
        ct = CacheSparseTable(limit=8, vocab_size=16, width=4,
                              key="emb", comm=comm, policy="LRU",
                              prefer_native=False)
        return srv, comm, ct, table

    def test_stale_hits_and_zero_misses_during_outage(self):
        srv, comm, ct, table = self._mk()
        warm = np.arange(6)
        np.testing.assert_allclose(ct.embedding_lookup(warm),
                                   table[warm])
        comm.down = True
        # hits: served from cache (stale within the budget)
        got = ct.embedding_lookup(warm)
        np.testing.assert_allclose(got, table[warm])
        assert ct.num_stale_served > 0
        # misses: zero vectors, not inserted
        got = ct.embedding_lookup(np.array([9]))
        np.testing.assert_allclose(got, 0.0)
        assert ct.num_zero_served == 1
        comm.down = False
        # recovery: the miss re-fetches for real
        np.testing.assert_allclose(ct.embedding_lookup(np.array([9])),
                                   table[[9]])

    def test_pushes_buffer_and_replay(self):
        srv, comm, ct, table = self._mk()
        warm = np.arange(4)
        ct.embedding_lookup(warm)
        comm.down = True
        # cold-id updates can't reach the PS: they buffer
        ct.embedding_update(np.array([12, 12, 13]),
                            np.ones((3, 4), np.float32))
        assert ct.perf_summary()["backlog_rows"] == 2   # merged dup id
        # flush during the outage buffers the dirty warm lines too
        ct.embedding_update(warm, np.full((4, 4), 0.5, np.float32))
        ct.flush()
        assert ct.perf_summary()["backlog_rows"] >= 2
        before = np.asarray(srv.pull("emb")).copy()
        comm.down = False
        ct.flush()                                      # replays
        assert ct.perf_summary()["backlog_rows"] == 0
        assert ct.num_replayed_rows > 0
        after = np.asarray(srv.pull("emb"))
        np.testing.assert_allclose(after[12], before[12] + 2.0)
        np.testing.assert_allclose(after[13], before[13] + 1.0)
        np.testing.assert_allclose(after[:4], before[:4] + 0.5)

    def test_outage_budget_bounds_degradation(self):
        srv, comm, ct, table = self._mk()
        ct.embedding_lookup(np.arange(4))
        ct.max_stale = 3
        comm.down = True
        for _ in range(3):
            ct.embedding_lookup(np.arange(4))   # within budget
        with pytest.raises(ConnectionError):
            for _ in range(5):
                ct.embedding_lookup(np.arange(4))


class TestExecutorOutageBacklog:
    def test_direct_path_buffers_pushes_across_outage(self):
        import hetu_tpu as ht

        srv = PSServer()
        # outage on the PUSH seam only: phase A's reads stay up, so the
        # backlog (not the read path) is what carries the step
        comm = _FlakyComm(srv, down_methods={"sparse_push", "push"})
        ids = ht.placeholder_op("fo_ids")
        y = ht.placeholder_op("fo_y")
        emb = ht.layers.Embedding(16, 4, name="fo_emb")
        h = ht.embedding_lookup_op(emb.embedding_table, ids)
        h = ht.reduce_mean_op(h, [1])
        logits = ht.matmul_op(h, ht.init.xavier_uniform(
            (4, 2), name="fo_head"))
        loss = ht.reduce_mean_op(
            ht.softmaxcrossentropy_op(logits, y), axes=0)
        train = ht.optim.SGDOptimizer(learning_rate=0.2).minimize(loss)
        ex = ht.Executor({"train": [loss, train]}, comm_mode="Hybrid",
                         ps_comm=comm)
        rng = np.random.RandomState(0)

        def step():
            a = rng.randint(0, 16, (8, 4)).astype(np.int32)
            b = np.eye(2, dtype=np.float32)[rng.randint(0, 2, 8)]
            out = ex.run("train", feed_dict={ids: a, y: b})
            ex.join_ps_push()
            return float(np.asarray(out[0]))

        assert np.isfinite(step())
        before = np.asarray(srv.pull("fo_emb_table")).copy()
        comm.down = True
        assert np.isfinite(step())              # push buffered, no raise
        assert len(ex._ps_push_backlog) >= 1
        np.testing.assert_allclose(np.asarray(srv.pull("fo_emb_table")),
                                   before)     # nothing landed while down
        comm.down = False
        assert np.isfinite(step())              # replays + current push
        assert ex._ps_push_backlog == []
        assert not np.allclose(
            np.asarray(srv.pull("fo_emb_table")), before)


class TestValidatorEventLogContract:
    """The verifier's JSONL report (HETU_VALIDATE_LOG) shares the
    failure log's record shape, keeping PR 1's event-log contract
    uniform: one ``tail | jq 'select(.event == ...)'`` pipeline reads
    launcher failures, serving telemetry, and validation reports."""

    def _record_shape_ok(self, rec):
        return isinstance(rec.get("t"), float) \
            and isinstance(rec.get("event"), str)

    def test_verifier_records_match_failure_log_shape(self, tmp_path,
                                                      monkeypatch):
        import hetu_tpu as ht
        log = tmp_path / "validate.jsonl"
        monkeypatch.setenv("HETU_VALIDATE", "1")
        monkeypatch.setenv("HETU_VALIDATE_LOG", str(log))
        a = ht.Variable("vc_a", value=np.ones((4, 3), np.float32))
        b = ht.Variable("vc_b", value=np.ones((3, 2), np.float32))
        ht.Executor({"eval": [ht.reduce_mean_op(
            ht.matmul_op(a, b), axes=0)]})
        recs = [json.loads(line)
                for line in log.read_text().splitlines()]
        assert recs and all(self._record_shape_ok(r) for r in recs)
        assert {r["event"] for r in recs} <= {
            "graph_verified", "graph_verify_error"}

    def test_verify_error_record_lands_like_a_failure_event(
            self, tmp_path, monkeypatch):
        import hetu_tpu as ht
        from hetu_tpu.analysis import GraphVerifyError
        log = tmp_path / "validate.jsonl"
        monkeypatch.setenv("HETU_VALIDATE", "1")
        monkeypatch.setenv("HETU_VALIDATE_LOG", str(log))
        a = ht.Variable("vc_c", value=np.ones((4, 3), np.float32))
        b = ht.Variable("vc_d", value=np.ones((5, 2), np.float32))
        bad = ht.matmul_op(a, b)
        with pytest.raises(GraphVerifyError):
            ht.Executor({"eval": [bad]})
        recs = [json.loads(line)
                for line in log.read_text().splitlines()]
        err = [r for r in recs if r["event"] == "graph_verify_error"]
        assert err and self._record_shape_ok(err[0])
        # the record carries the same attribution the exception does
        assert err[0]["node"] == bad.name
        assert err[0]["kind"] == "shape"

    def test_uniform_with_launcher_failure_records(self, tmp_path,
                                                   monkeypatch):
        # one merged stream: a launcher failure event and a verifier
        # record filter through the same (t, event) pipeline
        from hetu_tpu.analysis.report import emit_records, make_record
        log = tmp_path / "merged.jsonl"
        launcher_rec = {"t": round(time.time(), 3),
                        "event": "worker_exit", "rank": 0, "code": -9}
        with open(log, "a") as f:
            f.write(json.dumps(launcher_rec) + "\n")
        emit_records([make_record("graph_verified", subgraph="train",
                                  nodes=12)], path=str(log))
        recs = [json.loads(line)
                for line in log.read_text().splitlines()]
        assert len(recs) == 2
        assert all(self._record_shape_ok(r) for r in recs)


@pytest.mark.smoke
class TestFlightRecorderChaos:
    """ISSUE 7 tentpole (d): the chaos flight recorder under
    ``HETU_CHAOS``.  A ``kill=`` event must write the black box to
    ``$HETU_FLIGHT_LOG`` BEFORE the SIGKILL lands (the process gets no
    other chance), and a reset storm that exhausts the client's retries
    dumps from the ``PSConnectionError`` failure path — in both cases a
    contract-valid JSONL file holding the records that led up to the
    fault."""

    def _read_dump(self, path):
        with open(path) as f:
            return [json.loads(ln) for ln in f if ln.strip()]

    def test_chaos_kill_dumps_flight_log(self, tmp_path):
        import subprocess
        flog = str(tmp_path / "flight.jsonl")
        script = (
            "from hetu_tpu import telemetry\n"
            "from hetu_tpu.ps import faults\n"
            "for i in range(6):\n"
            "    telemetry.emit('worker_exit', _stream='failure',\n"
            "                   rank=i, rc=0)\n"
            "plan = faults.plan_from_env()\n"
            "for _ in range(10):\n"
            "    plan.draw('push')   # the 4th evaluated event SIGKILLs\n"
            "raise SystemExit('kill never fired')\n")
        env = dict(os.environ, HETU_CHAOS="seed=1,kill=4",
                   HETU_CHAOS_ROLE="", HETU_RESTART_COUNT="0",
                   HETU_FLIGHT_LOG=flog, HETU_FLIGHT_DEPTH="32",
                   JAX_PLATFORMS="cpu")
        proc = subprocess.run([sys.executable, "-c", script], env=env,
                              capture_output=True, timeout=120)
        assert proc.returncode == -9, (proc.returncode, proc.stderr)
        recs = self._read_dump(flog)
        assert recs[0]["event"] == "flight_dump"
        assert recs[0]["reason"] == "chaos_kill"
        assert recs[0]["chaos_event"] == 4
        assert recs[0]["records"] == len(recs) - 1
        # the records leading up to the kill are all there, in order
        assert [r["rank"] for r in recs[1:]] == list(range(6))
        from hetu_tpu.telemetry import validate_record
        for rec in recs:
            assert validate_record(rec) == [], rec
        # and hetu_trace --check accepts the dump as a stream
        from hetu_tpu.telemetry.trace import main as trace_main
        assert trace_main([flog, "--check"]) == 0

    def test_reset_storm_dumps_on_retry_exhaustion(self, tmp_path,
                                                   monkeypatch):
        from hetu_tpu import telemetry
        flog = str(tmp_path / "reset.jsonl")
        monkeypatch.setenv("HETU_FLIGHT_LOG", flog)
        telemetry.reset()
        telemetry.emit("worker_exit", _stream="failure", rank=7, rc=0)
        srv = PSServer()
        c = PSClient(transport=_LocalServerTransport(srv))
        c.param_set("fw", np.zeros(2, np.float32))
        monkeypatch.setenv("HETU_CHAOS", "seed=0,reset=1.0")
        with pytest.raises(PSConnectionError):
            c.pull("fw")
        recs = self._read_dump(flog)
        headers = [r for r in recs if r["event"] == "flight_dump"]
        assert headers and headers[0]["reason"] == "ps_connection_error"
        assert headers[0]["shard"] == "local"
        # the pre-fault marker made it into the black box
        assert any(r["event"] == "worker_exit" and r.get("rank") == 7
                   for r in recs)
        from hetu_tpu.telemetry import validate_record
        for rec in recs:
            assert validate_record(rec) == [], rec

    def test_no_flight_log_never_blocks_the_kill_path(self, monkeypatch):
        # HETU_FLIGHT_LOG unset: dump is a no-op returning None (the
        # chaos kill and error paths must not grow a new failure mode)
        from hetu_tpu.telemetry.flight import RECORDER
        monkeypatch.delenv("HETU_FLIGHT_LOG", raising=False)
        assert RECORDER.dump("chaos_kill") is None
