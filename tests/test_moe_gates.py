"""Gate-math unit tests for layers/moe.py (reference TopGate.py).

The serving path (models/moe_decode.py) re-derives the same capacity
formula and combine semantics in pure jax; these tests pin the graph-op
originals so the two can never drift silently:

- ``topkgating``: static capacity ``k * ceil(N/E * cf)``, top-k index
  agreement with a numpy oracle, within-expert locations forming exactly
  ``0..count_e-1``, and per-rank gate values equal to the softmax prob of
  the chosen expert.
- ``balance_loss``: analytic toy values (uniform gates -> 1.0 exactly).
- ``HashGate``: fully deterministic ``token_id mod E`` routing.
- ``KTop1Gate``: same weights + same input -> identical routing, and the
  chosen expert always lives in the top-mass group.
"""

import math

import numpy as np

import hetu_tpu as ht
from hetu_tpu.layers.moe import balance_loss, topkgating


def _ints(a):
    return np.asarray(a).reshape(-1).astype(np.int64)


class TestTopKGating:
    N, E, K, CF = 16, 4, 2, 1.5

    def _run(self, seed=0):
        rng = np.random.RandomState(seed)
        logits_np = rng.randn(self.N, self.E).astype(np.float32)
        x = ht.placeholder_op("logits")
        l_aux, idx_s, loc_s, gate_s, cap = topkgating(
            x, self.K, self.CF, self.N, self.E, embed_dim=8)
        ex = ht.Executor({"eval": [l_aux] + idx_s + loc_s + gate_s})
        out = ex.run("eval", feed_dict={x: logits_np})
        k = self.K
        return (logits_np, float(np.asarray(out[0])),
                [_ints(o) for o in out[1:1 + k]],
                [_ints(o) for o in out[1 + k:1 + 2 * k]],
                [np.asarray(o).reshape(-1) for o in out[1 + 2 * k:]],
                cap)

    def test_capacity_formula(self):
        _, _, _, _, _, cap = self._run()
        assert cap == self.K * math.ceil((self.N / self.E) * self.CF)
        assert cap == 12

    def test_indices_match_numpy_topk(self):
        logits, _, idx_s, _, _, _ = self._run()
        gates = np.exp(logits - logits.max(1, keepdims=True))
        gates /= gates.sum(1, keepdims=True)
        order = np.argsort(-gates, axis=1)
        for rank in range(self.K):
            np.testing.assert_array_equal(idx_s[rank], order[:, rank])
        # ranks pick distinct experts per token
        assert np.all(idx_s[0] != idx_s[1])

    def test_locations_enumerate_expert_slots(self):
        _, _, idx_s, loc_s, _, _ = self._run()
        for e in range(self.E):
            slots = []
            for rank in range(self.K):
                slots.extend(loc_s[rank][idx_s[rank] == e].tolist())
            # every token bound for expert e got a unique slot 0..count-1
            assert sorted(slots) == list(range(len(slots)))

    def test_rank0_slots_precede_rank1(self):
        # acc_base offsets rank-1 locations past ALL rank-0 assignments
        _, _, idx_s, loc_s, _, _ = self._run()
        for e in range(self.E):
            r0 = loc_s[0][idx_s[0] == e]
            r1 = loc_s[1][idx_s[1] == e]
            if len(r0) and len(r1):
                assert r0.max() < r1.min()

    def test_gate_values_are_softmax_probs(self):
        logits, _, idx_s, _, gate_s, _ = self._run()
        gates = np.exp(logits - logits.max(1, keepdims=True))
        gates /= gates.sum(1, keepdims=True)
        for rank in range(self.K):
            want = gates[np.arange(self.N), idx_s[rank]]
            np.testing.assert_allclose(gate_s[rank], want, atol=1e-5)

    def test_l_aux_matches_analytic(self):
        logits, l_aux, idx_s, _, _, _ = self._run()
        gates = np.exp(logits - logits.max(1, keepdims=True))
        gates /= gates.sum(1, keepdims=True)
        me = gates.mean(0)
        want = 0.0
        for rank in range(self.K):
            ce = np.eye(self.E)[idx_s[rank]].mean(0)
            want += self.E * float((me * ce).sum())
        np.testing.assert_allclose(l_aux, want, atol=1e-5)


class TestBalanceLoss:
    def _eval(self, gates_np, mask_np, E):
        g = ht.placeholder_op("g")
        m = ht.placeholder_op("m")
        ex = ht.Executor({"eval": [balance_loss(g, m, E)]})
        return float(np.asarray(
            ex.run("eval", feed_dict={g: gates_np, m: mask_np})[0]))

    def test_uniform_gates_give_exactly_one(self):
        # me_e = 1/E for all e, so loss = E * sum_e (1/E) * f_e = sum f_e = 1
        N, E = 12, 4
        gates = np.full((N, E), 1.0 / E, np.float32)
        mask = np.eye(E, dtype=np.float32)[np.arange(N) % E]
        np.testing.assert_allclose(self._eval(gates, mask, E), 1.0, atol=1e-6)

    def test_skewed_toy_value(self):
        # 2 tokens, 2 experts, both routed to expert 0:
        # me = [0.6, 0.4], ce = [1, 0], loss = 2 * 0.6 = 1.2
        gates = np.array([[0.7, 0.3], [0.5, 0.5]], np.float32)
        mask = np.array([[1.0, 0.0], [1.0, 0.0]], np.float32)
        np.testing.assert_allclose(self._eval(gates, mask, 2), 1.2, atol=1e-6)

    def test_matches_numpy_on_random(self):
        rng = np.random.RandomState(3)
        N, E = 24, 8
        gates = rng.rand(N, E).astype(np.float32)
        gates /= gates.sum(1, keepdims=True)
        mask = np.eye(E, dtype=np.float32)[rng.randint(0, E, N)]
        want = E * float((gates.mean(0) * mask.mean(0)).sum())
        np.testing.assert_allclose(self._eval(gates, mask, E), want,
                                   atol=1e-5)


class TestHashGate:
    def test_round_robin_and_capacity(self):
        N, E, CF = 16, 4, 1.5
        gate = ht.layers.HashGate(8, N, E, capacity_factor=CF)
        x = ht.placeholder_op("x")
        l_aux, idx_s, loc_s, gate_s, cap = gate(x)
        assert l_aux is None
        assert cap == math.ceil((N / E) * CF)
        ex = ht.Executor({"eval": [idx_s[0], loc_s[0], gate_s[0]]})
        idx, loc, g = ex.run("eval", feed_dict={
            x: np.zeros((N, 8), np.float32)})
        np.testing.assert_array_equal(_ints(idx), np.arange(N) % E)
        # round-robin => token t is the (t // E)-th arrival at its expert
        np.testing.assert_array_equal(_ints(loc), np.arange(N) // E)
        np.testing.assert_allclose(np.asarray(g).reshape(-1), 1.0)

    def test_input_independent(self):
        N, E = 8, 4
        gate = ht.layers.HashGate(4, N, E)
        x = ht.placeholder_op("x")
        _, idx_s, _, _, _ = gate(x)
        ex = ht.Executor({"eval": [idx_s[0]]})
        rng = np.random.RandomState(0)
        a = _ints(ex.run("eval", feed_dict={
            x: rng.randn(N, 4).astype(np.float32)})[0])
        b = _ints(ex.run("eval", feed_dict={
            x: rng.randn(N, 4).astype(np.float32)})[0])
        np.testing.assert_array_equal(a, b)


class TestKTop1Gate:
    N, E, D, GPUS = 16, 8, 8, 4  # group_size = E / GPUS = 2

    def _build(self):
        gate = ht.layers.KTop1Gate(self.D, self.N, self.E,
                                   num_local_gpus=self.GPUS)
        x = ht.placeholder_op("x")
        l_aux, idx_s, loc_s, gate_s, cap = gate(x)
        ex = ht.Executor({"eval": [idx_s[0], gate_s[0], l_aux]})
        return x, ex, cap

    def test_deterministic_across_runs_and_executors(self):
        rng = np.random.RandomState(7)
        xb = rng.randn(self.N, self.D).astype(np.float32)
        x, ex, cap = self._build()
        assert cap == math.ceil(self.N / self.E)
        a = _ints(ex.run("eval", feed_dict={x: xb})[0])
        b = _ints(ex.run("eval", feed_dict={x: xb})[0])
        np.testing.assert_array_equal(a, b)
        # a fresh executor loaded with the same weights routes identically
        x2, ex2, _ = self._build()
        ex2.load_dict(ex.return_tensor_values())
        c = _ints(ex2.run("eval", feed_dict={x2: xb})[0])
        np.testing.assert_array_equal(a, c)

    def test_expert_lives_in_top_mass_group(self):
        rng = np.random.RandomState(11)
        xb = rng.randn(self.N, self.D).astype(np.float32)
        x, ex, _ = self._build()
        idx = _ints(ex.run("eval", feed_dict={x: xb})[0])
        w = None
        for name, v in ex.return_tensor_values().items():
            if name.endswith("_linear_weight"):
                w = np.asarray(v)
        assert w is not None
        logits = xb @ w
        gates = np.exp(logits - logits.max(1, keepdims=True))
        gates /= gates.sum(1, keepdims=True)
        group_size = self.E // self.GPUS
        mass = gates.reshape(self.N, self.GPUS, group_size).sum(2)
        want_group = mass.argmax(1)
        np.testing.assert_array_equal(idx // group_size, want_group)
        assert np.all((idx >= 0) & (idx < self.E))
