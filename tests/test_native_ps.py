"""Native PS core tests: the C++ fused optimizer loops must match the
numpy fallback bit-for-bit-ish on every optimizer, dense and sparse
(reference equivalent: server optimizers in ps-lite server/optimizer.h,
exercised by tests/pstests)."""

import numpy as np
import pytest

from hetu_tpu.ps import server as S

pytestmark = pytest.mark.skipif(
    S._NATIVE is None, reason="no C++ toolchain: native core not built")

OPTS = [
    ("sgd", {"learning_rate": 0.1}),
    ("momentum", {"learning_rate": 0.1, "momentum": 0.9}),
    ("nesterov", {"learning_rate": 0.1}),
    ("adagrad", {"learning_rate": 0.1}),
    ("adam", {"learning_rate": 0.01}),
]


def _mk(opt, kw, shape=(32, 8), seed=0):
    rng = np.random.RandomState(seed)
    o = S.SERVER_OPTIMIZERS[opt](**kw)
    value = rng.randn(*shape).astype(np.float32)
    state = o.init_state(shape)
    return o, value, state, rng


@pytest.mark.parametrize("opt,kw", OPTS)
def test_dense_native_matches_numpy(opt, kw, monkeypatch):
    o, v_nat, s_nat, rng = _mk(opt, kw)
    _, v_np, s_np, _ = _mk(opt, kw)
    grads = [rng.randn(*v_nat.shape).astype(np.float32)
             for _ in range(5)]
    for g in grads:
        o.apply_dense(v_nat, g, s_nat)
    monkeypatch.setattr(S, "_NATIVE", None)
    for g in grads:
        o.apply_dense(v_np, g, s_np)
    np.testing.assert_allclose(v_nat, v_np, rtol=1e-5, atol=1e-6)
    for k in s_nat:
        np.testing.assert_allclose(np.asarray(s_nat[k]),
                                   np.asarray(s_np[k]), rtol=1e-5,
                                   atol=1e-6)


@pytest.mark.parametrize("opt,kw", OPTS)
def test_sparse_native_matches_numpy(opt, kw, monkeypatch):
    o, v_nat, s_nat, rng = _mk(opt, kw)
    _, v_np, s_np, _ = _mk(opt, kw)
    pushes = []
    for _ in range(4):
        ids = rng.randint(0, 32, 12).astype(np.int64)  # with duplicates
        rows = rng.randn(12, 8).astype(np.float32)
        pushes.append((ids, rows))
    for ids, rows in pushes:
        o.apply_sparse(v_nat, ids, rows, s_nat)
    monkeypatch.setattr(S, "_NATIVE", None)
    for ids, rows in pushes:
        o.apply_sparse(v_np, ids, rows, s_np)
    np.testing.assert_allclose(v_nat, v_np, rtol=1e-4, atol=1e-5)


def test_duplicate_ids_update_stateful_row_once():
    """Stateful optimizers must merge duplicate ids (reference dedups via
    IndexedSlices): two pushes of the same row in one call != two calls."""
    o, value, state, rng = _mk("adagrad", {"learning_rate": 0.1})
    v2 = value.copy()
    s2 = o.init_state(value.shape)
    g = rng.randn(8).astype(np.float32)
    ids = np.array([3, 3], np.int64)
    rows = np.stack([g, g])
    o.apply_sparse(value, ids, rows, state)       # one merged update of 2g
    o.apply_sparse(v2, np.array([3], np.int64), (2 * g)[None], s2)
    np.testing.assert_allclose(value[3], v2[3], rtol=1e-5)


def test_server_sparse_roundtrip_native():
    srv = S.PSServer()
    srv.param_init("t", (16, 4), init_type="constant", arg1=0.0,
                   opt="sgd", opt_args={"learning_rate": 1.0})
    ids = np.array([1, 5, 5], np.int64)
    rows = np.ones((3, 4), np.float32)
    srv.sparse_push("t", ids, rows)
    out = srv.sparse_pull("t", np.array([1, 5], np.int64))
    np.testing.assert_allclose(out[0], -1.0)
    np.testing.assert_allclose(out[1], -2.0)
    # versions bumped once per unique id
    assert srv.params["t"].versions[5] == 1
    assert srv.params["t"].versions[1] == 1
    assert srv.params["t"].versions[0] == 0
