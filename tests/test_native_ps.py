"""Native PS core tests: the C++ fused optimizer loops must match the
numpy fallback bit-for-bit-ish on every optimizer, dense and sparse
(reference equivalent: server optimizers in ps-lite server/optimizer.h,
exercised by tests/pstests)."""

import numpy as np
import pytest

from hetu_tpu.ps import server as S

pytestmark = pytest.mark.skipif(
    S._NATIVE is None, reason="no C++ toolchain: native core not built")

OPTS = [
    ("sgd", {"learning_rate": 0.1}),
    ("momentum", {"learning_rate": 0.1, "momentum": 0.9}),
    ("nesterov", {"learning_rate": 0.1}),
    ("adagrad", {"learning_rate": 0.1}),
    ("adam", {"learning_rate": 0.01}),
]


def _mk(opt, kw, shape=(32, 8), seed=0):
    rng = np.random.RandomState(seed)
    o = S.SERVER_OPTIMIZERS[opt](**kw)
    value = rng.randn(*shape).astype(np.float32)
    state = o.init_state(shape)
    return o, value, state, rng


@pytest.mark.parametrize("opt,kw", OPTS)
def test_dense_native_matches_numpy(opt, kw, monkeypatch):
    o, v_nat, s_nat, rng = _mk(opt, kw)
    _, v_np, s_np, _ = _mk(opt, kw)
    grads = [rng.randn(*v_nat.shape).astype(np.float32)
             for _ in range(5)]
    for g in grads:
        o.apply_dense(v_nat, g, s_nat)
    monkeypatch.setattr(S, "_NATIVE", None)
    for g in grads:
        o.apply_dense(v_np, g, s_np)
    np.testing.assert_allclose(v_nat, v_np, rtol=1e-5, atol=1e-6)
    for k in s_nat:
        np.testing.assert_allclose(np.asarray(s_nat[k]),
                                   np.asarray(s_np[k]), rtol=1e-5,
                                   atol=1e-6)


@pytest.mark.parametrize("opt,kw", OPTS)
def test_sparse_native_matches_numpy(opt, kw, monkeypatch):
    o, v_nat, s_nat, rng = _mk(opt, kw)
    _, v_np, s_np, _ = _mk(opt, kw)
    pushes = []
    for _ in range(4):
        ids = rng.randint(0, 32, 12).astype(np.int64)  # with duplicates
        rows = rng.randn(12, 8).astype(np.float32)
        pushes.append((ids, rows))
    for ids, rows in pushes:
        o.apply_sparse(v_nat, ids, rows, s_nat)
    monkeypatch.setattr(S, "_NATIVE", None)
    for ids, rows in pushes:
        o.apply_sparse(v_np, ids, rows, s_np)
    np.testing.assert_allclose(v_nat, v_np, rtol=1e-4, atol=1e-5)


def test_duplicate_ids_update_stateful_row_once():
    """Stateful optimizers must merge duplicate ids (reference dedups via
    IndexedSlices): two pushes of the same row in one call != two calls."""
    o, value, state, rng = _mk("adagrad", {"learning_rate": 0.1})
    v2 = value.copy()
    s2 = o.init_state(value.shape)
    g = rng.randn(8).astype(np.float32)
    ids = np.array([3, 3], np.int64)
    rows = np.stack([g, g])
    o.apply_sparse(value, ids, rows, state)       # one merged update of 2g
    o.apply_sparse(v2, np.array([3], np.int64), (2 * g)[None], s2)
    np.testing.assert_allclose(value[3], v2[3], rtol=1e-5)


def test_server_sparse_roundtrip_native():
    srv = S.PSServer()
    srv.param_init("t", (16, 4), init_type="constant", arg1=0.0,
                   opt="sgd", opt_args={"learning_rate": 1.0})
    ids = np.array([1, 5, 5], np.int64)
    rows = np.ones((3, 4), np.float32)
    srv.sparse_push("t", ids, rows)
    out = srv.sparse_pull("t", np.array([1, 5], np.int64))
    np.testing.assert_allclose(out[0], -1.0)
    np.testing.assert_allclose(out[1], -2.0)
    # versions bumped once per unique id
    assert srv.params["t"].versions[5] == 1
    assert srv.params["t"].versions[1] == 1
    assert srv.params["t"].versions[0] == 0


class TestNativeVan:
    """C++ PS van (native/ps_van.cpp + ps/van.py): the sparse hot path
    served entirely from C++ threads (reference ps-lite zmq_van tier)."""

    @pytest.fixture()
    def van_pair(self):
        from hetu_tpu.ps.van import NativeVan, VanClient, van_available
        if not van_available():
            pytest.skip("no C++ toolchain")
        van = NativeVan()
        port = van.listen()
        value = van.register_sgd_table(
            7, np.zeros((64, 4), np.float32), lr=0.5)
        cli = VanClient("127.0.0.1", port, dim=4)
        yield van, cli, value
        cli.close()
        van.stop()

    def test_push_pull_sgd_semantics(self, van_pair):
        van, cli, value = van_pair
        ids = np.array([3, 9, 3])          # duplicate id
        grads = np.ones((3, 4), np.float32)
        cli.push(7, ids, grads)
        # sequential scatter: id 3 stepped twice
        got = cli.pull(7, np.array([3, 9, 0]))
        np.testing.assert_allclose(got[0], -1.0)   # 2 * -0.5
        np.testing.assert_allclose(got[1], -0.5)
        np.testing.assert_allclose(got[2], 0.0)
        # the registered buffer IS the served table (zero copy)
        np.testing.assert_allclose(value[3], -1.0)

    def test_pushpull_roundtrip(self, van_pair):
        van, cli, _ = van_pair
        ids = np.arange(8)
        grads = np.full((8, 4), 2.0, np.float32)
        rows = cli.sd_pushpull(7, ids, grads)
        np.testing.assert_allclose(rows, -1.0)     # post-update rows

    def test_out_of_range_id_rejected(self, van_pair):
        van, cli, value = van_pair
        before = value.copy()
        with pytest.raises(RuntimeError):
            cli.push(7, np.array([64]), np.ones((1, 4), np.float32))
        np.testing.assert_allclose(value, before)  # nothing applied

    def test_unknown_key_rejected(self, van_pair):
        van, cli, _ = van_pair
        with pytest.raises(RuntimeError):
            cli.pull(99, np.array([0]))

    def test_version_counters_bump(self):
        from hetu_tpu.ps.van import NativeVan, VanClient, van_available
        if not van_available():
            pytest.skip("no C++ toolchain")
        van = NativeVan()
        port = van.listen()
        versions = np.zeros(16, np.int64)
        van.register_sgd_table(1, np.zeros((16, 2), np.float32),
                               lr=0.1, versions=versions)
        cli = VanClient("127.0.0.1", port, dim=2)
        cli.push(1, np.array([2, 2, 5]), np.ones((3, 2), np.float32))
        # one bump per UNIQUE id per request (python-tier parity)
        assert versions[2] == 1 and versions[5] == 1
        assert versions[0] == 0
        cli.close()
        van.stop()

    def test_concurrent_clients_serialize_on_table_mutex(self):
        from hetu_tpu.ps.van import NativeVan, VanClient, van_available
        if not van_available():
            pytest.skip("no C++ toolchain")
        import threading
        van = NativeVan()
        port = van.listen()
        value = van.register_sgd_table(
            0, np.zeros((128, 4), np.float32), lr=1.0)
        N, per = 4, 50
        ids = np.arange(128)

        def hammer(seed):
            c = VanClient("127.0.0.1", port, dim=4)
            g = np.ones((128, 4), np.float32)
            for _ in range(per):
                c.push(0, ids, g)
            c.close()

        ts = [threading.Thread(target=hammer, args=(i,))
              for i in range(N)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        # every update applied exactly once: value = -N*per
        np.testing.assert_allclose(value, -float(N * per))
        van.stop()


class TestVanServerIntegration:
    """PSServer.serve_van: one table served by BOTH tiers — the python
    PSFunc surface and the C++ van — consistently on the same buffer."""

    def test_both_tiers_update_one_buffer(self):
        from hetu_tpu.ps.server import PSServer
        from hetu_tpu.ps.van import VanClient, van_available
        if not van_available():
            pytest.skip("no C++ toolchain")
        PSServer._instance = None
        srv = PSServer.get()
        srv.param_init("emb", (32, 4), "constant", 0.0, opt="sgd",
                       opt_args={"learning_rate": 1.0})
        port, keymap = srv.serve_van(["emb"])
        try:
            cli = VanClient("127.0.0.1", port, dim=4)
            ids = np.arange(8)
            g = np.ones((8, 4), np.float32)
            cli.push(keymap["emb"], ids, g)          # via the van
            srv.sparse_push("emb", ids, g)           # via python PSFunc
            # both updates landed on the SAME buffer
            got = srv.sparse_pull("emb", ids)
            np.testing.assert_allclose(got, -2.0)
            got_van = cli.pull(keymap["emb"], ids)
            np.testing.assert_allclose(got_van, -2.0)
            # versions bumped by both tiers (HET sync sees van pushes)
            s_ids, _, vers = srv.sync_embedding(
                "emb", ids, np.zeros(8, np.int64), 0)
            assert len(s_ids) == 8 and (vers == 2).all()
            cli.close()
        finally:
            srv.shutdown()
            PSServer._instance = None

    def test_concurrent_tiers_serialize(self):
        from hetu_tpu.ps.server import PSServer
        from hetu_tpu.ps.van import VanClient, van_available
        if not van_available():
            pytest.skip("no C++ toolchain")
        import threading
        PSServer._instance = None
        srv = PSServer.get()
        srv.param_init("t", (64, 4), "constant", 0.0, opt="sgd",
                       opt_args={"learning_rate": 1.0})
        port, keymap = srv.serve_van(["t"])
        try:
            ids = np.arange(64)
            g = np.ones((64, 4), np.float32)
            per = 40

            def via_van():
                c = VanClient("127.0.0.1", port, dim=4)
                for _ in range(per):
                    c.push(keymap["t"], ids, g)
                c.close()

            def via_python():
                for _ in range(per):
                    srv.sparse_push("t", ids, g)

            ts = [threading.Thread(target=via_van),
                  threading.Thread(target=via_python),
                  threading.Thread(target=via_van)]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
            np.testing.assert_allclose(srv.sparse_pull("t", ids),
                                       -float(3 * per))
        finally:
            srv.shutdown()
            PSServer._instance = None

    def test_unservable_table_rejected(self):
        """Tables the van cannot serve (non-2-D) stay python-tier;
        r5 widened the family to include optimizer-less (accumulate)
        2-D tables, so the non-qualifying example is a 1-D vector."""
        from hetu_tpu.ps.server import PSServer
        from hetu_tpu.ps.van import van_available
        if not van_available():
            pytest.skip("no C++ toolchain")
        PSServer._instance = None
        srv = PSServer.get()
        srv.param_init("vec", (8,), "constant", 0.0, opt="sgd",
                       opt_args={"learning_rate": 0.1})
        try:
            with pytest.raises(ValueError):
                srv.serve_van(["vec"])
            # auto-selection simply skips non-qualifying tables
            port, keymap = srv.serve_van()
            assert "vec" not in keymap
        finally:
            srv.shutdown()
            PSServer._instance = None

    def test_adam_table_served_with_shared_step(self):
        """r5: the van applies the FULL server-optimizer family
        (reference server/optimizer.h via zmq_van); an adam table's
        slot state and step counter are SHARED with the python tier."""
        from hetu_tpu.ps.server import PSServer
        from hetu_tpu.ps.van import VanClient, van_available
        if not van_available():
            pytest.skip("no C++ toolchain")
        PSServer._instance = None
        srv = PSServer.get()
        srv.param_init("ad", (8, 2), "constant", 0.0, opt="adam",
                       opt_args={"learning_rate": 0.1})
        try:
            port, keymap = srv.serve_van(["ad"])
            assert "ad" in keymap
            cli = VanClient("127.0.0.1", port, dim=2)
            ids = np.array([1, 3], np.int64)
            cli.push(keymap["ad"], ids, np.ones((2, 2), np.float32))
            p = srv.params["ad"]
            assert int(p.state["t"]) == 1          # van bumped the
            assert float(p.state["m"][1, 0]) != 0  # python-side state
            # python tier continues the SAME trajectory (t -> 2)
            srv.sparse_push("ad", ids, np.ones((2, 2), np.float32))
            assert int(p.state["t"]) == 2
            cli.close()
        finally:
            srv.shutdown()
            PSServer._instance = None


@pytest.mark.parametrize("optname,kw", [
    ("momentum", {"learning_rate": 0.2, "momentum": 0.9}),
    ("nesterov", {"learning_rate": 0.2, "momentum": 0.8}),
    ("adagrad", {"learning_rate": 0.3}),
    ("adam", {"learning_rate": 0.05}),
])
def test_van_optimizer_matches_python_tier(optname, kw):
    """Van-served pushes (dup ids included) must land EXACTLY where the
    python tier's apply_sparse would: same value trajectory, same slot
    state, advanced in the registered (shared) buffers."""
    from hetu_tpu.ps.server import SERVER_OPTIMIZERS
    from hetu_tpu.ps.van import NativeVan, VanClient, van_available
    if not van_available():
        pytest.skip("no C++ toolchain")
    rng = np.random.RandomState(7)
    opt_py = SERVER_OPTIMIZERS[optname](**kw)
    opt_van = SERVER_OPTIMIZERS[optname](**kw)
    value_py = rng.randn(32, 4).astype(np.float32)
    state_py = opt_py.init_state(value_py.shape)
    value_van = value_py.copy()
    state_van = opt_van.init_state(value_van.shape)
    van = NativeVan()
    port = van.listen()
    served = van.register_table(3, value_van, opt_van, state_van)
    cli = VanClient("127.0.0.1", port, dim=4)
    try:
        for _ in range(3):
            ids = np.array([5, 9, 5, 20], np.int64)   # duplicate id
            rows = rng.randn(4, 4).astype(np.float32)
            opt_py.apply_sparse(value_py, ids, rows, state_py)
            cli.push(3, ids, rows)
        np.testing.assert_allclose(served, value_py, rtol=2e-6,
                                   atol=1e-6)
        for k in state_py:          # slot state advanced identically,
            np.testing.assert_allclose(                 # in the shared
                np.asarray(state_van[k]), np.asarray(state_py[k]),
                rtol=2e-6, atol=1e-6)                   # registered arrays
    finally:
        cli.close()
        van.stop()


def test_van_served_keys_refuse_buffer_replacement():
    from hetu_tpu.ps.server import PSServer
    from hetu_tpu.ps.van import van_available
    if not van_available():
        pytest.skip("no C++ toolchain")
    PSServer._instance = None
    srv = PSServer.get()
    srv.param_init("k", (8, 2), "constant", 0.0, opt="sgd",
                   opt_args={"learning_rate": 0.1})
    srv.serve_van(["k"])
    try:
        # r5: a qualifying re-set RE-REGISTERS the van table in place
        # (the executor bridge param_sets on load_dict); the served
        # buffer follows the new value
        srv.param_set("k", np.full((8, 2), 7.0, np.float32), opt="sgd",
                      opt_args={"learning_rate": 0.1})
        np.testing.assert_allclose(
            srv.sparse_pull("k", np.arange(8)), 7.0)
        assert "k" in srv._van_keys
        # a respec the van cannot serve (1-D) stays refused — it would
        # silently detach the fast tier
        with pytest.raises(ValueError):
            srv.param_set("k", np.ones(8, np.float32))
        with pytest.raises(ValueError):
            srv.param_clear("k")
        # the in-place path stays open (checkpoint restore)
        srv.param_assign("k", np.full((8, 2), 3.0, np.float32))
        np.testing.assert_allclose(
            srv.sparse_pull("k", np.arange(8)), 3.0)
    finally:
        srv.shutdown()
        PSServer._instance = None


def test_van_version_dedup_matches_python_tier():
    """[5,5,5] in one push bumps versions[5] ONCE on both tiers (HET
    staleness counters must not diverge by tier)."""
    from hetu_tpu.ps.server import PSServer
    from hetu_tpu.ps.van import VanClient, van_available
    if not van_available():
        pytest.skip("no C++ toolchain")
    PSServer._instance = None
    srv = PSServer.get()
    srv.param_init("vd", (16, 2), "constant", 0.0, opt="sgd",
                   opt_args={"learning_rate": 0.1})
    port, keymap = srv.serve_van(["vd"])
    try:
        cli = VanClient("127.0.0.1", port, dim=2)
        dup = np.array([5, 5, 5, 2])
        cli.push(keymap["vd"], dup, np.ones((4, 2), np.float32))
        srv.sparse_push("vd", dup, np.ones((4, 2), np.float32))
        _, _, vers = srv.sync_embedding("vd", np.array([5, 2]),
                                        np.zeros(2, np.int64), 0)
        assert list(vers) == [2, 2], vers   # one bump per tier each
        cli.close()
    finally:
        srv.shutdown()
        PSServer._instance = None


def test_shutdown_restores_python_locks():
    """PSFunc ops on a formerly-van-served key keep working after
    shutdown (the composite lock is unwound, no dead C++ handle)."""
    from hetu_tpu.ps.server import PSServer
    from hetu_tpu.ps.van import van_available
    if not van_available():
        pytest.skip("no C++ toolchain")
    PSServer._instance = None
    srv = PSServer.get()
    srv.param_init("s", (8, 2), "constant", 1.0, opt="sgd",
                   opt_args={"learning_rate": 0.5})
    srv.serve_van(["s"])
    srv.shutdown()
    # van gone: the python surface still serves the key...
    np.testing.assert_allclose(srv.sparse_pull("s", np.arange(8)), 1.0)
    srv.sparse_push("s", np.array([0]), np.ones((1, 2), np.float32))
    np.testing.assert_allclose(srv.sparse_pull("s", np.array([0])), 0.5)
    # ...and the replace/clear guards lift
    srv.param_set("s", np.zeros((8, 2), np.float32))
    srv.param_clear("s")
    PSServer._instance = None


def test_van_autoserve_and_discovery_over_tcp():
    """The heturun deployment shape: a TCP PSServer with autoserve on —
    tables created by clients over RPC register with the van as they
    appear; workers discover the fast tier via the van_info RPC and
    push through it consistently with the python surface."""
    from hetu_tpu.ps.server import PSServer
    from hetu_tpu.ps.client import PSClient, _TCPTransport
    from hetu_tpu.ps.van import VanClient, van_available
    if not van_available():
        pytest.skip("no C++ toolchain")
    PSServer._instance = None
    PSClient._instance = None
    srv = PSServer.get()
    srv.serve_tcp(23993, block=False)
    vport = srv.enable_van_autoserve()
    try:
        c = PSClient(transport=_TCPTransport("127.0.0.1", 23993))
        # created AFTER autoserve was enabled -> auto-registered
        c.parameter_init("auto", (16, 4), "constant", 0.0, opt="sgd",
                         opt_args={"learning_rate": 1.0})
        # r5: the full optimizer family + accumulate tables autoserve;
        # only shapes the van cannot serve (1-D) stay python-tier
        c.parameter_init("adam_t", (8, 2), "constant", 0.0, opt="adam",
                         opt_args={"learning_rate": 0.1})
        c.parameter_init("vec_t", (8,), "constant", 0.0, opt="sgd",
                         opt_args={"learning_rate": 0.1})
        got_port, keymap = c.t.call("van_info")
        assert got_port == vport
        assert "auto" in keymap and "adam_t" in keymap
        assert "vec_t" not in keymap
        vc = VanClient("127.0.0.1", got_port, dim=4)
        ids = np.arange(8)
        vc.push(keymap["auto"], ids, np.ones((8, 4), np.float32))
        np.testing.assert_allclose(c.sparse_pull("auto", ids), -1.0)
        vc.close()
        c.finalize()
    finally:
        srv.shutdown()
        PSServer._instance = None
        PSClient._instance = None


class TestVanCacheSync:
    """r5: the HET cache verbs ride the C++ tier — sync_embedding is
    van op 4, push_embedding is a push on an accumulate-mode table
    (reference: the hetu_cache protocol served by the C++ PS)."""

    def _server(self):
        from hetu_tpu.ps.server import PSServer
        PSServer._instance = None
        srv = PSServer.get()
        srv.param_init("ct", (16, 4), "constant", 1.0, opt=None)
        return srv

    def test_sync_embedding_parity_with_python_tier(self):
        from hetu_tpu.ps.van import VanClient, van_available
        if not van_available():
            pytest.skip("no C++ toolchain")
        srv = self._server()
        try:
            port, keymap = srv.serve_van(["ct"])
            cli = VanClient("127.0.0.1", port)
            # advance versions on rows 2 and 5 through the van
            cli.push(keymap["ct"], np.array([2, 5, 5]),
                     np.full((3, 4), 0.5, np.float32))
            ids = np.arange(8)
            stored = np.zeros(8, np.int64)
            want = srv.sync_embedding("ct", ids, stored, 0)
            got = cli.sync_embedding(keymap["ct"], ids, stored, 0)
            for w, g in zip(want, got):
                np.testing.assert_array_equal(np.asarray(g),
                                              np.asarray(w))
            # accumulate semantics: duplicate push rows SUMMED onto 1.0
            np.testing.assert_allclose(got[1][got[0] == 5], 2.0)
            np.testing.assert_allclose(got[1][got[0] == 2], 1.5)
            # bound filters rows within staleness tolerance: versions
            # bump once per unique id per REQUEST, so a second push
            # takes row 5 to version 2 while row 2 stays at 1
            cli.push(keymap["ct"], np.array([5]),
                     np.full((1, 4), 0.5, np.float32))
            s_ids, _, _ = cli.sync_embedding(keymap["ct"], ids, stored,
                                             bound=1)
            assert list(s_ids) == [5]
            cli.close()
        finally:
            srv.shutdown()
            from hetu_tpu.ps.server import PSServer
            PSServer._instance = None

    def test_client_routes_cache_verbs_through_van(self):
        """PSClient.sync_embedding/push_embedding reach the C++ tier
        when the table is van-served (cstable's hot verbs)."""
        from hetu_tpu.ps.server import PSServer
        from hetu_tpu.ps.van import van_available
        import hetu_tpu.ps.client as psc
        if not van_available():
            pytest.skip("no C++ toolchain")
        srv = self._server()
        psc.PSClient._instance = None
        try:
            srv.serve_van(["ct"])
            c = psc.PSClient()
            c.push_embedding("ct", np.array([3, 3]),
                             np.ones((2, 4), np.float32))
            st = c._van_local.state
            assert st["cli"] is not None    # the fast tier was used
            s_ids, rows, vers = c.sync_embedding(
                "ct", np.arange(16), np.zeros(16, np.int64), 0)
            assert list(s_ids) == [3]
            np.testing.assert_allclose(rows, 3.0)   # 1 + 2x1 summed
            assert list(vers) == [1]        # one bump per unique push
            c.finalize()
        finally:
            srv.shutdown()
            PSServer._instance = None
            psc.PSClient._instance = None

    def test_cstable_training_over_van_matches_dense(self):
        """Full hybrid+cache training with the table van-autoserved:
        the cstable sync protocol rides the C++ tier and the trajectory
        still equals the dense run."""
        import hetu_tpu as ht
        from hetu_tpu.ps.server import PSServer
        from hetu_tpu.ps.van import van_available
        import hetu_tpu.ps.client as psc
        if not van_available():
            pytest.skip("no C++ toolchain")

        def build():
            ids = ht.placeholder_op("ids")
            y = ht.placeholder_op("y")
            emb = ht.init.random_normal((50, 8), stddev=0.1,
                                        name="emb_vc")
            emb.is_embed = True
            e = ht.array_reshape_op(ht.embedding_lookup_op(emb, ids),
                                    [-1, 16])
            w = ht.init.xavier_uniform((16, 2), name="w_vc")
            loss = ht.reduce_mean_op(ht.softmaxcrossentropy_op(
                ht.matmul_op(e, w), y), axes=0)
            train = ht.optim.SGDOptimizer(
                learning_rate=0.1).minimize(loss)
            return ids, y, loss, train

        rng = np.random.RandomState(0)
        batches = [(rng.randint(0, 50, (16, 2)).astype(np.int32),
                    np.eye(2, dtype=np.float32)[rng.randint(0, 2, 16)])
                   for _ in range(8)]

        PSServer._instance = None
        psc.PSClient._instance = None
        ids, y, loss, train = build()
        ex1 = ht.Executor({"train": [loss, train]})
        w0 = ex1.return_tensor_values()
        base = [float(np.asarray(ex1.run(
            "train", feed_dict={ids: a, y: c})[0])) for a, c in batches]

        PSServer._instance = None
        psc.PSClient._instance = None
        srv = PSServer.get()
        srv.enable_van_autoserve()
        try:
            ids, y, loss, train = build()
            ex2 = ht.Executor({"train": [loss, train]},
                              comm_mode="Hybrid", cstable_policy="LRU",
                              cache_bound=8)
            ex2.load_dict(w0)
            tr = [float(np.asarray(ex2.run(
                "train", feed_dict={ids: a, y: c})[0]))
                for a, c in batches]
            np.testing.assert_allclose(tr, base, atol=1e-5)
            assert "emb_vc" in srv._van_keys
        finally:
            srv.shutdown()
            PSServer._instance = None
            psc.PSClient._instance = None


class TestVanFallbackContract:
    """The client's van fallback rules: reads retry anywhere, pushes
    retry ONLY when the frame never fully left (double-apply safety),
    and late serve_van is discovered within the refresh window."""

    def _pair(self):
        from hetu_tpu.ps.server import PSServer
        import hetu_tpu.ps.client as psc
        self._reset()
        srv = PSServer.get()
        srv.param_init("fb", (8, 2), "constant", 0.0, opt="sgd",
                       opt_args={"learning_rate": 1.0})
        return srv, psc.PSClient()

    def test_send_side_failure_falls_back_without_double_apply(self):
        from hetu_tpu.ps.van import VanTransportError, van_available
        if not van_available():
            pytest.skip("no C++ toolchain")
        srv, c = self._pair()
        try:
            srv.serve_van(["fb"])
            ids = np.array([1], np.int64)
            c.sparse_push("fb", ids, np.ones((1, 2), np.float32))
            st = c._van_local.state
            assert st["cli"] is not None

            # send-side failure: NOT applied -> python tier retries,
            # so the table advances exactly one more step, and the
            # broken van socket is dropped for this thread
            def boom(*a, **kw):
                raise VanTransportError("sim send fail",
                                        maybe_applied=False)
            st["cli"].push = boom
            c.sparse_push("fb", ids, np.ones((1, 2), np.float32))
            np.testing.assert_allclose(
                srv.params["fb"].value[1], -2.0)   # exactly 2 steps
            assert st["cli"] is None and st["dead"]
        finally:
            c.finalize()
            srv.shutdown()
            self._reset()

    def test_response_side_failure_raises_instead_of_double_apply(self):
        from hetu_tpu.ps.van import VanTransportError, van_available
        from hetu_tpu.ps.client import PSConnectionError
        if not van_available():
            pytest.skip("no C++ toolchain")
        srv, c = self._pair()
        try:
            srv.serve_van(["fb"])
            ids = np.array([2], np.int64)
            c.sparse_push("fb", ids, np.ones((1, 2), np.float32))
            st = c._van_local.state
            def boom(*a, **kw):
                raise VanTransportError("sim recv fail",
                                        maybe_applied=True)
            st["cli"].push = boom
            with pytest.raises(PSConnectionError):
                c.sparse_push("fb", ids, np.ones((1, 2), np.float32))
            # the update was NOT silently re-applied python-side
            np.testing.assert_allclose(srv.params["fb"].value[2], -1.0)
        finally:
            c.finalize()
            srv.shutdown()
            self._reset()

    def test_late_serve_van_discovered_after_refresh_window(self):
        """Traffic starts python-tier; serve_van afterwards is picked
        up once the per-thread refresh window elapses."""
        from hetu_tpu.ps.van import van_available
        if not van_available():
            pytest.skip("no C++ toolchain")
        srv, c = self._pair()
        try:
            ids = np.array([0], np.int64)
            c.sparse_push("fb", ids, np.ones((1, 2), np.float32))
            st = c._van_local.state
            assert st["cli"] is None          # python tier so far
            srv.serve_van(["fb"])
            st["checked_at"] = 0.0            # window elapsed
            c.sparse_push("fb", ids, np.ones((1, 2), np.float32))
            assert st["cli"] is not None      # fast tier picked up
            np.testing.assert_allclose(srv.params["fb"].value[0], -2.0)
        finally:
            c.finalize()
            srv.shutdown()
            self._reset()

    @staticmethod
    def _reset():
        from hetu_tpu.ps.server import PSServer
        import hetu_tpu.ps.client as psc
        PSServer._instance = None
        psc.PSClient._instance = None
