"""PS subsystem tests (reference tests/pstests/test_apis.py pattern:
InitTensor/Push/Pull/SparsePush/DDPushPull incl. multi-worker accumulation;
here tier-3 'cluster' = TCP server thread + client connections)."""

import numpy as np
import pytest

from hetu_tpu.ps.server import PSServer
from hetu_tpu.ps.client import PSClient, _TCPTransport, _LocalTransport


@pytest.fixture
def local_client():
    PSServer._instance = None
    PSClient._instance = None
    c = PSClient(transport=_LocalTransport())
    yield c
    PSServer._instance = None


def test_init_push_pull_dense(local_client):
    c = local_client
    assert c.parameter_init("w", (4, 3), "constant", 1.0)
    np.testing.assert_allclose(c.pull("w"), np.ones((4, 3)))
    c.push("w", np.full((4, 3), 0.5))  # no optimizer -> accumulate
    np.testing.assert_allclose(c.pull("w"), 1.5)


def test_server_side_sgd(local_client):
    c = local_client
    c.parameter_init("w2", (3,), "constant", 1.0, opt="sgd",
                     opt_args={"learning_rate": 0.1})
    out = c.dd_pushpull("w2", np.ones(3))
    np.testing.assert_allclose(out, 0.9, rtol=1e-6)


def test_sparse_pushpull_with_server_adam(local_client):
    c = local_client
    c.parameter_init("emb", (10, 4), "constant", 0.0, opt="adam",
                     opt_args={"learning_rate": 0.01})
    ids = np.array([1, 3, 3])
    rows = np.ones((3, 4), np.float32)
    c.sparse_push("emb", ids, rows)
    table = c.pull("emb")
    assert not np.allclose(table[1], 0)
    assert not np.allclose(table[3], 0)
    np.testing.assert_allclose(table[0], 0)
    # duplicate ids merged: row3 got grad 2.0, row1 got 1.0 -> row3 moved
    # at least as much (Adam normalizes, so just check both moved)
    pulled = c.sparse_pull("emb", np.array([1, 3]))
    np.testing.assert_allclose(pulled, table[[1, 3]])


def test_ssp_and_barrier(local_client):
    c = local_client
    c.ssp_init(group=0, bound=1)
    assert c.ssp_sync(group=0) == 1
    assert c.ssp_sync(group=0) == 2  # single worker never blocks


def test_preduce_partner_timeout(local_client):
    # single worker, wait_time elapses -> group of one + a match seq
    members, seq = local_client.preduce_get_partner("k", max_worker=4,
                                                    wait_time=0.05)
    assert members == [0]
    assert seq >= 1


def test_tcp_transport_roundtrip():
    PSServer._instance = None
    server = PSServer.get()
    tcp = server.serve_tcp(23987, block=False)
    try:
        c = PSClient(transport=_TCPTransport("127.0.0.1", 23987))
        c.parameter_init("t", (2, 2), "constant", 2.0)
        np.testing.assert_allclose(c.pull("t"), 2.0)
        fut = c.push("t", np.ones((2, 2)), async_=True)
        c.wait(fut)
        np.testing.assert_allclose(c.pull("t"), 3.0)
        c.finalize()
    finally:
        server.shutdown()
        PSServer._instance = None
        PSClient._instance = None


def test_embedding_version_sync(local_client):
    c = local_client
    c.parameter_init("he", (8, 2), "constant", 0.0)
    c.sparse_push("he", np.array([0, 1]), np.ones((2, 2), np.float32))
    # client cached versions = 0 for rows 0..3; bound=0 -> rows 0,1 stale
    ids, rows, vers = c.sync_embedding("he", np.arange(4), np.zeros(4), 0)
    assert set(ids.tolist()) == {0, 1}
    assert (vers > 0).all()
