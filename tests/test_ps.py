"""PS subsystem tests (reference tests/pstests/test_apis.py pattern:
InitTensor/Push/Pull/SparsePush/DDPushPull incl. multi-worker accumulation;
here tier-3 'cluster' = TCP server thread + client connections)."""

import numpy as np
import pytest

from hetu_tpu.ps.server import PSServer
from hetu_tpu.ps.client import PSClient, _TCPTransport, _LocalTransport

# smoke tier: this module is part of the <3-min verification
# battery (`pytest -m smoke`; ROADMAP tier-1 note)
pytestmark = pytest.mark.smoke


@pytest.fixture
def local_client():
    PSServer._instance = None
    PSClient._instance = None
    c = PSClient(transport=_LocalTransport())
    yield c
    PSServer._instance = None


def test_init_push_pull_dense(local_client):
    c = local_client
    assert c.parameter_init("w", (4, 3), "constant", 1.0)
    np.testing.assert_allclose(c.pull("w"), np.ones((4, 3)))
    c.push("w", np.full((4, 3), 0.5))  # no optimizer -> accumulate
    np.testing.assert_allclose(c.pull("w"), 1.5)


def test_server_side_sgd(local_client):
    c = local_client
    c.parameter_init("w2", (3,), "constant", 1.0, opt="sgd",
                     opt_args={"learning_rate": 0.1})
    out = c.dd_pushpull("w2", np.ones(3))
    np.testing.assert_allclose(out, 0.9, rtol=1e-6)


def test_sparse_pushpull_with_server_adam(local_client):
    c = local_client
    c.parameter_init("emb", (10, 4), "constant", 0.0, opt="adam",
                     opt_args={"learning_rate": 0.01})
    ids = np.array([1, 3, 3])
    rows = np.ones((3, 4), np.float32)
    c.sparse_push("emb", ids, rows)
    table = c.pull("emb")
    assert not np.allclose(table[1], 0)
    assert not np.allclose(table[3], 0)
    np.testing.assert_allclose(table[0], 0)
    # duplicate ids merged: row3 got grad 2.0, row1 got 1.0 -> row3 moved
    # at least as much (Adam normalizes, so just check both moved)
    pulled = c.sparse_pull("emb", np.array([1, 3]))
    np.testing.assert_allclose(pulled, table[[1, 3]])


def test_ssp_and_barrier(local_client):
    c = local_client
    c.ssp_init(group=0, bound=1)
    assert c.ssp_sync(group=0) == 1
    assert c.ssp_sync(group=0) == 2  # single worker never blocks


def test_preduce_partner_timeout(local_client):
    # single worker, wait_time elapses -> group of one + a match seq
    members, seq = local_client.preduce_get_partner("k", max_worker=4,
                                                    wait_time=0.05)
    assert members == [0]
    assert seq >= 1


def test_tcp_transport_roundtrip():
    PSServer._instance = None
    server = PSServer.get()
    tcp = server.serve_tcp(23987, block=False)
    try:
        c = PSClient(transport=_TCPTransport("127.0.0.1", 23987))
        c.parameter_init("t", (2, 2), "constant", 2.0)
        np.testing.assert_allclose(c.pull("t"), 2.0)
        fut = c.push("t", np.ones((2, 2)), async_=True)
        c.wait(fut)
        np.testing.assert_allclose(c.pull("t"), 3.0)
        c.finalize()
    finally:
        server.shutdown()
        PSServer._instance = None
        PSClient._instance = None


def test_embedding_version_sync(local_client):
    c = local_client
    c.parameter_init("he", (8, 2), "constant", 0.0)
    c.sparse_push("he", np.array([0, 1]), np.ones((2, 2), np.float32))
    # client cached versions = 0 for rows 0..3; bound=0 -> rows 0,1 stale
    ids, rows, vers = c.sync_embedding("he", np.arange(4), np.zeros(4), 0)
    assert set(ids.tolist()) == {0, 1}
    assert (vers > 0).all()


# --------------------------------------------------------------------- #
# transport hardening (VERDICT r2 item 7; ps-lite resender.h /
# postoffice.h parity)
# --------------------------------------------------------------------- #

import os
import socket as _socket
import subprocess as _subprocess
import sys as _sys
import time as _time

from hetu_tpu.ps.client import PSConnectionError
from hetu_tpu.ps.server import Scheduler


def _free_port():
    s = _socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


class TestTransportHardening:
    def test_dead_server_raises_not_hangs(self):
        """Request to a port nobody listens on: clean PSConnectionError
        within the retry budget, never a hang."""
        t = _TCPTransport("127.0.0.1", _free_port(), timeout=1.0,
                          connect_timeout=0.5, retries=2)
        t0 = _time.time()
        with pytest.raises(PSConnectionError, match="failed after 2"):
            t.call("pull", "nope")
        assert _time.time() - t0 < 10.0

    def test_server_killed_mid_training_surfaces_cleanly(self, tmp_path):
        """Fault injection: a Hybrid training run whose PS process is
        SIGKILLed mid-step must raise PSConnectionError on the next PS
        round trip (reference failure mode: hang / pickle error)."""
        port = _free_port()
        srv = _subprocess.Popen(
            [_sys.executable, "-m", "hetu_tpu.launcher",
             "--serve-ps", str(port)],
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
        try:
            deadline = _time.time() + 20
            while _time.time() < deadline:
                try:
                    s = _socket.create_connection(("127.0.0.1", port), 0.5)
                    s.close()
                    break
                except OSError:
                    _time.sleep(0.1)
            t = _TCPTransport("127.0.0.1", port, timeout=2.0,
                              connect_timeout=1.0, retries=2)
            c = PSClient(transport=t)
            c.parameter_init("fi_w", (4, 2), "constant", 0.0, opt="sgd",
                             opt_args={"learning_rate": 0.1})
            out = c.sd_pushpull("fi_w", np.array([0, 1]),
                                np.ones((2, 2), np.float32))
            assert out.shape == (2, 2)
            srv.kill()
            srv.wait()
            with pytest.raises(PSConnectionError):
                c.sd_pushpull("fi_w", np.array([0, 1]),
                              np.ones((2, 2), np.float32))
            c.finalize()
        finally:
            if srv.poll() is None:
                srv.kill()
                srv.wait()

    def test_retry_does_not_double_apply(self):
        """Resender parity: a retransmitted request (same client seq,
        e.g. after a lost response) must get the CACHED response replayed
        — the push is applied exactly once."""
        PSServer._instance = None
        server = PSServer.get()
        port = _free_port()
        tcp = server.serve_tcp(port, block=False)
        try:
            t = _TCPTransport("127.0.0.1", port)
            t.call("param_init", "dup_w", (3,), "constant", 1.0)
            t.call("push", "dup_w", np.ones(3, np.float32))
            # simulate the retransmit: rewind the client seq so the next
            # call reuses the seq the server just served
            t._state().seq -= 1
            t.call("push", "dup_w", np.ones(3, np.float32))
            np.testing.assert_allclose(server.pull("dup_w"), 2.0)  # not 3
            t.close()
        finally:
            server.shutdown()
            PSServer._instance = None


class TestScheduler:
    def test_rendezvous_blocks_until_group_complete(self):
        sched = Scheduler()
        port = _free_port()
        sched.serve_tcp(port, block=False)
        try:
            t = _TCPTransport("127.0.0.1", port)
            t.call("register_server", 1, "hostB:1001")
            # incomplete group times out with a clear error
            with pytest.raises(RuntimeError, match="rendezvous"):
                t.call("get_servers", 2, 0.2)
            t.call("register_server", 0, "hostA:1000")
            addrs = t.call("get_servers", 2, 5.0)
            assert addrs == ["hostA:1000", "hostB:1001"]   # index order
            t.close()
        finally:
            sched.shutdown()

    def test_client_resolves_group_via_scheduler(self, monkeypatch):
        """Worker with only HETU_SCHEDULER_ADDR set discovers the server
        and trains against it."""
        sched = Scheduler()
        sport = _free_port()
        sched.serve_tcp(sport, block=False)
        PSServer._instance = None
        server = PSServer.get()
        pport = _free_port()
        tcp = server.serve_tcp(pport, block=False)
        try:
            t = _TCPTransport("127.0.0.1", sport)
            t.call("register_server", 0, f"127.0.0.1:{pport}")
            t.close()
            monkeypatch.delenv("HETU_PS_ADDR", raising=False)
            monkeypatch.delenv("HETU_PS_ADDRS", raising=False)
            monkeypatch.setenv("HETU_SCHEDULER_ADDR", f"127.0.0.1:{sport}")
            monkeypatch.setenv("HETU_PS_NSERVERS", "1")
            PSClient._instance = None
            c = PSClient.get()
            c.parameter_init("sched_w", (2,), "constant", 5.0)
            np.testing.assert_allclose(c.pull("sched_w"), 5.0)
            c.finalize()
        finally:
            sched.shutdown()
            server.shutdown()
            PSServer._instance = None
            PSClient._instance = None


class TestWireCodec:
    """ps/wire.py: the typed no-pickle envelope (VERDICT r2 weak item —
    pickle.loads of network bytes)."""

    def test_roundtrip_envelope(self):
        import numpy as np
        from hetu_tpu.ps import wire

        cases = [
            None, True, False, 0, -7, 1 << 40, 3.5, -0.0, "",
            "uniçode", b"\x00raw", [1, "a", None],
            (2.5, (b"x", [True])), {"k": 1, "n": {"m": [1.0]}},
            np.arange(12, dtype=np.int32).reshape(3, 4),
            np.zeros((0, 5), np.float32),
            np.asarray(2.5, np.float64),          # 0-d array
            ("__req2__", "cid", 3, "push", ("k", np.ones(4, np.float32)),
             {"async_": False}),
        ]
        for obj in cases:
            back = wire.loads(wire.dumps(obj))
            if isinstance(obj, np.ndarray):
                np.testing.assert_array_equal(back, obj)
                assert back.dtype == obj.dtype
            elif isinstance(obj, tuple):
                assert isinstance(back, tuple)
            else:
                assert back == obj, (obj, back)

    def test_numpy_scalar_widening_contract(self):
        """np.bool_ -> bool; numpy int/float scalars widen to
        int64/float64 and come back as Python scalars (documented
        contract; arrays keep their exact dtype)."""
        import numpy as np
        from hetu_tpu.ps import wire
        assert wire.loads(wire.dumps(np.bool_(True))) is True
        assert wire.loads(wire.dumps(np.bool_(False))) is False
        back = wire.loads(wire.dumps(np.int16(-3)))
        assert back == -3 and type(back) is int
        back = wire.loads(wire.dumps(np.float32(0.5)))
        assert back == 0.5 and type(back) is float
        # composed, as a server reply envelope would carry it
        back = wire.loads(wire.dumps({"ok": np.bool_(True),
                                      "n": np.uint8(7)}))
        assert back == {"ok": True, "n": 7}

    def test_rejects_code_objects(self):
        import pytest
        from hetu_tpu.ps import wire
        with pytest.raises(wire.WireError):
            wire.dumps(object())
        with pytest.raises(wire.WireError):
            wire.dumps(lambda: 1)

    def test_rejects_bad_tags(self):
        import pytest
        from hetu_tpu.ps import wire
        with pytest.raises(Exception):
            wire.loads(b"Zjunk")
        with pytest.raises(Exception):
            wire.loads(wire.dumps([1, 2]) + b"extra")

    def test_noncontiguous_and_fortran_arrays(self):
        import numpy as np
        from hetu_tpu.ps import wire
        a = np.arange(24, dtype=np.float32).reshape(4, 6)[:, ::2]
        np.testing.assert_array_equal(wire.loads(wire.dumps(a)), a)
        f = np.asfortranarray(np.arange(6, dtype=np.int64).reshape(2, 3))
        np.testing.assert_array_equal(wire.loads(wire.dumps(f)), f)

    def test_error_contract_is_wireerror(self):
        import pytest
        from hetu_tpu.ps import wire
        # encode: out-of-range int
        with pytest.raises(wire.WireError):
            wire.dumps(1 << 70)
        # decode: truncated frames at various cut points
        good = wire.dumps(("m", [1.5, "x"], {"a": 2}))
        for cut in (1, 3, len(good) - 1):
            with pytest.raises(wire.WireError):
                wire.loads(good[:cut])
