"""Lint fixture: hot-path jit without donation (rule jit-donate)."""
import jax


def decode_step(cache, tok):
    return cache, tok


def prefill_batch(cache, toks):
    return cache, toks


decode = jax.jit(decode_step)                       # missing donation
prefill = jax.jit(prefill_batch, static_argnames=("n",))
