"""Lint fixture: a registry declaration nothing reads (rule
dead-knob).  The ``_reg`` call below is what marks this file as a
registry file to the cross-file sweep."""

REGISTRY = {}


def _reg(name, typ, default, doc):
    REGISTRY[name] = (typ, default, doc)


_reg("HETU_FIXTURE_UNUSED_KNOB", "bool", False, "never read anywhere")
