"""Lint fixture: code every rule must stay quiet on."""
import os

import jax
import jax.numpy as jnp
import numpy as np

from hetu_tpu import envvars

validate = envvars.get_bool("HETU_VALIDATE")
os.environ["HETU_VALIDATE"] = "1"          # writes are launcher business
os.environ.pop("HETU_VALIDATE", None)
other = os.environ.get("XLA_FLAGS", "")    # non-HETU reads untouched


class GoodOp:
    def compute(self, input_vals, tc):
        n = np.prod((2, 3))                # static metadata helper: fine
        return jnp.tanh(input_vals[0]) * n


def step_fn(params, x):
    return params, x


step = jax.jit(step_fn, donate_argnums=(0,))
host_stamp = __import__("time").time       # outside any trace scope
