"""Lint fixture: bare threading lock construction (rule raw-lock)."""
import threading

_mu = threading.Lock()
_rmu = threading.RLock()
_cv = threading.Condition(_mu)
