"""Lint fixture: trips the ``event-emit`` rule — JSONL event emission
outside hetu_tpu/telemetry/ (the pre-subsystem pattern every emitter
used; telemetry.emit() is the one pipeline now)."""

import json


def log_event(path, kind, **fields):
    rec = {"t": 0.0, "event": kind, **fields}
    with open(path, "a") as f:
        f.write(json.dumps(rec) + "\n")    # <- finding: event-emit
