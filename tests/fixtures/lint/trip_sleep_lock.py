"""Lint fixture: time.sleep inside a critical section (rule
sleep-under-lock)."""

import time

from hetu_tpu import locks


class Poller:
    def __init__(self):
        self._mu = locks.TracedLock("fixture.poller")

    def poll(self):
        with self._mu:
            time.sleep(0.5)
