"""Lint fixture: raw HETU_* environment reads (rule env-registry)."""
import os

mode = os.environ.get("HETU_SOME_KNOB", "0")
addr = os.environ["HETU_OTHER_KNOB"]
also = os.getenv("HETU_THIRD_KNOB")
