"""Lint fixture: host numpy call inside Op.compute (rule np-in-compute)."""
import numpy as np


class BadHostOp:
    def compute(self, input_vals, tc):
        x = np.asarray(input_vals[0])      # forces host materialization
        return x

    def jax_fn(self, x):
        return np.clip(x, 0, 1)            # host call in the trace body
