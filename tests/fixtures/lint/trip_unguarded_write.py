"""Lint fixture: public method mutates lock-protected state without
the lock (rule unguarded-shared-write)."""

from hetu_tpu import locks


class Counter:
    def __init__(self):
        self._mu = locks.TracedLock("fixture.counter")
        self._n = 0

    def bump(self):
        with self._mu:
            self._n += 1

    def reset(self):
        self._n = 0     # guarded everywhere else: the rule fires here
