"""Lint fixture: wall clock + host RNG inside jitted code (time-in-jit)."""
import time

import jax
import numpy as np


class BadClockOp:
    def compute(self, input_vals, tc):
        stamp = time.time()                # freezes at trace time
        np.random.seed(0)                  # host RNG state in the trace
        return input_vals[0] * stamp


@jax.jit
def decorated(x):
    return x + time.perf_counter()


def passed_by_name(x):
    return x * time.monotonic()


fn = jax.jit(passed_by_name, donate_argnums=(0,))
