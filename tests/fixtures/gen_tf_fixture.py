"""Generate the TF-side ONNX fixture (reference
tests/onnx/cnn_hetu_onnx_tf.py round-trips hetu<->TF through ONNX).

Builds a small Keras CNN, runs a REAL TensorFlow forward pass on a fixed
input, and serializes the network to ONNX with tf2onnx's structural
conventions — the graph takes the NHWC input TF models use, transposes
to NCHW for Conv/Pool (ONNX's only layout), and transposes back before
the NHWC flatten so the Dense weights keep TF's H*W*C ordering.  The
ONNX bytes come from hetu_tpu's own self-contained proto writer (no
tf2onnx/onnx wheels in the image; zero egress).

Run:  python tests/fixtures/gen_tf_fixture.py
Writes: tf_cnn.onnx, tf_cnn_input.npy, tf_cnn_output.npy
(the checked-in fixtures tests/test_onnx.py's TF parity tests consume;
tf_cnn_output.npy is TensorFlow's OWN forward output, so the test
asserts parity against TF execution, not against our importer).
"""

import os

import numpy as np

HERE = os.path.dirname(os.path.abspath(__file__))


def build_and_run_tf(seed=7):
    import tensorflow as tf
    tf.keras.utils.set_random_seed(seed)
    model = tf.keras.Sequential([
        tf.keras.layers.Input(shape=(8, 8, 3)),
        tf.keras.layers.Conv2D(4, 3, padding="same", activation="relu",
                               name="conv"),
        tf.keras.layers.MaxPool2D(2, name="pool"),
        tf.keras.layers.Flatten(name="flatten"),
        tf.keras.layers.Dense(10, name="dense"),
    ])
    rng = np.random.RandomState(seed)
    x = rng.randn(4, 8, 8, 3).astype(np.float32)
    y = model(x, training=False).numpy()
    return model, x, y


def export_tf2onnx_style(model, path):
    """tf2onnx-shaped graph: NHWC input, Transpose->NCHW around
    Conv/Pool, Transpose->NHWC before the flatten Reshape, Gemm-free
    MatMul+Add dense (tf2onnx emits MatMul/Add for Keras Dense)."""
    from hetu_tpu.onnx import proto as P

    conv_w, conv_b = [w.numpy() for w in model.get_layer("conv").weights]
    dense_w, dense_b = [w.numpy()
                        for w in model.get_layer("dense").weights]
    # TF conv kernels are HWIO; ONNX Conv wants OIHW
    conv_w_onnx = conv_w.transpose(3, 2, 0, 1).copy()

    nodes = [
        P.NodeProto(op_type="Transpose", name="to_nchw",
                    input=["x"], output=["x_nchw"],
                    attribute=[P.attr("perm", [0, 3, 1, 2])]),
        P.NodeProto(op_type="Conv",
                    name="StatefulPartitionedCall/model/conv/Conv2D",
                    input=["x_nchw", "conv/kernel:0", "conv/bias:0"],
                    output=["conv_out"],
                    attribute=[P.attr("kernel_shape", [3, 3]),
                               P.attr("pads", [1, 1, 1, 1]),
                               P.attr("strides", [1, 1])]),
        P.NodeProto(op_type="Relu",
                    name="StatefulPartitionedCall/model/conv/Relu",
                    input=["conv_out"], output=["relu_out"]),
        P.NodeProto(op_type="MaxPool",
                    name="StatefulPartitionedCall/model/pool/MaxPool",
                    input=["relu_out"], output=["pool_out"],
                    attribute=[P.attr("kernel_shape", [2, 2]),
                               P.attr("strides", [2, 2])]),
        # back to NHWC so the flatten matches TF's memory order — the
        # structural signature of a tf2onnx export
        P.NodeProto(op_type="Transpose", name="to_nhwc",
                    input=["pool_out"], output=["pool_nhwc"],
                    attribute=[P.attr("perm", [0, 2, 3, 1])]),
        P.NodeProto(op_type="Reshape",
                    name="StatefulPartitionedCall/model/flatten/Reshape",
                    input=["pool_nhwc", "flatten_shape"],
                    output=["flat"]),
        P.NodeProto(op_type="MatMul",
                    name="StatefulPartitionedCall/model/dense/MatMul",
                    input=["flat", "dense/kernel:0"],
                    output=["dense_mm"]),
        P.NodeProto(op_type="Add",
                    name="StatefulPartitionedCall/model/dense/BiasAdd",
                    input=["dense_mm", "dense/bias:0"],
                    output=["logits"]),
    ]
    inits = [
        P.tensor_from_numpy(conv_w_onnx, "conv/kernel:0"),
        P.tensor_from_numpy(conv_b, "conv/bias:0"),
        P.tensor_from_numpy(np.array([-1, 64], np.int64),
                            "flatten_shape"),
        P.tensor_from_numpy(dense_w, "dense/kernel:0"),
        P.tensor_from_numpy(dense_b, "dense/bias:0"),
    ]
    graph = P.GraphProto(
        name="tf_cnn", node=nodes, initializer=inits,
        input=[P.value_info("x", (4, 8, 8, 3))],
        output=[P.value_info("logits", (4, 10))])
    model_p = P.ModelProto(
        ir_version=8, producer_name="tf2onnx-style (hetu_tpu writer)",
        graph=graph,
        opset_import=[P.OperatorSetIdProto(domain="", version=13)])
    P.save_model(model_p, path)


def main():
    model, x, y = build_and_run_tf()
    export_tf2onnx_style(model, os.path.join(HERE, "tf_cnn.onnx"))
    np.save(os.path.join(HERE, "tf_cnn_input.npy"), x)
    np.save(os.path.join(HERE, "tf_cnn_output.npy"), y)
    print("fixture written; TF output head:", y[0, :4])


if __name__ == "__main__":
    main()
