"""Real-dataset parsers (VERDICT r2 item 9): reference-format local files
must parse into the model contracts (reference
examples/ctr/models/load_data.py, examples/rec/movielens.py).  Fixtures
are tiny files written in the exact on-disk formats."""

import os

import numpy as np
import pytest

import hetu_tpu as ht
from hetu_tpu.data import (
    load_criteo, load_adult, load_movielens, WDL_ADULT_WIDE_DIM,
)


def _write_criteo_txt(path, n=20, seed=0):
    """train.txt: tab-separated label, 13 int dense, 26 hex cats, some
    fields empty (criteo has many missing values)."""
    rng = np.random.RandomState(seed)
    with open(os.path.join(path, "train.txt"), "w") as f:
        for i in range(n):
            label = rng.randint(0, 2)
            dense = [("" if rng.rand() < 0.2 else str(rng.randint(0, 100)))
                     for _ in range(13)]
            cats = [("" if rng.rand() < 0.1 else
                     format(rng.randint(0, 8), "08x"))
                    for _ in range(26)]
            f.write("\t".join([str(label)] + dense + cats) + "\n")


class TestCriteo:
    def test_raw_txt_parses(self, tmp_path):
        _write_criteo_txt(str(tmp_path))
        dense, sparse, labels = load_criteo(str(tmp_path))
        assert dense.shape == (20, 13) and dense.dtype == np.float32
        assert sparse.shape == (20, 26) and sparse.dtype == np.int32
        assert labels.shape == (20, 1)
        # log(x+1) transform: all finite, nonneg for the >= 0 inputs
        assert np.isfinite(dense).all()
        # cumulative per-column offsets: ids strictly grouped by column
        for j in range(25):
            assert sparse[:, j].max() < sparse[:, j + 1].min() or \
                sparse[:, j + 1].size == 0

    def test_preprocessed_npy_roundtrip(self, tmp_path):
        _write_criteo_txt(str(tmp_path))
        dense, sparse, labels = load_criteo(str(tmp_path))
        np.save(tmp_path / "train_dense_feats.npy", dense)
        np.save(tmp_path / "train_sparse_feats.npy", sparse)
        np.save(tmp_path / "train_labels.npy", labels)
        d2, s2, l2 = load_criteo(str(tmp_path))   # .npy takes precedence
        np.testing.assert_array_equal(d2, dense)
        np.testing.assert_array_equal(s2, sparse)

    def test_missing_raises(self, tmp_path):
        with pytest.raises(FileNotFoundError):
            load_criteo(str(tmp_path / "nope"))

    def test_trains_wdl_criteo(self, tmp_path):
        """Parsed fixture drives the actual CTR model one step."""
        from hetu_tpu.models import ctr as ctr_models
        _write_criteo_txt(str(tmp_path), n=16)
        dense, sparse, labels = load_criteo(str(tmp_path))
        feature_dim = int(sparse.max()) + 1
        d = ht.placeholder_op("cd")
        s = ht.placeholder_op("cs")
        y = ht.placeholder_op("cy")
        loss, pred, _lab, train = ctr_models.wdl_criteo(
            d, s, y, feature_dimension=feature_dim, embedding_size=4)
        ex = ht.Executor({"train": [loss, train]})
        y2 = np.concatenate([1 - labels, labels], axis=1).astype(np.float32)
        out = ex.run("train", feed_dict={d: dense, s: sparse, y: y2})
        assert np.isfinite(float(np.asarray(out[0]).reshape(-1)[0]))


class TestAdult:
    _ROW = ("39, State-gov, 77516, Bachelors, 13, Never-married, "
            "Adm-clerical, Not-in-family, White, Male, 2174, 0, 40, "
            "United-States, <=50K")
    _ROW2 = ("50, Self-emp-not-inc, 83311, Bachelors, 13, "
             "Married-civ-spouse, Exec-managerial, Husband, White, Male, "
             "0, 0, 13, United-States, >50K")

    def test_parses_to_wdl_contract(self, tmp_path):
        with open(tmp_path / "train.csv", "w") as f:
            for _ in range(4):
                f.write(self._ROW + "\n")
                f.write(self._ROW2 + "\n")
        x_deep, x_wide, y = load_adult(str(tmp_path))
        assert x_deep.shape == (8, 12)
        assert x_wide.shape == (8, WDL_ADULT_WIDE_DIM)
        assert y.shape == (8, 2)
        # labels: alternating <=50K / >50K
        np.testing.assert_array_equal(y[:, 1], [0, 1] * 4)
        # embedding ids stay inside wdl_adult's [50, 8] tables
        assert x_deep[:, :8].max() < 50

    def test_trains_wdl_adult(self, tmp_path):
        from hetu_tpu.models import ctr as ctr_models
        with open(tmp_path / "train.csv", "w") as f:
            for _ in range(8):
                f.write(self._ROW + "\n")
                f.write(self._ROW2 + "\n")
        x_deep, x_wide, y = load_adult(str(tmp_path))
        X_deep = [ht.placeholder_op(f"ad{i}") for i in range(12)]
        X_wide = ht.placeholder_op("aw")
        y_ = ht.placeholder_op("ay")
        loss, pred, _lab, train = ctr_models.wdl_adult(X_deep, X_wide, y_)
        ex = ht.Executor({"train": [loss, train]})
        feeds = {X_wide: x_wide, y_: y}
        for i in range(8):
            feeds[X_deep[i]] = x_deep[:, i].astype(np.int32)
        for i in range(8, 12):
            feeds[X_deep[i]] = x_deep[:, i].astype(np.float32)
        out = ex.run("train", feed_dict=feeds)
        assert np.isfinite(float(np.asarray(out[0]).reshape(-1)[0]))


class TestMovielens:
    def test_ratings_csv(self, tmp_path):
        with open(tmp_path / "ratings.csv", "w") as f:
            f.write("userId,movieId,rating,timestamp\n")
            # user 1: items 10, 20 (20 is latest -> held out)
            f.write("1,10,4.0,100\n")
            f.write("1,20,5.0,200\n")
            # user 2: items 10, 30
            f.write("2,30,3.0,50\n")
            f.write("2,10,4.5,400\n")
        u, it, lab, nu, ni = load_movielens(str(tmp_path),
                                            num_negatives=1)
        assert nu == 2 and ni == 3
        # 2 training positives (one per user; latest held out), each
        # with 1 negative
        assert len(u) == 4
        assert lab.sum() == 2.0
        # negatives never collide with the user's seen set
        seen = {0: {0, 1}, 1: {2, 0}}
        for uu, ii, ll in zip(u, it, lab):
            if ll == 0.0:
                assert ii not in seen[int(uu)]

    def test_ratings_dat_ml1m(self, tmp_path):
        with open(tmp_path / "ratings.dat", "w") as f:
            f.write("1::1193::5::978300760\n")
            f.write("1::661::3::978302109\n")
            f.write("2::1193::4::978301968\n")
        u, it, lab, nu, ni = load_movielens(str(tmp_path),
                                            num_negatives=0)
        assert nu == 2 and ni == 2
        assert len(u) == 1          # one non-held-out positive

    def test_trains_ncf(self, tmp_path):
        from hetu_tpu.models.ncf import neural_mf
        rng = np.random.RandomState(0)
        with open(tmp_path / "ratings.csv", "w") as f:
            f.write("userId,movieId,rating,timestamp\n")
            for u in range(1, 9):
                for i in rng.choice(30, 6, replace=False):
                    f.write(f"{u},{i+1},4.0,{rng.randint(1000)}\n")
        users, items, labels, nu, ni = load_movielens(str(tmp_path))
        up = ht.placeholder_op("mu")
        ip = ht.placeholder_op("mi")
        yp = ht.placeholder_op("my")
        loss, pred, train = neural_mf(up, ip, yp, num_users=nu,
                                      num_items=ni)
        ex = ht.Executor({"train": [loss, train]})
        out = ex.run("train", feed_dict={
            up: users, ip: items,
            yp: labels.reshape(-1, 1)})
        assert np.isfinite(float(np.asarray(out[0]).reshape(-1)[0]))
