"""Live weight sync (ISSUE 15 tentpole): zero-downtime rolling weight
swaps across the serving fleet.

The acceptance spine: a v1 -> v2 rollout over a 2-replica fleet while a
request trace is in flight loses ZERO requests, every Result is
token-identical to offline ``generate_fast`` under the EXACT param
version it was admitted on (``Result.weight_version``), and the fleet
lands on v2.  Chaos (``HETU_CHAOS role=swap``) kills a replica
mid-drain or mid-swap: the rollout fails, already-swapped survivors
roll back to the committed version, the corpse respawns ON the
committed version, requests still retire exactly once, and the flight
recorder holds the swap timeline.  Around it: stale/corrupt version
push rejection, the PS torn-read-guarded ``pull_versioned`` handoff,
the engine-level ``swap_params`` contract (shape/key-set validation,
no recompile), the ``hetu_trace --check`` version-coherence rule, and
the ``hetu_top --fleet`` version column + rollout footer.

All CPU-harness, all smoke-tier (tiny random-weight GPTs — the
contract under test is swap orchestration, not model quality).
"""

import time

import numpy as np
import pytest

import hetu_tpu as ht  # noqa: F401  (platform forcing + compat shims)
from hetu_tpu import telemetry
from hetu_tpu.models import GPTConfig
from hetu_tpu.models.gpt_decode import generate_fast
from hetu_tpu.ps import faults
from hetu_tpu.ps.server import PSServer
from hetu_tpu.ps.sharded import ShardedPSClient
from hetu_tpu.serving import (
    Request, ServingEngine, ServingRouter, WeightSyncCoordinator,
)
from hetu_tpu.telemetry import top
from hetu_tpu.telemetry.trace import (
    check_span_balance, check_version_coherence, read_events,
)

pytestmark = pytest.mark.smoke


def _rand_gpt(name="ws", L=1, H=2, Dh=8, V=61, S=32, seed=0):
    """Deterministic random params in generate_fast's naming contract."""
    rng = np.random.RandomState(seed)
    hd = H * Dh
    p = {f"{name}_wte_table": rng.randn(V, hd) * 0.05,
         f"{name}_wpe": rng.randn(S, hd) * 0.05,
         f"{name}_ln_f_scale": np.ones(hd),
         f"{name}_ln_f_bias": np.zeros(hd)}
    for i in range(L):
        us = f"{name}_h{i}"
        for w, shp in [("attn_q", (hd, hd)), ("attn_k", (hd, hd)),
                       ("attn_v", (hd, hd)), ("attn_proj", (hd, hd)),
                       ("ffn_wi", (hd, 4 * hd)), ("ffn_wo", (4 * hd, hd))]:
            p[f"{us}_{w}_weight"] = rng.randn(*shp) * 0.05
            p[f"{us}_{w}_bias"] = np.zeros(shp[1])
        for ln in ("ln1", "ln2"):
            p[f"{us}_{ln}_scale"] = np.ones(hd)
            p[f"{us}_{ln}_bias"] = np.zeros(hd)
    cfg = GPTConfig(vocab_size=V, hidden_size=hd, num_hidden_layers=L,
                    num_attention_heads=H, max_position_embeddings=S,
                    batch_size=1, seq_len=S, dropout_rate=0.0)
    return p, cfg


@pytest.fixture(scope="module")
def model():
    # v1 and v2 share shapes/keys but not values: a swap visibly
    # changes greedy outputs, so token-identity pins the version
    p1, cfg = _rand_gpt(seed=0)
    p2, _ = _rand_gpt(seed=1)
    return p1, p2, cfg


@pytest.fixture(autouse=True)
def _fresh(monkeypatch):
    monkeypatch.setenv("HETU_TELEMETRY", "1")
    monkeypatch.delenv("HETU_CHAOS", raising=False)
    faults.reset_plans()
    telemetry.reset()
    yield
    faults.reset_plans()
    telemetry.reset()


def _fleet(model, **kw):
    p1, _, cfg = model
    kw.setdefault("slots", 2)
    kw.setdefault("queue_limit", 16)
    kw.setdefault("fast_path", False)
    router = ServingRouter(lambda i: ServingEngine(p1, cfg, **kw),
                           replicas=2, restart_backoff=0.01)
    return router, WeightSyncCoordinator(router, p1, version=1)


def _trace(n=10, seed=7, vocab=61):
    rng = np.random.RandomState(seed)
    return [Request(prompt=[int(t) for t in
                            rng.randint(0, vocab, int(rng.randint(1, 5)))],
                    max_new_tokens=int(rng.randint(3, 9)))
            for _ in range(n)]


def _offline(params, cfg, req):
    return generate_fast(params, cfg, [req.prompt],
                         num_tokens=req.max_new_tokens)[0].tolist()


def _wait_respawn(router, coord, n=2, budget=5.0):
    deadline = time.time() + budget
    while len(coord.fleet_versions()) < n and time.time() < deadline:
        router.step()
        time.sleep(0.005)
    return coord.fleet_versions()


# --------------------------------------------------------------------- #
# engine-level swap contract
# --------------------------------------------------------------------- #

class TestEngineSwap:
    def test_swap_changes_outputs_and_stamps_version(self, model):
        """swap_params rebinds the param dict between steps (no
        recompile): the SAME request decodes v1 tokens before the swap
        and v2 tokens after, and each Result carries the version it was
        admitted on."""
        p1, p2, cfg = model
        eng = ServingEngine(p1, cfg, slots=2, fast_path=False)
        eng.set_weight_version(1)
        req = Request(prompt=[5, 6, 7], max_new_tokens=5)
        r1 = next(iter(eng.run([req]).values()))
        assert r1.weight_version == 1
        assert r1.tokens.tolist() == _offline(p1, cfg, req)
        eng.swap_params(p2, version=2)
        assert eng.weight_version == 2
        assert eng.last_swap_at is not None
        req2 = Request(prompt=[5, 6, 7], max_new_tokens=5)
        r2 = next(iter(eng.run([req2]).values()))
        assert r2.weight_version == 2
        assert r2.tokens.tolist() == _offline(p2, cfg, req2)
        assert r2.tokens.tolist() != r1.tokens.tolist()

    def test_swap_rejects_shape_and_key_mismatch(self, model):
        """A corrupt pytree (wrong shape, missing/extra keys) must fail
        the validation BEFORE any resident buffer moves."""
        p1, p2, cfg = model
        eng = ServingEngine(p1, cfg, slots=2, fast_path=False)
        bad_shape = dict(p2)
        bad_shape["ws_wpe"] = np.zeros((3, 3))
        with pytest.raises(ValueError, match="shape"):
            eng.swap_params(bad_shape, version=2)
        missing = {k: v for k, v in p2.items() if k != "ws_wpe"}
        with pytest.raises(ValueError):
            eng.swap_params(missing, version=2)
        # the failed swaps left v1 resident and the version unchanged
        req = Request(prompt=[1, 2], max_new_tokens=4)
        r = next(iter(eng.run([req]).values()))
        assert r.tokens.tolist() == _offline(p1, cfg, req)


# --------------------------------------------------------------------- #
# the rolling swap (happy path)
# --------------------------------------------------------------------- #

class TestRollingSwap:
    def test_zero_loss_token_identity_and_trace_rules(
            self, model, tmp_path, monkeypatch):
        """A v1 -> v2 rollout mid-trace: every request retires exactly
        once, token-identical to offline under ITS OWN admission
        version; the fleet lands on v2; the serve stream passes both
        the span-balance and version-coherence checks."""
        slog = str(tmp_path / "serve.jsonl")
        monkeypatch.setenv("HETU_SERVE_LOG", slog)
        p1, p2, cfg = model
        router, coord = _fleet(model)
        reqs = _trace(12, seed=7)
        assert coord.begin(p2, 2)
        res = router.run(reqs)
        coord.drain()
        assert coord.state == "done"
        assert coord.committed_version == 2
        assert coord.fleet_versions() == {0: 2, 1: 2}
        assert len(res) == len(reqs)
        snap = router.snapshot()
        assert snap["lost"] == 0 and snap["duplicates"] == 0
        by_ver = {1: p1, 2: p2}
        seen = set()
        for r in reqs:
            out = res[r.request_id]
            assert out.weight_version in by_ver, out
            seen.add(out.weight_version)
            assert out.tokens.tolist() == \
                _offline(by_ver[out.weight_version], cfg, r), r.request_id
        assert 1 in seen   # the trace was live across the swap
        events, bad = read_events([slog])
        assert bad == 0
        assert check_span_balance(events) == []
        assert check_version_coherence(events) == []
        kinds = [e["event"] for e in events]
        for k in ("rollout_start", "swap_quiesce", "swap_drained",
                  "weight_swap", "swap_probe", "swap_readmit",
                  "rollout_done"):
            assert k in kinds, k
        for e in events:
            assert telemetry.validate_record(e) == [], e
        # router snapshot surfaces the sync state
        ws = snap["weight_sync"]
        assert ws["committed_version"] == 2
        assert ws["last"]["state"] == "done"

    def test_stale_version_rejected(self, model, tmp_path, monkeypatch):
        """Pushing a version <= committed is refused up front: no
        quiesce, no swap, a contract-valid swap_rejected_stale event."""
        flg = str(tmp_path / "failure.jsonl")
        monkeypatch.setenv("HETU_FAILURE_LOG", flg)
        p1, p2, cfg = model
        router, coord = _fleet(model)
        assert not coord.begin(p2, 1)        # same version: stale
        assert coord.state == "rejected_stale"
        assert coord.fleet_versions() == {0: 1, 1: 1}
        assert router._swap_hold == set()
        events, bad = read_events([flg])
        assert bad == 0
        assert any(e["event"] == "swap_rejected_stale" for e in events)
        for e in events:
            assert telemetry.validate_record(e) == [], e
        # and a fresh, HIGHER version still goes through afterwards
        assert coord.begin(p2, 2)
        router.run(_trace(4, seed=3))
        coord.drain()
        assert coord.state == "done"

    def test_corrupt_version_push_rejected(self, model, tmp_path,
                                           monkeypatch):
        """The chaos seam at swap.version_push (drop = a corrupt/torn
        version read) rejects the rollout before any replica moves."""
        flg = str(tmp_path / "failure.jsonl")
        monkeypatch.setenv("HETU_FAILURE_LOG", flg)
        monkeypatch.setenv("HETU_CHAOS", "seed=1,drop=1.0,role=swap")
        faults.reset_plans()
        _, p2, _ = model
        router, coord = _fleet(model)
        assert not coord.begin(p2, 2)
        assert coord.state == "rejected_stale"
        assert coord.fleet_versions() == {0: 1, 1: 1}
        events, _ = read_events([flg])
        assert any(e["event"] == "swap_rejected_stale" for e in events)


# --------------------------------------------------------------------- #
# chaos: mid-swap kills + rollback
# --------------------------------------------------------------------- #
#
# role=swap draw order (ps/faults.py): draw 1 = swap.version_push, then
# per replica in rollout order: swap.drain, swap.apply.  So kill=2 hits
# replica 0 mid-drain, kill=3 replica 0 mid-swap (buffers moved, probe
# pending), kill=4 replica 1 mid-drain AFTER replica 0 swapped — the
# real-rollback case.

class TestChaosSwap:
    @pytest.mark.parametrize("spec,label", [
        ("seed=5,kill=2,role=swap", "mid-drain"),
        ("seed=5,kill=3,role=swap", "mid-swap"),
    ])
    def test_kill_fails_rollout_cleanly(self, model, tmp_path,
                                        monkeypatch, spec, label):
        """A seeded kill of the quiesced replica mid-drain/mid-swap:
        zero request loss (the router requeues the corpse's work the
        same step), the rollout fails, the fleet converges back to the
        COMMITTED v1 (the corpse respawns on it), and the flight
        recorder holds the chaos kill + the swap timeline."""
        flog = str(tmp_path / "flight.jsonl")
        slog = str(tmp_path / "serve.jsonl")
        flg = str(tmp_path / "failure.jsonl")
        monkeypatch.setenv("HETU_FLIGHT_LOG", flog)
        monkeypatch.setenv("HETU_SERVE_LOG", slog)
        monkeypatch.setenv("HETU_FAILURE_LOG", flg)
        monkeypatch.setenv("HETU_CHAOS", spec)
        faults.reset_plans()
        p1, p2, cfg = model
        router, coord = _fleet(model)
        reqs = _trace(10, seed=11)
        assert coord.begin(p2, 2)
        res = router.run(reqs)
        coord.drain()
        assert len(res) == len(reqs), label
        assert coord.state == "rolled_back", (label, coord.last)
        assert coord.committed_version == 1
        assert _wait_respawn(router, coord) == {0: 1, 1: 1}, label
        snap = router.snapshot()
        assert snap["lost"] == 0 and snap["duplicates"] == 0
        # every retired result decoded on v1 (v2 never served traffic)
        for r in reqs:
            out = res[r.request_id]
            assert out.weight_version == 1, (label, out)
            assert out.tokens.tolist() == _offline(p1, cfg, r)
        fevents, fbad = read_events([flog])
        assert fbad == 0
        reasons = [e["reason"] for e in fevents
                   if e["event"] == "flight_dump"]
        assert "swap_chaos_kill" in reasons
        assert "swap_rollout_failed" in reasons
        events, bad = read_events([slog, flg])
        assert bad == 0
        assert any(e["event"] == "rollout_failed" for e in events)
        assert check_version_coherence(events) == []
        assert check_span_balance(events) == []
        # chaos kills are one-shot: the SAME process retries and lands
        assert coord.begin(p2, 2)
        res2 = router.run(_trace(6, seed=111))
        coord.drain()
        assert len(res2) == 6 and coord.state == "done"
        assert coord.fleet_versions() == {0: 2, 1: 2}

    def test_kill_after_first_swap_rolls_survivor_back(
            self, model, tmp_path, monkeypatch):
        """kill=4 fires at replica 1's drain AFTER replica 0 already
        swapped to v2: the failure path must roll the v2 survivor back
        to v1 (a mixed-version fleet never serves steady-state)."""
        flg = str(tmp_path / "failure.jsonl")
        slog = str(tmp_path / "serve.jsonl")
        monkeypatch.setenv("HETU_SERVE_LOG", slog)
        monkeypatch.setenv("HETU_FAILURE_LOG", flg)
        monkeypatch.setenv("HETU_CHAOS", "seed=5,kill=4,role=swap")
        faults.reset_plans()
        _, p2, _ = model
        router, coord = _fleet(model)
        reqs = _trace(10, seed=13)
        assert coord.begin(p2, 2)
        res = router.run(reqs)
        coord.drain()
        assert len(res) == len(reqs)
        assert coord.state == "rolled_back", coord.last
        assert _wait_respawn(router, coord) == {0: 1, 1: 1}
        events, bad = read_events([slog, flg])
        assert bad == 0
        kinds = [e["event"] for e in events]
        assert "rollout_rollback" in kinds   # the non-vacuous path
        assert "rollout_failed" in kinds
        assert check_version_coherence(events) == []


# --------------------------------------------------------------------- #
# PS handoff + observability
# --------------------------------------------------------------------- #

class TestPSVersionedPull:
    def test_begin_from_ps_rolls_the_stamped_version(self, model):
        """Weights pushed to a sharded PS + set_weights_version feed a
        rollout via the torn-read-guarded pull_versioned snapshot."""
        p1, p2, cfg = model
        ps = ShardedPSClient(servers=[PSServer(), PSServer()])
        for k, v in p2.items():
            ps.param_set(k, np.asarray(v, np.float32))
        ps.set_weights_version(2)
        assert ps.weights_version() == 2
        router, coord = _fleet(model)
        assert coord.begin_from_ps(ps, sorted(p2))
        res = router.run(_trace(6, seed=17))
        coord.drain()
        assert len(res) == 6 and coord.state == "done"
        assert coord.fleet_versions() == {0: 2, 1: 2}
        # the pulled pytree really is v2: post-swap decode matches it
        req = Request(prompt=[9, 10], max_new_tokens=5)
        out = next(iter(router.run([req]).values()))
        assert out.weight_version == 2
        assert out.tokens.tolist() == _offline(p2, cfg, req)

    def test_unstamped_ps_refused(self, model):
        """A PS that was never version-stamped cannot feed a rollout —
        there is no commit point to roll back to."""
        _, p2, _ = model
        ps = ShardedPSClient(servers=[PSServer()])
        for k, v in p2.items():
            ps.param_set(k, np.asarray(v, np.float32))
        router, coord = _fleet(model)
        with pytest.raises(ValueError, match="version"):
            coord.begin_from_ps(ps, sorted(p2))


class TestTopAndTrace:
    def test_fleet_top_version_column_and_rollout_footer(
            self, model, tmp_path, monkeypatch, capsys):
        """hetu_top --fleet shows each replica's weight version and the
        rollout progress footer; the single-engine view shows the
        version + last-swap time."""
        slog = str(tmp_path / "serve.jsonl")
        monkeypatch.setenv("HETU_SERVE_LOG", slog)
        _, p2, _ = model
        router, coord = _fleet(model)
        assert coord.begin(p2, 2)
        router.run(_trace(8, seed=19))
        coord.drain()
        events, _ = read_events([slog])
        stats = top.summarize_fleet(events)
        rows = {r["replica"]: r for r in stats["replicas"]}
        assert rows[0]["version"] == 2 and rows[1]["version"] == 2
        ro = stats["rollout"]
        assert ro["version"] == 2 and ro["state"] == "done"
        assert ro["done"] == ro["replicas"] == 2
        frame = top.render_fleet(stats)
        assert "ver" in frame and "v2" in frame
        assert "rollout" in frame
        rc = top.main([slog, "--fleet", "--once"])
        assert rc == 0
        assert "v2" in capsys.readouterr().out
        # single-engine view: version + last_swap ride the summary
        one = top.summarize(
            [e for e in events if e.get("replica") == 0])
        assert one["weight_version"] == 2
        assert one["last_swap_t"] is not None
        assert "version v2" in top.render(one)

    def test_trace_check_flags_mixed_version_request(self):
        """The version-coherence rule: one rid carrying records from
        two weight versions with no router requeue is a violation; a
        requeued (router_hop) rid is exempt."""
        base = {"t": 0.0, "kind": "serve"}
        bad = [dict(base, event="serve_admit", request="r1",
                    weight_version=1),
               dict(base, event="serve_finish", request="r1",
                    weight_version=2)]
        probs = check_version_coherence(bad)
        assert len(probs) == 1 and "r1" in probs[0]
        hopped = bad + [dict(base, event="router_hop", request="r1",
                             to_replica=1)]
        assert check_version_coherence(hopped) == []
