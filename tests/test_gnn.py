"""DistGCN 1.5-D tests (reference tests/test_DistGCN: N-device partitioned
GCN must match the single-device dense computation)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P
from jax import shard_map

import hetu_tpu as ht
from hetu_tpu.parallel.mesh import make_mesh
from hetu_tpu.graph.ops_gnn import gcn_layer_shard_specs


def _problem(n=16, f=8, h=4, seed=0):
    rng = np.random.RandomState(seed)
    adj = (rng.rand(n, n) < 0.3).astype(np.float32)
    adj /= np.maximum(adj.sum(1, keepdims=True), 1)  # row-normalized
    feat = rng.randn(n, f).astype(np.float32)
    w = rng.randn(f, h).astype(np.float32)
    return adj, feat, w


class TestSingleDevice:
    def test_matches_dense(self):
        adj, feat, w = _problem()
        a = ht.placeholder_op("a")
        hh = ht.placeholder_op("h")
        ww = ht.Variable("w", value=w)
        z = ht.distgcn_15d_op(a, hh, ww)
        ex = ht.Executor({"f": [z]})
        out = np.asarray(ex.run("f", feed_dict={a: adj, hh: feat})[0])
        np.testing.assert_allclose(out, (adj @ feat) @ w, rtol=1e-5)

    def test_no_w_variant(self):
        adj, feat, _ = _problem()
        a, hh = ht.placeholder_op("a"), ht.placeholder_op("h")
        z = ht.distgcn_15d_op(a, hh, None, need_W=False)
        ex = ht.Executor({"f": [z]})
        out = np.asarray(ex.run("f", feed_dict={a: adj, hh: feat})[0])
        np.testing.assert_allclose(out, adj @ feat, rtol=1e-5)

    def test_gradient_flows(self):
        adj, feat, w = _problem(8, 4, 2)
        a, hh = ht.placeholder_op("a"), ht.placeholder_op("h")
        ww = ht.Variable("w", value=w)
        z = ht.distgcn_15d_op(a, hh, ww)
        loss = ht.reduce_mean_op(ht.reduce_sum_op(ht.mul_op(z, z), [1]),
                                 [0])
        train = ht.optim.SGDOptimizer(learning_rate=0.1).minimize(loss)
        ex = ht.Executor({"t": [loss, train]})
        l0 = float(ex.run("t", feed_dict={a: adj, hh: feat})[0])
        l5 = [float(ex.run("t", feed_dict={a: adj, hh: feat})[0])
              for _ in range(5)][-1]
        assert l5 < l0


class TestSharded15d:
    def test_15d_psum_matches_dense(self):
        """The tier-2 equivalence pattern: 4x2 (row x col) grid result ==
        dense single-device result."""
        adj, feat, w = _problem(16, 8, 4)
        mesh = make_mesh({"dp": 4, "tp": 2})
        a_spec, h_spec, w_spec = gcn_layer_shard_specs("dp", "tp")

        def per_device(a_blk, h_blk, w_full):
            partial = a_blk @ h_blk
            z = jax.lax.psum(partial, "tp")
            return z @ w_full

        f = jax.jit(shard_map(per_device, mesh=mesh,
                              in_specs=(a_spec, h_spec, P(None, None)),
                              out_specs=P("dp", None)))
        out = np.asarray(f(adj, feat, w))
        np.testing.assert_allclose(out, (adj @ feat) @ w, rtol=1e-4,
                                   atol=1e-5)

    def test_op_inside_shard_map_trace(self):
        """distgcn_15d_op run via the executor on a mesh with pjit-style
        shardings still matches dense."""
        adj, feat, w = _problem(16, 8, 4)
        mesh = make_mesh({"dp": 4, "tp": 2})
        a = ht.placeholder_op("a")
        hh = ht.placeholder_op("h")
        ww = ht.Variable("w", value=w)
        z = ht.distgcn_15d_op(a, hh, ww)
        ex = ht.Executor({"f": [z]}, mesh=mesh)
        out = np.asarray(ex.run("f", feed_dict={a: adj, hh: feat})[0])
        np.testing.assert_allclose(out, (adj @ feat) @ w, rtol=1e-4,
                                   atol=1e-5)


def _sbm(n, n_classes, feat_dim, seed=0):
    """Small stochastic block model (the example's data shape)."""
    rng = np.random.RandomState(seed)
    labels = rng.randint(0, n_classes, n)
    same = labels[:, None] == labels[None, :]
    adj = (rng.rand(n, n) < np.where(same, 0.3, 0.02)).astype(np.float32)
    adj = np.maximum(adj, adj.T)
    np.fill_diagonal(adj, 1.0)
    adj /= adj.sum(1, keepdims=True)
    feat = rng.randn(n, feat_dim).astype(np.float32) * 0.5
    feat[np.arange(n), labels % feat_dim] += 1.0
    return adj, feat, labels.astype(np.int32)


def _build_gcn(feat_dim, hidden, classes, lr=0.1):
    a = ht.placeholder_op("adj")
    x = ht.placeholder_op("feat")
    y = ht.placeholder_op("labels")
    w1 = ht.init.xavier_uniform((feat_dim, hidden), name="gcn_w1")
    w2 = ht.init.xavier_uniform((hidden, classes), name="gcn_w2")
    h = ht.relu_op(ht.distgcn_15d_op(a, x, w1))
    logits = ht.distgcn_15d_op(a, h, w2)
    loss = ht.reduce_mean_op(
        ht.softmaxcrossentropy_sparse_op(logits, y), [0])
    train = ht.optim.SGDOptimizer(learning_rate=lr).minimize(loss)
    return (a, x, y), loss, train


class TestDistributedGCNTraining:
    """r5 (VERDICT r4 item 9): the reference trains GCN distributed
    (examples/gnn/run_dist.py) and hybrid-PS (run_dist_hybrid.py);
    here the SAME training trajectories must come off the 8-device
    mesh and the PS tiers."""

    N, F, H, C, STEPS = 32, 8, 16, 4, 8

    def _trajectory(self, ex, ph, adj, feat, labels):
        a, x, y = ph
        return [float(np.asarray(ex.run(
            "train", feed_dict={a: adj, x: feat, y: labels})[0]))
            for _ in range(self.STEPS)]

    def test_15d_training_matches_single_device(self):
        """Full 2-layer GCN TRAINING (not just one op) on the dp4xtp2
        mesh == single device, same init."""
        adj, feat, labels = _sbm(self.N, self.C, self.F)
        ph, loss, train = _build_gcn(self.F, self.H, self.C)
        ex1 = ht.Executor({"train": [loss, train]})
        w0 = ex1.return_tensor_values()
        base = self._trajectory(ex1, ph, adj, feat, labels)
        assert base[-1] < base[0]          # it actually trains

        ph, loss, train = _build_gcn(self.F, self.H, self.C)
        ex2 = ht.Executor({"train": [loss, train]},
                          mesh=make_mesh({"dp": 4, "tp": 2}))
        ex2.load_dict(w0)
        dist = self._trajectory(ex2, ph, adj, feat, labels)
        np.testing.assert_allclose(dist, base, atol=1e-5)

    def test_hybrid_ps_gcn_matches_dense(self):
        """The run_dist_hybrid.py shape: node features are a LEARNABLE
        embedding table on the PS (hybrid phases A/B); trajectory must
        equal the same model trained fully on-device."""
        from hetu_tpu.ps.server import PSServer
        import hetu_tpu.ps.client as psc

        adj, _, labels = _sbm(self.N, self.C, self.F)
        node_ids = np.arange(self.N).astype(np.int32)

        def build():
            a = ht.placeholder_op("adj")
            ids = ht.placeholder_op("ids")
            y = ht.placeholder_op("labels")
            emb = ht.init.random_normal((self.N, self.F), stddev=0.3,
                                        name="gcn_node_emb")
            emb.is_embed = True
            x = ht.embedding_lookup_op(emb, ids)
            w1 = ht.init.xavier_uniform((self.F, self.H), name="gcn_w1")
            w2 = ht.init.xavier_uniform((self.H, self.C), name="gcn_w2")
            h = ht.relu_op(ht.distgcn_15d_op(a, x, w1))
            logits = ht.distgcn_15d_op(a, h, w2)
            loss = ht.reduce_mean_op(
                ht.softmaxcrossentropy_sparse_op(logits, y), [0])
            train = ht.optim.SGDOptimizer(
                learning_rate=0.1).minimize(loss)
            return (a, ids, y), loss, train

        def run(ex, ph):
            a, ids, y = ph
            return [float(np.asarray(ex.run(
                "train",
                feed_dict={a: adj, ids: node_ids, y: labels})[0]))
                for _ in range(self.STEPS)]

        ph, loss, train = build()
        ex1 = ht.Executor({"train": [loss, train]})
        w0 = ex1.return_tensor_values()
        base = run(ex1, ph)
        assert base[-1] < base[0]

        PSServer._instance = None
        psc.PSClient._instance = None
        try:
            ph, loss, train = build()
            ex2 = ht.Executor({"train": [loss, train]},
                              comm_mode="Hybrid")
            ex2.load_dict(w0)
            hyb = run(ex2, ph)
            np.testing.assert_allclose(hyb, base, atol=1e-5)
        finally:
            PSServer._instance = None
            psc.PSClient._instance = None

    def test_hybrid_ps_gcn_through_native_van(self):
        """Hybrid GCN with the embedding table autoserved by the C++
        van — the run_dist_hybrid role on the fast tier."""
        from hetu_tpu.ps.server import PSServer
        from hetu_tpu.ps.van import van_available
        import hetu_tpu.ps.client as psc
        if not van_available():
            pytest.skip("no C++ toolchain")

        adj, _, labels = _sbm(self.N, self.C, self.F, seed=2)
        node_ids = np.arange(self.N).astype(np.int32)
        PSServer._instance = None
        psc.PSClient._instance = None
        srv = PSServer.get()
        srv.enable_van_autoserve()
        try:
            a = ht.placeholder_op("adj")
            ids = ht.placeholder_op("ids")
            y = ht.placeholder_op("labels")
            emb = ht.init.random_normal((self.N, self.F), stddev=0.3,
                                        name="gcn_node_emb")
            emb.is_embed = True
            x = ht.embedding_lookup_op(emb, ids)
            w1 = ht.init.xavier_uniform((self.F, self.H), name="gcn_w1")
            logits = ht.distgcn_15d_op(a, x, w1)
            loss = ht.reduce_mean_op(
                ht.softmaxcrossentropy_sparse_op(logits, y), [0])
            train = ht.optim.SGDOptimizer(
                learning_rate=0.2).minimize(loss)
            ex = ht.Executor({"train": [loss, train]},
                             comm_mode="Hybrid")
            tr = [float(np.asarray(ex.run(
                "train",
                feed_dict={a: adj, ids: node_ids, y: labels})[0]))
                for _ in range(10)]
            assert tr[-1] < tr[0]
            assert "gcn_node_emb" in srv._van_keys
        finally:
            srv.shutdown()
            PSServer._instance = None
            psc.PSClient._instance = None
