"""DistGCN 1.5-D tests (reference tests/test_DistGCN: N-device partitioned
GCN must match the single-device dense computation)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P
from jax import shard_map

import hetu_tpu as ht
from hetu_tpu.parallel.mesh import make_mesh
from hetu_tpu.graph.ops_gnn import gcn_layer_shard_specs


def _problem(n=16, f=8, h=4, seed=0):
    rng = np.random.RandomState(seed)
    adj = (rng.rand(n, n) < 0.3).astype(np.float32)
    adj /= np.maximum(adj.sum(1, keepdims=True), 1)  # row-normalized
    feat = rng.randn(n, f).astype(np.float32)
    w = rng.randn(f, h).astype(np.float32)
    return adj, feat, w


class TestSingleDevice:
    def test_matches_dense(self):
        adj, feat, w = _problem()
        a = ht.placeholder_op("a")
        hh = ht.placeholder_op("h")
        ww = ht.Variable("w", value=w)
        z = ht.distgcn_15d_op(a, hh, ww)
        ex = ht.Executor({"f": [z]})
        out = np.asarray(ex.run("f", feed_dict={a: adj, hh: feat})[0])
        np.testing.assert_allclose(out, (adj @ feat) @ w, rtol=1e-5)

    def test_no_w_variant(self):
        adj, feat, _ = _problem()
        a, hh = ht.placeholder_op("a"), ht.placeholder_op("h")
        z = ht.distgcn_15d_op(a, hh, None, need_W=False)
        ex = ht.Executor({"f": [z]})
        out = np.asarray(ex.run("f", feed_dict={a: adj, hh: feat})[0])
        np.testing.assert_allclose(out, adj @ feat, rtol=1e-5)

    def test_gradient_flows(self):
        adj, feat, w = _problem(8, 4, 2)
        a, hh = ht.placeholder_op("a"), ht.placeholder_op("h")
        ww = ht.Variable("w", value=w)
        z = ht.distgcn_15d_op(a, hh, ww)
        loss = ht.reduce_mean_op(ht.reduce_sum_op(ht.mul_op(z, z), [1]),
                                 [0])
        train = ht.optim.SGDOptimizer(learning_rate=0.1).minimize(loss)
        ex = ht.Executor({"t": [loss, train]})
        l0 = float(ex.run("t", feed_dict={a: adj, hh: feat})[0])
        l5 = [float(ex.run("t", feed_dict={a: adj, hh: feat})[0])
              for _ in range(5)][-1]
        assert l5 < l0


class TestSharded15d:
    def test_15d_psum_matches_dense(self):
        """The tier-2 equivalence pattern: 4x2 (row x col) grid result ==
        dense single-device result."""
        adj, feat, w = _problem(16, 8, 4)
        mesh = make_mesh({"dp": 4, "tp": 2})
        a_spec, h_spec, w_spec = gcn_layer_shard_specs("dp", "tp")

        def per_device(a_blk, h_blk, w_full):
            partial = a_blk @ h_blk
            z = jax.lax.psum(partial, "tp")
            return z @ w_full

        f = jax.jit(shard_map(per_device, mesh=mesh,
                              in_specs=(a_spec, h_spec, P(None, None)),
                              out_specs=P("dp", None)))
        out = np.asarray(f(adj, feat, w))
        np.testing.assert_allclose(out, (adj @ feat) @ w, rtol=1e-4,
                                   atol=1e-5)

    def test_op_inside_shard_map_trace(self):
        """distgcn_15d_op run via the executor on a mesh with pjit-style
        shardings still matches dense."""
        adj, feat, w = _problem(16, 8, 4)
        mesh = make_mesh({"dp": 4, "tp": 2})
        a = ht.placeholder_op("a")
        hh = ht.placeholder_op("h")
        ww = ht.Variable("w", value=w)
        z = ht.distgcn_15d_op(a, hh, ww)
        ex = ht.Executor({"f": [z]}, mesh=mesh)
        out = np.asarray(ex.run("f", feed_dict={a: adj, hh: feat})[0])
        np.testing.assert_allclose(out, (adj @ feat) @ w, rtol=1e-4,
                                   atol=1e-5)
