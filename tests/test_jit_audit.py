"""Recompile sentinel (ISSUE 19 satellite): the "ONE compiled core"
claim, asserted instead of hoped.

``analysis/jit_audit.py`` snapshots every registered engine's jit
cache sizes; ``assert_no_recompile`` turns silent steady-state
recompiles (the 320x-regression class: a shape leak re-tracing the
decode core every wave) into a named failure.  ``HETU_VALIDATE=1``
registers every ServingEngine at construction.
"""

import numpy as np
import pytest

from hetu_tpu.analysis import jit_audit
from hetu_tpu.models import GPTConfig
from hetu_tpu.serving import ServingEngine

pytestmark = pytest.mark.smoke

HD = 16


@pytest.fixture(autouse=True)
def _fresh_registry():
    jit_audit.reset()
    yield
    jit_audit.reset()


def _mk_params(seed=0):
    rng = np.random.RandomState(seed)
    p = {"kt_wte_table": rng.randn(61, HD) * 0.05,
         "kt_wpe": rng.randn(32, HD) * 0.05,
         "kt_ln_f_scale": np.ones(HD), "kt_ln_f_bias": np.zeros(HD)}
    for w, shp in [("attn_q", (HD, HD)), ("attn_k", (HD, HD)),
                   ("attn_v", (HD, HD)), ("attn_proj", (HD, HD)),
                   ("ffn_wi", (HD, 4 * HD)), ("ffn_wo", (4 * HD, HD))]:
        p[f"kt_h0_{w}_weight"] = rng.randn(*shp) * 0.05
        p[f"kt_h0_{w}_bias"] = np.zeros(shp[1])
    for ln in ("ln1", "ln2"):
        p[f"kt_h0_{ln}_scale"] = np.ones(HD)
        p[f"kt_h0_{ln}_bias"] = np.zeros(HD)
    return p


_CFG = GPTConfig(vocab_size=61, hidden_size=HD, num_hidden_layers=1,
                 num_attention_heads=2, max_position_embeddings=32,
                 batch_size=1, seq_len=32, dropout_rate=0.0)


def _reqs(rng):
    from hetu_tpu.serving import Request
    return [Request(prompt=list(rng.randint(1, 61, 6)), max_new_tokens=4)
            for _ in range(3)]


def test_fake_engine_cache_growth_raises():
    import jax

    class _Eng:
        pass

    e = _Eng()
    e._name = "fake"
    e._decode = jax.jit(lambda x: x + 1)
    label = jit_audit.register_engine(e)
    assert label.startswith("fake#")
    e._decode(np.ones(3, np.float32))
    before = jit_audit.snapshot()
    e._decode(np.ones(3, np.float32))          # same shape: cached
    jit_audit.assert_no_recompile(before, context="steady wave")
    e._decode(np.ones(5, np.float32))          # new shape: re-trace
    with pytest.raises(jit_audit.JitAuditError) as ei:
        jit_audit.assert_no_recompile(before, context="shape leak")
    assert "_decode" in str(ei.value) and "shape leak" in str(ei.value)


def test_dead_engine_drops_out():
    import jax

    class _Eng:
        pass

    e = _Eng()
    e._name = "mortal"
    e._decode = jax.jit(lambda x: x)
    jit_audit.register_engine(e)
    assert any(lbl.startswith("mortal#")
               for lbl in jit_audit.registered())
    del e
    import gc
    gc.collect()
    assert not any(lbl.startswith("mortal#")
                   for lbl in jit_audit.registered())


def test_engine_steady_state_and_swap_do_not_recompile(monkeypatch):
    """Real engine: HETU_VALIDATE=1 (the suite default) registers it;
    an identical second wave AND a live weight swap reuse every
    compiled core."""
    eng = ServingEngine(_mk_params(), _CFG, slots=2, queue_limit=16,
                        max_seq_len=32)
    assert jit_audit.registered(), \
        "HETU_VALIDATE=1 did not register the engine"
    rng = np.random.RandomState(7)
    first = _reqs(rng)
    eng.run(list(first))
    before = jit_audit.snapshot()
    assert before, "no jit cache sizes visible"
    eng.run(list(first))                       # identical second wave
    jit_audit.assert_no_recompile(before, context="second wave")
    eng.swap_params(_mk_params(seed=1), version=2)
    eng.run(list(first))
    jit_audit.assert_no_recompile(before, context="post-swap wave")
