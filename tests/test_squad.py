"""SQuAD processor + span head (reference
examples/nlp/bert/data/SquadDownloader.py, data/bertPrep.py stage the
official JSON; hetu_tpu/squad.py is the feature/eval counterpart of
glue.py for span prediction).  Hermetic via format-faithful fixtures."""

import os
import sys

import numpy as np
import pytest

from hetu_tpu.squad import (convert_examples_to_features,
                            exact_match_score, extract_predictions,
                            f1_score, features_to_arrays,
                            normalize_answer, read_squad_examples,
                            squad_evaluate)
from hetu_tpu.tokenizers import BertTokenizer

FIX = os.path.join(os.path.dirname(__file__), "fixtures", "squad")
GLUE_FIX = os.path.join(os.path.dirname(__file__), "fixtures", "glue")


@pytest.fixture(scope="module")
def tokenizer():
    return BertTokenizer.from_pretrained(
        os.path.join(GLUE_FIX, "vocab.txt"))


@pytest.fixture(scope="module")
def examples():
    return read_squad_examples(
        os.path.join(FIX, "train-tiny.json"), is_training=True)


class TestReader:
    def test_examples_parsed(self, examples):
        assert len(examples) == 7
        assert all(ex.orig_answer_text for ex in examples)

    def test_char_to_word_alignment(self, examples):
        """The whitespace-token span must CONTAIN the gold answer (it
        may carry trailing punctuation the wordpiece pass trims)."""
        for ex in examples:
            span = " ".join(
                ex.doc_tokens[ex.start_position:ex.end_position + 1])
            assert ex.orig_answer_text in span or \
                ex.orig_answer_text.rstrip(".") in span, \
                (ex.qas_id, span, ex.orig_answer_text)

    def test_v2_impossible_gets_null_span(self):
        exs = read_squad_examples(
            os.path.join(FIX, "dev-tiny-v2.json"), is_training=True)
        imp = [e for e in exs if e.is_impossible]
        assert len(imp) == 1
        assert imp[0].start_position == 0 and imp[0].end_position == 0
        assert len(exs) == 8      # the 7 answerable ones survive too

    def test_eval_mode_keeps_unanswerable(self):
        exs = read_squad_examples(
            os.path.join(FIX, "dev-tiny-v2.json"), is_training=False)
        assert len(exs) == 8


class TestFeatures:
    def test_window_positions_decode_to_answer(self, examples,
                                               tokenizer):
        """In every window that claims the answer, detokenizing
        tokens[start:end+1] must reproduce the tokenized answer."""
        feats = convert_examples_to_features(
            examples, tokenizer, max_seq_length=48, doc_stride=12,
            max_query_length=12)
        claimed = 0
        for f in feats:
            if f.start_position == 0:       # answer outside the window
                continue
            claimed += 1
            ex = examples[f.example_index]
            got = " ".join(
                f.tokens[f.start_position:f.end_position + 1])
            got = got.replace(" ##", "")
            want = " ".join(tokenizer.tokenize(ex.orig_answer_text))
            want = want.replace(" ##", "")
            assert got == want, (ex.qas_id, got, want)
        assert claimed >= len(examples)     # every answer claimed once

    def test_doc_stride_produces_overlapping_windows(self, examples,
                                                     tokenizer):
        feats = convert_examples_to_features(
            examples, tokenizer, max_seq_length=32, doc_stride=8,
            max_query_length=8)
        spans = [f for f in feats if f.example_index == 0]
        assert len(spans) > 1               # long context -> windows
        # max-context flags: each doc position scores in ONE window
        assert any(any(f.token_is_max_context.values()) for f in spans)

    def test_arrays_shapes_and_padding(self, examples, tokenizer):
        feats = convert_examples_to_features(
            examples, tokenizer, max_seq_length=40, doc_stride=16,
            max_query_length=12)
        arr = features_to_arrays(feats)
        n = len(feats)
        assert arr["input_ids"].shape == (n, 40)
        assert arr["input_mask"].shape == (n, 40)
        assert arr["segment_ids"].shape == (n, 40)
        assert arr["start_positions"].shape == (n,)
        # padding is masked out; positions stay inside the window
        assert ((arr["input_ids"] == 0) <= (arr["input_mask"] == 0)).all()
        assert (arr["start_positions"] < 40).all()
        assert (arr["end_positions"] >= arr["start_positions"]).all()


class TestExtraction:
    def test_oracle_logits_recover_gold(self, examples, tokenizer):
        """One-hot logits at the gold positions must extract text that
        scores 100 EM/F1 — the whole decode path round-trips."""
        feats = convert_examples_to_features(
            examples, tokenizer, max_seq_length=48, doc_stride=12,
            max_query_length=12)
        n = len(feats)
        start_logits = np.zeros((n, 48), np.float32)
        end_logits = np.zeros((n, 48), np.float32)
        for i, f in enumerate(feats):
            if f.start_position > 0:
                start_logits[i, f.start_position] = 10.0
                end_logits[i, f.end_position] = 10.0
        preds = extract_predictions(examples, feats, start_logits,
                                    end_logits)
        m = squad_evaluate(examples, preds)
        assert m["exact_match"] == 100.0 and m["f1"] == 100.0, (m, preds)


class TestMetrics:
    def test_normalization_official_rules(self):
        assert normalize_answer("The Old   Forest.") == "old forest"
        assert normalize_answer("an Answer!") == "answer"

    def test_exact_match(self):
        assert exact_match_score("the old forest", "Old Forest") == 1.0
        assert exact_match_score("a den", "the river") == 0.0

    def test_f1_partial_overlap(self):
        # pred {old, forest}, gold {old, forest, river}: P=1, R=2/3
        got = f1_score("the old forest", "old forest river")
        assert abs(got - 0.8) < 1e-9

    def test_v2_impossible_scored_against_empty(self):
        """Official v2 metric: unanswerable questions COUNT, crediting
        only an empty prediction."""
        exs = read_squad_examples(
            os.path.join(FIX, "dev-tiny-v2.json"), is_training=False)
        gold = {e.qas_id: (e.answers[0] if e.answers else "")
                for e in exs}
        m = squad_evaluate(exs, gold)       # oracle incl. empty string
        assert m["exact_match"] == 100.0 and m["f1"] == 100.0
        wrong = dict(gold)
        wrong["q_impossible"] = "the blue car"   # hallucinated answer
        m2 = squad_evaluate(exs, wrong)
        assert abs(m2["exact_match"] - 100.0 * 7 / 8) < 1e-9


def test_finetune_example_learns_spans():
    """End-to-end: the example script trains BertForQuestionAnswering
    on the fixture until the oracle-checked extraction path yields a
    real F1 — span supervision flows through start/end heads."""
    import importlib.util
    path = os.path.join(os.path.dirname(__file__), "..", "examples",
                        "nlp", "finetune_bert_squad.py")
    spec = importlib.util.spec_from_file_location("ex_squad", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    old = sys.argv
    sys.argv = ["prog", "--data", os.path.join(FIX, "train-tiny.json"),
                "--vocab-path", os.path.join(GLUE_FIX, "vocab.txt"),
                "--num-layers", "1", "--hidden", "32", "--heads", "2",
                "--batch-size", "8", "--seq-len", "48",
                "--doc-stride", "16", "--num-steps", "150",
                "--learning-rate", "2e-3"]
    try:
        metrics = mod.main()
    finally:
        sys.argv = old
    # 7 questions over a tiny model: learning the training spans to
    # F1 >= 50 shows real span supervision, not chance (~0)
    assert metrics["f1"] >= 50.0, metrics
