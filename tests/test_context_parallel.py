"""Context parallelism tests: ring/Ulysses attention vs exact attention
(tier-2 equivalence pattern — N-device must match 1-device ground truth)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from hetu_tpu.parallel.mesh import make_mesh
from hetu_tpu.parallel.context_parallel import (
    ring_attention, ulysses_attention, blockwise_attention,
)

B, S, H, D = 2, 32, 4, 8
CP = 4


def _exact(q, k, v, causal=False):
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * (D ** -0.5)
    if causal:
        mask = jnp.tril(jnp.ones((S, S), bool))
        s = jnp.where(mask[None, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


def _qkv(seed):
    rng = np.random.RandomState(seed)
    return tuple(jnp.asarray(rng.randn(B, S, H, D), jnp.float32)
                 for _ in range(3))


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_exact(causal):
    mesh = make_mesh({"cp": CP})
    q, k, v = _qkv(0)
    got = ring_attention(q, k, v, mesh=mesh, causal=causal)
    want = _exact(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_matches_exact(causal):
    mesh = make_mesh({"cp": CP})
    q, k, v = _qkv(1)
    got = ulysses_attention(q, k, v, mesh=mesh, causal=causal)
    want = _exact(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_blockwise_matches_exact(causal):
    q, k, v = _qkv(2)
    got = blockwise_attention(q, k, v, block_size=8, causal=causal)
    want = _exact(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-5)


def test_ring_attention_grads_match_exact():
    mesh = make_mesh({"cp": CP})
    q, k, v = _qkv(3)

    def loss_ring(q, k, v):
        return jnp.sum(ring_attention(q, k, v, mesh=mesh, causal=True) ** 2)

    def loss_exact(q, k, v):
        return jnp.sum(_exact(q, k, v, causal=True) ** 2)

    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    g_exact = jax.grad(loss_exact, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ring, g_exact):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-3, atol=2e-4)


class TestRingFlash:
    """VERDICT r2 item 6: the Pallas flash kernel as ring attention's
    per-block attention (default on TPU; exercised here explicitly on
    the CPU mesh via interpret mode)."""

    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_exact(self, causal):
        mesh = make_mesh({"cp": CP})
        q, k, v = _qkv(10)
        got = ring_attention(q, k, v, mesh=mesh, causal=causal,
                             impl="flash", block_q=8, block_k=8)
        want = _exact(q, k, v, causal)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-4, atol=2e-5)

    def test_grads_match_exact(self):
        mesh = make_mesh({"cp": CP})
        q, k, v = _qkv(11)

        def loss_ring(q, k, v):
            return jnp.sum(ring_attention(
                q, k, v, mesh=mesh, causal=True, impl="flash",
                block_q=8, block_k=8) ** 2)

        def loss_exact(q, k, v):
            return jnp.sum(_exact(q, k, v, causal=True) ** 2)

        g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
        g_exact = jax.grad(loss_exact, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g_ring, g_exact):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-3, atol=2e-4)

    def test_composes_with_dp(self):
        mesh = make_mesh({"dp": 2, "cp": 4})
        q, k, v = _qkv(12)
        got = ring_attention(q, k, v, mesh=mesh, causal=True,
                             impl="flash", block_q=8, block_k=8)
        want = _exact(q, k, v, True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-4, atol=2e-5)

    def test_default_backend_selection(self):
        """impl=None resolves by backend: exact on CPU (oracle), flash
        on TPU — the VERDICT's 'default on TPU' contract."""
        from hetu_tpu.parallel import context_parallel as cpar
        mesh = make_mesh({"cp": CP})
        q, k, v = _qkv(13)
        # on this CPU test mesh the default must be the exact oracle
        # (flash would run interpret-mode; correctness identical) — the
        # selection line itself is what we pin here
        assert jax.default_backend() != "tpu"
        got = ring_attention(q, k, v, mesh=mesh, causal=True)
        want = _exact(q, k, v, True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-4, atol=2e-5)


def test_flash_with_lse_combine_identity():
    """Splitting the KV range in two and merging with the (o, lse)
    streaming combine must equal one-shot attention — the algebra the
    ring relies on."""
    from hetu_tpu.kernels.flash_attention import flash_attention_with_lse
    q, k, v = _qkv(14)
    half = S // 2
    o1, l1 = flash_attention_with_lse(q, k[:, :half], v[:, :half],
                                      block_q=8, block_k=8)
    o2, l2 = flash_attention_with_lse(q, k[:, half:], v[:, half:],
                                      block_q=8, block_k=8)
    lse = jnp.logaddexp(l1, l2)
    w1 = jnp.exp(l1 - lse).transpose(0, 2, 1)[..., None]
    w2 = jnp.exp(l2 - lse).transpose(0, 2, 1)[..., None]
    got = o1 * w1 + o2 * w2
    want = _exact(q, k, v, False)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-5)


def test_ring_composes_with_dp():
    """cp and dp on the same mesh: batch-sharded + seq-sharded."""
    mesh = make_mesh({"dp": 2, "cp": 4})
    q, k, v = _qkv(4)
    got = ring_attention(q, k, v, mesh=mesh, causal=False)
    want = _exact(q, k, v, False)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-5)


def test_ring_indivisible_batch_stays_replicated():
    """B=1 on a dp mesh (eval / partial last batch): the batch dim must
    fall back to replicated instead of failing the dp split."""
    mesh = make_mesh({"dp": 2, "cp": 4})
    rng = np.random.RandomState(7)
    q, k, v = (jnp.asarray(rng.randn(1, S, H, D), jnp.float32)
               for _ in range(3))
    got = ring_attention(q, k, v, mesh=mesh, causal=True)
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * (D ** -0.5)
    mask = jnp.tril(jnp.ones((S, S), bool))
    p = jax.nn.softmax(jnp.where(mask[None, None], s, -1e30), axis=-1)
    want = jnp.einsum("bhqk,bkhd->bqhd", p, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-5)
