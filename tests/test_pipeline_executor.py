"""Pipeline as an executor mode (reference Executor(pipeline='gpipe')
partitioning the built graph and driving microbatch subexecutors —
gpipe_subexecutor.py:33-111, pipeline_subexecutor.py:29-81).

The reference's tier-2 correctness criterion applies: the pipelined run's
loss trajectory must equal the non-pipelined single-device run (GPipe and
synchronous 1F1B are mathematically identical to full-batch training)."""

import numpy as np
import pytest

import jax
import hetu_tpu as ht
from hetu_tpu.parallel.mesh import make_mesh
from hetu_tpu.parallel.partition import partition


BATCH, IN, HID, OUT = 16, 8, 16, 4
N_LAYERS = 4
N_STEPS = 6


def build_model(opt=None, n_layers=N_LAYERS):
    """Residual MLP with a uniform repeated body (the pipeline-friendly
    shape: embedding-ish pre, N identical blocks, head + loss post)."""
    x = ht.placeholder_op("x")
    y = ht.placeholder_op("y")
    h = ht.linear_op(x, ht.init.xavier_uniform((IN, HID), name="in_w"),
                     ht.init.zeros((HID,), name="in_b"))
    for i in range(n_layers):
        w1 = ht.init.xavier_uniform((HID, 2 * HID), name=f"l{i}_w1")
        b1 = ht.init.zeros((2 * HID,), name=f"l{i}_b1")
        w2 = ht.init.xavier_uniform((2 * HID, HID), name=f"l{i}_w2")
        b2 = ht.init.zeros((HID,), name=f"l{i}_b2")
        h = h + ht.linear_op(ht.gelu_op(ht.linear_op(h, w1, b1)), w2, b2)
    logits = ht.matmul_op(h, ht.init.xavier_uniform((HID, OUT),
                                                    name="head_w"))
    loss = ht.reduce_mean_op(ht.softmaxcrossentropy_op(logits, y), axes=0)
    train = (opt or ht.optim.SGDOptimizer(learning_rate=0.1)).minimize(loss)
    return x, y, loss, train


def make_batches(n=N_STEPS, seed=11):
    rng = np.random.RandomState(seed)
    out = []
    for _ in range(n):
        xb = rng.randn(BATCH, IN).astype(np.float32)
        yb = np.eye(OUT, dtype=np.float32)[xb[:, :OUT].argmax(axis=1)]
        out.append((xb, yb))
    return out


def run_traj(ex, x, y, batches):
    return [float(np.asarray(ex.run("train", feed_dict={x: a, y: b})[0]))
            for a, b in batches]


@pytest.fixture(scope="module")
def baseline():
    x, y, loss, train = build_model()
    ex = ht.Executor({"train": [loss, train]})
    w0 = ex.return_tensor_values()
    batches = make_batches()
    base = run_traj(ex, x, y, batches)
    assert base[-1] < base[0]
    return w0, batches, base


class TestPartitioner:
    def test_uniform_body_found(self):
        _, _, loss, _ = build_model()
        plan = partition(loss, 2)
        assert plan.uniform and plan.num_body_blocks() == N_LAYERS
        names = [[p.name for p in blk] for blk in plan.body_params]
        assert names[0] == ["l0_w1", "l0_b1", "l0_w2", "l0_b2"]
        assert names[3] == ["l3_w1", "l3_b1", "l3_w2", "l3_b2"]

    def test_trims_to_multiple_of_stages(self):
        _, _, loss, _ = build_model()
        plan = partition(loss, 3)
        assert plan.uniform and plan.num_body_blocks() == 3
        # l0 was trimmed into pre
        assert any(n.name == "l0_w1" for n in plan.pre_nodes)

    def test_shared_weight_defeats_stacking(self):
        x = ht.placeholder_op("x")
        y = ht.placeholder_op("y")
        w = ht.init.xavier_uniform((IN, IN), name="shared_w")
        h = x
        for _ in range(4):
            h = ht.gelu_op(ht.matmul_op(h, w))     # same w every block
        loss = ht.reduce_mean_op(
            ht.softmaxcrossentropy_op(
                ht.matmul_op(h, ht.init.xavier_uniform((IN, OUT),
                                                       name="hw")), y),
            axes=0)
        plan = partition(loss, 2)
        assert not plan.uniform

    def test_nonuniform_graph_no_body(self):
        x = ht.placeholder_op("x")
        y = ht.placeholder_op("y")
        h = ht.gelu_op(ht.matmul_op(
            x, ht.init.xavier_uniform((IN, HID), name="a")))
        h = ht.tanh_op(ht.matmul_op(
            h, ht.init.xavier_uniform((HID, HID), name="b")))
        loss = ht.reduce_mean_op(ht.softmaxcrossentropy_op(
            ht.matmul_op(h, ht.init.xavier_uniform((HID, OUT),
                                                   name="c")), y), axes=0)
        plan = partition(loss, 2)
        assert not plan.uniform
        assert len(plan.blocks) >= 2    # cuts still found


class TestHostPath:
    """No 'pp' mesh axis: jitted microbatch-scan lowering."""

    @pytest.mark.parametrize("mode", ["gpipe", "1f1b"])
    def test_sync_modes_match_baseline(self, baseline, mode):
        w0, batches, base = baseline
        x, y, loss, train = build_model()
        ex = ht.Executor({"train": [loss, train]}, pipeline=mode,
                         num_stages=2, num_microbatches=4)
        ex.load_dict(w0)
        tr = run_traj(ex, x, y, batches)
        np.testing.assert_allclose(tr, base, atol=1e-5)

    def test_adam_matches_baseline(self, baseline):
        _, batches, _ = baseline
        x, y, loss, train = build_model(
            ht.optim.AdamOptimizer(learning_rate=0.01))
        ex1 = ht.Executor({"train": [loss, train]})
        w0 = ex1.return_tensor_values()
        base = run_traj(ex1, x, y, batches)
        x, y, loss, train = build_model(
            ht.optim.AdamOptimizer(learning_rate=0.01))
        ex2 = ht.Executor({"train": [loss, train]}, pipeline="gpipe",
                          num_stages=4, num_microbatches=8)
        ex2.load_dict(w0)
        np.testing.assert_allclose(run_traj(ex2, x, y, batches), base,
                                   atol=1e-5)

    def test_pipedream_trains(self, baseline):
        w0, batches, base = baseline
        x, y, loss, train = build_model()
        ex = ht.Executor({"train": [loss, train]}, pipeline="pipedream",
                         num_stages=2, num_microbatches=4)
        ex.load_dict(w0)
        tr = run_traj(ex, x, y, make_batches(16))
        # per-microbatch updates train (trend over a window: single-step
        # deltas are init-sensitive on a tiny model)...
        assert np.mean(tr[-4:]) < np.mean(tr[:4]), tr
        # ...but do not reproduce the sync trajectory
        assert not np.allclose(tr[:len(base)], base)

    def test_eval_subgraph_untouched(self, baseline):
        """Forward-only subgraphs keep the plain jit path and see the
        pipeline-updated params."""
        w0, batches, base = baseline
        x, y, loss, train = build_model()
        ex = ht.Executor({"train": [loss, train], "eval": [loss]},
                         pipeline="gpipe", num_microbatches=4,
                         num_stages=2)
        ex.load_dict(w0)
        ev = float(np.asarray(ex.run(
            "eval", feed_dict={x: batches[0][0], y: batches[0][1]})[0]))
        np.testing.assert_allclose(ev, base[0], atol=1e-5)
        tr = run_traj(ex, x, y, batches)
        np.testing.assert_allclose(tr, base, atol=1e-5)

    def test_checkpoint_roundtrip(self, baseline, tmp_path):
        w0, batches, base = baseline
        x, y, loss, train = build_model()
        ex = ht.Executor({"train": [loss, train]}, pipeline="gpipe",
                         num_stages=2, num_microbatches=4)
        ex.load_dict(w0)
        run_traj(ex, x, y, batches[:3])
        ex.save(str(tmp_path))
        x, y, loss, train = build_model()
        ex2 = ht.Executor({"train": [loss, train]}, pipeline="gpipe",
                          num_stages=2, num_microbatches=4)
        ex2.load(str(tmp_path))
        tr = run_traj(ex2, x, y, batches[3:])
        np.testing.assert_allclose(tr, base[3:], atol=1e-5)


class TestSPMDPath:
    """'pp' mesh axis + uniform body: spmd_pipeline lowering."""

    @pytest.mark.parametrize("mode", ["gpipe", "1f1b"])
    @pytest.mark.parametrize("axes", [{"pp": 4}, {"pp": 2, "dp": 2}],
                             ids=["pp4", "pp2xdp2"])
    def test_matches_baseline(self, baseline, axes, mode):
        w0, batches, base = baseline
        x, y, loss, train = build_model()
        mesh = make_mesh(axes)
        ex = ht.Executor({"train": [loss, train]}, pipeline=mode,
                         mesh=mesh, num_microbatches=4)
        assert ex.subexecutor["train"].spmd, "SPMD lowering not chosen"
        ex.load_dict(w0)
        tr = run_traj(ex, x, y, batches)
        np.testing.assert_allclose(tr, base, atol=1e-5)

    def test_more_blocks_than_stages(self, baseline):
        """R=4 blocks on pp=2: each stage scans 2 blocks."""
        w0, batches, base = baseline
        x, y, loss, train = build_model()
        mesh = make_mesh({"pp": 2})
        ex = ht.Executor({"train": [loss, train]}, pipeline="gpipe",
                         mesh=mesh, num_microbatches=4)
        assert ex.subexecutor["train"].spmd
        ex.load_dict(w0)
        np.testing.assert_allclose(run_traj(ex, x, y, batches), base,
                                   atol=1e-5)

    def test_checkpoint_roundtrip_on_mesh(self, baseline, tmp_path):
        """load() must re-place optimizer slots on the mesh (a bare
        jnp.asarray pins them to device 0 and the next step rejects the
        mixed placements) — caught by the API drive, regression-pinned
        here."""
        w0, batches, base = baseline
        x, y, loss, train = build_model()
        mesh = make_mesh({"pp": 4})
        ex = ht.Executor({"train": [loss, train]}, pipeline="1f1b",
                         mesh=mesh, num_microbatches=4)
        ex.load_dict(w0)
        run_traj(ex, x, y, batches[:3])
        ex.save(str(tmp_path))
        x, y, loss, train = build_model()
        ex2 = ht.Executor({"train": [loss, train]}, pipeline="1f1b",
                          mesh=make_mesh({"pp": 4}), num_microbatches=4)
        ex2.load(str(tmp_path))
        tr = run_traj(ex2, x, y, batches[3:])
        np.testing.assert_allclose(tr, base[3:], atol=1e-5)

    def test_nonuniform_falls_back(self, baseline):
        """Shared weights: SPMD refused, scan path still correct."""
        mesh = make_mesh({"pp": 2})
        x = ht.placeholder_op("x")
        y = ht.placeholder_op("y")
        w = ht.init.xavier_uniform((IN, IN), name="shared_w2")
        h = x
        for _ in range(2):
            h = ht.gelu_op(ht.matmul_op(h, w))
        loss = ht.reduce_mean_op(ht.softmaxcrossentropy_op(
            ht.matmul_op(h, ht.init.xavier_uniform((IN, OUT),
                                                   name="hw2")), y), axes=0)
        train = ht.optim.SGDOptimizer(learning_rate=0.1).minimize(loss)
        ex = ht.Executor({"train": [loss, train]}, pipeline="gpipe",
                         mesh=mesh, num_microbatches=4)
        assert not ex.subexecutor["train"].spmd
        w_before = np.array(ex.var_values["shared_w2"], copy=True)
        batches = make_batches()
        tr = run_traj(ex, x, y, batches)
        assert np.all(np.isfinite(tr))
        # the scan path really applied updates
        assert not np.allclose(np.asarray(ex.var_values["shared_w2"]),
                               w_before)


class TestOneFOneBMemory:
    """VERDICT r2 item 2: '1f1b' must be a real staggered schedule whose
    activation high-water is O(S) in-flight microbatches, not an alias
    of gpipe's O(M + S) saved scan carries.  Proven the prescribed way:
    ``profiler.memory_analysis`` on the compiled step, 1f1b < gpipe at
    M >= 2S, with the gap accounted for by the saved boundary slots."""

    # boundary slot = (BATCH/M)*HID floats: sized so the slots the 1F1B
    # buffer avoids (several MB) dwarf XLA buffer-assignment noise (~0.5MB)
    BATCH, IN, HID, OUT, S = 16384, 64, 128, 8, 4

    def _build(self, n_layers=4):
        x = ht.placeholder_op("x")
        y = ht.placeholder_op("y")
        h = ht.linear_op(x, ht.init.xavier_uniform((self.IN, self.HID),
                                                   name="m_in_w"),
                         ht.init.zeros((self.HID,), name="m_in_b"))
        for i in range(n_layers):
            w1 = ht.init.xavier_uniform((self.HID, 2 * self.HID),
                                        name=f"m{i}_w1")
            b1 = ht.init.zeros((2 * self.HID,), name=f"m{i}_b1")
            w2 = ht.init.xavier_uniform((2 * self.HID, self.HID),
                                        name=f"m{i}_w2")
            b2 = ht.init.zeros((self.HID,), name=f"m{i}_b2")
            h = h + ht.linear_op(ht.gelu_op(ht.linear_op(h, w1, b1)),
                                 w2, b2)
        logits = ht.matmul_op(h, ht.init.xavier_uniform(
            (self.HID, self.OUT), name="m_head"))
        loss = ht.reduce_mean_op(ht.softmaxcrossentropy_op(logits, y),
                                 axes=0)
        train = ht.optim.SGDOptimizer(learning_rate=0.1).minimize(loss)
        return x, y, loss, train

    def _temp_bytes(self, mode, M):
        from hetu_tpu.profiler import HetuProfiler
        x, y, loss, train = self._build()
        ex = ht.Executor({"train": [loss, train]}, pipeline=mode,
                         mesh=make_mesh({"pp": self.S}),
                         num_microbatches=M)
        assert ex.subexecutor["train"].spmd
        xb = np.zeros((self.BATCH, self.IN), np.float32)
        yb = np.zeros((self.BATCH, self.OUT), np.float32)
        ex.run("train", feed_dict={x: xb, y: yb})
        prof = HetuProfiler(ex, feed_shapes={
            "x": (self.BATCH, self.IN), "y": (self.BATCH, self.OUT)})
        m = prof.memory_analysis("train")
        assert m is not None
        return m["temp_size_in_bytes"]

    @pytest.mark.parametrize("M", [8, 16], ids=["M=2S", "M=4S"])
    def test_activation_high_water_below_gpipe(self, M):
        S = self.S
        slot = (self.BATCH // M) * self.HID * 4     # one boundary, f32
        saved_slots = (M + S - 1) - min(M, 2 * S - 1)
        gp = self._temp_bytes("gpipe", M)
        of = self._temp_bytes("1f1b", M)
        assert of < gp, (of, gp)
        # the gap is the schedule's doing: at least half the boundary
        # slots the O(S) buffer avoids (allowing XLA layout noise)
        assert gp - of >= 0.5 * saved_slots * slot, \
            (gp, of, saved_slots, slot)


class TestShardedEnds:
    """VERDICT r2 item 3: embedding + head must stop being replicated
    across pp groups.  TPU-native form: end tensors are 1/S-sharded over
    the 'pp' axis (reference folds them into first/last stage —
    pipeline_subexecutor.py:29-81; same memory goal, better balance,
    tied weights need no special grads choreography)."""

    B, S_SEQ, H, L, V, M = 8, 16, 64, 4, 4096, 4

    def _build(self, batch):
        from hetu_tpu.models.bert import BertConfig, \
            BertForSequenceClassification
        cfg = BertConfig(vocab_size=self.V, hidden_size=self.H,
                         num_hidden_layers=self.L, num_attention_heads=2,
                         intermediate_size=2 * self.H, seq_len=self.S_SEQ,
                         batch_size=batch, hidden_dropout_prob=0.0,
                         attention_probs_dropout_prob=0.0)
        ids = ht.placeholder_op("input_ids")
        labels = ht.placeholder_op("labels")
        model = BertForSequenceClassification(cfg, num_labels=3)
        loss, _ = model(ids, labels=labels)
        train = ht.optim.SGDOptimizer(learning_rate=0.05).minimize(loss)
        return ids, labels, loss, train

    def _batches(self, n=3, seed=5):
        rng = np.random.RandomState(seed)
        return [(rng.randint(0, self.V, (self.B, self.S_SEQ))
                 .astype(np.int32),
                 rng.randint(0, 3, (self.B,)).astype(np.int32))
                for _ in range(n)]

    def _make(self, shard_ends, mode="gpipe"):
        ids, labels, loss, train = self._build(self.B // self.M)
        ex = ht.Executor({"train": [loss, train]}, pipeline=mode,
                         mesh=make_mesh({"pp": 2}), num_microbatches=self.M,
                         shard_pipeline_ends=shard_ends)
        assert ex.subexecutor["train"].spmd
        return ids, labels, ex

    def test_end_params_sharded_storage(self):
        ids, labels, ex = self._make(True)
        emb = ex.var_values["bert_embeddings_word_embeddings"]
        spec = tuple(emb.sharding.spec)
        assert "pp" in spec, spec
        # each device really holds a 1/S shard
        shard = emb.sharding.shard_shape(emb.shape)
        assert int(np.prod(shard)) == int(np.prod(emb.shape)) // 2
        # body-layer params stay unsharded (they stack over 'pp' instead)
        body = ex.var_values["bert_layer0_attn_q_weight"]
        assert "pp" not in tuple(body.sharding.spec)

    @pytest.mark.parametrize("mode", ["gpipe", "1f1b"])
    def test_trajectory_unchanged_by_end_sharding(self, mode):
        batches = self._batches()

        def traj(shard_ends):
            ids, labels, ex = self._make(shard_ends, mode)
            return [float(np.asarray(ex.run(
                "train", feed_dict={ids: a, labels: b})[0]))
                for a, b in batches]

        # same init seed -> same weights; only placement differs
        t_on = traj(True)
        t_off = traj(False)
        np.testing.assert_allclose(t_on, t_off, rtol=2e-4)

    def test_per_device_argument_bytes_drop(self):
        sizes = {}
        for shard_ends in (True, False):
            ids, labels, ex = self._make(shard_ends)
            xb, yb = self._batches(1)[0]
            ex.run("train", feed_dict={ids: xb, labels: yb})
            fn = next(iter(ex.subexecutor["train"]._compiled.values()))
            c = fn.lower(ex.var_values, ex.opt_states, ex.step, ex.rng,
                         {"input_ids": ex.device_put_feed(
                             "input_ids", xb),
                          "labels": ex.device_put_feed("labels", yb)}
                         ).compile()
            sizes[shard_ends] = c.memory_analysis().argument_size_in_bytes
        # embedding [V, H] f32 + its SGD state: at pp=2 a half of each
        # leaves every device; allow slack for the small sharded extras
        emb_bytes = self.V * self.H * 4
        assert sizes[False] - sizes[True] >= emb_bytes // 2, sizes


class TestBert4L:
    """The VERDICT's acceptance case: BERT-4L trains via
    Executor(pipeline=...) matching the non-pipelined trajectory."""

    B, S, H, L, V, M = 8, 16, 32, 4, 100, 4

    def _build(self, batch):
        """Graphs bake the batch dim into reshapes (static shapes), so the
        pipelined graph is built at the MICROBATCH size — exactly how the
        reference's pipeline examples set their per-worker dataloader."""
        from hetu_tpu.models.bert import BertConfig, \
            BertForSequenceClassification
        cfg = BertConfig(vocab_size=self.V, hidden_size=self.H,
                         num_hidden_layers=self.L, num_attention_heads=2,
                         intermediate_size=2 * self.H, seq_len=self.S,
                         batch_size=batch, hidden_dropout_prob=0.0,
                         attention_probs_dropout_prob=0.0)
        ids = ht.placeholder_op("input_ids")
        labels = ht.placeholder_op("labels")
        model = BertForSequenceClassification(cfg, num_labels=3)
        loss, _ = model(ids, labels=labels)
        # SGD: linear in the gradient, so microbatch-mean == full-batch
        # math is fp-stable.  (Adam's rsqrt-normalized update amplifies
        # ~1e-8 summation-order noise on near-zero grads into visible
        # trajectory divergence — true of the reference as well.)
        train = ht.optim.SGDOptimizer(learning_rate=0.05).minimize(loss)
        return ids, labels, loss, train

    def _batches(self, n=4, seed=5):
        rng = np.random.RandomState(seed)
        return [(rng.randint(0, self.V, (self.B, self.S)).astype(np.int32),
                 rng.randint(0, 3, (self.B,)).astype(np.int32))
                for _ in range(n)]

    def test_bert_pipeline_matches_baseline(self):
        ids, labels, loss, train = self._build(self.B)
        ex1 = ht.Executor({"train": [loss, train]})
        w0 = ex1.return_tensor_values()
        batches = self._batches()
        base = [float(np.asarray(ex1.run(
            "train", feed_dict={ids: a, labels: b})[0]))
            for a, b in batches]

        ids, labels, loss, train = self._build(self.B // self.M)
        mesh = make_mesh({"pp": 2})
        ex2 = ht.Executor({"train": [loss, train]}, pipeline="gpipe",
                          mesh=mesh, num_microbatches=self.M)
        sub = ex2.subexecutor["train"]
        assert sub.plan.uniform and sub.plan.num_body_blocks() == self.L
        assert sub.spmd
        ex2.load_dict(w0)
        tr = [float(np.asarray(ex2.run(
            "train", feed_dict={ids: a, labels: b})[0]))
            for a, b in batches]
        np.testing.assert_allclose(tr, base, rtol=2e-4)


class TestHetPipe:
    def test_hetpipe_syncs_via_ps(self, baseline):
        from hetu_tpu.ps.server import PSServer
        w0, batches, _ = baseline
        x, y, loss, train = build_model()
        ps = PSServer()
        ex = ht.Executor({"train": [loss, train]}, pipeline="hetpipe",
                         num_stages=2, num_microbatches=4, ps_comm=ps,
                         sync_every=2)
        ex.load_dict(w0)
        tr = run_traj(ex, x, y, make_batches(16))
        assert np.mean(tr[-4:]) < np.mean(tr[:4]), tr
        sub = ex.subexecutor["train"]
        assert sub._ps_snapshot is not None     # sync actually ran
        # server copy agrees with the post-sync worker copy
        np.testing.assert_allclose(
            np.asarray(ps.pull("l0_w1")), sub._ps_snapshot["l0_w1"])


class TestReviewRegressions:
    def test_hetpipe_default_ps_client(self, baseline):
        """hetpipe with no explicit ps_comm goes through PSClient, whose
        init method is parameter_init (not param_init) — the sync helper
        must handle both."""
        w0, batches, _ = baseline
        x, y, loss, train = build_model()
        ex = ht.Executor({"train": [loss, train]}, pipeline="hetpipe",
                         num_stages=2, num_microbatches=4, sync_every=1)
        ex.load_dict(w0)
        tr = run_traj(ex, x, y, batches[:2])
        assert np.all(np.isfinite(tr))
        assert ex.subexecutor["train"]._ps_snapshot is not None

    def test_tied_weights_across_pre_post(self, baseline):
        """A weight used both before and after the uniform body (tied
        embedding/LM-head pattern): SPMD path must bind it on demand in
        the post segment and sum both uses' grads."""
        def build_tied():
            x = ht.placeholder_op("x")
            y = ht.placeholder_op("y")
            w_in = ht.init.xavier_uniform((IN, HID), name="tied_w")
            h = ht.matmul_op(x, w_in)
            for i in range(2):
                w1 = ht.init.xavier_uniform((HID, HID), name=f"t{i}_w1")
                b1 = ht.init.zeros((HID,), name=f"t{i}_b1")
                h = h + ht.gelu_op(ht.linear_op(h, w1, b1))
            logits = ht.matmul_op(h, w_in, trans_B=True)   # tied reuse
            loss = ht.reduce_mean_op(
                ht.softmaxcrossentropy_op(logits, y), axes=0)
            train = ht.optim.SGDOptimizer(learning_rate=0.1).minimize(loss)
            return x, y, loss, train

        rng = np.random.RandomState(2)
        batches = [(rng.randn(BATCH, IN).astype(np.float32),
                    np.eye(IN, dtype=np.float32)[
                        rng.randint(0, IN, BATCH)])
                   for _ in range(4)]
        x, y, loss, train = build_tied()
        ex1 = ht.Executor({"train": [loss, train]})
        w0 = ex1.return_tensor_values()
        base = run_traj(ex1, x, y, batches)

        from hetu_tpu.parallel.mesh import make_mesh
        x, y, loss, train = build_tied()
        ex2 = ht.Executor({"train": [loss, train]}, pipeline="gpipe",
                          mesh=make_mesh({"pp": 2}), num_microbatches=4)
        assert ex2.subexecutor["train"].spmd
        ex2.load_dict(w0)
        np.testing.assert_allclose(run_traj(ex2, x, y, batches), base,
                                   atol=1e-5)

    def test_bn_state_chains_through_microbatches(self):
        """Pipedream == stepping the baseline once per microbatch: BN
        running stats must chain sequentially through the scan carry, not
        keep only the last microbatch's update."""
        def build_bn():
            x = ht.placeholder_op("x")
            y = ht.placeholder_op("y")
            h = ht.linear_op(x, ht.init.xavier_uniform((IN, HID),
                                                       name="bn_in_w"),
                             ht.init.zeros((HID,), name="bn_in_b"))
            h = ht.layers.BatchNorm(HID, name="bn0")(h)
            logits = ht.matmul_op(h, ht.init.xavier_uniform(
                (HID, OUT), name="bn_head"))
            loss = ht.reduce_mean_op(
                ht.softmaxcrossentropy_op(logits, y), axes=0)
            train = ht.optim.SGDOptimizer(
                learning_rate=0.1).minimize(loss)
            return x, y, loss, train

        M = 4
        mb = BATCH // M
        rng = np.random.RandomState(7)
        xb = rng.randn(BATCH, IN).astype(np.float32)
        yb = np.eye(OUT, dtype=np.float32)[rng.randint(0, OUT, BATCH)]

        # reference: baseline stepped once per microbatch, sequentially
        x, y, loss, train = build_bn()
        ex1 = ht.Executor({"train": [loss, train]})
        w0 = ex1.return_tensor_values()
        for m in range(M):
            ex1.run("train", feed_dict={x: xb[m * mb:(m + 1) * mb],
                                        y: yb[m * mb:(m + 1) * mb]})
        ref = ex1.return_tensor_values()

        x, y, loss, train = build_bn()
        ex2 = ht.Executor({"train": [loss, train]}, pipeline="pipedream",
                          num_stages=2, num_microbatches=M)
        ex2.load_dict(w0)
        ex2.run("train", feed_dict={x: xb, y: yb})
        got = ex2.return_tensor_values()
        for k in ref:
            np.testing.assert_allclose(got[k], ref[k], atol=1e-5,
                                       err_msg=k)


class TestValidation:
    def test_bad_mode_rejected(self):
        with pytest.raises(ValueError):
            ht.HetuConfig(pipeline="zigzag")

    def test_microbatch_divisibility_checked(self, baseline):
        x, y, loss, train = build_model()
        ex = ht.Executor({"train": [loss, train]}, pipeline="gpipe",
                         num_stages=2, num_microbatches=5)
        with pytest.raises(ValueError, match="divisible"):
            ex.run("train", feed_dict={
                x: np.zeros((16, IN), np.float32),
                y: np.zeros((16, OUT), np.float32)})

    def test_ps_comm_mode_rejected(self):
        x, y, loss, train = build_model()
        with pytest.raises(NotImplementedError):
            ht.Executor({"train": [loss, train]}, pipeline="gpipe",
                        comm_mode="Hybrid")


class TestNonBatchFeeds:
    def test_mask_feed_passed_whole(self, baseline):
        """A per-step constant feed (here a [HID, HID]-shaped additive
        term whose dim 0 happens to divide num_microbatches) must NOT be
        split along dim 0 when listed in non_batch_feeds."""
        w0, batches, base = baseline

        def build_with_const():
            x = ht.placeholder_op("x")
            y = ht.placeholder_op("y")
            c = ht.placeholder_op("cmask")       # [HID, HID] constant
            h = ht.linear_op(x, ht.init.xavier_uniform((IN, HID),
                                                       name="nb_in_w"),
                             ht.init.zeros((HID,), name="nb_in_b"))
            h = ht.matmul_op(h, c) + h
            logits = ht.matmul_op(h, ht.init.xavier_uniform(
                (HID, OUT), name="nb_head"))
            loss = ht.reduce_mean_op(
                ht.softmaxcrossentropy_op(logits, y), axes=0)
            train = ht.optim.SGDOptimizer(learning_rate=0.1).minimize(loss)
            return x, y, c, loss, train

        cmask = (np.eye(HID) * 0.1).astype(np.float32)

        x, y, c, loss, train = build_with_const()
        ex1 = ht.Executor({"train": [loss, train]})
        w0 = ex1.return_tensor_values()
        base = [float(np.asarray(ex1.run("train", feed_dict={
            x: a, y: b, c: cmask})[0])) for a, b in batches]

        x, y, c, loss, train = build_with_const()
        ex2 = ht.Executor({"train": [loss, train]}, pipeline="gpipe",
                          num_stages=2, num_microbatches=4,
                          non_batch_feeds=("cmask",))
        ex2.load_dict(w0)
        tr = [float(np.asarray(ex2.run("train", feed_dict={
            x: a, y: b, c: cmask})[0])) for a, b in batches]
        np.testing.assert_allclose(tr, base, atol=1e-5)

    def test_unlisted_indivisible_feed_error_mentions_knob(self, baseline):
        w0, batches, _ = baseline
        x, y, loss, train = build_model()
        ex = ht.Executor({"train": [loss, train]}, pipeline="gpipe",
                         num_stages=2, num_microbatches=4)
        with pytest.raises(ValueError, match="non_batch_feeds"):
            ex.run("train", feed_dict={
                x: np.zeros((15, IN), np.float32),
                y: np.zeros((15, OUT), np.float32)})


class TestMixedPrecisionPipeline:
    @pytest.mark.parametrize("spmd", [False, True], ids=["host", "spmd"])
    def test_bf16_pipeline_trains_fp32_masters(self, spmd):
        """mixed_precision='bf16' through both pipeline lowerings: bf16
        compute, fp32 masters, finite decreasing loss."""
        x, y, loss, train = build_model()
        kw = dict(pipeline="gpipe", num_microbatches=4,
                  mixed_precision="bf16")
        if spmd:
            kw["mesh"] = make_mesh({"pp": 2})
        else:
            kw["num_stages"] = 2
        ex = ht.Executor({"train": [loss, train]}, **kw)
        assert ex.subexecutor["train"].spmd == spmd
        tr = run_traj(ex, x, y, make_batches(10))
        assert np.all(np.isfinite(tr))
        assert np.mean(tr[-3:]) < np.mean(tr[:3]), tr
        assert ex.var_values["l0_w1"].dtype == np.float32   # masters


def test_gpt_model_pipeline_equivalence():
    """GPTForCausalLM (batch-polymorphic: broadcast positions + -1
    reshapes) through Executor(pipeline='gpipe') on a pp2 x dp2 mesh:
    trajectory == 1-device.  Labels carry no -1 padding here: the
    masked-mean denominator is per-microbatch under pipelining (see
    models/bert.py _masked_mean's microbatching caveat)."""
    from hetu_tpu.models import GPTConfig, GPTForCausalLM

    def run(mesh=None, **exkw):
        cfg = GPTConfig(vocab_size=61, hidden_size=32,
                        num_hidden_layers=4, num_attention_heads=2,
                        max_position_embeddings=16, batch_size=8,
                        seq_len=16, dropout_rate=0.0)
        m = GPTForCausalLM(cfg)
        ids = ht.placeholder_op("ids")
        labels = ht.placeholder_op("labels")
        loss, _ = m(ids, labels=labels)
        train = ht.optim.AdamOptimizer(learning_rate=3e-3).minimize(loss)
        ex = ht.Executor({"train": [loss, train]}, mesh=mesh, **exkw)
        rng = np.random.RandomState(1)
        ls = []
        for _ in range(6):
            iv = rng.randint(0, 61, (8, 16)).astype(np.int32)
            lv = ((iv + 1) % 61).astype(np.int32)
            ls.append(float(np.asarray(
                ex.run("train", feed_dict={ids: iv, labels: lv})[0])))
        return ls

    base = run()
    pp = run(mesh=make_mesh({"pp": 2, "dp": 2}), pipeline="gpipe",
             num_microbatches=4, num_stages=2)
    np.testing.assert_allclose(base, pp, rtol=2e-4, atol=2e-4)
